"""Block-shape sweep for the Pallas fused-combine kernel on the live
chip. Prints one line per configuration (GB/s, chained-iteration
methodology from bench.py) plus the XLA-fused baseline; use the winner
to retune rlo_tpu/pallas/reduce.py's defaults.

Usage: python benchmarks/pallas_sweep.py [--bytes N]
"""

from __future__ import annotations

import argparse
import sys
from functools import partial
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import jax                              # noqa: E402
import jax.numpy as jnp                 # noqa: E402
import numpy as np                      # noqa: E402

import bench                            # noqa: E402
from rlo_tpu.pallas.reduce import fused_combine  # noqa: E402

CONFIGS = [  # (block_rows, lane)
    (256, 128), (512, 128), (1024, 128), (2048, 128),
    (128, 256), (256, 256), (512, 256),
    (64, 512), (128, 512), (256, 512),
    (32, 1024), (64, 1024), (128, 1024),
]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--bytes", type=int, default=256 << 20)
    args = ap.parse_args()
    n = args.bytes // 4
    rows = n // 128
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((rows, 128)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((rows, 128)), jnp.float32)
    nbytes = a.size * 4
    want = np.asarray(a[0, :4] + 2 * b[0, :4])  # oracle after k=2 chain

    @partial(jax.jit, static_argnames=("k",))
    def xla_loop(x, y, k):
        return jax.lax.fori_loop(0, k, lambda i, acc: acc + y, x)

    t = bench._chain_time(xla_loop, a, b)
    base = 3 * nbytes / t / 1e9
    print(f"xla fused baseline: {base:.1f} GB/s", flush=True)

    results = []
    for block_rows, lane in CONFIGS:
        @partial(jax.jit, static_argnames=("k",))
        def ploop(x, y, k, block_rows=block_rows, lane=lane):
            return jax.lax.fori_loop(
                0, k, lambda i, acc: fused_combine(
                    acc, y, op="sum", block_rows=block_rows, lane=lane),
                x)
        try:
            got = np.asarray(ploop(a, b, 2)[0, :4])
            np.testing.assert_allclose(got, want, rtol=1e-5)
            t = bench._chain_time(ploop, a, b)
            gbps = 3 * nbytes / t / 1e9
            results.append((gbps, block_rows, lane))
            print(f"block_rows={block_rows:5d} lane={lane:4d}: "
                  f"{gbps:7.1f} GB/s ({gbps/base:.3f}x xla)", flush=True)
        except Exception as e:  # remote-compile size limits etc.
            print(f"block_rows={block_rows:5d} lane={lane:4d}: "
                  f"FAILED ({type(e).__name__}: {str(e)[:80]})",
                  flush=True)
    if results:
        best = max(results)
        print(f"BEST: block_rows={best[1]} lane={best[2]} "
              f"{best[0]:.1f} GB/s ({best[0]/base:.3f}x xla)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
