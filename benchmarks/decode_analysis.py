"""Dissect the decode step's HBM budget (round-5 VERDICT item 1).

decode_bench records 44-46% of the 819 GB/s weight+cache streaming
ceiling and more than half the bound was unaccounted. This does for
decode what mfu_analysis.py did for the train-step MFU cliff: split
the step into its streaming components, measure each AT ITS EXACT
DECODE SHAPE in the same chip window, and reconcile against both the
compiler's own byte accounting and a same-window streaming probe.

Two accounting surfaces:

1. Compiler: `jit(decode_step).lower().compile().cost_analysis()`
   gives the bytes XLA thinks the program touches — if that exceeds
   the model's weight+cache bytes, XLA is moving extra traffic
   (un-hoisted converts, cache copies); if it matches, the gap is
   delivery rate, not extra bytes.

2. Chip, per component (chained fori_loops, median stat, all in one
   window alongside a big-matmul streaming probe):
     - ffn matmuls   (b, d) x (d, ff) x (ff, d)      - weights stream
     - qkv + wo      (b, d) x (d, 3d), (b, d) x (d, d)
     - logits head   (b, d) x (d, vocab)
     - cache attend  flash_decode at (b, kvh, hd, max_len)
     - full step     decode_step (fixed mid-window position)
   Component GB/s = known bytes / measured time; the residual
   (step - sum of parts) is elementwise + scan overhead.

The streaming probe's achieved GB/s is the window's DELIVERED
bandwidth — the fraction-of-deliverable number is drift-immune the
same way train_bench's window-relative MFU is.

Usage: python benchmarks/decode_analysis.py [--tiny] [--batch N]
       [--plen N]   (the JSON record always prints on stdout)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from rlo_tpu.models.generate import decode_step, init_kv_cache  # noqa: E402
from rlo_tpu.models.transformer import (TransformerConfig,  # noqa: E402
                                        init_params)

V5E_HBM_GBPS = 819.0


def chain_time(run, x0, exp_bytes, *, pairs=7, label="", max_k=4096):
    """Per-op seconds for a chained loop ``run(x0, kk)``.

    Tunnel-budget-aware replacement for bench._chain_time: the
    escalating calibration there recompiles at every k and blew a
    30-minute budget across six probes on the tunneled chip. Here k
    comes from the component's own byte model (chain long enough that
    k ops dwarf the ~110 ms dispatch floor), exactly TWO compiles per
    probe (k and 2k), and per-op = median over interleaved pairs of
    (t(2k) - t(k)) / k — the floor and window drift cancel inside
    each pair (memory: tunnel-bench-protocols)."""
    import time
    t_exp = max(exp_bytes / (V5E_HBM_GBPS * 1e9), 2e-7)
    k = int(min(max_k, max(8, 0.25 / t_exp)))
    np.asarray(run(x0, k))
    np.asarray(run(x0, 2 * k))  # compile + warm both
    np.asarray(run(x0, k))
    diffs = []
    for _ in range(pairs):
        t0 = time.perf_counter()
        np.asarray(run(x0, 2 * k))
        t1 = time.perf_counter()
        np.asarray(run(x0, k))
        t2 = time.perf_counter()
        diffs.append((t1 - t0) - (t2 - t1))
    med = float(np.median(diffs))
    if med <= 0:
        raise RuntimeError(f"{label}: chained diff swallowed by noise "
                           f"(median {med*1e3:.3f} ms at k={k})")
    mad = float(np.median(np.abs(np.asarray(diffs) - med)))
    print(f"  {label}: k={k} per-op {med/k*1e6:.1f} us "
          f"(spread {mad/med:.0%})", file=sys.stderr)
    return med / k


def _count_params(params):
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))


def compiler_accounting(params, cfg, batch, max_len, pos):
    """XLA's own byte/flop accounting for ONE decode step."""
    cache = init_kv_cache(cfg, batch, max_len)
    tok = jnp.zeros((batch,), jnp.int32)

    @jax.jit
    def step(p, t, c):
        return decode_step(p, t, pos, c, cfg)

    compiled = step.lower(params, tok, cache).compile()
    rec = {}
    try:
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        rec["bytes_accessed"] = float(ca.get("bytes accessed", 0.0))
        rec["flops"] = float(ca.get("flops", 0.0))
    except Exception as e:  # noqa: BLE001
        rec["cost_analysis_error"] = repr(e)
    try:
        ma = compiled.memory_analysis()
        rec["temp_bytes"] = int(getattr(ma, "temp_size_in_bytes", 0))
        rec["arg_bytes"] = int(getattr(ma, "argument_size_in_bytes", 0))
        rec["out_bytes"] = int(getattr(ma, "output_size_in_bytes", 0))
    except Exception as e:  # noqa: BLE001
        rec["memory_analysis_error"] = repr(e)
    return rec


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true")
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--plen", type=int, default=16)
    ap.add_argument("--n-window", type=int, default=192,
                    help="decode window (max_len = plen + window), "
                         "matching decode_bench's n2")
    args = ap.parse_args()

    if args.tiny:
        cfg = TransformerConfig(vocab=512, d_model=128, n_heads=4,
                                n_layers=2, d_ff=512, dtype="float32")
        batch, plen, win = 2, 8, 16
    else:
        cfg = TransformerConfig(vocab=32768, d_model=1024, n_heads=16,
                                n_layers=8, d_ff=4096,
                                dtype="bfloat16")
        batch, plen, win = args.batch, args.plen, args.n_window

    # production caches round the seq axis to the 128-lane tile
    # (init_kv_cache) — the probes must measure the same shape or the
    # attend leg pays materialized pads production avoids
    max_len = -(-(plen + win) // 128) * 128
    # mid-differencing-window position (decode_bench differences
    # max_new = win/3 vs win): component probes use it; the flash
    # attend streams the FULL allocated max_len regardless
    pos = plen + (win // 3 + win) // 2
    params = init_params(jax.random.PRNGKey(0), cfg)
    n_params = _count_params(params)
    on_tpu = jax.default_backend() == "tpu"
    dt = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    wbytes = 2 if cfg.dtype == "bfloat16" else 4
    rng = np.random.default_rng(0)

    d, ff, vocab = cfg.d_model, cfg.d_ff, cfg.vocab
    nl, kvh, hd = cfg.n_layers, cfg.kv_heads, cfg.head_dim
    nh = cfg.n_heads

    # ---- component byte model (per step) ---------------------------
    comp_bytes = {
        "ffn": nl * 2 * d * ff * wbytes,
        "qkv_wo": nl * (d * (nh + 2 * kvh) * hd + nh * hd * d) * wbytes,
        "logits": d * vocab * wbytes,
        "attend": nl * 2 * batch * kvh * max_len * hd * wbytes,
    }
    other_w = (n_params * wbytes
               - comp_bytes["ffn"] - comp_bytes["qkv_wo"]
               - comp_bytes["logits"])  # embed gather table, norms
    model_bytes = n_params * wbytes + comp_bytes["attend"]

    # ---- compiler accounting ---------------------------------------
    ca = compiler_accounting(params, cfg, batch, max_len, pos)
    print(f"component byte model: "
          + "  ".join(f"{n}={b/2**20:.0f}MB"
                      for n, b in comp_bytes.items())
          + f"  other-weights={other_w/2**20:.0f}MB  "
          f"total={model_bytes/2**20:.0f}MB/step", file=sys.stderr)
    if "bytes_accessed" in ca:
        print(f"compiler: bytes_accessed={ca['bytes_accessed']/2**20:.0f}"
              f"MB ({ca['bytes_accessed']/model_bytes:.2f}x the model) "
              f"temp={ca.get('temp_bytes', 0)/2**20:.0f}MB",
              file=sys.stderr)

    # ---- chip probes (one window) ----------------------------------
    x0 = jnp.asarray(rng.standard_normal((batch, d)), dt)

    def chain(body):
        @partial(jax.jit, static_argnames=("kk",))
        def run(x, kk):
            def it(i, x):
                return body(x)
            return jax.lax.fori_loop(0, kk, it, x)
        return run

    # streaming probe: weights too big for VMEM residency, re-read
    # from HBM every iteration — the window's delivered GB/s
    mm = 4096
    W_probe = jnp.asarray(rng.standard_normal((mm, mm)), dt)
    xp = jnp.asarray(rng.standard_normal((batch, mm)), dt)
    probe = chain(lambda x: jnp.tanh(x @ W_probe))
    t_probe = chain_time(probe, xp, mm * mm * wbytes, label="probe")
    gbps_window = mm * mm * wbytes / t_probe / 1e9

    # ffn at decode shape
    W1 = jnp.asarray(rng.standard_normal((d, ff)) * 0.02, dt)
    W2 = jnp.asarray(rng.standard_normal((ff, d)) * 0.02, dt)
    ffn = chain(lambda x: jnp.tanh((jnp.tanh(x @ W1)) @ W2))
    t_ffn1 = chain_time(ffn, x0, 2 * d * ff * wbytes, label="ffn")

    # qkv + wo at decode shape
    Wqkv = jnp.asarray(
        rng.standard_normal((d, (nh + 2 * kvh) * hd)) * 0.02, dt)
    Wo = jnp.asarray(rng.standard_normal((nh * hd, d)) * 0.02, dt)
    qkv = chain(lambda x: jnp.tanh(
        (jnp.tanh(x @ Wqkv)[:, :nh * hd]) @ Wo))
    t_qkv1 = chain_time(
        qkv, x0, (d * (nh + 2 * kvh) * hd + nh * hd * d) * wbytes,
        label="qkv_wo")

    # logits head at decode shape (+ fold back so the chain stays
    # (b, d) -> (b, d) and data-dependent)
    We = jnp.asarray(rng.standard_normal((vocab, d)) * 0.02, dt)
    fold = jnp.asarray(rng.standard_normal((vocab, d)) * 1e-4, dt)
    logits_c = chain(lambda x: jnp.tanh((x @ We.T) @ fold))
    t_logits = chain_time(logits_c, x0, 2 * d * vocab * wbytes,
                          label="logits")
    logits_extra = d * vocab * wbytes  # the fold matrix also streams

    # cache attend at decode shape (one layer; x8 in accounting)
    kc = jnp.asarray(rng.standard_normal((batch, kvh, hd, max_len)),
                     dt)
    vc = jnp.asarray(rng.standard_normal((batch, kvh, hd, max_len)),
                     dt)
    from rlo_tpu.models.generate import _attend_cache
    scale = 1.0 / np.sqrt(hd)

    @partial(jax.jit, static_argnames=("kk",))
    def attend_chain(q, kk):
        def it(i, q):
            o = _attend_cache(q, kc, vc, pos, scale)
            return o.astype(dt)
        return jax.lax.fori_loop(0, kk, it, q)

    q0 = jnp.asarray(rng.standard_normal((batch, 1, nh, hd)), dt)
    t_attend1 = chain_time(
        attend_chain, q0, 2 * batch * kvh * max_len * hd * wbytes,
        label="attend")

    # the full decode step: whole-`generate` length differencing, the
    # ONE program shape the tunneled remote compiler reliably handles
    # (fori chains of the raw decode step kill it with a broken pipe
    # at any chain length — twice reproduced; decode_bench.py's
    # methodology note). Same interleaved-pair protocol: per-step =
    # median[(t(n2) - t(n1)) pair] / (n2 - n1).
    import time as _time
    from rlo_tpu.models.generate import generate
    n1, n2 = win // 3, win
    prompt = jnp.asarray(rng.integers(0, vocab, (batch, plen)),
                         jnp.int32)

    def build(max_new):
        f = jax.jit(lambda p, t: generate(p, t, cfg, max_new=max_new,
                                          max_len=max_len))
        np.asarray(f(params, prompt))
        return lambda: np.asarray(f(params, prompt))

    run_hi, run_lo = build(n2), build(n1)
    run_hi(), run_lo()
    sdiffs = []
    for _ in range(9):
        t0 = _time.perf_counter()
        run_hi()
        t1 = _time.perf_counter()
        run_lo()
        t2 = _time.perf_counter()
        sdiffs.append((t1 - t0) - (t2 - t1))
    smed = float(np.median(sdiffs))
    if smed <= 0:
        raise RuntimeError("step differencing swallowed by noise")
    t_step = smed / (n2 - n1)
    print(f"  step: generate-differenced per-op {t_step*1e6:.1f} us",
          file=sys.stderr)

    # ---- budget table ----------------------------------------------
    # the logits probe streams the fold matrix too (d*vocab extra
    # bytes the real step does not have) — charge the step's budget
    # only the head's byte share of the probe time, or the residual
    # is understated by the fold's stream time
    head_share = (d * vocab * wbytes) / (d * vocab * wbytes
                                         + logits_extra)
    comp_t = {"ffn": t_ffn1 * nl, "qkv_wo": t_qkv1 * nl,
              "logits": t_logits * head_share, "attend": t_attend1 * nl}
    meas_bytes = dict(comp_bytes)
    resid = t_step - sum(comp_t.values())
    print(f"\nwindow streaming probe: {gbps_window:.0f} GB/s delivered "
          f"({gbps_window/V5E_HBM_GBPS:.1%} of 819 nominal)",
          file=sys.stderr)
    print(f"{'component':>10} {'bytes/step':>11} {'t (ms)':>8} "
          f"{'GB/s':>6} {'vs window':>9}", file=sys.stderr)
    for name in comp_t:
        gbps = meas_bytes[name] / comp_t[name] / 1e9
        print(f"{name:>10} {meas_bytes[name]/2**20:>9.0f}MB "
              f"{comp_t[name]*1e3:>8.3f} {gbps:>6.0f} "
              f"{gbps/gbps_window:>8.1%}", file=sys.stderr)
    print(f"{'step':>10} {model_bytes/2**20:>9.0f}MB "
          f"{t_step*1e3:>8.3f} {model_bytes/t_step/1e9:>6.0f} "
          f"{model_bytes/t_step/1e9/gbps_window:>8.1%}",
          file=sys.stderr)
    print(f"{'residual':>10} {'':>11} {resid*1e3:>8.3f} "
          f"(elementwise + scan overhead, "
          f"{resid/t_step:.1%} of step)", file=sys.stderr)

    frac_window = model_bytes / t_step / 1e9 / gbps_window
    rec = {
        "metric": f"decode-step HBM budget, {n_params/1e6:.0f}M params,"
                  f" batch {batch}, max_len {max_len}, "
                  f"{'bf16 v5e chip' if on_tpu else jax.default_backend()}",
        "value": round(t_step * 1e3, 3),
        "unit": "ms/step",
        "vs_baseline": round(frac_window, 4),
        "vs_baseline_meaning": "step streaming rate / same-window "
                               "probe rate (drift-immune fraction of "
                               "DELIVERED bandwidth)",
        "window_probe_gbps": round(gbps_window, 1),
        "components_ms": {n: round(t * 1e3, 3)
                          for n, t in comp_t.items()},
        "component_bytes_mb": {n: round(b / 2**20, 1)
                               for n, b in meas_bytes.items()},
        "residual_ms": round(resid * 1e3, 3),
        "compiler": {kk: vv for kk, vv in ca.items()},
    }
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
