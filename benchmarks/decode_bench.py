"""KV-cache decode throughput for the flagship model on the live chip.

Methodology: one jitted `generate` is a single XLA program (prefill
scan + decode scan, static shapes). The tunneled dispatch floor and the
prefill cost cancel by differencing two generation lengths:

    tokens/s = (N2 - N1) / (t(N2) - t(N1))

where t(N2) - t(N1) is the MEDIAN OF INTERLEAVED PAIRS (paired_diff):
the two programs are timed back-to-back within each pair so the
chip's between-window throughput drift — the source of the round-3
numbers' ±30% run-to-run scatter — cancels, the same cure bench.py's
paired-ratio protocol applies to the headline number. Each recorded
value now also prints its own MAD/median spread.

Decode is matvec-bound (one (1, d) activation against every weight
matrix per token), so the interesting ceiling is HBM bandwidth over
the ~param bytes read per token, reported as achieved/ceiling.

--ttft measures time-to-first-token: the one-forward-pass blockwise
prefill (models.generate.prefill, flash-kernel path) vs the
token-at-a-time scan oracle at a given prompt length — the round-4
VERDICT item making prefill O(plen/block) instead of O(plen) serial
decode steps. Methodology: every timed unit is a whole `generate`
call (the shape the tunneled remote compiler demonstrably handles —
direct chains of the prefill graph reproducibly kill it with a broken
pipe), CHAINED k data-dependent times inside one jit so
millisecond-scale costs amortize over the ~110 ms dispatch floor:
prefill cost = per-op cost of chained generate(max_new=4) minus 4
decode steps; decode-step cost = interleaved paired difference of two
chains whose max_new differs by 64 (pairing cancels window drift;
each pair carries k*64 steps of signal). Both carry bench.py's
physical floors: a prefill below the 2*n_params*tokens/197e12 FLOP
floor is flagged and clamped. The per-token scan-prefill baseline IS
a decode step (same decode_step, same cache math), so scan TTFT =
plen * decode-step cost without compiling a plen-long scan program.

Usage: python benchmarks/decode_bench.py [--tiny] [--ttft] [--plen N]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from rlo_tpu.models.generate import generate  # noqa: E402
from rlo_tpu.models.transformer import (TransformerConfig,  # noqa: E402
                                        init_params)

V5E_HBM_GBPS = 819.0


def paired_diff(params, hi_args, lo_args, cfg, pairs=9, label="decode"):
    """Median of interleaved per-pair differences t(hi) - t(lo).

    The round-3 decode numbers carried ~±30% run-to-run drift because
    the two legs of the differencing were timed in separate blocks:
    the tunneled chip's throughput drifts between measurement windows
    (docs/DESIGN.md, "the chip drifts ~1.6x between windows"), so any
    window shift between block t(N1) and block t(N2) lands directly in
    the difference. Same cure as bench.py's paired-ratio protocol
    (round-2 VERDICT item 2): compile and warm BOTH programs, then
    alternate hi/lo timings back-to-back and take the median of the
    per-pair differences — drift slow relative to one pair cancels.
    The median runs over ALL pairs including non-positive ones —
    dropping negative pairs before the median would censor the noise
    distribution one-sidedly and bias the estimate up (and made the
    tiny smoke test flaky); only a non-positive MEDIAN means the gap
    is genuinely inside dispatch noise, and that raises.
    Returns (median_diff_seconds, relative_spread) where the spread is
    MAD/median over all pairs — the number carries its own
    uncertainty instead of hiding it.
    """
    def build(args):
        prompt, max_new, max_len = args
        f = jax.jit(lambda p, t: generate(p, t, cfg, max_new=max_new,
                                          max_len=max_len))
        np.asarray(f(params, prompt))  # compile + warm
        return lambda: np.asarray(f(params, prompt))

    run_hi, run_lo = build(hi_args), build(lo_args)
    run_hi(), run_lo()  # second warm pass after both are compiled
    diffs = []
    for _ in range(pairs):
        t0 = time.perf_counter()
        run_hi()
        t1 = time.perf_counter()
        run_lo()
        t2 = time.perf_counter()
        diffs.append((t1 - t0) - (t2 - t1))
    med = float(np.median(diffs))
    if med <= 0:
        raise RuntimeError(
            f"{label} paired differencing failed: median pair "
            f"difference {med*1e3:.3f} ms <= 0 over {pairs} pairs "
            f"(hi={hi_args[1:]}, lo={lo_args[1:]}) — the timing gap "
            f"is inside dispatch noise; widen the length gap")
    mad = float(np.median(np.abs(np.asarray(diffs) - med)))
    return med, mad / med


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--cast-weights", action="store_true",
                    help="store weights in HBM as bf16 (measured "
                         "SLOWER on v5e — see comment at the ceiling)")
    ap.add_argument("--ttft", action="store_true",
                    help="time-to-first-token: blockwise prefill vs "
                         "the scan oracle")
    ap.add_argument("--plen", type=int, default=1024,
                    help="prompt length for --ttft")
    ap.add_argument("--prompt-len", type=int, default=16,
                    help="prompt length for the decode measurement — "
                         "long prompts make each decode step read a "
                         "long cache (the regime where the cache, not "
                         "the weights, bounds decode)")
    ap.add_argument("--kv-dtype", choices=["act", "int8"], default="act",
                    help="KV-cache storage: activation dtype (exact) "
                         "or int8 (cfg.kv_cache_dtype='int8' — half "
                         "the cache HBM traffic)")
    ap.add_argument("--compare-kv", action="store_true",
                    help="measure act vs int8 cache decode in "
                         "INTERLEAVED pairs (drift-immune ratio; two "
                         "separate runs of this bench sit in "
                         "different chip-throughput windows and their "
                         "ratio is not trustworthy)")
    ap.add_argument("--compare-gqa", action="store_true",
                    help="MHA (16q/16kv) vs GQA (16q/4kv) decode in "
                         "interleaved pairs at long prompt — the "
                         "cache-bandwidth win GQA exists for")
    ap.add_argument("--capacity", action="store_true",
                    help="max servable batch at --prompt-len context "
                         "before HBM exhaustion: kv=16/4/4+int8, "
                         "each PROVEN by allocating the cache and "
                         "running a decode step at the claimed size")
    args = ap.parse_args()

    if args.compare_kv:
        return compare_kv(args)
    if args.compare_gqa:
        return compare_gqa(args)
    if args.capacity:
        return capacity(args)

    if args.ttft:
        return ttft(args)

    if args.tiny:
        cfg = TransformerConfig(vocab=128, d_model=64, n_heads=4,
                                n_layers=2, d_ff=256, dtype="float32")
        # wide length gap: at toy sizes the two timings are micro-
        # seconds apart and host contention (e.g. the full test suite)
        # can invert a narrow pair, tripping the differencing guard
        batch, n1, n2 = args.batch or 2, 4, 48
    else:
        cfg = TransformerConfig(vocab=32768, d_model=1024, n_heads=16,
                                n_layers=8, d_ff=4096, dtype="bfloat16")
        # batch swept on the chip (2026-07-30): 8 -> 8.9k tok/s, 32 ->
        # 28-40k across runs (weight reads amortized), 64 -> 27.4k
        # (cache-attention traffic dominates); 32 is the knee
        batch, n1, n2 = args.batch or 32, 64, 192

    params = init_params(jax.random.PRNGKey(0), cfg)
    # Weight residency: init_params keeps f32 (training layout); the
    # in-scan .astype(dt) is hoisted by XLA into a one-time bf16 copy,
    # so the streamed bytes are 2/param either way and the ceiling
    # below reflects the streamed copy (review finding). Pre-casting
    # the tree (--cast-weights) measured no better on the chip
    # (2026-07-30: 22.3k vs 22-40k tok/s default across runs — decode
    # differencing on the tunnel drifts ~±30% run to run, so treat
    # single-run comparisons here with suspicion).
    if args.cast_weights and cfg.dtype == "bfloat16":
        params = jax.tree.map(
            lambda p: p.astype(jnp.bfloat16)
            if p.dtype == jnp.float32 else p, params)
    if args.kv_dtype == "int8":
        import dataclasses
        cfg = dataclasses.replace(cfg, kv_cache_dtype="int8")
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree.leaves(params))
    rng = np.random.default_rng(0)
    plen = min(args.prompt_len, 16) if args.tiny else args.prompt_len
    prompt = jnp.asarray(rng.integers(0, cfg.vocab, (batch, plen)),
                         jnp.int32)
    max_len = prompt.shape[1] + n2
    diff, spread = paired_diff(params, (prompt, n2, max_len),
                               (prompt, n1, max_len), cfg)
    steps_s = (n2 - n1) / diff
    tok_s = steps_s * batch
    print(f"paired differencing spread (MAD/median): {spread:.1%}",
          file=sys.stderr)
    on_tpu = jax.default_backend() == "tpu"
    # HBM ceiling: every decode step reads at least the param bytes
    # PLUS the live K/V cache prefix (dominant at long prompt_len) —
    # cache bytes/step use the midpoint position of the differenced
    # window, per the storage dtype
    wdt = 2 if cfg.dtype == "bfloat16" else 4
    kv_elem = (1 + 4 / cfg.head_dim  # int8 + f32 scale per head row
               ) if cfg.kv_cache_dtype == "int8" else wdt
    mid_pos = plen + (n1 + n2) / 2
    cache_bytes = (2 * cfg.n_layers * batch * mid_pos * cfg.kv_heads
                   * cfg.head_dim * kv_elem)
    bytes_per_step = n_params * wdt + cache_bytes
    ceiling_steps = V5E_HBM_GBPS * 1e9 / bytes_per_step
    frac = steps_s / ceiling_steps if on_tpu else float("nan")
    print(f"params={n_params/1e6:.1f}M batch={batch} plen={plen} "
          f"cache={args.kv_dtype}: {steps_s:,.0f} steps/s, "
          f"{tok_s:,.0f} tok/s"
          + (f", {frac:.1%} of the HBM weight+cache streaming ceiling "
             f"({cache_bytes/2**20:.0f} MB cache read/step)"
             if on_tpu else " (not a TPU)"),
          file=sys.stderr)
    print(json.dumps({
        "metric": f"KV-cache greedy decode, {n_params/1e6:.0f}M params, "
                  f"batch {batch}, prompt {plen}, "
                  f"{args.kv_dtype} cache, "
                  f"{'bf16 v5e chip' if on_tpu else jax.default_backend()}",
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(frac, 4) if on_tpu else 0.0,
        "vs_baseline_meaning": "fraction of the HBM weight+cache "
                               "streaming ceiling (819 GB/s)",
    }))


def compare_kv(args):
    """act-vs-int8 cache decode ratio, drift-immune: each iteration
    times all four programs (act/int8 x n1/n2) back-to-back, diffs
    out the prefill+floor per variant, and takes the median of the
    per-iteration RATIOS — chip-throughput window drift cancels
    inside an iteration instead of landing between two separate
    bench invocations."""
    import dataclasses
    if args.tiny:
        cfg = TransformerConfig(vocab=128, d_model=64, n_heads=4,
                                n_layers=2, d_ff=256, dtype="float32")
        batch, n1, n2, plen = args.batch or 2, 4, 48, 16
    else:
        cfg = TransformerConfig(vocab=32768, d_model=1024, n_heads=16,
                                n_layers=8, d_ff=4096,
                                dtype="bfloat16")
        batch, n1, n2 = args.batch or 32, 64, 192
        plen = args.prompt_len if args.prompt_len > 16 else 1024
    params = init_params(jax.random.PRNGKey(0), cfg)
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree.leaves(params))
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab, (batch, plen)),
                         jnp.int32)
    max_len = plen + n2

    def build(kv_dtype, max_new):
        c = (dataclasses.replace(cfg, kv_cache_dtype="int8")
             if kv_dtype == "int8" else cfg)
        f = jax.jit(lambda p, t: generate(p, t, c, max_new=max_new,
                                          max_len=max_len))
        np.asarray(f(params, prompt))  # compile + warm
        return lambda: np.asarray(f(params, prompt))

    runs = {(kv, n): build(kv, n) for kv in ("act", "int8")
            for n in (n1, n2)}
    for f in runs.values():
        f()  # second warm pass after all four are compiled
    ratios, d_acts, d_ints = [], [], []
    for _ in range(9):
        t = {}
        for key, f in runs.items():
            t0 = time.perf_counter()
            f()
            t[key] = time.perf_counter() - t0
        d_act = t[("act", n2)] - t[("act", n1)]
        d_int = t[("int8", n2)] - t[("int8", n1)]
        if d_act > 0 and d_int > 0:
            ratios.append(d_act / d_int)
            d_acts.append(d_act)
            d_ints.append(d_int)
    if len(ratios) < 5:
        raise RuntimeError("compare-kv: too few valid iterations")
    ratio = float(np.median(ratios))
    tok_act = (n2 - n1) * batch / float(np.median(d_acts))
    tok_int = (n2 - n1) * batch / float(np.median(d_ints))
    on_tpu = jax.default_backend() == "tpu"
    print(f"compare-kv batch={batch} plen={plen}: act "
          f"{tok_act:,.0f} tok/s  int8 {tok_int:,.0f} tok/s  "
          f"interleaved speedup {ratio:.3f}x "
          f"({len(ratios)}/9 valid iterations)", file=sys.stderr)
    print(json.dumps({
        "metric": f"int8-vs-act KV cache decode speedup, "
                  f"{n_params/1e6:.0f}M params, "
                  f"batch {batch}, prompt {plen}, "
                  f"{'bf16 v5e chip' if on_tpu else jax.default_backend()}"
                  f" (interleaved paired ratio)",
        "value": round(ratio, 4),
        "unit": "x",
        "vs_baseline": round(ratio, 4),
        "vs_baseline_meaning": "decode-step time ratio act/int8; "
                               ">1 means the int8 cache is faster",
    }))


def compare_gqa(args):
    """MHA vs GQA decode, drift-immune (round-5 VERDICT item 3a): the
    kv-heads sweep through the flash-decode kernel at long prompt,
    where each step's HBM traffic is weights + the live K/V cache and
    GQA's 4x-smaller cache is a direct bandwidth win. Same interleaved
    four-program protocol as compare_kv. The GQA config also has
    smaller K/V projections (that is part of what GQA buys); the
    metric line reports both models' parameter counts."""
    import dataclasses
    if args.tiny:
        base = TransformerConfig(vocab=128, d_model=64, n_heads=4,
                                 n_layers=2, d_ff=256, dtype="float32")
        batch, n1, n2, plen, kvg = args.batch or 2, 4, 48, 16, 2
    else:
        base = TransformerConfig(vocab=32768, d_model=1024, n_heads=16,
                                 n_layers=8, d_ff=4096,
                                 dtype="bfloat16")
        batch, n1, n2 = args.batch or 32, 64, 192
        plen = args.prompt_len if args.prompt_len > 16 else 1024
        kvg = 4
    gqa = dataclasses.replace(base, n_kv_heads=kvg)
    params = {"mha": init_params(jax.random.PRNGKey(0), base),
              "gqa": init_params(jax.random.PRNGKey(0), gqa)}
    cfgs = {"mha": base, "gqa": gqa}
    n_par = {k: sum(int(np.prod(p.shape))
                    for p in jax.tree.leaves(v))
             for k, v in params.items()}
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, base.vocab, (batch, plen)),
                         jnp.int32)
    max_len = plen + n2

    def build(kind, max_new):
        c = cfgs[kind]
        f = jax.jit(lambda p, t: generate(p, t, c, max_new=max_new,
                                          max_len=max_len))
        np.asarray(f(params[kind], prompt))  # compile + warm
        return lambda: np.asarray(f(params[kind], prompt))

    runs = {(k, n): build(k, n) for k in ("mha", "gqa")
            for n in (n1, n2)}
    for f in runs.values():
        f()
    ratios, d_m, d_g = [], [], []
    for _ in range(9):
        t = {}
        for key, f in runs.items():
            t0 = time.perf_counter()
            f()
            t[key] = time.perf_counter() - t0
        dm = t[("mha", n2)] - t[("mha", n1)]
        dg = t[("gqa", n2)] - t[("gqa", n1)]
        if dm > 0 and dg > 0:
            ratios.append(dm / dg)
            d_m.append(dm)
            d_g.append(dg)
    if len(ratios) < 5:
        raise RuntimeError("compare-gqa: too few valid iterations")
    ratio = float(np.median(ratios))
    tok_m = (n2 - n1) * batch / float(np.median(d_m))
    tok_g = (n2 - n1) * batch / float(np.median(d_g))
    on_tpu = jax.default_backend() == "tpu"
    print(f"compare-gqa batch={batch} plen={plen}: "
          f"{base.n_heads}q/{base.kv_heads}kv {tok_m:,.0f} tok/s  "
          f"{gqa.n_heads}q/{gqa.kv_heads}kv {tok_g:,.0f} tok/s  "
          f"interleaved speedup {ratio:.3f}x "
          f"({len(ratios)}/9 valid)", file=sys.stderr)
    print(json.dumps({
        "metric": f"GQA decode speedup {base.n_heads}q/"
                  f"{gqa.kv_heads}kv vs MHA, batch {batch}, prompt "
                  f"{plen} ({n_par['mha']/1e6:.0f}M vs "
                  f"{n_par['gqa']/1e6:.0f}M params, "
                  f"{'bf16 v5e chip' if on_tpu else jax.default_backend()}"
                  f", interleaved paired ratio)",
        "value": round(ratio, 4),
        "unit": "x",
        "vs_baseline": round(ratio, 4),
        "vs_baseline_meaning": "decode-step time ratio MHA/GQA at the "
                               "same q heads; >1 means the compact "
                               "cache is faster",
    }))


def capacity(args):
    """Servable capacity (round-5 VERDICT item 3b): the largest batch
    of --plen-context rows whose KV cache fits HBM next to the
    weights, for MHA / GQA / GQA+int8 — PROVEN by allocating the full
    cache and running one decode step at that size (an analytic claim
    would hide allocator overheads); the recorded ratio is capacity
    vs the MHA baseline."""
    import dataclasses
    if args.tiny:
        base = TransformerConfig(vocab=128, d_model=64, n_heads=4,
                                 n_layers=2, d_ff=256, dtype="float32")
        L, budget, kvh_g = 128, 64 << 20, 2
    else:
        base = TransformerConfig(vocab=32768, d_model=1024, n_heads=16,
                                 n_layers=8, d_ff=4096,
                                 dtype="bfloat16")
        L = args.prompt_len if args.prompt_len > 16 else 4096
        budget = int(12.5e9)  # leave headroom of the 16 GB for
        # weights (0.8 GB f32+bf16), activations, and runtime slack
        kvh_g = 4
    variants = {
        "mha": base,
        "gqa4": dataclasses.replace(base, n_kv_heads=kvh_g),
        "gqa4_int8": dataclasses.replace(base, n_kv_heads=kvh_g,
                                         kv_cache_dtype="int8"),
    }
    rows = {}
    for name, cfg in variants.items():
        elem = (1 + 4 / cfg.head_dim) if cfg.kv_cache_dtype == "int8" \
            else (2 if cfg.dtype == "bfloat16" else 4)
        per_row = 2 * cfg.n_layers * cfg.kv_heads * L * cfg.head_dim \
            * elem
        b = max(1, int(budget / per_row))
        from rlo_tpu.models.generate import decode_step, init_kv_cache
        params = init_params(jax.random.PRNGKey(0), cfg)
        tok = jnp.zeros((b,), jnp.int32)
        cache = init_kv_cache(cfg, b, L)
        # donate the cache: input+output copies would double the
        # budget and OOM the 16 GB chip the leg sizes itself for
        step = jax.jit(lambda p, t, c, cfg=cfg: decode_step(
            p, t, L - 1, c, cfg), donate_argnums=(2,))
        logits, cache = step(params, tok, cache)
        np.asarray(logits[0, :4])  # force execution
        del cache, logits, params
        rows[name] = b
        print(f"capacity {name}: {per_row/2**20:.0f} MB/row at "
              f"context {L} -> {b} rows allocated AND decoded "
              f"({b * L / 1e6:.2f}M tokens of live context)",
              file=sys.stderr)
    on_tpu = jax.default_backend() == "tpu"
    print(json.dumps({
        "metric": f"servable capacity at context {L}: rows allocated+"
                  f"decoded within a {budget/1e9:.1f} GB cache budget "
                  f"(mha {rows['mha']}, gqa4 {rows['gqa4']}, "
                  f"gqa4+int8 {rows['gqa4_int8']}), "
                  f"{'bf16 v5e chip' if on_tpu else jax.default_backend()}",
        "value": rows["gqa4_int8"] * L / 1e6,
        "unit": "Mtokens live context",
        "vs_baseline": round(rows["gqa4_int8"] / rows["mha"], 2),
        "vs_baseline_meaning": "capacity ratio gqa4+int8 / MHA "
                               "(gqa4 alone: "
                               f"{round(rows['gqa4'] / rows['mha'], 2)}"
                               "x)",
    }))


def ttft(args):
    if args.tiny:
        cfg = TransformerConfig(vocab=128, d_model=64, n_heads=4,
                                n_layers=2, d_ff=256, dtype="float32")
        batch = args.batch or 2
        plen = min(args.plen, 128)
        n_dec = 4
    else:
        cfg = TransformerConfig(vocab=32768, d_model=1024, n_heads=16,
                                n_layers=8, d_ff=4096, dtype="bfloat16")
        batch = args.batch or 4
        plen = args.plen
        n_dec = 4
    p0 = 16
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)

    def prompt_of(n):
        return jnp.asarray(rng.integers(0, cfg.vocab, (batch, n)),
                           jnp.int32)

    # blockwise prefill cost: chain k data-dependent generate calls
    # (prefill + n_dec decode steps each) inside ONE jit — the chained
    # methodology bench.py uses everywhere, which resolves a
    # millisecond-scale op against the ~110 ms dispatch floor by
    # amortizing it over a calibrated k. (The previous protocol
    # differenced two SINGLE ~110 ms programs by prompt length; at
    # batch 1 the ~2 ms gap sits inside the noise and one recorded leg
    # printed 0.34 ms for 1008 tokens = 2.3x the chip's peak flops.)
    # Chaining raw prefill graphs kills the tunneled compiler (broken
    # pipe), so the chained unit stays a whole generate; each
    # iteration's prompt depends on the previous iteration's last
    # token, which defeats loop-invariant hoisting/CSE.
    import bench
    from functools import partial

    prompt_hi = prompt_of(plen)

    @partial(jax.jit, static_argnames=("kk",))
    def gen_chain(params, pr, kk):
        def it(i, carry):
            pr, acc = carry
            toks = generate(params, pr, cfg, max_new=n_dec,
                            max_len=plen + n_dec)
            pr = pr.at[0, 0].set(toks[0, -1] % cfg.vocab)
            return (pr, acc + toks[0, -1])
        _, acc = jax.lax.fori_loop(0, kk, it, (pr, jnp.int32(0)))
        return acc

    t_gen_op = bench._chain_time(
        lambda pr, kk: gen_chain(params, pr, kk), prompt_hi, k=4,
        stat="median")

    # scan-prefill baseline: one token of scan prefill IS one decode
    # step (same decode_step, same cache attend), so the baseline is
    # the decode-step cost. Measured by interleaving two CHAINED
    # programs whose max_new differs by m=64: chains amortize the
    # dispatch floor (a batch-1 step is ~0.1 ms — single-program
    # differencing of ~110 ms programs measured it with >1000%
    # spread), pairing cancels window drift, and each pair resolves
    # k*m decode steps of signal.
    m = 64
    prompt_lo = prompt_of(p0)

    @partial(jax.jit, static_argnames=("kk", "extra"))
    def dec_chain(params, pr, kk, extra):
        def it(i, carry):
            pr, acc = carry
            toks = generate(params, pr, cfg, max_new=n_dec + extra,
                            max_len=p0 + n_dec + m)
            pr = pr.at[0, 0].set(toks[0, -1] % cfg.vocab)
            return (pr, acc + toks[0, -1])
        _, acc = jax.lax.fori_loop(0, kk, it, (pr, jnp.int32(0)))
        return acc

    def loop_hi(pr, kk):
        return dec_chain(params, pr, kk, m)

    def loop_lo(pr, kk):
        return dec_chain(params, pr, kk, 0)

    k_dec = bench._calibrate_chain(loop_hi, prompt_lo, k=4)
    for f in (loop_hi, loop_lo):
        np.asarray(f(prompt_lo, k_dec))  # compile + warm both
    diffs = []
    for _ in range(9):
        t0 = time.perf_counter()
        np.asarray(loop_hi(prompt_lo, k_dec))
        t1 = time.perf_counter()
        np.asarray(loop_lo(prompt_lo, k_dec))
        t2 = time.perf_counter()
        diffs.append((t1 - t0) - (t2 - t1))
    d_med = float(np.median(diffs))
    if d_med <= 0:
        raise RuntimeError(
            f"ttft decode baseline failed: median chained diff "
            f"{d_med*1e3:.3f} ms <= 0 (k={k_dec}, m={m})")
    spread_d = float(np.median(np.abs(np.asarray(diffs) - d_med))
                     ) / d_med
    t_step = d_med / (k_dec * m)
    t_scan = t_step * plen  # scan-prefilling the WHOLE prompt
    # one chained generate op = blockwise prefill + n_dec decode steps
    t_block = t_gen_op - n_dec * t_step
    if t_block <= 0:
        raise RuntimeError(
            f"prefill cost non-positive: generate op "
            f"{t_gen_op*1e3:.3f} ms <= {n_dec} decode steps x "
            f"{t_step*1e3:.3f} ms")
    print(f"ttft: chained generate op {t_gen_op*1e3:.3f} ms, decode "
          f"spread {spread_d:.1%}", file=sys.stderr)

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        # physical floor (same gate as bench.py's 819 GB/s clamp): the
        # prefill's forward matmuls alone cost 2*n_params flops/token;
        # a differenced time below that at the 197 TFLOP/s bf16 peak is
        # floor corruption, not speed (a recorded batch-1 leg once
        # printed 0.34 ms for 1008 tokens = 2.3x the chip's peak)
        n_params = sum(int(np.prod(p.shape))
                       for p in jax.tree.leaves(params))
        t_floor = 2.0 * n_params * batch * plen / 197e12
        if t_block < t_floor:
            print(f"WARNING: prefill diff {t_block*1e3:.3f} ms below "
                  f"the {t_floor*1e3:.3f} ms FLOP floor — clamped "
                  f"(floor-corrupted differencing)", file=sys.stderr)
            t_block = t_floor
    print(f"ttft plen={plen} batch={batch}: blockwise prefill of "
          f"{plen} tokens {t_block*1e3:.2f} ms  scan "
          f"{t_scan*1e3:.2f} ms ({t_step*1e3:.3f} ms/token decode-"
          f"differenced)  speedup {t_scan/t_block:.1f}x",
          file=sys.stderr)
    print(json.dumps({
        "metric": f"time-to-first-token, blockwise prefill of "
                  f"{plen} prompt tokens, batch {batch}, "
                  f"{'bf16 v5e chip' if on_tpu else jax.default_backend()}",
        "value": round(t_block * 1e3, 3),
        "unit": "ms",
        "vs_baseline": round(t_scan / t_block, 2),
        "vs_baseline_meaning": "speedup over token-at-a-time prefill "
                               "(= decode-step cost per token, "
                               "length-differenced)",
    }))


if __name__ == "__main__":
    main()
