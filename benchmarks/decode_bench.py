"""KV-cache decode throughput for the flagship model on the live chip.

Methodology: one jitted `generate` is a single XLA program (prefill
scan + decode scan, static shapes). The tunneled dispatch floor and the
prefill cost cancel by differencing two generation lengths:

    tokens/s = (N2 - N1) / (t(N2) - t(N1))

Decode is matvec-bound (one (1, d) activation against every weight
matrix per token), so the interesting ceiling is HBM bandwidth over
the ~param bytes read per token, reported as achieved/ceiling.

--ttft measures time-to-first-token: the one-forward-pass blockwise
prefill (models.generate.prefill, flash-kernel path) vs the
token-at-a-time scan oracle (prefill_scan) at a given prompt length —
the round-4 VERDICT item making prefill O(plen/block) instead of
O(plen) serial decode steps.

Usage: python benchmarks/decode_bench.py [--tiny] [--ttft] [--plen N]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from rlo_tpu.models.generate import generate  # noqa: E402
from rlo_tpu.models.transformer import (TransformerConfig,  # noqa: E402
                                        init_params)

V5E_HBM_GBPS = 819.0


def time_generate(params, prompt, cfg, max_new, max_len, reps=7):
    f = jax.jit(lambda p, t: generate(p, t, cfg, max_new=max_new,
                                      max_len=max_len))
    np.asarray(f(params, prompt))  # compile + warm
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(f(params, prompt))
        ts.append(time.perf_counter() - t0)
    return float(min(ts))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--cast-weights", action="store_true",
                    help="store weights in HBM as bf16 (measured "
                         "SLOWER on v5e — see comment at the ceiling)")
    ap.add_argument("--ttft", action="store_true",
                    help="time-to-first-token: blockwise prefill vs "
                         "the scan oracle")
    ap.add_argument("--plen", type=int, default=1024,
                    help="prompt length for --ttft")
    args = ap.parse_args()

    if args.ttft:
        return ttft(args)

    if args.tiny:
        cfg = TransformerConfig(vocab=128, d_model=64, n_heads=4,
                                n_layers=2, d_ff=256, dtype="float32")
        # wide length gap: at toy sizes the two timings are micro-
        # seconds apart and host contention (e.g. the full test suite)
        # can invert a narrow pair, tripping the differencing guard
        batch, n1, n2 = args.batch or 2, 4, 48
    else:
        cfg = TransformerConfig(vocab=32768, d_model=1024, n_heads=16,
                                n_layers=8, d_ff=4096, dtype="bfloat16")
        # batch swept on the chip (2026-07-30): 8 -> 8.9k tok/s, 32 ->
        # 28-40k across runs (weight reads amortized), 64 -> 27.4k
        # (cache-attention traffic dominates); 32 is the knee
        batch, n1, n2 = args.batch or 32, 64, 192

    params = init_params(jax.random.PRNGKey(0), cfg)
    # Weight residency: init_params keeps f32 (training layout); the
    # in-scan .astype(dt) is hoisted by XLA into a one-time bf16 copy,
    # so the streamed bytes are 2/param either way and the ceiling
    # below reflects the streamed copy (review finding). Pre-casting
    # the tree (--cast-weights) measured no better on the chip
    # (2026-07-30: 22.3k vs 22-40k tok/s default across runs — decode
    # differencing on the tunnel drifts ~±30% run to run, so treat
    # single-run comparisons here with suspicion).
    if args.cast_weights and cfg.dtype == "bfloat16":
        params = jax.tree.map(
            lambda p: p.astype(jnp.bfloat16)
            if p.dtype == jnp.float32 else p, params)
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree.leaves(params))
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab, (batch, 16)),
                         jnp.int32)
    max_len = prompt.shape[1] + n2
    t1 = time_generate(params, prompt, cfg, n1, max_len)
    t2 = time_generate(params, prompt, cfg, n2, max_len)
    if t2 <= t1:
        raise RuntimeError(
            f"differencing failed (t({n2})={t2:.3f} <= t({n1})={t1:.3f})"
            f" — dispatch noise swamped the decode cost")
    steps_s = (n2 - n1) / (t2 - t1)
    tok_s = steps_s * batch
    on_tpu = jax.default_backend() == "tpu"
    # HBM ceiling: every decode step reads at least the param bytes
    # (bf16 weights; embeddings gather + cache traffic excluded)
    bytes_per_step = n_params * (2 if cfg.dtype == "bfloat16" else 4)
    ceiling_steps = V5E_HBM_GBPS * 1e9 / bytes_per_step
    frac = steps_s / ceiling_steps if on_tpu else float("nan")
    print(f"params={n_params/1e6:.1f}M batch={batch}: "
          f"{steps_s:,.0f} steps/s, {tok_s:,.0f} tok/s"
          + (f", {frac:.1%} of the HBM weight-streaming ceiling"
             if on_tpu else " (not a TPU)"),
          file=sys.stderr)
    print(json.dumps({
        "metric": f"KV-cache greedy decode, {n_params/1e6:.0f}M params, "
                  f"batch {batch}, "
                  f"{'bf16 v5e chip' if on_tpu else jax.default_backend()}",
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(frac, 4) if on_tpu else 0.0,
        "vs_baseline_meaning": "fraction of the HBM weight-streaming "
                               "ceiling (819 GB/s / param bytes)",
    }))


def ttft(args):
    from rlo_tpu.models.generate import (init_kv_cache, prefill,
                                         prefill_scan)

    if args.tiny:
        cfg = TransformerConfig(vocab=128, d_model=64, n_heads=4,
                                n_layers=2, d_ff=256, dtype="float32")
        batch = args.batch or 2
    else:
        cfg = TransformerConfig(vocab=32768, d_model=1024, n_heads=16,
                                n_layers=8, d_ff=4096, dtype="bfloat16")
        batch = args.batch or 8
    plen = args.plen if not args.tiny else min(args.plen, 64)
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab, (batch, plen)),
                         jnp.int32)
    cache = init_kv_cache(cfg, batch, plen + 8)
    from functools import partial

    import bench

    def make(fn):
        # chained-iteration timing (bench.py protocol: the tunnel's
        # block_until_ready does not synchronize). The carry scalar z
        # feeds back into the tokens through a runtime-opaque zero
        # (isnan of real data), so each prefill depends on the previous
        # one — XLA cannot hoist the loop-invariant prompt pass — and z
        # pulls from the logits AND the last layer's cached V, so no
        # layer is dead code.
        @partial(jax.jit, static_argnames=("kk",))
        def loop(z0, kk):
            def it(i, carry):
                z, c = carry
                dep = jnp.where(jnp.isnan(z), 1, 0).astype(jnp.int32)
                logits, c2 = fn(params, prompt + dep, c, cfg)
                z2 = logits[0, 0] + c2[-1]["v"] \
                    .astype(jnp.float32)[0, plen - 1, 0, 0]
                return (z2, c2)
            z, _ = jax.lax.fori_loop(0, kk, it, (z0, cache))
            return z.reshape(1)
        return lambda x, kk: loop(x, kk)

    z0 = jnp.zeros((), jnp.float32)
    t_block = bench._chain_time(make(prefill), z0, k=4)

    # The scan oracle is measured at a CAPPED length and scaled
    # linearly: a plen-1024 scan is a 1024-iteration decode program
    # whose HLO the tunneled remote-compile service cannot even build
    # (broken pipe) — itself evidence for the blockwise path. The scan
    # is exactly linear in plen (one decode_step per position, no
    # cross-position reuse), so t_scan(plen) = t_scan(cap) * plen/cap.
    scan_cap = min(plen, 256)
    rng2 = np.random.default_rng(1)
    prompt_cap = jnp.asarray(
        rng2.integers(0, cfg.vocab, (batch, scan_cap)), jnp.int32)
    cache_cap = init_kv_cache(cfg, batch, scan_cap + 8)

    def make_scan_cap():
        from functools import partial as _partial

        @_partial(jax.jit, static_argnames=("kk",))
        def loop(z0, kk):
            def it(i, carry):
                z, c = carry
                dep = jnp.where(jnp.isnan(z), 1, 0).astype(jnp.int32)
                logits, c2 = prefill_scan(params, prompt_cap + dep, c,
                                          cfg)
                z2 = logits[0, 0] + c2[-1]["v"] \
                    .astype(jnp.float32)[0, scan_cap - 1, 0, 0]
                return (z2, c2)
            z, _ = jax.lax.fori_loop(0, kk, it, (z0, cache_cap))
            return z.reshape(1)
        return lambda x, kk: loop(x, kk)

    t_scan_cap = bench._chain_time(make_scan_cap(), z0, k=1)
    t_scan = t_scan_cap * plen / scan_cap
    on_tpu = jax.default_backend() == "tpu"
    print(f"ttft plen={plen} batch={batch}: blockwise "
          f"{t_block*1e3:.2f} ms  scan {t_scan*1e3:.2f} ms "
          f"(measured {t_scan_cap*1e3:.2f} ms at plen {scan_cap}, "
          f"linear-scaled)  speedup {t_scan/t_block:.1f}x",
          file=sys.stderr)
    print(json.dumps({
        "metric": f"time-to-first-token, plen {plen}, batch {batch}, "
                  f"{'bf16 v5e chip' if on_tpu else jax.default_backend()}",
        "value": round(t_block * 1e3, 3),
        "unit": "ms",
        "vs_baseline": round(t_scan / t_block, 2),
        "vs_baseline_meaning": "speedup over one-token-at-a-time "
                               f"prefill (scan measured at plen "
                               f"{scan_cap}, linear-scaled)",
    }))


if __name__ == "__main__":
    main()
