"""KV-cache decode throughput for the flagship model on the live chip.

Methodology: one jitted `generate` is a single XLA program (prefill
scan + decode scan, static shapes). The tunneled dispatch floor and the
prefill cost cancel by differencing two generation lengths:

    tokens/s = (N2 - N1) / (t(N2) - t(N1))

where t(N2) - t(N1) is the MEDIAN OF INTERLEAVED PAIRS (paired_diff):
the two programs are timed back-to-back within each pair so the
chip's between-window throughput drift — the source of the round-3
numbers' ±30% run-to-run scatter — cancels, the same cure bench.py's
paired-ratio protocol applies to the headline number. Each recorded
value now also prints its own MAD/median spread.

Decode is matvec-bound (one (1, d) activation against every weight
matrix per token), so the interesting ceiling is HBM bandwidth over
the ~param bytes read per token, reported as achieved/ceiling.

--ttft measures time-to-first-token: the one-forward-pass blockwise
prefill (models.generate.prefill, flash-kernel path) vs the
token-at-a-time scan oracle at a given prompt length — the round-4
VERDICT item making prefill O(plen/block) instead of O(plen) serial
decode steps. Methodology: every timed program is a `generate` call
(the shape the tunneled remote compiler demonstrably handles — direct
chains of the prefill graph reproducibly kill it with a broken pipe):
blockwise prefill cost = t(generate, plen=P) − t(generate, plen=P0)
at fixed max_new (the dispatch floor and decode tail cancel), and the
scan baseline = (P − P0) / decode_steps_per_s measured by the main
length-differencing — per-token scan prefill IS a decode step (same
decode_step, same cache math), so this is the scan's cost without
compiling a plen-long scan program.

Usage: python benchmarks/decode_bench.py [--tiny] [--ttft] [--plen N]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from rlo_tpu.models.generate import generate  # noqa: E402
from rlo_tpu.models.transformer import (TransformerConfig,  # noqa: E402
                                        init_params)

V5E_HBM_GBPS = 819.0


def paired_diff(params, hi_args, lo_args, cfg, pairs=9, label="decode"):
    """Median of interleaved per-pair differences t(hi) - t(lo).

    The round-3 decode numbers carried ~±30% run-to-run drift because
    the two legs of the differencing were timed in separate blocks:
    the tunneled chip's throughput drifts between measurement windows
    (docs/DESIGN.md, "the chip drifts ~1.6x between windows"), so any
    window shift between block t(N1) and block t(N2) lands directly in
    the difference. Same cure as bench.py's paired-ratio protocol
    (round-2 VERDICT item 2): compile and warm BOTH programs, then
    alternate hi/lo timings back-to-back and take the median of the
    per-pair differences — drift slow relative to one pair cancels.
    The median runs over ALL pairs including non-positive ones —
    dropping negative pairs before the median would censor the noise
    distribution one-sidedly and bias the estimate up (and made the
    tiny smoke test flaky); only a non-positive MEDIAN means the gap
    is genuinely inside dispatch noise, and that raises.
    Returns (median_diff_seconds, relative_spread) where the spread is
    MAD/median over all pairs — the number carries its own
    uncertainty instead of hiding it.
    """
    def build(args):
        prompt, max_new, max_len = args
        f = jax.jit(lambda p, t: generate(p, t, cfg, max_new=max_new,
                                          max_len=max_len))
        np.asarray(f(params, prompt))  # compile + warm
        return lambda: np.asarray(f(params, prompt))

    run_hi, run_lo = build(hi_args), build(lo_args)
    run_hi(), run_lo()  # second warm pass after both are compiled
    diffs = []
    for _ in range(pairs):
        t0 = time.perf_counter()
        run_hi()
        t1 = time.perf_counter()
        run_lo()
        t2 = time.perf_counter()
        diffs.append((t1 - t0) - (t2 - t1))
    med = float(np.median(diffs))
    if med <= 0:
        raise RuntimeError(
            f"{label} paired differencing failed: median pair "
            f"difference {med*1e3:.3f} ms <= 0 over {pairs} pairs "
            f"(hi={hi_args[1:]}, lo={lo_args[1:]}) — the timing gap "
            f"is inside dispatch noise; widen the length gap")
    mad = float(np.median(np.abs(np.asarray(diffs) - med)))
    return med, mad / med


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--cast-weights", action="store_true",
                    help="store weights in HBM as bf16 (measured "
                         "SLOWER on v5e — see comment at the ceiling)")
    ap.add_argument("--ttft", action="store_true",
                    help="time-to-first-token: blockwise prefill vs "
                         "the scan oracle")
    ap.add_argument("--plen", type=int, default=1024,
                    help="prompt length for --ttft")
    args = ap.parse_args()

    if args.ttft:
        return ttft(args)

    if args.tiny:
        cfg = TransformerConfig(vocab=128, d_model=64, n_heads=4,
                                n_layers=2, d_ff=256, dtype="float32")
        # wide length gap: at toy sizes the two timings are micro-
        # seconds apart and host contention (e.g. the full test suite)
        # can invert a narrow pair, tripping the differencing guard
        batch, n1, n2 = args.batch or 2, 4, 48
    else:
        cfg = TransformerConfig(vocab=32768, d_model=1024, n_heads=16,
                                n_layers=8, d_ff=4096, dtype="bfloat16")
        # batch swept on the chip (2026-07-30): 8 -> 8.9k tok/s, 32 ->
        # 28-40k across runs (weight reads amortized), 64 -> 27.4k
        # (cache-attention traffic dominates); 32 is the knee
        batch, n1, n2 = args.batch or 32, 64, 192

    params = init_params(jax.random.PRNGKey(0), cfg)
    # Weight residency: init_params keeps f32 (training layout); the
    # in-scan .astype(dt) is hoisted by XLA into a one-time bf16 copy,
    # so the streamed bytes are 2/param either way and the ceiling
    # below reflects the streamed copy (review finding). Pre-casting
    # the tree (--cast-weights) measured no better on the chip
    # (2026-07-30: 22.3k vs 22-40k tok/s default across runs — decode
    # differencing on the tunnel drifts ~±30% run to run, so treat
    # single-run comparisons here with suspicion).
    if args.cast_weights and cfg.dtype == "bfloat16":
        params = jax.tree.map(
            lambda p: p.astype(jnp.bfloat16)
            if p.dtype == jnp.float32 else p, params)
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree.leaves(params))
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab, (batch, 16)),
                         jnp.int32)
    max_len = prompt.shape[1] + n2
    diff, spread = paired_diff(params, (prompt, n2, max_len),
                               (prompt, n1, max_len), cfg)
    steps_s = (n2 - n1) / diff
    tok_s = steps_s * batch
    print(f"paired differencing spread (MAD/median): {spread:.1%}",
          file=sys.stderr)
    on_tpu = jax.default_backend() == "tpu"
    # HBM ceiling: every decode step reads at least the param bytes
    # (bf16 weights; embeddings gather + cache traffic excluded)
    bytes_per_step = n_params * (2 if cfg.dtype == "bfloat16" else 4)
    ceiling_steps = V5E_HBM_GBPS * 1e9 / bytes_per_step
    frac = steps_s / ceiling_steps if on_tpu else float("nan")
    print(f"params={n_params/1e6:.1f}M batch={batch}: "
          f"{steps_s:,.0f} steps/s, {tok_s:,.0f} tok/s"
          + (f", {frac:.1%} of the HBM weight-streaming ceiling"
             if on_tpu else " (not a TPU)"),
          file=sys.stderr)
    print(json.dumps({
        "metric": f"KV-cache greedy decode, {n_params/1e6:.0f}M params, "
                  f"batch {batch}, "
                  f"{'bf16 v5e chip' if on_tpu else jax.default_backend()}",
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(frac, 4) if on_tpu else 0.0,
        "vs_baseline_meaning": "fraction of the HBM weight-streaming "
                               "ceiling (819 GB/s / param bytes)",
    }))


def ttft(args):
    if args.tiny:
        cfg = TransformerConfig(vocab=128, d_model=64, n_heads=4,
                                n_layers=2, d_ff=256, dtype="float32")
        batch = args.batch or 2
        plen = min(args.plen, 128)
        n_dec = 4
    else:
        cfg = TransformerConfig(vocab=32768, d_model=1024, n_heads=16,
                                n_layers=8, d_ff=4096, dtype="bfloat16")
        batch = args.batch or 4
        plen = args.plen
        n_dec = 4
    p0 = 16
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)

    def prompt_of(n):
        return jnp.asarray(rng.integers(0, cfg.vocab, (batch, n)),
                           jnp.int32)

    # blockwise prefill cost by PROMPT-LENGTH differencing of whole
    # generate programs: decode tail (fixed n_dec) and dispatch floor
    # cancel in the difference; interleaved pairs cancel window drift
    t_block, spread_b = paired_diff(
        params, (prompt_of(plen), n_dec, plen + n_dec),
        (prompt_of(p0), n_dec, p0 + n_dec), cfg,
        label="prefill (gap = --plen)")

    # scan-prefill baseline: one token of scan prefill IS one decode
    # step (same decode_step, same cache attend), so its cost is the
    # decode steps/s from the same length-differencing as the main
    # mode — no plen-long scan program needs to compile. Wide gap: at
    # batch 1 a step is ~0.15 ms and a narrow pair sits inside the
    # dispatch noise (the differencing guard tripped on it)
    n1, n2 = 8, 192
    d_dec, spread_d = paired_diff(
        params, (prompt_of(p0), n2, p0 + n2),
        (prompt_of(p0), n1, p0 + n2), cfg,
        label=f"ttft decode baseline (gap = n1,n2={n1},{n2})")
    t_step = d_dec / (n2 - n1)
    t_scan = t_step * (plen - p0)
    print(f"ttft paired spreads: prefill {spread_b:.1%}  decode "
          f"{spread_d:.1%}", file=sys.stderr)

    on_tpu = jax.default_backend() == "tpu"
    print(f"ttft plen={plen} batch={batch}: blockwise prefill of "
          f"{plen - p0} tokens {t_block*1e3:.2f} ms  scan "
          f"{t_scan*1e3:.2f} ms ({t_step*1e3:.3f} ms/token decode-"
          f"differenced)  speedup {t_scan/t_block:.1f}x",
          file=sys.stderr)
    print(json.dumps({
        "metric": f"time-to-first-token, blockwise prefill of "
                  f"{plen - p0} prompt tokens, batch {batch}, "
                  f"{'bf16 v5e chip' if on_tpu else jax.default_backend()}",
        "value": round(t_block * 1e3, 3),
        "unit": "ms",
        "vs_baseline": round(t_scan / t_block, 2),
        "vs_baseline_meaning": "speedup over token-at-a-time prefill "
                               "(= decode-step cost per token, "
                               "length-differenced)",
    }))


if __name__ == "__main__":
    main()
