"""KV-cache decode throughput for the flagship model on the live chip.

Methodology: one jitted `generate` is a single XLA program (prefill
scan + decode scan, static shapes). The tunneled dispatch floor and the
prefill cost cancel by differencing two generation lengths:

    tokens/s = (N2 - N1) / (t(N2) - t(N1))

Decode is matvec-bound (one (1, d) activation against every weight
matrix per token), so the interesting ceiling is HBM bandwidth over
the ~param bytes read per token, reported as achieved/ceiling.

--ttft measures time-to-first-token: the one-forward-pass blockwise
prefill (models.generate.prefill, flash-kernel path) vs the
token-at-a-time scan oracle at a given prompt length — the round-4
VERDICT item making prefill O(plen/block) instead of O(plen) serial
decode steps. Methodology: every timed program is a `generate` call
(the shape the tunneled remote compiler demonstrably handles — direct
chains of the prefill graph reproducibly kill it with a broken pipe):
blockwise prefill cost = t(generate, plen=P) − t(generate, plen=P0)
at fixed max_new (the dispatch floor and decode tail cancel), and the
scan baseline = (P − P0) / decode_steps_per_s measured by the main
length-differencing — per-token scan prefill IS a decode step (same
decode_step, same cache math), so this is the scan's cost without
compiling a plen-long scan program.

Usage: python benchmarks/decode_bench.py [--tiny] [--ttft] [--plen N]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from rlo_tpu.models.generate import generate  # noqa: E402
from rlo_tpu.models.transformer import (TransformerConfig,  # noqa: E402
                                        init_params)

V5E_HBM_GBPS = 819.0


def time_generate(params, prompt, cfg, max_new, max_len, reps=7):
    f = jax.jit(lambda p, t: generate(p, t, cfg, max_new=max_new,
                                      max_len=max_len))
    np.asarray(f(params, prompt))  # compile + warm
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(f(params, prompt))
        ts.append(time.perf_counter() - t0)
    return float(min(ts))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--cast-weights", action="store_true",
                    help="store weights in HBM as bf16 (measured "
                         "SLOWER on v5e — see comment at the ceiling)")
    ap.add_argument("--ttft", action="store_true",
                    help="time-to-first-token: blockwise prefill vs "
                         "the scan oracle")
    ap.add_argument("--plen", type=int, default=1024,
                    help="prompt length for --ttft")
    args = ap.parse_args()

    if args.ttft:
        return ttft(args)

    if args.tiny:
        cfg = TransformerConfig(vocab=128, d_model=64, n_heads=4,
                                n_layers=2, d_ff=256, dtype="float32")
        # wide length gap: at toy sizes the two timings are micro-
        # seconds apart and host contention (e.g. the full test suite)
        # can invert a narrow pair, tripping the differencing guard
        batch, n1, n2 = args.batch or 2, 4, 48
    else:
        cfg = TransformerConfig(vocab=32768, d_model=1024, n_heads=16,
                                n_layers=8, d_ff=4096, dtype="bfloat16")
        # batch swept on the chip (2026-07-30): 8 -> 8.9k tok/s, 32 ->
        # 28-40k across runs (weight reads amortized), 64 -> 27.4k
        # (cache-attention traffic dominates); 32 is the knee
        batch, n1, n2 = args.batch or 32, 64, 192

    params = init_params(jax.random.PRNGKey(0), cfg)
    # Weight residency: init_params keeps f32 (training layout); the
    # in-scan .astype(dt) is hoisted by XLA into a one-time bf16 copy,
    # so the streamed bytes are 2/param either way and the ceiling
    # below reflects the streamed copy (review finding). Pre-casting
    # the tree (--cast-weights) measured no better on the chip
    # (2026-07-30: 22.3k vs 22-40k tok/s default across runs — decode
    # differencing on the tunnel drifts ~±30% run to run, so treat
    # single-run comparisons here with suspicion).
    if args.cast_weights and cfg.dtype == "bfloat16":
        params = jax.tree.map(
            lambda p: p.astype(jnp.bfloat16)
            if p.dtype == jnp.float32 else p, params)
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree.leaves(params))
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab, (batch, 16)),
                         jnp.int32)
    max_len = prompt.shape[1] + n2
    t1 = time_generate(params, prompt, cfg, n1, max_len)
    t2 = time_generate(params, prompt, cfg, n2, max_len)
    if t2 <= t1:
        raise RuntimeError(
            f"differencing failed (t({n2})={t2:.3f} <= t({n1})={t1:.3f})"
            f" — dispatch noise swamped the decode cost")
    steps_s = (n2 - n1) / (t2 - t1)
    tok_s = steps_s * batch
    on_tpu = jax.default_backend() == "tpu"
    # HBM ceiling: every decode step reads at least the param bytes
    # (bf16 weights; embeddings gather + cache traffic excluded)
    bytes_per_step = n_params * (2 if cfg.dtype == "bfloat16" else 4)
    ceiling_steps = V5E_HBM_GBPS * 1e9 / bytes_per_step
    frac = steps_s / ceiling_steps if on_tpu else float("nan")
    print(f"params={n_params/1e6:.1f}M batch={batch}: "
          f"{steps_s:,.0f} steps/s, {tok_s:,.0f} tok/s"
          + (f", {frac:.1%} of the HBM weight-streaming ceiling"
             if on_tpu else " (not a TPU)"),
          file=sys.stderr)
    print(json.dumps({
        "metric": f"KV-cache greedy decode, {n_params/1e6:.0f}M params, "
                  f"batch {batch}, "
                  f"{'bf16 v5e chip' if on_tpu else jax.default_backend()}",
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(frac, 4) if on_tpu else 0.0,
        "vs_baseline_meaning": "fraction of the HBM weight-streaming "
                               "ceiling (819 GB/s / param bytes)",
    }))


def ttft(args):
    if args.tiny:
        cfg = TransformerConfig(vocab=128, d_model=64, n_heads=4,
                                n_layers=2, d_ff=256, dtype="float32")
        batch = args.batch or 2
        plen = min(args.plen, 128)
        n_dec = 4
    else:
        cfg = TransformerConfig(vocab=32768, d_model=1024, n_heads=16,
                                n_layers=8, d_ff=4096, dtype="bfloat16")
        batch = args.batch or 4
        plen = args.plen
        n_dec = 4
    p0 = 16
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)

    def prompt_of(n):
        return jnp.asarray(rng.integers(0, cfg.vocab, (batch, n)),
                           jnp.int32)

    # blockwise prefill cost by PROMPT-LENGTH differencing of whole
    # generate programs: decode tail (fixed n_dec) and dispatch floor
    # cancel in the difference
    t_hi = time_generate(params, prompt_of(plen), cfg, n_dec,
                         plen + n_dec)
    t_lo = time_generate(params, prompt_of(p0), cfg, n_dec, p0 + n_dec)
    t_block = t_hi - t_lo
    if t_block <= 0:
        raise RuntimeError(
            f"prefill differencing failed (t({plen})={t_hi:.4f} <= "
            f"t({p0})={t_lo:.4f})")

    # scan-prefill baseline: one token of scan prefill IS one decode
    # step (same decode_step, same cache attend), so its cost is the
    # decode steps/s from the same length-differencing as the main
    # mode — no plen-long scan program needs to compile. Wide gap: at
    # batch 1 a step is ~0.15 ms and a narrow pair sits inside the
    # dispatch noise (the differencing guard tripped on it)
    n1, n2 = 8, 192
    td1 = time_generate(params, prompt_of(p0), cfg, n1, p0 + n2)
    td2 = time_generate(params, prompt_of(p0), cfg, n2, p0 + n2)
    if td2 <= td1:
        raise RuntimeError(
            f"decode differencing failed (t({n2})={td2:.4f} <= "
            f"t({n1})={td1:.4f})")
    t_step = (td2 - td1) / (n2 - n1)
    t_scan = t_step * (plen - p0)

    on_tpu = jax.default_backend() == "tpu"
    print(f"ttft plen={plen} batch={batch}: blockwise prefill of "
          f"{plen - p0} tokens {t_block*1e3:.2f} ms  scan "
          f"{t_scan*1e3:.2f} ms ({t_step*1e3:.3f} ms/token decode-"
          f"differenced)  speedup {t_scan/t_block:.1f}x",
          file=sys.stderr)
    print(json.dumps({
        "metric": f"time-to-first-token, blockwise prefill of "
                  f"{plen - p0} prompt tokens, batch {batch}, "
                  f"{'bf16 v5e chip' if on_tpu else jax.default_backend()}",
        "value": round(t_block * 1e3, 3),
        "unit": "ms",
        "vs_baseline": round(t_scan / t_block, 2),
        "vs_baseline_meaning": "speedup over token-at-a-time prefill "
                               "(= decode-step cost per token, "
                               "length-differenced)",
    }))


if __name__ == "__main__":
    main()
