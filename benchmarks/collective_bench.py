"""Collective data-plane benchmark — BENCH_collective.json
(docs/DESIGN.md §21).

Two leg families, one perf_gate document:

**Seed-exact sim legs** (``sim_*``): run each instrumented schedule
(ring allreduce, recursive doubling) over the deterministic SimWorld
substrate at n in {4, 8, 16} and pin, at ZERO tolerance:

  - ``steps``: Ev.STEP events the instrumentation emitted — the
    ledger's step count times ranks; any dropped or duplicated probe
    emission moves it;
  - ``bytes``: the fleet's ``coll_bytes`` counter total, which must
    equal the cost ledger's fleet-wide byte account exactly (the
    measured-equals-predicted contract rlo-scope enforces as S2);
  - ``events``: the simulator's delivery-schedule length — the
    substrate message cost of the schedule, instrumentation included
    (instrumentation must NOT change it: probes never send);
  - ``vtime_usec``: virtual drain time — seed-exact latency;
  - ``ledger_digest``: the schedule's canonical per-step/edge listing
    hash — pins the proven schedule shape itself.

**Informational wall-clock legs** (``wall_*``): per-algorithm achieved
GB/s of the jax executor (ops/tpu_collectives.allreduce) against
``lax.psum`` on a shard_map mesh. On CPU (this repo's CI) the mesh is
4 forced host devices and the figures are informational only (CPU
serializes every ppermute through one memory bus — see
``allreduce_cost``'s model notes); on a real TPU slice the same legs
become the ROADMAP item 2 bandwidth bar. ``direction: higher`` with
null tolerance: perf_gate requires presence, not level.

Usage:
    python benchmarks/collective_bench.py --out BENCH_collective.json
    python benchmarks/collective_bench.py --quick   # sim legs only
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

#: per-rank payload for every leg: 256 KiB f32 (divisible by every
#: leg's n, so chunking is exact and the ledger's byte figures match
#: the closed forms with no padding residue)
NBYTES = 1 << 18

SIM_NS = (4, 8, 16)
SIM_SCHEDULES = ("ring_allreduce", "recursive_doubling")

WALL_ALGORITHMS = ("psum", "ring", "recursive_doubling",
                   "halving_doubling")
WALL_DEVICES = 4
WALL_ITERS = 20


def exact(value):
    return {"value": value, "direction": "exact", "tolerance": None}


def info(value):
    return {"value": value, "direction": "higher", "tolerance": None}


def sim_legs() -> dict:
    """The seed-exact family: every figure is a pure function of
    (schedule, n, seed) and gates at zero tolerance."""
    from rlo_tpu.observe.ledger import ledger
    from rlo_tpu.tools.rlo_scope import run_sim_collective

    metrics = {}
    for schedule in SIM_SCHEDULES:
        for n in SIM_NS:
            run = run_sim_collective(schedule, n, NBYTES, seed=0)
            led = ledger(schedule, n, NBYTES)
            if not run["result_correct"]:
                raise RuntimeError(
                    f"{schedule} n={n}: wrong allreduce result on "
                    f"the sim substrate")
            fleet_bytes = sum(run["coll_bytes"])
            if fleet_bytes != led.total_bytes:
                raise RuntimeError(
                    f"{schedule} n={n}: measured fleet bytes "
                    f"{fleet_bytes} != ledger {led.total_bytes}")
            pfx = f"sim_{schedule}_n{n}"
            metrics[f"{pfx}.steps"] = exact(len(run["events"]))
            metrics[f"{pfx}.bytes"] = exact(fleet_bytes)
            metrics[f"{pfx}.events"] = exact(run["sim_events"])
            metrics[f"{pfx}.vtime_usec"] = exact(
                run["drain_vtime_usec"])
            metrics[f"{pfx}.ledger_digest"] = exact(led.digest())
            print(f"{pfx}: {len(run['events'])} step events, "
                  f"{fleet_bytes} B, {run['sim_events']} sim events, "
                  f"drain {run['drain_vtime_usec']}us",
                  file=sys.stderr)
    return metrics


def wall_legs() -> dict:
    """The informational family: jax executor GB/s per algorithm vs
    lax.psum on a shard_map mesh (forced host devices on CPU)."""
    import inspect

    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    from rlo_tpu.observe.ledger import ledger
    from rlo_tpu.ops import tpu_collectives

    # older-jax compat: lax.axis_size is the psum of a static 1 (which
    # old jax already evaluates statically), and the replication check
    # kwarg was renamed check_rep -> check_vma across versions
    if not hasattr(lax, "axis_size"):
        lax.axis_size = lambda name: lax.psum(1, name)
    sm_kw = {}
    sm_params = inspect.signature(shard_map).parameters
    for kwname in ("check_rep", "check_vma"):
        if kwname in sm_params:
            sm_kw[kwname] = False
            break

    n_dev = len(jax.devices())
    devs = jax.devices()[:WALL_DEVICES]
    n = len(devs)
    mesh = Mesh(devs, ("x",))
    x = jnp.ones((n, NBYTES // 4), jnp.float32)

    # ring-allreduce bus bytes per chip from the ledger — the same
    # single source of truth bench.py uses
    bus_bytes = ledger("ring_allreduce", n, NBYTES).bytes_per_rank

    metrics = {}
    t_psum = None
    for alg in WALL_ALGORITHMS:
        if alg == "psum":
            def body(v):
                return jax.lax.psum(v, "x")
        else:
            def body(v, _alg=alg):
                return tpu_collectives.allreduce(
                    x=v, axis="x", algorithm=_alg)
        fn = jax.jit(shard_map(body, mesh=mesh, in_specs=P("x"),
                               out_specs=P(), **sm_kw))
        fn(x).block_until_ready()  # compile outside the timed window
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(WALL_ITERS):
                out = fn(x)
            out.block_until_ready()
            best = min(best, (time.perf_counter() - t0) / WALL_ITERS)
        gbps = bus_bytes / best / 1e9
        if alg == "psum":
            t_psum = best
        metrics[f"wall_{alg}_n{n}.gbps"] = info(round(gbps, 3))
        if t_psum is not None and alg != "psum":
            metrics[f"wall_{alg}_n{n}.vs_psum"] = info(
                round(t_psum / best, 4))
        print(f"wall_{alg}_n{n}: {best * 1e3:.3f} ms/iter "
              f"({gbps:.2f} GB/s)", file=sys.stderr)
    metrics["wall.devices"] = exact(n)
    metrics["wall.backend_tpu"] = exact(
        1 if jax.default_backend() == "tpu" else 0)
    print(f"wall legs: backend={jax.default_backend()} "
          f"devices={n_dev} (using {n})", file=sys.stderr)
    return metrics


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="sim legs only (skip the jax wall legs)")
    ap.add_argument("--out", help="write benchmark JSON here")
    args = ap.parse_args(argv)

    metrics = sim_legs()
    if not args.quick:
        metrics.update(wall_legs())

    doc = {
        "suite": "collective_bench",
        "config": {"nbytes": NBYTES, "seed": 0,
                   "sim_ns": list(SIM_NS),
                   "sim_schedules": list(SIM_SCHEDULES),
                   "wall_devices": WALL_DEVICES,
                   "wall_iters": WALL_ITERS},
        "metrics": metrics,
    }
    text = json.dumps(doc, indent=1, sort_keys=True)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text + "\n")
    else:
        print(text)
    return 0


if __name__ == "__main__":
    # the wall legs need a multi-device mesh; force host devices
    # BEFORE jax initializes (harmless under a real TPU runtime,
    # which ignores the host-platform flag)
    if "jax" not in sys.modules:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={WALL_DEVICES}")
    sys.exit(main())
