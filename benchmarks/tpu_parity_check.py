"""On-chip numerics parity checks (run on the real TPU, outside the
CPU-forced pytest conftest):

1. flash_block_decode vs the einsum block oracle on TPU (Mosaic path,
   not the interpreter).
2. Greedy speculative_generate == plain greedy generate token-for-token
   on TPU — the losslessness claim under the production kernels
   (decode_step takes flash T=1, the verify takes flash T=gamma).

Exit 0 on full parity; prints per-check status.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from rlo_tpu.models.generate import (_attend_cache_block,  # noqa: E402
                                     generate)
from rlo_tpu.models.speculative import speculative_generate  # noqa: E402
from rlo_tpu.models.transformer import (TransformerConfig,  # noqa: E402
                                        init_params)
from rlo_tpu.pallas.decode import flash_block_decode  # noqa: E402


def check_kernel():
    rng = np.random.default_rng(0)
    b, T, nh, nkv, d, L = 2, 4, 8, 2, 64, 512
    q = jnp.asarray(rng.standard_normal((b, T, nh, d)), jnp.bfloat16)
    kc = jnp.asarray(rng.standard_normal((b, nkv, d, L)), jnp.bfloat16)
    vc = jnp.asarray(rng.standard_normal((b, nkv, d, L)), jnp.bfloat16)
    pos0 = jnp.asarray([100, L - T], jnp.int32)
    scale = 1.0 / np.sqrt(d)
    got = np.asarray(jax.jit(
        lambda q, k, v: flash_block_decode(q, k, v, pos0, scale))(
            q, kc, vc))
    pos_q = pos0[:, None] + jnp.arange(T, dtype=jnp.int32)
    want = np.asarray(jax.jit(
        lambda q, k, v: _attend_cache_block(q, k, v, pos_q, scale,
                                            use_flash=False))(
            q, kc, vc))
    err = np.max(np.abs(got - want))
    ok = err < 2e-2  # bf16-dot class
    print(f"flash_block_decode vs einsum (TPU): max|diff| {err:.2e} "
          f"{'OK' if ok else 'FAIL'}")
    return ok


def check_speculative(kv_heads=None, kv_cache_dtype=None):
    import dataclasses
    # head_dim must pass can_flash_decode (64 or %128==0) or both
    # paths silently take the einsum fallback and the check pins
    # nothing: 512/8 = 64
    cfg = TransformerConfig(vocab=4096, d_model=512, n_heads=8,
                            n_layers=4, d_ff=1024, dtype="bfloat16")
    if kv_heads:
        cfg = dataclasses.replace(cfg, n_kv_heads=kv_heads,
                                  pos_encoding="rope")
    if kv_cache_dtype:
        cfg = dataclasses.replace(cfg, kv_cache_dtype=kv_cache_dtype)
    dcfg = dataclasses.replace(cfg, n_layers=1, d_model=256,
                               n_heads=4, d_ff=256,
                               n_kv_heads=None)
    plen, max_new, gamma = 32, 48, 4
    from rlo_tpu.pallas.decode import can_flash_decode
    assert can_flash_decode(plen + max_new + gamma, cfg.head_dim), \
        "config fails the flash gate; this check would pin nothing"
    params = init_params(jax.random.PRNGKey(0), cfg)
    dparams = init_params(jax.random.PRNGKey(1), dcfg)
    rng = np.random.default_rng(2)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab, (2, plen)),
                         jnp.int32)
    want = np.asarray(jax.jit(lambda p, t: generate(
        p, t, cfg, max_new=max_new))(params, prompt))
    got = np.asarray(jax.jit(lambda p, d, t: speculative_generate(
        p, d, t, cfg, dcfg, max_new=max_new, gamma=gamma))(
            params, dparams, prompt))
    n_mismatch = int((got != want).sum())
    tag = (f"kv_heads={kv_heads} cache={kv_cache_dtype}"
           if (kv_heads or kv_cache_dtype) else "dense")
    print(f"speculative greedy parity (TPU, {tag}): "
          f"{n_mismatch} mismatched tokens of {want.size} "
          f"{'OK' if n_mismatch == 0 else 'FAIL'}")
    return n_mismatch == 0


def main():
    print(f"backend: {jax.default_backend()}, {jax.devices()}")
    ok = check_kernel()
    ok &= check_speculative()
    ok &= check_speculative(kv_heads=2)
    ok &= check_speculative(kv_cache_dtype="int8")
    print("ALL PARITY CHECKS PASSED" if ok else "PARITY FAILURES")
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
