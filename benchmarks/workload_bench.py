"""Traffic-laboratory benchmark — BENCH_workload.json
(docs/DESIGN.md §14; ROADMAP item 4's "planet-scale traffic lab").

Pins the whole workloads subsystem seed-exact through
``rlo_tpu.tools.perf_gate``:

  - **trace generators** (rlo_tpu/workloads/traces.py): request count
    + SHA-256 trace digest for every canned workload shape (diurnal /
    mmpp / flash / swarm) at fixed seeds — a generator edit that moves
    one token fails here with a named cause.
  - **calendar-queue scale**: the n=10,000-rank protocol-only fan-out
    AND post-kill membership-convergence datapoints, run on
    ``SimWorld(scheduler="calendar")`` — virtual time and schedule
    length gate exact. An in-bench oracle check first replays the
    n=256 fan-out on BOTH schedulers and hard-asserts identical
    (vtime, events): the §14 pop-order-equivalence rule, enforced at
    run time on top of the unit tests.
  - **trace-driven serving**: one swarm trace through the 4-rank
    serving fabric (StubBackend over the deterministic simulator —
    drain vtime / events / requeues exact) and one mmpp trace through
    the real tiny-model ``DecodeServer`` open loop (rounds / occupancy
    / efficiency exact) — each with its trace digest pinned, so
    "millions of users" is a replayable input, not a synthetic knob.

``--quick`` shrinks the scale legs (n=1024, no jax serving leg) for
unit-test reproducibility runs; the committed baseline and the
check.sh gate use the FULL config under a wall-time budget (the
10k-rank smoke).

Usage:
    python benchmarks/workload_bench.py --out BENCH_workload.json
    python benchmarks/workload_bench.py --quick
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

#: the big-world datapoint the acceptance criterion names; --quick
#: drops it to 1024 so tests stay fast
BIG_N_FULL = 10_000
BIG_N_QUICK = 1024

#: canned generator pins: (kind, seed, overrides) — defaults
#: everywhere else so the pinned digests cover the default configs
TRACE_PINS = (
    ("diurnal", 0, {}),
    ("mmpp", 0, {}),
    ("flash", 0, {}),
    ("swarm", 0, {}),
)


def exact(value):
    return {"value": value, "direction": "exact", "tolerance": None}


def info(value):
    return {"value": value, "direction": "higher", "tolerance": None}


def _load_bench(name: str):
    """Sibling benchmark module by file path (benchmarks/ is not a
    package)."""
    spec = importlib.util.spec_from_file_location(
        name, Path(__file__).resolve().parent / f"{name}.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def trace_metrics():
    from rlo_tpu.workloads.traces import make_trace

    metrics = {}
    for kind, seed, overrides in TRACE_PINS:
        t0 = time.perf_counter()
        tr = make_trace(kind, seed, **overrides)
        dt = time.perf_counter() - t0
        metrics[f"trace.{kind}.requests"] = exact(len(tr.requests))
        metrics[f"trace.{kind}.digest"] = exact(tr.digest())
        print(f"trace {kind} seed={seed}: {len(tr.requests)} reqs, "
              f"digest {tr.digest()[:12]}, {dt * 1e3:.0f} ms",
              file=sys.stderr)
    return metrics


def scale_metrics(big_n: int, sim_bench):
    """Calendar-queue scale legs + the heap-oracle equivalence
    assertion (docs/DESIGN.md §14)."""
    metrics = {}
    # oracle: same fan-out, both schedulers, identical results
    h = sim_bench.bench_fanout(256, scheduler="heap")
    c = sim_bench.bench_fanout(256, scheduler="calendar")
    assert (h[0], h[1]) == (c[0], c[1]), (
        f"calendar scheduler diverged from the heapq oracle at "
        f"n=256: heap (vtime={h[0]}, events={h[1]}) vs calendar "
        f"(vtime={c[0]}, events={c[1]})")
    metrics["oracle.n256.schedulers_match"] = exact(1)
    print(f"oracle n=256: heap == calendar "
          f"(vtime {h[0]:.4f}, {h[1]} events)", file=sys.stderr)

    vt, events, n_bcast, wdt = sim_bench.bench_fanout(
        big_n, n_bcast=1, scheduler="calendar")
    metrics[f"fanout.n{big_n}.vtime"] = exact(vt)
    metrics[f"fanout.n{big_n}.events_per_bcast"] = exact(
        events / n_bcast)
    metrics[f"fanout.n{big_n}.wall_events_per_sec"] = info(
        events / wdt if wdt > 0 else 0.0)
    print(f"fanout n={big_n}: {vt:.3f} vsec, "
          f"{events / n_bcast:.0f} events/bcast, {wdt:.1f}s wall",
          file=sys.stderr)

    vt, ev, wdt = sim_bench.bench_membership(big_n,
                                             scheduler="calendar")
    metrics[f"member.n{big_n}.converge_vtime"] = exact(vt)
    metrics[f"member.n{big_n}.events"] = exact(ev)
    metrics[f"member.n{big_n}.wall_events_per_sec"] = info(
        ev / wdt if wdt > 0 else 0.0)
    print(f"member n={big_n}: converged {vt:.2f} vsec after kill, "
          f"{ev} events, {wdt:.1f}s wall", file=sys.stderr)
    return metrics


def fabric_trace_metrics(fabric_bench):
    """One swarm trace through the 4-rank serving fabric."""
    from rlo_tpu.workloads.traces import make_trace

    tr = make_trace("swarm", 5, horizon=30.0, rate=0.8,
                    n_prefixes=4, prefix_len=(4, 8), plen=(2, 6),
                    budget=(4, 16), vocab=32000)
    doc = fabric_bench.trace_doc(tr, n=4)
    return {f"fabric.{k}": v for k, v in doc["metrics"].items()}


def serve_trace_metrics(serve_bench):
    """One mmpp trace through the real tiny-model DecodeServer."""
    import jax

    from rlo_tpu.models.transformer import (TransformerConfig,
                                            init_params)
    from rlo_tpu.workloads.traces import make_trace

    cfg = TransformerConfig(vocab=128, d_model=64, n_heads=4,
                            n_layers=2, d_ff=256, dtype="float32")
    params = init_params(jax.random.PRNGKey(0), cfg)
    tr = make_trace("mmpp", 3, horizon=24.0, tenants=3,
                    tenant_rate=1.0, mean_on=6.0, mean_off=10.0,
                    vocab=128, plen=(3, 8), budget=(4, 12))
    doc = serve_bench.trace_leg(params, cfg, tr, tiny=True, slots=2,
                                round_len=4, max_len=64,
                                buckets=(16,))
    return {f"serve.{k}": v for k, v in doc["metrics"].items()}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="n=1024 scale leg, no jax serving leg (the "
                         "committed baseline uses the FULL config)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    import logging
    logging.getLogger("rlo_tpu").setLevel(logging.ERROR)

    big_n = BIG_N_QUICK if args.quick else BIG_N_FULL
    sim_bench = _load_bench("sim_bench")
    fabric_bench = _load_bench("fabric_bench")
    metrics = {}
    metrics.update(trace_metrics())
    metrics.update(scale_metrics(big_n, sim_bench))
    metrics.update(fabric_trace_metrics(fabric_bench))
    if not args.quick:
        serve_bench = _load_bench("serve_bench")
        metrics.update(serve_trace_metrics(serve_bench))
    doc = {
        "suite": "workload_bench",
        "schema": 1,
        "quick": bool(args.quick),
        "config": {"big_n": big_n, "quick": bool(args.quick)},
        "metrics": metrics,
    }
    text = json.dumps(doc, indent=1, sort_keys=True)
    if args.out:
        Path(args.out).write_text(text + "\n")
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
