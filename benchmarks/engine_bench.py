"""Message-engine throughput benchmark — the perf face of the paper's
actual contribution (rootless bcast + IAR over the skip ring).

ROADMAP item 3: three robustness PRs added per-frame work (ARQ, epoch
stamping, metrics, tracing) to the hot path with no engine-throughput
benchmark guarding it. This leg measures, per transport:

  - sustained broadcast throughput (bcast ops/sec and frames/sec);
  - IAR consensus round throughput;
  - op-latency percentiles (p50/p99 estimated from the engines' log2
    histograms — metrics.hist_summary);
  - the **robustness tax**: the same workload with ARQ + metrics +
    profiler enabled vs. everything off, printed as a percent so the
    "fast as the hardware allows" claim is a number, not a vibe.

Transports: ``loopback`` (Python engines, in-process), ``native``
(C engines through ctypes, plus the wholly-native bcast floor),
``sim`` (the deterministic simulator's protocol-only fast path —
virtual-time fan-out latency is seed-exact and therefore gateable at
zero tolerance), and ``tcp`` (one OS process per rank over the socket
mesh via the tcprun launcher; excluded from --quick).

Output: one JSON document (``--out``), schema shared with
benchmarks/sim_bench.py and consumed by ``rlo_tpu.tools.perf_gate``:

    {"suite": "engine_bench", "quick": true, "config": {...},
     "metrics": {"<name>": {"value": V, "direction": "higher|lower|exact",
                            "tolerance": {"factor": F} | {"rel": R} | null}}}

Deterministic metrics (frame counts per bcast on the seeded loopback,
virtual-time latencies on the simulator) carry ``"exact"`` direction —
they catch protocol regressions (an extra frame per hop, an O(log n)
schedule gone O(n)) mechanically. Wall-clock metrics carry generous
``factor`` tolerances so the gate stays non-flaky across machines.

Usage:
    python benchmarks/engine_bench.py --quick --out BENCH_engine.json
    python benchmarks/engine_bench.py --transports loopback,native,tcp
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from rlo_tpu.utils.metrics import hist_quantile  # noqa: E402

#: generous wall-clock tolerance — the gate exists to catch order-of-
#: magnitude hot-path regressions and O(n) blowups, not scheduler
#: jitter (the --quick legs run ~10-100 ms, where a tight factor
#: flakes under load)
WALL_FACTOR = 10.0

PAYLOAD = 256  # bytes per broadcast


def metric(value, direction="higher", tolerance=None):
    return {"value": value, "direction": direction,
            "tolerance": tolerance}


def wall(value):
    return metric(value, "higher", {"factor": WALL_FACTOR})


def wall_lower(value):
    return metric(value, "lower", {"factor": WALL_FACTOR})


def exact(value):
    return metric(value, "exact")


def info(value):
    return metric(value, "higher", None)  # informational: never gated


# ---------------------------------------------------------------------------
# loopback (Python engines)
# ---------------------------------------------------------------------------

def _drive_python(ws, rounds, iar_rounds, arq, obs):
    """One workload on Python engines over the seeded loopback world:
    ``rounds`` rounds of every-rank-broadcasts + pickup, then
    ``iar_rounds`` sequential IAR rounds. Returns raw numbers."""
    from rlo_tpu.engine import EngineManager, ProgressEngine, drain
    from rlo_tpu.transport.loopback import LoopbackWorld

    world = LoopbackWorld(ws, latency=0, seed=1)
    mgr = EngineManager()
    engines = [ProgressEngine(world.transport(r), manager=mgr,
                              arq_rto=0.05 if arq else None)
               for r in range(ws)]
    if obs:
        for e in engines:
            e.enable_metrics()
            e.enable_profiler()
    payload = b"x" * PAYLOAD
    t0 = time.perf_counter()
    for _ in range(rounds):
        for e in engines:
            e.bcast(payload)
        drain([world], engines)
        for e in engines:
            while e.pickup_next() is not None:
                pass
    bcast_dt = time.perf_counter() - t0
    # snapshot BEFORE the IAR phase: frames/sec and the exact
    # frames-per-bcast pin must cover the bcast window only
    bcast_frames = world.delivered_cnt
    t0 = time.perf_counter()
    for i in range(iar_rounds):
        p = engines[i % ws]
        if p.submit_proposal(b"p" * 32, pid=7) == -1:
            drain([world], engines)
            assert p.vote_my_proposal() in (0, 1)
        for e in engines:
            while e.pickup_next() is not None:
                pass
    iar_dt = time.perf_counter() - t0
    out = {
        "bcasts": rounds * ws,
        "bcast_dt": bcast_dt,
        "iar_rounds": iar_rounds,
        "iar_dt": iar_dt,
        "frames": bcast_frames,
    }
    if obs:
        merged = {"count": 0, "sum": 0.0, "min": float("inf"),
                  "max": 0.0, "buckets": None}
        for e in engines:
            h = e.metrics()["op_latency_usec"]["bcast_complete"]
            merged["count"] += h["count"]
            merged["sum"] += h["sum"]
            merged["min"] = min(merged["min"], h["min"])
            merged["max"] = max(merged["max"], h["max"])
            merged["buckets"] = (h["buckets"] if merged["buckets"] is None
                                 else [a + b for a, b in
                                       zip(merged["buckets"],
                                           h["buckets"])])
        out["bcast_p50_usec"] = hist_quantile(merged, 0.5)
        out["bcast_p99_usec"] = hist_quantile(merged, 0.99)
        out["phase_samples"] = sum(
            h["count"]
            for e in engines
            for h in e.metrics()["phases"].values())
        # the ARQ due-heap win lives in this histogram: with nothing
        # due, the per-tick scan is a single heap peek instead of a
        # per-frame walk of every unacked queue (engine.py _arq_wake)
        arq_hist = {"count": 0, "sum": 0.0, "min": float("inf"),
                    "max": 0.0, "buckets": None}
        for e in engines:
            h = e.metrics()["phases"]["arq_scan"]
            arq_hist["count"] += h["count"]
            arq_hist["sum"] += h["sum"]
            arq_hist["min"] = min(arq_hist["min"], h["min"])
            arq_hist["max"] = max(arq_hist["max"], h["max"])
            arq_hist["buckets"] = (
                h["buckets"] if arq_hist["buckets"] is None
                else [a + b for a, b in
                      zip(arq_hist["buckets"], h["buckets"])])
        out["arq_scan_p50_usec"] = hist_quantile(arq_hist, 0.5)
        out["arq_scan_mean_usec"] = (
            arq_hist["sum"] / arq_hist["count"]
            if arq_hist["count"] else 0.0)
    for e in engines:
        e.cleanup()
    return out


def leg_loopback(metrics, quick):
    ws = 4
    rounds = 40 if quick else 200
    iar = 20 if quick else 100
    base = _drive_python(ws, rounds, iar, arq=False, obs=False)
    full = _drive_python(ws, rounds, iar, arq=True, obs=True)
    fps = base["frames"] / base["bcast_dt"]
    ops = base["bcasts"] / base["bcast_dt"]
    fps_full = full["frames"] / full["bcast_dt"]
    ops_full = full["bcasts"] / full["bcast_dt"]
    metrics["loopback.base.frames_per_sec"] = wall(fps)
    metrics["loopback.base.bcast_per_sec"] = wall(ops)
    metrics["loopback.base.iar_rounds_per_sec"] = wall(
        base["iar_rounds"] / base["iar_dt"])
    # seeded loopback + ARQ off => the delivery schedule is
    # deterministic: frames-per-bcast is a protocol-shape invariant
    # (an extra frame per hop is a REGRESSION, not noise)
    metrics["loopback.base.frames_per_bcast"] = exact(
        base["frames"] / base["bcasts"])
    metrics["loopback.obs.frames_per_sec"] = wall(fps_full)
    metrics["loopback.obs.bcast_per_sec"] = wall(ops_full)
    metrics["loopback.obs.iar_rounds_per_sec"] = wall(
        full["iar_rounds"] / full["iar_dt"])
    # the robustness tax: ARQ+metrics+profiler overhead as a percent
    # of base throughput (informational — the obs fps is gated above)
    metrics["loopback.obs.tax_pct"] = info(
        100.0 * (ops / ops_full - 1.0))
    metrics["loopback.obs.bcast_p50_usec"] = wall_lower(
        full["bcast_p50_usec"])
    # the p99 tail is what ARQ retransmit timers look like under load
    # (one 50 ms rto in 160 samples owns the tail): recorded, not gated
    metrics["loopback.obs.bcast_p99_usec"] = info(
        full["bcast_p99_usec"])
    metrics["loopback.obs.phase_samples"] = info(full["phase_samples"])
    # per-tick ARQ scan latency (the ROADMAP item-2 due-heap target):
    # wall-based, recorded informationally — the scan's CORRECTNESS
    # is pinned by the seed-exact frame counts above
    metrics["loopback.obs.arq_scan_p50_usec"] = info(
        full["arq_scan_p50_usec"])
    metrics["loopback.obs.arq_scan_mean_usec"] = info(
        round(full["arq_scan_mean_usec"], 3))
    print(f"loopback: base {ops:.0f} bcast/s {fps:.0f} frames/s | "
          f"obs {ops_full:.0f} bcast/s (tax "
          f"{metrics['loopback.obs.tax_pct']['value']:.1f}%) | "
          f"p50 {full['bcast_p50_usec']:.0f}us "
          f"p99 {full['bcast_p99_usec']:.0f}us", file=sys.stderr)


# ---------------------------------------------------------------------------
# native (C engines)
# ---------------------------------------------------------------------------

def _drive_native(ws, rounds, iar_rounds, arq, obs):
    from rlo_tpu.native.bindings import NativeEngine, NativeWorld

    world = NativeWorld(ws, latency=0, seed=1)
    engines = [NativeEngine(world, r) for r in range(ws)]
    for e in engines:
        if arq:
            e.enable_arq(50_000)
        if obs:
            e.enable_metrics()
            e.enable_profiler()
    payload = b"x" * PAYLOAD
    t0 = time.perf_counter()
    for _ in range(rounds):
        for e in engines:
            e.bcast(payload)
        world.drain()
        for e in engines:
            while e.pickup_next() is not None:
                pass
    bcast_dt = time.perf_counter() - t0
    # snapshot BEFORE the IAR phase (same rule as _drive_python)
    bcast_frames = world.delivered_cnt
    t0 = time.perf_counter()
    for i in range(iar_rounds):
        p = engines[i % ws]
        if p.submit_proposal(b"p" * 32, pid=7) == -1:
            world.drain()
            assert p.vote_my_proposal() in (0, 1)
        for e in engines:
            while e.pickup_next() is not None:
                pass
    iar_dt = time.perf_counter() - t0
    out = {
        "bcasts": rounds * ws,
        "bcast_dt": bcast_dt,
        "iar_rounds": iar_rounds,
        "iar_dt": iar_dt,
        "frames": bcast_frames,
    }
    if obs:
        h = engines[0].metrics()["op_latency_usec"]["bcast_complete"]
        out["bcast_p50_usec"] = hist_quantile(h, 0.5)
        out["phase_samples"] = sum(
            ph["count"]
            for e in engines
            for ph in e.metrics()["phases"].values())
        # the C due-heap win lives here, exactly as the Python heap's
        # did: with nothing due the per-tick scan is one heap peek
        arq_ph = {"count": 0, "sum": 0.0}
        for e in engines:
            ph = e.metrics()["phases"]["arq_scan"]
            arq_ph["count"] += ph["count"]
            arq_ph["sum"] += ph["sum"]
        out["arq_scan_mean_usec"] = (arq_ph["sum"] / arq_ph["count"]
                                     if arq_ph["count"] else 0.0)
        out["arq_scan_gated"] = sum(e.arq_scan_gated for e in engines)
    world.close()
    return out


def _drive_native_granular(ws, rounds, batched):
    """The harness-overhead contrast leg (docs/DESIGN.md §13): the SAME
    seeded workload — ARQ + metrics + profiler all enabled — driven at
    two granularities. ``stepped`` pays one Python→ctypes crossing per
    frame (``NativeEngine.progress(max_frames=1)`` round-robin — the
    one-call-per-frame harness the C engine lived under before the
    batched entry points); ``batched`` drains each round with a single
    ``NativeWorld.progress_n`` call that loops sweeps inside C with
    the GIL released. Latency injection defers delivery into the
    drive phase, and only the drive phase is timed (the per-round
    bcast crossings are identical in both modes and measure nothing
    about driving granularity). Returns (frames driven, seconds)."""
    from rlo_tpu.native.bindings import NativeEngine, NativeWorld

    world = NativeWorld(ws, latency=96, seed=7)
    engines = [NativeEngine(world, r) for r in range(ws)]
    for e in engines:
        e.enable_arq(50_000)
        e.enable_metrics()
        e.enable_profiler()
    payload = b"x" * PAYLOAD
    dt = 0.0
    frames = 0
    for _ in range(rounds):
        for e in engines:
            e.bcast(payload)
        f0 = sum(e.frames_dispatched for e in engines)
        t0 = time.perf_counter()
        if batched:
            world.progress_n()  # one crossing: sweeps until quiescent
        else:
            while True:
                got = 0
                for e in engines:
                    got += e.progress(max_frames=1)
                if got == 0 and world.quiescent():
                    break
        dt += time.perf_counter() - t0
        frames += sum(e.frames_dispatched for e in engines) - f0
    for e in engines:
        while e.pickup_next() is not None:
            pass
    world.close()
    return frames, dt


def leg_native_batched(metrics, quick):
    ws = 4
    rounds = 60 if quick else 300
    f_step, dt_step = _drive_native_granular(ws, rounds, batched=False)
    f_bat, dt_bat = _drive_native_granular(ws, rounds, batched=True)
    fps_step = f_step / dt_step
    fps_bat = f_bat / dt_bat
    speedup = fps_bat / fps_step
    # the ISSUE-11 acceptance bar: batched driving must beat
    # one-call-per-frame by >= 5x with ARQ+metrics+profiler enabled
    assert speedup >= 5.0, (
        f"batched progress only {speedup:.1f}x over per-call stepping "
        f"({fps_bat:.0f} vs {fps_step:.0f} frames/s) — the batched "
        f"entry point is not paying for itself")
    metrics["native.stepped.frames_per_sec"] = wall(fps_step)
    metrics["native.batched.frames_per_sec"] = wall(fps_bat)
    metrics["native.batched.speedup"] = wall(speedup)
    print(f"native.batched: {fps_bat:.0f} frames/s batched vs "
          f"{fps_step:.0f} stepped ({speedup:.1f}x, ARQ+metrics+"
          f"profiler on)", file=sys.stderr)


def leg_native(metrics, quick):
    from rlo_tpu.native.bindings import bench_bcast_usec

    ws = 4
    rounds = 100 if quick else 500
    iar = 50 if quick else 200
    base = _drive_native(ws, rounds, iar, arq=False, obs=False)
    full = _drive_native(ws, rounds, iar, arq=True, obs=True)
    ops = base["bcasts"] / base["bcast_dt"]
    ops_full = full["bcasts"] / full["bcast_dt"]
    metrics["native.base.bcast_per_sec"] = wall(ops)
    metrics["native.base.frames_per_sec"] = wall(
        base["frames"] / base["bcast_dt"])
    metrics["native.base.iar_rounds_per_sec"] = wall(
        base["iar_rounds"] / base["iar_dt"])
    metrics["native.base.frames_per_bcast"] = exact(
        base["frames"] / base["bcasts"])
    metrics["native.obs.bcast_per_sec"] = wall(ops_full)
    metrics["native.obs.tax_pct"] = info(100.0 * (ops / ops_full - 1.0))
    metrics["native.obs.bcast_p50_usec"] = wall_lower(
        full["bcast_p50_usec"])
    metrics["native.obs.phase_samples"] = info(full["phase_samples"])
    # per-tick ARQ scan cost with the C due-heap gate (mirror of the
    # loopback leg's Python-heap metric; informational — correctness
    # is pinned by the exact frame counts above)
    metrics["native.obs.arq_scan_mean_usec"] = info(
        round(full["arq_scan_mean_usec"], 3))
    metrics["native.obs.arq_scan_gated"] = info(full["arq_scan_gated"])
    # wholly-native floor: no ctypes in the measured loop
    metrics["native.floor.bcast_usec"] = wall_lower(
        bench_bcast_usec(8, PAYLOAD, reps=3 if quick else 7))
    print(f"native: base {ops:.0f} bcast/s | obs {ops_full:.0f} "
          f"bcast/s (tax {metrics['native.obs.tax_pct']['value']:.1f}%)"
          f" | floor {metrics['native.floor.bcast_usec']['value']:.1f}"
          f"us/bcast", file=sys.stderr)


# ---------------------------------------------------------------------------
# simulator (protocol-only fast path; virtual metrics are seed-exact)
# ---------------------------------------------------------------------------

def leg_sim(metrics, quick):
    from rlo_tpu.engine import EngineManager, ProgressEngine
    from rlo_tpu.transport.sim import SimWorld

    ws = 16
    n_bcast = 20 if quick else 100
    world = SimWorld(ws, seed=3, protocol_only=True)
    mgr = EngineManager()
    engines = [ProgressEngine(world.transport(r), manager=mgr,
                              clock=world.clock) for r in range(ws)]
    delivered = [0] * ws
    t0 = time.perf_counter()
    vt0 = world.now
    for i in range(n_bcast):
        engines[i % ws].bcast(b"y" * PAYLOAD)
        while not world.quiescent():
            if world.step() and world.last_dst is not None:
                d = world.last_dst
                engines[d]._progress_once()
                while engines[d].pickup_next() is not None:
                    delivered[d] += 1
    dt = time.perf_counter() - t0
    assert sum(delivered) == n_bcast * (ws - 1), delivered
    metrics["sim.events"] = exact(world.events)
    metrics["sim.vtime"] = exact(world.now - vt0)
    metrics["sim.wall_events_per_sec"] = wall(world.events / dt)
    print(f"sim: {world.events} events in {dt:.2f}s wall / "
          f"{world.now - vt0:.2f}s virtual "
          f"({world.events / dt:.0f} ev/s)", file=sys.stderr)
    for e in engines:
        e.cleanup()


# ---------------------------------------------------------------------------
# tcp (one OS process per rank; excluded from --quick)
# ---------------------------------------------------------------------------

def tcp_worker(out_path, rounds):
    """Per-rank body (run under tcprun): C engines over the socket
    mesh; rank 0 measures and writes the JSON."""
    from rlo_tpu.native.bindings import NativeEngine, NativeWorld, load

    lib = load()
    w = lib.rlo_tcp_world_new()
    if not w:
        raise RuntimeError("rlo_tcp_world_new failed (run under tcprun)")
    # adopt the per-rank C world into the NativeWorld shell (the
    # TcpBackend._adopt_world pattern) so NativeEngine works unchanged
    world = NativeWorld.__new__(NativeWorld)
    world._lib = lib
    world._w = w
    world.world_size = lib.rlo_world_size(w)
    world.engines = []
    world.colls = []
    rank = lib.rlo_world_my_rank(w)
    eng = NativeEngine(world, rank)
    world.barrier()
    payload = b"x" * PAYLOAD
    t0 = time.perf_counter()
    for i in range(rounds):
        if rank == 0:
            eng.bcast(payload)
        # every rank drains the round: one bcast delivered everywhere.
        # Batched poll-wait (docs/DESIGN.md §13): the C loop spins the
        # socket mesh for up to 200us per crossing, GIL released,
        # instead of one ctypes call per sweep
        got = 0
        while got < (1 if rank != 0 else 0):
            eng.progress(deadline_usec=200)
            while eng.pickup_next() is not None:
                got += 1
        world.barrier()
    dt = time.perf_counter() - t0
    if rank == 0:
        with open(out_path, "w") as f:
            json.dump({"rounds": rounds, "dt": dt}, f)
    world.barrier()
    world.close()
    return 0


def leg_tcp(metrics, quick):
    import subprocess
    import tempfile

    rounds = 50 if quick else 200
    launcher = REPO / "rlo_tpu" / "native" / "tcprun"
    with tempfile.TemporaryDirectory() as td:
        out = Path(td) / "tcp.json"
        proc = subprocess.run(
            [sys.executable, str(launcher), "-n", "4", "-t", "240",
             sys.executable, str(Path(__file__).resolve()),
             "--tcp-worker", str(out), "--tcp-rounds", str(rounds)],
            capture_output=True, text=True, timeout=300)
        if proc.returncode != 0 or not out.exists():
            print(f"tcp leg FAILED (rc={proc.returncode}):\n"
                  f"{proc.stdout}\n{proc.stderr}", file=sys.stderr)
            raise RuntimeError("tcp leg failed")
        res = json.loads(out.read_text())
    ops = res["rounds"] / res["dt"]
    metrics["tcp.bcast_per_sec"] = wall(ops)
    print(f"tcp: {ops:.0f} bcast/s over real sockets (4 ranks)",
          file=sys.stderr)


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

LEGS = {"loopback": leg_loopback, "native": leg_native,
        "native_batched": leg_native_batched, "sim": leg_sim,
        "tcp": leg_tcp}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="small sizes — the check.sh smoke AND the "
                         "committed-baseline config")
    ap.add_argument("--out", default=None, help="write the JSON here")
    ap.add_argument("--transports", default=None,
                    help="comma list of %s (default: loopback,native,"
                         "sim; full runs add tcp)"
                         % ",".join(LEGS))
    ap.add_argument("--tcp-worker", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--tcp-rounds", type=int, default=50,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    if args.tcp_worker:
        return tcp_worker(args.tcp_worker, args.tcp_rounds)

    legs = (args.transports.split(",") if args.transports else
            ["loopback", "native", "native_batched", "sim"] +
            ([] if args.quick else ["tcp"]))
    metrics = {}
    for leg in legs:
        if leg not in LEGS:
            print(f"unknown transport {leg!r}", file=sys.stderr)
            return 2
        LEGS[leg](metrics, args.quick)
    doc = {
        "suite": "engine_bench",
        "schema": 1,
        "quick": bool(args.quick),
        # workload sizes are a pure function of `quick`, so carrying it
        # in the gate-compared config block makes a quick-vs-full
        # comparison a structural mismatch (exit 2), not a silent pass
        "config": {"payload": PAYLOAD, "legs": sorted(legs),
                   "quick": bool(args.quick)},
        "metrics": metrics,
    }
    text = json.dumps(doc, indent=1, sort_keys=True)
    if args.out:
        Path(args.out).write_text(text + "\n")
    print(text)
    return 0


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.exit(main())
