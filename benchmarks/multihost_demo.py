"""Multi-controller demo: 4 OS processes, engine consensus gating a real
cross-process XLA collective (round-2 VERDICT "What's missing" #1).

Run from the repo root (the launcher provides FEMTOMPI_RANK/SHM; the
env forces per-process CPU JAX so jax.distributed federates locally):

    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    RLO_COORDINATOR=127.0.0.1:28741 \
    rlo_tpu/native/femtompirun -n 4 python benchmarks/multihost_demo.py

Every process is BOTH an engine rank (femtompi shm rings — real
cross-process vote frames) and a JAX controller (federated into one
4-device CPU mesh — real cross-process AllReduce). Scenario:

  round 1: proposer = rank 1 (rootless: not rank 0), all local tensors
           finite -> every process approves -> the global psum runs and
           every process gets the replicated sum.
  round 2: rank 2 poisons ITS OWN local tensor with NaN; its judge
           votes NO -> the AND-merged decision is 0 on EVERY process
           and the device collective never runs anywhere.

Self-verifying: each process checks both outcomes and prints one
MULTIHOST-OK line; the launcher's collective exit makes any failure a
nonzero rc.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from rlo_tpu.parallel.multihost import MultiHostContext  # noqa: E402


def main():
    ctx = MultiHostContext()
    rank, ws = ctx.rank, ctx.world_size

    def judge(local):
        return bool(np.isfinite(local).all())

    # round 1: clean tensors, rootless proposer (rank 1)
    local = np.full(256, float(rank + 1), np.float32)
    decision, out = ctx.propose_collective(local, proposer=1,
                                           judge=judge)
    want = sum(range(1, ws + 1))
    assert decision == 1, f"rank {rank}: clean round vetoed"
    assert out is not None and np.allclose(out, want), (
        f"rank {rank}: psum wrong: {out[:4]} != {want}")

    # round 2: rank 2's local state is poisoned; everyone must see 0
    local2 = local.copy()
    if rank == 2:
        local2[7] = np.nan
    decision2, out2 = ctx.propose_collective(local2, proposer=3,
                                             judge=judge)
    assert decision2 == 0 and out2 is None, (
        f"rank {rank}: poisoned round not vetoed (decision={decision2})")

    print(f"MULTIHOST-OK rank={rank}/{ws} sum={float(out[0])}",
          flush=True)
    ctx.close()


if __name__ == "__main__":
    main()
