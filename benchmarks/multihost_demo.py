"""Multi-controller demo: 4 OS processes, engine consensus gating a real
cross-process XLA collective (round-2 VERDICT "What's missing" #1).

Run from the repo root (the launcher provides FEMTOMPI_RANK/SHM; the
env forces per-process CPU JAX so jax.distributed federates locally):

    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    RLO_COORDINATOR=127.0.0.1:28741 \
    rlo_tpu/native/femtompirun -n 4 python benchmarks/multihost_demo.py

Every process is BOTH an engine rank (femtompi shm rings — real
cross-process vote frames) and a JAX controller (federated into one
4-device CPU mesh — real cross-process AllReduce). Scenario:

  round 1: proposer = rank 1 (rootless: not rank 0), all local tensors
           finite -> every process approves -> the global psum runs and
           every process gets the replicated sum.
  round 2: rank 2 poisons ITS OWN local tensor with NaN; its judge
           votes NO -> the AND-merged decision is 0 on EVERY process
           and the device collective never runs anywhere.

Self-verifying: each process checks both outcomes and prints one
MULTIHOST-OK line; the launcher's collective exit makes any failure a
nonzero rc.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from rlo_tpu.parallel.multihost import MultiHostContext  # noqa: E402


def main():
    ctx = MultiHostContext()
    rank, ws = ctx.rank, ctx.world_size

    def judge(local):
        return bool(np.isfinite(local).all())

    # round 1: clean tensors, rootless proposer (rank 1)
    local = np.full(256, float(rank + 1), np.float32)
    decision, out = ctx.propose_collective(local, proposer=1,
                                           judge=judge)
    want = sum(range(1, ws + 1))
    assert decision == 1, f"rank {rank}: clean round vetoed"
    assert out is not None and np.allclose(out, want), (
        f"rank {rank}: psum wrong: {out[:4]} != {want}")

    # round 2: one rank's local state is poisoned; everyone must see 0
    # (ranks chosen to exercise a non-proposing poisoner when ws allows)
    poisoner = 2 if ws > 2 else ws - 1
    proposer2 = 3 if ws > 3 else 0
    local2 = local.copy()
    if rank == poisoner:
        local2[7] = np.nan
    decision2, out2 = ctx.propose_collective(local2, proposer=proposer2,
                                             judge=judge)
    assert decision2 == 0 and out2 is None, (
        f"rank {rank}: poisoned round not vetoed (decision={decision2})")

    # rounds 3-4 (round-4 VERDICT): a SUBSET of the hosts ({0, 2,
    # ws-1}) runs its own consensus-gated collective — subset engine
    # frames on their own comm, subset device sub-mesh — while rank 1
    # stands by on the parent world
    members = [0, 2, ws - 1] if ws >= 4 else [0, ws - 1]
    sctx = ctx.sub_context(members)
    assert (sctx is None) == (rank not in members)
    if sctx is not None:
        pos, n = sctx.rank, sctx.world_size
        loc = np.full(64, float(pos + 1), np.float32)
        bad = loc.copy()
        if pos == n - 1:  # the highest member poisons: subset veto
            bad[3] = np.nan
        d3, out3 = sctx.propose_collective(bad, proposer=1, judge=judge)
        assert d3 == 0 and out3 is None, (
            f"rank {rank}: subset veto failed (decision={d3})")
        d4, out4 = sctx.propose_collective(loc, proposer=0, judge=judge)
        want4 = n * (n + 1) / 2
        assert d4 == 1 and out4 is not None and np.allclose(out4, want4), (
            f"rank {rank}: subset psum wrong")
        sctx.close()
    ctx.backend.barrier()  # the bystander re-joins the full world here

    print(f"MULTIHOST-OK rank={rank}/{ws} sum={float(out[0])}",
          flush=True)
    ctx.close()


if __name__ == "__main__":
    main()
