"""Fleet-scale simulator benchmark — the published scaling curve
(ROADMAP item 4: "push transport/sim.py from 4-8 ranks to thousands").

Runs the deterministic simulator's **protocol-only fast path**
(SimWorld(protocol_only=True): no payload copies, no schedule digest)
and records two curves:

  - **bcast fan-out latency vs. n**: virtual time from a rank-0
    broadcast to the last of n-1 deliveries, plus the schedule length
    (delivery events) per broadcast. Both are seed-exact, so the gate
    compares them at ZERO tolerance — an O(log n) overlay schedule
    regressing toward O(n) moves these numbers and fails mechanically.
  - **membership convergence vs. n**: virtual time from a crash-stop
    kill to every survivor holding the converged view (heartbeats,
    FAILURE flood, overlay re-form, re-flood all included) — again
    seed-exact.

Wall-clock events/sec per size is recorded with a generous tolerance
(machine-dependent). The driver uses targeted stepping: only the rank
that just received a frame is progressed, plus a periodic full sweep
at half the heartbeat interval so time-driven machinery still fires —
this is what makes n >= 1024 tractable in Python.

Output schema is shared with benchmarks/engine_bench.py and consumed
by ``rlo_tpu.tools.perf_gate``. The committed BENCH_sim.json baseline
— and the check.sh gate step — use the FULL curve (no --quick; the
fast path makes n=1024 cheap enough to run every time, ~7 s total).
``--quick`` is the small-n config for unit tests; the full sweep also
reruns against the committed baseline under tier-1's `-m slow` marker
(tests/test_perf_gate.py).

Usage:
    python benchmarks/sim_bench.py --out BENCH_sim.json  # full, n to 1024
    python benchmarks/sim_bench.py --quick               # test config
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

FANOUT_NS_QUICK = (4, 16, 64, 256)
FANOUT_NS_FULL = (4, 16, 64, 256, 1024)
MEMBER_NS_QUICK = (4, 8, 16)
MEMBER_NS_FULL = (4, 16, 64, 256, 1024)


def exact(value):
    return {"value": value, "direction": "exact", "tolerance": None}


def wall(value):
    """Wall-clock rate, recorded but NOT gated: the small-n legs
    finish in milliseconds, where scheduler noise swamps any honest
    tolerance (a 5x factor flaked in practice). The deterministic
    vtime/event metrics are this suite's gate; sustained wall-clock
    throughput gating lives in engine_bench's longer runs."""
    return {"value": value, "direction": "higher", "tolerance": None}


def bench_fanout(n: int, n_bcast: int = 3, seed: int = 0):
    """Virtual-time bcast fan-out latency at n ranks (protocol-only
    fast path + targeted stepping). Returns (mean vtime per bcast,
    TOTAL schedule events, broadcasts run, wall seconds)."""
    from rlo_tpu.engine import EngineManager, ProgressEngine
    from rlo_tpu.transport.sim import SimWorld

    world = SimWorld(n, seed=seed, protocol_only=True)
    mgr = EngineManager()
    engines = [ProgressEngine(world.transport(r), manager=mgr,
                              clock=world.clock) for r in range(n)]
    t_wall = time.perf_counter()
    vtimes = []
    ev0 = world.events
    for i in range(n_bcast):
        got = 0
        t0 = world.now
        engines[0].bcast(b"s")
        t_last = t0
        while got < n - 1:
            if not world.step():
                continue
            d = world.last_dst
            if d is None:
                continue
            engines[d]._progress_once()
            while engines[d].pickup_next() is not None:
                got += 1
                t_last = world.now
        vtimes.append(t_last - t0)
    wall_dt = time.perf_counter() - t_wall
    events = world.events - ev0
    for e in engines:
        e.cleanup()
    return (sum(vtimes) / len(vtimes), events, n_bcast, wall_dt)


def bench_membership(n: int, seed: int = 0, kill_at: float = 2.0,
                     failure_timeout: float = 3.0,
                     heartbeat: float = 1.0, limit: float = 120.0):
    """Virtual time from a crash-stop kill of rank n-1 to every
    survivor's membership view converging on the survivor set.
    Targeted stepping + a full progress sweep every heartbeat/2 keeps
    n >= 1024 tractable. Returns (convergence vtime, schedule events,
    wall seconds)."""
    from rlo_tpu.engine import EngineManager, ProgressEngine
    from rlo_tpu.transport.sim import SimWorld

    world = SimWorld(n, seed=seed, protocol_only=True)
    mgr = EngineManager()
    engines = [ProgressEngine(world.transport(r), manager=mgr,
                              clock=world.clock,
                              failure_timeout=failure_timeout,
                              heartbeat_interval=heartbeat)
               for r in range(n)]
    victim = n - 1
    want = list(range(n - 1))
    t_wall = time.perf_counter()
    killed_at = None
    last_full = world.now

    def converged():
        return all(engines[r]._alive == want for r in range(n - 1))

    t_conv = None
    while world.now < limit:
        if killed_at is None and world.now >= kill_at:
            world.kill_rank(victim)
            engines[victim].cleanup()
            killed_at = world.now
        world.step()
        d = world.last_dst
        if d is not None and d != victim:
            engines[d]._progress_once()
            while engines[d].pickup_next() is not None:
                pass
        if world.now - last_full >= heartbeat / 2.0:
            last_full = world.now
            mgr.progress_all()
            for r in range(n):
                if r == victim:
                    continue
                while engines[r].pickup_next() is not None:
                    pass
            if killed_at is not None and converged():
                t_conv = world.now - killed_at
                break
    wall_dt = time.perf_counter() - t_wall
    events = world.events
    for e in engines:
        e.cleanup()
    if t_conv is None:
        raise RuntimeError(
            f"membership did not converge at n={n} within {limit} "
            f"virtual seconds")
    return (t_conv, events, wall_dt)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="small-n test config (the committed baseline "
                         "and check.sh use the FULL curve)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    import logging
    logging.getLogger("rlo_tpu").setLevel(logging.ERROR)

    fanout_ns = FANOUT_NS_QUICK if args.quick else FANOUT_NS_FULL
    member_ns = MEMBER_NS_QUICK if args.quick else MEMBER_NS_FULL
    metrics = {}
    for n in fanout_ns:
        vt, events, n_bcast, wdt = bench_fanout(n)
        metrics[f"fanout.n{n}.vtime"] = exact(vt)
        metrics[f"fanout.n{n}.events_per_bcast"] = exact(
            events / n_bcast)
        metrics[f"fanout.n{n}.wall_events_per_sec"] = wall(
            events / wdt if wdt > 0 else 0.0)
        print(f"fanout n={n}: {vt:.3f} vsec/bcast, "
              f"{events / n_bcast:.1f} events/bcast, {wdt:.2f}s wall",
              file=sys.stderr)
    for n in member_ns:
        vt, ev, wdt = bench_membership(n)
        metrics[f"member.n{n}.converge_vtime"] = exact(vt)
        metrics[f"member.n{n}.events"] = exact(ev)
        metrics[f"member.n{n}.wall_events_per_sec"] = wall(
            ev / wdt if wdt > 0 else 0.0)
        print(f"member n={n}: converged {vt:.2f} vsec after kill, "
              f"{ev} events, {wdt:.2f}s wall", file=sys.stderr)
    doc = {
        "suite": "sim_bench",
        "schema": 1,
        "quick": bool(args.quick),
        "config": {"fanout_ns": list(fanout_ns),
                   "member_ns": list(member_ns),
                   "quick": bool(args.quick)},
        "metrics": metrics,
    }
    text = json.dumps(doc, indent=1, sort_keys=True)
    if args.out:
        Path(args.out).write_text(text + "\n")
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
