"""Fleet-scale simulator benchmark — the published scaling curve
(ROADMAP item 4: "push transport/sim.py from 4-8 ranks to thousands").

Runs the deterministic simulator's **protocol-only fast path**
(SimWorld(protocol_only=True): no payload copies, no schedule digest)
and records two curves:

  - **bcast fan-out latency vs. n**: virtual time from a rank-0
    broadcast to the last of n-1 deliveries, plus the schedule length
    (delivery events) per broadcast. Both are seed-exact, so the gate
    compares them at ZERO tolerance — an O(log n) overlay schedule
    regressing toward O(n) moves these numbers and fails mechanically.
  - **membership convergence vs. n**: virtual time from a crash-stop
    kill to every survivor holding the converged view (heartbeats,
    FAILURE flood, overlay re-form, re-flood all included) — again
    seed-exact.
  - **churn-rate vs. convergence** (docs/DESIGN.md §14): sustained
    kill/rejoin churn from a seeded weather schedule at several rates;
    the fleet's total "dirty" (divergent-view) virtual time, span
    count and rejoin volume gate exact.
  - **ARQ retransmit storms under correlated loss**: the same average
    loss rate applied iid vs as Gilbert burst loss — the retransmit
    counts and completion vtimes gate exact, pinning the storm
    amplification factor correlation causes.

Wall-clock events/sec per size is recorded with a generous tolerance
(machine-dependent). The driver uses targeted stepping: only the rank
that just received a frame is progressed, plus a periodic full sweep
at half the heartbeat interval so time-driven machinery still fires —
this is what makes n >= 1024 tractable in Python.

Output schema is shared with benchmarks/engine_bench.py and consumed
by ``rlo_tpu.tools.perf_gate``. The committed BENCH_sim.json baseline
— and the check.sh gate step — use the FULL curve (no --quick; the
fast path makes n=1024 cheap enough to run every time, ~7 s total).
``--quick`` is the small-n config for unit tests; the full sweep also
reruns against the committed baseline under tier-1's `-m slow` marker
(tests/test_perf_gate.py).

Usage:
    python benchmarks/sim_bench.py --out BENCH_sim.json  # full, n to 1024
    python benchmarks/sim_bench.py --quick               # test config
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

FANOUT_NS_QUICK = (4, 16, 64, 256)
FANOUT_NS_FULL = (4, 16, 64, 256, 1024)
MEMBER_NS_QUICK = (4, 8, 16)
MEMBER_NS_FULL = (4, 16, 64, 256, 1024)
#: churn-rate-vs-convergence curve (docs/DESIGN.md §14): (n, kills
#: per virtual second) legs of sustained kill/rejoin churn. Before
#: the §18 healing work (epoch catch-up, joiner liveness grace,
#: incremental re-flood, batched admissions) the r=0.05 leg sat PAST
#: the measured knee — mid-rejoin ranks stopped heartbeating, got
#: re-declared failed, and the fleet collapsed into a rejoin cascade
#: (final_converged pinned 0). §18 moved the knee: the whole curve
#: now ends converged at n=32, and the heal-cost counters pin HOW
#: (reflood_skipped replacing reflood_frames, epoch_syncs replacing
#: full rejoins). See DESIGN.md §14 "churn findings" and §18.
CHURN_LEGS_QUICK = ((16, 0.02),)
CHURN_LEGS_FULL = ((32, 0.01), (32, 0.02), (32, 0.05))
#: ARQ-storm legs: iid loss vs correlated (Gilbert) burst loss at the
#: SAME average loss rate — the storm is in the correlation
STORM_N = 16


def exact(value):
    return {"value": value, "direction": "exact", "tolerance": None}


def wall(value):
    """Wall-clock rate, recorded but NOT gated: the small-n legs
    finish in milliseconds, where scheduler noise swamps any honest
    tolerance (a 5x factor flaked in practice). The deterministic
    vtime/event metrics are this suite's gate; sustained wall-clock
    throughput gating lives in engine_bench's longer runs."""
    return {"value": value, "direction": "higher", "tolerance": None}


def info(value):
    """Informational cost counter (lower is better, never gated):
    recorded so `perf_gate --report` prints its drift every check.sh
    run — the heal-cost counters live here because the item-4 healing
    work is SUPPOSED to move them."""
    return {"value": value, "direction": "lower", "tolerance": None}


def bench_fanout(n: int, n_bcast: int = 3, seed: int = 0,
                 scheduler: str = "heap"):
    """Virtual-time bcast fan-out latency at n ranks (protocol-only
    fast path + targeted stepping). Returns (mean vtime per bcast,
    TOTAL schedule events, broadcasts run, wall seconds).
    ``scheduler`` selects the event queue — results are identical by
    the §14 oracle-equivalence rule; the calendar queue is what makes
    n >= 10,000 tractable (benchmarks/workload_bench.py)."""
    from rlo_tpu.engine import EngineManager, ProgressEngine
    from rlo_tpu.transport.sim import SimWorld

    world = SimWorld(n, seed=seed, protocol_only=True,
                     scheduler=scheduler)
    mgr = EngineManager()
    engines = [ProgressEngine(world.transport(r), manager=mgr,
                              clock=world.clock) for r in range(n)]
    t_wall = time.perf_counter()
    vtimes = []
    ev0 = world.events
    for i in range(n_bcast):
        got = 0
        t0 = world.now
        engines[0].bcast(b"s")
        t_last = t0
        while got < n - 1:
            if not world.step():
                continue
            d = world.last_dst
            if d is None:
                continue
            engines[d]._progress_once()
            while engines[d].pickup_next() is not None:
                got += 1
                t_last = world.now
        vtimes.append(t_last - t0)
    wall_dt = time.perf_counter() - t_wall
    events = world.events - ev0
    for e in engines:
        e.cleanup()
    return (sum(vtimes) / len(vtimes), events, n_bcast, wall_dt)


def bench_membership(n: int, seed: int = 0, kill_at: float = 2.0,
                     failure_timeout: float = 3.0,
                     heartbeat: float = 1.0, limit: float = 120.0,
                     scheduler: str = "heap"):
    """Virtual time from a crash-stop kill of rank n-1 to every
    survivor's membership view converging on the survivor set.
    Targeted stepping + a full progress sweep every heartbeat/2 keeps
    n >= 1024 tractable. Returns (convergence vtime, schedule events,
    wall seconds)."""
    from rlo_tpu.engine import EngineManager, ProgressEngine
    from rlo_tpu.transport.sim import SimWorld

    world = SimWorld(n, seed=seed, protocol_only=True,
                     scheduler=scheduler)
    mgr = EngineManager()
    engines = [ProgressEngine(world.transport(r), manager=mgr,
                              clock=world.clock,
                              failure_timeout=failure_timeout,
                              heartbeat_interval=heartbeat)
               for r in range(n)]
    victim = n - 1
    want = list(range(n - 1))
    t_wall = time.perf_counter()
    killed_at = None
    last_full = world.now

    def converged():
        # O(1)-per-rank length screen before the O(n) list compare:
        # pre-convergence views still hold n entries, and the full
        # equality walk at n=10k costs ~100M comparisons per sweep
        return all(len(engines[r]._alive) == n - 1
                   for r in range(n - 1)) and \
            all(engines[r]._alive == want for r in range(n - 1))

    t_conv = None
    while world.now < limit:
        if killed_at is None and world.now >= kill_at:
            world.kill_rank(victim)
            engines[victim].cleanup()
            killed_at = world.now
        world.step()
        d = world.last_dst
        if d is not None and d != victim:
            engines[d]._progress_once()
            while engines[d].pickup_next() is not None:
                pass
        if world.now - last_full >= heartbeat / 2.0:
            last_full = world.now
            mgr.progress_all()
            for r in range(n):
                if r == victim:
                    continue
                while engines[r].pickup_next() is not None:
                    pass
            if killed_at is not None and converged():
                t_conv = world.now - killed_at
                break
    wall_dt = time.perf_counter() - t_wall
    events = world.events
    for e in engines:
        e.cleanup()
    if t_conv is None:
        raise RuntimeError(
            f"membership did not converge at n={n} within {limit} "
            f"virtual seconds")
    return (t_conv, events, wall_dt)


def bench_churn(n: int, rate: float, seed: int = 0,
                duration: float = 120.0,
                failure_timeout: float = 3.0, heartbeat: float = 1.0):
    """Membership convergence under sustained churn RATE (not one
    scripted kill): a seeded weather churn schedule
    (rlo_tpu/workloads/weather.py, exponential kill/rejoin
    interarrivals) runs against n full engines; measured are the
    total virtual time the fleet spends with a divergent view
    ("dirty" spans: from a fault event until every live view equals
    the live set again), the span count, churn volume, and the
    schedule length — all seed-exact. Returns (dirty_vtime, spans,
    kills, rejoins, events, final_converged, wall)."""
    from rlo_tpu.engine import EngineManager, ProgressEngine
    from rlo_tpu.transport.sim import SimWorld
    from rlo_tpu.workloads.weather import churn_script

    script = churn_script(seed + 1, world_size=n, rate=rate,
                          duration=duration, start=8.0,
                          mean_down=20.0,
                          min_down=failure_timeout * 3 + 4.0,
                          min_live=max(2, n - max(2, n // 8)),
                          settle=50.0)
    kills = sum(1 for s in script if s[1] == "kill")
    world = SimWorld(n, seed=seed, protocol_only=True)
    mgr = EngineManager()
    kw = dict(clock=world.clock, failure_timeout=failure_timeout,
              heartbeat_interval=heartbeat, arq_rto=1.5,
              arq_max_retries=6, op_deadline=30.0)
    engines = [ProgressEngine(world.transport(r), manager=mgr, **kw)
               for r in range(n)]
    incarnation = [0] * n
    live = set(range(n))
    si = 0
    dirty_since = None
    dirty_vtime = 0.0
    spans = 0
    last_check = world.now
    t_wall = time.perf_counter()

    def converged() -> bool:
        want = sorted(live)
        k = len(want)
        return all(len(engines[r]._alive) == k for r in want) and \
            all(engines[r]._alive == want for r in want) and \
            not any(engines[r]._awaiting_welcome for r in want)

    while world.now < duration:
        while si < len(script) and script[si][0] <= world.now:
            _, act, r = script[si]
            si += 1
            if act == "kill":
                world.kill_rank(r)
                engines[r].cleanup()
                live.discard(r)
            else:
                world.restart_rank(r)
                incarnation[r] += 1
                engines[r] = ProgressEngine(
                    world.transport(r), manager=mgr,
                    incarnation=incarnation[r], **kw)
                live.add(r)
            if dirty_since is None:
                dirty_since = world.now
                spans += 1
        world.step()
        # targeted stepping (see bench_membership): progress only the
        # engine with fresh input; the periodic sweep keeps the
        # time-driven machinery (heartbeats, JOIN probes, watchdogs)
        # firing on everyone
        d = world.last_dst
        if d is not None and d in live:
            engines[d]._progress_once()
            while engines[d].pickup_next() is not None:
                pass
        if world.now - last_check >= heartbeat / 2.0:
            last_check = world.now
            mgr.progress_all()
            for r in sorted(live):
                while engines[r].pickup_next() is not None:
                    pass
            if dirty_since is not None and converged():
                dirty_vtime += world.now - dirty_since
                dirty_since = None
    wall = time.perf_counter() - t_wall
    final_ok = converged()
    if dirty_since is not None:
        dirty_vtime += world.now - dirty_since
    rejoins = sum(engines[r].rejoins for r in live)
    # heal-cost counters (docs/DESIGN.md §17/§18): the committed
    # record of what healing COSTS. The §18 work (epoch catch-up,
    # joiner liveness grace, incremental re-flood, batched
    # admissions) drove reflood_frames and admission_rounds down
    # against the pre-§18 cascade baseline; the new counters
    # (epoch_syncs, reflood_skipped, batched_admits) pin where the
    # avoided work went. Informational in BENCH_sim.json: they move
    # whenever the heal protocol improves, which is the point.
    heal = {
        "view_changes": sum(engines[r].view_changes for r in live),
        "reflood_frames": sum(engines[r].reflood_frames
                              for r in live),
        "reflood_skipped": sum(engines[r].reflood_skipped
                               for r in live),
        "epoch_syncs": sum(engines[r].epoch_syncs for r in live),
        "batched_admits": sum(engines[r].batched_admits
                              for r in live),
        "admission_rounds": sum(engines[r].admission_rounds
                                for r in live),
        "epoch_lag_max": max((engines[r].epoch_lag_max
                              for r in live), default=0),
        "quar_mid_rejoin": sum(engines[r].quar_mid_rejoin
                               for r in live),
        "quar_failed_sender": sum(engines[r].quar_failed_sender
                                  for r in live),
        "quar_below_floor": sum(engines[r].quar_below_floor
                                for r in live),
    }
    for e in engines:
        e.cleanup()
    return (dirty_vtime, spans, kills, rejoins, world.events,
            final_ok, wall, heal)


def bench_storm(n: int, seed: int = 0, correlated: bool = False,
                n_bcast: int = 30, limit: float = 240.0):
    """ARQ retransmit behavior under lossy weather: ``n_bcast``
    staggered broadcasts with ARQ on, under either iid loss or a
    Gilbert burst-loss profile of the SAME average loss rate
    (rlo_tpu/workloads/weather.py). Correlated loss concentrates
    drops into runs that defeat single-retransmit recovery — the
    retransmit-storm shape — while iid loss of equal intensity heals
    almost invisibly. Returns (retransmits, gave_up, complete_vtime,
    events, delivered_frac, wall), all but wall seed-exact."""
    from rlo_tpu.engine import EngineManager, ProgressEngine
    from rlo_tpu.transport.sim import SimWorld
    from rlo_tpu.workloads.weather import GilbertLoss

    # equal average loss: the Gilbert chain is in the bad state
    # p_enter/(p_enter+p_exit) of sends, dropping loss_bad of them —
    # mean burst length 1/p_exit sends, long enough to wipe a whole
    # retransmit batch when one lands inside a bad run
    p_enter, p_exit, loss_bad = 0.01, 0.08, 0.8
    avg_loss = loss_bad * p_enter / (p_enter + p_exit)
    drop_fn = (GilbertLoss(p_enter=p_enter, p_exit=p_exit,
                           loss_bad=loss_bad) if correlated else None)
    world = SimWorld(n, seed=seed, protocol_only=True,
                     drop_fn=drop_fn,
                     drop_p=0.0 if correlated else avg_loss)
    mgr = EngineManager()
    engines = [ProgressEngine(world.transport(r), manager=mgr,
                              clock=world.clock, arq_rto=1.5,
                              arq_max_retries=10)
               for r in range(n)]
    sent = 0
    next_send = 1.0
    got = [0] * n
    t_wall = time.perf_counter()
    complete_at = None
    while world.now < limit:
        if sent < n_bcast and world.now >= next_send:
            engines[sent % n].bcast(b"storm%d" % sent)
            sent += 1
            next_send += 0.5
        world.step()
        mgr.progress_all()
        for r in range(n):
            while engines[r].pickup_next() is not None:
                got[r] += 1
        if sent == n_bcast and complete_at is None and \
                sum(got) >= n_bcast * (n - 1):
            # every rank picked up every broadcast it did not originate
            complete_at = world.now
            break
    wall = time.perf_counter() - t_wall
    retrans = sum(e.arq_retransmits for e in engines)
    gave_up = sum(e.arq_gave_up for e in engines)
    delivered = sum(got) / float(n_bcast * (n - 1))
    for e in engines:
        e.cleanup()
    return (retrans, gave_up,
            complete_at if complete_at is not None else -1.0,
            world.events, round(delivered, 6), wall)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="small-n test config (the committed baseline "
                         "and check.sh use the FULL curve)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    import logging
    logging.getLogger("rlo_tpu").setLevel(logging.ERROR)

    fanout_ns = FANOUT_NS_QUICK if args.quick else FANOUT_NS_FULL
    member_ns = MEMBER_NS_QUICK if args.quick else MEMBER_NS_FULL
    metrics = {}
    for n in fanout_ns:
        vt, events, n_bcast, wdt = bench_fanout(n)
        metrics[f"fanout.n{n}.vtime"] = exact(vt)
        metrics[f"fanout.n{n}.events_per_bcast"] = exact(
            events / n_bcast)
        metrics[f"fanout.n{n}.wall_events_per_sec"] = wall(
            events / wdt if wdt > 0 else 0.0)
        print(f"fanout n={n}: {vt:.3f} vsec/bcast, "
              f"{events / n_bcast:.1f} events/bcast, {wdt:.2f}s wall",
              file=sys.stderr)
    for n in member_ns:
        vt, ev, wdt = bench_membership(n)
        metrics[f"member.n{n}.converge_vtime"] = exact(vt)
        metrics[f"member.n{n}.events"] = exact(ev)
        metrics[f"member.n{n}.wall_events_per_sec"] = wall(
            ev / wdt if wdt > 0 else 0.0)
        print(f"member n={n}: converged {vt:.2f} vsec after kill, "
              f"{ev} events, {wdt:.2f}s wall", file=sys.stderr)
    churn_legs = (CHURN_LEGS_QUICK if args.quick
                  else CHURN_LEGS_FULL)
    for cn, rate in churn_legs:
        (dirty, spans, kills, rejoins, ev, ok,
         wdt, heal) = bench_churn(cn, rate)
        key = f"churn.n{cn}.r{rate}"
        metrics[f"{key}.dirty_vtime"] = exact(round(dirty, 9))
        metrics[f"{key}.spans"] = exact(spans)
        metrics[f"{key}.kills"] = exact(kills)
        metrics[f"{key}.rejoins"] = exact(rejoins)
        metrics[f"{key}.events"] = exact(ev)
        metrics[f"{key}.final_converged"] = exact(int(ok))
        metrics[f"{key}.wall_events_per_sec"] = wall(
            ev / wdt if wdt > 0 else 0.0)
        # heal-cost counters (docs/DESIGN.md §17/§18): informational
        # drift record of what healing costs per leg (perf_gate
        # --report prints the movement every check.sh run)
        for hk, hv in sorted(heal.items()):
            metrics[f"{key}.heal.{hk}"] = info(hv)
        print(f"churn n={cn} rate={rate}: {kills} kills/"
              f"{rejoins} rejoins, {dirty:.2f} dirty vsec over "
              f"{spans} spans, converged={ok}, {ev} events, "
              f"{wdt:.2f}s wall; heal cost {heal}", file=sys.stderr)
    for name, corr in (("iid", False), ("burst", True)):
        (retrans, gave_up, cvt, ev, frac,
         wdt) = bench_storm(STORM_N, correlated=corr)
        key = f"storm.n{STORM_N}.{name}"
        metrics[f"{key}.retransmits"] = exact(retrans)
        metrics[f"{key}.gave_up"] = exact(gave_up)
        metrics[f"{key}.complete_vtime"] = exact(round(cvt, 9))
        metrics[f"{key}.events"] = exact(ev)
        metrics[f"{key}.delivered_frac"] = exact(frac)
        metrics[f"{key}.wall_events_per_sec"] = wall(
            ev / wdt if wdt > 0 else 0.0)
        print(f"storm {name} n={STORM_N}: {retrans} retransmits, "
              f"{gave_up} give-ups, complete {cvt:.2f} vsec, "
              f"{ev} events, delivered {frac:.3f}", file=sys.stderr)
    doc = {
        "suite": "sim_bench",
        "schema": 1,
        "quick": bool(args.quick),
        "config": {"fanout_ns": list(fanout_ns),
                   "member_ns": list(member_ns),
                   "churn_legs": [list(leg) for leg in churn_legs],
                   "storm_n": STORM_N,
                   "quick": bool(args.quick)},
        "metrics": metrics,
    }
    text = json.dumps(doc, indent=1, sort_keys=True)
    if args.out:
        Path(args.out).write_text(text + "\n")
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
