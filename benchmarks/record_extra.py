"""Record the auxiliary perf numbers as an in-repo artifact.

Round-4 VERDICT item 3: the MFU / decode / TTFT / GQA numbers lived in
code comments and stderr — nothing a reviewer could regression-track.
This runs each auxiliary bench as a subprocess (sequentially: the
tunneled chip is contention-sensitive) and writes BENCH_extra.json at
the repo root — one entry per leg with the bench's own JSON line (or
its diagnostic tail, for text-only legs like flash_bench) plus the
exit status, so a failed leg is recorded as failed instead of
silently absent.

Usage: python benchmarks/record_extra.py [--skip NAME ...] [--out PATH]
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

#: (name, argv, timeout_sec) — argv relative to the repo root
LEGS = [
    ("train_mfu_batch4",
     [sys.executable, "benchmarks/train_bench.py"], 2400),
    ("train_mfu_batch8",
     [sys.executable, "benchmarks/train_bench.py", "--batch", "8"], 2400),
    ("decode_tok_s",
     [sys.executable, "benchmarks/decode_bench.py"], 2400),
    ("ttft_blockwise_prefill_b1",
     [sys.executable, "benchmarks/decode_bench.py", "--ttft",
      "--plen", "1024", "--batch", "1"], 2400),
    ("ttft_blockwise_prefill_b4",
     [sys.executable, "benchmarks/decode_bench.py", "--ttft",
      "--plen", "1024", "--batch", "4"], 2400),
    ("flash_gqa_compact_vs_repeated",
     [sys.executable, "benchmarks/flash_bench.py", "--seq", "4096",
      "--heads", "8", "--dim", "128", "--gqa", "2"], 2400),
    # GQA where it is measurable on one chip (round-5 item 3): the
    # decode cache-bandwidth win at long prompt, and the servable-
    # capacity win proven by allocation + a real decode step
    ("decode_gqa_compare",
     [sys.executable, "benchmarks/decode_bench.py", "--compare-gqa"],
     2400),
    ("decode_capacity",
     [sys.executable, "benchmarks/decode_bench.py", "--capacity"],
     2400),
    # long-context decode: the cache (not the weights) is the HBM
    # bound. decode_longctx records the absolute number through the
    # flash-decode kernel; decode_kv_compare measures the int8-cache
    # speedup with INTERLEAVED pairs (separate runs sit in different
    # chip-throughput windows; their ratio is meaningless) — measured
    # 1.17-1.43x across windows at batch 32 / plen 1024 (2026-07-31).
    ("decode_longctx",
     [sys.executable, "benchmarks/decode_bench.py",
      "--prompt-len", "1024"], 2400),
    ("decode_kv_compare",
     [sys.executable, "benchmarks/decode_bench.py",
      "--compare-kv"], 2400),
    # speculative-decoding infra costs at batch 1 (the latency-bound
    # serving case): round-4 recorded verify of gamma=4 = 1.12 decode
    # steps (~90% of ideal), draft step 0.04-0.08 of a target step
    ("spec_verify_b1",
     [sys.executable, "benchmarks/spec_bench.py", "--batch", "1"],
     2400),
    # round-5 item 2: the REALIZED speculative speedup — distill a
    # draft on-chip, measure acceptance and end-to-end tokens/s
    ("spec_e2e_b1",
     [sys.executable, "benchmarks/spec_bench.py", "--e2e",
      "--gamma", "8", "--draft-layers", "1", "--draft-dim", "256"],
     3000),
    # round-5 item 1: the decode HBM budget decomposition (per-
    # component GB/s vs a same-window streaming probe)
    ("decode_budget",
     [sys.executable, "benchmarks/decode_analysis.py",
      "--plen", "1024"], 3300),
    # round-5 item 6: continuous batching vs naive batch-restart
    ("serve_continuous",
     [sys.executable, "benchmarks/serve_bench.py"], 2400),
]


def run_leg(name, argv, timeout):
    t0 = time.time()
    try:
        proc = subprocess.run(argv, cwd=str(REPO), capture_output=True,
                              text=True, timeout=timeout)
        rc = proc.returncode
        out, err = proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as e:
        rc, out, err = -1, e.stdout or "", f"timeout after {timeout}s"
        out = out if isinstance(out, str) else out.decode()
    rec = {"name": name, "argv": argv[1:], "rc": rc,
           "wall_s": round(time.time() - t0, 1)}
    # the benches print exactly one JSON line on stdout; text-only
    # legs (flash_bench) get their informative stdout tail instead
    for line in reversed(out.strip().splitlines()):
        try:
            rec["result"] = json.loads(line)
            break
        except ValueError:
            continue
    if "result" not in rec:
        # every leg must emit a parseable JSON result line — a leg
        # that does not is recorded as BROKEN, not silently tailed
        # (round-4's flash_gqa leg regression-tracked nothing)
        rec["unparsed"] = True
        rec["stdout_tail"] = out.strip().splitlines()[-8:]
        if rc == 0:
            rc = 1          # broken, not silently tailed
            rec["rc"] = 1
    if rc != 0:
        rec["stderr_tail"] = (err or "").strip().splitlines()[-8:]
    print(f"  {name}: rc={rc} ({rec['wall_s']}s)", file=sys.stderr)
    return rec


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip", action="append", default=[])
    ap.add_argument("--only", action="append", default=[])
    ap.add_argument("--out", default=str(REPO / "BENCH_extra.json"))
    args = ap.parse_args()

    import jax

    meta = {
        "backend": jax.default_backend(),
        "n_devices": len(jax.devices()),
        "recorded_unix": int(time.time()),
    }
    legs = []
    for name, argv, timeout in LEGS:
        if name in args.skip or (args.only and name not in args.only):
            continue
        legs.append(run_leg(name, argv, timeout))
    out_path = Path(args.out)
    if (args.only or args.skip) and out_path.exists():
        # partial rerun: merge into the existing artifact by leg name
        # so re-measuring one flaky leg keeps the rest; the replaced
        # measurement moves into the leg's `prior` list — the tunneled
        # chip drifts up to ~1.6x between windows (docs/DESIGN.md),
        # and that variance is itself part of the record
        prev = json.loads(out_path.read_text())
        merged = {r["name"]: r for r in prev.get("legs", [])}
        for r in legs:
            old = merged.get(r["name"])
            if old is not None:
                r["prior"] = old.pop("prior", []) + [old]
            merged[r["name"]] = r
        legs_out = [merged[n] for n, _, _ in LEGS if n in merged]
    else:
        legs_out = legs
    out = {"meta": meta, "legs": legs_out}
    out_path.write_text(json.dumps(out, indent=1) + "\n")
    print(f"wrote {args.out} ({len(legs_out)} legs)", file=sys.stderr)
    return 0 if all(r["rc"] == 0 for r in legs) else 1


if __name__ == "__main__":
    sys.exit(main())
