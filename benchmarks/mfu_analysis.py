"""Diagnose the train-step MFU cliff (batch 4 ~80% -> batch 6-8 ~55%).

Round-4 VERDICT item 3: a 30-point MFU collapse from batch 4 to 6 on a
memory-rich chip needs a mechanism, not a comment. The tunneled chip
cannot serve the interactive profiler, so this uses the two compiler
surfaces that ARE available per batch size:

  - compiled.cost_analysis(): flops / bytes accessed -> arithmetic
    intensity the compiler thinks the program has;
  - compiled.memory_analysis(): peak / argument / output / temp HBM
    bytes -> whether a batch step crosses an allocation threshold that
    changes XLA's fusion or forces rematerialization;
  - the HLO module text, grep-counted for fusion kinds and all-reduce/
    copy/convert ops, to spot structural changes between batches.

Prints one summary line per batch plus a JSON artifact on stdout.

Usage: python benchmarks/mfu_analysis.py [--batches 2,4,6,8] [--seq N]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from rlo_tpu.models.transformer import (TransformerConfig,  # noqa: E402
                                        init_params, train_step)

V5E_BF16_PEAK = 197e12
V5E_HBM_GBPS = 819.0


def analyze(cfg, params, batch, seq):
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab, (batch, seq)),
                         jnp.int32)

    @jax.jit
    def step(p, t):
        return train_step(p, t, cfg, lr=1e-4)

    lowered = step.lower(params, tokens)
    compiled = lowered.compile()
    rec = {"batch": batch}
    try:
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        rec["flops"] = float(ca.get("flops", float("nan")))
        rec["bytes_accessed"] = float(ca.get("bytes accessed",
                                             float("nan")))
        if rec["bytes_accessed"]:
            rec["arith_intensity"] = rec["flops"] / rec["bytes_accessed"]
        # the roofline the compiler's own numbers imply
        t_flops = rec["flops"] / V5E_BF16_PEAK
        t_bytes = rec["bytes_accessed"] / (V5E_HBM_GBPS * 1e9)
        rec["compiler_roofline_bound"] = (
            "compute" if t_flops >= t_bytes else "memory")
        rec["t_flops_ms"] = t_flops * 1e3
        rec["t_bytes_ms"] = t_bytes * 1e3
    except Exception as e:  # noqa: BLE001 - record, don't die
        rec["cost_analysis_error"] = repr(e)
    try:
        ma = compiled.memory_analysis()
        for name in ("temp_size_in_bytes", "argument_size_in_bytes",
                     "output_size_in_bytes", "alias_size_in_bytes",
                     "generated_code_size_in_bytes"):
            v = getattr(ma, name, None)
            if v is not None:
                rec[name] = int(v)
        if "temp_size_in_bytes" in rec:
            rec["temp_gib"] = round(rec["temp_size_in_bytes"] / 2**30, 3)
    except Exception as e:  # noqa: BLE001
        rec["memory_analysis_error"] = repr(e)
    try:
        hlo = compiled.as_text()
        rec["hlo_counts"] = {
            "fusion": len(re.findall(r"\bfusion\b", hlo)),
            "kLoop": hlo.count("kLoop"),
            "kOutput": hlo.count("kOutput"),
            "custom-call": hlo.count("custom-call"),
            "copy": len(re.findall(r"\bcopy\(", hlo)),
            "convert": len(re.findall(r"\bconvert\b", hlo)),
            "while": len(re.findall(r"\bwhile\b", hlo)),
            "reduce": len(re.findall(r"\breduce\(", hlo)),
        }
    except Exception as e:  # noqa: BLE001
        rec["hlo_error"] = repr(e)
    return rec


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", default="2,4,6,8")
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--tiny", action="store_true")
    args = ap.parse_args()

    if args.tiny:
        cfg = TransformerConfig(vocab=128, d_model=64, n_heads=4,
                                n_layers=2, d_ff=256, dtype="float32")
        seq = min(args.seq, 64)
    else:
        cfg = TransformerConfig(vocab=32768, d_model=1024, n_heads=16,
                                n_layers=8, d_ff=4096, dtype="bfloat16")
        seq = args.seq
    params = init_params(jax.random.PRNGKey(0), cfg)

    out = []
    for b in [int(x) for x in args.batches.split(",")]:
        rec = analyze(cfg, params, b, seq)
        out.append(rec)
        flat = {k: v for k, v in rec.items() if k != "hlo_counts"}
        print(f"batch {b}: " + json.dumps(flat), file=sys.stderr)
    print(json.dumps({"seq": seq, "per_batch": out}))


if __name__ == "__main__":
    main()
