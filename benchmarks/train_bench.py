"""Flagship train-step benchmark: tokens/s and MFU on the live chip.

Round-2 VERDICT item 5: all recorded perf was collective
microbenchmarks; the model-driven entry (`__graft_entry__.entry`) had
never been timed. This measures the full causal-transformer train step
(forward, loss, grads, SGD update — the same `train_step` the dryrun
shards) with bench.py's chained methodology: K serially-dependent steps
inside one jit (params carry), minus the empty-chain dispatch floor.

MFU accounting (PaLM-style):
  flops/token = 6 * n_params                (fwd+bwd matmuls)
              + 12 * n_layers * d_model * seq * 0.5   (causal attention
                q·k and p·v, fwd+bwd, halved by the causal mask)
  MFU = achieved flops/s / peak, peak = 197e12 (v5e bf16).

Prints one JSON line; diagnostics to stderr. --tiny runs a toy config
(CPU-safe smoke shape for tests).
"""

import argparse
import json
import os
import sys
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import bench  # noqa: E402
from rlo_tpu.models.transformer import (TransformerConfig,  # noqa: E402
                                        init_params, train_step)

V5E_BF16_PEAK = 197e12


def flops_per_token(cfg, n_params: int, seq: int) -> float:
    return (6.0 * n_params
            + 12.0 * cfg.n_layers * cfg.d_model * seq * 0.5)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true",
                    help="toy shapes (CPU smoke test)")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--seq", type=int, default=None)
    args = ap.parse_args()

    if args.tiny:
        cfg = TransformerConfig(vocab=128, d_model=64, n_heads=4,
                                n_layers=2, d_ff=256, dtype="float32")
        batch, seq = args.batch or 2, args.seq or 32
        k = 4
    else:
        # ~134M params; batch tuned on the chip (2026-07-30 sweep:
        # batch 2 -> 66%, 4 -> 74-84%, 6 -> 54%, 8 -> 56%, 16 -> 51%
        # MFU — batch 4 is a sharp sweet spot. Chunked loss
        # (cfg.loss_vocab_chunk) was tried and measured SLOWER at
        # every batch, so the falloff above 4 is not the logits
        # working set; left at the empirical optimum.
        cfg = TransformerConfig(vocab=32768, d_model=1024, n_heads=16,
                                n_layers=8, d_ff=4096, dtype="bfloat16")
        batch, seq = args.batch or 4, args.seq or 1024
        k = 8

    params = init_params(jax.random.PRNGKey(0), cfg)
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree.leaves(params))
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab, (batch, seq)),
                         jnp.int32)

    @partial(jax.jit, static_argnames=("kk",))
    def chain(p, t, kk):
        def it(i, p):
            new_p, _ = train_step(p, t, cfg, lr=1e-4)
            return new_p
        return jax.lax.fori_loop(0, kk, it, p)

    def loop(p, t, kk):
        return jax.tree.leaves(chain(p, t, kk))[0]

    # median, not min: at batch >= 8 the dispatch floor is a sizable
    # fraction of the chain and min() picks the rep with the most
    # inflated floor estimate (a recorded batch-8 MFU of 1.22 — above
    # the physical peak — came from exactly that; see _chain_time)
    t_step = bench._chain_time(loop, params, tokens, k=k, stat="median")
    tok_per_step = batch * seq
    tok_s = tok_per_step / t_step
    fl_tok = flops_per_token(cfg, n_params, seq)
    achieved = tok_s * fl_tok
    on_tpu = jax.default_backend() == "tpu"
    mfu = achieved / V5E_BF16_PEAK if on_tpu else float("nan")

    # window-relative MFU: the tunneled chip's DELIVERED throughput
    # drifts ~1.6x between windows (identical code recorded 0.52 and
    # 0.86 nominal MFU), so also time a roofline probe — a big bf16
    # matmul chain — in the SAME window and report the step's flops as
    # a fraction of the probe's achieved flops. This ratio is the
    # drift-immune number: how close the train step is to what the
    # chip will actually give you right now.
    mfu_rel = float("nan")
    if on_tpu:
        mm = 2048
        a = jnp.asarray(np.random.default_rng(1).standard_normal(
            (mm, mm)), jnp.bfloat16)

        @partial(jax.jit, static_argnames=("kk",))
        def mm_chain(a, kk):
            def it(i, x):
                return jnp.tanh(x @ a)  # tanh blocks trivial fusion
            return jax.lax.fori_loop(0, kk, it, a)

        t_mm = bench._chain_time(lambda x, kk: mm_chain(x, kk), a,
                                 k=256, stat="median")
        probe_flops = 2.0 * mm ** 3 / t_mm
        mfu_rel = achieved / probe_flops
        print(f"roofline probe: {probe_flops/1e12:.1f} TFLOP/s "
              f"({probe_flops/V5E_BF16_PEAK:.1%} of nominal peak this "
              f"window); window-relative MFU {mfu_rel:.1%}",
              file=sys.stderr)
    print(f"params={n_params/1e6:.1f}M batch={batch} seq={seq} "
          f"step={t_step*1e3:.2f} ms  {tok_s:,.0f} tok/s  "
          f"{achieved/1e12:.1f} TFLOP/s"
          + (f"  MFU={mfu:.1%} of v5e bf16 peak" if on_tpu else
             "  (not a TPU: no MFU)"),
          file=sys.stderr)
    note = ""
    if on_tpu and mfu > 1.0:
        # same physical gate as bench.py's 819 GB/s clamp: an MFU
        # above peak proves floor-subtraction corruption, not speed
        note = (f" [measured {mfu:.3f} > 1.0 physical peak: floor-"
                f"corrupted rep; clamped]")
        print(f"WARNING: impossible MFU {mfu:.3f}{note}",
              file=sys.stderr)
        mfu = 1.0
        tok_s = min(tok_s, V5E_BF16_PEAK / fl_tok)
    rec = {
        "metric": f"causal-transformer train step, {n_params/1e6:.0f}M "
                  f"params, batch {batch} x seq {seq}, "
                  f"{'bf16 v5e chip' if on_tpu else jax.default_backend()}"
                  + note,
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu, 4) if on_tpu else 0.0,
        "vs_baseline_meaning": "MFU fraction of 197 TFLOP/s v5e bf16 peak",
    }
    if on_tpu and mfu_rel == mfu_rel:
        rec["mfu_window_relative"] = round(mfu_rel, 4)
        rec["mfu_window_relative_meaning"] = (
            "step flops / same-window roofline-matmul flops — "
            "drift-immune (the chip's delivered peak moves ~1.6x "
            "between windows)")
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
