"""Speculative-decoding infrastructure costs on the live chip.

Speculative decoding pays off when (a) verifying gamma tokens in one
target forward costs about one decode step (weights stream once), and
(b) the draft step is much cheaper than the target step. Those two
ratios are properties of THIS framework on THIS chip — measured here
— while the acceptance rate is a property of the model pair, so the
bench reports the measured cost terms and the implied end-to-end
speedup curve over acceptance:

    yield(a)   = sum_{i<gamma} a^i          (expected tokens/round)
    speedup(a) = yield(a) / (gamma*c_d + c_v)

with c_d, c_v in units of one target decode step. Timings use the
interleaved chained protocol (chain k data-dependent ops in one jit;
interleave the contenders pair-by-pair so window drift cancels —
docs/DESIGN.md measurement methodology).

--e2e (round-5 VERDICT item 2) makes the speedup REAL rather than
implied: distill a 2-layer draft from the flagship target on-chip
(teacher greedy continuations -> masked-CE student training, one
jitted scan), measure the realized acceptance (verify rounds taken,
via speculative_generate(return_rounds=True)), and time WHOLE
speculative_generate vs generate calls in interleaved pairs — the
recorded number is measured end-to-end speedup at batch 1, with the
measured acceptance in the metric line.

Usage: python benchmarks/spec_bench.py [--tiny] [--gamma N] [--e2e]
"""

import argparse
import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from rlo_tpu.models.generate import (block_decode, decode_step,  # noqa: E402
                                     init_kv_cache, prefill)
from rlo_tpu.models.transformer import (TransformerConfig,  # noqa: E402
                                        init_params)


def build_chain(params, cfg, cache, plen, batch, gamma, mode):
    """One jit: k outer iterations of either gamma sequential decode
    steps ('steps') or one gamma-wide block_decode ('block'), writing
    the SAME cache slots every iteration (fixed position window; the
    data dependence token <- argmax keeps iterations ordered)."""

    @partial(jax.jit, static_argnames=("kk",))
    def run(params, cache, tok, kk):
        def outer(i, carry):
            tok, cache = carry
            if mode == "steps":
                for g in range(gamma):
                    logits, cache = decode_step(params, tok, plen + g,
                                                cache, cfg)
                    tok = jnp.argmax(logits, -1).astype(jnp.int32)
            else:
                blk = jnp.broadcast_to(tok[:, None],
                                       (batch, gamma)).astype(jnp.int32)
                logits, cache = block_decode(
                    params, blk, jnp.full((batch,), plen, jnp.int32),
                    cache, cfg)
                tok = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
            return tok, cache
        tok, cache = jax.lax.fori_loop(0, kk, outer, (tok, cache))
        return tok

    return run


def chain_time_pair(run_a, run_b, args_a, args_b, k, pairs=9):
    """Median per-op times of two chains: each is timed at k and 2k
    iterations within the same interleaved pair, per-op = (t(2k) -
    t(k)) / k — the ~110 ms dispatch floor AND window drift both
    cancel inside the pair (an early revision skipped the floor
    subtraction and reported 1.7 ms of floor as the 'step cost')."""
    for run, a in ((run_a, args_a), (run_b, args_b)):
        np.asarray(run(*a, k))
        np.asarray(run(*a, 2 * k))  # compile + warm both lengths
    ta, tb = [], []
    for _ in range(pairs):
        t = []
        for run, a, kk in ((run_a, args_a, k), (run_a, args_a, 2 * k),
                           (run_b, args_b, k), (run_b, args_b, 2 * k)):
            t0 = time.perf_counter()
            np.asarray(run(*a, kk))
            t.append(time.perf_counter() - t0)
        ta.append((t[1] - t[0]) / k)
        tb.append((t[3] - t[2]) / k)
    ta, tb = float(np.median(ta)), float(np.median(tb))
    if ta <= 0 or tb <= 0:
        raise RuntimeError(
            f"chain differencing swallowed by noise (ta={ta}, tb={tb})"
            f" — raise k")
    return ta, tb


def distill_draft(params, cfg, dcfg, *, plen, seq, n_batches, batch,
                  steps, lr, seed=0):
    """Distill a draft from the target's own greedy trajectories:
    teacher-generate (batch, seq) sequences from random prompts, then
    train the draft with next-token CE masked to the continuation
    region (the prompt region is random noise) in ONE jitted scan.
    Returns (draft_params, heldout_agreement)."""
    import optax

    from rlo_tpu.models.generate import generate
    from rlo_tpu.models.transformer import forward, init_params

    rng = np.random.default_rng(seed)
    # ONE generate call for the whole corpus: every extra tunnel round
    # trip is a chance for the remote compiler to wedge (two runs died
    # with broken pipes mid-loop), and a (nb+1)*batch-row generate is
    # cheap — the cache at seq 128 is a few GB at most
    rows = (n_batches + 1) * batch
    pr = jnp.asarray(rng.integers(0, cfg.vocab, (rows, plen)),
                     jnp.int32)
    # params MUST be jit arguments, not closure constants: captured
    # arrays ship inside the remote-compile request body and the 537MB
    # f32 flagship weights blow the tunnel's HTTP limit (413; at other
    # sizes it presents as a broken pipe)
    toks = np.asarray(jax.jit(lambda P, pr: generate(
        P, pr, cfg, max_new=seq - plen))(params, pr))
    corpus = np.concatenate([np.asarray(pr), toks], axis=1)
    held = jnp.asarray(corpus[:batch])
    data = jnp.asarray(corpus[batch:].reshape(n_batches, batch, seq))
    print(f"distill: teacher data {data.shape} generated",
          file=sys.stderr)

    dparams = init_params(jax.random.PRNGKey(seed + 1), dcfg)
    opt = optax.adam(lr)
    opt_state = opt.init(dparams)
    m = (jnp.arange(seq - 1) >= plen - 1)[None, :]

    def ce(dp, toks):
        lg = forward(dp, toks[:, :-1], dcfg).astype(jnp.float32)
        ll = jnp.take_along_axis(jax.nn.log_softmax(lg),
                                 toks[:, 1:, None], -1)[..., 0]
        return -(ll * m).sum() / (m.sum() * toks.shape[0])

    @jax.jit
    def train(dp, st):
        def step(carry, i):
            dp, st = carry
            loss, g = jax.value_and_grad(ce)(dp, data[i % n_batches])
            upd, st = opt.update(g, st)
            return (optax.apply_updates(dp, upd), st), loss
        (dp, _), losses = jax.lax.scan(step, (dp, st),
                                       jnp.arange(steps))
        return dp, losses

    dparams, losses = train(dparams, opt_state)
    losses = np.asarray(losses)
    lg = jax.jit(lambda dp, t: forward(dp, t, dcfg))(
        dparams, held[:, :-1])
    agree = np.asarray(
        (jnp.argmax(lg, -1) == held[:, 1:]) & m).sum() / float(
            np.asarray(m).sum() * batch)
    print(f"distill: loss {losses[0]:.3f} -> {losses[-1]:.3f} over "
          f"{len(losses)} steps; held-out argmax agreement "
          f"{agree:.1%}", file=sys.stderr)
    return dparams, float(agree)


def e2e(args, cfg, dcfg, gamma):
    """Measured end-to-end: distilled draft, realized acceptance,
    whole-call interleaved timing at batch 1."""
    from rlo_tpu.models.generate import generate
    from rlo_tpu.models.speculative import speculative_generate
    from rlo_tpu.models.transformer import init_params

    params = init_params(jax.random.PRNGKey(0), cfg)
    if args.tiny:
        plen, seq, nb, dbatch, steps, lr = 8, 32, 2, 4, 20, 1e-3
        max_new, k = 16, 2
    else:
        plen, seq, nb, dbatch, steps, lr = 16, 128, 24, 32, 1200, 3e-4
        max_new, k = 128, 4
    dparams, agree = distill_draft(params, cfg, dcfg, plen=plen,
                                   seq=seq, n_batches=nb, batch=dbatch,
                                   steps=steps, lr=lr)

    # measurement prompt length: speculative pays when steps are big
    # relative to the per-round control machinery — long prompts make
    # the target step cache-bound (the latency-sensitive serving
    # case). Distillation stays at short prompts (the corpus is about
    # the model pair, not the prompt length).
    plen_m = args.prompt_len if args.prompt_len > plen else plen

    # realized acceptance at batch 1: verify rounds over fresh prompts
    # (vmapped over 8 prompts — one chip call, not eight)
    rng = np.random.default_rng(99)
    prompts = jnp.asarray(rng.integers(0, cfg.vocab, (8, 1, plen_m)),
                          jnp.int32)
    spec_v = jax.jit(lambda P, D, prs: jax.vmap(
        lambda pr: speculative_generate(
            P, D, pr, cfg, dcfg, max_new=max_new, gamma=gamma,
            return_rounds=True)[1])(prs))
    rounds = [int(r) for r in np.asarray(
        spec_v(params, dparams, prompts))]
    tok_round = (max_new - 1) / float(np.mean(rounds))
    print(f"e2e: rounds over 8 prompts {rounds} -> "
          f"{tok_round:.2f} tokens/round (ideal {gamma})",
          file=sys.stderr)

    # end-to-end interleaved timing: chain whole generate /
    # speculative_generate calls (each iteration's prompt depends on
    # the previous output — no CSE), paired at k and 2k
    p0 = prompts[0]

    @partial(jax.jit, static_argnames=("kk",))
    def plain_chain(P, pr, kk):
        def it(i, carry):
            pr, acc = carry
            toks = generate(P, pr, cfg, max_new=max_new)
            pr = pr.at[0, 0].set(toks[0, -1] % cfg.vocab)
            return pr, acc + toks[0, -1]
        return jax.lax.fori_loop(0, kk, it, (pr, jnp.int32(0)))[1]

    @partial(jax.jit, static_argnames=("kk",))
    def spec_chain(P, D, pr, kk):
        def it(i, carry):
            pr, acc = carry
            toks = speculative_generate(
                P, D, pr, cfg, dcfg, max_new=max_new, gamma=gamma)
            pr = pr.at[0, 0].set(toks[0, -1] % cfg.vocab)
            return pr, acc + toks[0, -1]
        return jax.lax.fori_loop(0, kk, it, (pr, jnp.int32(0)))[1]

    t_plain, t_spec = chain_time_pair(plain_chain, spec_chain,
                                      (params, p0),
                                      (params, dparams, p0), k)
    speedup = t_plain / t_spec
    tok_s = max_new / t_spec
    on_tpu = jax.default_backend() == "tpu"
    print(f"e2e batch 1: plain {max_new/t_plain:,.0f} tok/s, "
          f"speculative {tok_s:,.0f} tok/s -> {speedup:.2f}x "
          f"(agreement {agree:.1%}, {tok_round:.2f} tok/round)",
          file=sys.stderr)
    print(json.dumps({
        "metric": f"speculative decoding END-TO-END, distilled "
                  f"{dcfg.n_layers}-layer draft, gamma={gamma}, "
                  f"batch 1, prompt {plen_m}, measured acceptance "
                  f"{round(tok_round, 2)} tok/round "
                  f"(held-out argmax agreement {round(agree, 3)}), "
                  f"{'bf16 v5e chip' if on_tpu else jax.default_backend()}",
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(speedup, 4),
        "vs_baseline_meaning": "realized speedup over plain greedy "
                               "generate (interleaved whole-call "
                               "pairs)",
    }))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true")
    ap.add_argument("--gamma", type=int, default=4)
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--e2e", action="store_true",
                    help="distill a draft on-chip and measure the "
                         "realized acceptance + end-to-end speedup")
    ap.add_argument("--draft-layers", type=int, default=None)
    ap.add_argument("--draft-dim", type=int, default=None)
    ap.add_argument("--prompt-len", type=int, default=16,
                    help="e2e measurement prompt length (the "
                         "distillation corpus stays short)")
    args = ap.parse_args()
    gamma = args.gamma

    if args.tiny:
        cfg = TransformerConfig(vocab=128, d_model=64, n_heads=4,
                                n_layers=2, d_ff=256, dtype="float32")
        dcfg = TransformerConfig(vocab=128, d_model=32, n_heads=2,
                                 n_layers=1, d_ff=64, dtype="float32")
        batch, plen, k = args.batch or 2, 16, 4
    else:
        cfg = TransformerConfig(vocab=32768, d_model=1024, n_heads=16,
                                n_layers=8, d_ff=4096,
                                dtype="bfloat16")
        dcfg = TransformerConfig(vocab=32768, d_model=512, n_heads=8,
                                 n_layers=2, d_ff=2048,
                                 dtype="bfloat16")
        batch, plen, k = args.batch or 8, 256, 16

    if args.e2e:
        import dataclasses
        if args.draft_layers or args.draft_dim:
            dcfg = dataclasses.replace(
                dcfg,
                n_layers=args.draft_layers or dcfg.n_layers,
                d_model=args.draft_dim or dcfg.d_model,
                n_heads=max(1, (args.draft_dim or dcfg.d_model) // 64),
                d_ff=4 * (args.draft_dim or dcfg.d_model))
        return e2e(args, cfg, dcfg, gamma)

    max_len = plen + gamma + 1
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab, (batch, plen)),
                         jnp.int32)
    tok0 = jnp.asarray(rng.integers(0, cfg.vocab, (batch,)), jnp.int32)

    params = init_params(jax.random.PRNGKey(0), cfg)
    dparams = init_params(jax.random.PRNGKey(1), dcfg)
    t_cache = init_kv_cache(cfg, batch, max_len)
    _, t_cache = prefill(params, prompt, t_cache, cfg)
    d_cache = init_kv_cache(dcfg, batch, max_len)
    _, d_cache = prefill(dparams, prompt, d_cache, dcfg)

    # --- target: gamma steps vs one gamma-block verify --------------
    run_steps = build_chain(params, cfg, t_cache, plen, batch, gamma,
                            "steps")
    run_block = build_chain(params, cfg, t_cache, plen, batch, gamma,
                            "block")
    t_steps, t_block = chain_time_pair(
        run_steps, run_block, (params, t_cache, tok0),
        (params, t_cache, tok0), k)
    verify_eff = t_steps / t_block

    # --- draft step cost vs target step cost ------------------------
    run_t1 = build_chain(params, cfg, t_cache, plen, batch, 1, "steps")
    run_d1 = build_chain(dparams, dcfg, d_cache, plen, batch, 1,
                         "steps")
    t_t1, t_d1 = chain_time_pair(run_t1, run_d1,
                                 (params, t_cache, tok0),
                                 (dparams, d_cache, tok0), k * gamma)
    c_d = t_d1 / t_t1
    c_v = t_block / t_t1

    def speedup(a):
        yld = sum(a ** i for i in range(gamma))
        return yld / (gamma * c_d + c_v)

    on_tpu = jax.default_backend() == "tpu"
    print(f"gamma={gamma} batch={batch}: target step "
          f"{t_t1*1e3:.3f} ms, {gamma}-block verify {t_block*1e3:.3f} "
          f"ms ({verify_eff:.2f}x cheaper than {gamma} steps), draft "
          f"step {t_d1*1e3:.3f} ms (c_d={c_d:.3f}, c_v={c_v:.3f})",
          file=sys.stderr)
    print("implied end-to-end speedup: "
          + "  ".join(f"a={a}: {speedup(a):.2f}x"
                      for a in (0.5, 0.7, 0.8, 0.9, 1.0)),
          file=sys.stderr)
    print(json.dumps({
        "metric": f"speculative verify efficiency: {gamma}-token "
                  f"block verify vs {gamma} decode steps, "
                  f"{'bf16 v5e chip' if on_tpu else jax.default_backend()}"
                  f" (interleaved chained ratio; c_d={round(c_d, 3)}, "
                  f"implied speedup at 80% acceptance "
                  f"{round(speedup(0.8), 2)}x)",
        "value": round(verify_eff, 3),
        "unit": "x",
        "vs_baseline": round(verify_eff / gamma, 4),
        "vs_baseline_meaning": "fraction of the ideal (verify == one "
                               "step would be 1.0 at value == gamma)",
    }))


if __name__ == "__main__":
    main()
