"""Speculative-decoding infrastructure costs on the live chip.

Speculative decoding pays off when (a) verifying gamma tokens in one
target forward costs about one decode step (weights stream once), and
(b) the draft step is much cheaper than the target step. Those two
ratios are properties of THIS framework on THIS chip — measured here
— while the acceptance rate is a property of the model pair, so the
bench reports the measured cost terms and the implied end-to-end
speedup curve over acceptance:

    yield(a)   = sum_{i<gamma} a^i          (expected tokens/round)
    speedup(a) = yield(a) / (gamma*c_d + c_v)

with c_d, c_v in units of one target decode step. Timings use the
interleaved chained protocol (chain k data-dependent ops in one jit;
interleave the contenders pair-by-pair so window drift cancels —
docs/DESIGN.md measurement methodology).

Usage: python benchmarks/spec_bench.py [--tiny] [--gamma N]
"""

import argparse
import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from rlo_tpu.models.generate import (block_decode, decode_step,  # noqa: E402
                                     init_kv_cache, prefill)
from rlo_tpu.models.transformer import (TransformerConfig,  # noqa: E402
                                        init_params)


def build_chain(params, cfg, cache, plen, batch, gamma, mode):
    """One jit: k outer iterations of either gamma sequential decode
    steps ('steps') or one gamma-wide block_decode ('block'), writing
    the SAME cache slots every iteration (fixed position window; the
    data dependence token <- argmax keeps iterations ordered)."""

    @partial(jax.jit, static_argnames=("kk",))
    def run(params, cache, tok, kk):
        def outer(i, carry):
            tok, cache = carry
            if mode == "steps":
                for g in range(gamma):
                    logits, cache = decode_step(params, tok, plen + g,
                                                cache, cfg)
                    tok = jnp.argmax(logits, -1).astype(jnp.int32)
            else:
                blk = jnp.broadcast_to(tok[:, None],
                                       (batch, gamma)).astype(jnp.int32)
                logits, cache = block_decode(
                    params, blk, jnp.full((batch,), plen, jnp.int32),
                    cache, cfg)
                tok = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
            return tok, cache
        tok, cache = jax.lax.fori_loop(0, kk, outer, (tok, cache))
        return tok

    return run


def chain_time_pair(run_a, run_b, args_a, args_b, k, pairs=9):
    """Median per-op times of two chains: each is timed at k and 2k
    iterations within the same interleaved pair, per-op = (t(2k) -
    t(k)) / k — the ~110 ms dispatch floor AND window drift both
    cancel inside the pair (an early revision skipped the floor
    subtraction and reported 1.7 ms of floor as the 'step cost')."""
    for run, a in ((run_a, args_a), (run_b, args_b)):
        np.asarray(run(*a, k))
        np.asarray(run(*a, 2 * k))  # compile + warm both lengths
    ta, tb = [], []
    for _ in range(pairs):
        t = []
        for run, a, kk in ((run_a, args_a, k), (run_a, args_a, 2 * k),
                           (run_b, args_b, k), (run_b, args_b, 2 * k)):
            t0 = time.perf_counter()
            np.asarray(run(*a, kk))
            t.append(time.perf_counter() - t0)
        ta.append((t[1] - t[0]) / k)
        tb.append((t[3] - t[2]) / k)
    ta, tb = float(np.median(ta)), float(np.median(tb))
    if ta <= 0 or tb <= 0:
        raise RuntimeError(
            f"chain differencing swallowed by noise (ta={ta}, tb={tb})"
            f" — raise k")
    return ta, tb


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true")
    ap.add_argument("--gamma", type=int, default=4)
    ap.add_argument("--batch", type=int, default=None)
    args = ap.parse_args()
    gamma = args.gamma

    if args.tiny:
        cfg = TransformerConfig(vocab=128, d_model=64, n_heads=4,
                                n_layers=2, d_ff=256, dtype="float32")
        dcfg = TransformerConfig(vocab=128, d_model=32, n_heads=2,
                                 n_layers=1, d_ff=64, dtype="float32")
        batch, plen, k = args.batch or 2, 16, 4
    else:
        cfg = TransformerConfig(vocab=32768, d_model=1024, n_heads=16,
                                n_layers=8, d_ff=4096,
                                dtype="bfloat16")
        dcfg = TransformerConfig(vocab=32768, d_model=512, n_heads=8,
                                 n_layers=2, d_ff=2048,
                                 dtype="bfloat16")
        batch, plen, k = args.batch or 8, 256, 16

    max_len = plen + gamma + 1
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab, (batch, plen)),
                         jnp.int32)
    tok0 = jnp.asarray(rng.integers(0, cfg.vocab, (batch,)), jnp.int32)

    params = init_params(jax.random.PRNGKey(0), cfg)
    dparams = init_params(jax.random.PRNGKey(1), dcfg)
    t_cache = init_kv_cache(cfg, batch, max_len)
    _, t_cache = prefill(params, prompt, t_cache, cfg)
    d_cache = init_kv_cache(dcfg, batch, max_len)
    _, d_cache = prefill(dparams, prompt, d_cache, dcfg)

    # --- target: gamma steps vs one gamma-block verify --------------
    run_steps = build_chain(params, cfg, t_cache, plen, batch, gamma,
                            "steps")
    run_block = build_chain(params, cfg, t_cache, plen, batch, gamma,
                            "block")
    t_steps, t_block = chain_time_pair(
        run_steps, run_block, (params, t_cache, tok0),
        (params, t_cache, tok0), k)
    verify_eff = t_steps / t_block

    # --- draft step cost vs target step cost ------------------------
    run_t1 = build_chain(params, cfg, t_cache, plen, batch, 1, "steps")
    run_d1 = build_chain(dparams, dcfg, d_cache, plen, batch, 1,
                         "steps")
    t_t1, t_d1 = chain_time_pair(run_t1, run_d1,
                                 (params, t_cache, tok0),
                                 (dparams, d_cache, tok0), k * gamma)
    c_d = t_d1 / t_t1
    c_v = t_block / t_t1

    def speedup(a):
        yld = sum(a ** i for i in range(gamma))
        return yld / (gamma * c_d + c_v)

    on_tpu = jax.default_backend() == "tpu"
    print(f"gamma={gamma} batch={batch}: target step "
          f"{t_t1*1e3:.3f} ms, {gamma}-block verify {t_block*1e3:.3f} "
          f"ms ({verify_eff:.2f}x cheaper than {gamma} steps), draft "
          f"step {t_d1*1e3:.3f} ms (c_d={c_d:.3f}, c_v={c_v:.3f})",
          file=sys.stderr)
    print("implied end-to-end speedup: "
          + "  ".join(f"a={a}: {speedup(a):.2f}x"
                      for a in (0.5, 0.7, 0.8, 0.9, 1.0)),
          file=sys.stderr)
    print(json.dumps({
        "metric": f"speculative verify efficiency: {gamma}-token "
                  f"block verify vs {gamma} decode steps, "
                  f"{'bf16 v5e chip' if on_tpu else jax.default_backend()}"
                  f" (interleaved chained ratio; c_d={round(c_d, 3)}, "
                  f"implied speedup at 80% acceptance "
                  f"{round(speedup(0.8), 2)}x)",
        "value": round(verify_eff, 3),
        "unit": "x",
        "vs_baseline": round(verify_eff / gamma, 4),
        "vs_baseline_meaning": "fraction of the ideal (verify == one "
                               "step would be 1.0 at value == gamma)",
    }))


if __name__ == "__main__":
    main()
