"""Flash-decode attend bandwidth diagnosis (round-5 item 1 follow-up).

decode_analysis measured the cache attend at ~370 GB/s while every
matmul component streams at ~700+ GB/s in the same window. Leading
hypothesis: a head-minor cache layout (b, kvh, L, head_dim=64) has a 64-wide
minor dimension — half a (8, 128) native lane tile — so HBM tiles are
lane-padded and the DMA streams at half width. This sweep pins it by
measuring the SAME cache bytes under different shapes/layouts in one
window:

  a. flash (32, 16, ., 64)    - production shape (hd 64)
  b. flash (32, 8, ., 128)    - same bytes, wider head_dim
  c. flash block_k=128        - finer cache tiles (DMA pipelining)
  d. einsum same shape        - the XLA path for reference
  e. L = 1280 (plen-1024 serving regime) variants of a/b

RESULT (2026-07-31, pre-fix head-minor layout): hd64 365 GB/s vs
hd128 703 GB/s at identical bytes — confirmed the lane-padding
hypothesis, and the cache layout was flipped to SEQ-MINOR
(models.generate.init_kv_cache); this sweep now measures the new
layout, where hd64 and hd128 should both stream at full width.

Usage: python benchmarks/attend_sweep.py [--tiny]
"""

import argparse
import json
import os
import sys
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from benchmarks.decode_analysis import chain_time  # noqa: E402
from rlo_tpu.models.generate import _attend_cache  # noqa: E402

V5E_HBM_GBPS = 819.0


def attend_leg(batch, kvh, L, hd, *, block_k=None, use_flash=True,
               dt=jnp.bfloat16, label=""):
    rng = np.random.default_rng(0)
    nh = 16  # total query heads fixed: (kvh, hd) vary, bytes constant
    kc = jnp.asarray(rng.standard_normal((batch, kvh, hd, L)), dt)
    vc = jnp.asarray(rng.standard_normal((batch, kvh, hd, L)), dt)
    q0 = jnp.asarray(rng.standard_normal((batch, 1, nh, hd)), dt)
    scale = 1.0 / np.sqrt(hd)
    pos = L - 8

    kwargs = {}
    if block_k is not None:
        from rlo_tpu.pallas.decode import flash_decode

        @partial(jax.jit, static_argnames=("kk",))
        def run(q, kk):
            def it(i, q):
                o = flash_decode(q, kc, vc, pos, scale,
                                 block_k=block_k)
                return o.astype(dt)
            return jax.lax.fori_loop(0, kk, it, q)
    else:
        @partial(jax.jit, static_argnames=("kk",))
        def run(q, kk):
            def it(i, q):
                o = _attend_cache(q, kc, vc, pos, scale,
                                  use_flash=use_flash)
                return o.astype(dt)
            return jax.lax.fori_loop(0, kk, it, q)

    nbytes = 2 * batch * kvh * L * hd * (2 if dt == jnp.bfloat16 else 4)
    t = chain_time(run, q0, nbytes, label=label)
    gbps = nbytes / t / 1e9
    print(f"{label}: {t*1e6:.1f} us, {nbytes/2**20:.1f} MB -> "
          f"{gbps:.0f} GB/s ({gbps/V5E_HBM_GBPS:.0%} of nominal)",
          file=sys.stderr)
    return gbps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true")
    args = ap.parse_args()
    if args.tiny:
        legs = {
            "hd64": attend_leg(2, 4, 64, 64, dt=jnp.float32,
                               label="hd64"),
            "hd128": attend_leg(2, 2, 64, 128, dt=jnp.float32,
                                label="hd128"),
        }
    else:
        legs = {}
        legs["hd64_L208"] = attend_leg(32, 16, 208, 64,
                                       label="hd64_L208")
        legs["hd128_L208"] = attend_leg(32, 8, 208, 128,
                                        label="hd128_L208")
        legs["hd64_L208_bk128"] = attend_leg(32, 16, 208, 64,
                                             block_k=128,
                                             label="hd64_L208_bk128")
        legs["hd64_L208_einsum"] = attend_leg(32, 16, 208, 64,
                                              use_flash=False,
                                              label="hd64_L208_einsum")
        legs["hd64_L1280"] = attend_leg(32, 16, 1280, 64,
                                        label="hd64_L1280")
        legs["hd128_L1280"] = attend_leg(32, 8, 1280, 128,
                                         label="hd128_L1280")
        legs["hd64_L1280_bk128"] = attend_leg(32, 16, 1280, 64,
                                              block_k=128,
                                              label="hd64_L1280_bk128")
    print(json.dumps({"attend_gbps": {k: round(v, 1)
                                      for k, v in legs.items()}}))


if __name__ == "__main__":
    main()
