"""Serving-fabric benchmark — BENCH_fabric.json (docs/DESIGN.md §11).

Runs the rootless serving fabric (rlo_tpu/serving) over the
deterministic simulator with the stub backend and records, per leg:

  - **drain_vtime**: virtual time from the first client arrival to
    every accepted request completed at every live rank — admission
    broadcast, IAR placement, decode rounds, and (in the failover leg)
    failure detection + re-queue all included. Seed-exact, so the gate
    compares at ZERO tolerance: a protocol change that adds a hop or
    slows fail-over moves this number and fails mechanically.
  - **events**: total simulator schedule length — the fabric's
    message cost. Seed-exact.
  - **requeues / e2e_mean_usec**: fail-over work and the fleet
    end-to-end latency rollup (virtual usec) — seed-exact.
  - **wall_events_per_sec**: host throughput, informational.

Legs: ``steady4`` (4 ranks, no faults), ``failover4`` (4 ranks, the
warm-up owner killed mid-decode), ``steady8`` (8 ranks), and
``failover4_remedy`` (the ``remedy_flap`` chaos shape with the §22
remediation loop armed — the gate pins the schedule digest, the IAR
decision count, executed quarantines, and the recovered end state).
Output schema shared with engine_bench/sim_bench, consumed by
``rlo_tpu.tools.perf_gate`` (check.sh gates against the committed
BENCH_fabric.json).

Usage:
    python benchmarks/fabric_bench.py --out BENCH_fabric.json
    python benchmarks/fabric_bench.py --quick   # smaller leg set
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path
from random import Random

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def exact(value):
    return {"value": value, "direction": "exact", "tolerance": None}


def info(value):
    return {"value": value, "direction": "higher", "tolerance": None}


def run_leg(n: int, n_req: int, seed: int, kill_at=None,
            decode_interval: float = 0.5, limit: float = 600.0,
            arrivals=None):
    """One fabric run to drain: returns (drain vtime, events,
    requeues, fleet e2e mean usec, wall seconds).

    ``arrivals`` switches the client load from the historical
    seeded-rng mix (None — byte-identical to the committed
    BENCH_fabric.json legs) to explicit ``(t, gateway, prompt,
    max_new)`` rows — the trace-driven path fed by
    rlo_tpu/workloads/traces.py (``Trace.fabric_arrivals``)."""
    import logging
    logging.getLogger("rlo_tpu").setLevel(logging.ERROR)
    from rlo_tpu.engine import EngineManager, ProgressEngine
    from rlo_tpu.serving.backend import StubBackend
    from rlo_tpu.serving.fabric import DecodeFabric, fleet_stats
    from rlo_tpu.serving.scenario import FABRIC_ENGINE_KW
    from rlo_tpu.transport.sim import SimWorld

    world = SimWorld(n, seed=seed, protocol_only=True)
    mgr = EngineManager()
    engines = [ProgressEngine(world.transport(r), manager=mgr,
                              clock=world.clock, **FABRIC_ENGINE_KW)
               for r in range(n)]
    fabrics = [DecodeFabric(engines[r], StubBackend(n_slots=2),
                            decode_interval=decode_interval)
               for r in range(n)]
    rng = Random(seed * 9_176_867 + 5)
    victim = 0 if kill_at is not None else None
    gateways = [r for r in range(n) if r != victim]
    if arrivals is None:
        # client arrivals spread over the first 12 vtime units
        arrivals = sorted(
            (round(rng.uniform(1.0, 12.0), 3), rng.choice(gateways))
            for _ in range(n_req))
        rows = None
    else:
        rows = sorted(arrivals, key=lambda a: a[0])
        if not rows:
            raise ValueError(
                "empty arrivals: the trace holds no requests (a "
                "fully torn JSONL file loads as an empty Trace)")
        arrivals = [(t, g) for t, g, _, _ in rows]
        n_req = len(arrivals)
    submitted = []
    live = set(range(n))
    killed = False
    ai = 0
    t_first = arrivals[0][0]
    t_wall = time.perf_counter()
    drain_at = None
    while world.now < limit:
        while ai < len(arrivals) and arrivals[ai][0] <= world.now:
            t, g = arrivals[ai]
            if rows is None:
                plen = rng.randrange(3, 10)
                prompt = tuple(rng.randrange(1, 1 << 15)
                               for _ in range(plen))
                max_new = rng.randrange(6, 30)
            else:
                prompt = tuple(int(x) for x in rows[ai][2])
                max_new = int(rows[ai][3])
            ai += 1
            rid = fabrics[g].submit(prompt, max_new)
            submitted.append(rid)
        if kill_at is not None and not killed and \
                world.now >= kill_at:
            killed = True
            world.kill_rank(victim)
            engines[victim].cleanup()
            live.discard(victim)
        world.step()
        mgr.progress_all()
        for r in sorted(live):
            fabrics[r].pump()
        if ai == len(arrivals) and (kill_at is None or killed):
            if all(rid in fabrics[r].done
                   for r in live for rid in submitted):
                drain_at = world.now
                break
    wall = time.perf_counter() - t_wall
    if drain_at is None:
        raise RuntimeError(
            f"fabric leg (n={n}, kill={kill_at}) did not drain by "
            f"vtime {limit}")
    fl = fleet_stats([fabrics[r] for r in sorted(live)])
    requeues = sum(fabrics[r].requeues for r in live)
    e2e_mean = fl["e2e_usec"]["mean"] or 0.0
    return (drain_at - t_first, world.events, requeues,
            e2e_mean, wall)


def remedy_leg(seed: int = 0):
    """The §22 remediation leg: the ``remedy_flap`` chaos shape (kill
    + elastic rejoin + a sustained loss window) with telemetry, the
    DEFAULT watchdog SLOs and the RemedyPolicy armed. The scenario
    property-checks the remediation invariants internally (min-alive
    quorum, blast-radius cap, expected quarantine target, drain,
    recovered admission) and everything it returns is seed-exact, so
    the gate pins the WHOLE loop at zero tolerance: the schedule
    digest, the IAR decision count, the executed quarantines, and the
    fully-recovered end state (no rank quarantined, backpressure back
    at level 0). A change that delays the trip, re-orders a decision,
    or wedges the hysteresis moves one of these and fails
    mechanically."""
    from rlo_tpu.serving.scenario import make_fabric_scenario

    t_wall = time.perf_counter()
    res = make_fabric_scenario("remedy_flap", seed).run()
    wall = time.perf_counter() - t_wall
    rem = res["remedy"]
    quar = sum(1 for log in rem["logs"].values()
               for e in log if e[1] == "QUARANTINE")
    print(f"failover4_remedy: {res['events']} events, "
          f"{res['requeues']} requeues, {rem['decided']} decided, "
          f"{quar} quarantine execs, bp_final {rem['bp_final']}, "
          f"wall {wall:.2f}s", file=sys.stderr)
    pfx = "failover4_remedy"
    return {
        f"{pfx}.digest": exact(res["digest"]),
        f"{pfx}.events": exact(res["events"]),
        f"{pfx}.submitted": exact(res["submitted"]),
        f"{pfx}.requeues": exact(res["requeues"]),
        f"{pfx}.remedies_decided": exact(rem["decided"]),
        f"{pfx}.quarantines_executed": exact(quar),
        f"{pfx}.final_quarantined": exact(
            len(rem["final_quarantined"])),
        f"{pfx}.bp_final": exact(rem["bp_final"]),
        f"{pfx}.wall_events_per_sec": info(
            round(res["events"] / wall, 1) if wall > 0 else 0.0),
    }


def trace_doc(trace, n: int, time_scale: float = 1.0,
              decode_interval: float = 0.5):
    """Run one trace-driven fabric leg (rlo_tpu/workloads traces
    mapped onto gateways via ``Trace.fabric_arrivals``) and return a
    perf_gate document whose metrics — including the trace digest —
    all gate exact. benchmarks/workload_bench.py commits one of these
    into BENCH_workload.json."""
    rows = trace.fabric_arrivals(list(range(n)),
                                 time_scale=time_scale)
    vt, events, requeues, e2e, wall = run_leg(
        n=n, n_req=len(rows), seed=trace.seed, arrivals=rows,
        decode_interval=decode_interval)
    print(f"trace[{trace.kind}]: {len(rows)} reqs, drain {vt:.2f} "
          f"vtime, {events} events, {requeues} requeues, "
          f"wall {wall:.2f}s", file=sys.stderr)
    pfx = f"trace_{trace.kind}"
    return {
        "suite": "fabric_bench",
        "config": {"trace_kind": trace.kind,
                   "trace_seed": trace.seed, "n": n,
                   "time_scale": time_scale},
        "metrics": {
            f"{pfx}.digest": exact(trace.digest()),
            f"{pfx}.requests": exact(len(rows)),
            f"{pfx}.drain_vtime": exact(round(vt, 9)),
            f"{pfx}.events": exact(events),
            f"{pfx}.requeues": exact(requeues),
            f"{pfx}.e2e_mean_usec": exact(round(e2e, 3)),
            f"{pfx}.wall_events_per_sec": info(
                round(events / wall, 1) if wall > 0 else 0.0),
        },
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="drop the 8-rank leg (unit-test config)")
    ap.add_argument("--trace",
                    help="run ONE trace-driven leg from a workloads "
                         "JSONL trace instead of the committed legs "
                         "(abstract trace time -> vtime; the document "
                         "pins the trace digest)")
    ap.add_argument("--trace-ranks", type=int, default=4)
    ap.add_argument("--time-scale", type=float, default=1.0)
    ap.add_argument("--out", help="write benchmark JSON here")
    args = ap.parse_args(argv)

    if args.trace:
        from rlo_tpu.workloads.traces import Trace
        doc = trace_doc(Trace.load_jsonl(args.trace),
                        n=args.trace_ranks,
                        time_scale=args.time_scale)
        text = json.dumps(doc, indent=1, sort_keys=True)
        if args.out:
            with open(args.out, "w") as fh:
                fh.write(text + "\n")
        else:
            print(text)
        return 0

    metrics = {}
    legs = [("steady4", dict(n=4, n_req=16, seed=0)),
            ("failover4", dict(n=4, n_req=16, seed=0, kill_at=8.0))]
    if not args.quick:
        legs.append(("steady8", dict(n=8, n_req=32, seed=0)))
    for name, kw in legs:
        vt, events, requeues, e2e, wall = run_leg(**kw)
        print(f"{name}: drain {vt:.2f} vtime, {events} events, "
              f"{requeues} requeues, e2e mean {e2e/1e6:.2f} vsec, "
              f"wall {wall:.2f}s", file=sys.stderr)
        metrics[f"{name}.drain_vtime"] = exact(round(vt, 9))
        metrics[f"{name}.events"] = exact(events)
        metrics[f"{name}.requeues"] = exact(requeues)
        metrics[f"{name}.e2e_mean_usec"] = exact(round(e2e, 3))
        metrics[f"{name}.wall_events_per_sec"] = info(
            round(events / wall, 1) if wall > 0 else 0.0)
    metrics.update(remedy_leg(seed=0))

    doc = {"suite": "fabric_bench",
           "config": {"quick": bool(args.quick)},
           "metrics": metrics}
    text = json.dumps(doc, indent=1, sort_keys=True)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text + "\n")
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
