"""Continuous batching vs naive batch-restart serving throughput.

Workload: N requests with mixed decode budgets. The naive server
groups them into batches of n_slots and runs `generate` with
max_new = the batch's LARGEST budget (finished rows burn steps until
the batch restarts). The continuous server (models.serve.DecodeServer)
refills finished slots from the queue every round.

Two readings, both printed:
  - slot-step efficiency: useful tokens / (decode steps x slots).
    Deterministic, hardware-independent — the pure scheduling claim.
    Continuous wastes only round-quantization + tail bubbles; naive
    wastes (max - budget) per row per batch.
  - wall tokens/s. Caveat on THIS environment: the tunneled chip's
    ~110 ms dispatch floor taxes the continuous server once per round
    (and once per admission prefill) but the naive server only once
    per batch, so tunnel wall-clock UNDERSTATES continuous batching;
    on a locally-attached TPU the per-dispatch cost is ~100 us and
    the efficiency ratio is what wall-clock converges to. The
    recorded vs_baseline is the efficiency ratio for that reason.

The ``--arrivals poisson`` leg (pre-work for ROADMAP item 2) replaces
the closed-loop submit-everything-up-front workload with an OPEN-loop
production mix: per-round Poisson arrivals of a bimodal
short-interactive / long-batch request distribution, measuring
sustained tokens/s and occupancy under load rather than batch-drain
latency. Arrival times are measured in decode ROUNDS (the scheduler's
own clock), so the scheduling metrics — occupancy, rounds,
slot-step efficiency, end-to-end latency in rounds — are
seed-deterministic and gate at ZERO tolerance through
``rlo_tpu.tools.perf_gate`` (committed baseline BENCH_serve.json);
wall tokens/s is recorded informationally. No eos is used, so decode
lengths are budget-fixed and the exact metrics are machine- and
model-output-independent.

Usage: python benchmarks/serve_bench.py [--tiny] [--n-req N]
       python benchmarks/serve_bench.py --tiny --arrivals poisson \
           --out BENCH_serve.json
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from rlo_tpu.models.generate import generate  # noqa: E402
from rlo_tpu.models.serve import DecodeServer  # noqa: E402
from rlo_tpu.models.transformer import (TransformerConfig,  # noqa: E402
                                        init_params)


def exact(value):
    return {"value": value, "direction": "exact", "tolerance": None}


def info(value):
    return {"value": value, "direction": "higher", "tolerance": None}


def _poisson_trace(cfg, *, n_req, rate, seed, max_len, buckets,
                   prefix_len=0):
    """The seed-deterministic open-loop trace: bimodal requests plus
    per-round Poisson arrival counts. ``prefix_len`` > 0 prepends a
    SHARED system prefix of that many tokens to ~70% of the prompts
    (the prefix-heavy variant the radix cache serves); 0 reproduces
    the original dense-leg trace byte-for-byte."""
    rng = np.random.default_rng(seed)
    prefix = (rng.integers(0, cfg.vocab, (prefix_len,))
              if prefix_len else None)
    reqs = []
    for _ in range(n_req):
        if rng.random() < 0.7:  # short interactive
            plen = int(rng.integers(3, 9))
            budget = int(rng.integers(4, 13))
        else:                   # long batch
            plen = int(rng.integers(8, min(15, buckets[-1] + 1)))
            budget = int(rng.integers(24, min(41, max_len - plen)))
        prompt = rng.integers(0, cfg.vocab, (plen,))
        if prefix is not None and rng.random() < 0.7:
            prompt = np.concatenate([prefix, prompt])
        if prefix is not None and reqs and rng.random() < 0.25:
            # an exact resubmission: the full-prefix radix hit whose
            # first decode write lands in a shared page — the COW path
            prompt = reqs[rng.integers(0, len(reqs))][0]
        reqs.append((prompt, budget))
    # arrival round of each request: cumulative Poisson per round
    arrival, rnd = [], 0
    while len(arrival) < n_req:
        k = int(rng.poisson(rate))
        arrival.extend([rnd] * min(k, n_req - len(arrival)))
        rnd += 1
    return reqs, arrival


def _drive_open_loop(srv, reqs, arrival):
    """Run the open-loop trace to drain; returns (occupancy mean %,
    e2e p50/p99 in rounds, wall seconds)."""
    submit_round = {}
    e2e_rounds = []
    submitted = 0
    round_idx = 0
    n_req = len(reqs)
    t0 = time.perf_counter()
    while submitted < n_req or srv.has_work():
        while submitted < n_req and arrival[submitted] <= round_idx:
            p, m = reqs[submitted]
            rid = srv.submit(p, m)
            submit_round[rid] = round_idx
            submitted += 1
        if not srv.has_work():
            # open-loop idle gap: fast-forward to the next arrival
            round_idx = arrival[submitted]
            continue
        srv.step_round()
        for rid, _toks in srv.poll_completed():
            e2e_rounds.append(round_idx - submit_round[rid])
        round_idx += 1
    wall = time.perf_counter() - t0
    occ = srv.metrics.histogram("serve.occupancy_pct")
    occ_mean = occ.sum / occ.count if occ.count else 0.0
    e2e_rounds.sort()
    p50 = e2e_rounds[len(e2e_rounds) // 2]
    p99 = e2e_rounds[min(len(e2e_rounds) - 1,
                         (len(e2e_rounds) * 99) // 100)]
    return occ_mean, p50, p99, wall


def poisson_leg(params, cfg, *, tiny, n_req, slots, round_len,
                max_len, buckets, rate, seed, paged=False,
                page_size=8):
    """Open-loop Poisson arrival mix: per-round arrival counts drawn
    Poisson(rate), bimodal prompt/budget distribution (70% short
    interactive, 30% long batch). Returns a perf_gate benchmark
    document; the scheduling metrics are functions of the seed alone
    (no eos => budget-fixed decode lengths), the tokens/s is wall.

    ``--paged`` adds two more legs over the SAME arrival process
    (docs/DESIGN.md §12): ``poisson_paged.*`` runs the paged server
    on the identical trace — chunked prefill, page pool, and
    budget-clipped rounds must STRICTLY improve occupancy and
    slot-step efficiency over the dense leg (asserted here, gated
    exact) — and ``poisson_prefix.*`` runs a prefix-heavy variant
    (a shared system prefix on ~70% of prompts) whose radix-reuse
    counters (prefix hits, shared tokens, COW copies) gate exact."""
    from rlo_tpu.utils.metrics import Registry

    reqs, arrival = _poisson_trace(cfg, n_req=n_req, rate=rate,
                                   seed=seed, max_len=max_len,
                                   buckets=buckets)
    useful = sum(m for _, m in reqs)

    reg = Registry()
    srv = DecodeServer(params, cfg, n_slots=slots, max_len=max_len,
                       round_len=round_len, prompt_buckets=buckets,
                       metrics=reg)
    occ_mean, p50, p99, wall = _drive_open_loop(srv, reqs, arrival)
    eff = useful / (srv.steps_run * slots)
    print(f"poisson mix: {n_req} reqs, rate {rate}/round, "
          f"{srv.rounds_run} rounds, occupancy {occ_mean:.1f}%, "
          f"e2e p50/p99 {p50}/{p99} rounds, "
          f"{useful/wall:,.0f} tok/s wall", file=sys.stderr)
    metrics = {
        # seed-deterministic scheduling numbers: gate exact
        "poisson.rounds": exact(srv.rounds_run),
        "poisson.useful_tokens": exact(useful),
        "poisson.occupancy_mean_pct": exact(round(occ_mean, 6)),
        "poisson.slot_step_efficiency": exact(round(eff, 6)),
        "poisson.e2e_rounds_p50": exact(p50),
        "poisson.e2e_rounds_p99": exact(p99),
        # wall throughput: machine-dependent, informational
        "poisson.sustained_tokens_per_sec": info(
            round(useful / wall, 1)),
    }
    doc = {
        "suite": "serve_bench",
        "config": {"tiny": tiny, "arrivals": "poisson",
                   "n_req": n_req, "slots": slots,
                   "round_len": round_len, "rate": rate,
                   "seed": seed, "paged": bool(paged)},
        "metrics": metrics,
    }
    if not paged:
        return doc

    # ---- paged leg: the SAME trace through the paged server --------
    reg_p = Registry()
    srv_p = DecodeServer(params, cfg, n_slots=slots, max_len=max_len,
                         round_len=round_len, metrics=reg_p,
                         paged=True, page_size=page_size)
    occ_p, p50_p, p99_p, wall_p = _drive_open_loop(srv_p, reqs,
                                                   arrival)
    eff_p = useful / (srv_p.steps_run * slots)
    snap_p = reg_p.snapshot()["counters"]
    print(f"paged:       {srv_p.rounds_run} rounds, occupancy "
          f"{occ_p:.1f}%, efficiency {eff_p:.3f} (dense {eff:.3f}), "
          f"e2e p50/p99 {p50_p}/{p99_p}, "
          f"{useful/wall_p:,.0f} tok/s wall", file=sys.stderr)
    # the acceptance bar: the paged scheduler must STRICTLY beat the
    # dense one on the same trace — fail the bench loudly, not just
    # the gate, if the win ever evaporates
    assert occ_p > occ_mean, (occ_p, occ_mean)
    assert eff_p > eff, (eff_p, eff)
    metrics.update({
        "poisson_paged.rounds": exact(srv_p.rounds_run),
        "poisson_paged.occupancy_mean_pct": exact(round(occ_p, 6)),
        "poisson_paged.slot_step_efficiency": exact(round(eff_p, 6)),
        "poisson_paged.e2e_rounds_p50": exact(p50_p),
        "poisson_paged.e2e_rounds_p99": exact(p99_p),
        "poisson_paged.prefill_chunks": exact(
            snap_p.get("serve.prefill_chunks", 0)),
        "poisson_paged.pages_peak": exact(
            srv_p.allocator.peak_in_use),
        "poisson_paged.sustained_tokens_per_sec": info(
            round(useful / wall_p, 1)),
    })

    # ---- prefix-heavy leg: shared system prefix, radix reuse -------
    reqs_x, arrival_x = _poisson_trace(
        cfg, n_req=n_req, rate=rate, seed=seed + 1,
        max_len=max_len, buckets=buckets, prefix_len=page_size)
    useful_x = sum(m for _, m in reqs_x)
    reg_x = Registry()
    srv_x = DecodeServer(params, cfg, n_slots=slots, max_len=max_len,
                         round_len=round_len, metrics=reg_x,
                         paged=True, page_size=page_size)
    occ_x, p50_x, p99_x, _ = _drive_open_loop(srv_x, reqs_x,
                                              arrival_x)
    snap_x = reg_x.snapshot()["counters"]
    hits = snap_x.get("serve.prefix_hits", 0)
    shared_toks = snap_x.get("serve.prefix_tokens_shared", 0)
    print(f"prefix-heavy: {hits} prefix hits, {shared_toks} prompt "
          f"tokens served from the radix cache, "
          f"{snap_x.get('serve.cow_copies', 0)} COW copies, "
          f"{snap_x.get('serve.prefill_chunks', 0)} prefill chunks",
          file=sys.stderr)
    # >= 1 measured prefill skipped via radix reuse (the acceptance
    # criterion); gate the exact counters so reuse can never silently
    # regress to zero
    assert hits >= 1 and shared_toks >= page_size, (hits, shared_toks)
    metrics.update({
        "poisson_prefix.useful_tokens": exact(useful_x),
        "poisson_prefix.rounds": exact(srv_x.rounds_run),
        "poisson_prefix.occupancy_mean_pct": exact(round(occ_x, 6)),
        "poisson_prefix.prefix_hits": exact(hits),
        "poisson_prefix.prefix_tokens_shared": exact(shared_toks),
        "poisson_prefix.cow_copies": exact(
            snap_x.get("serve.cow_copies", 0)),
        "poisson_prefix.prefill_chunks": exact(
            snap_x.get("serve.prefill_chunks", 0)),
        "poisson_prefix.e2e_rounds_p50": exact(p50_x),
        "poisson_prefix.e2e_rounds_p99": exact(p99_x),
    })
    return doc


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true")
    ap.add_argument("--n-req", type=int, default=32)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--round-len", type=int, default=32)
    ap.add_argument("--arrivals", choices=("batch", "poisson"),
                    default="batch",
                    help="batch: the closed-loop continuous-vs-naive "
                         "comparison; poisson: the open-loop "
                         "production arrival mix (perf_gate schema)")
    ap.add_argument("--rate", type=float, default=1.5,
                    help="poisson: mean arrivals per decode round")
    ap.add_argument("--paged", action="store_true",
                    help="poisson: add the paged-server leg (same "
                         "trace; occupancy/efficiency must strictly "
                         "beat dense) and the prefix-heavy radix-"
                         "reuse leg (docs/DESIGN.md §12)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", help="poisson: write the benchmark JSON "
                                  "here instead of stdout")
    args = ap.parse_args()

    if args.tiny:
        cfg = TransformerConfig(vocab=128, d_model=64, n_heads=4,
                                n_layers=2, d_ff=256, dtype="float32")
        n_req, slots, round_len = 8, 2, 4
        plen_rng, bud_rng, max_len, buckets = (4, 12), (4, 24), 64, (16,)
    else:
        cfg = TransformerConfig(vocab=32768, d_model=1024, n_heads=16,
                                n_layers=8, d_ff=4096,
                                dtype="bfloat16")
        n_req, slots, round_len = args.n_req, args.slots, args.round_len
        plen_rng, bud_rng, max_len, buckets = ((32, 64), (16, 160),
                                               256, (64,))

    params = init_params(jax.random.PRNGKey(0), cfg)

    if args.arrivals == "poisson":
        doc = poisson_leg(params, cfg, tiny=args.tiny, n_req=n_req,
                          slots=slots, round_len=round_len,
                          max_len=max_len, buckets=buckets,
                          rate=args.rate, seed=args.seed,
                          paged=args.paged)
        text = json.dumps(doc, indent=1, sort_keys=True)
        if args.out:
            with open(args.out, "w") as fh:
                fh.write(text + "\n")
        else:
            print(text)
        return

    rng = np.random.default_rng(7)
    reqs = [(rng.integers(0, cfg.vocab, (int(rng.integers(*plen_rng)),)),
             int(rng.integers(*bud_rng))) for _ in range(n_req)]
    useful = sum(m for _, m in reqs)

    # ---- continuous ------------------------------------------------
    srv = DecodeServer(params, cfg, n_slots=slots, max_len=max_len,
                       round_len=round_len, prompt_buckets=buckets)
    for p, m in reqs:
        srv.submit(p, m)
    # warm round on the SAME server (the jit wrappers are per-
    # instance), then exclude its already-emitted tokens from the
    # timed numerator so compile cost and pre-timed work both stay
    # out of the tokens/s
    srv.step_round()
    pre_emitted = sum(len(o) for o in srv._out if o is not None)
    t0 = time.perf_counter()
    outs = srv.run()
    t_cont = time.perf_counter() - t0
    cont_slot_steps = srv.steps_run * slots
    timed_tokens = useful - pre_emitted
    assert len(outs) == n_req

    # ---- naive batch-restart ---------------------------------------
    # equal-compile footing: pad prompts to the same bucket
    bucket = buckets[0]
    gen = {}
    naive_slot_steps = 0
    t_naive = 0.0
    for i in range(0, n_req, slots):
        chunk = reqs[i:i + slots]
        mx = max(m for _, m in chunk)
        prompts = np.zeros((slots, bucket), np.int32)
        lengths = np.ones((slots,), np.int32)
        for j, (p, _) in enumerate(chunk):
            prompts[j, :len(p)] = p
            lengths[j] = len(p)
        key = mx
        if key not in gen:
            # params as a jit ARGUMENT: closures ship the weights in
            # the remote-compile request and blow the tunnel's HTTP
            # body limit (413)
            f = jax.jit(lambda P, pr, ln, m=mx: generate(
                P, pr, cfg, max_new=m, max_len=bucket + m,
                prompt_lengths=ln))
            np.asarray(f(params, jnp.asarray(prompts),
                         jnp.asarray(lengths)))  # compile+warm
            gen[key] = f
        t0 = time.perf_counter()
        np.asarray(gen[key](params, jnp.asarray(prompts),
                            jnp.asarray(lengths)))
        t_naive += time.perf_counter() - t0
        naive_slot_steps += mx * slots

    eff_cont = useful / cont_slot_steps
    eff_naive = useful / naive_slot_steps
    on_tpu = jax.default_backend() == "tpu"
    print(f"continuous: {useful} useful tokens ({timed_tokens} in the "
          f"timed section), {srv.rounds_run} rounds x {round_len} "
          f"steps x {slots} slots = {cont_slot_steps} slot-steps "
          f"(efficiency {eff_cont:.1%}), wall {t_cont:.2f}s "
          f"({timed_tokens/t_cont:,.0f} tok/s)", file=sys.stderr)
    print(f"naive:      {naive_slot_steps} slot-steps "
          f"(efficiency {eff_naive:.1%}), wall {t_naive:.2f}s "
          f"({useful/t_naive:,.0f} tok/s)", file=sys.stderr)
    print(f"scheduling efficiency ratio {eff_cont/eff_naive:.2f}x, "
          f"wall speedup {t_naive/t_cont:.2f}x (tunnel wall "
          f"under-credits continuous; see module docstring)",
          file=sys.stderr)
    print(json.dumps({
        "metric": f"continuous batching, {n_req} mixed-budget requests "
                  f"over {slots} slots, round {round_len}, "
                  f"{'bf16 v5e chip' if on_tpu else jax.default_backend()}"
                  f" (naive restart: {useful/t_naive:,.0f} tok/s wall, "
                  f"{round(eff_naive, 4)} step-efficiency)",
        "value": round(timed_tokens / t_cont, 1),
        "unit": "tokens/s",
        "vs_baseline": round(eff_cont / eff_naive, 4),
        "vs_baseline_meaning": "slot-step efficiency ratio vs naive "
                               "batch-restart (useful tokens per "
                               "decode slot-step; dispatch-floor-"
                               "independent scheduling win)",
    }))


if __name__ == "__main__":
    main()
