"""Continuous batching vs naive batch-restart serving throughput.

Workload: N requests with mixed decode budgets. The naive server
groups them into batches of n_slots and runs `generate` with
max_new = the batch's LARGEST budget (finished rows burn steps until
the batch restarts). The continuous server (models.serve.DecodeServer)
refills finished slots from the queue every round.

Two readings, both printed:
  - slot-step efficiency: useful tokens / (decode steps x slots).
    Deterministic, hardware-independent — the pure scheduling claim.
    Continuous wastes only round-quantization + tail bubbles; naive
    wastes (max - budget) per row per batch.
  - wall tokens/s. Caveat on THIS environment: the tunneled chip's
    ~110 ms dispatch floor taxes the continuous server once per round
    (and once per admission prefill) but the naive server only once
    per batch, so tunnel wall-clock UNDERSTATES continuous batching;
    on a locally-attached TPU the per-dispatch cost is ~100 us and
    the efficiency ratio is what wall-clock converges to. The
    recorded vs_baseline is the efficiency ratio for that reason.

The ``--arrivals poisson`` leg (pre-work for ROADMAP item 2) replaces
the closed-loop submit-everything-up-front workload with an OPEN-loop
production mix: per-round Poisson arrivals of a bimodal
short-interactive / long-batch request distribution, measuring
sustained tokens/s and occupancy under load rather than batch-drain
latency. Arrival times are measured in decode ROUNDS (the scheduler's
own clock), so the scheduling metrics — occupancy, rounds,
slot-step efficiency, end-to-end latency in rounds — are
seed-deterministic and gate at ZERO tolerance through
``rlo_tpu.tools.perf_gate`` (committed baseline BENCH_serve.json);
wall tokens/s is recorded informationally. No eos is used, so decode
lengths are budget-fixed and the exact metrics are machine- and
model-output-independent.

The Poisson trace itself now comes from the workloads subsystem
(``rlo_tpu/workloads/traces.py poisson_compat`` — the byte-identical
relocation of the generator that used to live inline here), and the
committed legs' trace digests are pinned in ``_PINNED_COMPAT``:
generator drift fails the bench at the source, not just the gate.
``--trace FILE`` instead drives the open loop from any serialized
workloads trace (diurnal waves, MMPP tenant bursts, flash crowds,
prefix swarms — docs/DESIGN.md §14), pinning the trace digest in the
emitted document; benchmarks/workload_bench.py gates one such leg in
BENCH_workload.json.

Usage: python benchmarks/serve_bench.py [--tiny] [--n-req N]
       python benchmarks/serve_bench.py --tiny --arrivals poisson \
           --out BENCH_serve.json
       python benchmarks/serve_bench.py --tiny --trace t.jsonl --paged
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from rlo_tpu.models.generate import generate  # noqa: E402
from rlo_tpu.models.serve import DecodeServer  # noqa: E402
from rlo_tpu.models.transformer import (TransformerConfig,  # noqa: E402
                                        init_params)
from rlo_tpu.workloads.traces import (Trace, compat_digest,  # noqa: E402
                                      poisson_compat)


def exact(value):
    return {"value": value, "direction": "exact", "tolerance": None}


def info(value):
    return {"value": value, "direction": "higher", "tolerance": None}


#: Trace digests of the COMMITTED BENCH_serve.json legs (tiny config,
#: n_req=8, rate=1.5): the dense + paged legs replay the seed-0 trace,
#: the prefix-heavy leg the seed-1 prefix trace. The generator now
#: lives in rlo_tpu/workloads/traces.py (poisson_compat); these pins
#: prove the migration — and any later generator edit — keeps the
#: committed legs byte-identical instead of silently re-rolling them
#: (the perf gate would catch the metric drift; this catches it at the
#: SOURCE with a named cause).
_PINNED_COMPAT = {
    ("dense", 8, 1.5, 0, 0): "2e170cbc3e3069f4f24598ed9b4e250b"
                             "70ec6245e1346814b928f82e3b36cb6a",
    ("prefix", 8, 1.5, 1, 8): "b7018e756d78af9db7232d1b353eba48"
                              "0224d7aabb0e32ab668b777bdd325214",
}


def _poisson_trace(cfg, *, n_req, rate, seed, max_len, buckets,
                   prefix_len=0):
    """Compatibility wrapper over the relocated generator
    (rlo_tpu/workloads/traces.py poisson_compat — byte-identical draw
    sequence): returns the historical (requests, arrival) pair and
    asserts the committed-leg trace digests still pin."""
    reqs, arrival = poisson_compat(
        cfg.vocab, n_req=n_req, rate=rate, seed=seed, max_len=max_len,
        buckets=buckets, prefix_len=prefix_len)
    key = ("prefix" if prefix_len else "dense", n_req, rate, seed,
           prefix_len)
    pinned = _PINNED_COMPAT.get(key)
    if pinned is not None and cfg.vocab == 128:
        got = compat_digest(reqs, arrival)
        assert got == pinned, (
            f"poisson_compat drifted for committed leg {key}: trace "
            f"digest {got} != pinned {pinned} — the generator no "
            f"longer reproduces BENCH_serve.json's traffic")
    return reqs, arrival


def _drive_open_loop(srv, reqs, arrival):
    """Run the open-loop trace to drain; returns (occupancy mean %,
    e2e p50/p99 in rounds, wall seconds)."""
    submit_round = {}
    e2e_rounds = []
    submitted = 0
    round_idx = 0
    n_req = len(reqs)
    t0 = time.perf_counter()
    while submitted < n_req or srv.has_work():
        while submitted < n_req and arrival[submitted] <= round_idx:
            p, m = reqs[submitted]
            rid = srv.submit(p, m)
            submit_round[rid] = round_idx
            submitted += 1
        if not srv.has_work():
            # open-loop idle gap: fast-forward to the next arrival
            round_idx = arrival[submitted]
            continue
        srv.step_round()
        for rid, _toks in srv.poll_completed():
            e2e_rounds.append(round_idx - submit_round[rid])
        round_idx += 1
    wall = time.perf_counter() - t0
    occ = srv.metrics.histogram("serve.occupancy_pct")
    occ_mean = occ.sum / occ.count if occ.count else 0.0
    e2e_rounds.sort()
    p50 = e2e_rounds[len(e2e_rounds) // 2]
    p99 = e2e_rounds[min(len(e2e_rounds) - 1,
                         (len(e2e_rounds) * 99) // 100)]
    return occ_mean, p50, p99, wall


def trace_leg(params, cfg, trace, *, tiny, slots, round_len, max_len,
              buckets, paged=False, page_size=8):
    """Open-loop leg driven by a workloads trace (rlo_tpu/workloads):
    request arrival ROUNDS are the trace's abstract times floored, so
    every scheduling metric is a function of the trace alone and gates
    exact — alongside the trace digest itself, pinning the traffic
    seed-exact (docs/DESIGN.md §14). ``paged=True`` runs the paged
    server (the swarm kind's shared prefixes then exercise the radix
    cache, reported in ``prefix_hits``/``cow_copies``)."""
    from rlo_tpu.utils.metrics import Registry

    reqs, arrival = trace.serve_requests()
    if not reqs:
        raise ValueError(
            f"trace {trace.kind!r} (seed {trace.seed}) holds no "
            f"requests (a fully torn JSONL file loads as an empty "
            f"Trace)")
    useful = sum(m for _, m in reqs)
    reg = Registry()
    kw = (dict(paged=True, page_size=page_size) if paged
          else dict(prompt_buckets=buckets))
    srv = DecodeServer(params, cfg, n_slots=slots, max_len=max_len,
                       round_len=round_len, metrics=reg, **kw)
    occ, p50, p99, wall = _drive_open_loop(srv, reqs, arrival)
    eff = useful / (srv.steps_run * slots)
    pfx = f"trace_{trace.kind}"
    print(f"{pfx}: {len(reqs)} reqs, {srv.rounds_run} rounds, "
          f"occupancy {occ:.1f}%, efficiency {eff:.3f}, e2e p50/p99 "
          f"{p50}/{p99} rounds, digest {trace.digest()[:12]}",
          file=sys.stderr)
    metrics = {
        f"{pfx}.digest": exact(trace.digest()),
        f"{pfx}.requests": exact(len(reqs)),
        f"{pfx}.useful_tokens": exact(useful),
        f"{pfx}.rounds": exact(srv.rounds_run),
        f"{pfx}.occupancy_mean_pct": exact(round(occ, 6)),
        f"{pfx}.slot_step_efficiency": exact(round(eff, 6)),
        f"{pfx}.e2e_rounds_p50": exact(p50),
        f"{pfx}.e2e_rounds_p99": exact(p99),
        f"{pfx}.sustained_tokens_per_sec": info(
            round(useful / wall, 1)),
    }
    if paged:
        snap = reg.snapshot()["counters"]
        metrics.update({
            f"{pfx}.prefix_hits": exact(
                snap.get("serve.prefix_hits", 0)),
            f"{pfx}.prefix_tokens_shared": exact(
                snap.get("serve.prefix_tokens_shared", 0)),
            f"{pfx}.cow_copies": exact(
                snap.get("serve.cow_copies", 0)),
        })
    return {
        "suite": "serve_bench",
        "config": {"tiny": tiny, "arrivals": "trace",
                   "kind": trace.kind, "seed": trace.seed,
                   "slots": slots, "round_len": round_len,
                   "paged": bool(paged)},
        "metrics": metrics,
    }


def poisson_leg(params, cfg, *, tiny, n_req, slots, round_len,
                max_len, buckets, rate, seed, paged=False,
                page_size=8):
    """Open-loop Poisson arrival mix: per-round arrival counts drawn
    Poisson(rate), bimodal prompt/budget distribution (70% short
    interactive, 30% long batch). Returns a perf_gate benchmark
    document; the scheduling metrics are functions of the seed alone
    (no eos => budget-fixed decode lengths), the tokens/s is wall.

    ``--paged`` adds two more legs over the SAME arrival process
    (docs/DESIGN.md §12): ``poisson_paged.*`` runs the paged server
    on the identical trace — chunked prefill, page pool, and
    budget-clipped rounds must STRICTLY improve occupancy and
    slot-step efficiency over the dense leg (asserted here, gated
    exact) — and ``poisson_prefix.*`` runs a prefix-heavy variant
    (a shared system prefix on ~70% of prompts) whose radix-reuse
    counters (prefix hits, shared tokens, COW copies) gate exact."""
    from rlo_tpu.utils.metrics import Registry

    reqs, arrival = _poisson_trace(cfg, n_req=n_req, rate=rate,
                                   seed=seed, max_len=max_len,
                                   buckets=buckets)
    useful = sum(m for _, m in reqs)

    reg = Registry()
    srv = DecodeServer(params, cfg, n_slots=slots, max_len=max_len,
                       round_len=round_len, prompt_buckets=buckets,
                       metrics=reg)
    occ_mean, p50, p99, wall = _drive_open_loop(srv, reqs, arrival)
    eff = useful / (srv.steps_run * slots)
    print(f"poisson mix: {n_req} reqs, rate {rate}/round, "
          f"{srv.rounds_run} rounds, occupancy {occ_mean:.1f}%, "
          f"e2e p50/p99 {p50}/{p99} rounds, "
          f"{useful/wall:,.0f} tok/s wall", file=sys.stderr)
    metrics = {
        # seed-deterministic scheduling numbers: gate exact
        "poisson.rounds": exact(srv.rounds_run),
        "poisson.useful_tokens": exact(useful),
        "poisson.occupancy_mean_pct": exact(round(occ_mean, 6)),
        "poisson.slot_step_efficiency": exact(round(eff, 6)),
        "poisson.e2e_rounds_p50": exact(p50),
        "poisson.e2e_rounds_p99": exact(p99),
        # wall throughput: machine-dependent, informational
        "poisson.sustained_tokens_per_sec": info(
            round(useful / wall, 1)),
    }
    doc = {
        "suite": "serve_bench",
        "config": {"tiny": tiny, "arrivals": "poisson",
                   "n_req": n_req, "slots": slots,
                   "round_len": round_len, "rate": rate,
                   "seed": seed, "paged": bool(paged)},
        "metrics": metrics,
    }
    if not paged:
        return doc

    # ---- paged leg: the SAME trace through the paged server --------
    reg_p = Registry()
    srv_p = DecodeServer(params, cfg, n_slots=slots, max_len=max_len,
                         round_len=round_len, metrics=reg_p,
                         paged=True, page_size=page_size)
    occ_p, p50_p, p99_p, wall_p = _drive_open_loop(srv_p, reqs,
                                                   arrival)
    eff_p = useful / (srv_p.steps_run * slots)
    snap_p = reg_p.snapshot()["counters"]
    print(f"paged:       {srv_p.rounds_run} rounds, occupancy "
          f"{occ_p:.1f}%, efficiency {eff_p:.3f} (dense {eff:.3f}), "
          f"e2e p50/p99 {p50_p}/{p99_p}, "
          f"{useful/wall_p:,.0f} tok/s wall", file=sys.stderr)
    # the acceptance bar: the paged scheduler must STRICTLY beat the
    # dense one on the same trace — fail the bench loudly, not just
    # the gate, if the win ever evaporates
    assert occ_p > occ_mean, (occ_p, occ_mean)
    assert eff_p > eff, (eff_p, eff)
    metrics.update({
        "poisson_paged.rounds": exact(srv_p.rounds_run),
        "poisson_paged.occupancy_mean_pct": exact(round(occ_p, 6)),
        "poisson_paged.slot_step_efficiency": exact(round(eff_p, 6)),
        "poisson_paged.e2e_rounds_p50": exact(p50_p),
        "poisson_paged.e2e_rounds_p99": exact(p99_p),
        "poisson_paged.prefill_chunks": exact(
            snap_p.get("serve.prefill_chunks", 0)),
        "poisson_paged.pages_peak": exact(
            srv_p.allocator.peak_in_use),
        "poisson_paged.sustained_tokens_per_sec": info(
            round(useful / wall_p, 1)),
    })

    # ---- prefix-heavy leg: shared system prefix, radix reuse -------
    reqs_x, arrival_x = _poisson_trace(
        cfg, n_req=n_req, rate=rate, seed=seed + 1,
        max_len=max_len, buckets=buckets, prefix_len=page_size)
    useful_x = sum(m for _, m in reqs_x)
    reg_x = Registry()
    srv_x = DecodeServer(params, cfg, n_slots=slots, max_len=max_len,
                         round_len=round_len, metrics=reg_x,
                         paged=True, page_size=page_size)
    occ_x, p50_x, p99_x, _ = _drive_open_loop(srv_x, reqs_x,
                                              arrival_x)
    snap_x = reg_x.snapshot()["counters"]
    hits = snap_x.get("serve.prefix_hits", 0)
    shared_toks = snap_x.get("serve.prefix_tokens_shared", 0)
    print(f"prefix-heavy: {hits} prefix hits, {shared_toks} prompt "
          f"tokens served from the radix cache, "
          f"{snap_x.get('serve.cow_copies', 0)} COW copies, "
          f"{snap_x.get('serve.prefill_chunks', 0)} prefill chunks",
          file=sys.stderr)
    # >= 1 measured prefill skipped via radix reuse (the acceptance
    # criterion); gate the exact counters so reuse can never silently
    # regress to zero
    assert hits >= 1 and shared_toks >= page_size, (hits, shared_toks)
    metrics.update({
        "poisson_prefix.useful_tokens": exact(useful_x),
        "poisson_prefix.rounds": exact(srv_x.rounds_run),
        "poisson_prefix.occupancy_mean_pct": exact(round(occ_x, 6)),
        "poisson_prefix.prefix_hits": exact(hits),
        "poisson_prefix.prefix_tokens_shared": exact(shared_toks),
        "poisson_prefix.cow_copies": exact(
            snap_x.get("serve.cow_copies", 0)),
        "poisson_prefix.prefill_chunks": exact(
            snap_x.get("serve.prefill_chunks", 0)),
        "poisson_prefix.e2e_rounds_p50": exact(p50_x),
        "poisson_prefix.e2e_rounds_p99": exact(p99_x),
    })
    return doc


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true")
    ap.add_argument("--n-req", type=int, default=32)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--round-len", type=int, default=32)
    ap.add_argument("--arrivals", choices=("batch", "poisson"),
                    default="batch",
                    help="batch: the closed-loop continuous-vs-naive "
                         "comparison; poisson: the open-loop "
                         "production arrival mix (perf_gate schema)")
    ap.add_argument("--rate", type=float, default=1.5,
                    help="poisson: mean arrivals per decode round")
    ap.add_argument("--paged", action="store_true",
                    help="poisson: add the paged-server leg (same "
                         "trace; occupancy/efficiency must strictly "
                         "beat dense) and the prefix-heavy radix-"
                         "reuse leg (docs/DESIGN.md §12)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--trace",
                    help="drive the open-loop leg from a workloads "
                         "JSONL trace (rlo_tpu/workloads/traces.py) "
                         "instead of the synthetic arrival mixes; "
                         "abstract trace time = decode rounds. The "
                         "emitted document pins the trace digest.")
    ap.add_argument("--out", help="poisson/trace: write the benchmark "
                                  "JSON here instead of stdout")
    args = ap.parse_args()

    if args.tiny:
        cfg = TransformerConfig(vocab=128, d_model=64, n_heads=4,
                                n_layers=2, d_ff=256, dtype="float32")
        n_req, slots, round_len = 8, 2, 4
        plen_rng, bud_rng, max_len, buckets = (4, 12), (4, 24), 64, (16,)
    else:
        cfg = TransformerConfig(vocab=32768, d_model=1024, n_heads=16,
                                n_layers=8, d_ff=4096,
                                dtype="bfloat16")
        n_req, slots, round_len = args.n_req, args.slots, args.round_len
        plen_rng, bud_rng, max_len, buckets = ((32, 64), (16, 160),
                                               256, (64,))

    params = init_params(jax.random.PRNGKey(0), cfg)

    if args.trace:
        trace = Trace.load_jsonl(args.trace)
        doc = trace_leg(params, cfg, trace, tiny=args.tiny,
                        slots=slots, round_len=round_len,
                        max_len=max_len, buckets=buckets,
                        paged=args.paged)
        text = json.dumps(doc, indent=1, sort_keys=True)
        if args.out:
            with open(args.out, "w") as fh:
                fh.write(text + "\n")
        else:
            print(text)
        return

    if args.arrivals == "poisson":
        doc = poisson_leg(params, cfg, tiny=args.tiny, n_req=n_req,
                          slots=slots, round_len=round_len,
                          max_len=max_len, buckets=buckets,
                          rate=args.rate, seed=args.seed,
                          paged=args.paged)
        text = json.dumps(doc, indent=1, sort_keys=True)
        if args.out:
            with open(args.out, "w") as fh:
                fh.write(text + "\n")
        else:
            print(text)
        return

    rng = np.random.default_rng(7)
    reqs = [(rng.integers(0, cfg.vocab, (int(rng.integers(*plen_rng)),)),
             int(rng.integers(*bud_rng))) for _ in range(n_req)]
    useful = sum(m for _, m in reqs)

    # ---- continuous ------------------------------------------------
    srv = DecodeServer(params, cfg, n_slots=slots, max_len=max_len,
                       round_len=round_len, prompt_buckets=buckets)
    for p, m in reqs:
        srv.submit(p, m)
    # warm round on the SAME server (the jit wrappers are per-
    # instance), then exclude its already-emitted tokens from the
    # timed numerator so compile cost and pre-timed work both stay
    # out of the tokens/s
    srv.step_round()
    pre_emitted = sum(len(o) for o in srv._out if o is not None)
    t0 = time.perf_counter()
    outs = srv.run()
    t_cont = time.perf_counter() - t0
    cont_slot_steps = srv.steps_run * slots
    timed_tokens = useful - pre_emitted
    assert len(outs) == n_req

    # ---- naive batch-restart ---------------------------------------
    # equal-compile footing: pad prompts to the same bucket
    bucket = buckets[0]
    gen = {}
    naive_slot_steps = 0
    t_naive = 0.0
    for i in range(0, n_req, slots):
        chunk = reqs[i:i + slots]
        mx = max(m for _, m in chunk)
        prompts = np.zeros((slots, bucket), np.int32)
        lengths = np.ones((slots,), np.int32)
        for j, (p, _) in enumerate(chunk):
            prompts[j, :len(p)] = p
            lengths[j] = len(p)
        key = mx
        if key not in gen:
            # params as a jit ARGUMENT: closures ship the weights in
            # the remote-compile request and blow the tunnel's HTTP
            # body limit (413)
            f = jax.jit(lambda P, pr, ln, m=mx: generate(
                P, pr, cfg, max_new=m, max_len=bucket + m,
                prompt_lengths=ln))
            np.asarray(f(params, jnp.asarray(prompts),
                         jnp.asarray(lengths)))  # compile+warm
            gen[key] = f
        t0 = time.perf_counter()
        np.asarray(gen[key](params, jnp.asarray(prompts),
                            jnp.asarray(lengths)))
        t_naive += time.perf_counter() - t0
        naive_slot_steps += mx * slots

    eff_cont = useful / cont_slot_steps
    eff_naive = useful / naive_slot_steps
    on_tpu = jax.default_backend() == "tpu"
    print(f"continuous: {useful} useful tokens ({timed_tokens} in the "
          f"timed section), {srv.rounds_run} rounds x {round_len} "
          f"steps x {slots} slots = {cont_slot_steps} slot-steps "
          f"(efficiency {eff_cont:.1%}), wall {t_cont:.2f}s "
          f"({timed_tokens/t_cont:,.0f} tok/s)", file=sys.stderr)
    print(f"naive:      {naive_slot_steps} slot-steps "
          f"(efficiency {eff_naive:.1%}), wall {t_naive:.2f}s "
          f"({useful/t_naive:,.0f} tok/s)", file=sys.stderr)
    print(f"scheduling efficiency ratio {eff_cont/eff_naive:.2f}x, "
          f"wall speedup {t_naive/t_cont:.2f}x (tunnel wall "
          f"under-credits continuous; see module docstring)",
          file=sys.stderr)
    print(json.dumps({
        "metric": f"continuous batching, {n_req} mixed-budget requests "
                  f"over {slots} slots, round {round_len}, "
                  f"{'bf16 v5e chip' if on_tpu else jax.default_backend()}"
                  f" (naive restart: {useful/t_naive:,.0f} tok/s wall, "
                  f"{round(eff_naive, 4)} step-efficiency)",
        "value": round(timed_tokens / t_cont, 1),
        "unit": "tokens/s",
        "vs_baseline": round(eff_cont / eff_naive, 4),
        "vs_baseline_meaning": "slot-step efficiency ratio vs naive "
                               "batch-restart (useful tokens per "
                               "decode slot-step; dispatch-floor-"
                               "independent scheduling win)",
    }))


if __name__ == "__main__":
    main()
