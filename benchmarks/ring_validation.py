"""Manual-ring allreduce validation on the virtual CPU mesh.

Validates what CAN be validated without multi-chip hardware (VERDICT
round-1 item 1): that the bidirectional sub-chunk-pipelined ring
(`allreduce(algorithm='bidir_ring')`) compiles, executes, and matches
`lax.psum` numerically at 8 virtual devices, and reports the wall-time
ratios honestly.

On the CPU-mesh WALL-TIME proxy: XLA's CPU AllReduce is a single
shared-memory reduction across the in-process "devices" (two passes
over the data, no real links), while ANY decomposed schedule pays
2*(ws-1) cross-device copy rounds plus a rendezvous per ppermute.
Measured on this image (8 virtual devices, 4 MB/shard fp32):

    psum           ~12 ms      (one in-process reduction)
    all_to_all+AG  ~2x psum    (TWO fused XLA collectives!)
    halving-dbl    ~3.2x psum  (6 rounds)
    bidir ring     ~4-5x psum  (14 rounds, 2 permutes each)

Even a two-op XLA schedule cannot reach ~1.1x of psum here, so the
CPU-mesh ratio says nothing about ICI behavior — on TPU hardware the
ring's per-step cost is link bandwidth (which psum's own ring also
pays), not rendezvous overhead. What makes the bidir ring win by
construction on ICI is in its docstring
(rlo_tpu/ops/tpu_collectives.py): both link directions carry half the
payload, the schedule is fully unrolled with static chunk indices, and
each step's sub-chunk sends are independent of that step's combines so
XLA's latency-hiding scheduler can keep a CollectivePermute in flight
during every combine. The numbers that exist on real hardware are the
single-chip building blocks: the fused combine at HBM peak (bench.py)
and the flash block update at 4.3x the unfused path
(benchmarks/flash_bench.py).

Usage: XLA_FLAGS=--xla_force_host_platform_device_count=8
       JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
       python benchmarks/ring_validation.py [--mb 4]
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mb", type=int, default=4, help="MB per shard")
    ap.add_argument("--devices", type=int, default=8)
    args = ap.parse_args()

    from __graft_entry__ import _ensure_devices
    _ensure_devices(args.devices)

    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from rlo_tpu.ops import tpu_collectives as tc
    from rlo_tpu.parallel.mesh import make_mesh, shard_jit

    n = len(jax.devices())
    mesh = make_mesh((n,), ("x",))
    per = (args.mb << 20) // 4
    rng = np.random.default_rng(0)
    x = jax.device_put(
        rng.standard_normal((n, per)).astype(np.float32),
        NamedSharding(mesh, P("x")))

    def timed(fn, reps=5):
        out = fn(x)
        out.block_until_ready()
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(x)
        out.block_until_ready()
        return (time.perf_counter() - t0) / reps, out

    from rlo_tpu.topology import is_power_of_2
    algos = ["psum", "ring", "bidir_ring"]
    if is_power_of_2(n):  # halving-doubling is pow2-only
        algos.append("halving_doubling")
    results = {}
    outs = {}
    for algo in algos:
        f = shard_jit(
            lambda v, a=algo: tc.allreduce(v, "x", algorithm=a,
                                           use_pallas=False),
            mesh, P("x"), P("x"))
        results[algo], outs[algo] = timed(f)

    want = np.asarray(outs["psum"])
    ok = True
    for algo in algos[1:]:
        try:
            np.testing.assert_allclose(np.asarray(outs[algo]), want,
                                       rtol=1e-4, atol=1e-5)
        except AssertionError as e:
            ok = False
            print(f"{algo}: NUMERICS MISMATCH\n{e}", file=sys.stderr)
    base = results["psum"]
    for algo in algos:
        print(f"{algo:>18}: {results[algo]*1e3:8.2f} ms "
              f"({results[algo]/base:5.2f}x psum)")
    print(f"numerics: {'OK' if ok else 'FAILED'} "
          f"({n} devices, {args.mb} MB/shard)")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
