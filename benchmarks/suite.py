"""BASELINE.json config benchmark suite — one JSON line per config.

The reference publishes no benchmark numbers (its README is untouched
boilerplate; SURVEY.md §6) — only run-time-printed harnesses. The rebuild's
targets come from BASELINE.json's five configs; this suite makes each one a
runnable, self-describing benchmark:

  1  float32 allreduce, 1 MB buffer, 8 ranks on the engine substrate
     (the reference's `mpirun on CPU` analogue: C core vs pure Python)
  2  rootless bcast over an 8-device mesh (static ppermute spanning tree
     vs the all_gather 'gather' strategy)
  3  bf16 recursive-doubling allreduce with the Pallas fused add, vs psum
  4  reduce-scatter + all-gather (recursive halving/doubling) for large
     gradient tensors, vs one XLA psum
  5  rootless leaderless consensus (IAR) throughput on the engine
     substrate, vs the 1k ops/s north-star target

Adaptive to hardware like bench.py (the headline benchmark at the repo
root): configs 2-4 build a device mesh — a real one when multiple chips
are visible, else the forced 8-device virtual CPU mesh. Sizes shrink on
CPU (the numbers then demonstrate the harness and relative behavior, not
TPU bandwidth). ``--tiny`` shrinks further for smoke tests.

Usage:  python benchmarks/suite.py --config {1..5|all} [--tiny]
Each config prints exactly one JSON line on stdout:
  {"config": N, "metric": ..., "value": V, "unit": ..., "vs_baseline": B}
Diagnostics go to stderr. `--config all` runs each config in a fresh
subprocess (jax backend setup is per-process) and relays the lines.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def _emit(config: int, metric: str, value: float, unit: str,
          vs_baseline: float, **extra) -> None:
    line = {"config": config, "metric": metric, "value": round(value, 3),
            "unit": unit, "vs_baseline": round(vs_baseline, 4), **extra}
    print(json.dumps(line))


def _fmt_bytes(nbytes: int) -> str:
    if nbytes >= 1 << 20:
        return f"{nbytes >> 20} MB"
    return f"{nbytes >> 10} KB"


def _wall_median(fn, reps: int = 5) -> float:
    fn()  # warmup
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]


# ---------------------------------------------------------------------------
# Config 1 — engine-substrate allreduce (CPU, 8 ranks): C core vs Python
# ---------------------------------------------------------------------------

def bench_config1(tiny: bool) -> None:
    """C engines vs Python engines running the IDENTICAL algorithm:
    allreduce as bcast-gather over the rootless broadcast overlay (the
    reference's any-rank-initiates notion generalized to tensors, the
    NativeBackend data-collective path). The C side runs wholly inside
    the library (rlo_bench_allreduce) so the measurement is the engine
    substrate, not the ctypes boundary."""
    import numpy as np
    from rlo_tpu.engine import EngineManager, ProgressEngine, drain
    from rlo_tpu.native.bindings import bench_allreduce
    from rlo_tpu.ops.collectives import _pack_array, _unpack_array
    from rlo_tpu.transport.loopback import LoopbackWorld

    ws = 8
    n = ((64 << 10) if tiny else (1 << 20)) // 4  # BASELINE: 1 MB fp32
    rng = np.random.default_rng(0)
    xs = [rng.standard_normal(n).astype(np.float32) for _ in range(ws)]
    want = np.sum(xs, axis=0)
    reps = 3 if tiny else 7

    t_c = bench_allreduce(ws, n, reps) / 1e6

    world = LoopbackWorld(ws)
    mgr = EngineManager()
    engines = [ProgressEngine(world.transport(r), manager=mgr,
                              msg_size_max=n * 4 + 64) for r in range(ws)]

    def op_python():  # same bcast-gather, pure-Python engines
        for r, e in enumerate(engines):
            e.bcast(_pack_array(xs[r]))
        drain([world], engines)
        for r, e in enumerate(engines):
            acc = xs[r].copy()
            for _ in range(ws - 1):
                acc += _unpack_array(e.pickup_next().data)
            # single-element oracle, mirroring the C harness's check so
            # the timed work is identical on both sides
            if r == 0 and abs(float(acc[0]) - float(want[0])) > 1e-3:
                raise AssertionError(f"bad reduction: {acc[0]} vs {want[0]}")
    t_py = _wall_median(op_python, reps=reps)
    # observability rides along (docs/DESIGN.md §7): re-run one rep
    # with the metrics registry on (enabled AFTER timing so the
    # accounting never pollutes the measured number) and emit the
    # engine snapshot alongside the timing JSON
    for e in engines:
        e.enable_metrics()
    op_python()
    metrics_snap = engines[0].metrics()
    for e in engines:
        e.cleanup()

    print(f"config1 C: {t_c*1e6:.0f} usec  python: {t_py*1e6:.0f} usec",
          file=sys.stderr)
    _emit(1, f"engine-substrate allreduce (bcast-gather over the rootless "
             f"overlay), {_fmt_bytes(n*4)} fp32, {ws} ranks, C core "
             f"(baseline = pure-Python engines, same algorithm)",
          t_c * 1e6, "usec", t_py / t_c,
          metrics_substrate="python-engines",
          metrics_scope="links/histograms: one un-timed rep; "
                        "counters: engine lifetime (all reps)",
          metrics=metrics_snap)

    # ring vs bcast-gather, both substrates (rlo_coll.c vs the Python
    # coroutine Comm): the bandwidth-optimal 2*(ws-1) chunk rounds
    # against the O(ws^2) overlay gather
    from rlo_tpu.native.bindings import bench_allreduce_ring
    from rlo_tpu.ops.collectives import Comm, run_collectives
    from rlo_tpu.transport.loopback import LoopbackWorld as LW

    t_c_ring = bench_allreduce_ring(ws, n, reps) / 1e6

    ring_world = LW(ws)
    comms = [Comm(ring_world.transport(r)) for r in range(ws)]

    def op_python_ring():
        outs = run_collectives(
            [c.allreduce(xs[r], algorithm="ring")
             for r, c in enumerate(comms)])
        if abs(float(outs[0][0]) - float(want[0])) > 1e-3:
            raise AssertionError("bad ring reduction")
    t_py_ring = _wall_median(op_python_ring, reps=reps)
    print(f"config1 ring C: {t_c_ring*1e6:.0f} usec  ring python: "
          f"{t_py_ring*1e6:.0f} usec  (C ring is "
          f"{t_c/t_c_ring:.2f}x faster than C bcast-gather)",
          file=sys.stderr)
    _emit(1, f"engine-substrate RING allreduce (rlo_coll.c), "
             f"{_fmt_bytes(n*4)} fp32, {ws} ranks, C core "
             f"(baseline = C bcast-gather, same substrate)",
          t_c_ring * 1e6, "usec", t_c / t_c_ring)

    import re
    import subprocess
    from pathlib import Path
    native = Path(__file__).resolve().parent.parent / "rlo_tpu" / "native"

    # ring vs bcast-gather across REAL OS processes (shm transport, one
    # process per rank — the config's "via mpirun" run shape)
    try:
        subprocess.run(["make", "-s", "demo"], cwd=native, check=True,
                       capture_output=True, timeout=120)
        proc = subprocess.run(
            [str(native / "rlo_demo"), "-n", str(ws), "-c", "bench",
             "-m", "3" if tiny else "5", "-b", str(n * 4)],
            capture_output=True, text=True, timeout=280, check=True)
        mg = re.search(r"bcast-gather.*median (\d+) usec", proc.stdout)
        mr = re.search(r"ring allreduce.*median (\d+) usec", proc.stdout)
        if mg and mr:
            t_bg, t_ring = float(mg.group(1)), float(mr.group(1))
            print(f"config1 shm processes: ring {t_ring:.0f} usec  "
                  f"bcast-gather {t_bg:.0f} usec", file=sys.stderr)
            _emit(1, f"engine-substrate RING allreduce across {ws} real "
                     f"OS processes (shm transport, {_fmt_bytes(n*4)} "
                     f"fp32; baseline = bcast-gather, same processes)",
                  t_ring, "usec", t_bg / t_ring)
    except (subprocess.SubprocessError, OSError) as ex:
        print(f"config1 shm-process leg skipped: {ex}", file=sys.stderr)

    # overlay bcast vs the native library broadcast over REAL MPI
    # processes — the reference's native_benchmark_single_point_bcast
    # (rootless_ops.c:1675-1709), run via femtompirun + the nbcast demo
    # case. The overlay loses (store-and-forward through a polled
    # engine vs a direct library collective); reported honestly.
    try:
        subprocess.run(["make", "-s", "mpidemo"], cwd=native, check=True,
                       capture_output=True, timeout=120)
        reps_b = 8 if tiny else 32
        bytes_b = 4096 if tiny else 65536  # VERDICT item 6: 64 KB leg
        proc = subprocess.run(
            [str(native / "femtompirun"), "-n", str(ws), "-t", "240",
             str(native / "rlo_demo_mpi"), "-c", "nbcast",
             "-m", str(reps_b), "-b", str(bytes_b)],
            capture_output=True, text=True, timeout=280, check=True)
        m = re.search(r"overlay ([\d.]+) usec/bcast, MPI_Bcast "
                      r"([\d.]+) usec/bcast", proc.stdout)
        if m:
            t_ov, t_nat = float(m.group(1)), float(m.group(2))
            print(f"config1 nbcast overlay: {t_ov:.1f} usec  "
                  f"MPI_Bcast: {t_nat:.1f} usec", file=sys.stderr)
            _emit(1, f"rootless overlay bcast vs native MPI_Bcast "
                     f"({bytes_b >> 10} KB, {ws} real MPI processes "
                     f"via femtompi; reference rootless_ops.c:1675)",
                  t_ov, "usec/bcast", t_nat / t_ov)
    except (subprocess.SubprocessError, OSError) as ex:
        print(f"config1 nbcast leg skipped: {ex}", file=sys.stderr)


# ---------------------------------------------------------------------------
# Configs 2-4 — mesh collectives (shared scaffolding)
# ---------------------------------------------------------------------------

def _mesh_setup(n_devices: int = 8):
    from __graft_entry__ import _ensure_devices
    _ensure_devices(n_devices)
    import jax

    from rlo_tpu.parallel.mesh import make_mesh
    n = len(jax.devices())
    return jax.default_backend(), n, make_mesh((n,), ("x",))


def _sharded_rows(mesh, n: int, per: int, dtype):
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    def make(idx):
        rows = idx[0]
        seed = rows.start if isinstance(rows, slice) else int(rows)
        rng = np.random.default_rng(seed)
        return rng.standard_normal((1, per)).astype(dtype)

    return jax.make_array_from_callback(
        (n, per), NamedSharding(mesh, P("x")), make)


def _chain(fn_of_v_k, x):
    """bench.py's chained-iteration timing (handles the tunneled device's
    dispatch latency and escalates k above the noise floor)."""
    import bench

    def loop(v, k):
        return fn_of_v_k(v, int(k))
    return bench._chain_time(loop, x, k=8)


def bench_config2(tiny: bool) -> None:
    backend, n, mesh = _mesh_setup()
    import numpy as np
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from rlo_tpu.ops import tpu_collectives as tc
    from rlo_tpu.parallel.mesh import shard_jit

    on_tpu = backend == "tpu"
    per = ((64 << 10) if tiny else (4 << 20) if not on_tpu
           else (64 << 20)) // 4
    x = _sharded_rows(mesh, n, per, np.float32)
    origin = 3 % n

    def chained(schedule):
        def inner(v, k):
            def it(i, acc):
                return tc.rootless_bcast(acc, origin=origin, axis="x",
                                         schedule=schedule)
            return lax.fori_loop(0, k, it, v)
        f = shard_jit(inner, mesh, (P("x"), P()), P("x"))
        return lambda v, k: f(v, k)

    t_tree = _chain(chained("binomial"), x)
    t_gather = _chain(chained("gather"), x)
    print(f"config2 binomial: {t_tree*1e6:.0f} usec  "
          f"gather: {t_gather*1e6:.0f} usec", file=sys.stderr)
    _emit(2, f"rootless bcast ({_fmt_bytes(per*4)} fp32, origin {origin}) "
             f"over {n}-device {backend} mesh, static binomial ppermute "
             f"tree (baseline = all_gather strategy)",
          t_tree * 1e6, "usec", t_gather / t_tree)


def bench_config3(tiny: bool) -> None:
    backend, n, mesh = _mesh_setup()
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from rlo_tpu.ops import tpu_collectives as tc
    from rlo_tpu.parallel.mesh import shard_jit, vary_like

    on_tpu = backend == "tpu"
    per = ((64 << 10) if tiny else (1 << 20) if not on_tpu
           else (64 << 20)) // 2
    x = _sharded_rows(mesh, n, per, jnp.bfloat16)

    def chained(algorithm):
        def inner(v, k):
            def it(i, acc):
                out = tc.allreduce(acc, "x", algorithm=algorithm,
                                   use_pallas=on_tpu)
                # psum results are typed invariant; cast back to the
                # carry's varying type so the fori_loop carry is stable
                return vary_like((out / jnp.bfloat16(n)).astype(v.dtype),
                                 v)
            return lax.fori_loop(0, k, it, v)
        f = shard_jit(inner, mesh, (P("x"), P()), P("x"))
        return lambda v, k: f(v, k)

    t_rd = _chain(chained("recursive_doubling"), x)
    t_psum = _chain(chained("psum"), x)
    print(f"config3 rd+pallas: {t_rd*1e6:.0f} usec  psum: "
          f"{t_psum*1e6:.0f} usec", file=sys.stderr)
    _emit(3, f"bf16 recursive-doubling allreduce ({_fmt_bytes(per*2)}"
             f"/shard, Pallas fused add on TPU) over {n}-device "
             f"{backend} mesh (baseline = lax.psum)",
          t_rd * 1e6, "usec", t_psum / t_rd)


def bench_config4(tiny: bool) -> None:
    backend, n, mesh = _mesh_setup()
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from rlo_tpu.ops import tpu_collectives as tc
    from rlo_tpu.parallel.mesh import shard_jit, vary_like

    on_tpu = backend == "tpu"
    # BASELINE asks for 256 MB gradient tensors on TPU; scale down on CPU
    per = ((64 << 10) if tiny else (16 << 20) if not on_tpu
           else (256 << 20)) // 4
    x = _sharded_rows(mesh, n, per, np.float32)

    def inner_ours(v, k):
        def it(i, acc):
            flat = acc[0]
            rs = tc.reduce_scatter(flat, "x", algorithm="halving",
                                   use_pallas=on_tpu)
            ag = tc.all_gather(rs, "x", algorithm="doubling")
            out = ag.reshape(-1)[:flat.size] / jnp.float32(n)
            return vary_like(out[None], v)
        return lax.fori_loop(0, k, it, v)

    def inner_base(v, k):
        def it(i, acc):
            return vary_like(lax.psum(acc, "x") / jnp.float32(n), v)
        return lax.fori_loop(0, k, it, v)

    f_ours = shard_jit(inner_ours, mesh, (P("x"), P()), P("x"))
    f_base = shard_jit(inner_base, mesh, (P("x"), P()), P("x"))
    t_ours = _chain(lambda v, k: f_ours(v, k), x)
    t_base = _chain(lambda v, k: f_base(v, k), x)
    print(f"config4 halving/doubling RS+AG: {t_ours*1e6:.0f} usec  "
          f"psum: {t_base*1e6:.0f} usec", file=sys.stderr)
    _emit(4, f"reduce-scatter + all-gather (recursive halving/doubling, "
             f"{_fmt_bytes(per*4)}/shard fp32) over {n}-device {backend} "
             f"mesh (baseline = one lax.psum)",
          t_ours * 1e6, "usec", t_base / t_ours)


# ---------------------------------------------------------------------------
# Config 5 — leaderless consensus (IAR) throughput on the engine substrate
# ---------------------------------------------------------------------------

def bench_config5(tiny: bool) -> None:
    from rlo_tpu.native.bindings import NativeEngine, NativeWorld

    ws = 8
    rounds = 20 if tiny else 200
    with NativeWorld(ws) as world:
        engines = [NativeEngine(world, r) for r in range(ws)]
        engines[0].submit_proposal(b"warm", pid=0)  # warmup round
        world.drain()
        engines[0].proposal_reset()
        t0 = time.perf_counter()
        for i in range(rounds):
            proposer = engines[i % ws]
            rc = proposer.submit_proposal(b"go", pid=i % ws)
            while rc == -1:
                world.progress_all()
                rc = proposer.vote_my_proposal()
            if rc != 1:  # a declined round must not count as an op
                raise AssertionError(f"round {i}: decision {rc}, want 1")
            world.drain()
            proposer.proposal_reset()
        dt = time.perf_counter() - t0
        # observability rides along: one extra (un-timed) round with
        # the C-side metrics registry on; the native rlo_engine_stats
        # snapshot travels with the timing line
        for e in engines:
            e.enable_metrics()
        rc = engines[0].submit_proposal(b"obs", pid=0)
        if rc == -1:
            world.drain()
        engines[0].proposal_reset()
        metrics_snap = engines[0].metrics()
    rate = rounds / dt
    print(f"config5: {rounds} IAR rounds in {dt*1e3:.1f} ms "
          f"({rate:.0f} ops/s)", file=sys.stderr)
    _emit(5, f"rootless leaderless consensus (IAR) throughput, {ws} ranks, "
             f"rotating proposer, C engine substrate (baseline = 1k ops/s "
             f"north-star target)",
          rate, "ops/s", rate / 1000.0,
          metrics_substrate="native-c-engine",
          metrics_scope="links/histograms: one un-timed round; "
                        "counters: engine lifetime (all rounds)",
          metrics=metrics_snap)

    # TPU-side decision step: the device pmin vote-merge round-trip on
    # real hardware, measured two ways (the 1k ops/s target needs a
    # device-path number, not just the CPU engine substrate):
    #   - chained: K pmin rounds inside one jit (bench.py methodology)
    #     = the device cost of the vote reduction itself;
    #   - dispatch: one jit call + blocking readback per round = the
    #     end-to-end floor when every round must return to the host for
    #     the judge/action callbacks (dominated by host<->device
    #     latency, ~110 ms on the tunneled chip — reported honestly).
    import jax
    try:
        on_tpu = jax.default_backend() == "tpu"
    except RuntimeError:
        on_tpu = False  # half-disabled platform plugin (test env)
    if not on_tpu:
        return
    import numpy as np_
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import bench
    from rlo_tpu.ops import tpu_collectives as tc
    from rlo_tpu.parallel.mesh import make_mesh, shard_jit

    mesh = make_mesh((len(jax.devices()),), ("x",))
    f = shard_jit(
        lambda v, k: jax.lax.fori_loop(
            0, int(k) if not hasattr(k, "dtype") else k,
            lambda i, a: tc.consensus(jnp.minimum(a, 1), "x"), v),
        mesh, (P(), P()), P())
    v0 = jnp.ones((), jnp.int32)

    bound_only = False
    try:
        t_chained = bench._chain_time(lambda v, k: f(v, jnp.int32(k)),
                                      v0, k=1 << 20)
    except RuntimeError:
        # even 2^20 chained rounds sit below the dispatch noise floor:
        # the per-round cost is BOUNDED by noise/k but was not
        # measured. A bound must never travel through the same field as
        # a measurement (round-2 VERDICT item 8a) — emit it labeled.
        t_chained = 0.005 / (1 << 20)
        bound_only = True
    one = jax.jit(lambda v: f(v, jnp.int32(1)))
    one(v0).block_until_ready()
    t0 = time.perf_counter()
    reps_rt = 5
    for _ in range(reps_rt):
        np_.asarray(one(v0))
    t_rt = (time.perf_counter() - t0) / reps_rt
    kind = "BOUND (not measured)" if bound_only else "measured"
    print(f"config5 TPU pmin [{kind}]: chained {t_chained*1e6:.3f} "
          f"usec/round ({1/t_chained:.0f} ops/s), host round-trip "
          f"{t_rt*1e3:.1f} ms ({1/t_rt:.1f} ops/s)", file=sys.stderr)
    if bound_only:
        # labeled lower bound on the rate; vs_baseline is zeroed so no
        # consumer keying on it can mistake the bound for a measured
        # comparison (the bound itself rides "value" + bound=True)
        _emit(5, f"device consensus vote-merge (pmin) on "
                 f"{len(jax.devices())}-chip TPU: LOWER BOUND only "
                 f"(chain below dispatch noise floor); host-round-trip "
                 f"floor {t_rt*1e3:.1f} ms/round",
              1 / t_chained, "ops/s", 0.0, bound=True)
    else:
        _emit(5, f"device consensus vote-merge (pmin) on "
                 f"{len(jax.devices())}-chip TPU, chained in-jit rounds; "
                 f"host-round-trip floor {t_rt*1e3:.1f} ms/round "
                 f"(baseline = 1k ops/s north-star target)",
              1 / t_chained, "ops/s", (1 / t_chained) / 1000.0)


# ---------------------------------------------------------------------------

CONFIGS = {1: bench_config1, 2: bench_config2, 3: bench_config3,
           4: bench_config4, 5: bench_config5}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", default="all",
                    help="1..5 or 'all' (default)")
    ap.add_argument("--tiny", action="store_true",
                    help="smoke-test sizes")
    args = ap.parse_args()

    if args.config == "all":
        # fresh subprocess per config: jax backend selection (real chips
        # vs forced CPU mesh) is per-process state
        rc = 0
        for c in sorted(CONFIGS):
            cmd = [sys.executable, str(Path(__file__).resolve()),
                   "--config", str(c)] + (["--tiny"] if args.tiny else [])
            proc = subprocess.run(cmd, text=True, capture_output=True)
            sys.stderr.write(proc.stderr)
            sys.stdout.write(proc.stdout)
            if proc.returncode != 0:
                print(f"config {c} FAILED (rc={proc.returncode})",
                      file=sys.stderr)
                rc = 1
        return rc

    CONFIGS[int(args.config)](args.tiny)
    return 0


if __name__ == "__main__":
    sys.exit(main())
