"""Flash vs unfused ring-attention block update on the live chip.

Measures the per-ring-step online-softmax update both ways (the Pallas
kernel rlo_tpu/pallas/flash.py vs the einsum path
ring_attention._block_update) with bench.py's chained-iteration timing,
after checking numerics against full_attention.

Measured 2026-07-30 on the tunneled v5e chip (causal, seq block 2048,
8 heads, head_dim 128, bf16 inputs, block_q 512):
    einsum block update: 0.610 ms   flash: 0.142 ms   -> 4.31x
The unfused path materializes the (H, Lq, Lk) score/probability tensors
in HBM between ops; the kernel keeps each (BQ, Lk) tile in VMEM and the
ring loop carries all state in the kernel's head-leading layout (one
transpose in, one out).

Usage: python benchmarks/flash_bench.py [--seq N] [--heads H] [--dim D]
"""

from __future__ import annotations

import argparse
import sys
from functools import partial
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import jax                              # noqa: E402
import jax.numpy as jnp                 # noqa: E402
import numpy as np                      # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

import bench                            # noqa: E402
from rlo_tpu.ops.ring_attention import (full_attention,  # noqa: E402
                                        ring_attention)
from rlo_tpu.parallel.mesh import make_mesh, shard_jit  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--block-q", type=int, default=512)
    args = ap.parse_args()

    mesh = make_mesh((1,), ("sp",))
    rng = np.random.default_rng(0)

    def mk():
        return jnp.asarray(rng.standard_normal(
            (args.seq, args.heads, args.dim)) * 0.3, jnp.bfloat16)
    q, k, v = mk(), mk(), mk()

    def make(use_pallas):
        f = shard_jit(lambda q_, k_, v_: ring_attention(
            q_, k_, v_, "sp", causal=True, use_pallas=use_pallas,
            block_q=args.block_q),
            mesh, (P("sp"), P("sp"), P("sp")), P("sp"))

        @partial(jax.jit, static_argnames=("kk",))
        def loop(q_, kk):
            return jax.lax.fori_loop(
                0, kk, lambda i, acc: f(acc, k, v).astype(jnp.bfloat16),
                q_)
        return lambda x, kk: loop(x, kk)

    want = np.asarray(full_attention(q, k, v, causal=True), np.float32)
    got = np.asarray(make(True)(q, 1), np.float32)
    np.testing.assert_allclose(got, want, rtol=5e-2, atol=5e-2)
    print("numerics ok", file=sys.stderr)

    t_einsum = bench._chain_time(make(False), q, k=16)
    t_flash = bench._chain_time(make(True), q, k=16)
    print(f"einsum block update: {t_einsum*1e3:.3f} ms  "
          f"flash: {t_flash*1e3:.3f} ms  "
          f"speedup {t_einsum/t_flash:.2f}x")
    return 0


if __name__ == "__main__":
    sys.exit(main())
