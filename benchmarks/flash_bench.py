"""Flash vs unfused ring-attention block update on the live chip.

Measures the per-ring-step online-softmax update both ways (the Pallas
kernel rlo_tpu/pallas/flash.py vs the einsum path
ring_attention._block_update) with bench.py's chained-iteration timing,
after checking numerics against full_attention.

Measured 2026-07-30 on the tunneled v5e chip (causal, 8 heads,
head_dim 128, bf16 inputs, block_q 512):
  seq block 2048 (single K tile in VMEM):
    einsum block update: 0.502 ms   flash: 0.153 ms   -> 3.3x
    fwd+bwd einsum:      1.276 ms   flash: 0.295 ms   -> 4.3x
  seq block 8192 (K/V streamed through VMEM in 512-wide tiles):
    einsum block update: 8.997 ms   flash: 4.404 ms   -> 2.0x
    fwd+bwd einsum:     23.864 ms   flash: 10.77 ms   -> 2.2x
The unfused path materializes the (H, Lq, Lk) score/probability tensors
in HBM between ops (its backward re-materializes them again); the
kernel keeps each (BQ, Lk) tile in VMEM, the ring loop carries all
state in the kernel's head-leading layout (one transpose in, one out),
and the round-3 custom_vjp backward (pallas dq / dkv kernels)
recomputes score tiles in VMEM instead of saving them.

The --gqa leg measures grouped-query attention through the SAME kernel
two ways: compact K/V (n_kv_heads streamed from HBM, the group dim
folded into the kernel's Q axis) vs K/V explicitly repeated to n_heads
first (what the training path did before round 4). Measured 2026-07-31
(seq 4096, 8q/2kv heads, dim 128, bf16, paired-ratio protocol):
fwd 0.993x, fwd+bwd 0.976x — PARITY, and that is the expected result:
per-step K/V tile traffic is grid-identical (the fold trades the head
grid dim for Q tiles; total K reads = (total q rows / block_q) * Lk
either way) and these shapes are MXU-bound. The compact path's real
wins are structural, not kernel-time: n_heads/n_kv_heads fewer ICI
bytes per ring-attention step (pinned by the ppermute-shape tests in
tests/test_gqa_flash.py — only measurable on real multi-chip ICI), an
n_heads/n_kv_heads smaller K/V footprint (no repeated HBM copies
materialized), and the decode cache (where the K/V-HBM-bound regime
actually lives — see decode_bench.py). The leg exists so regressions
from kernel changes show up, not to claim a single-chip speedup.

Usage: python benchmarks/flash_bench.py [--seq N] [--heads H] [--dim D]
       [--gqa KV_HEADS]
"""

from __future__ import annotations

import argparse
import sys
from functools import partial
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import jax                              # noqa: E402
import jax.numpy as jnp                 # noqa: E402
import numpy as np                      # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

import bench                            # noqa: E402
from rlo_tpu.ops.ring_attention import (full_attention,  # noqa: E402
                                        ring_attention)
from rlo_tpu.parallel.mesh import make_mesh, shard_jit  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--block-q", type=int, default=512)
    ap.add_argument("--gqa", type=int, default=0, metavar="KV_HEADS",
                    help="also run the grouped-vs-repeated K/V leg "
                         "with this many K/V heads")
    args = ap.parse_args()

    mesh = make_mesh((1,), ("sp",))
    rng = np.random.default_rng(0)

    def mk():
        return jnp.asarray(rng.standard_normal(
            (args.seq, args.heads, args.dim)) * 0.3, jnp.bfloat16)
    q, k, v = mk(), mk(), mk()

    def make(use_pallas):
        f = shard_jit(lambda q_, k_, v_: ring_attention(
            q_, k_, v_, "sp", causal=True, use_pallas=use_pallas,
            block_q=args.block_q),
            mesh, (P("sp"), P("sp"), P("sp")), P("sp"))

        @partial(jax.jit, static_argnames=("kk",))
        def loop(q_, kk):
            return jax.lax.fori_loop(
                0, kk, lambda i, acc: f(acc, k, v).astype(jnp.bfloat16),
                q_)
        return lambda x, kk: loop(x, kk)

    want = np.asarray(full_attention(q, k, v, causal=True), np.float32)
    got = np.asarray(make(True)(q, 1), np.float32)
    np.testing.assert_allclose(got, want, rtol=5e-2, atol=5e-2)
    print("numerics ok", file=sys.stderr)

    t_einsum = bench._chain_time(make(False), q, k=16)
    t_flash = bench._chain_time(make(True), q, k=16)
    print(f"einsum block update: {t_einsum*1e3:.3f} ms  "
          f"flash: {t_flash*1e3:.3f} ms  "
          f"speedup {t_einsum/t_flash:.2f}x")

    # -- training: forward + backward through the attention (the path
    # the round-3 custom_vjp unlocked; bwd = the pallas dq/dkv kernels
    # recomputing score tiles in VMEM vs XLA autodiff of the einsum
    # path materializing (H, Lq, Lk) tensors) --
    def make_grad(use_pallas):
        # check_vma off for BOTH: reverse-mode through the ring's
        # ppermute/fori_loop doesn't thread varying-manual-axes types
        # (same rough edge the grad-parity tests document)
        f = shard_jit(lambda q_, k_, v_: ring_attention(
            q_, k_, v_, "sp", causal=True, use_pallas=use_pallas,
            block_q=args.block_q),
            mesh, (P("sp"), P("sp"), P("sp")), P("sp"),
            check_vma=False)

        def loss(q_, k_, v_):
            return jnp.sum(f(q_, k_, v_).astype(jnp.float32) ** 2)

        g = jax.grad(loss, argnums=(0, 1, 2))

        @partial(jax.jit, static_argnames=("kk",))
        def loop(q_, kk):
            def it(i, acc):
                dq, dk, dv = g(acc, k, v)
                return (acc + 1e-6 * (dq + dk + dv)).astype(jnp.bfloat16)
            return jax.lax.fori_loop(0, kk, it, q_)
        return lambda x, kk: loop(x, kk)

    gf = jax.grad(lambda q_: jnp.sum(ring_attention(
        q_, k, v, "sp", causal=True, use_pallas=True,
        block_q=args.block_q).astype(jnp.float32) ** 2))
    gu = jax.grad(lambda q_: jnp.sum(ring_attention(
        q_, k, v, "sp", causal=True, use_pallas=False)
        .astype(jnp.float32) ** 2))
    fgf = shard_jit(gf, mesh, (P("sp"),), P("sp"), check_vma=False)
    fgu = shard_jit(gu, mesh, (P("sp"),), P("sp"), check_vma=False)
    np.testing.assert_allclose(np.asarray(fgf(q), np.float32),
                               np.asarray(fgu(q), np.float32),
                               rtol=5e-2, atol=5e-2)
    print("grad numerics ok", file=sys.stderr)
    t_gu = bench._chain_time(make_grad(False), q, k=16)
    t_gp = bench._chain_time(make_grad(True), q, k=16)
    print(f"fwd+bwd einsum: {t_gu*1e3:.3f} ms  "
          f"fwd+bwd flash (pallas vjp): {t_gp*1e3:.3f} ms  "
          f"speedup {t_gu/t_gp:.2f}x")

    if args.gqa:
        gqa_leg(args.seq, args.heads, args.gqa, args.dim, args.block_q)
    return 0


def gqa_leg(seq, h, hkv, d, block_q):
    """Compact vs repeated K/V through the flash kernel (fwd and
    fwd+bwd): the single-chip-measurable HBM-bytes reduction of GQA."""
    from rlo_tpu.pallas.flash import flash_attention

    g = h // hkv
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((seq, h, d)) * 0.3, jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((seq, hkv, d)) * 0.3,
                    jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((seq, hkv, d)) * 0.3,
                    jnp.bfloat16)

    def att(q_, k_, v_, compact):
        if not compact:
            k_, v_ = (jnp.repeat(t, g, axis=1) for t in (k_, v_))
        return flash_attention(q_, k_, v_, causal=True, block_q=block_q)

    # parity first
    a = np.asarray(jax.jit(partial(att, compact=True))(q, k, v),
                   np.float32)
    b = np.asarray(jax.jit(partial(att, compact=False))(q, k, v),
                   np.float32)
    np.testing.assert_allclose(a, b, rtol=5e-2, atol=5e-2)
    print("gqa numerics ok", file=sys.stderr)

    def make(compact, with_grad):
        def fwd_it(i, acc):
            return att(acc, k, v, compact).astype(jnp.bfloat16)

        def grad_it(i, acc):
            gq, gk, gv = jax.grad(
                lambda q_, k_, v_: jnp.sum(
                    att(q_, k_, v_, compact).astype(jnp.float32) ** 2),
                argnums=(0, 1, 2))(acc, k, v)
            return (acc + 1e-6 * gq).astype(jnp.bfloat16)

        it = grad_it if with_grad else fwd_it

        @partial(jax.jit, static_argnames=("kk",))
        def loop(q_, kk):
            return jax.lax.fori_loop(0, kk, it, q_)
        return lambda x, kk: loop(x, kk)

    # drift-immune paired protocol (bench.py): each rep times
    # [empty, repeated, compact] back-to-back; median per-pair ratio
    import json
    ratios = {}
    for label, with_grad in (("fwd", False), ("fwd+bwd", True)):
        base = make(False, with_grad)
        chain = bench._calibrate_chain(base, q, k=16)
        results, _ = bench._paired_race(
            base, [("compact", make(True, with_grad))], q, k=chain)
        r = results["compact"]
        ratios[label] = r["ratio"]
        print(f"gqa {label} ({h}q/{hkv}kv heads): compact "
              f"{r['t_med']*1e3:.3f} ms/op, median paired ratio "
              f"repeated/compact = {r['ratio']:.3f}x", file=sys.stderr)
    on_tpu = jax.default_backend() == "tpu"
    print(json.dumps({
        "metric": f"GQA compact vs repeated K/V through the flash "
                  f"kernel, seq {seq}, {h}q/{hkv}kv, dim {d}, "
                  f"{'bf16 v5e chip' if on_tpu else jax.default_backend()}"
                  f" (regression guard: parity expected — these shapes "
                  f"are MXU-bound; the GQA wins are ICI bytes, "
                  f"footprint, and the decode cache, see "
                  f"decode_bench --compare-gqa)",
        "value": round(ratios["fwd"], 4),
        "unit": "x",
        "vs_baseline": round(ratios["fwd+bwd"], 4),
        "vs_baseline_meaning": "fwd+bwd median paired ratio "
                               "repeated/compact",
    }))


if __name__ == "__main__":
    sys.exit(main())
