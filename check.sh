#!/bin/sh
# One-shot verification of the whole framework:
#   sh check.sh
# Runs the Python test suite (forced 8-device virtual CPU mesh via
# tests/conftest.py), the ASan/UBSan native selftest, the multi-process
# shm demo scenarios, the MPI-path syntax check, the driver entry-point
# dryrun, and the tiny-size benchmark suite. Exits nonzero on the first
# failure.
#
# The sanitized selftest also runs INSIDE the pytest suite
# (tests/test_native_selftest.py), so the C engine's ack/retransmit
# and fault-injection paths are sanitizer-clean in tier-1, not just in
# this script; the explicit leg below keeps a fast standalone entry
# point and covers environments that skip pytest.
set -e
cd "$(dirname "$0")"

echo "== rlo-model (exhaustive protocol model checking + automaton parity) =="
# explicit-state exploration of EVERY interleaving of the small
# membership/healing/IAR configurations (n=3: one-kill-one-rejoin,
# healed split-brain, crossed stale syncs) against invariants M1-M5,
# plus the cross-engine membership automaton extracted from BOTH
# engine.py and rlo_engine.c (A1 parity, A2 extracted<->explored
# coverage) and the sim-backed mode driving the REAL engines through
# transport.sim — docs/DESIGN.md §20. Also in tier-1
# (tests/test_model.py). The timeout IS the wall budget: exhaustive
# at this scale or not at all.
timeout 10 python -m rlo_tpu.tools.rlo_model

echo "== static analyzers (merged rlo-lint+sentinel+prover+model report) =="
# all four analyzers in one process via runner.run_static: cross-engine
# conformance (docs/DESIGN.md §9), CFG/dataflow safety (§15), symbolic
# schedule/geometry proofs (§16), and the protocol model checker (§20)
# — one merged --json findings document, consumed here with a per-tool
# timing line (the timing prints on stderr; the document must parse
# and be finding-free). Each analyzer also runs inside tier-1
# (tests/test_{lint,sentinel,prover,model}.py).
static_json=$(mktemp -t rlo_static.XXXXXX)
timeout 60 python -m rlo_tpu.tools.runner --json > "$static_json"
python - "$static_json" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
tools = {t["tool"]: t for t in doc["tools"]}
assert set(tools) == {"rlo-lint", "rlo-sentinel", "rlo-prover",
                      "rlo-model"}, sorted(tools)
assert doc["findings"] == [], doc["findings"]
print(" ".join(f"{n}={t['seconds']:.2f}s" for n, t in tools.items()))
EOF
rm -f "$static_json"

echo "== pytest =="
python -m pytest tests/ -q

echo "== native selftest (ASan/UBSan) =="
(cd rlo_tpu/native && make -s selftest && ./rlo_selftest)

echo "== native selftest (TSan) =="
# ThreadSanitizer variant of the full selftest (loopback chaos paths
# included). The engine model is single-threaded cooperative polling,
# so tsan.supp is expected to stay empty — a report here is a real
# race, most likely in a transport that grew threads.
(cd rlo_tpu/native && make -s tsan && \
    TSAN_OPTIONS="suppressions=$PWD/tsan.supp" ./rlo_selftest_tsan)

echo "== TCP transport under TSan (socket mesh) =="
(cd rlo_tpu/native && TSAN_OPTIONS="suppressions=$PWD/tsan.supp" \
    ./tcprun -n 8 -t 240 ./rlo_demo_tsan -m 4 -b 65536)

echo "== multi-process demo + TCP under ASan/UBSan =="
(cd rlo_tpu/native && make -s demo_asan && ./rlo_demo_asan -n 8 -m 8 && \
    ./tcprun -n 8 -t 240 ./rlo_demo_asan -m 4 -b 65536)

echo "== multi-process demo =="
(cd rlo_tpu/native && make -s demo && ./rlo_demo -n 8 -m 8)

echo "== MPI transport syntax check =="
(cd rlo_tpu/native && make -s mpicheck)

echo "== MPI transport executed (femtompi mpirun) =="
(cd rlo_tpu/native && make -s mpidemo && \
    ./femtompirun -n 8 -t 240 ./rlo_demo_mpi -m 4 -b 65536)

echo "== TCP transport executed (socket mesh) =="
(cd rlo_tpu/native && ./tcprun -n 8 -t 240 ./rlo_demo -m 4 -b 65536)

echo "== observability smoke (loopback soak -> chrome timeline) =="
# 4-rank soak with tracing + metrics on and fault injection, per-rank
# JSONL dumps merged to a Chrome trace-event file, schema validated
# (flow edges included) — docs/DESIGN.md §7
JAX_PLATFORMS=cpu python -m rlo_tpu.utils.timeline smoke

echo "== fleet telescope smoke (rlo-top --json, 8-rank sim fleet) =="
# in-band telemetry plane (docs/DESIGN.md §17): drive a seeded 8-rank
# sim fleet, converge the Tag.TELEM digests, and self-check the view
# from rank 0 — every live rank's digest present and fleet rollups
# equal to the sum of the per-rank captures (exit 1 on drift)
JAX_PLATFORMS=cpu python -m rlo_tpu.tools.rlo_top --json --ranks 8 \
    --vtime 12 > /dev/null

echo "== incident watchdog mutation fixture (canary rule must trip) =="
# a watchdog that never fires is indistinguishable from none: hand a
# healthy fleet an SLO mutated down to a threshold ordinary traffic
# crosses, and require the trip plus a complete incident bundle
# (rule + fleet view + traces) — the check.sh-sized mirror of
# tests/test_observe.py's churn-cascade leg
JAX_PLATFORMS=cpu python - <<'EOF'
import json, os, tempfile
from rlo_tpu.tools.rlo_top import run_fleet
d = tempfile.mkdtemp(prefix="rlo_incident.")
fleet = run_fleet(4, seed=0,
                  watchdog_rules=["canary: sum(sent_bcast) >= 1"],
                  incident_dir=d)
fleet.drive(8.0)
fleet.converge()
incs = [i for p in fleet.planes if p.watchdog
        for i in p.watchdog.incidents]
assert incs, "mutated canary SLO never tripped"
first = next(i for i in incs if i.bundle_dir)
names = sorted(os.listdir(first.bundle_dir))
assert "incident.json" in names and "fleet_view.json" in names, names
doc = json.load(open(os.path.join(first.bundle_dir, "incident.json")))
assert doc["name"] == "canary" and doc["value"] >= 1, doc
fleet.cleanup()
print(f"canary tripped at vtime {first.vtime:.1f}; bundle: {names}")
EOF

echo "== causal trace smoke (rlo-trace --json, seeded 8-rank fabric_kill) =="
# request-scoped causal tracing (docs/DESIGN.md §19): run the seeded
# fabric_kill failover shape with every rid sampled, reconstruct the
# span trees, and require a complete report — every traced request
# delivered and stage attribution telescoping exactly to e2e (exit 1
# on analyzer findings, 2 on tool error). The same (kind, seed) pair
# is pinned bit-for-bit across runs by tests/test_spans.py. The
# timeout IS the wall budget.
JAX_PLATFORMS=cpu timeout 10 python -m rlo_tpu.tools.rlo_trace \
    --scenario fabric_kill --seed 7 --world-size 8 --json > /dev/null

echo "== collective attribution smoke (rlo-scope --json, seeded 8-rank ring) =="
# collective data-plane observatory (docs/DESIGN.md §21): run the
# instrumented ring allreduce on the seeded sim substrate and join the
# measured Ev.STEP timings against the rlo-prover-checked cost ledger
# — step identities, per-rank send counts, and payload bytes must all
# match the ledger exactly (S1/S2) and the reduction must be right
# (S3); exit 1 on findings, 2 on tool error. The same report is
# bit-for-bit pinned per (schedule, n, seed) by tests/test_scope.py.
JAX_PLATFORMS=cpu timeout 10 python -m rlo_tpu.tools.rlo_scope \
    --schedule ring_allreduce --n 8 --seed 0 --json > /dev/null

echo "== simulator fuzz sweep (25 seeds x 13 chaos scripts) =="
# fixed-seed deterministic sweep over the partition/restart/burst-loss/
# mixed scenario scripts — exactly-once, termination, and membership
# convergence checked per run — plus the churn_weather healing shape
# (sustained churn_script kills/rejoins UNDER Gilbert burst loss with
# the default watchdog SLOs armed: any incident is a sweep violation,
# docs/DESIGN.md §18) — PLUS the serving-fabric shapes
# (fabric_kill/fabric_split/fabric_rejoin/fabric_paged and the
# weather-driven fabric_churn: sustained kill/rejoin churn from a
# seeded churn_script, docs/DESIGN.md §11/§14): exactly-once request
# completion with oracle-identical tokens, re-admission after heal,
# and placement convergence — PLUS the §22 remediation shapes
# (remedy_flap/remedy_hotspot/remedy_split: default watchdog SLOs AND
# the consensus-gated RemedyPolicy armed — the fleet must quarantine
# the flapper through IAR, throttle admissions under the hotspot,
# never dual-quarantine across a partition, and recover fully once
# the fault clears). A violation prints the seed + a replay
# recipe with the live pending-event count (docs/DESIGN.md §8). The C
# engine runs the same protocol shapes via the native loopback fault
# hooks inside pytest (tests/test_membership.py); the long 500-run
# sweep is `pytest tests/test_sim.py -m slow`.
JAX_PLATFORMS=cpu python -m rlo_tpu.transport.sim --seeds 25

echo "== engine bench smoke + perf gate (BENCH_engine.json) =="
# message-engine throughput at the committed-baseline (--quick) config,
# gated against the committed numbers: wall metrics at generous factors,
# seed-deterministic frame counts at zero tolerance — docs/DESIGN.md §10.
# Includes the round-13 native_batched leg (batched vs one-call-per-
# frame driving, ARQ+metrics+profiler on; the bench itself asserts the
# >=5x bar); the full (non-quick) run's tcp leg drives the socket mesh
# through the batched GIL-releasing pump — docs/DESIGN.md §13
fresh_engine=$(mktemp -t rlo_bench_engine.XXXXXX)
JAX_PLATFORMS=cpu python benchmarks/engine_bench.py --quick \
    --out "$fresh_engine" > /dev/null
JAX_PLATFORMS=cpu python -m rlo_tpu.tools.perf_gate \
    --baseline BENCH_engine.json --fresh "$fresh_engine" --report
rm -f "$fresh_engine"

echo "== simulator scaling curve + perf gate (BENCH_sim.json) =="
# protocol-only fast path: fan-out latency + membership convergence vs n
# up to 1024 simulated ranks, PLUS the round-14 weather curves —
# churn-rate-vs-convergence (every leg now ends converged: the §18
# healing work moved the knee past r=0.05 at n=32, pinned by the
# heal-cost counters) and ARQ-retransmit-storm-under-correlated-loss
# (docs/DESIGN.md §14, §18); virtual-time metrics gate at zero
# tolerance (same seed => identical schedule), so O(log n)
# regressions fail here
fresh_sim=$(mktemp -t rlo_bench_sim.XXXXXX)
JAX_PLATFORMS=cpu python benchmarks/sim_bench.py \
    --out "$fresh_sim" > /dev/null
JAX_PLATFORMS=cpu python -m rlo_tpu.tools.perf_gate \
    --baseline BENCH_sim.json --fresh "$fresh_sim" --report
rm -f "$fresh_sim"

echo "== serving-fabric bench + perf gate (BENCH_fabric.json) =="
# 4/8-rank fabric legs in the deterministic simulator: drain vtime,
# schedule events, fail-over requeues and fleet e2e latency are all
# seed-exact and gate at zero tolerance — a protocol change that adds
# a hop or slows fail-over fails mechanically (docs/DESIGN.md §11).
# The failover4_remedy leg pins the whole §22 remediation loop the
# same way: schedule digest, IAR decision count, executed
# quarantines, and the recovered end state (nothing quarantined,
# backpressure back at 0)
fresh_fabric=$(mktemp -t rlo_bench_fabric.XXXXXX)
JAX_PLATFORMS=cpu python benchmarks/fabric_bench.py \
    --out "$fresh_fabric" > /dev/null
JAX_PLATFORMS=cpu python -m rlo_tpu.tools.perf_gate \
    --baseline BENCH_fabric.json --fresh "$fresh_fabric" --report
rm -f "$fresh_fabric"

echo "== workload bench + perf gate (BENCH_workload.json, 10k smoke) =="
# the traffic laboratory (docs/DESIGN.md §14): trace-generator digests
# for every canned workload shape, the calendar-queue n=10,000-rank
# protocol-only fan-out AND membership-convergence datapoints (with an
# in-bench heap-oracle equivalence assertion at n=256), and the
# trace-driven fabric + DecodeServer serving legs — every metric
# seed-exact at zero tolerance. The `timeout` IS the wall-time budget
# for the 10k-rank smoke: the whole bench must finish inside it.
fresh_workload=$(mktemp -t rlo_bench_workload.XXXXXX)
JAX_PLATFORMS=cpu timeout 420 python benchmarks/workload_bench.py \
    --out "$fresh_workload" > /dev/null
JAX_PLATFORMS=cpu python -m rlo_tpu.tools.perf_gate \
    --baseline BENCH_workload.json --fresh "$fresh_workload" --report
rm -f "$fresh_workload"

echo "== serve bench arrival mix + perf gate (BENCH_serve.json) =="
# open-loop Poisson production mix on the tiny model: the scheduling
# metrics (rounds, occupancy, slot-step efficiency, e2e-in-rounds)
# are seed-deterministic and gate exact; wall tok/s is informational.
# --paged adds the paged-server leg (same trace, occupancy/efficiency
# must strictly beat dense — asserted in the bench AND gated exact)
# and the prefix-heavy radix-reuse leg (docs/DESIGN.md §12)
fresh_serve=$(mktemp -t rlo_bench_serve.XXXXXX)
JAX_PLATFORMS=cpu python benchmarks/serve_bench.py --tiny \
    --arrivals poisson --paged --out "$fresh_serve"
JAX_PLATFORMS=cpu python -m rlo_tpu.tools.perf_gate \
    --baseline BENCH_serve.json --fresh "$fresh_serve" --report
rm -f "$fresh_serve"

echo "== collective bench + perf gate (BENCH_collective.json) =="
# collective data-plane legs (docs/DESIGN.md §21): instrumented sim
# runs pin step-event counts, measured-fleet bytes (== the ledger's
# account), substrate message counts, virtual drain times, and ledger
# digests at zero tolerance; the jax wall-clock GB/s-vs-psum legs are
# informational on CPU and become the ROADMAP item 2 bandwidth bar on
# a real slice. The full (non-quick) run is required: the baseline's
# wall legs must stay structurally present.
fresh_coll=$(mktemp -t rlo_bench_coll.XXXXXX)
PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=4 \
    python benchmarks/collective_bench.py --out "$fresh_coll" \
    2> /dev/null
JAX_PLATFORMS=cpu python -m rlo_tpu.tools.perf_gate \
    --baseline BENCH_collective.json --fresh "$fresh_coll" --report
rm -f "$fresh_coll"

echo "== manual-ring validation (8 virtual devices) =="
PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python benchmarks/ring_validation.py --mb 1

echo "== driver dryrun (8 virtual devices) =="
PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python __graft_entry__.py 8

echo "== benchmark suite (tiny) =="
python benchmarks/suite.py --tiny

echo "ALL CHECKS PASSED"
