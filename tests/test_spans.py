"""rlo-trace span plumbing (docs/DESIGN.md §19).

Four contracts from the tracing design:

  1. codec parity — the Python span-context trailer
     (wire.encode_span_ctx) and the C codec (rlo_wire.c
     rlo_span_encode/decode) interoperate byte-for-byte in both
     directions, and the structural discriminator (split_span_ctx)
     never misfires on clean record bodies;
  2. sampling determinism — trace_sample=1/N picks the SAME rid set
     on every rank and every re-run of a seed, with no coordination;
  3. kill-mid-decode lineage — a fabric_kill trace shows the
     re-queued request's critical path crossing the ``requeue``
     marker exactly once, attribution telescopes to e2e in exact
     integer usec for every request, and the analyzer report is
     bit-for-bit identical across two runs;
  4. the disabled path — an untraced fabric emits zero Ev.SPAN
     events and stamps no trailers (the hop probe never misfires on
     clean record bodies), the observation path replays the same
     seed to the same schedule digest, and tracing never changes
     RESULTS — a traced run delivers the identical token streams.
"""

import json

import pytest

from rlo_tpu.native import bindings as nb
from rlo_tpu.observe.spans import STAGE_NAMES, SpanRecorder, Stage
from rlo_tpu.serving.scenario import make_fabric_scenario
from rlo_tpu.tools.rlo_trace import analyze, collect, parse_rid
from rlo_tpu.utils.tracing import TRACER, Ev, Tracer
from rlo_tpu.wire import (SPAN_CTX_SIZE, SPAN_F_SAMPLED, SPAN_MAGIC,
                          decode_span_ctx, encode_span_ctx,
                          split_span_ctx)

# a spread of (gateway, seq, stage, t_usec, flags) corner cases:
# gateway -1 is the placement pseudo-rid, seq hits the &0x7FFFFFFF
# mask edge, t_usec hits the u64 edge
VECTORS = [
    (0, 0, int(Stage.ADMIT_BCAST), 0, SPAN_F_SAMPLED),
    (3, 17, int(Stage.QUEUE), 1_234_567, SPAN_F_SAMPLED),
    (-1, 5, int(Stage.PLACEMENT_IAR), 42, 0),
    (7, 0x7FFFFFFF, int(Stage.DELIVER), 2**63, SPAN_F_SAMPLED),
    (1, 2, int(Stage.REQUEUE), 2**64 - 1, 0xFF),
]


class TestCodecParity:
    def test_python_roundtrip(self):
        for gw, seq, stage, t, fl in VECTORS:
            raw = encode_span_ctx(gw, seq, stage, t, fl)
            assert len(raw) == SPAN_CTX_SIZE
            assert raw.startswith(SPAN_MAGIC)
            assert decode_span_ctx(raw) == \
                (fl & 0xFF, stage & 0xFF, gw, seq & 0x7FFFFFFF,
                 t & 0xFFFFFFFFFFFFFFFF)

    def test_python_encode_c_decode(self):
        for gw, seq, stage, t, fl in VECTORS:
            raw = encode_span_ctx(gw, seq, stage, t, fl)
            assert nb.span_decode(raw) == decode_span_ctx(raw)

    def test_c_encode_byte_parity(self):
        for gw, seq, stage, t, fl in VECTORS:
            c_raw = nb.span_encode(gw, seq & 0x7FFFFFFF,
                                   stage & 0xFF,
                                   t & 0xFFFFFFFFFFFFFFFF,
                                   flags=fl & 0xFF)
            assert c_raw == encode_span_ctx(gw, seq, stage, t, fl)
            assert decode_span_ctx(c_raw) == nb.span_decode(c_raw)

    def test_decode_rejects_garbage(self):
        assert decode_span_ctx(b"") is None
        assert decode_span_ctx(b"\x00" * SPAN_CTX_SIZE) is None
        raw = encode_span_ctx(1, 2, 3, 4)
        assert decode_span_ctx(raw[:-1]) is None  # truncated
        assert nb.span_decode(raw[:-1]) is None
        assert nb.span_decode(b"X" + raw[1:]) is None

    def test_split_clean_vs_trailed(self):
        # clean record bodies are header + whole i32 words — the
        # structural discriminator must return None for EVERY such
        # length, including ones longer than the trailer
        base = 20
        for words in range(12):
            body = b"\x00" * (base + 4 * words)
            assert split_span_ctx(body, base) == (len(body), None)
        ctx = encode_span_ctx(2, 9, int(Stage.DELIVER), 77)
        body = b"\x00" * (base + 8) + ctx
        end, got = split_span_ctx(body, base)
        assert end == len(body) - SPAN_CTX_SIZE
        assert got == decode_span_ctx(ctx)

    def test_stage_names_cover_enum(self):
        assert set(STAGE_NAMES) == {int(s) for s in Stage}


class TestSamplingDeterminism:
    RIDS = [(g, s) for g in range(8) for s in range(64)]

    def _sampled(self, rank, seed, n):
        rec = SpanRecorder(rank, lambda: 0.0, sample=n, seed=seed,
                           tracer=Tracer(enabled=False))
        return {rid for rid in self.RIDS if rec.sampled(rid)}

    def test_same_seed_same_set_across_ranks(self):
        want = self._sampled(0, seed=7, n=4)
        for rank in range(1, 6):
            assert self._sampled(rank, seed=7, n=4) == want

    def test_rerun_stable(self):
        assert self._sampled(3, seed=123, n=8) == \
            self._sampled(3, seed=123, n=8)

    def test_seed_varies_set(self):
        # crc32 is XOR-linear, so two salts CAN alias to the same
        # residue class mod a power of two — across several seeds the
        # sets must still differ somewhere
        sets = {frozenset(self._sampled(0, seed=s, n=4))
                for s in range(6)}
        assert len(sets) > 1

    def test_sample_one_takes_all(self):
        assert self._sampled(0, seed=99, n=1) == set(self.RIDS)

    def test_rate_roughly_one_in_n(self):
        got = len(self._sampled(0, seed=5, n=4))
        want = len(self.RIDS) / 4
        assert want * 0.5 <= got <= want * 1.6


def _traced_kill(seed=7, ws=8):
    sc = make_fabric_scenario("fabric_kill", seed, world_size=ws)
    sc.trace_sample = 1
    res = sc.run()
    return sc, res


class TestKillMidDecodeLineage:
    def test_requeue_on_critical_path_exactly_once(self):
        sc, res = _traced_kill()
        assert res["requeues"] > 0, "scenario no longer fails over"
        report, findings = analyze(sc.tracer.events())
        assert findings == [], [str(f) for f in findings]
        assert report["complete"] == report["requests"] > 0
        assert report["failover"], "no traced request crossed requeue"
        for rid_text in report["failover"]:
            full, _ = analyze(sc.tracer.events(),
                              request=parse_rid(rid_text))
            req = full["request"]
            path_stages = [s["stage"] for s in req["critical_path"]]
            assert path_stages.count("requeue") == 1, \
                f"{rid_text}: {path_stages}"
            # the requeue marker is the lineage link: the dead
            # owner's queue span precedes it, the survivor's follows
            assert "queue" in path_stages
            assert "deliver" == path_stages[-1]

    def test_attribution_telescopes_exact(self):
        sc, _ = _traced_kill()
        spans, _ = collect(sc.tracer.events())
        from rlo_tpu.tools.rlo_trace import analyze_request
        checked = 0
        for rid, ss in spans.items():
            if rid[0] < 0:
                continue  # placement pseudo-rids have no deliver
            r = analyze_request(ss)
            assert r is not None, f"{rid} never delivered"
            assert sum(r["attribution"].values()) == r["e2e_usec"]
            checked += 1
        assert checked > 0

    def test_report_bit_for_bit_across_runs(self):
        texts = []
        for _ in range(2):
            sc, _ = _traced_kill()
            report, findings = analyze(sc.tracer.events())
            assert findings == []
            texts.append(json.dumps(report, sort_keys=True))
        assert texts[0] == texts[1]


class TestDisabledPath:
    def test_untraced_run_emits_no_spans(self):
        # with the global tracer wide open, an untraced fabric run
        # may not emit one Ev.SPAN — no recorder means no stage
        # spans, and trailer-free records mean the engine's hop probe
        # never fires (the trailer's structural discriminator never
        # misfires on real record bodies either)
        sc = make_fabric_scenario("fabric_kill", 11, world_size=4)
        assert sc.trace_sample is None
        with TRACER.enable():
            TRACER.clear()
            res = sc.run()
            span_evs = TRACER.events(Ev.SPAN)
            TRACER.clear()
        assert sc.tracer is None
        assert span_evs == []

        # the observation path itself perturbs nothing: the same
        # untraced seed replays the identical schedule with the
        # global tracer off
        sc2 = make_fabric_scenario("fabric_kill", 11, world_size=4)
        res2 = sc2.run()
        assert res2["digest"] == res["digest"]
        assert res2["done_tokens"] == res["done_tokens"]

        # a traced run changes wire BYTES (the context is in-band)
        # but never RESULTS: same requests, same tokens delivered
        sc_t, res_t = _traced_kill(seed=11, ws=4)
        assert sc_t.tracer.events(Ev.SPAN), "traced run saw no spans"
        assert res_t["done_tokens"] == res["done_tokens"]
        assert res_t["submitted"] == res["submitted"]

    def test_recorder_emit_clamps_and_stamps_end(self):
        ring = Tracer(capacity=16, enabled=True)
        rec = SpanRecorder(2, lambda: 0.0, tracer=ring)
        rec.emit((1, 3), Stage.QUEUE, 0.0105, 0.0042)  # end < start
        rec.emit((1, 3), Stage.DECODE_ROUND, 0.0, 9999.0)
        evs = ring.events(Ev.SPAN)
        assert [e.b for e in evs] == [0, 0x7FFFFFFF]  # clamped usec
        assert evs[0].ts_usec == 4200  # stamped at stage END
        assert (evs[0].d, evs[0].c) == (1, 3)  # rid = (gw, seq)


class TestTimelineRendering:
    def test_timeline_renders_request_tracks(self):
        # span events flow through the Chrome-trace merger: one
        # request track per sampled rid, span slices on it, flow
        # edges chaining consecutive stages, and the --by-request
        # stats block keyed by rid text
        from rlo_tpu.utils.timeline import (merge_timeline,
                                            render_request_stats,
                                            trace_stats,
                                            validate_chrome_trace)
        sc, res = _traced_kill(seed=11, ws=4)
        events = [e.to_dict() for e in sc.tracer.events()]
        trace = merge_timeline([events])
        validate_chrome_trace(trace)
        evs = trace["traceEvents"]
        slices = [e for e in evs
                  if e.get("ph") == "X" and e.get("cat") == "span"]
        assert slices and all(e["pid"] == 1 for e in slices)
        assert any(e.get("cat") == "span_flow" for e in evs)
        tracks = {e["args"]["name"] for e in evs
                  if e.get("ph") == "M" and e["pid"] == 1
                  and e.get("name") == "thread_name"}
        assert any(t.startswith("req ") for t in tracks)
        stats = trace_stats(trace)
        assert stats["requests"], "no per-request stats block"
        text = render_request_stats(stats)
        assert "deliver" in text
