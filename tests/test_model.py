"""rlo-model self-verification (docs/DESIGN.md §20).

Mirror of tests/test_sentinel.py's two-halves pattern:

  1. The clean-tree contract: ``run_model`` on this checkout reports
     zero findings — every interleaving of the explored configurations
     satisfies the invariant catalog M1–M5, the two engines induce the
     same membership automaton (A1), and the extracted automaton and
     the explored model agree edge-for-edge (A2) — in tier-1, on
     every run.

  2. Mutation fixtures: each invariant family must FIRE when its
     protecting construct is deleted from the real engine source (a
     rule that never fires is indistinguishable from no rule).  Two
     fixture classes:

     - engine mutations — delete the stale-RSP guard (M5), delete the
       joiner-liveness grace (M4), un-batch admissions divergently in
       one engine (A1): the checker re-extracts its semantics from the
       mutated tree, so weakening the ENGINE weakens the MODEL and the
       matching invariant trips with a replayable Scenario recipe;
     - checker-side knobs (--mutate) — model semantics the engines
       never had (epoch downgrade, skewed admission certificates,
       dup-delivery without dedup) that pin M1/M2/M3's detection
       machinery directly.
"""

from __future__ import annotations

import json
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from rlo_tpu.tools.rlo_model import run_model

REPO_ROOT = Path(__file__).resolve().parents[1]

_IGNORE = shutil.ignore_patterns(
    "__pycache__", ".pytest_cache", "*.so", "*.o", "*.pyc",
    "rlo_selftest*", "rlo_demo", "rlo_demo_mpi", "rlo_demo_tsan",
    "rlo_demo_asan", "femtompirun")


@pytest.fixture()
def tree(tmp_path):
    """An analyzable copy of the source tree (sources only, no build
    artifacts) that fixtures may mutate freely.  run_model's sim mode
    auto-skips on copies (it needs this very checkout), so fixture
    runs are pure abstract-model explorations."""
    shutil.copytree(REPO_ROOT / "rlo_tpu", tmp_path / "rlo_tpu",
                    ignore=_IGNORE)
    return tmp_path


def mutate(root: Path, rel: str, old: str, new: str) -> int:
    """Replace ``old`` (must occur exactly once) with ``new``; returns
    the 1-indexed line of the edit."""
    path = root / rel
    text = path.read_text()
    assert text.count(old) == 1, \
        f"fixture drift: {old!r} occurs {text.count(old)}x in {rel}"
    line = text[:text.index(old)].count("\n") + 1
    path.write_text(text.replace(old, new))
    return line


def _only(findings, rule):
    return [f for f in findings if f.rule == rule]


# ---------------------------------------------------------------------------
# clean tree
# ---------------------------------------------------------------------------

def test_clean_tree_has_no_findings():
    """Exhaustive exploration of every configuration, the cross-engine
    automaton parity check, the coverage audit, and the sim-backed
    mode all pass on this checkout."""
    assert run_model(REPO_ROOT) == []


def test_cli_clean_json_and_exit_zero():
    p = subprocess.run(
        [sys.executable, "-m", "rlo_tpu.tools.rlo_model", "--json",
         "--root", str(REPO_ROOT), "--no-sim"],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert p.returncode == 0, p.stderr
    assert json.loads(p.stdout) == []


# ---------------------------------------------------------------------------
# engine-mutation fixtures (extraction-parameterized semantics)
# ---------------------------------------------------------------------------

def test_m5_fires_when_stale_rsp_guard_deleted(tree):
    """Deleting the stale-MSYNC_RSP guard re-opens the last-member
    self-demotion hole: a crossed stale response demotes the fleet's
    only member to joiner, leaving no admitter."""
    mutate(tree, "rlo_tpu/engine.py",
           "            if stale:", "            if stale and False:")
    hits = _only(run_model(tree, configs=["sync-crossfire"]), "M5")
    assert hits, "M5 did not fire on the guard-less tree"
    assert all("replay: Scenario(" in f.msg for f in hits)
    assert any("sync-crossfire" in f.msg for f in hits)


def test_m4_fires_when_joiner_grace_deleted(tree):
    """Deleting the joiner-liveness grace makes a freshly-admitted
    member immediately suspectable: the kill-rejoin configuration
    reaches a closed revocation/readmission livelock with no
    fault-free escape to a converged view."""
    mutate(tree, "rlo_tpu/engine.py",
           "        self._hb_seen[joiner] = self.clock() + max(\n"
           "            2 * (self.failure_timeout or 0.0), "
           "10 * self.join_interval)",
           "        self._hb_seen[joiner] = self.clock()")
    hits = _only(run_model(tree, rules=["M4"], configs=["kill-rejoin"],
                           max_states=40_000), "M4")
    assert hits, "M4 did not fire on the grace-less tree"
    assert all("replay: Scenario(" in f.msg for f in hits)


def test_a1_fires_on_divergently_unbatched_admissions(tree):
    """Un-batching admissions in ONE engine only (the Python WELCOME
    pack always claims a single record) splits the two engines'
    extracted admission semantics: automaton parity must fail."""
    mutate(tree, "rlo_tpu/engine.py",
           '"<ii", new_epoch, len(batch))', '"<ii", new_epoch, 1)')
    hits = _only(run_model(tree, rules=["A1"]), "A1")
    assert hits, "A1 did not fire on the divergent tree"


# ---------------------------------------------------------------------------
# checker-side knob fixtures (detection machinery)
# ---------------------------------------------------------------------------

def test_m1_fires_with_sync_downgrade_knob(tree):
    """Replacing the engines' max-merge epoch adoption with a bare
    assignment (what the code would do WITHOUT `max`) lets a crossed
    stale response drag an epoch backwards: M1 trips."""
    hits = _only(run_model(tree, mutate=("m1-sync-downgrade",),
                           configs=["sync-crossfire"]), "M1")
    assert hits, "M1 did not fire under m1-sync-downgrade"
    assert all("replay: Scenario(" in f.msg for f in hits)


def test_m2_fires_with_skewed_decision_knob(tree):
    """Skewing one admitter's certificate stream models divergent
    admission execution: co-viewed members disagree on a (member,
    epoch) certificate and M2 trips."""
    hits = _only(run_model(tree, mutate=("m2-skewed-decision",),
                           configs=["kill-rejoin"]), "M2")
    assert hits, "M2 did not fire under m2-skewed-decision"


def test_m3_fires_with_no_dedup_knob(tree):
    """Disabling the per-incarnation pickup dedup lets a duplicated
    DECIDE deliver the same proposal twice: M3 trips."""
    hits = _only(run_model(tree, mutate=("m3-no-dedup",),
                           configs=["kill-rejoin"]), "M3")
    assert hits, "M3 did not fire under m3-no-dedup"
    assert all("replay: Scenario(" in f.msg for f in hits)
