"""Engines over rank subsets — sub-communicators (round-2 VERDICT
missing #2).

The reference creates engines on ANY MPI communicator
(RLO_progress_engine_new dup's it, rootless_ops.c:467, 1461), so an
engine can span ranks {0,2,5} of an 8-rank world. Oracles: bcast and
IAR span exactly the member set (delivery counts, decision agreement);
non-members see none of the subset's traffic; a concurrently active
full-world engine set (the "bystanders") is undisturbed — on both the
Python and C engines.
"""

import pytest

from rlo_tpu.engine import EngineManager, ProgressEngine, drain
from rlo_tpu.transport import make_world
from rlo_tpu.wire import Tag

MEMBERS = [0, 2, 5]
WS = 8


def collect(eng):
    out = []
    while (m := eng.pickup_next()) is not None:
        out.append(m)
    return out


class TestPythonSubset:
    def build(self, **kw):
        # the subset engine lives on its own world (= its own dup'ed
        # communicator, exactly the reference model); bystander ranks
        # simply have no engine on it
        world = make_world("loopback", WS)
        mgr = EngineManager()
        engines = {r: ProgressEngine(world.transport(r), manager=mgr,
                                     members=MEMBERS, **kw)
                   for r in MEMBERS}
        return world, mgr, engines

    def test_validation(self):
        world = make_world("loopback", WS)
        mgr = EngineManager()
        with pytest.raises(ValueError, match="not in members"):
            ProgressEngine(world.transport(1), manager=mgr,
                           members=MEMBERS)
        with pytest.raises(ValueError, match=">= 2 members"):
            ProgressEngine(world.transport(0), manager=mgr, members=[0])

    @pytest.mark.parametrize("origin", MEMBERS)
    def test_bcast_spans_exactly_the_subset(self, origin):
        world, mgr, engines = self.build()
        engines[origin].bcast(b"sub")
        drain([world], list(engines.values()))
        for r, eng in engines.items():
            msgs = collect(eng)
            if r == origin:
                assert msgs == []
            else:
                assert [m.data for m in msgs] == [b"sub"], (r, msgs)
        # nothing ever addressed a non-member endpoint
        for r in range(WS):
            if r not in MEMBERS:
                assert world.transport(r).poll() is None

    @pytest.mark.parametrize("proposer", MEMBERS)
    @pytest.mark.parametrize("veto_rank", [None, 0, 5])
    def test_iar_on_subset(self, proposer, veto_rank):
        votes = {r: 0 if r == veto_rank else 1 for r in MEMBERS}
        world, mgr, engines = self.build()
        for r, eng in engines.items():
            eng.judge_cb = lambda p, c, r=r: votes[r]
        decision = engines[proposer].submit_proposal(b"prop",
                                                     pid=proposer)
        for _ in range(10_000):
            if decision != -1:
                break
            mgr.progress_all()
            decision = engines[proposer].vote_my_proposal()
        drain([world], list(engines.values()))
        want = 0 if veto_rank is not None else 1
        assert decision == want
        for r, eng in engines.items():
            if r == proposer:
                continue
            ds = [m for m in collect(eng)
                  if m.type == int(Tag.IAR_DECISION)]
            assert len(ds) == 1 and ds[0].vote == want, (r, ds)

    def test_bystanders_active_on_their_own_comm(self):
        """A full-world engine set runs interleaved traffic while the
        subset round proceeds; both see exactly their own."""
        world, mgr, engines = self.build()
        world_full = make_world("loopback", WS)
        full = [ProgressEngine(world_full.transport(r), manager=mgr)
                for r in range(WS)]
        engines[2].bcast(b"sub")
        full[3].bcast(b"full")      # a bystander initiates concurrently
        engines[5].bcast(b"sub2")
        drain([world, world_full], list(engines.values()) + full)
        for r, eng in engines.items():
            want = {b"sub", b"sub2"} - ({b"sub"} if r == 2 else set()) \
                - ({b"sub2"} if r == 5 else set())
            assert {m.data for m in collect(eng)} == want, r
        for r, eng in enumerate(full):
            want = set() if r == 3 else {b"full"}
            assert {m.data for m in collect(eng)} == want, r


class TestSubsetFailureInteraction:
    def test_member_death_reforms_the_subset(self):
        """Failure detection INSIDE a sub-communicator: the subset ring
        heartbeats among members only; a dead member is detected, the
        subset overlay re-forms over the survivors, and bcast +
        consensus keep working within the (shrunken) subset. Pins the
        interaction between the two users of the exclusion machinery
        (static non-members + dynamic failures)."""
        from tests.test_failure import FakeClock, spin

        members = [0, 2, 5, 7]
        world = make_world("loopback", WS)
        mgr = EngineManager()
        clock = FakeClock()
        engines = {r: ProgressEngine(world.transport(r), manager=mgr,
                                     members=members,
                                     failure_timeout=8.0,
                                     heartbeat_interval=1.0,
                                     clock=clock)
                   for r in members}
        # healthy subset round first
        engines[2].bcast(b"pre")
        drain([world], list(engines.values()))
        for r, eng in engines.items():
            want = [] if r == 2 else [b"pre"]
            assert [m.data for m in collect(eng)] == want, r
        # member 5 dies; survivors must detect and re-form
        world.kill_rank(5)
        engines[5].cleanup()
        survivors = {r: engines[r] for r in members if r != 5}
        spin(mgr, clock, 80)
        for r, eng in survivors.items():
            assert 5 in eng.failed, (r, eng.failed)
            # non-members remain excluded too
            assert set(range(WS)) - set(members) <= eng.failed
        drain([world], list(survivors.values()))
        for eng in survivors.values():
            while eng.pickup_next() is not None:
                pass
        # bcast among the surviving subset
        engines[7].bcast(b"post")
        drain([world], list(survivors.values()))
        for r, eng in survivors.items():
            want = [] if r == 7 else [b"post"]
            assert [m.data for m in collect(eng)] == want, r
        # consensus among the surviving subset (veto by 2)
        for r, eng in survivors.items():
            eng.judge_cb = lambda p, c, r=r: 0 if r == 2 else 1
        decision = engines[0].submit_proposal(b"post-prop", pid=0)
        for _ in range(10_000):
            if decision != -1:
                break
            mgr.progress_all()
            decision = engines[0].vote_my_proposal()
        assert decision == 0
        drain([world], list(survivors.values()))


class TestFacadeSubGroup:
    """backend.sub_group(members): the facade-level sub-communicator —
    same op surface, lists indexed by subset position."""

    @pytest.mark.parametrize("name", ["loopback", "native"])
    def test_ops_over_subgroup(self, name):
        import numpy as np

        import rlo_tpu

        with rlo_tpu.init(backend=name, world_size=WS) as b:
            g = b.sub_group(MEMBERS)
            assert g.world_size == len(MEMBERS)
            # bcast from subset position 1 (real rank 2)
            out = g.bcast(1, np.arange(6, dtype=np.float32))
            assert len(out) == len(MEMBERS)
            for o in out:
                np.testing.assert_allclose(o, np.arange(6))
            # allreduce over the subset only
            xs = [np.full(5, float(r + 1), np.float32)
                  for r in MEMBERS]
            outs = g.allreduce(xs)
            want = sum(r + 1 for r in MEMBERS)
            for o in outs:
                np.testing.assert_allclose(o, want)
            # consensus among group-size participants (position 0 veto)
            assert g.consensus([0] + [1] * (len(MEMBERS) - 1)) == 0
            assert g.consensus([1] * len(MEMBERS)) == 1
            # the PARENT facade still works at full scope alongside
            outs = b.allreduce([np.full(4, 1.0, np.float32)
                                for _ in range(WS)])
            for o in outs:
                np.testing.assert_allclose(o, float(WS))
            # all_gather stacks subset-position slots
            ag = g.all_gather([np.array([r], np.int32)
                               for r in MEMBERS])
            for o in ag:
                np.testing.assert_array_equal(
                    np.asarray(o).reshape(-1), MEMBERS)
            g.barrier()
            g.close()

    @pytest.mark.parametrize("name", ["loopback", "native"])
    def test_subgroup_consensus_with_interleaved_bystanders(self, name):
        """Round-4 VERDICT item: facade consensus runs on the FACADE'S
        OWN engines (subset engines for sub_groups, on the parent
        world for native), not a fabricated per-round world — so
        subset consensus must interleave with live bystander traffic.
        Pattern: parent bcast in flight semantics -> subgroup veto
        round -> parent collective -> subgroup unanimous round ->
        subgroup bcast -> parent consensus, with every decision and
        delivery checked. Any state leakage between the parent and
        subset engines (stolen pickups, stale votes, comm cross-talk)
        breaks an oracle."""
        import numpy as np

        import rlo_tpu

        with rlo_tpu.init(backend=name, world_size=WS) as b:
            g = b.sub_group(MEMBERS)
            # bystander traffic before and between consensus rounds
            out = b.bcast(1, np.arange(4, dtype=np.float32))
            assert len(out) == WS
            # subset veto round, any-position proposer (rootless)
            votes = [1] * len(MEMBERS)
            votes[-1] = 0
            assert g.consensus(votes, proposer=1) == 0
            # parent collective while the subgroup engines stay live
            outs = b.allreduce([np.full(3, 2.0, np.float32)
                                for _ in range(WS)])
            for o in outs:
                np.testing.assert_allclose(o, 2.0 * WS)
            # unanimous subset round from another proposer
            assert g.consensus([1] * len(MEMBERS),
                               proposer=len(MEMBERS) - 1) == 1
            # subgroup bcast still clean after two consensus rounds
            sub_out = g.bcast(0, np.array([7.0], np.float32))
            assert len(sub_out) == len(MEMBERS)
            for o in sub_out:
                np.testing.assert_allclose(o, 7.0)
            # the PARENT's consensus also rides persistent engines now
            assert b.consensus([1] * WS) == 1
            assert b.consensus([1] * (WS - 1) + [0], proposer=2) == 0
            # parent bcast after everything: pickups uncorrupted
            out = b.bcast(0, np.array([9.0], np.float32))
            for o in out:
                np.testing.assert_allclose(o, 9.0)
            g.close()

    @pytest.mark.parametrize("name", ["loopback", "native"])
    def test_repeated_consensus_rounds_reuse_engines(self, name):
        """Back-to-back rounds on the persistent engines: generations
        disambiguate pid reuse, decisions never leak across rounds."""
        import rlo_tpu

        with rlo_tpu.init(backend=name, world_size=4) as b:
            for i in range(6):
                votes = [1] * 4
                if i % 2:
                    votes[i % 4] = 0
                want = 0 if i % 2 else 1
                assert b.consensus(votes, proposer=i % 4) == want

    @pytest.mark.parametrize("name", ["loopback", "native"])
    def test_nested_subgroup_rejected(self, name):
        import rlo_tpu

        with rlo_tpu.init(backend=name, world_size=WS) as b:
            g = b.sub_group(MEMBERS)
            with pytest.raises(NotImplementedError):
                g.sub_group(MEMBERS[:2])
            g.close()


class TestPythonCollectivesSubset:
    def test_coroutine_collectives_over_subset(self):
        """The Python coroutine collectives (ops/collectives.py::Comm)
        scoped to members {0,2,5}, interleaved with a full-world Comm
        set on a second world — mirror of the C rlo_coll_new_sub
        semantics: virtual ring math, subset slot layouts."""
        import numpy as np

        from rlo_tpu.ops.collectives import Comm, run_collectives

        world = make_world("loopback", WS)
        world2 = make_world("loopback", WS)
        sub = {r: Comm(world.transport(r), members=MEMBERS)
               for r in MEMBERS}
        full = [Comm(world2.transport(r)) for r in range(WS)]
        outs = run_collectives(
            [sub[r].allreduce(np.full(5, float(r + 1), np.float32))
             for r in MEMBERS] +
            [c.allreduce(np.full(5, 1.0, np.float32)) for c in full])
        want_sub = sum(r + 1 for r in MEMBERS)
        for o in outs[:len(MEMBERS)]:
            np.testing.assert_allclose(o, want_sub)
        for o in outs[len(MEMBERS):]:
            np.testing.assert_allclose(o, float(WS))
        # all_gather: slots by subset position
        outs = run_collectives(
            [sub[r].all_gather(np.array([r], np.int32))
             for r in MEMBERS])
        for o in outs:
            np.testing.assert_array_equal(np.asarray(o).reshape(-1),
                                          MEMBERS)
        # all_to_all: position i's chunk j goes to position j
        outs = run_collectives(
            [sub[r].all_to_all([np.array([10 * r + j], np.int32)
                                for j in range(len(MEMBERS))])
             for r in MEMBERS])
        for i, o in enumerate(outs):
            got = [int(np.asarray(ch)[0]) for ch in o]
            assert got == [10 * src + i for src in MEMBERS], (i, got)
        # reduce_scatter + barrier complete over the subset
        outs = run_collectives(
            [sub[r].reduce_scatter(
                np.arange(6, dtype=np.float32) + (r + 1))
             for r in MEMBERS])
        total = np.sum([np.arange(6, dtype=np.float32) + (r + 1)
                        for r in MEMBERS], axis=0)
        for i, o in enumerate(outs):
            np.testing.assert_allclose(np.asarray(o).reshape(-1),
                                       total[i * 2:(i + 1) * 2])
        run_collectives([sub[r].barrier() for r in MEMBERS])

    def test_validation(self):
        from rlo_tpu.ops.collectives import Comm

        world = make_world("loopback", WS)
        with pytest.raises(ValueError, match="not in members"):
            Comm(world.transport(1), members=MEMBERS)
        with pytest.raises(ValueError, match=">= 2 members"):
            Comm(world.transport(0), members=[0])


class TestNativeSubset:
    def test_bcast_and_iar_with_bystanders(self):
        """C mirror over one NativeWorld: the subset engine rides
        comm=1 on member ranks while a full-world comm=0 engine set
        runs interleaved traffic. Delivery counts and the vetoed
        decision pin the subset scope; the comm demux keeps both
        engine sets' traffic apart."""
        from rlo_tpu.native.bindings import NativeEngine, NativeWorld

        with NativeWorld(WS) as world:
            full = [NativeEngine(world, r) for r in range(WS)]
            sub = {r: NativeEngine(
                world, r, comm=1, members=MEMBERS,
                judge_cb=lambda p, c, r=r: 0 if r == 5 else 1)
                for r in MEMBERS}
            sub[2].bcast(b"sub")
            full[3].bcast(b"full")
            rc = sub[0].submit_proposal(b"prop", pid=0)
            for _ in range(100_000):
                world.progress_all()
                if rc == -1:
                    rc = sub[0].vote_my_proposal()
                if rc != -1:
                    break
            world.drain()
            assert rc == 0  # rank 5's veto reached the subset proposer
            for r in MEMBERS:
                msgs = [m for m in iter(sub[r].pickup_next, None)]
                datas = [m.data for m in msgs
                         if m.type == int(Tag.BCAST)]
                assert datas == ([] if r == 2 else [b"sub"]), (r, datas)
            for r in range(WS):
                datas = [m.data for m in iter(full[r].pickup_next, None)
                         if m.type == int(Tag.BCAST)]
                assert datas == ([] if r == 3 else [b"full"]), (r, datas)

    def test_non_member_rejected(self):
        from rlo_tpu.native.bindings import NativeEngine, NativeWorld

        with NativeWorld(WS) as world:
            with pytest.raises(RuntimeError):
                NativeEngine(world, 1, comm=1, members=MEMBERS)

    def test_data_collectives_over_subset(self):
        """The ring data collectives (rlo_coll.c) scoped to a subset:
        allreduce / reduce_scatter / all_gather / all_to_all / barrier
        run over members {0,2,5} with slot layouts indexed by subset
        position, while a FULL-WORLD allreduce runs interleaved on a
        different comm — both must produce their own scopes' results."""
        import numpy as np

        from rlo_tpu.native.bindings import (NativeColl, NativeWorld,
                                             run_colls)

        with NativeWorld(WS) as world:
            sub = [NativeColl(world, r, comm=70, members=MEMBERS)
                   for r in MEMBERS]
            full = [NativeColl(world, r, comm=71) for r in range(WS)]
            xs = {r: np.full(40, float(r + 1), np.float32)
                  for r in MEMBERS}
            outs = run_colls(
                sub + full,
                [lambda r=r, c=c: c.allreduce_start(xs[r])
                 for r, c in zip(MEMBERS, sub)] +
                [lambda r=r, c=c: c.allreduce_start(
                    np.full(8, float(r), np.float32))
                 for r, c in enumerate(full)])
            want_sub = sum(r + 1 for r in MEMBERS)
            for o in outs[:len(MEMBERS)]:
                np.testing.assert_allclose(o, want_sub)
            want_full = sum(range(WS))
            for o in outs[len(MEMBERS):]:
                np.testing.assert_allclose(o, want_full)
            # all_gather: slots indexed by subset position
            parts = run_colls(
                sub, [lambda r=r, c=c: c.all_gather_start(
                    f"m{r}".encode()) for r, c in zip(MEMBERS, sub)])
            for out in parts:
                raw = out.tobytes()
                n = len(raw) // len(MEMBERS)
                got = [raw[i * n:(i + 1) * n] for i in
                       range(len(MEMBERS))]
                assert got == [f"m{r}".encode() for r in MEMBERS]
            # all_to_all: member at position i sends chunk j to the
            # member at position j
            chunks = {r: [bytes([10 * r + j]) for j in
                          range(len(MEMBERS))] for r in MEMBERS}
            outs = run_colls(
                sub, [lambda r=r, c=c: c.all_to_all_start(chunks[r])
                      for r, c in zip(MEMBERS, sub)])
            for i, out in enumerate(outs):
                got = list(out.tobytes())
                want = [10 * src + i for src in MEMBERS]
                assert got == want, (i, got, want)
            # reduce_scatter: each member gets its position's chunk
            ys = {r: np.arange(6, dtype=np.float32) + (r + 1)
                  for r in MEMBERS}
            outs = run_colls(
                sub, [lambda r=r, c=c: c.reduce_scatter_start(ys[r])
                      for r, c in zip(MEMBERS, sub)])
            total = np.sum([ys[r] for r in MEMBERS], axis=0)
            for i, out in enumerate(outs):
                np.testing.assert_allclose(out, total[i * 2:(i + 1) * 2])
            run_colls(sub, [lambda c=c: c.barrier_start() or 1
                            for c in sub])
            for c in sub + full:
                c.close()
