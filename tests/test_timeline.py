"""Cross-rank causal timelines (rlo_tpu/utils/timeline.py).

Acceptance oracle: a 4-rank loopback chaos run (seeded reorder, loss,
duplication, ARQ recovery) dumped per rank and merged produces VALID
Chrome trace-event JSON — json-loadable, schema-checked — with at
least one send->recv flow edge per forwarded broadcast. Plus unit
coverage for the validator, dict-source merging, and the native
(C-core) event dump flowing through the same merger.
"""

import json

import pytest

from rlo_tpu.engine import EngineManager, ProgressEngine, drain
from rlo_tpu.native import bindings as nb
from rlo_tpu.transport.loopback import LoopbackWorld
from rlo_tpu.utils.timeline import (count_flow_edges, load_jsonl,
                                    merge_timeline, validate_chrome_trace)
from rlo_tpu.utils.tracing import TRACER, Ev

WS = 4


def run_chaos(n_bcasts: int = 6):
    """Seeded chaos: latency reordering + targeted loss + duplication,
    ARQ recovering everything; returns the initiated (origin, seq)
    identities. Caller wraps in TRACER.enable()."""
    world = LoopbackWorld(WS, latency=3, seed=11)
    mgr = EngineManager()
    engines = [ProgressEngine(world.transport(r), manager=mgr,
                              arq_rto=0.005) for r in range(WS)]
    world.dup_next(0, 1, 2)
    world.drop_next(1, 3, 1)
    world.drop_next(2, 0, 1)
    idents = []
    for i in range(n_bcasts):
        origin = i % WS
        seq = engines[origin]._bcast_seq  # stamped into the frame next
        engines[origin].bcast(f"payload-{i}".encode())
        idents.append((origin, seq))
    drain([world], engines)
    for e in engines:
        while e.pickup_next() is not None:
            pass
    for e in engines:
        e.cleanup()
    return idents


@pytest.fixture()
def chaos_trace(tmp_path):
    TRACER.clear()
    with TRACER.enable():
        idents = run_chaos()
    paths = []
    for r in range(WS):
        p = tmp_path / f"rank{r}.jsonl"
        assert TRACER.dump_jsonl(str(p), rank=r) > 0
        paths.append(str(p))
    out = tmp_path / "trace.json"
    merge_timeline(paths, out_path=str(out))
    TRACER.clear()
    return idents, out


def test_chaos_run_merges_to_valid_chrome_trace(chaos_trace):
    """The acceptance criterion: per-rank dumps from a 4-rank chaos
    run merge into valid Chrome trace JSON with >= 1 flow edge per
    forwarded bcast."""
    idents, out = chaos_trace
    trace = json.loads(out.read_text())  # json-loads the written file
    validate_chrome_trace(trace)
    flows = [e for e in trace["traceEvents"] if e.get("ph") == "s"]
    assert len(flows) >= 1
    # every forwarded bcast (all of them: WS=4, every origin fans out)
    # has at least one send->recv edge, identified by its exactly-once
    # (origin, seq) identity in the flow label
    for origin, seq in idents:
        label = f"bcast {origin}:{seq}"
        assert any(e["name"] == label for e in flows), (label, flows)
    # edges terminate: every start has a finish at or after it
    finishes = {e["id"]: e for e in trace["traceEvents"]
                if e.get("ph") == "f"}
    for s in flows:
        assert finishes[s["id"]]["ts"] >= s["ts"]


def test_flow_edges_point_at_immediate_sender(chaos_trace):
    """Edge endpoints are (sender rank, receiver rank) tracks — the
    receiver's BCAST_FWD anchor names its immediate sender, so edges
    follow the actual store-and-forward path, not the origin."""
    _, out = chaos_trace
    trace = json.loads(out.read_text())
    by_id = {}
    for e in trace["traceEvents"]:
        if e.get("ph") in ("s", "f"):
            by_id.setdefault(e["id"], {})[e["ph"]] = e
    assert by_id
    for pair in by_id.values():
        assert pair["s"]["tid"] != pair["f"]["tid"]
        assert 0 <= pair["s"]["tid"] < WS
        assert 0 <= pair["f"]["tid"] < WS


def test_one_track_per_rank(chaos_trace):
    _, out = chaos_trace
    trace = json.loads(out.read_text())
    names = {e["tid"]: e["args"]["name"]
             for e in trace["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "thread_name"}
    assert names == {r: f"rank {r}" for r in range(WS)}


def test_merge_accepts_dict_sources_and_single_file(tmp_path):
    """dump_jsonl round-trips through the merger: merging the four
    per-rank files, one combined file, or in-memory dicts yields the
    same slices and edges."""
    TRACER.clear()
    with TRACER.enable():
        run_chaos(n_bcasts=3)
    combined = tmp_path / "all.jsonl"
    TRACER.dump_jsonl(str(combined))
    events = [e.to_dict() for e in TRACER.events()]
    TRACER.clear()
    t_file = merge_timeline([str(combined)])
    t_dict = merge_timeline([events])
    t_split = merge_timeline(
        [[e for e in events if e["rank"] == r] for r in range(WS)])
    assert load_jsonl(str(combined)) == events
    for t in (t_file, t_dict, t_split):
        validate_chrome_trace(t)
    assert (count_flow_edges(t_file) == count_flow_edges(t_dict)
            == count_flow_edges(t_split) >= 3)


def test_native_events_flow_through_merger():
    """The C core's trace_drain dicts share the schema: a native
    scenario merges into a valid timeline with flow edges."""
    nb.trace_clear()
    nb.trace_set(True)
    try:
        with nb.NativeWorld(WS) as world:
            engines = [nb.NativeEngine(world, r) for r in range(WS)]
            for i in range(3):
                engines[i % WS].bcast(f"n{i}".encode())
            world.drain()
            for e in engines:
                while e.pickup_next() is not None:
                    pass
    finally:
        nb.trace_set(False)
    events = nb.trace_drain()
    nb.trace_clear()
    trace = merge_timeline([events])
    validate_chrome_trace(trace)
    assert count_flow_edges(trace) >= 3


def test_validator_rejects_malformed_traces():
    ok = {"traceEvents": [
        {"ph": "X", "name": "e", "pid": 0, "tid": 0, "ts": 1, "dur": 1}]}
    validate_chrome_trace(ok)
    with pytest.raises(ValueError):
        validate_chrome_trace({"traceEvents": "nope"})
    with pytest.raises(ValueError):
        validate_chrome_trace({"traceEvents": [
            {"ph": "X", "name": "e", "pid": 0, "tid": 0, "ts": 1}]})
    with pytest.raises(ValueError):  # flow start without finish
        validate_chrome_trace({"traceEvents": [
            {"ph": "s", "name": "f", "pid": 0, "tid": 0, "ts": 1,
             "id": 7}]})
    with pytest.raises(ValueError):  # finish before start
        validate_chrome_trace({"traceEvents": [
            {"ph": "s", "name": "f", "pid": 0, "tid": 0, "ts": 5,
             "id": 7},
            {"ph": "f", "bp": "e", "name": "f", "pid": 0, "tid": 1,
             "ts": 2, "id": 7}]})


def test_smoke_entry_point(tmp_path):
    """The check.sh smoke step end to end (merge + validate inside)."""
    from rlo_tpu.utils.timeline import _smoke
    out = tmp_path / "smoke.json"
    res = _smoke(str(out))
    assert res["ok"] and res["flow_edges"] >= 1
    validate_chrome_trace(json.loads(out.read_text()))


def test_merge_tolerates_crashed_rank_dumps(tmp_path, caplog):
    """A rank that crashed before (or during) its dump must not sink
    the whole merge: missing, empty, and tail-truncated per-rank files
    are warned about and skipped, the surviving tracks are kept."""
    ok = tmp_path / "r0.jsonl"
    ok.write_text(json.dumps({"ts_usec": 10, "rank": 0,
                              "kind": "HEARTBEAT", "a": 1, "b": 0,
                              "c": 0, "d": 0}) + "\n")
    truncated = tmp_path / "r1.jsonl"
    truncated.write_text(
        json.dumps({"ts_usec": 11, "rank": 1, "kind": "HEARTBEAT",
                    "a": 0, "b": 0, "c": 0, "d": 0}) +
        '\n{"ts_usec": 12, "ra')  # died mid-write
    empty = tmp_path / "r2.jsonl"
    empty.write_text("")
    missing = tmp_path / "r3.jsonl"  # never created
    trace = merge_timeline([str(ok), str(truncated), str(empty),
                            str(missing)])
    validate_chrome_trace(trace)
    assert trace["otherData"]["ranks"] == [0, 1]
    assert trace["otherData"]["events"] == 2

    # corruption in the MIDDLE of a file is not a crash artifact
    corrupt = tmp_path / "bad.jsonl"
    corrupt.write_text('{"broken\n' + json.dumps(
        {"ts_usec": 1, "rank": 0, "kind": "HEARTBEAT",
         "a": 0, "b": 0, "c": 0, "d": 0}) + "\n")
    with pytest.raises(json.JSONDecodeError):
        merge_timeline([str(corrupt)])
