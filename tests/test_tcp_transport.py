"""TCP socket transport executed end-to-end (round-4 VERDICT
"What's missing" #2: a transport whose frames genuinely cross host
boundaries).

rlo_tcp.c implements the rlo_transport vtable over a full mesh of
nonblocking stream sockets — the same engine/coll code that runs over
loopback/shm/MPI runs here over real TCP connections between real OS
processes. Locally the `tcprun` launcher assigns localhost ports; on a
real deployment each rank gets RLO_TCP_HOSTS="host:port,..." and the
identical code spans machines (docs/DEPLOY.md's control plane row).
"""

import subprocess
import sys
from pathlib import Path

import pytest

NATIVE = Path(__file__).resolve().parent.parent / "rlo_tpu" / "native"


@pytest.fixture(scope="module")
def tcp_bins():
    subprocess.run(["make", "demo"], cwd=NATIVE, check=True,
                   capture_output=True)
    return NATIVE / "tcprun", NATIVE / "rlo_demo"


def tcprun(tcp_bins, n, *args, timeout=280):
    launcher, demo = tcp_bins
    proc = subprocess.run(
        [sys.executable, str(launcher), "-n", str(n),
         "-t", str(timeout - 10), str(demo), *map(str, args)],
        capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, (
        f"tcprun failed (rc={proc.returncode})\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}")
    return proc.stdout


@pytest.mark.parametrize("ws", [2, 4, 8])
def test_all_cases_over_tcp(tcp_bins, ws):
    """Every transport-agnostic scenario passes over real sockets
    (fail/efail are shm-only: SKIP)."""
    out = tcprun(tcp_bins, ws, "-m", 4, "-b", 65536)
    assert "FAIL" not in out
    assert out.count("PASS") == 9
    assert out.count("SKIP") == 2
    assert "[tcp]" in out


def test_subcomm_over_tcp_n6(tcp_bins):
    """Subset engines (sub-communicator) with interleaved full-world
    traffic, every frame over a socket."""
    out = tcprun(tcp_bins, 6, "-c", "subcomm")
    assert "PASS" in out and "FAIL" not in out


def test_multi_proposal_over_tcp_n5(tcp_bins):
    """Concurrent multi-proposal consensus, non-power-of-2 world."""
    out = tcprun(tcp_bins, 5, "-c", "multi2")
    assert "PASS" in out and "FAIL" not in out


TCP_BACKEND_PROG = r"""
import sys
sys.path.insert(0, {repo!r})
import numpy as np
from rlo_tpu.backend import TcpBackend

b = TcpBackend()
r, ws = b.rank, b.world_size
x = np.full((8,), float(r + 1), np.float32)
got = b.allreduce(x)
assert np.allclose(got, ws * (ws + 1) / 2), (r, got)
g = b.all_gather(np.int32([r]))
assert list(g.reshape(-1)) == list(range(ws)), (r, g)
assert b.consensus(my_vote=1) == 1
d = b.consensus(my_vote=0 if r == ws - 1 else 1, proposer=1)
assert d == 0, (r, d)
# subset of the real socket-connected processes
members = [0, ws - 1]
g = b.sub_group(members)
assert (g is None) == (r not in members)
if g is not None:
    d = g.consensus(my_vote=0 if g.pos == 1 else 1, proposer=0)
    assert d == 0, (r, d)
    out = g.bcast(0, np.arange(4, dtype=np.float32)
                  if g.pos == 0 else None)
    assert np.allclose(out, np.arange(4)), (r, out)
b.barrier()
if g is not None:
    g.close()
b.release_sub_comm()            # collective, like MPI_Comm_free
# recycled comm ids: a fresh sub_group reuses the released pair
n0 = b._sub_comm_next
g2 = b.sub_group(members)
assert b._sub_comm_next == n0, "comm pair was not recycled"
if g2 is not None:
    d = g2.consensus(my_vote=1, proposer=0)
    assert d == 1, (r, d)
    g2.close()
b.release_sub_comm()
b.barrier()
if r == 0:
    print("TCP-BACKEND-OK", ws)
b.close()
"""


def test_python_tcp_backend(tcp_bins, tmp_path):
    """The Python TcpBackend facade end-to-end: one Python process per
    rank over the socket mesh — collectives, rootless consensus with
    veto, and a sub_group of the real processes."""
    launcher, _ = tcp_bins
    repo = str(Path(__file__).resolve().parent.parent)
    prog = tmp_path / "prog.py"
    prog.write_text(TCP_BACKEND_PROG.format(repo=repo))
    proc = subprocess.run(
        [sys.executable, str(launcher), "-n", "4", "-t", "240",
         sys.executable, str(prog)],
        capture_output=True, text=True, timeout=280)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "TCP-BACKEND-OK 4" in proc.stdout


TCP_LIVENESS_PROG = r"""
import sys, time
sys.path.insert(0, {repo!r})
from rlo_tpu.backend import TcpBackend

b = TcpBackend()
r, ws = b.rank, b.world_size
assert all(b.world.peer_alive(p, 0) for p in range(ws)), r
b.barrier()
if r == ws - 1:
    # rank ws-1 departs gracefully; the others see its socket close
    b.close()
    sys.exit(0)
deadline = time.time() + 30
while b.world.peer_alive(ws - 1, 0):
    b.world.progress_all()
    if time.time() > deadline:
        raise RuntimeError(f"rank {{r}}: never saw the peer depart")
    time.sleep(0.001)
# a clean departure is NOT a world failure (graceful-EOF contract).
# (No cross-survivor aliveness check here: survivors exit at their
# own pace, so peer_alive on another survivor races its departure.)
assert not b.world.failed(), r
if r == 0:
    print("TCP-LIVENESS-OK")
b.close()
"""


def test_peer_alive_sees_graceful_departure(tcp_bins, tmp_path):
    """The TCP transport's socket-level liveness (round 4): a
    gracefully departed peer reads as not-alive on every survivor
    without marking the world failed (crash = mid-frame EOF, which
    does)."""
    launcher, _ = tcp_bins
    repo = str(Path(__file__).resolve().parent.parent)
    prog = tmp_path / "prog.py"
    prog.write_text(TCP_LIVENESS_PROG.format(repo=repo))
    proc = subprocess.run(
        [sys.executable, str(launcher), "-n", "3", "-t", "120",
         sys.executable, str(prog)],
        capture_output=True, text=True, timeout=150)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "TCP-LIVENESS-OK" in proc.stdout


def test_multihost_demo_over_tcp_two_hosts(tcp_bins, tmp_path):
    """The multihost demo (engine consensus gating a federated-JAX
    device collective) with 2 'hosts' = 2 processes whose CONTROL
    plane is the TCP transport — the deployment shape of
    docs/DEPLOY.md with no MPI anywhere."""
    import os
    launcher, _ = tcp_bins
    repo = Path(__file__).resolve().parent.parent
    env = dict(os.environ)
    env.update({"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
                "RLO_COORDINATOR": "127.0.0.1:29877",
                "RLO_TRANSPORT": "tcp"})
    proc = subprocess.run(
        [sys.executable, str(launcher), "-n", "2", "-t", "240",
         sys.executable, str(repo / "benchmarks" / "multihost_demo.py")],
        capture_output=True, text=True, timeout=280, env=env,
        cwd=str(repo))
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert proc.stdout.count("MULTIHOST-OK") == 2
