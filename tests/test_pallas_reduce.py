"""Direct unit tests of the Pallas fused-combine kernel (interpret mode on
the CPU backend; the identical kernel compiles on TPU)."""

import jax.numpy as jnp
import numpy as np
import pytest

from rlo_tpu.pallas.reduce import fused_combine


class TestFusedCombine:
    @pytest.mark.parametrize("shape", [(8, 128), (1024,), (3, 5, 7),
                                       (1,), (513,)])
    def test_sum_arbitrary_shapes(self, shape):
        rng = np.random.default_rng(0)
        a = jnp.asarray(rng.standard_normal(shape), jnp.float32)
        b = jnp.asarray(rng.standard_normal(shape), jnp.float32)
        np.testing.assert_allclose(np.asarray(fused_combine(a, b)),
                                   np.asarray(a) + np.asarray(b), rtol=1e-6)

    @pytest.mark.parametrize("op,npop", [("min", np.minimum),
                                         ("max", np.maximum)])
    def test_min_max(self, op, npop):
        rng = np.random.default_rng(1)
        a = jnp.asarray(rng.standard_normal((64, 64)), jnp.float32)
        b = jnp.asarray(rng.standard_normal((64, 64)), jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(fused_combine(a, b, op=op)),
            npop(np.asarray(a), np.asarray(b)))

    def test_int_and(self):
        a = jnp.ones((16, 128), jnp.int32).at[3, 4].set(0)
        b = jnp.ones((16, 128), jnp.int32).at[5, 6].set(0)
        got = np.asarray(fused_combine(a, b, op="and"))
        assert got[3, 4] == 0 and got[5, 6] == 0 and got.sum() == 16 * 128 - 2

    def test_bf16_f32_accumulation(self):
        # values whose bf16 sum would lose precision without f32 accum
        a = jnp.full((256,), 1.001, jnp.bfloat16)
        b = jnp.full((256,), 1e-3, jnp.bfloat16)
        got = np.asarray(fused_combine(a, b), np.float32)
        want = (np.full(256, np.float32(jnp.bfloat16(1.001)))
                + np.full(256, np.float32(jnp.bfloat16(1e-3))))
        # result re-quantizes to bf16 at the end; error bounded by one ulp
        np.testing.assert_allclose(got, want, rtol=4e-3)

    def test_blocking_covers_multiple_grid_steps(self):
        rng = np.random.default_rng(2)
        a = jnp.asarray(rng.standard_normal((4096, 128)), jnp.float32)
        b = jnp.asarray(rng.standard_normal((4096, 128)), jnp.float32)
        got = fused_combine(a, b, block_rows=256)
        np.testing.assert_allclose(np.asarray(got),
                                   np.asarray(a) + np.asarray(b), rtol=1e-6)

    def test_mismatched_operands_raise(self):
        with pytest.raises(ValueError):
            fused_combine(jnp.zeros((4,)), jnp.zeros((5,)))
        with pytest.raises(ValueError):
            fused_combine(jnp.zeros((4,)), jnp.zeros((4,), jnp.int32))

    def test_unknown_op_raises(self):
        with pytest.raises(ValueError):
            fused_combine(jnp.zeros(4), jnp.zeros(4), op="xor")
