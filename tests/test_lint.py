"""rlo-lint self-verification (docs/DESIGN.md §9).

Two halves:

  1. The clean-tree contract: ``run_lint`` on this checkout reports
     zero findings. This is the tier-1 wrapper the CI step leans on —
     any parity drift between the Python and C engines (wire layout,
     metrics schema, ctypes contracts, dispatch coverage, determinism
     hygiene) fails the ordinary test suite, not just check.sh.

  2. Mutation fixtures: for each rule family R1–R5 a temp copy of the
     tree is seeded with exactly one violation and the lint must trip
     with the right rule ID at the right file:line — proving every
     rule actually fires (a linter that never fires is
     indistinguishable from no linter).
"""

from __future__ import annotations

import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from rlo_tpu.tools.rlo_lint import run_lint

REPO_ROOT = Path(__file__).resolve().parents[1]

_IGNORE = shutil.ignore_patterns(
    "__pycache__", ".pytest_cache", "*.so", "*.o", "*.pyc",
    "rlo_selftest*", "rlo_demo", "rlo_demo_mpi", "rlo_demo_tsan",
    "rlo_demo_asan", "femtompirun")


@pytest.fixture()
def tree(tmp_path):
    """A lintable copy of the source tree (sources only, no build
    artifacts) that fixtures may mutate freely."""
    shutil.copytree(REPO_ROOT / "rlo_tpu", tmp_path / "rlo_tpu",
                    ignore=_IGNORE)
    return tmp_path


def mutate(root: Path, rel: str, old: str, new: str) -> int:
    """Replace ``old`` (must occur exactly once) with ``new``; returns
    the 1-indexed line of the edit."""
    path = root / rel
    text = path.read_text()
    assert text.count(old) == 1, \
        f"fixture drift: {old!r} occurs {text.count(old)}x in {rel}"
    line = text[:text.index(old)].count("\n") + 1
    path.write_text(text.replace(old, new))
    return line


def findings_for(root: Path, rule: str):
    return [f for f in run_lint(root) if f.rule == rule]


# ---------------------------------------------------------------------------
# 1. clean tree
# ---------------------------------------------------------------------------

def test_head_is_clean():
    """Zero findings on this checkout — the tier-1 drift gate."""
    findings = run_lint(REPO_ROOT)
    assert findings == [], "\n".join(str(f) for f in findings)


# ---------------------------------------------------------------------------
# 2. one seeded violation per rule family
# ---------------------------------------------------------------------------

def test_r1_fires_on_wire_offset_drift(tree):
    line = mutate(tree, "rlo_tpu/wire.py",
                  "SEQ_OFFSET = 12", "SEQ_OFFSET = 13")
    hits = findings_for(tree, "R1")
    assert any(f.file == "rlo_tpu/wire.py" and f.line == line and
               "SEQ_OFFSET" in f.msg for f in hits), hits


def test_r1_fires_on_tag_value_drift(tree):
    line = mutate(tree, "rlo_tpu/wire.py",
                  "HEARTBEAT = 11", "HEARTBEAT = 42")
    hits = findings_for(tree, "R1")
    assert any(f.file == "rlo_tpu/wire.py" and f.line == line and
               "HEARTBEAT" in f.msg for f in hits), hits


def test_r1_fires_on_deleted_error_constant(tree):
    """A constant pair with one side missing is a finding, not a
    silently skipped check."""
    mutate(tree, "rlo_tpu/native/bindings.py", "ERR_STALL = -15\n", "")
    hits = findings_for(tree, "R1")
    assert any(f.file == "rlo_tpu/native/bindings.py" and
               "ERR_STALL" in f.msg for f in hits), hits


def test_r1_fires_on_fanout_drift(tree):
    line = mutate(tree, "rlo_tpu/native/bindings.py",
                  "FANOUT_FLAT = 1", "FANOUT_FLAT = 2")
    hits = findings_for(tree, "R1")
    assert any(f.line == line and "FANOUT_FLAT" in f.msg
               for f in hits), hits


def test_r1_fires_on_span_ctx_size_drift(tree):
    """The span trailer's fixed size is pinned twice: against
    RLO_SPAN_CTX_SIZE and against the actual struct layout — and 24
    additionally breaks the %4==3 structural discriminator."""
    line = mutate(tree, "rlo_tpu/wire.py",
                  "SPAN_CTX_SIZE = 23", "SPAN_CTX_SIZE = 24")
    hits = findings_for(tree, "R1")
    assert any(f.file == "rlo_tpu/wire.py" and f.line == line and
               "SPAN_CTX_SIZE" in f.msg for f in hits), hits
    assert any("% 4" in f.msg for f in hits), hits


def test_r1_fires_on_span_magic_drift(tree):
    line = mutate(tree, "rlo_tpu/wire.py",
                  'SPAN_MAGIC = b"RLOS', 'SPAN_MAGIC = b"RLOX')
    hits = findings_for(tree, "R1")
    assert any(f.file == "rlo_tpu/wire.py" and f.line == line and
               "RLO_SPAN_MAGIC" in f.msg for f in hits), hits


def test_r1_fires_on_span_event_id_drift(tree):
    """Ev <-> rlo_ev value parity: renumbering Ev.SPAN without the C
    tracer is a finding (the merged timeline would mislabel)."""
    mutate(tree, "rlo_tpu/utils/tracing.py",
           "SPAN = 15", "SPAN = 99")
    hits = findings_for(tree, "R1")
    assert any("Ev.SPAN" in f.msg and "RLO_EV_SPAN" in f.msg
               for f in hits), hits


def test_r2_fires_on_counter_key_drift(tree):
    mutate(tree, "rlo_tpu/utils/metrics.py",
           '"epoch", "epoch_quarantined", "rejoins",',
           '"epoch", "epoch_quarantined",')
    hits = findings_for(tree, "R2")
    assert any("rejoins" in f.msg for f in hits), hits
    assert any(f.file == "rlo_tpu/utils/metrics.py" for f in hits), hits


def test_r2_fires_on_phase_key_drift(tree):
    """Profiler-schema parity: dropping a phase from
    ENGINE_PHASE_KEYS breaks the tuple <-> rlo_phase_stats field-order
    pin (docs/DESIGN.md §10)."""
    mutate(
        tree, "rlo_tpu/utils/metrics.py",
        '"frame_encode", "frame_decode", "send", "arq_scan", '
        '"tag_dispatch",',
        '"frame_encode", "frame_decode", "send", "tag_dispatch",')
    hits = findings_for(tree, "R2")
    # anchored at the tuple assignment: keys != C struct field order
    assert any(f.file == "rlo_tpu/utils/metrics.py" and
               "rlo_phase_stats" in f.msg for f in hits), hits
    # the engine's phase literal now disagrees with the shrunk tuple
    assert any(f.file == "rlo_tpu/engine.py" and
               "assembles phases" in f.msg for f in hits), hits


def test_r2_fires_on_phobs_key_typo(tree):
    """A _phobs() observation into a key the snapshot never emits is
    silent schema drift — and a runtime KeyError — R2 catches it
    statically."""
    line = mutate(tree, "rlo_tpu/engine.py",
                  'self._phobs("arq_scan", t0)',
                  'self._phobs("arq_scanz", t0)')
    hits = findings_for(tree, "R2")
    assert any(f.file == "rlo_tpu/engine.py" and f.line == line and
               "arq_scanz" in f.msg for f in hits), hits
    # ...and the schema key it abandoned is now unobserved
    assert any("no _phobs() observation site" in f.msg
               for f in hits), hits


def test_r2_fires_on_heal_counter_drift(tree):
    """The §18 healing counters (epoch_syncs / reflood_skipped /
    batched_admits) ride the same schema chain as every other engine
    counter: dropping one from ENGINE_COUNTER_KEYS must break the
    tuple <-> rlo_stats field-order pin AND the metrics() assembly."""
    mutate(tree, "rlo_tpu/utils/metrics.py",
           '"epoch_syncs", "reflood_skipped", "batched_admits",',
           '"epoch_syncs", "batched_admits",')
    hits = findings_for(tree, "R2")
    assert any(f.file == "rlo_tpu/utils/metrics.py" and
               "reflood_skipped" in f.msg for f in hits), hits


def test_r2_fires_on_telem_key_drift(tree):
    """Dropping a digest key from wire.py's TELEM schema must trip
    the §17 extension: the C codec's k_telem_keys name table and
    RLO_TELEM_NKEYS now disagree with the mask-bit order — the drift
    that would decode every fleet digest into the wrong slots."""
    mutate(tree, "rlo_tpu/wire.py",
           '"q_wait", "pickup_backlog", "pages_in_use", "pages_free",',
           '"q_wait", "pickup_backlog", "pages_in_use",')
    hits = findings_for(tree, "R2")
    assert any(f.file == "rlo_tpu/native/rlo_core.h" and
               "RLO_TELEM_NKEYS" in f.msg for f in hits), hits
    assert any(f.file == "rlo_tpu/native/rlo_wire.c" and
               "k_telem_keys" in f.msg for f in hits), hits


def test_r2_fires_on_coll_key_drift(tree):
    """The §21 collective rollups (coll_steps / coll_bytes) ride the
    same schema chain as every §17 digest key: dropping one from
    TELEM_EXTRA_KEYS must break the C codec's name table and the
    RLO_TELEM_NKEYS pin."""
    mutate(tree, "rlo_tpu/wire.py",
           '"coll_steps", "coll_bytes",',
           '"coll_steps",')
    hits = findings_for(tree, "R2")
    assert any(f.file == "rlo_tpu/native/rlo_core.h" and
               "RLO_TELEM_NKEYS" in f.msg for f in hits), hits
    assert any(f.file == "rlo_tpu/native/rlo_wire.c" and
               "k_telem_keys" in f.msg for f in hits), hits


def test_r2_fires_on_remedy_key_drift(tree):
    """The §22 remediation counters (remedies_proposed /
    remedies_executed / quarantined / backpressure_level) ride the
    same schema chain as every §17 digest key: dropping one from
    TELEM_EXTRA_KEYS must break the C codec's name table and the
    RLO_TELEM_NKEYS pin."""
    mutate(tree, "rlo_tpu/wire.py",
           '"remedies_proposed", "remedies_executed",',
           '"remedies_proposed",')
    hits = findings_for(tree, "R2")
    assert any(f.file == "rlo_tpu/native/rlo_core.h" and
               "RLO_TELEM_NKEYS" in f.msg for f in hits), hits
    assert any(f.file == "rlo_tpu/native/rlo_wire.c" and
               "k_telem_keys" in f.msg for f in hits), hits


def test_r2_fires_on_telem_header_drift(tree):
    """The byte-pinned digest header size is a paired constant: a
    Python-side bump without the C twin is a finding at the
    assignment line."""
    line = mutate(tree, "rlo_tpu/wire.py",
                  "TELEM_HEADER_SIZE = 26", "TELEM_HEADER_SIZE = 27")
    hits = findings_for(tree, "R2")
    assert any(f.file == "rlo_tpu/wire.py" and f.line == line and
               "TELEM_HEADER_SIZE" in f.msg for f in hits), hits


def test_r3_fires_on_missing_binding(tree):
    mutate(tree, "rlo_tpu/native/bindings.py",
           '    sig("rlo_engine_set_fanout", C.c_int, [p, C.c_int])\n',
           "")
    hits = findings_for(tree, "R3")
    assert any(f.file == "rlo_tpu/native/bindings.py" and
               "rlo_engine_set_fanout" in f.msg and
               "no argtypes/restype" in f.msg for f in hits), hits


def test_r3_fires_on_missing_batched_binding(tree):
    """ISSUE-11 surface: dropping the batched entry point's sig()
    declaration must fail R3 (its int64 return would otherwise ride
    the implicit-int default and truncate frame counts)."""
    mutate(tree, "rlo_tpu/native/bindings.py",
           '    sig("rlo_engine_progress_n", C.c_int64,'
           '  # rlo-sentinel: gil-released\n'
           '        [p, C.c_int64, C.c_uint64])\n',
           "")
    hits = findings_for(tree, "R3")
    assert any(f.file == "rlo_tpu/native/bindings.py" and
               "rlo_engine_progress_n" in f.msg and
               "no argtypes/restype" in f.msg for f in hits), hits


def test_r3_fires_on_64bit_truncation(tree):
    """A uint64_t-returning function declared c_int is exactly the
    truncation hazard R3 exists for."""
    line = mutate(tree, "rlo_tpu/native/bindings.py",
                  'sig("rlo_now_usec", C.c_uint64, [])',
                  'sig("rlo_now_usec", C.c_int, [])')
    hits = findings_for(tree, "R3")
    assert any(f.line == line and "rlo_now_usec" in f.msg
               for f in hits), hits


def test_r4_fires_on_dispatch_hole(tree):
    # ABORT loses its handler (BARRIER is default-routed, so the
    # rewritten branch itself stays legal)
    mutate(tree, "rlo_tpu/engine.py",
           "elif tag == Tag.ABORT:", "elif tag == Tag.BARRIER:")
    hits = findings_for(tree, "R4")
    assert any(f.file == "rlo_tpu/wire.py" and "Tag.ABORT" in f.msg
               for f in hits), hits


def test_r4_fires_on_deleted_membership_handler(tree):
    """A membership guard (`tag in EPOCH_EXEMPT_TAGS`) must not mask a
    deleted handler inside it: only the explicit `tag == Tag.X`
    comparison counts as dispatch."""
    mutate(tree, "rlo_tpu/engine.py",
           "                elif tag == Tag.JOIN_WELCOME:\n"
           "                    self._on_welcome(msg)\n",
           "")
    hits = findings_for(tree, "R4")
    assert any(f.file == "rlo_tpu/wire.py" and "Tag.JOIN_WELCOME" in
               f.msg for f in hits), hits


def test_r4_fires_on_fabric_record_dispatch_hole(tree):
    """The serving fabric's Rec record kinds are held to the same
    dispatch-exhaustiveness bar as engine Tags (docs/DESIGN.md §11):
    a kind whose _on_record branch disappears is a finding."""
    line = mutate(tree, "rlo_tpu/serving/fabric.py",
                  "elif kind == Rec.LOAD:", "elif False:")
    hits = findings_for(tree, "R4")
    assert any(f.file == "rlo_tpu/serving/fabric.py" and
               "Rec.LOAD" in f.msg for f in hits), hits
    assert line > 0


def test_r4_fires_on_remedy_record_dispatch_hole(tree):
    """The remediation record kinds (Rec 5..8, docs/DESIGN.md §22)
    are full members of the fabric's record vocabulary: deleting a
    _on_record arm must name the orphaned kind, or a heal
    re-broadcast would silently drop the very record that keeps the
    quarantine state convergent."""
    mutate(tree, "rlo_tpu/serving/fabric.py",
           "elif kind == Rec.QUARANTINE:", "elif False:")
    hits = findings_for(tree, "R4")
    assert any(f.file == "rlo_tpu/serving/fabric.py" and
               "Rec.QUARANTINE" in f.msg for f in hits), hits


def test_r4_fires_on_msync_subkind_hole(tree):
    """The MSYNC kind byte rides an open if/elif chain in both
    engines; dropping one arm must name the orphaned sub-kind on each
    side (there is no catch-all to default-route it to)."""
    mutate(tree, "rlo_tpu/engine.py",
           "elif kind == MSYNC_AD:", "elif False:")
    mutate(tree, "rlo_tpu/native/rlo_engine.c",
           "} else if (kind == RLO_MSYNC_WANT) {",
           "} else if (0) {")
    hits = findings_for(tree, "R4")
    assert any(f.file == "rlo_tpu/engine.py" and "MSYNC_AD" in f.msg
               for f in hits), hits
    assert any(f.file == "rlo_tpu/native/rlo_engine.c" and
               "RLO_MSYNC_WANT" in f.msg for f in hits), hits


def test_r5_fires_on_fabric_wallclock_leak(tree):
    """serving/ is in the deterministic-replay scope: a wall-clock
    read in the fabric would break seed-exact fleet replays."""
    path = tree / "rlo_tpu/serving/fabric.py"
    path.write_text(path.read_text() +
                    "\nimport time\n_T0 = time.time()\n")
    hits = findings_for(tree, "R5")
    assert any(f.file == "rlo_tpu/serving/fabric.py" and
               "time.time" in f.msg for f in hits), hits


def test_r5_fires_on_page_allocator_wallclock_leak(tree):
    """The paged-KV allocator module (serving/pages.py) is in the
    deterministic-replay scope (docs/DESIGN.md §12): page handout
    order replays seed-exactly in fleet scenarios, so a wall-clock
    (or module-random) dependency there is a finding."""
    path = tree / "rlo_tpu/serving/pages.py"
    path.write_text(path.read_text() +
                    "\nimport time\n_T0 = time.time()\n")
    hits = findings_for(tree, "R5")
    assert any(f.file == "rlo_tpu/serving/pages.py" and
               "time.time" in f.msg for f in hits), hits


def test_r5_fires_on_trace_generator_wallclock_leak(tree):
    """The workloads subsystem (docs/DESIGN.md §14) is in the
    deterministic-replay scope: trace digests are pinned seed-exact in
    BENCH_workload.json/BENCH_serve.json, so a wall-clock (or
    module-random) dependency in a generator would unpin every
    committed trace."""
    path = tree / "rlo_tpu/workloads/traces.py"
    path.write_text(path.read_text() +
                    "\nimport time\n_T0 = time.time()\n")
    hits = findings_for(tree, "R5")
    assert any(f.file == "rlo_tpu/workloads/traces.py" and
               "time.time" in f.msg for f in hits), hits


def test_r5_fires_on_weather_module_random_leak(tree):
    """Weather samplers must draw ONLY from the rng the simulator
    passes in — module-level randomness would decouple runs from the
    world seed."""
    path = tree / "rlo_tpu/workloads/weather.py"
    path.write_text(path.read_text() +
                    "\nimport random\n_J = random.random()\n")
    hits = findings_for(tree, "R5")
    assert any(f.file == "rlo_tpu/workloads/weather.py" and
               "random.random" in f.msg for f in hits), hits


def test_r5_fires_on_telemetry_wallclock_leak(tree):
    """The telemetry plane is in the deterministic-replay scope
    (docs/DESIGN.md §17): emission paces on the engine clock so
    instrumented fleets replay bit-for-bit from the seed — a
    wall-clock read in observe/ would unpin every instrumented
    schedule (and every watchdog trip vtime)."""
    path = tree / "rlo_tpu/observe/telemetry.py"
    path.write_text(path.read_text() +
                    "\nimport time\n_T0 = time.time()\n")
    hits = findings_for(tree, "R5")
    assert any(f.file == "rlo_tpu/observe/telemetry.py" and
               "time.time" in f.msg for f in hits), hits


def test_r5_fires_on_wallclock_leak(tree):
    path = tree / "rlo_tpu/transport/sim.py"
    path.write_text(path.read_text() +
                    "\nimport time\n_T0 = time.time()\n")
    hits = findings_for(tree, "R5")
    assert any(f.file == "rlo_tpu/transport/sim.py" and
               "time.time" in f.msg for f in hits), hits


def test_r5_anchor_suppresses(tree):
    path = tree / "rlo_tpu/transport/sim.py"
    path.write_text(path.read_text() +
                    "\nimport time\n"
                    "_T0 = time.time()  # rlo-lint: allow-wallclock\n")
    assert findings_for(tree, "R5") == []


# ---------------------------------------------------------------------------
# CLI contract
# ---------------------------------------------------------------------------

def test_cli_exit_codes(tree):
    mutate(tree, "rlo_tpu/wire.py", "SEQ_OFFSET = 12", "SEQ_OFFSET = 13")
    proc = subprocess.run(
        [sys.executable, "-m", "rlo_tpu.tools.rlo_lint",
         "--root", str(tree)],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "R1" in proc.stdout
    # rule selection: a family that is still clean exits 0
    proc = subprocess.run(
        [sys.executable, "-m", "rlo_tpu.tools.rlo_lint",
         "--root", str(tree), "--rules", "R5"],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert proc.returncode == 0, proc.stdout + proc.stderr
