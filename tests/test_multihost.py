"""Multi-controller deployment path (round-2 VERDICT missing #1).

Four real OS processes, each BOTH an engine rank (femtompi shm — real
cross-process vote frames) and a federated JAX controller (one global
CPU mesh via jax.distributed — real cross-process AllReduce). Oracles
(inside benchmarks/multihost_demo.py, self-verifying per process):

  - rootless initiation: a non-zero rank proposes;
  - approval path: the device psum runs cross-process and every process
    holds the replicated sum;
  - veto path: ONE process's poisoned local tensor declines the round
    on EVERY process and the collective never runs.
"""

import socket
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
NATIVE = REPO / "rlo_tpu" / "native"
DEMO = REPO / "benchmarks" / "multihost_demo.py"


@pytest.fixture(scope="module")
def launcher():
    subprocess.run(["make", "mpidemo"], cwd=NATIVE, check=True,
                   capture_output=True)
    return NATIVE / "femtompirun"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.parametrize("nproc", [4, 8])
def test_consensus_gated_psum_across_processes(launcher, nproc):
    env = {
        "PATH": "/usr/bin:/bin:/usr/local/bin",
        "HOME": "/tmp",
        # per-process CPU JAX (the axon TPU hook must stay out of
        # worker processes; only then does jax.distributed federate)
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "RLO_COORDINATOR": f"127.0.0.1:{_free_port()}",
    }
    proc = subprocess.run(
        [str(launcher), "-n", str(nproc), "-t", "280", sys.executable,
         str(DEMO)],
        capture_output=True, text=True, timeout=300, cwd=str(REPO),
        env=env)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    ok = [ln for ln in proc.stdout.splitlines()
          if ln.startswith("MULTIHOST-OK")]
    assert len(ok) == nproc, proc.stdout
    want = float(sum(range(1, nproc + 1)))
    for ln in ok:
        assert f"sum={want}" in ln, ln
