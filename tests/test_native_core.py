"""Native C core tests: parity against the Python implementation.

Three layers of oracles:
  1. pure-function table parity — topology math (level, last_wall,
     send_list, check_passed_origin, fwd_targets, fwd_send_cnt) must agree
     exactly with rlo_tpu.topology for every (ws, rank, origin, from);
  2. wire-format parity — C frame encode/decode interoperates byte-for-byte
     with rlo_tpu.wire.Frame;
  3. behavioral parity — bcast delivery counts, IAR decision agreement,
     callback activity, and drain termination, mirroring the reference
     integration suite (testcases.c) like the Python engine tests do.
"""

import random

import pytest

from rlo_tpu import topology
from rlo_tpu.native import bindings as nb
from rlo_tpu.wire import Frame, Tag

WORLD_SIZES = [2, 3, 4, 5, 6, 7, 8, 11, 16, 23, 32, 33]


# ---------------------------------------------------------------------------
# 1. topology parity
# ---------------------------------------------------------------------------

class TestTopologyParity:
    @pytest.mark.parametrize("ws", WORLD_SIZES)
    def test_level_last_wall_send_list(self, ws):
        for r in range(ws):
            assert nb.level(ws, r) == topology.level(ws, r)
            assert nb.last_wall(ws, r) == topology.last_wall(ws, r)
            assert nb.send_list(ws, r) == topology.send_list(ws, r)
            assert nb.initiator_targets(ws, r) == \
                topology.initiator_targets(ws, r)

    @pytest.mark.parametrize("ws", [2, 3, 5, 8, 11, 16])
    def test_check_passed_origin(self, ws):
        for me in range(ws):
            for origin in range(ws):
                for to in range(ws):
                    assert nb.check_passed_origin(ws, me, origin, to) == \
                        topology.check_passed_origin(ws, me, origin, to), \
                        (ws, me, origin, to)

    @pytest.mark.parametrize("ws", [2, 3, 5, 8, 11, 16, 23])
    def test_fwd_targets_and_cnt(self, ws):
        for rank in range(ws):
            for origin in range(ws):
                for frm in range(-1, ws):
                    assert nb.fwd_targets(ws, rank, origin, frm) == \
                        topology.fwd_targets(ws, rank, origin, frm), \
                        (ws, rank, origin, frm)
                    assert nb.fwd_send_cnt(ws, rank, origin, frm) == \
                        topology.fwd_send_cnt(ws, rank, origin, frm)


# ---------------------------------------------------------------------------
# 2. wire parity
# ---------------------------------------------------------------------------

class TestWireParity:
    @pytest.mark.parametrize("origin,pid,vote,payload", [
        (0, -1, -1, b""),
        (3, 7, 1, b"hello"),
        (31, -2, 0, bytes(range(256)) * 4),
    ])
    def test_roundtrip_matches_python(self, origin, pid, vote, payload):
        o, p, v, data, raw, s = nb.frame_roundtrip(origin, pid, vote,
                                                   payload)
        assert (o, p, v, data, s) == (origin, pid, vote, payload, -1)
        # byte-for-byte interop with the Python encoder
        assert raw == Frame(origin, pid, vote, payload).encode()
        f = Frame.decode(raw)
        assert (f.origin, f.pid, f.vote, f.payload) == \
            (origin, pid, vote, payload)

    def test_seq_field_roundtrips(self):
        # the ARQ link seq is part of the header in both encoders
        o, p, v, data, raw, s = nb.frame_roundtrip(2, 5, 1, b"q", seq=37)
        assert s == 37
        assert raw == Frame(2, 5, 1, b"q", seq=37).encode()
        assert Frame.decode(raw).seq == 37

    def test_truncated_frame_rejected(self):
        raw = Frame(1, 2, 3, b"abcdef").encode()
        import ctypes as C
        lib = nb.load()
        buf = (C.c_uint8 * len(raw)).from_buffer_copy(raw)
        assert lib.rlo_frame_decode(buf, 10, None, None, None, None,
                                    None) < 0
        assert lib.rlo_frame_decode(buf, len(raw) - 1, None, None, None,
                                    None, None) < 0


# ---------------------------------------------------------------------------
# 3. behavioral parity
# ---------------------------------------------------------------------------

def collect_all(eng):
    out = []
    while (m := eng.pickup_next()) is not None:
        out.append(m)
    return out


def build_world(ws, latency=0, seed=1, **kwargs):
    world = nb.NativeWorld(ws, latency=latency, seed=seed)
    engines = [nb.NativeEngine(world, r, **kwargs) for r in range(ws)]
    return world, engines


class TestNativeBcast:
    @pytest.mark.parametrize("ws", WORLD_SIZES)
    def test_single_root_counts(self, ws):
        with nb.NativeWorld(ws) as world:
            engines = [nb.NativeEngine(world, r) for r in range(ws)]
            cnt = 5
            root = ws // 2
            for i in range(cnt):
                engines[root].bcast(f"msg-{i}".encode())
            world.drain()
            for r, eng in enumerate(engines):
                msgs = collect_all(eng)
                if r == root:
                    assert msgs == []
                else:
                    assert len(msgs) == cnt, (ws, r)
                    assert [m.data.decode() for m in msgs] == \
                        [f"msg-{i}" for i in range(cnt)]
                    assert all(m.origin == root for m in msgs)
                    assert all(m.type == Tag.BCAST for m in msgs)
                assert eng.err == 0

    @pytest.mark.parametrize("ws", WORLD_SIZES)
    def test_every_rank_broadcasts(self, ws):
        with nb.NativeWorld(ws) as world:
            engines = [nb.NativeEngine(world, r) for r in range(ws)]
            for r in range(ws):
                engines[r].bcast(f"from-{r}".encode())
            world.drain()
            for r, eng in enumerate(engines):
                msgs = collect_all(eng)
                assert len(msgs) == ws - 1
                assert {m.data.decode() for m in msgs} == \
                    {f"from-{o}" for o in range(ws) if o != r}

    @pytest.mark.parametrize("ws,latency,seed", [
        (4, 3, 10), (7, 5, 11), (8, 4, 12), (16, 6, 13), (23, 8, 14)])
    def test_bcast_under_latency_fuzz(self, ws, latency, seed):
        with nb.NativeWorld(ws, latency=latency, seed=seed) as world:
            engines = [nb.NativeEngine(world, r) for r in range(ws)]
            for r in range(ws):
                engines[r].bcast(f"fuzz-{r}".encode())
            world.drain()
            for eng in engines:
                assert len(collect_all(eng)) == ws - 1
                assert eng.err == 0

    @pytest.mark.parametrize("ws", [4, 8, 16])
    def test_hacky_sack(self, ws):
        """All-to-all stress (testcases.c:638-697): every throw is a bcast;
        total pickups must be rounds * (ws-1)."""
        with nb.NativeWorld(ws, latency=2, seed=99) as world:
            engines = [nb.NativeEngine(world, r) for r in range(ws)]
            rng = random.Random(7)
            rounds = 20
            holder = 0
            for i in range(rounds):
                engines[holder].bcast(f"ball-{i}".encode())
                holder = rng.choice([r for r in range(ws) if r != holder])
            world.drain()
            total = sum(len(collect_all(e)) for e in engines)
            assert total == rounds * (ws - 1)

    def test_counters_match_python_semantics(self):
        with nb.NativeWorld(4) as world:
            engines = [nb.NativeEngine(world, r) for r in range(4)]
            engines[1].bcast(b"a")
            world.drain()
            assert engines[1].sent_bcast_cnt == 1
            assert sum(e.recved_bcast_cnt for e in engines) == 3
            assert world.sent_cnt == world.delivered_cnt

    def test_payload_too_large(self):
        with nb.NativeWorld(2) as world:
            e = nb.NativeEngine(world, 0)
            nb.NativeEngine(world, 1)
            with pytest.raises(ValueError):
                e.bcast(b"x" * (e.msg_size_max + 1))

    def test_world_size_one_rejected(self):
        with pytest.raises(ValueError):
            nb.NativeWorld(1)


class Ctx:
    def __init__(self, rank, veto=False):
        self.rank = rank
        self.veto = veto
        self.judged = []
        self.actions = []


def judge(payload, ctx):
    ctx.judged.append(bytes(payload))
    return 0 if ctx.veto else 1


def action(payload, ctx):
    ctx.actions.append(bytes(payload))


def build_iar(ws, veto_ranks=(), latency=0, seed=1):
    world = nb.NativeWorld(ws, latency=latency, seed=seed)
    ctxs = [Ctx(r, veto=(r in veto_ranks)) for r in range(ws)]
    engines = [nb.NativeEngine(world, r, judge_cb=judge, app_ctx=ctxs[r],
                               action_cb=action) for r in range(ws)]
    return world, engines, ctxs


def decisions_of(eng):
    return [m for m in collect_all(eng) if m.type == Tag.IAR_DECISION]


IAR_SIZES = [2, 3, 4, 5, 7, 8, 16, 23]


class TestNativeConsensus:
    @pytest.mark.parametrize("ws", IAR_SIZES)
    @pytest.mark.parametrize("proposer", [0, 1])
    def test_all_approve(self, ws, proposer):
        proposer = proposer % ws
        world, engines, ctxs = build_iar(ws)
        with world:
            engines[proposer].submit_proposal(b"prop", pid=proposer)
            world.drain()
            assert engines[proposer].vote_my_proposal() == 1
            assert engines[proposer].check_proposal_state() == nb.COMPLETED
            for r in range(ws):
                if r == proposer:
                    continue
                assert ctxs[r].judged == [b"prop"]
                assert ctxs[r].actions == [b"prop"]
                ds = decisions_of(engines[r])
                assert len(ds) == 1 and ds[0].vote == 1
                assert ds[0].pid == proposer

    @pytest.mark.parametrize("ws", IAR_SIZES)
    def test_one_veto_declines(self, ws):
        world, engines, ctxs = build_iar(ws, veto_ranks={ws - 1})
        with world:
            engines[0].submit_proposal(b"prop", pid=0)
            world.drain()
            assert engines[0].vote_my_proposal() == 0
            for r in range(1, ws):
                ds = decisions_of(engines[r])
                assert len(ds) == 1 and ds[0].vote == 0
                assert ctxs[r].actions == []

    @pytest.mark.parametrize("ws", [4, 8, 16])
    def test_proposer_self_veto_via_rejudge(self, ws):
        world, engines, ctxs = build_iar(ws)
        with world:
            ctxs[0].veto = True  # app state changes before votes return
            engines[0].submit_proposal(b"prop", pid=0)
            world.drain()
            assert engines[0].vote_my_proposal() == 0

    @pytest.mark.parametrize("ws,latency,seed", [
        (5, 4, 21), (8, 3, 22), (16, 6, 23)])
    def test_under_latency_fuzz(self, ws, latency, seed):
        world, engines, ctxs = build_iar(ws, latency=latency, seed=seed)
        with world:
            engines[ws // 2].submit_proposal(b"p", pid=ws // 2)
            world.drain()
            assert engines[ws // 2].vote_my_proposal() == 1

    @pytest.mark.parametrize("ws", [4, 8, 16, 23])
    def test_two_proposers_consistent(self, ws):
        """Two simultaneous proposers with distinct pids: both complete,
        every other rank sees both decisions (testcases.c:401-486)."""
        world, engines, ctxs = build_iar(ws, latency=2, seed=31)
        with world:
            a, b = 0, ws // 2
            engines[a].submit_proposal(b"A", pid=a)
            engines[b].submit_proposal(b"B", pid=b)
            world.drain()
            assert engines[a].vote_my_proposal() == 1
            assert engines[b].vote_my_proposal() == 1
            for r in range(ws):
                ds = decisions_of(engines[r])
                expect = sum(1 for p in (a, b) if p != r)
                assert len(ds) == expect, (r, ds)
                assert all(d.vote == 1 for d in ds)
                assert all(e.err == 0 for e in engines)

    def test_busy_proposer_rejected(self):
        # latency keeps the first proposal in flight
        world, engines, ctxs = build_iar(4, latency=50, seed=3)
        with world:
            engines[0].submit_proposal(b"one", pid=0)
            if engines[0].check_proposal_state() == nb.IN_PROGRESS:
                with pytest.raises(RuntimeError):
                    engines[0].submit_proposal(b"two", pid=100)
            world.drain()

    def test_proposal_reset_allows_reuse(self):
        world, engines, ctxs = build_iar(4)
        with world:
            assert engines[0].submit_proposal(b"one", pid=0) in (-1, 1)
            world.drain()
            assert engines[0].vote_my_proposal() == 1
            engines[0].proposal_reset()
            engines[0].submit_proposal(b"two", pid=10)
            world.drain()
            assert engines[0].vote_my_proposal() == 1
            # second round delivered on every other rank too
            for r in range(1, 4):
                ds = decisions_of(engines[r])
                assert [d.pid for d in ds] == [0, 10]


class TestEngineMultiplex:
    @pytest.mark.parametrize("ws", [4, 8])
    def test_two_comms_isolated(self, ws):
        """Two engines per rank on different comm ids (the analogue of the
        reference's two engines over dup'ed comms, testcases.c:110-241):
        traffic must not cross."""
        with nb.NativeWorld(ws, latency=1, seed=5) as world:
            ea = [nb.NativeEngine(world, r, comm=0) for r in range(ws)]
            eb = [nb.NativeEngine(world, r, comm=1) for r in range(ws)]
            ea[0].bcast(b"on-comm-0")
            eb[1].bcast(b"on-comm-1")
            world.drain()
            for r in range(ws):
                ma = collect_all(ea[r])
                mb = collect_all(eb[r])
                if r != 0:
                    assert [m.data for m in ma] == [b"on-comm-0"]
                else:
                    assert ma == []
                if r != 1:
                    assert [m.data for m in mb] == [b"on-comm-1"]
                else:
                    assert mb == []


class TestCrossImplementation:
    """Run the same scenario on the Python engine and the C engine; compare
    delivery outcomes exactly."""

    @pytest.mark.parametrize("ws,latency,seed", [
        (5, 0, 1), (8, 3, 42), (11, 5, 7), (16, 2, 9)])
    def test_bcast_outcomes_match(self, ws, latency, seed):
        from rlo_tpu.engine import ProgressEngine, EngineManager, drain
        from rlo_tpu.transport import make_world

        # python side
        pw = make_world("loopback", ws, latency=latency, seed=seed)
        mgr = EngineManager()
        pes = [ProgressEngine(pw.transport(r), manager=mgr)
               for r in range(ws)]
        for r in range(ws):
            pes[r].bcast(f"x-{r}".encode())
        drain([pw], pes)
        py_out = [sorted(m.data for m in collect_all(e)) for e in pes]

        # native side
        with nb.NativeWorld(ws, latency=latency, seed=seed + 1) as world:
            nes = [nb.NativeEngine(world, r) for r in range(ws)]
            for r in range(ws):
                nes[r].bcast(f"x-{r}".encode())
            world.drain()
            nat_out = [sorted(m.data for m in collect_all(e)) for e in nes]

        assert py_out == nat_out

    @pytest.mark.parametrize("ws", [4, 8, 23])
    @pytest.mark.parametrize("veto", [(), (2,)])
    def test_consensus_outcomes_match(self, ws, veto):
        from rlo_tpu.engine import ProgressEngine, EngineManager, drain
        from rlo_tpu.transport import make_world

        veto = tuple(v for v in veto if v < ws)

        pw = make_world("loopback", ws)
        mgr = EngineManager()
        pcs = [Ctx(r, veto=(r in veto)) for r in range(ws)]
        pes = [ProgressEngine(pw.transport(r), judge_cb=judge,
                              app_ctx=pcs[r], action_cb=action, manager=mgr)
               for r in range(ws)]
        pes[0].submit_proposal(b"prop", pid=0)
        drain([pw], pes)
        py_vote = pes[0].vote_my_proposal()
        py_actions = [len(c.actions) for c in pcs]

        world, nes, ncs = build_iar(ws, veto_ranks=veto)
        with world:
            nes[0].submit_proposal(b"prop", pid=0)
            world.drain()
            nat_vote = nes[0].vote_my_proposal()
            nat_actions = [len(c.actions) for c in ncs]

        assert py_vote == nat_vote
        assert py_actions == nat_actions


class TestUtils:
    def test_now_usec_monotonicish(self):
        a = nb.now_usec()
        b = nb.now_usec()
        assert b >= a > 1_000_000_000_000  # after 2001 in usec

    def test_consume_retires_peeked_message_not_new_head(self):
        """rlo_pickup_consume must retire exactly the peeked message even
        if progress ran in between and a newer message became the
        delivery-queue head (it would otherwise be swallowed unseen)."""
        lib = nb.load()
        import ctypes as C
        with nb.NativeWorld(4) as w:
            engines = [nb.NativeEngine(w, r) for r in range(4)]
            engines[0].bcast(b"first")
            w.drain()
            e3 = engines[3]
            tag = C.c_int()
            origin = C.c_int()
            pid = C.c_int()
            vote = C.c_int()
            payload = C.POINTER(C.c_uint8)()
            n = lib.rlo_pickup_peek(e3._e, C.byref(tag), C.byref(origin),
                                    C.byref(pid), C.byref(vote),
                                    C.byref(payload))
            assert n == 5 and C.string_at(payload, 5) == b"first"
            # a second broadcast lands between peek and consume
            engines[1].bcast(b"second")
            w.drain()
            assert lib.rlo_pickup_consume(e3._e) == 0
            # the second message must still be delivered intact
            msg = e3.pickup_next()
            assert msg is not None and msg.data == b"second"
            assert e3.pickup_next() is None
            # consume with no pending peek is an error
            assert lib.rlo_pickup_consume(e3._e) < 0

    def test_peer_alive_loopback_always_true(self):
        # the in-process loopback transport has no liveness signal: peers
        # share the process and cannot die independently; out-of-range
        # ranks are dead by definition. The shm transport's real
        # heartbeat-staleness path is exercised by the demo binary's
        # `fail` case (tests/test_shm_demo.py::test_failure_detection).
        with nb.NativeWorld(4) as w:
            assert all(w.peer_alive(r, timeout_usec=1) for r in range(4))
            assert not w.peer_alive(4)
            assert not w.peer_alive(-1)
