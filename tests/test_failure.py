"""Failure detection + elastic recovery + fault injection.

The reference has NO failure handling: RLO_FAILED exists in the status
enum (/root/reference/rootless_ops.h:66) but is never assigned, and there
are no timeouts, retries, or rank-failure paths (SURVEY.md §5). This is
the net-new subsystem's test suite: ring-heartbeat liveness detection,
rootless FAILURE notification over the broadcast overlay, elastic
re-forming of the survivor topology, and the loopback transport's fault
injection (rank kill, message drop).
"""

import pytest

from rlo_tpu.engine import EngineManager, ProgressEngine, drain
from rlo_tpu.transport.loopback import LoopbackWorld
from rlo_tpu.wire import Tag


class FakeClock:
    """Deterministic injectable clock shared by every engine."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def make_world(ws, timeout=8.0, interval=1.0, **kw):
    clock = FakeClock()
    world = LoopbackWorld(ws)
    mgr = EngineManager()
    notices = []
    engines = [
        ProgressEngine(world.transport(r), manager=mgr,
                       failure_timeout=timeout,
                       heartbeat_interval=interval,
                       failure_cb=lambda rank, local, r=r: notices.append(
                           (r, rank, local)),
                       clock=clock, **kw)
        for r in range(ws)
    ]
    return world, mgr, engines, clock, notices


def kill(world, mgr, engines, rank):
    """Fault injection: the rank's process dies."""
    world.kill_rank(rank)
    engines[rank].cleanup()  # a dead process stops turning its gears


def spin(mgr, clock, ticks, dt=0.5):
    for _ in range(ticks):
        clock.advance(dt)
        mgr.progress_all()


# ---------------------------------------------------------------------------
# Transport-level fault injection
# ---------------------------------------------------------------------------

class TestInjection:
    def test_kill_blackholes_traffic(self):
        world = LoopbackWorld(4)
        world.kill_rank(2)
        h = world.transport(0).isend(2, int(Tag.BCAST), b"x")
        assert h.done() and h.failed
        h2 = world.transport(2).isend(0, int(Tag.BCAST), b"y")
        assert h2.done() and h2.failed
        assert world.transport(2).poll() is None
        assert world.transport(0).poll() is None  # nothing arrived
        assert world.quiescent()

    def test_kill_drops_in_flight(self):
        world = LoopbackWorld(4, latency=10, seed=3)
        h = world.transport(0).isend(3, int(Tag.BCAST), b"x")
        assert not h.done()
        world.kill_rank(3)
        assert h.done() and h.failed
        assert world.quiescent()

    def test_drop_next(self):
        world = LoopbackWorld(2)
        world.drop_next(0, 1, count=1)
        h = world.transport(0).isend(1, int(Tag.BCAST), b"lost")
        assert h.done() and h.failed
        world.transport(0).isend(1, int(Tag.BCAST), b"kept")
        src, tag, data = world.transport(1).poll()
        assert data == b"kept" and world.dropped_cnt == 1


# ---------------------------------------------------------------------------
# Chaos: kills injected MID-TRAFFIC under seeded latency/reordering.
# The view-change window is documented best-effort (duplicates/drops of
# in-flight messages are allowed) — what must ALWAYS hold is liveness:
# no exception, every survivor converges to the same failed set, and the
# overlay works for traffic initiated after the view settles.
# ---------------------------------------------------------------------------

class TestChaos:
    # a 60-seed sweep of this scenario drove the anti-starvation
    # hardening (any frame counts as liveness; FAILURE notices flood
    # with duplicate suppression) — before it, 21/60 seeds cascaded
    # into false-positive meshes of mutual death declarations
    @pytest.mark.parametrize("seed", list(range(1, 13)))
    def test_kill_mid_broadcast_storm(self, seed):
        import random
        ws = 8
        clock = FakeClock()
        world = LoopbackWorld(ws, latency=3, seed=seed)
        mgr = EngineManager()
        engines = [ProgressEngine(world.transport(r), manager=mgr,
                                  failure_timeout=8.0,
                                  heartbeat_interval=1.0, clock=clock)
                   for r in range(ws)]
        rng = random.Random(seed)
        victims = rng.sample(range(ws), 2)
        alive = [r for r in range(ws) if r not in victims]
        # storm: every rank broadcasts repeatedly while the victims die
        # at staggered points mid-traffic
        for step in range(30):
            for r in range(ws):
                if r not in world.dead:
                    engines[r].bcast(f"s{step}r{r}".encode())
            if step == 7:
                world.kill_rank(victims[0])
                engines[victims[0]].cleanup()
            if step == 15:
                world.kill_rank(victims[1])
                engines[victims[1]].cleanup()
            clock.advance(0.7)
            mgr.progress_all()
        spin(mgr, clock, 80)  # let detection + notices settle
        survivors = [engines[r] for r in alive]
        assert all(e.failed == set(victims) for e in survivors), \
            [(e.rank, e.failed) for e in survivors]
        # engines remain responsive: drain the storm debris, then one
        # clean broadcast delivers exactly once everywhere
        drain([world], survivors)
        for e in survivors:
            while e.pickup_next() is not None:
                pass
        origin = alive[0]
        engines[origin].bcast(b"post-chaos")
        drain([world], survivors)
        for e in survivors:
            if e.rank == origin:
                continue
            msgs = []
            while (m := e.pickup_next()) is not None:
                msgs.append(m.data)
            assert msgs == [b"post-chaos"], (e.rank, msgs)
        # and consensus still completes among the survivors
        engines[origin].submit_proposal(b"post", pid=origin)
        for _ in range(50_000):
            mgr.progress_all()
            if engines[origin].vote_my_proposal() != -1:
                break
        assert engines[origin].vote_my_proposal() == 1

    @pytest.mark.parametrize("seed", list(range(1, 13)))
    def test_consensus_relay_killed_mid_round(self, seed):
        """A consensus relay dies somewhere in the middle of the round
        (between proposal fan-out and decision settlement) under
        latency fuzz. The round-3 contract under this chaos: every
        SURVIVOR eventually clears its pending queue (the proposer
        discounts the dead subtree; parked parent-died rounds are
        cleared by the decision, which survives the relay's death via
        the decision re-flood) and survivors that saw the decision
        agree on it. A stuck pending round would also wedge engine
        snapshots — the regression the review feared."""
        import random
        ws = 8
        clock = FakeClock()
        world = LoopbackWorld(ws, latency=3, seed=seed)
        mgr = EngineManager()
        engines = [ProgressEngine(world.transport(r), manager=mgr,
                                  failure_timeout=8.0,
                                  heartbeat_interval=1.0, clock=clock)
                   for r in range(ws)]
        rng = random.Random(seed)
        victim = rng.choice(range(1, ws))  # never the proposer
        kill_at = rng.randint(1, 6)
        engines[0].submit_proposal(b"chaos-round", pid=0)
        for step in range(40):
            if step == kill_at:
                world.kill_rank(victim)
                engines[victim].cleanup()
            clock.advance(0.7)
            mgr.progress_all()
        spin(mgr, clock, 120)
        survivors = [e for e in engines if e.rank != victim]
        drain([world], survivors)
        # proposer's round resolved (either verdict is legitimate
        # depending on where the kill landed; it must not hang)
        assert engines[0].vote_my_proposal() in (0, 1)
        decision = engines[0].vote_my_proposal()
        # no survivor left with a parked round: consensus state fully
        # settled (this is what keeps checkpointing possible)
        for e in survivors:
            assert not e.queue_iar_pending, (
                f"rank {e.rank} stuck with parked rounds "
                f"{[(m.frame.pid, m.prop_state and m.prop_state.gen) for m in e.queue_iar_pending]}")
        # survivors that delivered the decision agree with the proposer
        for e in survivors:
            if e.rank == 0:
                continue
            ds = []
            while (m := e.pickup_next()) is not None:
                if m.type == int(Tag.IAR_DECISION):
                    ds.append(m.vote)
            assert len(ds) <= 1, (e.rank, ds)
            if ds:
                assert ds[0] == decision, (e.rank, ds, decision)

    @pytest.mark.parametrize("seed", list(range(1, 13)))
    def test_exactly_once_across_view_change(self, seed):
        """Traffic initiated by SURVIVORS before the kill must deliver
        exactly once at every other survivor, even when its forwarding
        crosses the membership change: (origin, seq) dedup makes twice
        impossible, the view-change re-flood makes zero impossible.
        Victim-initiated traffic is at-most-once (a frame the dead
        origin never handed a survivor has no copy left to re-flood)."""
        import random
        from collections import Counter
        ws = 8
        clock = FakeClock()
        world = LoopbackWorld(ws, latency=4, seed=seed)
        mgr = EngineManager()
        engines = [ProgressEngine(world.transport(r), manager=mgr,
                                  failure_timeout=8.0,
                                  heartbeat_interval=1.0, clock=clock)
                   for r in range(ws)]
        rng = random.Random(seed)
        victim = rng.randrange(ws)
        alive = [r for r in range(ws) if r != victim]
        sent_by_survivors = []
        # burst of pre-kill traffic from every rank, then the kill lands
        # while much of it is still in flight (latency=4)
        for step in range(6):
            for r in range(ws):
                payload = f"pre{step}r{r}".encode()
                engines[r].bcast(payload)
                if r != victim:
                    sent_by_survivors.append(payload)
        world.kill_rank(victim)
        engines[victim].cleanup()
        spin(mgr, clock, 120)  # detection + re-flood + settle
        survivors = [engines[r] for r in alive]
        assert all(e.failed == {victim} for e in survivors)
        drain([world], survivors)
        got = {e.rank: Counter() for e in survivors}
        for e in survivors:
            while (m := e.pickup_next()) is not None:
                if m.type == int(Tag.BCAST):
                    got[e.rank][m.data] += 1
        for e in survivors:
            for payload in sent_by_survivors:
                origin = int(payload.decode().rsplit("r", 1)[1])
                want = 0 if e.rank == origin else 1
                assert got[e.rank][payload] == want, (
                    seed, e.rank, payload, got[e.rank][payload])
            # victim-initiated: at most once
            for payload, n in got[e.rank].items():
                assert n == 1, (seed, e.rank, payload, n)


# ---------------------------------------------------------------------------
# Native (C) engine parity: same detect / re-form / recover behavior
# ---------------------------------------------------------------------------

class TestNativeParity:
    def test_c_engines_detect_and_recover(self):
        """The C core's failure machinery behaves like the Python
        engine's: kill a rank, survivors detect by heartbeat timeout,
        the overlay re-forms, and bcast + consensus keep working."""
        import time
        from rlo_tpu.native.bindings import NativeEngine, NativeWorld

        ws, victim = 6, 2
        with NativeWorld(ws) as world:
            engines = [NativeEngine(world, r) for r in range(ws)]
            for e in engines:
                e.enable_failure_detection(timeout_usec=200_000,
                                           interval_usec=40_000)
            t0 = time.monotonic()
            while time.monotonic() - t0 < 0.3:
                world.progress_all()
            world.kill_rank(victim)
            engines[victim].close()
            t0 = time.monotonic()
            while time.monotonic() - t0 < 3.0:
                world.progress_all()
                if all(e.rank_failed(victim) for r, e in enumerate(engines)
                       if r != victim):
                    break
            survivors = [e for r, e in enumerate(engines) if r != victim]
            assert all(e.rank_failed(victim) for e in survivors)
            world.drain()
            for e in survivors:
                while e.pickup_next() is not None:
                    pass
            engines[0].bcast(b"after-failure")
            world.drain()
            for e in survivors[1:]:
                msgs = []
                while (m := e.pickup_next()) is not None:
                    msgs.append(m.data)
                assert msgs == [b"after-failure"], (e.rank, msgs)
            rc = engines[0].submit_proposal(b"p", pid=9)
            t0 = time.monotonic()
            while rc == -1 and time.monotonic() - t0 < 3.0:
                world.progress_all()
                rc = engines[0].vote_my_proposal()
            assert rc == 1
            world.drain()

    @pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
    def test_c_exactly_once_across_view_change(self, seed):
        """C-engine mirror of test_exactly_once_across_view_change:
        survivor-initiated broadcasts in flight across the kill must
        deliver exactly once at every other survivor ((origin, seq)
        dedup + view-change re-flood); victim-initiated at most once."""
        import random
        import time
        from collections import Counter
        from rlo_tpu.native.bindings import NativeEngine, NativeWorld

        ws = 8
        rng = random.Random(seed)
        victim = rng.randrange(ws)
        with NativeWorld(ws, latency=4, seed=seed) as world:
            engines = [NativeEngine(world, r) for r in range(ws)]
            for e in engines:
                e.enable_failure_detection(timeout_usec=200_000,
                                           interval_usec=40_000)
            t0 = time.monotonic()
            while time.monotonic() - t0 < 0.3:
                world.progress_all()
            sent_by_survivors = []
            for step in range(6):
                for r in range(ws):
                    payload = f"pre{step}r{r}".encode()
                    engines[r].bcast(payload)
                    if r != victim:
                        sent_by_survivors.append(payload)
            world.kill_rank(victim)
            engines[victim].close()
            t0 = time.monotonic()
            survivors = [e for r, e in enumerate(engines) if r != victim]
            while time.monotonic() - t0 < 3.0:
                world.progress_all()
                if all(e.rank_failed(victim) for e in survivors):
                    break
            assert all(e.rank_failed(victim) for e in survivors)
            world.drain()
            for e in survivors:
                got = Counter()
                while (m := e.pickup_next()) is not None:
                    if m.type == int(Tag.BCAST):
                        got[m.data] += 1
                for payload in sent_by_survivors:
                    origin = int(payload.decode().rsplit("r", 1)[1])
                    want = 0 if e.rank == origin else 1
                    assert got[payload] == want, (
                        seed, e.rank, payload, got[payload])
                for payload, n in got.items():
                    assert n == 1, (seed, e.rank, payload, n)


# ---------------------------------------------------------------------------
# Detection
# ---------------------------------------------------------------------------

class TestDetection:
    def test_no_false_positive_while_healthy(self):
        world, mgr, engines, clock, notices = make_world(5)
        spin(mgr, clock, 100)  # 50 time units >> timeout, but all alive
        assert notices == []
        assert all(not e.failed for e in engines)

    def test_successor_detects_and_world_learns(self):
        ws = 6
        world, mgr, engines, clock, notices = make_world(ws)
        spin(mgr, clock, 8)  # establish heartbeats
        kill(world, mgr, engines, 2)
        spin(mgr, clock, 40)
        survivors = [e for e in engines if e.rank != 2]
        assert all(e.failed == {2} for e in survivors)
        # rank 3 (ring successor of 2) detected locally; others learned
        local = {r for (r, rank, loc) in notices if loc and rank == 2}
        assert local == {3}
        learned = {r for (r, rank, loc) in notices if not loc and rank == 2}
        assert learned == {0, 1, 4, 5}

    def test_failure_notice_delivered_to_user(self):
        ws = 4
        world, mgr, engines, clock, notices = make_world(ws)
        spin(mgr, clock, 8)
        kill(world, mgr, engines, 1)
        spin(mgr, clock, 40)
        detector = 2  # ring successor of the dead rank
        for e in engines:
            if e.rank == 1:
                continue
            got = []
            while True:
                m = e.pickup_next()
                if m is None:
                    break
                got.append(m)
            fails = [m for m in got if m.type == int(Tag.FAILURE)]
            if e.rank == detector:
                # the detector initiated the notice; like any broadcast
                # initiator it does not deliver its own message — it
                # already saw the failure through failure_cb
                assert fails == []
            else:
                assert len(fails) == 1 and fails[0].pid == 1

    def test_callback_fires_once_per_failure(self):
        ws = 5
        world, mgr, engines, clock, notices = make_world(ws)
        spin(mgr, clock, 8)
        kill(world, mgr, engines, 4)
        spin(mgr, clock, 60)
        per_rank = {}
        for (r, rank, _) in notices:
            per_rank[(r, rank)] = per_rank.get((r, rank), 0) + 1
        assert all(v == 1 for v in per_rank.values())

    def test_detection_disabled_by_default(self):
        world = LoopbackWorld(3)
        mgr = EngineManager()
        engines = [ProgressEngine(world.transport(r), manager=mgr)
                   for r in range(3)]
        for _ in range(50):
            mgr.progress_all()
        assert all(e.failed == set() for e in engines)
        assert world.sent_cnt == 0  # no heartbeat traffic


# ---------------------------------------------------------------------------
# Elastic recovery: the survivor overlay keeps working
# ---------------------------------------------------------------------------

class TestElasticRecovery:
    @pytest.mark.parametrize("ws,dead", [(4, 1), (6, 0), (7, 3), (8, 7)])
    def test_bcast_among_survivors(self, ws, dead):
        world, mgr, engines, clock, _ = make_world(ws)
        spin(mgr, clock, 8)
        kill(world, mgr, engines, dead)
        spin(mgr, clock, 60)
        survivors = [e for e in engines if e.rank != dead]
        for e in survivors:  # flush FAILURE notices
            while e.pickup_next() is not None:
                pass
        origin = survivors[0].rank
        engines[origin].bcast(b"after-failure")
        drain([world], survivors)
        for e in survivors:
            if e.rank == origin:
                continue
            msgs = []
            while True:
                m = e.pickup_next()
                if m is None:
                    break
                msgs.append(m)
            assert [m.data for m in msgs] == [b"after-failure"], \
                f"rank {e.rank} got {msgs}"

    def test_consensus_among_survivors(self):
        ws = 6
        world, mgr, engines, clock, _ = make_world(ws)
        spin(mgr, clock, 8)
        kill(world, mgr, engines, 5)
        spin(mgr, clock, 60)
        survivors = [e for e in engines if e.rank != 5]
        for e in survivors:
            while e.pickup_next() is not None:
                pass
        engines[0].submit_proposal(b"p", pid=0)
        for _ in range(10_000):
            mgr.progress_all()
            if engines[0].vote_my_proposal() != -1:
                break
        assert engines[0].vote_my_proposal() == 1
        drain([world], survivors)

    def test_sequential_double_failure(self):
        ws = 8
        world, mgr, engines, clock, _ = make_world(ws)
        spin(mgr, clock, 8)
        kill(world, mgr, engines, 3)
        spin(mgr, clock, 60)
        kill(world, mgr, engines, 6)
        spin(mgr, clock, 60)
        survivors = [e for e in engines if e.rank not in (3, 6)]
        assert all(e.failed == {3, 6} for e in survivors)
        for e in survivors:
            while e.pickup_next() is not None:
                pass
        engines[1].bcast(b"two-down")
        drain([world], survivors)
        for e in survivors:
            if e.rank == 1:
                continue
            m = e.pickup_next()
            assert m is not None and m.data == b"two-down"
            assert e.pickup_next() is None

    @pytest.mark.parametrize("ws,victim", [(6, 4), (8, 2), (5, 1)])
    def test_consensus_completes_when_voter_dies_mid_round(self, ws,
                                                           victim):
        """A participant dies after the proposal went out but before its
        subtree voted: detection must discount the dead subtree so the
        round completes instead of waiting forever (a dead rank cannot
        veto)."""
        world, mgr, engines, clock, _ = make_world(ws)
        spin(mgr, clock, 8)
        # crash the victim, then immediately propose — before detection,
        # so the proposal's vote accounting still counts the dead subtree
        kill(world, mgr, engines, victim)
        proposer = 0
        rc = engines[proposer].submit_proposal(b"mid-round", pid=0)
        assert rc == -1  # cannot complete: a vote will never arrive
        spin(mgr, clock, 80)
        assert engines[proposer].vote_my_proposal() == 1
        survivors = [e for e in engines if e.rank != victim]
        drain([world], survivors)
        # and the engine is free for the next round
        engines[proposer].submit_proposal(b"next", pid=1)
        for _ in range(10_000):
            mgr.progress_all()
            if engines[proposer].vote_my_proposal() != -1:
                break
        assert engines[proposer].vote_my_proposal() == 1

    def test_false_positive_vote_cannot_mask_live_veto(self):
        """A falsely-suspected child's vote arriving after it was
        discounted must not complete the round while a live child's veto
        is outstanding — and the late vote must not crash the engine."""
        import struct
        from rlo_tpu.engine import EngineManager, ProgressEngine
        from rlo_tpu.wire import Frame, Tag
        world = LoopbackWorld(4)
        mgr_p, mgr_o = EngineManager(), EngineManager()
        # the [1, 2] await set below is the skip-ring schedule's — pin
        # it so the suite also passes under RLO_FANOUT=flat
        proposer = ProgressEngine(world.transport(0), manager=mgr_p,
                                  failure_timeout=1e9,  # no auto detection
                                  clock=lambda: 0.0,
                                  fanout="skip_ring")
        _others = [ProgressEngine(world.transport(r), manager=mgr_o,
                                  fanout="skip_ring")
                   for r in range(1, 4)]
        assert proposer.submit_proposal(b"p", pid=0) == -1
        assert sorted(proposer.my_own_proposal.await_from) == [1, 2]
        gen = struct.pack("<i", proposer.my_own_proposal.gen)
        # a FAILURE notice about rank 2 (actually alive) discounts it
        proposer._mark_failed(2)
        assert proposer.my_own_proposal.votes_needed == 1
        # rank 2's in-flight YES arrives anyway: must NOT complete
        world.transport(2).isend(
            0, int(Tag.IAR_VOTE),
            Frame(origin=2, pid=0, vote=1, payload=gen).encode())
        mgr_p.progress_all()
        assert proposer.vote_my_proposal() == -1
        # rank 1's veto decides the round
        world.transport(1).isend(
            0, int(Tag.IAR_VOTE),
            Frame(origin=1, pid=0, vote=0, payload=gen).encode())
        mgr_p.progress_all()
        assert proposer.vote_my_proposal() == 0
        # another stray late vote is dropped, not a RuntimeError
        world.transport(2).isend(
            0, int(Tag.IAR_VOTE),
            Frame(origin=2, pid=0, vote=1, payload=gen).encode())
        mgr_p.progress_all()
        # and a stale-generation vote is ignored outright
        world.transport(1).isend(
            0, int(Tag.IAR_VOTE),
            Frame(origin=1, pid=0, vote=1,
                  payload=struct.pack("<i", 12345)).encode())
        mgr_p.progress_all()

    def test_dead_proposer_unparks_relayed_proposals(self):
        """When the proposer dies mid-round, survivors must abort the
        relayed proposal (state FAILED, unparked) so they stay
        checkpointable and the pid is freed."""
        from rlo_tpu.engine import EngineManager, ProgressEngine, ReqState
        from rlo_tpu.utils import checkpoint as ck
        clock = FakeClock()
        world = LoopbackWorld(4)
        mgr_p, mgr_o = EngineManager(), EngineManager()
        proposer = ProgressEngine(world.transport(0), manager=mgr_p)
        others = [ProgressEngine(world.transport(r), manager=mgr_o,
                                 failure_timeout=8.0,
                                 heartbeat_interval=1.0, clock=clock)
                  for r in range(1, 4)]
        proposer.submit_proposal(b"p", pid=0)
        world.kill_rank(0)
        proposer.cleanup()
        for _ in range(10):  # others receive + park + vote (blackholed)
            mgr_o.progress_all()
        parked = [e for e in others if e.queue_iar_pending]
        assert parked, "no survivor parked the relayed proposal"
        states = [pm.prop_state for e in others
                  for pm in e.queue_iar_pending]
        for _ in range(60):  # heartbeat detection of the dead proposer
            clock.advance(0.5)
            mgr_o.progress_all()
        assert all(e.failed == {0} for e in others)
        assert all(not e.queue_iar_pending for e in others)
        assert all(ps.state == ReqState.FAILED for ps in states)
        for e in others:
            while e.pickup_next() is not None:
                pass
            ck.engine_state_dict(e)  # checkpointable again

    def test_learned_failure_does_not_rearm_pred_timer(self):
        """A learned failure elsewhere must not reset the heartbeat grace
        of an unchanged predecessor (correlated failures would otherwise
        defer detection indefinitely)."""
        world, mgr, engines, clock, _ = make_world(6)
        spin(mgr, clock, 8)
        e3 = engines[3]
        before = e3._hb_seen[2]  # rank 3 watches rank 2
        e3._mark_failed(5)       # unrelated learned failure
        assert e3._hb_seen[2] == before
        e3._mark_failed(2)       # pred dies -> new pred gets fresh grace
        assert e3._hb_seen[1] == clock()

    def test_sole_survivor_consensus_completes(self):
        """A proposal with zero awaited voters (everyone else died) must
        complete immediately instead of polling -1 forever."""
        world, mgr, engines, clock, _ = make_world(2)
        spin(mgr, clock, 8)
        kill(world, mgr, engines, 1)
        spin(mgr, clock, 60)
        assert engines[0].failed == {1}
        rc = engines[0].submit_proposal(b"alone", pid=0)
        if rc == -1:
            for _ in range(1000):
                mgr.progress_all()
                if engines[0].vote_my_proposal() != -1:
                    break
        assert engines[0].vote_my_proposal() == 1

    def test_adjacent_failure_shifts_monitor(self):
        """Kill the detector's own predecessor twice over: after rank 2
        dies, rank 3 watches rank 1; killing rank 1 must then be detected
        by rank 3 as well."""
        ws = 5
        world, mgr, engines, clock, notices = make_world(ws)
        spin(mgr, clock, 8)
        kill(world, mgr, engines, 2)
        spin(mgr, clock, 60)
        kill(world, mgr, engines, 1)
        spin(mgr, clock, 60)
        local = {(r, rank) for (r, rank, loc) in notices if loc}
        assert (3, 2) in local and (3, 1) in local
        survivors = [e for e in engines if e.rank in (0, 3, 4)]
        assert all(e.failed == {1, 2} for e in survivors)
