"""Metrics registry (rlo_tpu/utils/metrics.py) + engine integration.

Primitive semantics (log2 histogram layout is shared with the C core's
rlo_hist — bucket index = bit_length of the integer part), registry
snapshots, and the ProgressEngine metrics surface: per-link accounting
symmetric across a healthy run, RTT EWMA measured from ARQ ack timing,
ARQ counters folded into the snapshot while the PR-1 attribute aliases
stay live, heartbeat-age-carrying FAILURE events, and the structured
warning on a local failure declaration.
"""

import logging

import pytest

from rlo_tpu.engine import EngineManager, ProgressEngine, drain
from rlo_tpu.transport.loopback import LoopbackWorld
from rlo_tpu.utils.metrics import (HIST_BUCKETS, Counter, Gauge, Histogram,
                                   LinkStats, Registry, hist_quantile)
from rlo_tpu.utils.tracing import TRACER, Ev


class TestPrimitives:
    def test_counter_gauge(self):
        c, g = Counter(), Gauge()
        c.inc()
        c.inc(4)
        g.set(7)
        g.set(3)
        assert c.value == 5 and g.value == 3

    def test_histogram_buckets_are_log2(self):
        h = Histogram()
        assert Histogram.bucket_index(0) == 0
        assert Histogram.bucket_index(1) == 1
        assert Histogram.bucket_index(2) == 2
        assert Histogram.bucket_index(3) == 2
        assert Histogram.bucket_index(1024) == 11
        assert Histogram.bucket_index(2 ** 40) == HIST_BUCKETS - 1
        for v in (0, 1, 3, 1024, 2.5e6):
            h.observe(v)
        s = h.snapshot()
        assert s["count"] == 5
        assert s["min"] == 0 and s["max"] == 2.5e6
        assert s["sum"] == pytest.approx(2.5e6 + 1028)
        assert sum(s["buckets"]) == 5

    def test_histogram_bucket_placement(self):
        h = Histogram()
        h.observe(2)
        h.observe(3)
        assert h.buckets[2] == 2  # [2, 4) is bucket 2 (bit_length 2)

    def test_quantile_from_snapshot(self):
        h = Histogram()
        for v in [1] * 90 + [1000] * 10:
            h.observe(v)
        s = h.snapshot()
        assert hist_quantile(s, 0.5) == 2.0   # bucket upper bound of 1
        assert hist_quantile(s, 0.99) == 1024.0
        assert hist_quantile({"count": 0, "buckets": []}, 0.5) is None

    def test_registry_snapshot_and_reuse(self):
        r = Registry()
        r.counter("a").inc()
        assert r.counter("a") is r.counter("a")
        r.gauge("g").set(2)
        r.histogram("h").observe(5)
        s = r.snapshot()
        assert s["counters"] == {"a": 1}
        assert s["gauges"] == {"g": 2}
        assert s["histograms"]["h"]["count"] == 1
        r.clear()
        assert r.snapshot() == {"counters": {}, "gauges": {},
                                "histograms": {}}

    def test_linkstats_rtt_ewma(self):
        ls = LinkStats()
        ls.rtt_sample(800.0)
        assert ls.rtt_ewma_usec == 800.0
        ls.rtt_sample(1600.0)  # +1/8 of the delta
        assert ls.rtt_ewma_usec == pytest.approx(900.0)


def _world(ws=4, **kw):
    world = LoopbackWorld(ws, **kw)
    mgr = EngineManager()
    engines = [ProgressEngine(world.transport(r), manager=mgr,
                              arq_rto=0.005) for r in range(ws)]
    for e in engines:
        e.enable_metrics()
    return world, engines


class TestEngineMetrics:
    def test_link_accounting_is_symmetric(self):
        """Without loss, every frame rank A accounts tx toward B shows
        up as rx at B from A — byte-exact."""
        world, engines = _world(latency=2, seed=5)
        for i in range(5):
            engines[i % 4].bcast(f"payload {i}".encode())
        drain([world], engines)
        for e in engines:
            while e.pickup_next() is not None:
                pass
        snaps = [e.metrics() for e in engines]
        for a in range(4):
            for b in range(4):
                if a == b:
                    continue
                tx = snaps[a]["links"][str(b)]
                rx = snaps[b]["links"][str(a)]
                assert tx["tx_frames"] == rx["rx_frames"]
                assert tx["tx_bytes"] == rx["rx_bytes"]
        for e in engines:
            e.cleanup()

    def test_rtt_ewma_measured_under_arq(self):
        """ARQ ack timing populates the per-link RTT EWMA on links
        that carried reliable traffic."""
        world, engines = _world(latency=2, seed=3)
        for i in range(4):
            engines[0].bcast(f"rtt {i}".encode())
        drain([world], engines)
        snap = engines[0].metrics()
        measured = [l["rtt_ewma_usec"] for l in snap["links"].values()
                    if l["tx_frames"]]
        assert measured and all(r > 0 for r in measured)
        for e in engines:
            e.cleanup()

    def test_arq_counter_aliases_and_registry_agree(self):
        """Satellite: the PR-1 ad-hoc ARQ counters are registry-backed
        now; the attribute aliases and the snapshot always agree."""
        world, engines = _world()
        world.drop_next(0, 1, 1)
        world.dup_next(0, 2, 1)
        engines[0].bcast(b"lossy")
        drain([world], engines)
        e0 = engines[0]
        snap = e0.metrics()["counters"]
        assert snap["arq_retransmits"] == e0.arq_retransmits >= 1
        assert snap["arq_gave_up"] == e0.arq_gave_up
        assert snap["arq_unacked"] == e0.arq_unacked() == 0
        dups = sum(e.metrics()["counters"]["arq_dup_drops"]
                   for e in engines)
        assert dups == sum(e.arq_dup_drops for e in engines) >= 1
        # per-link attribution: the dup drop landed on rank 2's link
        # from rank 0, the retransmit on rank 0's link toward rank 1
        assert engines[2].metrics()["links"]["0"]["dup_drops"] >= 1
        assert e0.metrics()["links"]["1"]["retransmits"] >= 1
        for e in engines:
            e.cleanup()

    def test_pickup_backlog_and_wait(self):
        """Queue-depth gauges expose the pickup backlog; draining it
        feeds the pickup-wait histogram."""
        world, engines = _world()
        engines[0].bcast(b"one")
        engines[1].bcast(b"two")
        drain([world], engines)
        s = engines[2].metrics()
        assert s["queues"]["pickup"] + s["queues"]["wait_and_pickup"] == 2
        while engines[2].pickup_next() is not None:
            pass
        s = engines[2].metrics()
        assert s["queues"]["pickup"] == 0
        assert s["op_latency_usec"]["pickup_wait"]["count"] == 2
        for e in engines:
            e.cleanup()

    def test_failure_event_carries_heartbeat_age(self, caplog):
        """Satellite: Ev.FAILURE from a local detection carries the
        last-seen heartbeat age (usec) in c, and declaration logs one
        structured warning."""
        clock = [0.0]
        world = LoopbackWorld(4)
        mgr = EngineManager()
        engines = [ProgressEngine(world.transport(r), manager=mgr,
                                  failure_timeout=1.0,
                                  clock=lambda: clock[0])
                   for r in range(4)]
        TRACER.clear()
        with TRACER.enable(), caplog.at_level(
                logging.WARNING, logger="rlo_tpu.engine"):
            for t in (0.3, 0.6, 0.9):  # heartbeats flow, all healthy
                clock[0] = t
                mgr.progress_all()
            world.kill_rank(2)
            engines[2].cleanup()  # a dead process's engine stops too
            clock[0] = 2.5  # > timeout since rank 2's last frame
            for _ in range(20):
                mgr.progress_all()
        local = [e for e in TRACER.events(Ev.FAILURE) if e.b == 1]
        assert local, "no local failure declaration"
        ev = local[0]
        assert ev.a == 2
        # age is the declared silence: > timeout, <= the full window
        assert 1.0e6 < ev.c <= 2.5e6
        warnings = [r for r in caplog.records
                    if "FAILED" in r.getMessage() and r.name ==
                    "rlo_tpu.engine"]
        assert len(warnings) == 1
        assert "rank 2" in warnings[0].getMessage()
        assert "timeout" in warnings[0].getMessage()
        TRACER.clear()
        for e in engines:
            e.cleanup()

    def test_disabled_metrics_skip_collection(self):
        """With metrics off, links stay zeroed and histograms empty
        (the one-branch disabled path), while plain counters advance."""
        world = LoopbackWorld(2)
        mgr = EngineManager()
        engines = [ProgressEngine(world.transport(r), manager=mgr)
                   for r in range(2)]
        engines[0].bcast(b"x")
        drain([world], engines)
        s = engines[0].metrics()
        assert s["counters"]["sent_bcast"] == 1
        assert all(v == 0 for l in s["links"].values()
                   for k, v in l.items())
        assert all(h["count"] == 0
                   for h in s["op_latency_usec"].values())
        for e in engines:
            e.cleanup()
