"""Smoke tests for the BASELINE-config benchmark suite.

Each config must run end-to-end at --tiny sizes and print exactly one
valid JSON line with the contract fields. Configs 2-4 set up their own
jax backend (forced CPU mesh when multi-chip is absent), so every config
runs in a subprocess, exactly as `--config all` drives them.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

SUITE = Path(__file__).resolve().parent.parent / "benchmarks" / "suite.py"
TRAIN = Path(__file__).resolve().parent.parent / "benchmarks" / "train_bench.py"


def test_decode_bench_emits_json_line():
    """The KV-cache decode benchmark must run end-to-end at --tiny
    sizes and emit one valid JSON line."""
    import os
    env = dict(os.environ,
               PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")
    bench = Path(__file__).resolve().parent.parent / "benchmarks" / \
        "decode_bench.py"
    proc = subprocess.run(
        [sys.executable, str(bench), "--tiny"],
        capture_output=True, text=True, timeout=600, env=env)
    assert proc.returncode == 0, proc.stderr
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, proc.stdout
    rec = json.loads(lines[0])
    assert rec["unit"] == "tokens/s" and rec["value"] > 0


def test_train_bench_emits_json_line():
    """The train-step MFU benchmark (round-2 VERDICT item 5) must run
    end-to-end at --tiny sizes and emit one valid JSON line."""
    import os
    env = dict(os.environ,
               PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, str(TRAIN), "--tiny"],
        capture_output=True, text=True, timeout=600, env=env)
    assert proc.returncode == 0, proc.stderr
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, proc.stdout
    rec = json.loads(lines[0])
    assert rec["unit"] == "tokens/s" and rec["value"] > 0


#: configs that emit several comparison lines (ring vs bcast-gather +
#: the MPI_Bcast leg for 1; the TPU device leg for 5 when a chip is up)
MULTI_LINE = {1: (2, 4), 5: (1, 2)}


@pytest.mark.parametrize("config", [1, 2, 3, 4, 5])
def test_config_emits_json_line(config):
    proc = subprocess.run(
        [sys.executable, str(SUITE), "--config", str(config), "--tiny"],
        capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    lo, hi = MULTI_LINE.get(config, (1, 1))
    assert lo <= len(lines) <= hi, proc.stdout
    for ln in lines:
        rec = json.loads(ln)
        assert rec["config"] == config
        assert set(rec) >= {"config", "metric", "value", "unit",
                            "vs_baseline"}
        assert rec["value"] > 0
        if rec.get("bound"):  # labeled bound: no comparison claimed
            assert rec["vs_baseline"] == 0
        else:
            assert rec["vs_baseline"] > 0


def test_native_bench_allreduce_correctness_gate():
    # the C-side harness self-verifies the reduction; a wrong result
    # raises instead of reporting a time
    from rlo_tpu.native.bindings import bench_allreduce
    t = bench_allreduce(4, 1024, reps=3)
    assert t > 0


def test_spec_bench_emits_json_line():
    """The speculative-decoding infra bench must run end-to-end at
    --tiny sizes and emit one valid JSON line."""
    import os
    env = dict(os.environ,
               PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")
    bench = Path(__file__).resolve().parent.parent / "benchmarks" / \
        "spec_bench.py"
    proc = subprocess.run(
        [sys.executable, str(bench), "--tiny"],
        capture_output=True, text=True, timeout=600, env=env)
    assert proc.returncode == 0, proc.stderr
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, proc.stdout
    rec = json.loads(lines[0])
    assert rec["unit"] == "x" and rec["value"] > 0
