"""Test configuration: force an 8-device virtual CPU mesh.

The container's sitecustomize registers a single-chip TPU ("axon") backend at
interpreter startup, so jax is already imported by the time pytest runs. JAX
backends initialize lazily, which lets us still retarget to CPU here — this
must happen before the first jax.devices()/jit call.
"""

import os

N_VIRTUAL_DEVICES = 8

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={N_VIRTUAL_DEVICES}"
)
os.environ["PALLAS_AXON_POOL_IPS"] = ""  # disable axon TPU registration path

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
