"""Test configuration: force an 8-device virtual CPU mesh.

The container's sitecustomize registers a single-chip TPU ("axon") backend at
interpreter startup, so jax is already imported by the time pytest runs. JAX
backends initialize lazily, which lets us still retarget to CPU here — this
must happen before the first jax.devices()/jit call.
"""

import os

N_VIRTUAL_DEVICES = 8

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={N_VIRTUAL_DEVICES}"
)
os.environ["PALLAS_AXON_POOL_IPS"] = ""  # disable axon TPU registration path

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running sweeps excluded from tier-1 "
        "(run explicitly with `pytest -m slow`)")


def pytest_collection_modifyitems(config, items):
    """Deselect `slow` tests unless a -m expression names them, so the
    tier-1 run (`pytest tests/`) never pays for the 500-run sweeps."""
    import pytest

    if "slow" in (config.getoption("-m") or ""):
        return
    skip = pytest.mark.skip(reason="slow sweep: opt in with -m slow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)
