"""rlo-scope + collective instrumentation (docs/DESIGN.md §21).

Four contracts:

  1. **Measured equals predicted**: an instrumented sim-substrate
     allreduce produces exactly the ledger's step identities, send
     counts, and payload bytes — zero findings, exit 0.

  2. **Bit-for-bit reproducibility**: the full ``--json`` report is a
     pure function of (schedule, n, nbytes, seed).

  3. **Disabled path**: an uninstrumented ``Comm`` emits nothing and
     leaves the SimWorld delivery schedule (digest, event count,
     virtual span) byte-identical to the instrumented run — probes
     observe, they never perturb.  The always-on counters still count.

  4. **Trace-time hooks**: ``tpu_collectives.set_step_hook`` fires
     once per Python-unrolled schedule step during jax tracing, in
     ledger order, and restores cleanly.

Plus the timeline contract: STEP events render as ``cat: coll``
Chrome slices with per-hop flow edges, and the merged trace stays
schema-valid.
"""

import json

import numpy as np
import pytest

from rlo_tpu.observe.ledger import ledger
from rlo_tpu.ops.collectives import Comm
from rlo_tpu.tools import rlo_scope
from rlo_tpu.tools.rlo_scope import analyze, run_sim_collective
from rlo_tpu.transport.sim import SimWorld
from rlo_tpu.utils.timeline import (merge_timeline, trace_stats,
                                    validate_chrome_trace)
from rlo_tpu.utils.tracing import Tracer

N = 4
NBYTES = 4096


def _analyze(run):
    return analyze(run["events"], run["schedule"], run["nbytes"],
                   measured_steps=run["coll_steps"],
                   measured_bytes=run["coll_bytes"],
                   min_delay_usec=run["min_delay_usec"],
                   result_correct=run["result_correct"])


# ---------------------------------------------------------------------------
# 1. measured == predicted
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("schedule", rlo_scope.SIM_SCHEDULES)
def test_instrumented_sim_run_matches_ledger(schedule):
    run = run_sim_collective(schedule, N, NBYTES, seed=0)
    led = ledger(schedule, N, NBYTES)
    assert run["result_correct"]
    # one STEP event per (rank, ledger step); counters agree exactly
    assert len(run["events"]) == N * led.num_steps
    assert run["coll_steps"] == [led.num_steps] * N
    assert run["coll_bytes"] == led.sent_bytes_by_rank()
    assert sum(run["coll_bytes"]) == led.total_bytes

    report, findings = _analyze(run)
    assert findings == []
    assert report["measured"]["ops"] == 1
    assert report["bus_fraction"] is not None
    assert [(r["algorithm"], r["step"]) for r in report["steps"]] == \
        sorted((s.algorithm, s.index) for s in led.steps)
    assert report["ledger"]["digest"] == led.digest()


def test_render_covers_every_step():
    run = run_sim_collective("ring_allreduce", N, NBYTES, seed=0)
    report, _ = _analyze(run)
    text = rlo_scope.render(report)
    assert "bus utilisation" in text
    for row in report["steps"]:
        assert f"{row['algorithm']}:{row['step']}" in text


# ---------------------------------------------------------------------------
# 2. bit-for-bit reproducibility
# ---------------------------------------------------------------------------

def test_report_is_bit_for_bit_reproducible():
    docs = []
    for _ in range(2):
        report, findings = _analyze(
            run_sim_collective("ring_allreduce", N, NBYTES, seed=7))
        assert findings == []
        docs.append(json.dumps(report, sort_keys=True))
    assert docs[0] == docs[1]
    # ...and a different seed moves the measured timings, not the join
    other, _ = _analyze(
        run_sim_collective("ring_allreduce", N, NBYTES, seed=8))
    assert json.dumps(other, sort_keys=True) != docs[0]
    assert other["ledger"] == json.loads(docs[0])["ledger"]


def test_cli_json_is_reproducible_and_clean(capsys):
    argv = ["--schedule", "recursive_doubling", "--n", str(N),
            "--nbytes", str(NBYTES), "--seed", "0", "--json"]
    outs = []
    for _ in range(2):
        assert rlo_scope.main(argv) == 0
        outs.append(capsys.readouterr().out)
    assert outs[0] == outs[1]
    doc = json.loads(outs[0])
    assert doc["findings"] == []
    assert doc["seed"] == 0 and "sim_schedule_digest" in doc


def test_cli_rejects_bad_invocations(capsys):
    assert rlo_scope.main(["--schedule", "nope", "--json"]) == 2
    assert rlo_scope.main(["--n", "1", "--json"]) == 2
    capsys.readouterr()


# ---------------------------------------------------------------------------
# findings fire on contract violations
# ---------------------------------------------------------------------------

def test_findings_fire_on_drift():
    run = run_sim_collective("ring_allreduce", N, NBYTES, seed=0)

    # S1: a dropped step (instrumentation lost events)
    pruned = dict(run)
    pruned["events"] = [e for e in run["events"]
                        if e["c"] % 1024 != 0 or e["a"] != 2]
    _, findings = _analyze(pruned)
    assert any(f.rule == "S1" and "no measured" in f.msg
               for f in findings)

    # S1: counter drift on one rank
    bad = dict(run)
    bad["coll_steps"] = [run["coll_steps"][0] + 1] + \
        run["coll_steps"][1:]
    _, findings = _analyze(bad)
    assert any(f.rule == "S1" and "coll_steps" in f.msg
               for f in findings)

    # S2: byte drift
    bad = dict(run)
    bad["coll_bytes"] = [run["coll_bytes"][0] - 4] + \
        run["coll_bytes"][1:]
    _, findings = _analyze(bad)
    assert any(f.rule == "S2" for f in findings)

    # S3: wrong reduction
    bad = dict(run)
    bad["result_correct"] = False
    _, findings = _analyze(bad)
    assert any(f.rule == "S3" for f in findings)


# ---------------------------------------------------------------------------
# 3. disabled path: observe, never perturb
# ---------------------------------------------------------------------------

def _drive(seed, instrument):
    world = SimWorld(N, seed=seed)
    comms = [Comm(world.transport(r)) for r in range(N)]
    tracer = Tracer(enabled=True)
    if instrument:
        for c in comms:
            c.instrument(world.clock, tracer)
    xs = [np.full(NBYTES // 4, float(r + 1), dtype=np.float32)
          for r in range(N)]
    coros = [c.allreduce(x, algorithm="ring")
             for c, x in zip(comms, xs)]
    results = [None] * N
    alive = set(range(N))
    while alive:
        for i in list(alive):
            try:
                next(coros[i])
            except StopIteration as e:
                results[i] = e.value
                alive.discard(i)
        if alive:
            world.step()
    return world, comms, tracer, results


def test_uninstrumented_run_is_silent_and_unperturbed():
    w_on, c_on, t_on, r_on = _drive(seed=3, instrument=True)
    w_off, c_off, t_off, r_off = _drive(seed=3, instrument=False)
    # no probe -> zero events collected
    assert len(t_off.events()) == 0
    assert len(t_on.events()) == N * ledger("ring_allreduce", N,
                                            NBYTES).num_steps
    # the delivery schedule is byte-identical: probes never send
    assert w_off.schedule_digest() == w_on.schedule_digest()
    assert w_off.events == w_on.events
    assert w_off.now == w_on.now
    # the always-on counters count either way
    assert [c.coll_steps for c in c_off] == \
        [c.coll_steps for c in c_on]
    assert [c.coll_bytes for c in c_off] == \
        [c.coll_bytes for c in c_on]
    for a, b in zip(r_on, r_off):
        assert np.array_equal(a, b)


# ---------------------------------------------------------------------------
# timeline: coll slices + flow edges
# ---------------------------------------------------------------------------

def test_timeline_renders_coll_slices_and_flows():
    run = run_sim_collective("ring_allreduce", N, NBYTES, seed=0)
    trace = merge_timeline([run["events"]])
    validate_chrome_trace(trace)
    slices = [e for e in trace["traceEvents"]
              if e.get("cat") == "coll"]
    assert len(slices) == len(run["events"])
    # every received hop gets a sender-start -> receiver-end edge
    starts = [e for e in trace["traceEvents"]
              if e.get("cat") == "coll_flow" and e.get("ph") == "s"]
    finishes = [e for e in trace["traceEvents"]
                if e.get("cat") == "coll_flow" and e.get("ph") == "f"]
    assert len(starts) == len(finishes) == len(slices)
    stats = trace_stats(trace)
    per_alg = {}
    for r in stats["ranks"].values():
        for alg, slot in r["coll"].items():
            per_alg[alg] = per_alg.get(alg, 0) + slot["count"]
    assert sum(per_alg.values()) == len(slices)
    assert set(per_alg) == {"ring_reduce_scatter", "ring_all_gather"}


# ---------------------------------------------------------------------------
# 4. trace-time step hooks (jax executor)
# ---------------------------------------------------------------------------

def test_tpu_step_hook_fires_in_ledger_order(monkeypatch):
    jax = pytest.importorskip("jax")
    shard_map_mod = pytest.importorskip("jax.experimental.shard_map")
    import inspect

    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    from rlo_tpu.ops import tpu_collectives

    if not hasattr(lax, "axis_size"):
        monkeypatch.setattr(lax, "axis_size",
                            lambda name: lax.psum(1, name),
                            raising=False)
    sm_kw = {}
    params = inspect.signature(shard_map_mod.shard_map).parameters
    for kwname in ("check_rep", "check_vma"):
        if kwname in params:
            sm_kw[kwname] = False
            break
    devs = jax.devices()[:N]
    if len(devs) < N:
        pytest.skip(f"need {N} devices")
    mesh = Mesh(devs, ("x",))
    x = jnp.ones((N, 64), jnp.float32)

    for alg, phases in [
            ("recursive_doubling", ("recursive_doubling",)),
            ("halving_doubling", ("halving_reduce_scatter",
                                  "doubling_all_gather"))]:
        calls = []
        prev = tpu_collectives.set_step_hook(
            lambda a, s, ws, _c=calls: _c.append((a, s, ws)))
        try:
            fn = shard_map_mod.shard_map(
                lambda v, _a=alg: tpu_collectives.allreduce(
                    x=v, axis="x", algorithm=_a),
                mesh=mesh, in_specs=P("x"), out_specs=P(), **sm_kw)
            jax.jit(fn).lower(x)  # trace only — hooks are trace-time
        finally:
            assert tpu_collectives.set_step_hook(prev) is not None
        led = ledger(alg, N, 64 * N * 4)
        want = [(s.algorithm, None, N) for s in led.steps]
        assert [(a, None, ws) for a, _s, ws in calls] == want
        # per-phase step indices restart at 0 and ascend
        for phase in phases:
            idxs = [s for a, s, _ in calls if a == phase]
            assert idxs == list(range(len(idxs)))


def test_tpu_step_hook_fires_for_bcast(monkeypatch):
    jax = pytest.importorskip("jax")
    shard_map_mod = pytest.importorskip("jax.experimental.shard_map")
    import inspect

    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    from rlo_tpu.ops import tpu_collectives

    if not hasattr(lax, "axis_size"):
        monkeypatch.setattr(lax, "axis_size",
                            lambda name: lax.psum(1, name),
                            raising=False)
    sm_kw = {}
    params = inspect.signature(shard_map_mod.shard_map).parameters
    for kwname in ("check_rep", "check_vma"):
        if kwname in params:
            sm_kw[kwname] = False
            break
    devs = jax.devices()[:N]
    if len(devs) < N:
        pytest.skip(f"need {N} devices")
    mesh = Mesh(devs, ("x",))
    x = jnp.ones((N, 8), jnp.float32)

    calls = []
    prev = tpu_collectives.set_step_hook(
        lambda a, s, ws: calls.append((a, s, ws)))
    try:
        fn = shard_map_mod.shard_map(
            lambda v: tpu_collectives.rootless_bcast(
                v, origin=0, axis="x", schedule="binomial"),
            mesh=mesh, in_specs=P("x"), out_specs=P("x"), **sm_kw)
        jax.jit(fn).lower(x)
    finally:
        tpu_collectives.set_step_hook(prev)
    led = ledger("binomial_bcast", N, 8 * 4, origin=0)
    assert calls == [("binomial_bcast", i, N)
                     for i in range(led.num_steps)]
