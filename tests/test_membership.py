"""Membership epochs, quarantine, and elastic rejoin — both engines
(docs/DESIGN.md §8).

Python engine scenarios run on the loopback world with a fake clock
(fully deterministic); the C engine mirror runs the same protocol over
the native loopback world's fault-injection hooks (kill/revive/
partition/heal) in real time with tight timeouts. The two engines must
expose the SAME counters (`epoch`, `epoch_quarantined`, `rejoins`)
through the same metrics schema, and both must escalate an ARQ
give-up into a FAILURE declaration.
"""

import time

import pytest

from rlo_tpu.engine import EngineManager, ProgressEngine
from rlo_tpu.transport.loopback import LoopbackWorld
from rlo_tpu.utils.tracing import TRACER, Ev
from rlo_tpu.wire import EPOCH_OFFSET, HEADER_SIZE, Frame, Tag


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def make_world(ws, seed=3, **kw):
    clock = FakeClock()
    world = LoopbackWorld(ws, seed=seed)
    mgr = EngineManager()
    kw.setdefault("failure_timeout", 20.0)
    kw.setdefault("heartbeat_interval", 2.0)
    engines = [ProgressEngine(world.transport(r), manager=mgr,
                              clock=clock, **kw)
               for r in range(ws)]
    return world, mgr, engines, clock


def spin(mgr, clock, ticks, dt=1.0):
    for _ in range(ticks):
        clock.advance(dt)
        mgr.progress_all()


# ---------------------------------------------------------------------------
# Python engine: epochs, quarantine, rejoin
# ---------------------------------------------------------------------------

class TestEpochs:
    def test_wire_frame_carries_epoch(self):
        f = Frame(origin=1, payload=b"x", epoch=7)
        raw = f.encode()
        assert len(raw) == HEADER_SIZE + 1
        assert Frame.decode(raw).epoch == 7
        assert int.from_bytes(raw[EPOCH_OFFSET:EPOCH_OFFSET + 4],
                              "little") == 7

    def test_every_failure_adoption_bumps_the_epoch(self):
        world, mgr, engines, clock = make_world(4)
        spin(mgr, clock, 3)
        assert all(e.epoch == 0 for e in engines)
        world.kill_rank(3)
        engines[3].cleanup()
        spin(mgr, clock, 40)
        for e in engines[:3]:
            assert 3 in e.failed
            assert e.epoch >= 1

    def test_frames_from_failed_sender_are_quarantined(self):
        world, mgr, engines, clock = make_world(4)
        spin(mgr, clock, 3)
        # rank 0 adopts a (false) failure of rank 1 WITHOUT announcing
        # it, so rank 1 keeps sending: rank 1's DIRECT frames must be
        # quarantined and counted (never touching link state or
        # liveness). Copies relayed by live peers still deliver — at
        # most once, via the (origin, seq) dedup — the quarantine is
        # a link-level gate on the immediate sender, not an
        # origin-level censor (that would desync delivery across
        # ranks and break the admission replay). Hold rank 0's heal
        # probes off so the quarantine window stays open: since the
        # PR-16 batched heal, one probe re-converges the whole fleet
        # within a single tick.
        engines[0]._mark_failed(1)
        engines[0]._join_last_probe = float("inf")
        before = engines[0].epoch_quarantined
        engines[1].bcast(b"from the dead")
        # the overlay routes origin-1 traffic to rank 0 through a live
        # relay; send the direct copy too so the link-level gate on
        # the immediate sender is actually exercised
        engines[1]._send_raw(
            0, int(Tag.BCAST),
            Frame(origin=1, vote=engines[1]._bcast_seq - 1,
                  payload=b"from the dead").encode())
        spin(mgr, clock, 10)
        assert engines[0].epoch_quarantined > before
        assert engines[0].quar_failed_sender > 0
        drained = list(iter(engines[0].pickup_next, None))
        assert sum(m.data == b"from the dead" for m in drained) <= 1
        m = engines[0].metrics()["counters"]
        assert m["epoch_quarantined"] == engines[0].epoch_quarantined
        assert m["epoch"] == engines[0].epoch

    def test_false_positive_survivor_rejoins(self):
        """A FAILURE notice about a LIVE rank: it records the
        suspicion, becomes a joiner, petitions, and the survivors
        readmit it through the IAR admission round."""
        world, mgr, engines, clock = make_world(4)
        spin(mgr, clock, 3)
        engines[0]._announce_failed(1)  # false positive
        # rank 1 never hears the notice (the survivor overlay excludes
        # it) — it learns from the survivors' JOIN heal-probes that its
        # view lost, becomes a joiner, petitions, and is readmitted
        spin(mgr, clock, 80)
        assert not engines[1]._awaiting_welcome
        assert engines[1].rejoins >= 1
        for e in engines:
            assert sorted(e._alive) == [0, 1, 2, 3], \
                f"rank {e.rank} view {e._alive}"
        # and traffic flows again, exactly once
        engines[1].bcast(b"back")
        spin(mgr, clock, 20)
        for r in (0, 2, 3):
            got = []
            while (m := engines[r].pickup_next()) is not None:
                if m.type == int(Tag.BCAST):
                    got.append((m.origin, m.data))
            assert got.count((1, b"back")) == 1

    def test_explicit_rejoin_bumps_incarnation_and_seq_spaces(self):
        world, mgr, engines, clock = make_world(4)
        spin(mgr, clock, 3)
        inc = engines[2].rejoin()
        assert inc == 1
        assert engines[2]._awaiting_welcome
        assert engines[2]._bcast_seq >= (1 << 20)
        with pytest.raises(ValueError):
            engines[2].rejoin(incarnation=0)  # backwards
        spin(mgr, clock, 80)
        assert not engines[2]._awaiting_welcome
        for e in engines:
            assert sorted(e._alive) == [0, 1, 2, 3]

    def test_joiner_quarantines_everything_but_membership(self):
        world, mgr, engines, clock = make_world(4)
        spin(mgr, clock, 3)
        engines[1]._become_joiner()
        before = engines[1].epoch_quarantined
        engines[0].bcast(b"while joining")
        spin(mgr, clock, 2, dt=0.1)  # short: admission hasn't landed
        assert engines[1].epoch_quarantined > before

    def test_arq_give_up_declares_failure_with_trace(self):
        world, mgr, engines, clock = make_world(
            4, failure_timeout=None, arq_rto=1.0, arq_max_retries=3)
        victim = engines[0]._cur_initiator_targets()[0]
        world.drop_next(0, victim, 100_000)
        TRACER.clear()
        with TRACER.enable():
            engines[0].bcast(b"x")
            for _ in range(100):
                spin(mgr, clock, 1)
                if victim in engines[0].failed:
                    break
        assert victim in engines[0].failed
        giveups = TRACER.events(Ev.ARQ_GIVEUP, rank=0)
        assert giveups and giveups[0].a == victim
        assert giveups[0].b == 3  # the retransmit count rides the event
        fails = [e for e in TRACER.events(Ev.FAILURE, rank=0)
                 if e.a == victim and e.b == 1]
        assert fails, "give-up did not escalate to a declaration"
        TRACER.clear()


class TestHealing:
    """The §18 churn-proof healing paths: epoch catch-up without full
    rejoin, sync-supersedes-welcome, and batched-admission
    determinism."""

    @staticmethod
    def _deafen(engine, drop_tags):
        """Drop inbound frames with the given tags at one rank —
        deterministic loss (ARQ is off on this world, so nothing
        retransmits). Mutate ``drop_tags`` to change phases."""
        orig = engine.transport.poll

        def poll():
            m = orig()
            while m is not None and m[1] in drop_tags:
                m = orig()
            return m

        engine.transport.poll = poll

    def test_epoch_catchup_without_full_rejoin(self):
        """An epoch-lagging but ALIVE member syncs back via MSYNC
        instead of being torn down for a full rejoin — root cause 1
        of the rejoin cascade. Rank 2 misses a failure adoption AND
        the readmission decision; the readmitted rank's below-floor
        quarantine of rank 2's traffic triggers a stale probe, rank 2
        answers with a sync REQUEST (the probe says it is still a
        member), adopts the view state, and never rejoins."""
        world, mgr, engines, clock = make_world(4)
        spin(mgr, clock, 3)
        drop = {int(Tag.FAILURE), int(Tag.IAR_DECISION),
                int(Tag.MSYNC)}
        self._deafen(engines[2], drop)
        # false-positive declaration of rank 3: ranks 0/1 adopt it
        # (and later readmit 3); rank 2 hears none of it
        engines[0]._announce_failed(3)
        for _ in range(80):
            spin(mgr, clock, 1)
            if engines[3].rejoins >= 1 and \
                    not engines[3]._awaiting_welcome and \
                    sorted(engines[0]._alive) == [0, 1, 2, 3]:
                break
        assert engines[3].rejoins >= 1
        # rank 2 is lagging: it saw 3's petition (announced the
        # failure itself) but missed the admission decision
        assert engines[2].epoch < engines[0].epoch
        drop.clear()  # loss window over
        spin(mgr, clock, 40)
        for e in engines:
            assert sorted(e._alive) == [0, 1, 2, 3], \
                f"rank {e.rank} view {e._alive}"
        assert len({e.epoch for e in engines}) == 1
        # the laggard caught up WITHOUT a rejoin: the fleet ran
        # exactly ONE admission round (rank 3's) — a torn-down rank 2
        # would have needed a second — and rank 2 kept incarnation 0
        assert sum(e.admission_rounds for e in engines) == 1
        assert engines[2].incarnation == 0
        assert engines[2].epoch_syncs >= 1
        assert not engines[2]._awaiting_welcome

    def test_sync_supersedes_lost_welcome(self):
        """A joiner whose WELCOME was lost re-petitions; the admitter
        that ALREADY admitted it (same incarnation, certified link
        reset) answers with a view-state sync instead of burning a
        second admission round — the sync-supersedes-welcome path."""
        world, mgr, engines, clock = make_world(4)
        spin(mgr, clock, 3)
        # every welcome from the admitter vanishes
        engines[0]._send_welcome = lambda *a, **k: None
        engines[0]._announce_failed(3)  # false positive; 3 rejoins
        spin(mgr, clock, 120)
        assert not engines[3]._awaiting_welcome, \
            "joiner stayed wedged behind the lost welcome"
        assert engines[3].rejoins >= 1
        assert engines[3].epoch_syncs >= 1  # un-wedged via MSYNC
        # ONE admission round: the re-petition was answered with a
        # sync, not a second failure/admission cycle
        assert engines[0].admission_rounds == 1
        for e in engines:
            assert sorted(e._alive) == [0, 1, 2, 3], \
                f"rank {e.rank} view {e._alive}"
        assert len({e.epoch for e in engines}) == 1

    def test_batched_admission_is_deterministic(self):
        """k queued joiners ride ONE admission record; the whole
        healed run replays byte-identically (same schedule digest)
        and the batch shows up in the batched_admits counter."""
        from rlo_tpu.transport.sim import Scenario
        # three joiners: the first petition opens a round, the other
        # two queue behind it and ride the next record as ONE batch
        script = [(2.0, "bcast", 0),
                  (10.0, "partition", [[0, 1], [2, 3, 4]]),
                  (40.0, "heal"),
                  (140.0, "bcast", 1)]
        runs = []
        for _ in range(2):
            s = Scenario(world_size=5, seed=7, duration=180.0,
                         script=script, telemetry=True,
                         check_delivery=False)
            runs.append(s.run())
        assert runs[0]["digest"] == runs[1]["digest"]
        roll = runs[0]["fleet_view"]["rollups"]
        assert roll["batched_admits"] >= 2
        assert runs[0]["views"] == {r: (0, 1, 2, 3, 4)
                                    for r in range(5)}


# ---------------------------------------------------------------------------
# Native C engine mirror (loopback world fault hooks)
# ---------------------------------------------------------------------------

def native():
    pytest.importorskip("numpy")
    from rlo_tpu.native import bindings as nb
    try:
        nb.load()
    except Exception as exc:  # pragma: no cover - no cc in env
        pytest.skip(f"native core unavailable: {exc}")
    return nb


def nspin(world, seconds):
    t0 = time.time()
    while time.time() - t0 < seconds:
        world.progress_all()
        time.sleep(0.001)


def nspin_until(world, cond, timeout):
    t0 = time.time()
    while time.time() - t0 < timeout:
        world.progress_all()
        if cond():
            return True
        time.sleep(0.001)
    return False


class TestNativeMembership:
    def _world(self, nb, ws=4, fd=True, arq=True):
        world = nb.NativeWorld(ws)
        engines = [nb.NativeEngine(world, r) for r in range(ws)]
        for e in engines:
            if fd:
                e.enable_failure_detection(100_000, 25_000)
            if arq:
                e.enable_arq(30_000, 4)
        return world, engines

    def test_kill_restart_rejoin_with_replay(self, monkeypatch):
        monkeypatch.setenv("RLO_QUIET", "1")
        nb = native()
        world, engines = self._world(nb)
        with world:
            nspin(world, 0.05)
            world.kill_rank(3)
            engines[3].close()
            ok = nspin_until(
                world, lambda: all(e.rank_failed(3)
                                   for e in engines[:3]), 5.0)
            assert ok, "survivors never declared the dead rank"
            assert all(e.epoch >= 1 for e in engines[:3])
            # a broadcast while rank 3 is dead — the replay must
            # deliver it to the restarted incarnation
            engines[0].bcast(b"while-dead")
            nspin(world, 0.1)
            world.revive_rank(3)
            e3 = nb.NativeEngine(world, 3)
            e3.enable_failure_detection(100_000, 25_000)
            e3.enable_arq(30_000, 4)
            e3.set_incarnation(1)
            assert e3.awaiting_welcome
            ok = nspin_until(
                world,
                lambda: not e3.awaiting_welcome and not any(
                    e.rank_failed(3) for e in engines[:3]), 8.0)
            assert ok, "restarted rank never rejoined"
            assert e3.rejoins >= 1
            nspin(world, 0.2)
            got = []
            while (m := e3.pickup_next()) is not None:
                if m.type == int(Tag.BCAST):
                    got.append(m.data)
            assert got.count(b"while-dead") == 1
            assert all(e.err == 0 for e in engines[:3] + [e3])

    def test_split_brain_heal_converges(self, monkeypatch):
        monkeypatch.setenv("RLO_QUIET", "1")
        nb = native()
        world, engines = self._world(nb)
        with world:
            nspin(world, 0.05)
            world.partition([[0, 1], [2, 3]])
            ok = nspin_until(
                world,
                lambda: engines[0].rank_failed(2) and
                engines[2].rank_failed(0), 5.0)
            assert ok, "partition was never detected"
            world.heal()
            ok = nspin_until(
                world,
                lambda: not any(e.rank_failed(r) for e in engines
                                for r in range(4)), 10.0)
            assert ok, "membership never converged after heal"
            # the last welcome adoption may still be settling when the
            # failed flags clear: wait for the epochs too
            ok = nspin_until(
                world,
                lambda: len({e.epoch for e in engines}) == 1, 5.0)
            assert ok, "epochs never converged after heal"
            assert all(e.rejoins >= 1 for e in engines)
            assert all(e.err == 0 for e in engines)
            # consensus works on the healed membership (the own-
            # proposal slot may still hold a settling admission round
            # right after convergence: wait for it to free up)
            rc = None
            t0 = time.time()
            while rc is None and time.time() - t0 < 5.0:
                try:
                    rc = engines[1].submit_proposal(b"post-heal",
                                                    pid=9)
                except RuntimeError:
                    nspin(world, 0.02)
            assert rc is not None, "admission round never settled"
            if rc == -1:
                ok = nspin_until(
                    world,
                    lambda: engines[1].vote_my_proposal() in (0, 1),
                    5.0)
                assert ok
                rc = engines[1].vote_my_proposal()
            assert rc == 1

    def test_native_arq_give_up_declares_failure(self, monkeypatch):
        monkeypatch.setenv("RLO_QUIET", "1")
        nb = native()
        # no heartbeat detector: the declaration must come from the
        # ARQ give-up escalation alone (satellite contract)
        world, engines = self._world(nb, fd=False)
        with world:
            victim = 1
            world.drop_next(0, victim, 100_000)
            engines[0].bcast(b"x")
            ok = nspin_until(
                world, lambda: engines[0].rank_failed(victim), 8.0)
            assert ok, "give-up never escalated to FAILURE"
            assert engines[0].arq_gave_up >= 1

    def test_stale_epoch_frame_quarantined_and_counted(self,
                                                       monkeypatch):
        monkeypatch.setenv("RLO_QUIET", "1")
        nb = native()
        world, engines = self._world(nb)
        with world:
            # drive one full false-positive rejoin so epoch floors are
            # armed, then inject a stale (epoch 0) frame
            nspin(world, 0.05)
            world.partition([[0, 1], [2, 3]])
            nspin_until(world, lambda: engines[0].rank_failed(2), 5.0)
            world.heal()
            ok = nspin_until(
                world,
                lambda: not any(e.rank_failed(r) for e in engines
                                for r in range(4)), 10.0)
            assert ok
            # pick a CROSS-partition pair: rank 0 either adopted a
            # welcome (floors armed for every member) or executed the
            # admission of rank 2 (floor[2] armed) — both guarantee a
            # nonzero epoch floor on the 2 -> 0 edge
            tgt, src = engines[0], 2
            before = tgt.epoch_quarantined
            raw = Frame(origin=src, payload=b"stale", vote=0,
                        epoch=0).encode()
            world.inject(src, tgt.rank, int(Tag.BCAST), raw)
            nspin(world, 0.1)
            assert tgt.epoch_quarantined > before
            assert all(e.err == 0 for e in engines)


# ---------------------------------------------------------------------------
# Cross-engine metrics schema parity for the new counters
# ---------------------------------------------------------------------------

def test_membership_counters_schema_parity():
    nb = native()
    from rlo_tpu.utils.metrics import ENGINE_COUNTER_KEYS
    for key in ("epoch", "epoch_quarantined", "rejoins"):
        assert key in ENGINE_COUNTER_KEYS
    world, mgr, engines, clock = make_world(2)
    py = engines[0].metrics()
    with nb.NativeWorld(2) as nw:
        ne = nb.NativeEngine(nw, 0)
        cm = ne.metrics()
    assert list(py["counters"]) == list(cm["counters"])
    assert py["counters"]["epoch"] == cm["counters"]["epoch"] == 0
