"""Continuous batching (models.serve) — the scheduling-not-numerics
oracle: every request's tokens equal its dense `generate` exactly, for
any stream shape (more requests than slots, mixed lengths/budgets,
late submissions, eos early-exit, int8/GQA configs)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rlo_tpu.models.generate import generate
from rlo_tpu.models.serve import DecodeServer, _bucket
from rlo_tpu.models.transformer import TransformerConfig, init_params

CFG = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                        d_ff=64, dtype="float32")


@pytest.fixture(scope="module")
def setup():
    params = init_params(jax.random.PRNGKey(0), CFG)
    return params


def dense_oracle(params, cfg, prompt, max_new):
    out = generate(params, jnp.asarray(prompt, jnp.int32)[None, :],
                   cfg, max_new=max_new)
    return np.asarray(out)[0]


def test_stream_matches_dense(setup):
    """8 requests through 3 slots, mixed prompt lengths and budgets —
    each result equals its dense generate."""
    params = setup
    rng = np.random.default_rng(0)
    srv = DecodeServer(params, CFG, n_slots=3, max_len=96,
                       round_len=5, prompt_buckets=(8, 16, 32))
    reqs = []
    for i in range(8):
        plen = int(rng.integers(3, 30))
        max_new = int(rng.integers(1, 20))
        prompt = rng.integers(0, CFG.vocab, (plen,))
        reqs.append((prompt, max_new))
        srv.submit(prompt, max_new)
    outs = srv.run()
    assert len(outs) == 8
    for (prompt, max_new), got in zip(reqs, outs):
        want = dense_oracle(params, CFG, prompt, max_new)
        np.testing.assert_array_equal(got, want)


def test_late_submission_joins_running_batch(setup):
    """Requests submitted while the loop is running fill freed slots
    mid-stream."""
    params = setup
    rng = np.random.default_rng(1)
    srv = DecodeServer(params, CFG, n_slots=2, max_len=64,
                       round_len=4, prompt_buckets=(8, 16))
    first = [(rng.integers(0, CFG.vocab, (5,)), 6),
             (rng.integers(0, CFG.vocab, (9,)), 14)]
    for p, m in first:
        srv.submit(p, m)
    srv.step_round()  # both running
    late = (rng.integers(0, CFG.vocab, (12,)), 9)
    srv.submit(*late[:1], late[1])
    outs = srv.run()
    for (p, m), got in zip(first + [late], outs):
        np.testing.assert_array_equal(got,
                                      dense_oracle(params, CFG, p, m))


def test_eos_frees_slot_early(setup):
    """eos truncates the output (eos included) and frees the slot; a
    queued request then completes. Oracle: dense generate truncated at
    its own first eos."""
    params = setup
    rng = np.random.default_rng(2)
    # find an eos id that actually occurs early in some dense output
    prompt = rng.integers(0, CFG.vocab, (7,))
    dense = dense_oracle(params, CFG, prompt, 16)
    eos = int(dense[3])
    srv = DecodeServer(params, CFG, n_slots=1, max_len=64,
                       round_len=4, prompt_buckets=(8,))
    srv.submit(prompt, 16, eos_id=eos)
    p2 = rng.integers(0, CFG.vocab, (6,))
    srv.submit(p2, 5)
    outs = srv.run()
    want = dense[:list(dense).index(eos) + 1]
    np.testing.assert_array_equal(outs[0], want)
    np.testing.assert_array_equal(outs[1],
                                  dense_oracle(params, CFG, p2, 5))


@pytest.mark.parametrize("variant", ["gqa_rope", "int8"])
def test_variants(setup, variant):
    cfg = (dataclasses.replace(CFG, n_kv_heads=2, pos_encoding="rope")
           if variant == "gqa_rope"
           else dataclasses.replace(CFG, kv_cache_dtype="int8"))
    params = init_params(jax.random.PRNGKey(3), cfg)
    rng = np.random.default_rng(3)
    srv = DecodeServer(params, cfg, n_slots=2, max_len=64,
                       round_len=3, prompt_buckets=(8, 16))
    reqs = [(rng.integers(0, cfg.vocab, (int(rng.integers(3, 14)),)),
             int(rng.integers(2, 10))) for _ in range(5)]
    for p, m in reqs:
        srv.submit(p, m)
    outs = srv.run()
    for (p, m), got in zip(reqs, outs):
        np.testing.assert_array_equal(got,
                                      dense_oracle(params, cfg, p, m))


def test_slot_reuse_no_stale_leak(setup):
    """A short request reuses a slot that previously held a LONGER
    sequence — stale cache beyond the new row's positions must never
    be attended."""
    params = setup
    rng = np.random.default_rng(4)
    srv = DecodeServer(params, CFG, n_slots=1, max_len=64,
                       round_len=8, prompt_buckets=(8, 32))
    long_p = rng.integers(0, CFG.vocab, (30,))
    short_p = rng.integers(0, CFG.vocab, (4,))
    srv.submit(long_p, 12)
    srv.submit(short_p, 12)
    outs = srv.run()
    np.testing.assert_array_equal(
        outs[0], dense_oracle(params, CFG, long_p, 12))
    np.testing.assert_array_equal(
        outs[1], dense_oracle(params, CFG, short_p, 12))


def test_long_prompt_exceeds_largest_bucket(setup):
    """Prompts LONGER than the largest bucket are admissible now:
    admission prefills the bucket-sized head and extends through
    jitted block_decode chunks — dense-generate parity holds for any
    plen <= max_len - max_new (docs/DESIGN.md §12 satellite)."""
    params = setup
    rng = np.random.default_rng(13)
    srv = DecodeServer(params, CFG, n_slots=2, max_len=96,
                       round_len=4, prompt_buckets=(8, 16))
    reqs = [(rng.integers(0, CFG.vocab, (30,)), 10),   # 1 chunk
            (rng.integers(0, CFG.vocab, (41,)), 7),    # 2 chunks
            (rng.integers(0, CFG.vocab, (5,)), 6)]     # in-bucket
    for p, m in reqs:
        srv.submit(p, m)
    outs = srv.run()
    for (p, m), got in zip(reqs, outs):
        np.testing.assert_array_equal(got,
                                      dense_oracle(params, CFG, p, m))


def test_errors(setup):
    srv = DecodeServer(setup, CFG, n_slots=1, max_len=16,
                       prompt_buckets=(8,))
    with pytest.raises(ValueError, match="max_len"):
        srv.submit(np.zeros(8, np.int32), 20)
    with pytest.raises(ValueError, match="bucket"):
        _bucket(100, (8, 16))


def test_poll_completed_and_cancel(setup):
    """The fabric-facing hooks (docs/DESIGN.md §11): poll_completed
    drains (rid, tokens) incrementally and matches the dense oracle;
    cancel frees a slot mid-decode (or de-queues) so ownership can
    move; canceled requests never complete and free capacity for the
    rest of the stream."""
    from rlo_tpu.utils.metrics import Registry

    params = setup
    rng = np.random.default_rng(11)
    reg = Registry()
    srv = DecodeServer(params, CFG, n_slots=2, max_len=64,
                       round_len=4, prompt_buckets=(8, 16),
                       metrics=reg)
    reqs = [(rng.integers(0, CFG.vocab, (5,)), 10),
            (rng.integers(0, CFG.vocab, (7,)), 6),
            (rng.integers(0, CFG.vocab, (4,)), 8)]
    rids = [srv.submit(p, m) for p, m in reqs]
    assert srv.has_work() and srv.queue_depth() == 3
    srv.step_round()  # admits rids 0+1 into the 2 slots
    assert srv.queue_depth() == 1
    assert set(srv.slot_ownership()) <= {rids[0], rids[1], None}
    assert srv.cancel(rids[0]) is True          # in-slot cancel
    assert srv.cancel(rids[0]) is False         # idempotent
    outs = srv.run()
    got = dict()
    for rid, toks in srv.poll_completed():
        got[rid] = toks
    assert srv.poll_completed() == []           # drained
    assert set(got) == {rids[1], rids[2]}       # canceled never lands
    for i in (1, 2):
        p, m = reqs[i]
        np.testing.assert_array_equal(got[rids[i]],
                                      dense_oracle(params, CFG, p, m))
        np.testing.assert_array_equal(outs[rids[i]], got[rids[i]])
    snap = srv.stats()
    assert snap["counters"]["serve.requests_canceled"] == 1
    assert snap["counters"]["serve.requests_completed"] == 2
    # e2e latency (submit -> last token) recorded per completion only
    assert snap["histograms"]["serve.e2e_usec"]["count"] == 2
    assert snap["histograms"]["serve.e2e_usec"]["p50"] is not None
    assert srv.free_slots() == 2 and not srv.has_work()


def test_cancel_queued_before_admission(setup):
    """A request canceled while still queued never prefills; run()
    returns an empty row for it and the stream completes."""
    params = setup
    rng = np.random.default_rng(12)
    srv = DecodeServer(params, CFG, n_slots=1, max_len=64,
                       round_len=4, prompt_buckets=(8,))
    r0 = srv.submit(rng.integers(0, CFG.vocab, (5,)), 6)
    r1 = srv.submit(rng.integers(0, CFG.vocab, (6,)), 4)
    assert srv.cancel(r1) is True
    outs = srv.run()
    assert len(outs[r0]) == 6 and len(outs[r1]) == 0


def test_serving_telemetry(setup):
    """Serving telemetry (docs/DESIGN.md §7): every request's TTFT and
    queue wait are recorded, occupancy/round histograms advance, and
    the token counter equals the emitted tokens — without perturbing
    the scheduling oracle (outputs still equal dense generate)."""
    from rlo_tpu.utils.metrics import Registry

    params = setup
    rng = np.random.default_rng(7)
    reg = Registry()
    srv = DecodeServer(params, CFG, n_slots=2, max_len=64,
                       round_len=4, prompt_buckets=(8, 16),
                       metrics=reg)
    reqs = [(rng.integers(0, CFG.vocab, (int(rng.integers(3, 12)),)),
             int(rng.integers(1, 7))) for _ in range(3)]
    for p, m in reqs:
        srv.submit(p, m)
    outs = srv.run()
    for (p, m), got in zip(reqs, outs):
        np.testing.assert_array_equal(got,
                                      dense_oracle(params, CFG, p, m))

    snap = srv.stats()
    c, h = snap["counters"], snap["histograms"]
    assert c["serve.requests_submitted"] == 3
    assert c["serve.requests_completed"] == 3
    assert c["serve.tokens_out"] == sum(len(o) for o in outs)
    assert h["serve.ttft_usec"]["count"] == 3
    assert h["serve.queue_wait_usec"]["count"] == 3
    assert h["serve.round_usec"]["count"] == srv.rounds_run >= 1
    assert h["serve.tok_usec"]["count"] == srv.rounds_run
    occ = h["serve.occupancy_pct"]
    assert occ["count"] == srv.rounds_run
    assert 0.0 < occ["min"] <= occ["max"] <= 100.0
    assert snap["gauges"]["serve.queue_depth"] == 0
    # stats() emits percentile summaries (not raw bucket dumps): the
    # quantile estimates are ordered and bracketed by min/max
    ttft = h["serve.ttft_usec"]
    assert ttft["min"] <= ttft["p50"] <= ttft["p90"] <= ttft["p99"]
    assert ttft["p99"] <= 2 * max(ttft["max"], 1.0)  # log2 upper bound
    assert "buckets" not in ttft
    # TTFT >= queue wait for the same request set (it includes it);
    # counts are equal so the mean comparison is the old sum one
    assert ttft["mean"] >= h["serve.queue_wait_usec"]["mean"]


def test_generate_timed_matches_generate_and_records(setup):
    """generate_timed: exact token parity with generate() plus TTFT /
    per-token records into its registry (the DecodeServer-shared
    schema)."""
    from rlo_tpu.models.generate import generate_timed
    from rlo_tpu.utils.metrics import Registry

    params = setup
    rng = np.random.default_rng(9)
    prompt = jnp.asarray(rng.integers(0, CFG.vocab, (2, 6)), jnp.int32)
    reg = Registry()
    got = np.asarray(generate_timed(params, prompt, CFG, max_new=5,
                                    metrics=reg))
    want = np.asarray(generate(params, prompt, CFG, max_new=5))
    np.testing.assert_array_equal(got, want)
    snap = reg.snapshot()
    assert snap["histograms"]["serve.ttft_usec"]["count"] == 1
    assert snap["histograms"]["serve.tok_usec"]["count"] == 1
    assert snap["counters"]["serve.tokens_out"] == 2 * 5
    assert snap["histograms"]["serve.ttft_usec"]["min"] > 0

    # sampling path: same key stream -> same tokens as generate()
    key = jax.random.PRNGKey(0)
    got_s = np.asarray(generate_timed(params, prompt, CFG, max_new=3,
                                      temperature=0.7, rng=key,
                                      metrics=reg))
    want_s = np.asarray(generate(params, prompt, CFG, max_new=3,
                                 temperature=0.7, rng=key))
    np.testing.assert_array_equal(got_s, want_s)
