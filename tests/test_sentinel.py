"""rlo-sentinel self-verification (docs/DESIGN.md §15).

Mirror of tests/test_lint.py's two-halves pattern:

  1. The clean-tree contract: ``run_sentinel`` on this checkout reports
     zero findings — GIL-release safety, wire-input taint, error-path
     leaks, state-machine absorption, and the stale-anchor audit all
     hold on HEAD, in tier-1, on every run.

  2. Mutation fixtures: for each rule family S0–S4 a temp copy of the
     tree is seeded with exactly one violation and the analyzer must
     trip with the right rule ID at the right place — a rule that
     never fires is indistinguishable from no rule.  Each fixture
     re-creates a real bug class this PR fixed (or proved absent) on
     the seed tree: the unlocked trace ring (S1), the unvalidated shm
     record header (S2-C), the magic-only fabric record crash (S2-Py),
     the early-return pool leak (S3), and a DONE→IDLE escape from a
     settled proposal state (S4) — injected in either engine alone.
"""

from __future__ import annotations

import json
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from rlo_tpu.tools.rlo_sentinel import run_sentinel

REPO_ROOT = Path(__file__).resolve().parents[1]

_IGNORE = shutil.ignore_patterns(
    "__pycache__", ".pytest_cache", "*.so", "*.o", "*.pyc",
    "rlo_selftest*", "rlo_demo", "rlo_demo_mpi", "rlo_demo_tsan",
    "rlo_demo_asan", "femtompirun")


@pytest.fixture()
def tree(tmp_path):
    """An analyzable copy of the source tree (sources only, no build
    artifacts) that fixtures may mutate freely."""
    shutil.copytree(REPO_ROOT / "rlo_tpu", tmp_path / "rlo_tpu",
                    ignore=_IGNORE)
    return tmp_path


def mutate(root: Path, rel: str, old: str, new: str) -> int:
    """Replace ``old`` (must occur exactly once) with ``new``; returns
    the 1-indexed line of the edit."""
    path = root / rel
    text = path.read_text()
    assert text.count(old) == 1, \
        f"fixture drift: {old!r} occurs {text.count(old)}x in {rel}"
    line = text[:text.index(old)].count("\n") + 1
    path.write_text(text.replace(old, new))
    return line


def findings_for(root: Path, rule: str):
    return [f for f in run_sentinel(root) if f.rule == rule]


# ---------------------------------------------------------------------------
# 1. clean tree
# ---------------------------------------------------------------------------

def test_head_is_clean():
    """Zero findings on this checkout — the tier-1 drift gate."""
    findings = run_sentinel(REPO_ROOT)
    assert findings == [], "\n".join(str(f) for f in findings)


# ---------------------------------------------------------------------------
# 2. one seeded violation per rule family
# ---------------------------------------------------------------------------

def test_s0_fires_on_stale_anchor(tree):
    """An anchor nothing consumes is annotation rot: an
    allow-wallclock suppression with no wall-clock use beneath it."""
    path = tree / "rlo_tpu/engine.py"
    path.write_text(path.read_text() +
                    "\n# rlo-lint: allow-wallclock\n_ZZ = 1\n")
    hits = findings_for(tree, "S0")
    assert any(f.file == "rlo_tpu/engine.py" and
               "allow-wallclock" in f.msg and "stale" in f.msg
               for f in hits), hits
    # ...and only the injected anchor, not the legitimate ones
    assert len(hits) == 1, hits


def test_s0_fires_on_detached_transfers_anchor(tree):
    """A transfers() anchor naming a parameter the function does not
    have attaches to nothing and must be flagged, not silently
    ignored (that is exactly how ownership facts rot when a function
    is re-signatured)."""
    mutate(tree, "rlo_tpu/native/rlo_engine.c",
           "/* rlo-sentinel: transfers(rt) — the retransmit queue owns it */",
           "/* rlo-sentinel: transfers(zzz) — renamed param, stale fact */")
    hits = run_sentinel(tree)
    assert any(f.rule == "S0" and "transfers(zzz" in f.msg
               for f in hits), hits
    # losing the rtx_link fact ALSO resurfaces the S3 leak it declared
    assert any(f.rule == "S3" and "rt" in f.msg and
               "eng_isend_frame" in f.msg for f in hits), hits


def test_s1_fires_on_global_write_in_gil_released_code(tree):
    """A file-scope counter bumped inside the batched progress path is
    the trace-ring bug class: process-global state written from
    GIL-released code races across worlds."""
    line = mutate(tree, "rlo_tpu/native/rlo_engine.c",
                  "int64_t rlo_engine_progress_budget(rlo_engine *e, "
                  "int64_t max_frames)\n{\n    int64_t polled = 0;",
                  "static int64_t dbg_turns;\n"
                  "int64_t rlo_engine_progress_budget(rlo_engine *e, "
                  "int64_t max_frames)\n{\n    int64_t polled = 0;\n"
                  "    dbg_turns++;")
    hits = findings_for(tree, "S1")
    assert any(f.file == "rlo_tpu/native/rlo_engine.c" and
               "dbg_turns" in f.msg and
               "rlo_engine_progress_budget" in f.msg
               for f in hits), hits
    assert line > 0


def test_s1_guarded_by_anchor_suppresses(tree):
    """The same injected global, declared lock-protected, is
    sanctioned — and the anchor is consumed, so S0 stays quiet."""
    mutate(tree, "rlo_tpu/native/rlo_engine.c",
           "int64_t rlo_engine_progress_budget(rlo_engine *e, "
           "int64_t max_frames)\n{\n    int64_t polled = 0;",
           "/* rlo-sentinel: guarded-by(dbg_mu) */\n"
           "static int64_t dbg_turns;\n"
           "int64_t rlo_engine_progress_budget(rlo_engine *e, "
           "int64_t max_frames)\n{\n    int64_t polled = 0;\n"
           "    dbg_turns++;")
    hits = run_sentinel(tree)
    assert not [f for f in hits if f.rule in ("S0", "S1")], hits


def test_s2_fires_on_unvalidated_shm_record(tree):
    """Dropping the shm receive-record validation re-opens the
    pre-round-15 hole: a scribbled rec.len sizes an allocation and a
    ring copy unchecked."""
    mutate(tree, "rlo_tpu/native/rlo_shm.c",
           "            if (rec.len < 0 ||\n"
           "                rec.len > cap - (int64_t)sizeof(shm_rec) ||\n"
           "                rec.size != rec_size(rec.len) ||\n"
           "                rec.src != src) {\n"
           "                atomic_store(&w->hdr->abort_flag, 1);\n"
           "                return RLO_ERR_PROTO;\n"
           "            }\n",
           "")
    hits = findings_for(tree, "S2")
    assert any(f.file == "rlo_tpu/native/rlo_shm.c" and
               "rec.len" in f.msg and "length" in f.msg
               for f in hits), hits


def test_s2_fires_on_unguarded_fabric_record_index(tree):
    """Dropping the _on_record length guard re-opens the magic-only
    frame crash: wire bytes indexed without a dominating len check."""
    mutate(tree, "rlo_tpu/serving/fabric.py",
           "        if len(data) <= len(FABRIC_MAGIC):\n"
           "            # a magic-only (or truncated) frame: the caller's\n"
           "            # startswith(FABRIC_MAGIC) proves nothing about the kind\n"
           "            # byte existing — without this guard a 5-byte payload\n"
           "            # raises IndexError inside every rank's pump\n"
           "            # (rlo-sentinel S2, round 15)\n"
           "            self.metrics.counter(\"fabric.unknown_records\").inc()\n"
           "            return\n",
           "")
    hits = findings_for(tree, "S2")
    assert any(f.file == "rlo_tpu/serving/fabric.py" and
               "_on_record" in f.msg and "IndexError" in f.msg
               for f in hits), hits


def test_s2_fires_on_unclamped_msync_record_count_py(tree):
    """The MSYNC_RSP member-record count is wire input driving a
    range() loop: dropping its clamp must fire the loop-bound sink."""
    mutate(tree, "rlo_tpu/engine.py",
           "        if n < 0 or len(p) < 9 + 12 * n:\n"
           "            return\n",
           "")
    hits = findings_for(tree, "S2")
    assert any(f.file == "rlo_tpu/engine.py" and "'n'" in f.msg and
               "loop bound" in f.msg and "_msync_adopt" in f.msg
               for f in hits), hits


def test_s2_fires_on_unclamped_msync_record_count_c(tree):
    """Same hole, C engine: the record count read by get_le32 bounds
    the member-record walk; without the clamp a hostile count walks
    past the payload."""
    mutate(tree, "rlo_tpu/native/rlo_engine.c",
           "    if (n < 0 || plen < 9 + 12 * (int64_t)n)\n"
           "        return;\n",
           "")
    hits = findings_for(tree, "S2")
    assert any(f.file == "rlo_tpu/native/rlo_engine.c" and
               "'n'" in f.msg and "loop bound" in f.msg and
               "msync_adopt" in f.msg for f in hits), hits


def test_s2_fires_on_unguarded_span_trailer_decode(tree):
    """The PR-17 span-context trailer is parsed with a Struct-instance
    unpack (_SPAN_CTX): dropping the length arm of the guard leaves
    wire bytes unpacked with no dominating len(raw) check."""
    mutate(tree, "rlo_tpu/wire.py",
           "    if len(raw) - off < SPAN_CTX_SIZE or \\",
           "    if False or \\")
    hits = findings_for(tree, "S2")
    assert any(f.file == "rlo_tpu/wire.py" and "'raw'" in f.msg and
               "decode_span_ctx" in f.msg for f in hits), hits


def test_s2_fires_on_unchecked_span_field_index(tree):
    """rlo_span_decode's &out-params are wire bytes: dropping the
    success check and indexing on the stage byte must fire — the
    trailer fields are attacker-set."""
    mutate(tree, "rlo_tpu/native/rlo_engine.c",
           "        if (rlo_span_decode(m->payload + m->len - "
           "RLO_SPAN_CTX_SIZE,\n"
           "                            RLO_SPAN_CTX_SIZE, &gw, &sq, "
           "&st, &fl,\n"
           "                            0) >= 0)\n"
           "            rlo_trace_emit(e->rank, RLO_EV_SPAN, st, -1, "
           "sq, gw);\n",
           "        rlo_span_decode(m->payload + m->len - "
           "RLO_SPAN_CTX_SIZE,\n"
           "                        RLO_SPAN_CTX_SIZE, &gw, &sq, &st, "
           "&fl, 0);\n"
           "        rlo_trace_emit(e->rank, RLO_EV_SPAN, "
           "span_kind[st], -1, sq, gw);\n")
    hits = findings_for(tree, "S2")
    assert any(f.file == "rlo_tpu/native/rlo_engine.c" and
               "'st'" in f.msg and "array index" in f.msg
               for f in hits), hits


def test_s3_fires_on_early_return_pool_leak(tree):
    """Dropping the error-branch rlo_pool_free re-creates the leak
    shape S3 exists for: acquire, fail a second acquisition, return
    without releasing the first."""
    line = mutate(tree, "rlo_tpu/native/rlo_engine.c",
                  "            if (!stamped) {\n"
                  "                rlo_pool_free(rt);\n"
                  "                return RLO_ERR_NOMEM;\n"
                  "            }",
                  "            if (!stamped)\n"
                  "                return RLO_ERR_NOMEM;")
    hits = findings_for(tree, "S3")
    assert any(f.file == "rlo_tpu/native/rlo_engine.c" and
               "'rt'" in f.msg and "eng_isend_frame" in f.msg
               for f in hits), hits
    assert line > 0


def test_s4_fires_on_done_to_idle_in_c_engine(tree):
    """A guarded COMPLETED -> INVALID (DONE -> IDLE) reset injected in
    the C engine alone breaks absorption: a settled verdict may only
    re-arm to IN_PROGRESS."""
    mutate(tree, "rlo_tpu/native/rlo_engine.c",
           "    p->pid = -1;\n    p->vote = 1;\n"
           "    p->state = RLO_INVALID;\n}",
           "    p->pid = -1;\n    p->vote = 1;\n"
           "    if (p->state == RLO_COMPLETED)\n"
           "        p->state = RLO_INVALID;\n"
           "    p->state = RLO_INVALID;\n}")
    hits = findings_for(tree, "S4")
    assert any(f.file == "rlo_tpu/native/rlo_engine.c" and
               "COMPLETED -> INVALID" in f.msg and "settled" in f.msg
               for f in hits), hits


def test_s4_fires_on_done_to_idle_in_py_engine(tree):
    """The same DONE -> IDLE escape injected in the Python engine
    alone is caught symmetrically."""
    mutate(tree, "rlo_tpu/engine.py",
           "        p = self.my_own_proposal\n"
           "        if p.state == ReqState.IN_PROGRESS and "
           "p.decision_pending:",
           "        p = self.my_own_proposal\n"
           "        if p.state == ReqState.COMPLETED:\n"
           "            p.state = ReqState.INVALID\n"
           "        if p.state == ReqState.IN_PROGRESS and "
           "p.decision_pending:")
    hits = findings_for(tree, "S4")
    assert any(f.file == "rlo_tpu/engine.py" and
               "COMPLETED -> INVALID" in f.msg and "settled" in f.msg
               for f in hits), hits


def test_s4_fires_on_cross_engine_divergence(tree):
    """Retargeting one engine's guarded completion makes the two
    engines' induced relations diverge — flagged even though each
    relation is individually legal."""
    mutate(tree, "rlo_tpu/engine.py",
           "                p.state = ReqState.COMPLETED\n"
           "                p.decision_pending = False",
           "                p.state = ReqState.IN_PROGRESS\n"
           "                p.decision_pending = False")
    hits = findings_for(tree, "S4")
    assert any("diverge" in f.msg for f in hits), hits


# ---------------------------------------------------------------------------
# CLI contract
# ---------------------------------------------------------------------------

def test_cli_exit_codes_and_json(tree):
    mutate(tree, "rlo_tpu/native/rlo_engine.c",
           "            if (!stamped) {\n"
           "                rlo_pool_free(rt);\n"
           "                return RLO_ERR_NOMEM;\n"
           "            }",
           "            if (!stamped)\n"
           "                return RLO_ERR_NOMEM;")
    proc = subprocess.run(
        [sys.executable, "-m", "rlo_tpu.tools.rlo_sentinel",
         "--root", str(tree)],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "S3" in proc.stdout
    # findings print as file:line: diagnostics (the check.sh contract)
    assert any(ln.split(":")[0].endswith(".c") and
               ln.split(":")[1].isdigit()
               for ln in proc.stdout.splitlines() if "S3" in ln)
    # machine-readable output carries the same findings
    proc = subprocess.run(
        [sys.executable, "-m", "rlo_tpu.tools.rlo_sentinel",
         "--root", str(tree), "--json"],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert proc.returncode == 1
    data = json.loads(proc.stdout)
    assert any(d["rule"] == "S3" and d["line"] > 0 and
               d["severity"] == "error" for d in data), data
    # rule selection: a family that is still clean exits 0
    proc = subprocess.run(
        [sys.executable, "-m", "rlo_tpu.tools.rlo_sentinel",
         "--root", str(tree), "--rules", "S4"],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_lint_json_output():
    """The shared runner gives rlo-lint the same --json face."""
    proc = subprocess.run(
        [sys.executable, "-m", "rlo_tpu.tools.rlo_lint", "--json"],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(proc.stdout) == []
