"""Batched C-engine progress (docs/DESIGN.md §13).

The contract under test: `NativeWorld.progress_n` / `NativeEngine.progress`
change how often the driver crosses into C — never what the engines do.
Driving the same seeded loopback world one sweep per ctypes call and
batched must produce byte-identical delivery order and identical engine
counters, budgets must bind exactly, the deadline must turn the call
into a GIL-released poll-wait, and the C ARQ due-heap must gate the
retransmit sweep without changing retransmit behavior.
"""

import time

import pytest

from rlo_tpu.native.bindings import NativeEngine, NativeWorld

WS = 5
ROUNDS = 4
PAYLOADS = [b"alpha", b"beta-beta", b"g" * 64]


def _workload(batched: bool):
    """Drive ROUNDS rounds of every-rank broadcasts on a fresh seeded
    world (ARQ + metrics + profiler on, latency 0 so the schedule is a
    pure function of the isend/poll order, which both driving modes
    share sweep for sweep). Returns (per-rank delivery order, per-rank
    counters, world sent/delivered)."""
    world = NativeWorld(WS, latency=0, seed=11)
    engines = [NativeEngine(world, r) for r in range(WS)]
    for e in engines:
        e.enable_arq(60_000_000)  # rto >> test: no retransmit jitter
        e.enable_metrics()
        e.enable_profiler()
    order = [[] for _ in range(WS)]
    for rnd in range(ROUNDS):
        for r, e in enumerate(engines):
            e.bcast(PAYLOADS[(rnd + r) % len(PAYLOADS)])
        if batched:
            world.progress_n(max_frames=4096)
        else:
            while not world.quiescent():
                world.progress_all()
        world.drain()
        for r, e in enumerate(engines):
            while (m := e.pickup_next()) is not None:
                order[r].append((m.origin, m.data))
    counters = [e.metrics()["counters"] for e in engines]
    sent, delivered = world.sent_cnt, world.delivered_cnt
    world.close()
    return order, counters, (sent, delivered)


def test_batched_vs_single_step_parity():
    """progress_n(max_frames=4096) == the one-sweep-per-call loop:
    byte-identical delivery order and metrics() counters."""
    o_single, c_single, w_single = _workload(batched=False)
    o_batched, c_batched, w_batched = _workload(batched=True)
    assert o_single == o_batched
    assert c_single == c_batched
    assert w_single == w_batched
    # every broadcast delivered exactly once at every other rank
    assert all(len(o) == ROUNDS * (WS - 1) for o in o_single)
    for c in c_single:
        assert c["arq_unacked"] == 0
        assert c["arq_dup_drops"] == 0


def test_progress_n_budget_binds_exactly():
    world = NativeWorld(4, latency=0, seed=3)
    engines = [NativeEngine(world, r) for r in range(4)]
    engines[0].bcast(b"x" * 32)
    total = 0
    for _ in range(10_000):
        if world.quiescent():
            break
        got = world.progress_n(max_frames=1)
        assert got <= 1
        total += got
    assert world.quiescent()
    for r in range(1, 4):
        got = 0
        while engines[r].pickup_next() is not None:
            got += 1
        assert got == 1
    world.close()


def test_engine_progress_returns_at_first_fruitless_turn():
    """The single-engine face must not spin on other engines'
    traffic: with nothing addressed to it, progress() returns 0."""
    world = NativeWorld(4, latency=0, seed=3)
    engines = [NativeEngine(world, r) for r in range(4)]
    t0 = time.perf_counter()
    assert engines[2].progress() == 0
    assert time.perf_counter() - t0 < 1.0
    world.close()


def test_progress_n_deadline_is_a_poll_wait():
    """With a deadline armed the call keeps polling through idleness —
    the GIL-released serving-pump shape."""
    world = NativeWorld(2, latency=0, seed=1)
    engines = [NativeEngine(world, r) for r in range(2)]
    t0 = time.perf_counter()
    assert world.progress_n(deadline_usec=50_000) == 0
    elapsed = time.perf_counter() - t0
    assert 0.02 <= elapsed < 10.0
    del engines
    world.close()


def test_arq_due_heap_gates_and_recovers_loss():
    """Loss still recovers exactly as before (the heap only gates the
    sweep), and idle ticks ride the O(1) peek."""
    world = NativeWorld(4, latency=0, seed=13)
    engines = [NativeEngine(world, r) for r in range(4)]
    for e in engines:
        e.enable_arq(500, max_retries=12)
    world.drop_next(0, 1, 2)
    for i in range(3):
        engines[0].bcast(b"m%d" % i)
    world.drain()
    retx = sum(e.arq_retransmits for e in engines)
    assert retx >= 2  # the dropped frames really were retransmitted
    for r in range(1, 4):
        got = 0
        while engines[r].pickup_next() is not None:
            got += 1
        assert got == 3  # exactly once despite the loss
    assert all(e.arq_unacked == 0 for e in engines)
    # a long-rto engine parks its wake-ups in the future: every
    # subsequent tick is gated on the heap peek
    for e in engines:
        e.enable_arq(60_000_000)
    engines[0].bcast(b"tail")
    world.drain()
    g0 = engines[0].arq_scan_gated
    for _ in range(50):
        world.progress_all()
    assert engines[0].arq_scan_gated > g0
    assert engines[0].arq_heap_len >= 0
    world.close()


def test_frames_dispatched_counts_every_polled_frame():
    world = NativeWorld(3, latency=0, seed=2)
    engines = [NativeEngine(world, r) for r in range(3)]
    base = sum(e.frames_dispatched for e in engines)
    assert base == 0
    engines[0].bcast(b"count-me")
    world.drain()
    assert sum(e.frames_dispatched for e in engines) >= 2
    world.close()


@pytest.mark.parametrize("latency", [0, 7])
def test_batched_run_is_deterministic(latency):
    """Same seed + same batched call sequence => identical delivery
    order and counters run to run (latency worlds included: the
    dead-time skip must preserve the virtual delivery schedule)."""

    def run():
        world = NativeWorld(4, latency=latency, seed=21)
        engines = [NativeEngine(world, r) for r in range(4)]
        for e in engines:
            e.enable_arq(60_000_000)
            e.enable_metrics()
        out = []
        for rnd in range(3):
            for e in engines:
                e.bcast(b"r%d" % rnd)
            world.progress_n()
            world.drain()
            for r, e in enumerate(engines):
                while (m := e.pickup_next()) is not None:
                    out.append((r, m.origin, m.data))
        counters = [e.metrics()["counters"] for e in engines]
        world.close()
        return out, counters

    assert run() == run()
