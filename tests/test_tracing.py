"""Tracing + metrics: Python and C engines emit the same streams.

The reference has no tracing (SURVEY.md §5); the rebuild's oracle is
cross-implementation: the identical scenario (one bcast + one vetoed IAR
round on the same world size) must produce the same multiset of protocol
events — AND the same metrics-registry snapshot (counter keys identical,
deterministic values equal) — from the Python engine and the native C
core, and the jax.profiler integration must annotate device work without
error.
"""

import copy
from collections import Counter

import pytest

from rlo_tpu.engine import ProgressEngine, EngineManager, drain
from rlo_tpu.native import bindings as nb
from rlo_tpu.transport.loopback import LoopbackWorld
from rlo_tpu.utils.tracing import TRACER, Ev, Tracer, annotate

WS = 8


def run_python_scenario(metrics: bool = False):
    """One bcast from rank 2 + one vetoed proposal from rank 0."""
    world = LoopbackWorld(WS)
    mgr = EngineManager()
    engines = [ProgressEngine(
        world.transport(r),
        judge_cb=lambda payload, ctx, r=r: 0 if r == WS - 1 else 1,
        manager=mgr) for r in range(WS)]
    if metrics:
        for e in engines:
            e.enable_metrics()
    engines[2].bcast(b"hello")
    drain([world], engines)
    for e in engines:
        while e.pickup_next() is not None:
            pass
    engines[0].submit_proposal(b"prop", pid=0)
    drain([world], engines)
    snaps = [e.metrics() for e in engines]
    for e in engines:
        e.cleanup()
    return snaps


def run_native_scenario(metrics: bool = False):
    with nb.NativeWorld(WS) as world:
        engines = [nb.NativeEngine(
            world, r,
            judge_cb=lambda payload, ctx, r=r: 0 if r == WS - 1 else 1)
            for r in range(WS)]
        if metrics:
            for e in engines:
                e.enable_metrics()
        engines[2].bcast(b"hello")
        world.drain()
        for e in engines:
            while e.pickup_next() is not None:
                pass
        rc = engines[0].submit_proposal(b"prop", pid=0)
        if rc == -1:
            world.drain()
        return [e.metrics() for e in engines]


def python_event_counts():
    TRACER.clear()
    with TRACER.enable():
        run_python_scenario()
    counts = Counter(e.kind.name for e in TRACER.events())
    TRACER.clear()
    return counts


def native_event_counts():
    nb.trace_clear()
    nb.trace_set(True)
    try:
        run_native_scenario()
    finally:
        nb.trace_set(False)
    events = nb.trace_drain()
    return Counter(e["kind"] for e in events)


def test_python_and_native_emit_identical_streams():
    py = python_event_counts()
    nat = native_event_counts()
    assert py == nat, (py, nat)
    # structural sanity: three initiations (payload bcast + proposal
    # bcast + decision bcast — all ride the rootless broadcast path)
    assert py["BCAST_INIT"] == 3
    assert py["PROPOSAL_SUBMIT"] == 1
    assert py["DECISION"] == 1
    # every non-origin rank picked up the payload bcast (decisions stay
    # queued — the scenario never drains pickups after the IAR round)
    assert py["DELIVER"] == WS - 1
    # every non-proposer judged the proposal (the veto rank too)
    assert py["JUDGE"] == WS - 1


def _scrub_timing(snap):
    """Zero the wall-clock-dependent metric fields (histogram
    sum/min/max/bucket spread, RTT EWMA) so snapshots compare on the
    deterministic parts; every KEY stays, so schema parity is asserted
    in full."""
    snap = copy.deepcopy(snap)
    for link in snap["links"].values():
        link["rtt_ewma_usec"] = 0.0
    for h in snap["op_latency_usec"].values():
        h["sum"] = h["min"] = h["max"] = 0.0
        h["buckets"] = [0] * len(h["buckets"])
    return snap


def test_python_and_native_report_identical_metrics():
    """Metrics parity (the registry face of the event-parity oracle):
    same scenario -> identical counter keys AND matching deterministic
    values — per-link frame/byte counts, ARQ counters, queue depths,
    histogram counts — from both engines. Only wall-clock-derived
    fields (latency sums/extremes, RTT EWMA) are exempt."""
    py = [_scrub_timing(s) for s in run_python_scenario(metrics=True)]
    nat = [_scrub_timing(s) for s in run_native_scenario(metrics=True)]
    for r in range(WS):
        assert py[r] == nat[r], (r, py[r], nat[r])
    # structural sanity: rank 2's bcast fan-out was accounted, every
    # rank delivered it, and the histograms saw the ops complete
    assert py[2]["counters"]["sent_bcast"] == 1
    assert py[2]["op_latency_usec"]["bcast_complete"]["count"] == 1
    assert py[0]["op_latency_usec"]["proposal_resolve"]["count"] == 1
    for r in range(WS):
        if r == 2:
            continue
        assert py[r]["op_latency_usec"]["pickup_wait"]["count"] >= 1
        total_rx = sum(l["rx_frames"] for l in py[r]["links"].values())
        assert total_rx >= 1


def test_metrics_disabled_schema_is_stable():
    """metrics() with collection off returns the same keys (zeros in
    the gated sections) — dashboards need one schema, not two."""
    on = run_python_scenario(metrics=True)[0]
    off = run_python_scenario(metrics=False)[0]

    def keys(d, prefix=""):
        out = set()
        for k, v in d.items():
            out.add(f"{prefix}{k}")
            if isinstance(v, dict):
                out |= keys(v, f"{prefix}{k}.")
        return out

    assert keys(on) == keys(off)
    assert all(l["tx_frames"] == 0 for l in off["links"].values())
    # counters are always live — they predate the registry
    assert off["counters"]["sent_bcast"] == on["counters"]["sent_bcast"]


def test_tracer_rings_report_dropped_consistently():
    """Overflow accounting satellite: both rings at capacity report
    `dropped` with the same semantics — emitted minus capacity — and
    keep exactly `capacity` newest events."""
    # Python ring (capacity is a constructor knob)
    cap, extra = 64, 9
    t = Tracer(capacity=cap)
    with t.enable():
        for i in range(cap + extra):
            t.emit(0, Ev.DELIVER, i)
    assert t.dropped == extra
    evs = t.events()
    assert len(evs) == cap
    assert [e.a for e in evs] == list(range(extra, cap + extra))

    # C ring (fixed capacity, same overwrite-oldest semantics)
    ccap = nb.trace_capacity()
    nb.trace_clear()
    nb.trace_set(True)
    try:
        for i in range(ccap + extra):
            nb.trace_emit(0, int(Ev.DELIVER), i)
    finally:
        nb.trace_set(False)
    assert nb.trace_dropped() == extra
    evs = nb.trace_drain(ccap + extra)
    assert len(evs) == ccap
    assert evs[0]["a"] == extra and evs[-1]["a"] == ccap + extra - 1
    nb.trace_clear()


def test_tracer_disabled_emits_nothing():
    t = Tracer()
    t.emit(0, Ev.BCAST_INIT, 1, 2)
    assert t.events() == []


def test_tracer_ring_drops_oldest():
    t = Tracer(capacity=4)
    with t.enable():
        for i in range(10):
            t.emit(i, Ev.DELIVER)
    assert len(t.events()) == 4
    assert t.dropped == 6
    assert [e.rank for e in t.events()] == [6, 7, 8, 9]


def test_dump_jsonl(tmp_path):
    t = Tracer()
    with t.enable():
        t.emit(1, Ev.VOTE, 5, 1)
    path = tmp_path / "trace.jsonl"
    assert t.dump_jsonl(str(path)) == 1
    import json
    rec = json.loads(path.read_text().strip())
    assert rec["kind"] == "VOTE" and rec["rank"] == 1 and rec["a"] == 5


def test_profiler_annotation_smoke():
    import jax.numpy as jnp
    with annotate("rlo-allreduce"):
        x = jnp.ones((8, 8)) @ jnp.ones((8, 8))
    assert float(x[0, 0]) == 8.0
