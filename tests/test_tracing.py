"""Tracing subsystem: Python and C engines emit the same event stream.

The reference has no tracing (SURVEY.md §5); the rebuild's oracle is
cross-implementation: the identical scenario (one bcast + one vetoed IAR
round on the same world size) must produce the same multiset of protocol
events from the Python engine and the native C core, and the jax.profiler
integration must annotate device work without error.
"""

from collections import Counter

import pytest

from rlo_tpu.engine import ProgressEngine, EngineManager, drain
from rlo_tpu.native import bindings as nb
from rlo_tpu.transport.loopback import LoopbackWorld
from rlo_tpu.utils.tracing import TRACER, Ev, Tracer, annotate

WS = 8


def run_python_scenario():
    """One bcast from rank 2 + one vetoed proposal from rank 0."""
    world = LoopbackWorld(WS)
    mgr = EngineManager()
    engines = [ProgressEngine(
        world.transport(r),
        judge_cb=lambda payload, ctx, r=r: 0 if r == WS - 1 else 1,
        manager=mgr) for r in range(WS)]
    engines[2].bcast(b"hello")
    drain([world], engines)
    for e in engines:
        while e.pickup_next() is not None:
            pass
    engines[0].submit_proposal(b"prop", pid=0)
    drain([world], engines)
    for e in engines:
        e.cleanup()


def run_native_scenario():
    with nb.NativeWorld(WS) as world:
        engines = [nb.NativeEngine(
            world, r,
            judge_cb=lambda payload, ctx, r=r: 0 if r == WS - 1 else 1)
            for r in range(WS)]
        engines[2].bcast(b"hello")
        world.drain()
        for e in engines:
            while e.pickup_next() is not None:
                pass
        rc = engines[0].submit_proposal(b"prop", pid=0)
        if rc == -1:
            world.drain()


def python_event_counts():
    TRACER.clear()
    with TRACER.enable():
        run_python_scenario()
    counts = Counter(e.kind.name for e in TRACER.events())
    TRACER.clear()
    return counts


def native_event_counts():
    nb.trace_clear()
    nb.trace_set(True)
    try:
        run_native_scenario()
    finally:
        nb.trace_set(False)
    events = nb.trace_drain()
    return Counter(e["kind"] for e in events)


def test_python_and_native_emit_identical_streams():
    py = python_event_counts()
    nat = native_event_counts()
    assert py == nat, (py, nat)
    # structural sanity: three initiations (payload bcast + proposal
    # bcast + decision bcast — all ride the rootless broadcast path)
    assert py["BCAST_INIT"] == 3
    assert py["PROPOSAL_SUBMIT"] == 1
    assert py["DECISION"] == 1
    # every non-origin rank picked up the payload bcast (decisions stay
    # queued — the scenario never drains pickups after the IAR round)
    assert py["DELIVER"] == WS - 1
    # every non-proposer judged the proposal (the veto rank too)
    assert py["JUDGE"] == WS - 1


def test_tracer_disabled_emits_nothing():
    t = Tracer()
    t.emit(0, Ev.BCAST_INIT, 1, 2)
    assert t.events() == []


def test_tracer_ring_drops_oldest():
    t = Tracer(capacity=4)
    with t.enable():
        for i in range(10):
            t.emit(i, Ev.DELIVER)
    assert len(t.events()) == 4
    assert t.dropped == 6
    assert [e.rank for e in t.events()] == [6, 7, 8, 9]


def test_dump_jsonl(tmp_path):
    t = Tracer()
    with t.enable():
        t.emit(1, Ev.VOTE, 5, 1)
    path = tmp_path / "trace.jsonl"
    assert t.dump_jsonl(str(path)) == 1
    import json
    rec = json.loads(path.read_text().strip())
    assert rec["kind"] == "VOTE" and rec["rank"] == 1 and rec["a"] == 5


def test_profiler_annotation_smoke():
    import jax.numpy as jnp
    with annotate("rlo-allreduce"):
        x = jnp.ones((8, 8)) @ jnp.ones((8, 8))
    assert float(x[0, 0]) == 8.0
