"""Flagship transformer: sharded == unsharded, and training decreases loss.

Oracles:
  1. forward parity — logits from the (dp=2, sp=4) sharded model equal
     the single-device model on the same batch;
  2. loss parity — the sp-sharded next-token loss (cross-shard label
     shift via ppermute) equals the unsharded loss;
  3. training works — a few sharded SGD steps on a learnable pattern
     reduce the loss, with ring-allreduce gradient combining.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from rlo_tpu.models.transformer import (TransformerConfig, forward,
                                        init_params, loss_fn, train_step)
from rlo_tpu.parallel.mesh import make_mesh, shard_jit

CFG = TransformerConfig(vocab=64, d_model=64, n_heads=4, n_layers=2,
                        d_ff=128, dtype="float32")
BATCH, SEQ = 4, 32
DP, SP = 2, 4


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def tokens():
    rng = np.random.default_rng(0)
    return jnp.asarray(rng.integers(0, CFG.vocab, (BATCH, SEQ)), jnp.int32)


def test_forward_parity_2d_mesh(params, tokens):
    want = np.asarray(forward(params, tokens, CFG))
    mesh = make_mesh((DP, SP), ("dp", "sp"))
    fn = shard_jit(
        lambda p, t: forward(p, t, CFG, sp_axis="sp"),
        mesh, (P(), P("dp", "sp")), P("dp", "sp"))
    got = np.asarray(fn(params, tokens))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_loss_parity_sp_shift(params, tokens):
    want = float(loss_fn(params, tokens, CFG))
    mesh = make_mesh((SP,), ("sp",))
    fn = shard_jit(
        lambda p, t: loss_fn(p, t, CFG, sp_axis="sp"),
        mesh, (P(), P(None, "sp")), P())
    got = float(fn(params, tokens))
    assert abs(got - want) < 2e-4, (got, want)


@pytest.mark.parametrize("grad_algorithm", ["psum", "ring"])
def test_training_reduces_loss(grad_algorithm):
    cfg = TransformerConfig(vocab=16, d_model=32, n_heads=2, n_layers=1,
                            d_ff=64, dtype="float32")
    params = init_params(jax.random.PRNGKey(1), cfg)
    # learnable data: token t follows t-1 (mod vocab)
    rows = []
    rng = np.random.default_rng(1)
    for _ in range(DP * 2):
        start = rng.integers(0, cfg.vocab)
        rows.append((start + np.arange(SEQ)) % cfg.vocab)
    tokens = jnp.asarray(np.stack(rows), jnp.int32)

    mesh = make_mesh((DP, SP), ("dp", "sp"))
    step = shard_jit(
        lambda p, t: train_step(p, t, cfg, lr=0.2, sp_axis="sp",
                                dp_axis="dp",
                                grad_algorithm=grad_algorithm),
        mesh, (P(), P("dp", "sp")), (P(), P()))
    losses = []
    for _ in range(60):
        params, loss = step(params, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def test_train_step_parity_dp_sp_vs_single_device():
    """Sharded (dp, sp) training must take EXACTLY the step the
    single-device model takes. Regression for the vma migration: under
    check_vma=False the transpose-of-psum-is-psum semantics made
    explicit sp grad syncing scale-wrong; vma AD inserts the correct
    cotangent reductions."""
    cfg = TransformerConfig(vocab=16, d_model=32, n_heads=2, n_layers=1,
                            d_ff=64, dtype="float32")
    p0 = init_params(jax.random.PRNGKey(3), cfg)
    rng = np.random.default_rng(3)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab, (DP * 2, SEQ)),
                         jnp.int32)
    ref_p, ref_loss = jax.jit(
        lambda p, t: train_step(p, t, cfg, lr=0.1))(p0, tokens)
    mesh = make_mesh((DP, SP), ("dp", "sp"))
    step = shard_jit(
        lambda p, t: train_step(p, t, cfg, lr=0.1, sp_axis="sp",
                                dp_axis="dp"),
        mesh, (P(), P("dp", "sp")), (P(), P()))
    new_p, loss = step(p0, tokens)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for (ka, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(new_p)[0],
            jax.tree_util.tree_flatten_with_path(ref_p)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5,
                                   err_msg=jax.tree_util.keystr(ka))


def test_train_step_explicit_ring_pure_dp_matches_single_device():
    """The explicit framework gradient combine (ring + Pallas fused
    per-step reduction) engages on a pure-dp mesh under check_vma=False
    and must reproduce the single-device step exactly."""
    cfg = TransformerConfig(vocab=16, d_model=32, n_heads=2, n_layers=1,
                            d_ff=64, dtype="float32")
    p0 = init_params(jax.random.PRNGKey(4), cfg)
    rng = np.random.default_rng(4)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab, (8, SEQ)), jnp.int32)
    ref_p, ref_loss = jax.jit(
        lambda p, t: train_step(p, t, cfg, lr=0.1))(p0, tokens)
    mesh = make_mesh((8,), ("dp",))
    step = shard_jit(
        lambda p, t: train_step(p, t, cfg, lr=0.1, dp_axis="dp",
                                grad_algorithm="ring"),
        mesh, (P(), P("dp")), (P(), P()), check_vma=False)
    new_p, loss = step(p0, tokens)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(new_p), jax.tree.leaves(ref_p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_train_step_two_tier_dp_matches_single_device():
    """Multi-slice data parallelism (dcn_axis): the explicit two-tier
    combine — in-slice reduce-scatter, DCN allreduce of the scattered
    shard, in-slice all-gather — must reproduce the single-device step
    on a (dcn=2, dp=4) mesh under check_vma=False."""
    cfg = TransformerConfig(vocab=16, d_model=32, n_heads=2, n_layers=1,
                            d_ff=64, dtype="float32")
    p0 = init_params(jax.random.PRNGKey(7), cfg)
    rng = np.random.default_rng(7)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab, (8, SEQ)), jnp.int32)
    ref_p, ref_loss = jax.jit(
        lambda p, t: train_step(p, t, cfg, lr=0.1))(p0, tokens)
    mesh = make_mesh((2, 4), ("dcn", "dp"))
    step = shard_jit(
        lambda p, t: train_step(p, t, cfg, lr=0.1, dp_axis="dp",
                                dcn_axis="dcn"),
        mesh, (P(), P(("dcn", "dp"))), (P(), P()), check_vma=False)
    new_p, loss = step(p0, tokens)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(new_p), jax.tree.leaves(ref_p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_train_step_two_tier_dp_vma_path():
    """Same mesh under vma typing: AD inserts the psums over both data
    axes and grads_and_loss only rescales by the PRODUCT of the two
    axis sizes — a wrong n here silently scales the step."""
    cfg = TransformerConfig(vocab=16, d_model=32, n_heads=2, n_layers=1,
                            d_ff=64, dtype="float32")
    p0 = init_params(jax.random.PRNGKey(8), cfg)
    rng = np.random.default_rng(8)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab, (8, SEQ)), jnp.int32)
    ref_p, ref_loss = jax.jit(
        lambda p, t: train_step(p, t, cfg, lr=0.1))(p0, tokens)
    mesh = make_mesh((2, 4), ("dcn", "dp"))
    step = shard_jit(
        lambda p, t: train_step(p, t, cfg, lr=0.1, dp_axis="dp",
                                dcn_axis="dcn"),
        mesh, (P(), P(("dcn", "dp"))), (P(), P()))
    new_p, loss = step(p0, tokens)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(new_p), jax.tree.leaves(ref_p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_train_step_two_tier_int8_dcn_close_to_exact():
    """Compressed (int8) DCN gradient sync: the step must stay within
    quantization distance of the exact two-tier step — bounded, not
    bit-identical (8-bit mantissas on the slow hop)."""
    cfg = TransformerConfig(vocab=16, d_model=32, n_heads=2, n_layers=1,
                            d_ff=64, dtype="float32")
    p0 = init_params(jax.random.PRNGKey(11), cfg)
    rng = np.random.default_rng(11)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab, (8, SEQ)), jnp.int32)
    mesh = make_mesh((2, 4), ("dcn", "dp"))

    def run(dcn_algorithm):
        step = shard_jit(
            lambda p, t: train_step(p, t, cfg, lr=0.1, dp_axis="dp",
                                    dcn_axis="dcn",
                                    dcn_algorithm=dcn_algorithm),
            mesh, (P(), P(("dcn", "dp"))), (P(), P()), check_vma=False)
        return step(p0, tokens)

    exact_p, exact_loss = run("psum")
    q_p, q_loss = run("int8")
    np.testing.assert_allclose(float(q_loss), float(exact_loss),
                               rtol=1e-5)  # loss precedes the sync
    for a, b in zip(jax.tree.leaves(q_p), jax.tree.leaves(exact_p)):
        a, b = np.asarray(a), np.asarray(b)
        # params moved by lr*grad; quantization perturbs each grad by
        # at most a half-step of its slice's amax/127 scale (~0.4%)
        np.testing.assert_allclose(a, b, rtol=5e-3, atol=5e-4)


def test_int8_dcn_rejected_on_vma_path():
    """Under vma typing the AD-inserted AllReduce cannot be compressed;
    a silently-ignored int8 request must refuse loudly instead."""
    cfg = TransformerConfig(vocab=16, d_model=32, n_heads=2, n_layers=1,
                            d_ff=64, dtype="float32")
    p0 = init_params(jax.random.PRNGKey(12), cfg)
    tokens = jnp.zeros((8, SEQ), jnp.int32)
    mesh = make_mesh((2, 4), ("dcn", "dp"))
    step = shard_jit(
        lambda p, t: train_step(p, t, cfg, lr=0.1, dp_axis="dp",
                                dcn_axis="dcn", dcn_algorithm="int8"),
        mesh, (P(), P(("dcn", "dp"))), (P(), P()))  # check_vma=True
    with pytest.raises(ValueError, match="check_vma=False"):
        step(p0, tokens)


def test_dcn_axis_requires_dp_axis():
    cfg = TransformerConfig(vocab=16, d_model=32, n_heads=2, n_layers=1,
                            d_ff=64, dtype="float32")
    p0 = init_params(jax.random.PRNGKey(9), cfg)
    tokens = jnp.zeros((2, SEQ), jnp.int32)
    with pytest.raises(ValueError, match="dcn_axis requires dp_axis"):
        train_step(p0, tokens, cfg, dcn_axis="dcn")


def test_remat_matches_non_remat_exactly():
    """jax.checkpoint per layer must not change forward numerics or the
    training step — it only changes what the backward rematerializes."""
    import dataclasses
    cfg = TransformerConfig(vocab=16, d_model=32, n_heads=2, n_layers=2,
                            d_ff=64, dtype="float32")
    cfg_r = dataclasses.replace(cfg, remat=True)
    params = init_params(jax.random.PRNGKey(6), cfg)
    rng = np.random.default_rng(6)
    tokens = jnp.asarray(rng.integers(0, 16, (2, SEQ)), jnp.int32)
    f = np.asarray(forward(params, tokens, cfg))
    fr = np.asarray(forward(params, tokens, cfg_r))
    np.testing.assert_array_equal(f, fr)
    p1, l1 = jax.jit(lambda p, t: train_step(p, t, cfg, lr=0.1))(params,
                                                                 tokens)
    p2, l2 = jax.jit(lambda p, t: train_step(p, t, cfg_r, lr=0.1))(params,
                                                                   tokens)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)


def test_optax_adam_training_on_dp_mesh():
    """train_step_optax with Adam on a (dp, sp) mesh: converges, and the
    sharded step matches the single-device optax step exactly."""
    import optax
    from rlo_tpu.models.transformer import train_step_optax
    cfg = TransformerConfig(vocab=16, d_model=32, n_heads=2, n_layers=1,
                            d_ff=64, dtype="float32")
    params = init_params(jax.random.PRNGKey(7), cfg)
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)
    rng = np.random.default_rng(7)
    rows = [(rng.integers(0, 16) + np.arange(SEQ)) % 16
            for _ in range(DP * 2)]
    tokens = jnp.asarray(np.stack(rows), jnp.int32)

    ref_p, ref_s, ref_loss = jax.jit(
        lambda p, s, t: train_step_optax(p, s, t, cfg, opt))(
            params, opt_state, tokens)
    mesh = make_mesh((DP, SP), ("dp", "sp"))
    step = shard_jit(
        lambda p, s, t: train_step_optax(p, s, t, cfg, opt,
                                         sp_axis="sp", dp_axis="dp"),
        mesh, (P(), P(), P("dp", "sp")), (P(), P(), P()))
    new_p, new_s, loss = step(params, opt_state, tokens)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(new_p), jax.tree.leaves(ref_p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)
    # and it actually learns
    losses = []
    p, s = params, opt_state
    for _ in range(60):
        p, s, loss = step(p, s, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_optax_adam_with_tensor_parallel_sharded_moments():
    """Adam on a (dp, tp) mesh: the optimizer moments shard like the
    params (opt_state_pspecs) and the step matches single-device."""
    import optax
    from rlo_tpu.models.transformer import (opt_state_pspecs,
                                            param_pspecs,
                                            train_step_optax)
    cfg = TransformerConfig(vocab=16, d_model=32, n_heads=4, n_layers=1,
                            d_ff=64, dtype="float32")
    params = init_params(jax.random.PRNGKey(8), cfg)
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)
    rng = np.random.default_rng(8)
    tokens = jnp.asarray(rng.integers(0, 16, (4, SEQ)), jnp.int32)
    ref_p, _, ref_loss = jax.jit(
        lambda p, s, t: train_step_optax(p, s, t, cfg, opt))(
            params, opt_state, tokens)
    mesh = make_mesh((2, 4), ("dp", "tp"))
    pspecs = param_pspecs(cfg, "tp")
    sspecs = opt_state_pspecs(opt_state, params, pspecs)
    step = shard_jit(
        lambda p, s, t: train_step_optax(p, s, t, cfg, opt,
                                         dp_axis="dp", tp_axis="tp"),
        mesh, (pspecs, sspecs, P("dp")), (pspecs, sspecs, P()))
    new_p, new_s, loss = step(params, opt_state, tokens)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for (k, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(new_p)[0],
            jax.tree_util.tree_flatten_with_path(ref_p)[0]):
        # adam's rsqrt amplifies last-ulp grad differences from the
        # sharded reduction order early in training
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=5e-5,
            err_msg=jax.tree_util.keystr(k))


def test_grad_parity_ring_vs_psum():
    cfg = TransformerConfig(vocab=16, d_model=32, n_heads=2, n_layers=1,
                            d_ff=64, dtype="float32")
    p0 = init_params(jax.random.PRNGKey(2), cfg)
    rng = np.random.default_rng(2)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab, (DP * 2, SEQ)),
                         jnp.int32)
    mesh = make_mesh((DP, SP), ("dp", "sp"))

    def run(alg):
        step = shard_jit(
            lambda p, t: train_step(p, t, cfg, lr=0.2, sp_axis="sp",
                                    dp_axis="dp", grad_algorithm=alg),
            mesh, (P(), P("dp", "sp")), (P(), P()))
        new_p, loss = step(p0, tokens)
        return new_p, float(loss)

    p_ring, l_ring = run("ring")
    p_psum, l_psum = run("psum")
    assert abs(l_ring - l_psum) < 1e-5
    for a, b in zip(jax.tree.leaves(p_ring), jax.tree.leaves(p_psum)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_chunked_loss_matches_unfused():
    """nll_sum_chunked (online-logsumexp LM head, never materializes
    the (b, blk, vocab) logits) must match the plain loss in value AND
    parameter grads — including a vocab that does not divide the chunk
    (padding-row masking) and the chunk path wired through loss_fn via
    cfg.loss_vocab_chunk."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from rlo_tpu.models.transformer import (TransformerConfig,
                                            init_params, loss_fn)

    cfg0 = TransformerConfig(vocab=1000, d_model=64, n_heads=4,
                             n_layers=2, d_ff=128, dtype="float32",
                             loss_vocab_chunk=0)
    cfg1 = dataclasses.replace(cfg0, loss_vocab_chunk=256)  # 1000 % 256 != 0
    params = init_params(jax.random.PRNGKey(0), cfg0)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg0.vocab, (2, 32)), jnp.int32)

    l0, g0 = jax.value_and_grad(
        lambda p: loss_fn(p, tokens, cfg0))(params)
    l1, g1 = jax.value_and_grad(
        lambda p: loss_fn(p, tokens, cfg1))(params)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_local_attention_flash_fold_matches_unfused():
    """The batch→head fold feeding the flash kernel must match the
    vmapped unfused attention in values AND grads (the single-chip
    train-step path on TPU; interpret mode exercises the same
    kernel)."""
    import jax
    import jax.numpy as jnp

    from rlo_tpu.models.transformer import _local_attention

    rng = np.random.default_rng(4)
    shape = (3, 32, 2, 16)
    q = jnp.asarray(rng.standard_normal(shape) * 0.5, jnp.float32)
    k = jnp.asarray(rng.standard_normal(shape) * 0.5, jnp.float32)
    v = jnp.asarray(rng.standard_normal(shape) * 0.5, jnp.float32)

    def loss(fn):
        def f(q_, k_, v_):
            out = fn(q_, k_, v_)
            w = jnp.sin(jnp.arange(out.size).reshape(out.shape) * 0.01)
            return jnp.sum(out.astype(jnp.float32) * w)
        return f

    flash = lambda a, b_, c: _local_attention(a, b_, c, use_flash=True,
                                              interpret=True)
    plain = lambda a, b_, c: _local_attention(a, b_, c, use_flash=False)
    np.testing.assert_allclose(
        np.asarray(flash(q, k, v)), np.asarray(plain(q, k, v)),
        rtol=2e-5, atol=2e-5)
    gf = jax.grad(loss(flash), argnums=(0, 1, 2))(q, k, v)
    gp = jax.grad(loss(plain), argnums=(0, 1, 2))(q, k, v)
    for a, b_, name in zip(gf, gp, ["dq", "dk", "dv"]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=3e-4, atol=3e-4, err_msg=name)
