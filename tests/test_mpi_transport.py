"""MPI transport executed end-to-end under femtompirun.

The reference's entire L0 is live MPI point-to-point
(/root/reference/rootless_ops.c:656 irecv, :1123/:1152/:1588 isends,
:1613 iallreduce drain) driven by `mpirun -n N ./demo`. The image has no
MPI install, so femtompi (rlo_tpu/native/femtompi/) provides a
functional single-host MPI subset over shared memory plus a launcher;
these tests run the SAME demo scenarios over the real rlo_mpi.c
transport code paths — nonblocking isends, ANY_SOURCE/ANY_TAG probing,
and the MPI_Iallreduce-based termination-detection drain — with real
multi-process traffic (BASELINE config 1's run shape).
"""

import subprocess
from pathlib import Path

import pytest

NATIVE = Path(__file__).resolve().parent.parent / "rlo_tpu" / "native"


@pytest.fixture(scope="module")
def mpi_bins():
    subprocess.run(["make", "mpidemo"], cwd=NATIVE, check=True,
                   capture_output=True)
    return NATIVE / "femtompirun", NATIVE / "rlo_demo_mpi"


def mpirun(mpi_bins, n, *args, timeout=280):
    launcher, demo = mpi_bins
    proc = subprocess.run(
        [str(launcher), "-n", str(n), "-t", str(timeout - 10), str(demo),
         *map(str, args)],
        capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, (
        f"femtompirun failed (rc={proc.returncode})\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}")
    return proc.stdout


@pytest.mark.parametrize("ws", [2, 4, 8])
def test_all_cases_over_mpi(mpi_bins, ws):
    """Every transport-agnostic scenario passes over the MPI transport
    (fail/efail are shm-only and reported as SKIP)."""
    out = mpirun(mpi_bins, ws, "-m", 4, "-b", 65536)
    assert "FAIL" not in out
    assert out.count("PASS") == 11  # runnable cases incl. benches
    assert out.count("SKIP") == 2   # fail/efail


def test_all_cases_flat_fanout(mpi_bins):
    """RLO_FANOUT=flat (depth-1 spanning tree — the round-4 adaptive
    fanout) must pass every scenario: rootlessness, dedup, and IAR
    vote accounting are schedule-independent, and this pins it."""
    import os
    launcher, demo = mpi_bins
    env = dict(os.environ, RLO_FANOUT="flat")
    proc = subprocess.run(
        [str(launcher), "-n", "8", "-t", "270", str(demo), "-m", "4",
         "-b", "65536"],
        capture_output=True, text=True, timeout=280, env=env)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "FAIL" not in proc.stdout
    assert proc.stdout.count("PASS") == 11


def test_multi2_n13_over_mpi(mpi_bins):
    """Concurrent multi-proposal on two engines, non-power-of-2 world,
    real processes, MPI transport."""
    out = mpirun(mpi_bins, 13, "-c", "multi2")
    assert "PASS" in out and "FAIL" not in out


def test_tiny_rings_exercise_pending_sends(mpi_bins):
    """Shrink the shared-memory rings far below the traffic volume so
    femtompi's lazy-flush path (sends parked when a ring is full,
    re-pushed in per-destination FIFO order from the progress loop)
    carries the load — the eager-path-only happy case can't see it."""
    launcher, demo = mpi_bins
    proc = subprocess.run(
        [str(launcher), "-n", "4", "-r", "8192", "-t", "240", str(demo),
         "-c", "hacky", "-m", "32"],
        capture_output=True, text=True, timeout=280)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "PASS" in proc.stdout


def test_iallreduce_drain_under_traffic(mpi_bins):
    """The hacky-sack stress ends in the nonblocking-iallreduce drain
    with traffic still settling — the reference's cleanup-drain shape
    (rootless_ops.c:1613-1625)."""
    out = mpirun(mpi_bins, 8, "-c", "hacky", "-m", 16)
    assert "PASS" in out and "FAIL" not in out


MPI_BACKEND_PROG = r"""
import sys
sys.path.insert(0, {repo!r})
import numpy as np
from rlo_tpu.backend import MpiBackend

b = MpiBackend()
r, ws = b.rank, b.world_size
x = np.full((8,), float(r + 1), np.float32)
got = b.allreduce(x)
assert np.allclose(got, ws * (ws + 1) / 2), (r, got)
g = b.all_gather(np.int32([r]))
assert list(g.reshape(-1)) == list(range(ws)), (r, g)
rs = b.reduce_scatter(np.arange(ws * 2, dtype=np.float32))
assert np.allclose(rs, ws * np.arange(r * 2, r * 2 + 2)), (r, rs)
assert b.consensus(my_vote=1) == 1
d = b.consensus(my_vote=0 if r == ws - 1 else 1)
assert d == 0, (r, d)
b.barrier()
if r == 0:
    print("MPI-BACKEND-OK", ws)
b.close()
"""


def test_python_mpi_backend(mpi_bins, tmp_path):
    """The Python MpiBackend facade end-to-end: one Python process per
    rank over femtompirun, data collectives + veto/approve consensus
    (the bindings auto-build the femtompi-linked native core)."""
    import sys
    launcher, _ = mpi_bins
    repo = str(Path(__file__).resolve().parent.parent)
    prog = tmp_path / "prog.py"
    prog.write_text(MPI_BACKEND_PROG.format(repo=repo))
    proc = subprocess.run(
        [str(launcher), "-n", "4", "-t", "240", sys.executable,
         str(prog)],
        capture_output=True, text=True, timeout=280)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "MPI-BACKEND-OK 4" in proc.stdout


MPI_SUBGROUP_PROG = r"""
import sys
sys.path.insert(0, {repo!r})
import numpy as np
from rlo_tpu.backend import MpiBackend

b = MpiBackend()
r, ws = b.rank, b.world_size
members = [0, 2, ws - 1]
g = b.sub_group(members)          # collective; non-members get None
assert (g is None) == (r not in members), (r, g)
# full-world collective first (everyone)
x = np.full((4,), float(r + 1), np.float32)
assert np.allclose(b.allreduce(x), ws * (ws + 1) / 2)
if g is not None:
    pos = g.pos
    n = g.world_size
    # veto round among the REAL member processes (highest position
    # vetoes; proposer is position 1 — rootless initiation), while
    # the non-member processes are concurrently progressing toward
    # the full-world barrier below on the same world
    d = g.consensus(my_vote=0 if pos == n - 1 else 1, proposer=1)
    assert d == 0, (r, d)
    d = g.consensus(my_vote=1, proposer=0)
    assert d == 1, (r, d)
    got = g.allreduce(np.full((4,), float(pos + 1), np.float32))
    assert np.allclose(got, n * (n + 1) / 2), (r, got)
    out = g.bcast(0, np.arange(3, dtype=np.float32)
                  if pos == 0 else None)
    assert np.allclose(out, np.arange(3)), (r, out)
# everyone re-joins the full world: barrier, then a full consensus
b.barrier()
assert b.consensus(my_vote=1) == 1
if g is not None:
    g.close()
b.barrier()
if r == 0:
    print("MPI-SUBGROUP-OK", ws)
b.close()
"""


def test_mpi_subgroup_consensus_real_processes(mpi_bins, tmp_path):
    """Round-4 VERDICT item: a subset of REAL MPI processes reaches
    consensus (and runs subset collectives) through sub_group while
    the excluded processes coexist on the same world — the backend
    whose ranks are actual OS processes now has the reference's
    consensus-on-any-communicator (rootless_ops.c:467, 1461)."""
    import sys
    launcher, _ = mpi_bins
    repo = str(Path(__file__).resolve().parent.parent)
    prog = tmp_path / "prog.py"
    prog.write_text(MPI_SUBGROUP_PROG.format(repo=repo))
    proc = subprocess.run(
        [str(launcher), "-n", "6", "-t", "240", sys.executable,
         str(prog)],
        capture_output=True, text=True, timeout=280)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "MPI-SUBGROUP-OK 6" in proc.stdout


def test_config1_bench_shape(mpi_bins):
    """BASELINE config 1: fp32 allreduce, 8 MPI ranks, 1 MB buffer —
    the engine-substrate allreduce measured over real MPI processes
    (numeric oracle inside the case)."""
    out = mpirun(mpi_bins, 8, "-c", "bench", "-m", 3, "-b", 1 << 20)
    assert "PASS" in out and "FAIL" not in out
    assert "bench[mpi]" in out
