"""Expert parallelism: MoE FFN with all_to_all dispatch over an ep axis.

Net-new capability completing the framework's strategy set (dp/sp/tp/ep;
the reference has none — SURVEY.md §5). Oracles:
  - all_to_all (xla and ring variants) against the numpy transpose;
  - ep-sharded MoE forward == unsharded MoE on identical params (with
    capacity high enough that no token is dropped, sharding is an
    implementation detail);
  - (dp, ep) training step parity with the single-device step;
  - capacity truncation drops overflow tokens (residual passes through).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from rlo_tpu.models.moe import init_moe_params, moe_ffn
from rlo_tpu.models.transformer import (TransformerConfig, forward,
                                        init_params, param_pspecs,
                                        train_step)
from rlo_tpu.ops import tpu_collectives as tc
from rlo_tpu.parallel.mesh import make_mesh, shard_jit


class TestAllToAll:
    @pytest.mark.parametrize("algorithm", ["xla", "ring", "direct"])
    @pytest.mark.parametrize("ws", [4, 8])
    def test_matches_numpy_transpose(self, algorithm, ws):
        rng = np.random.default_rng(0)
        # global (ws, ws, 3): shard r holds row r = its chunks for all
        data = rng.standard_normal((ws, ws, 3)).astype(np.float32)
        mesh = make_mesh((ws,), ("x",))
        f = shard_jit(
            lambda v: tc.all_to_all(v[0], "x", algorithm=algorithm)[None],
            mesh, (P("x"),), P("x"))
        got = np.asarray(f(jnp.asarray(data)))
        want = np.swapaxes(data, 0, 1)  # chunk (r, s) -> (s, r)
        np.testing.assert_allclose(got, want)

    def test_leading_axis_must_match(self):
        mesh = make_mesh((4,), ("x",))
        with pytest.raises(ValueError, match="leading axis"):
            shard_jit(lambda v: tc.all_to_all(v[0], "x")[None],
                      mesh, (P("x"),), P("x"))(jnp.zeros((4, 3, 2)))


class TestMoEFFN:
    def test_routing_capacity_truncation(self):
        """With capacity 1 and all tokens routed to one expert, only the
        first token gets an output; the rest are dropped (zero)."""
        d, f, e = 8, 16, 4
        params = init_moe_params(jax.random.PRNGKey(0), d, f, e)
        # force routing: huge router weight toward expert 2
        wr = np.zeros((d, e), np.float32)
        wr[:, 2] = 100.0
        params["wr"] = jnp.asarray(wr)
        h = jnp.ones((4, d), jnp.float32)
        out, aux = moe_ffn(params, h, e, capacity_factor=0.25)  # C = 1
        out = np.asarray(out)
        assert np.abs(out[0]).max() > 0
        np.testing.assert_array_equal(out[1:], 0)
        assert float(aux) > 1.0  # heavily imbalanced -> large aux

    @pytest.mark.parametrize("ep", [2, 4])
    def test_ep_sharded_matches_unsharded(self, ep):
        d, f, e, t = 16, 32, 8, 24
        params = init_moe_params(jax.random.PRNGKey(1), d, f, e)
        rng = np.random.default_rng(1)
        h = jnp.asarray(rng.standard_normal((t, d)), jnp.float32)
        # generous capacity: nothing dropped, so sharding is invisible
        ref, ref_aux = moe_ffn(params, h, e, capacity_factor=float(e))
        mesh = make_mesh((ep,), ("ep",))
        specs = {"wr": P(), "w1": P("ep", None, None),
                 "w2": P("ep", None, None)}
        # tokens replicated over ep: every shard must reconstruct the
        # full output. all_to_all results are vma-varying (replication
        # is numeric, not typed), so collect per-shard rows and compare
        # each against the unsharded reference.
        fn = shard_jit(
            lambda p, x: tuple(
                o[None] for o in moe_ffn(p, x, e,
                                         capacity_factor=float(e),
                                         ep_axis="ep")),
            mesh, (specs, P()), (P("ep"), P("ep")))
        out, aux = fn(params, h)
        for r in range(ep):
            np.testing.assert_allclose(np.asarray(out)[r],
                                       np.asarray(ref),
                                       rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(float(np.asarray(aux)[0]),
                                   float(ref_aux), rtol=1e-5)

    def test_ring_all_to_all_variant_matches(self):
        d, f, e = 16, 32, 8
        params = init_moe_params(jax.random.PRNGKey(2), d, f, e)
        rng = np.random.default_rng(2)
        h = jnp.asarray(rng.standard_normal((16, d)), jnp.float32)
        mesh = make_mesh((4,), ("ep",))
        specs = {"wr": P(), "w1": P("ep", None, None),
                 "w2": P("ep", None, None)}

        def run(alg):
            fn = shard_jit(
                lambda p, x: moe_ffn(p, x, e, capacity_factor=float(e),
                                     ep_axis="ep",
                                     all_to_all_algorithm=alg)[0][None],
                mesh, (specs, P()), P("ep"))
            return np.asarray(fn(params, h))
        base = run("xla")
        np.testing.assert_allclose(run("ring"), base, rtol=1e-6)
        np.testing.assert_allclose(run("direct"), base, rtol=1e-6)


class TestMoETransformer:
    CFG = TransformerConfig(vocab=32, d_model=32, n_heads=4, n_layers=2,
                            d_ff=64, dtype="float32", n_experts=4,
                            capacity_factor=8.0)

    def _data(self, batch=2, seq=16):
        params = init_params(jax.random.PRNGKey(0), self.CFG)
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(
            rng.integers(0, self.CFG.vocab, (batch, seq)), jnp.int32)
        return params, tokens

    def test_moe_params_match_pspecs(self):
        params, _ = self._data()
        specs = param_pspecs(self.CFG, ep_axis="ep")
        assert (jax.tree_util.tree_structure(params)
                == jax.tree_util.tree_structure(
                    specs, is_leaf=lambda x: isinstance(x, P)))

    def test_ep_forward_matches_unsharded(self):
        params, tokens = self._data()
        ref = np.asarray(forward(params, tokens, self.CFG))
        mesh = make_mesh((4,), ("ep",))
        specs = param_pspecs(self.CFG, ep_axis="ep")
        # tokens replicated over ep (pure expert parallelism): every
        # shard must produce the full logits; collect per-shard rows
        # since all_to_all results are vma-varying
        f = shard_jit(
            lambda p, t: forward(p, t, self.CFG, ep_axis="ep")[None],
            mesh, (specs, P()), P("ep"))
        got = np.asarray(f(params, tokens))
        for r in range(4):
            np.testing.assert_allclose(got[r], ref, rtol=2e-4, atol=2e-4)

    def test_dp_ep_train_step_matches_single_device(self):
        """(dp, ep) = (2, 4): tokens sharded over both axes, experts over
        ep. Must take the same step as the single device, with the same
        loss (incl. the aux term). Capacity per shard scales with local
        token count, so with a generous factor nothing drops either
        way."""
        cfg = self.CFG
        params = init_params(jax.random.PRNGKey(3), cfg)
        rng = np.random.default_rng(3)
        tokens = jnp.asarray(rng.integers(0, cfg.vocab, (8, 16)),
                             jnp.int32)
        ref_p, ref_loss = jax.jit(
            lambda p, t: train_step(p, t, cfg, lr=1e-2))(params, tokens)
        mesh = make_mesh((2, 4), ("dp", "ep"))
        specs = param_pspecs(cfg, ep_axis="ep")
        step = shard_jit(
            lambda p, t: train_step(p, t, cfg, lr=1e-2, dp_axis="dp",
                                    ep_axis="ep"),
            mesh, (specs, P(("dp", "ep"))), (specs, P()))
        new_p, loss = step(params, tokens)
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=1e-5)
        for (k, a), (_, b) in zip(
                jax.tree_util.tree_flatten_with_path(new_p)[0],
                jax.tree_util.tree_flatten_with_path(ref_p)[0]):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-3, atol=1e-4,
                err_msg=jax.tree_util.keystr(k))

    def test_moe_composes_with_sp(self):
        """MoE + sequence parallelism: the local aux terms must be
        averaged over sp so the loss is sp-invariant (regression: this
        raised an out_specs replication error)."""
        cfg = TransformerConfig(vocab=32, d_model=32, n_heads=4,
                                n_layers=1, d_ff=64, dtype="float32",
                                n_experts=4, capacity_factor=8.0)
        params = init_params(jax.random.PRNGKey(4), cfg)
        rng = np.random.default_rng(4)
        tokens = jnp.asarray(rng.integers(0, cfg.vocab, (2, 32)),
                             jnp.int32)
        ref_p, ref_loss = jax.jit(
            lambda p, t: train_step(p, t, cfg, lr=1e-2))(params, tokens)
        mesh = make_mesh((2, 4), ("ep", "sp"))
        specs = param_pspecs(cfg, ep_axis="ep")
        step = shard_jit(
            lambda p, t: train_step(p, t, cfg, lr=1e-2, sp_axis="sp",
                                    ep_axis="ep"),
            mesh, (specs, P(None, "sp")), (specs, P()))
        new_p, loss = step(params, tokens)
        assert np.isfinite(float(loss))
        # note: sp splits each shard's token slice, so routing capacity
        # and queue order are per-slice — outputs are not bitwise equal
        # to the single-device model, but the loss must be close
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=5e-2)
        del ref_p, new_p

    def test_moe_training_reduces_loss(self):
        cfg = TransformerConfig(vocab=16, d_model=32, n_heads=2,
                                n_layers=1, d_ff=32, dtype="float32",
                                n_experts=4, capacity_factor=4.0)
        params = init_params(jax.random.PRNGKey(5), cfg)
        rng = np.random.default_rng(5)
        rows = [(rng.integers(0, 16) + np.arange(32)) % 16
                for _ in range(4)]
        tokens = jnp.asarray(np.stack(rows), jnp.int32)
        mesh = make_mesh((4,), ("ep",))
        specs = param_pspecs(cfg, ep_axis="ep")
        step = shard_jit(
            lambda p, t: train_step(p, t, cfg, lr=0.2, ep_axis="ep"),
            mesh, (specs, P("ep")), (specs, P()))
        losses = []
        for _ in range(80):
            params, loss = step(params, tokens)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
