"""perf-gate + benchmark-suite contract (docs/DESIGN.md §10).

Unit half: synthetic baseline/fresh documents drive every comparison
rule (exact / factor / rel / abs, both directions, informational
metrics, structural drift) and the rlo-lint-style 0/1/2 exit codes.

Integration half: the committed benchmark scripts produce gateable
documents — the sim scaling curve reproduces its own exact metrics
from a fresh run (tier-1 at --quick; the full n=1024 sweep against
the committed BENCH_sim.json baseline rides the `slow` marker).
"""

import copy
import json
import subprocess
import sys
from pathlib import Path

import pytest

from rlo_tpu.tools.perf_gate import (GateError, compare_metric, main,
                                     run_gate)

REPO_ROOT = Path(__file__).resolve().parents[1]


def doc(**metrics):
    return {"suite": "engine_bench", "schema": 1, "quick": True,
            "config": {"payload": 256},
            "metrics": copy.deepcopy(metrics)}


def m(value, direction="higher", tolerance=None):
    return {"value": value, "direction": direction,
            "tolerance": tolerance}


class TestCompareRules:
    def test_exact_pass_and_fail(self):
        base = m(4.125, "exact")
        assert compare_metric("x", base, 4.125) is None
        msg = compare_metric("x", base, 4.25)
        assert msg and "seed-deterministic" in msg

    def test_factor_higher_better(self):
        base = m(1000.0, "higher", {"factor": 5.0})
        assert compare_metric("x", base, 201.0) is None
        assert compare_metric("x", base, 5000.0) is None  # improvement
        assert compare_metric("x", base, 199.0) is not None

    def test_factor_lower_better(self):
        base = m(100.0, "lower", {"factor": 5.0})
        assert compare_metric("x", base, 499.0) is None
        assert compare_metric("x", base, 1.0) is None  # improvement
        assert compare_metric("x", base, 501.0) is not None

    def test_rel_and_abs(self):
        assert compare_metric("x", m(100.0, "higher", {"rel": 0.1}),
                              91.0) is None
        assert compare_metric("x", m(100.0, "higher", {"rel": 0.1}),
                              89.0) is not None
        assert compare_metric("x", m(100.0, "lower", {"abs": 7.0}),
                              106.0) is None
        assert compare_metric("x", m(100.0, "lower", {"abs": 7.0}),
                              108.0) is not None

    def test_informational_never_fails(self):
        assert compare_metric("x", m(100.0, "higher", None),
                              0.001) is None

    def test_unknown_direction_is_a_finding(self):
        msg = compare_metric("x", m(100.0, "Higher", {"factor": 2.0}),
                             100.0)
        assert msg and "unknown direction" in msg


class TestRunGate:
    def test_clean_run(self):
        base = doc(a=m(100.0, "higher", {"factor": 2.0}),
                   b=m(3.0, "exact"))
        fresh = doc(a=m(60.0), b=m(3.0))
        assert run_gate(base, fresh) == []

    def test_regression_found(self):
        base = doc(a=m(100.0, "higher", {"factor": 2.0}))
        fresh = doc(a=m(40.0))
        findings = run_gate(base, fresh)
        assert len(findings) == 1 and "a:" in findings[0]

    def test_missing_metric_is_a_finding_both_directions(self):
        base = doc(a=m(100.0, "higher", {"factor": 2.0}))
        fresh = doc(b=m(1.0))
        findings = run_gate(base, fresh)
        assert len(findings) == 2
        assert "missing from the fresh run" in findings[0]
        # fresh-only metrics would run ungated — also a finding
        assert "absent from the baseline" in findings[1]

    def test_malformed_fresh_metric_is_an_error(self):
        base = doc(a=m(1.0, "exact"))
        broken = doc()
        broken["metrics"]["a"] = {}
        with pytest.raises(GateError):
            run_gate(base, broken)

    def test_suite_and_config_mismatch_are_errors(self):
        base = doc(a=m(1.0, "exact"))
        other = doc(a=m(1.0, "exact"))
        other["suite"] = "sim_bench"
        with pytest.raises(GateError):
            run_gate(base, other)
        other = doc(a=m(1.0, "exact"))
        other["config"] = {"payload": 999}
        with pytest.raises(GateError):
            run_gate(base, other)


class TestCliExitCodes:
    def _write(self, tmp_path, name, document):
        p = tmp_path / name
        p.write_text(json.dumps(document))
        return str(p)

    def test_exit_0_1_2(self, tmp_path, capsys):
        base = self._write(tmp_path, "base.json",
                           doc(a=m(100.0, "higher", {"factor": 2.0}),
                               b=m(4.0, "exact")))
        good = self._write(tmp_path, "good.json",
                           doc(a=m(90.0), b=m(4.0)))
        bad = self._write(tmp_path, "bad.json",
                          doc(a=m(10.0), b=m(4.0)))
        drifted = self._write(
            tmp_path, "drifted.json",
            {**doc(a=m(90.0), b=m(4.0)), "suite": "sim_bench"})
        assert main(["--baseline", base, "--fresh", good]) == 0
        assert main(["--baseline", base, "--fresh", bad]) == 1
        out = capsys.readouterr().out
        assert "a:" in out and "regression" in out
        assert main(["--baseline", base, "--fresh", drifted]) == 2
        assert main(["--baseline", base,
                     "--fresh", str(tmp_path / "nope.json")]) == 2
        # exact drift is a finding (exit 1), not an error
        exact_drift = self._write(tmp_path, "exact.json",
                                  doc(a=m(90.0), b=m(4.5)))
        assert main(["--baseline", base, "--fresh", exact_drift]) == 1


class TestBenchIntegration:
    def test_sim_bench_quick_reproduces_itself(self, tmp_path):
        """Two --quick sim_bench runs gate clean against each other:
        the virtual-time scaling metrics are seed-exact end to end
        (produce -> JSON -> gate)."""
        outs = []
        for name in ("a.json", "b.json"):
            out = tmp_path / name
            proc = subprocess.run(
                [sys.executable, "benchmarks/sim_bench.py", "--quick",
                 "--out", str(out)],
                capture_output=True, text=True, cwd=REPO_ROOT,
                timeout=240)
            assert proc.returncode == 0, proc.stderr
            outs.append(out)
        rc = main(["--baseline", str(outs[0]), "--fresh", str(outs[1])])
        assert rc == 0
        d = json.loads(outs[0].read_text())
        assert d["suite"] == "sim_bench"
        # the curve covers the documented quick sizes with exact vtime
        assert any(k.startswith("fanout.n256.") for k in d["metrics"])

    @pytest.mark.slow
    def test_full_sweep_gates_against_committed_baseline(self, tmp_path):
        """The full n=1024 scaling sweep reproduces the committed
        BENCH_sim.json exactly (the check.sh gate, run from tier-1's
        slow lane)."""
        out = tmp_path / "sim_full.json"
        proc = subprocess.run(
            [sys.executable, "benchmarks/sim_bench.py", "--out",
             str(out)],
            capture_output=True, text=True, cwd=REPO_ROOT, timeout=600)
        assert proc.returncode == 0, proc.stderr
        rc = main(["--baseline", str(REPO_ROOT / "BENCH_sim.json"),
                   "--fresh", str(out)])
        assert rc == 0

    def test_committed_baselines_are_wellformed(self):
        """The committed benchmark baselines parse and carry gateable
        tolerance specs (every metric has a direction; exact metrics
        exist so protocol drift is actually pinned)."""
        for name in ("BENCH_engine.json", "BENCH_sim.json",
                     "BENCH_fabric.json", "BENCH_serve.json"):
            d = json.loads((REPO_ROOT / name).read_text())
            assert d["metrics"], name
            dirs = {v["direction"] for v in d["metrics"].values()}
            assert dirs <= {"higher", "lower", "exact"}
            assert "exact" in dirs, f"{name} pins nothing exactly"
