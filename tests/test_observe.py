"""Fleet telescope (docs/DESIGN.md §17): telemetry digest codec
(Python ⇔ C byte parity), the in-band telemetry plane's fleet-view
convergence, cross-engine heal-counter parity, and the incident
watchdog's deterministic trip on the churn cascade.
"""

import json
import random

import pytest

from rlo_tpu import wire
from rlo_tpu.engine import EngineManager, ProgressEngine, drain
from rlo_tpu.native import bindings as nb
from rlo_tpu.observe import (DEFAULT_RULES, FleetView, Rule,
                             TelemetryPlane, Watchdog, parse_rule)
from rlo_tpu.transport.loopback import LoopbackWorld
from rlo_tpu.transport.sim import Scenario, SimViolation, SimWorld
from rlo_tpu.utils.metrics import ENGINE_COUNTER_KEYS
from rlo_tpu.wire import (TELEM_HEADER_SIZE, TELEM_KEYS, Frame, Tag,
                          decode_telem, encode_telem)


# ---------------------------------------------------------------------------
# digest codec: round-trip + Python ⇔ C byte parity
# ---------------------------------------------------------------------------

class TestTelemCodec:
    def test_python_roundtrip_full_and_delta(self):
        vals = list(range(10, 10 + len(TELEM_KEYS)))
        raw = encode_telem(3, 2, 7, vals, None)
        rank, epoch, seq, full, deltas = decode_telem(raw)
        assert (rank, epoch, seq, full) == (3, 2, 7, True)
        assert [deltas[k] for k in TELEM_KEYS] == vals
        # delta digest carries only the changed keys
        prev = list(vals)
        vals[0] += 5
        vals[3] -= 2
        raw = encode_telem(3, 2, 8, vals, prev)
        rank, epoch, seq, full, deltas = decode_telem(raw)
        assert not full
        assert deltas == {TELEM_KEYS[0]: 5, TELEM_KEYS[3]: -2}

    def test_python_c_byte_parity(self):
        """The acceptance pin: both codecs produce IDENTICAL bytes for
        identical inputs, and each decodes the other's output."""
        rng = random.Random(17)
        for trial in range(100):
            vals = [rng.randrange(0, 2 ** 40)
                    for _ in range(len(TELEM_KEYS))]
            prev = [v - rng.randrange(-1000, 1000) for v in vals]
            full = trial % 3 == 0
            py = encode_telem(9, 4, trial, vals, prev, full=full)
            c = nb.telem_encode(9, 4, trial, vals, prev, full=full)
            assert py == c
            assert decode_telem(c) == nb.telem_decode(py)

    def test_c_key_table_matches_schema(self):
        assert nb.telem_key_names() == TELEM_KEYS

    def test_malformed_digests_raise(self):
        good = encode_telem(0, 0, 0, [1] * len(TELEM_KEYS))
        with pytest.raises(ValueError):
            decode_telem(b"XXXX" + good[4:])      # bad magic
        with pytest.raises(ValueError):
            decode_telem(good[:10])               # truncated header
        with pytest.raises(ValueError):
            decode_telem(good[:-1])               # truncated varints
        if len(TELEM_KEYS) < 64:
            bad = bytearray(good)
            bad[18 + 7] |= 0x80                   # mask bit 63
            with pytest.raises(ValueError):
                decode_telem(bytes(bad))
        # overlong varint (> 64 payload bits): malformed in BOTH
        # codecs, never a Python bigint the C side would reject
        overlong = good[:TELEM_HEADER_SIZE] + b"\x80" * 10 + b"\x00"
        with pytest.raises(ValueError):
            decode_telem(overlong)
        with pytest.raises(ValueError):
            nb.telem_decode(overlong)

    def test_schema_embeds_counter_keys(self):
        assert TELEM_KEYS[:len(ENGINE_COUNTER_KEYS)] == \
            ENGINE_COUNTER_KEYS
        assert len(TELEM_KEYS) <= 64

    def test_native_engine_originates_digests(self):
        """The C engine's digests decode into its own metrics() —
        full snapshot first, then a correct delta — through the same
        FleetView merge the Python plane uses."""
        with nb.NativeWorld(4) as world:
            engines = [nb.NativeEngine(world, r) for r in range(4)]
            for e in engines:
                e.enable_metrics()
            engines[0].bcast(b"one")
            world.drain()
            view = FleetView(4, self_rank=99)
            raw = engines[0].telem_digest()
            rank, epoch, seq, full, deltas = decode_telem(raw)
            assert (rank, full) == (0, True)
            view.entry(0).apply(epoch, seq, full, deltas, 0.0)
            m = engines[0].metrics()["counters"]
            for k in ENGINE_COUNTER_KEYS:
                if k == "arq_unacked":
                    continue  # live value; may move with drains
                assert view.entry(0).values[k] == m[k], k
            # more traffic -> a DELTA digest that applies cleanly
            engines[0].bcast(b"two")
            world.drain()
            rank, epoch, seq2, full, deltas = decode_telem(
                engines[0].telem_digest())
            assert seq2 == seq + 1 and not full
            view.entry(0).apply(epoch, seq2, full, deltas, 1.0)
            m = engines[0].metrics()["counters"]
            assert view.entry(0).values["sent_bcast"] == \
                m["sent_bcast"] == 2


# ---------------------------------------------------------------------------
# fleet view mechanics
# ---------------------------------------------------------------------------

class TestFleetView:
    def test_gap_parks_entry_until_full_snapshot(self):
        view = FleetView(4, 0)
        ent = view.entry(1)
        base = {k: 10 for k in TELEM_KEYS}
        assert ent.apply(0, 0, True, base, 0.0)
        assert ent.apply(0, 1, False, {"sent_bcast": 2}, 1.0)
        assert ent.values["sent_bcast"] == 12
        # seq 2 lost; seq 3 must NOT apply (it would corrupt values)
        assert not ent.apply(0, 3, False, {"sent_bcast": 1}, 2.0)
        assert ent.gap and ent.values["sent_bcast"] == 12
        # the next full snapshot heals
        assert ent.apply(0, 8, True, {k: 20 for k in TELEM_KEYS}, 3.0)
        assert not ent.gap and ent.values["sent_bcast"] == 20

    def test_rollups_sum_and_max(self):
        view = FleetView(4, 0)
        view.entry(0).apply(0, 0, True, {k: 1 for k in TELEM_KEYS}, 0)
        view.entry(1).apply(0, 0, True, {k: 5 for k in TELEM_KEYS}, 0)
        assert view.rollups()["sent_bcast"] == 6
        assert view.rollup_max()["sent_bcast"] == 5
        snap = view.snapshot(2.0, self_epoch=3)
        assert snap["present"] == 2
        assert snap["ranks"]["1"]["stale_epochs"] == 3


# ---------------------------------------------------------------------------
# the acceptance criterion: 8-rank sim fleet, every digest present,
# rollups == sum of per-rank metrics()
# ---------------------------------------------------------------------------

class TestFleetConvergence:
    def test_8rank_rollups_equal_metrics_sums(self):
        from rlo_tpu.tools.rlo_top import run_fleet
        fleet = run_fleet(8, seed=3)
        fleet.drive(15.0)
        captured = fleet.converge()
        plane = fleet.planes[2]  # ANY rank serves the fleet view
        snap = plane.view.snapshot(fleet.world.now,
                                   self_epoch=fleet.engines[2].epoch)
        assert snap["present"] == 8
        sums = {k: sum(c[k] for c in captured) for k in TELEM_KEYS}
        for k in TELEM_KEYS:
            assert snap["rollups"][k] == sums[k], k
        # and the captures ARE the engines' metrics() at flush time:
        # per-rank counter values in the view match the digest capture
        for r, cap in enumerate(captured):
            ent = snap["ranks"][str(r)]["values"]
            for k in TELEM_KEYS:
                assert ent[k] == cap[k], (r, k)
        assert sums["sent_bcast"] > 0  # traffic actually flowed
        fleet.cleanup()

    def test_rlo_top_json_cli(self, capsys):
        from rlo_tpu.tools import rlo_top
        rc = rlo_top.main(["--json", "--vtime", "6", "--ranks", "4",
                           "--from-rank", "3"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0 and out["ok"] and out["problems"] == []
        assert out["fleet"]["present"] == 4
        assert out["from_rank"] == 3

    def test_rlo_top_bad_invocation(self):
        from rlo_tpu.tools import rlo_top
        assert rlo_top.main(["--ranks", "1"]) == 2

    def test_scenario_telemetry_through_kill_restart(self, tmp_path):
        s = Scenario(world_size=4, seed=3, duration=120.0,
                     script=[(2.0, "bcast", 0), (20.0, "kill", 2),
                             (45.0, "restart", 2),
                             (100.0, "bcast", 3)],
                     telemetry=True)
        res = s.run()
        fv = res["fleet_view"]
        assert fv["present"] == 4  # the REJOINED rank reports too
        assert fv["rollups"]["rejoins"] >= 4
        assert fv["rollups"]["view_changes"] >= 4
        # §18 advert-scoped re-flood: nobody actually lost a frame
        # here, so the adverts suppress every retransmission — the
        # suppression itself is the telemetry signal
        assert fv["rollups"]["reflood_frames"] == 0
        assert fv["rollups"]["reflood_skipped"] > 0
        assert res["telemetry"][0]["malformed"] == 0

    def test_fabric_fleet_stats_is_view_consumer(self):
        from rlo_tpu.serving.fabric import fleet_stats
        from rlo_tpu.tools.rlo_top import run_fleet
        fleet = run_fleet(4, seed=1, fabric=True)
        fleet.drive(12.0)
        fleet.converge()
        fs = fleet_stats(fleet.fabrics)
        # the merged-counters face is unchanged...
        assert fs["counters"]["fabric.requests_admitted"] > 0
        assert "e2e_usec" in fs and "ranks" in fs
        # ...and the attached planes make it a view consumer: the
        # engine-level fleet picture rides along, page occupancy
        # included (the paged stub backend feeds the digest extras)
        fv = fs["fleet_view"]
        assert fv["present"] == 4
        assert fv["rollup_max"]["pages_free"] > 0
        fleet.cleanup()


# ---------------------------------------------------------------------------
# cross-engine parity: every new heal-cost counter, same scenario,
# same values from the Python and C engines
# ---------------------------------------------------------------------------

NEW_KEYS = ("view_changes", "reflood_frames", "epoch_lag_max",
            "quar_mid_rejoin", "quar_failed_sender",
            "quar_below_floor", "admission_rounds",
            "epoch_syncs", "reflood_skipped", "batched_admits")


def _drive_heal_scenario_python():
    ws = 8
    world = LoopbackWorld(ws)
    mgr = EngineManager()
    engines = [ProgressEngine(world.transport(r), manager=mgr,
                              failure_timeout=(0.05 if r == 0
                                               else None))
               for r in range(ws)]
    for i in range(3):
        engines[2].bcast(b"m%d" % i)
    drain([world], engines)
    for e in engines:
        while e.pickup_next() is not None:
            pass
    world.kill_rank(ws - 1)
    engines[-1].cleanup()  # a dead process stops turning its gears
    import time
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and not all(
            len(e.failed) == 1 for e in engines[:-1]):
        mgr.progress_all()
    assert all(len(e.failed) == 1 for e in engines[:-1])
    drain([world], engines[:-1])
    # a stale frame from the dead rank -> failed-sender quarantine
    world.inject(ws - 1, 0, int(Tag.BCAST),
                 Frame(origin=ws - 1, vote=999).encode())
    # an old-epoch frame from a live rank -> accepted, epoch lag
    world.inject(1, 0, int(Tag.BCAST),
                 Frame(origin=1, vote=998, epoch=0).encode())
    mgr.progress_all()
    drain([world], engines[:-1])
    snaps = [e.metrics() for e in engines[:-1]]
    for e in engines:
        e.cleanup()
    return snaps


def _drive_heal_scenario_native():
    import time
    ws = 8
    with nb.NativeWorld(ws) as world:
        engines = [nb.NativeEngine(world, r) for r in range(ws)]
        engines[0].enable_failure_detection(timeout_usec=50_000,
                                            interval_usec=12_500)
        for i in range(3):
            engines[2].bcast(b"m%d" % i)
        world.drain()
        for e in engines:
            while e.pickup_next() is not None:
                pass
        world.kill_rank(ws - 1)
        engines[-1].close()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not all(
                e.failed_count == 1 for e in engines[:-1]):
            world.progress_all()
        assert all(e.failed_count == 1 for e in engines[:-1])
        world.drain()
        raw = nb.frame_roundtrip(ws - 1, -1, 999, b"")[4]
        world.inject(ws - 1, 0, int(Tag.BCAST), raw)
        raw = nb.frame_roundtrip(1, -1, 998, b"")[4]
        world.inject(1, 0, int(Tag.BCAST), raw)
        world.progress_all()
        world.drain()
        return [e.metrics() for e in engines[:-1]]


def test_cross_engine_heal_counter_parity():
    """The churn-flavored parity leg: one kill detected by rank 0 and
    adopted fleet-wide, the view-change re-flood, a stale frame from
    the dead rank (failed-sender quarantine) and an old-epoch frame
    from a live one (epoch lag) — every NEW counter must come out
    IDENTICAL from the two engines, every rank."""
    py = _drive_heal_scenario_python()
    nat = _drive_heal_scenario_native()
    for r in range(7):  # rank 7 is dead
        pc = py[r]["counters"]
        ncs = nat[r]["counters"]
        for k in NEW_KEYS:
            assert pc[k] == ncs[k], (r, k, pc[k], ncs[k])
    # and the values are the deterministic ones the scenario pins:
    # every survivor re-formed once and ADVERTISED its 3-deep log to
    # 6 peers (§18 incremental re-flood) — nobody lost a frame, so
    # each receiver skips all 6x3 advertised entries and not one
    # retransmission goes out; only rank 0 saw the injected frames
    for r in range(7):
        assert py[r]["counters"]["view_changes"] == 1
        assert py[r]["counters"]["reflood_frames"] == 0
        assert py[r]["counters"]["reflood_skipped"] == 18
    assert py[0]["counters"]["quar_failed_sender"] == 1
    assert py[0]["counters"]["epoch_lag_max"] == 1
    assert py[0]["counters"]["quar_mid_rejoin"] == 0
    assert py[0]["counters"]["quar_below_floor"] == 0
    # breakdown sums to the total at every rank
    for r in range(7):
        c = py[r]["counters"]
        assert (c["quar_mid_rejoin"] + c["quar_failed_sender"] +
                c["quar_below_floor"]) == c["epoch_quarantined"]


# ---------------------------------------------------------------------------
# watchdog: grammar, determinism, the churn-cascade trip + bundle
# ---------------------------------------------------------------------------

class TestWatchdogRules:
    def test_grammar_roundtrip(self):
        r = parse_rule("rejoin-cascade: sum(rejoins) / 30s >= 0.5")
        assert (r.name, r.key, r.agg, r.mode, r.window,
                r.op, r.threshold) == \
            ("rejoin-cascade", "rejoins", "sum", "rate", 30.0,
             ">=", 0.5)
        assert parse_rule(r.spec()) == r
        lvl = parse_rule("lag: max(epoch_lag_max) >= 8")
        assert lvl.mode == "level" and lvl.agg == "max"
        for rule in DEFAULT_RULES:
            assert parse_rule(rule).spec()  # all defaults parse

    def test_grammar_rejects(self):
        with pytest.raises(ValueError):
            parse_rule("bad rule text")
        with pytest.raises(ValueError):
            parse_rule("x: sum(not_a_key) >= 1")
        with pytest.raises(ValueError):
            Rule("x", "rejoins", 1.0, agg="median")

    def test_level_rule_trips_with_cooldown(self):
        world = SimWorld(2, seed=0)
        mgr = EngineManager()
        engines = [ProgressEngine(world.transport(r), manager=mgr,
                                  clock=world.clock)
                   for r in range(2)]
        plane = TelemetryPlane(engines[0], interval=0.5)
        wd = Watchdog(plane, ["sent: sum(sent_bcast) >= 2"],
                      cooldown=10.0)
        assert wd.check() == []
        engines[0].bcast(b"a")
        engines[0].bcast(b"b")
        plane.emit()
        fired = wd.check()
        assert [i.rule.name for i in fired] == ["sent"]
        assert fired[0].value >= 2
        assert wd.check() == []  # cooldown holds
        for e in engines:
            e.cleanup()


def _cascade_scenario(seed, incident_dir=None):
    from rlo_tpu.workloads.weather import make_weather
    w = make_weather("churn", seed=1, world_size=16, rate=0.05,
                     duration=60.0, start=8.0, mean_down=20.0,
                     min_down=13.0, min_live=14, settle=25.0,
                     immortal=(0,))
    return Scenario(
        world_size=16, seed=seed, duration=60.0, weather=w,
        failure_timeout=3.0, heartbeat_interval=1.0, arq_rto=1.5,
        arq_max_retries=6, op_deadline=30.0, check_delivery=False,
        telemetry=True,
        # hair-trigger threshold: the §18 healing work cured the
        # genuine cascade this leg used to produce (the run now ENDS
        # CONVERGED), so the trip machinery is exercised against the
        # ordinary-churn rejoin rate instead of a pathology
        watchdog_rules=["rejoin-cascade: sum(rejoins) / 30s >= 0.02"],
        incident_dir=incident_dir)


class TestCascadeWatchdog:
    def test_trips_deterministically_with_complete_bundle(
            self, tmp_path):
        """The watchdog trips deterministically, writes a complete
        incident bundle, and the embedded replay recipe reproduces
        the trip. (The churn.n16.r0.05 leg this rides used to END
        UNCONVERGED — a rejoin cascade — and the run itself raised;
        since the §18 healing work it converges, so the scenario arms
        a hair-trigger threshold to exercise the same machinery.)"""
        s = _cascade_scenario(0, incident_dir=str(tmp_path))
        s.run()  # converges now — the §18 acceptance, not a violation
        incs = s._watchdog.incidents
        assert [i.rule.name for i in incs][:1] == ["rejoin-cascade"]
        first = incs[0]
        assert first.bundle_dir is not None
        bundle = json.load(open(f"{first.bundle_dir}/incident.json"))
        # bundle completeness: rule + value + vtime + replay + fleet
        # view + per-rank traces + merged Chrome trace
        assert bundle["name"] == "rejoin-cascade"
        assert bundle["value"] >= 0.02
        assert bundle["vtime"] == first.vtime
        assert "Scenario(" in bundle["replay"]
        fv = json.load(open(f"{first.bundle_dir}/fleet_view.json"))
        assert fv["present"] >= 2
        trace = json.load(open(f"{first.bundle_dir}/trace.json"))
        assert "traceEvents" in trace
        import os
        names = sorted(os.listdir(first.bundle_dir))
        assert "incident.json" in names and "trace.json" in names
        assert any(n.startswith("rank") and n.endswith(".jsonl")
                   for n in names)

        # the replay recipe replays: same seed => same trip vtime
        ns = {}
        from rlo_tpu.transport import sim as sim_mod
        from rlo_tpu.workloads.weather import make_weather
        ns["Scenario"] = sim_mod.Scenario
        ns["make_weather"] = make_weather
        expr = bundle["replay"]
        assert expr.endswith(".run()")
        s2 = eval(expr[:-len(".run()")], ns)  # noqa: S307 - own recipe
        s2.run()
        assert s2._watchdog.incidents[0].vtime == first.vtime
        assert s2._watchdog.incidents[0].value == first.value

    def test_no_false_trip_across_watched_rank_restart(self):
        """An ordinary kill/restart of the WATCHED rank must not trip
        the rate rules: the fresh plane's view rebuild is not a storm
        (the watchdog rebind clears rate histories), and a burst
        denominated over a short retained history is not a rate
        (Δ is divided by the NOMINAL window)."""
        s = Scenario(world_size=4, seed=3, duration=120.0,
                     script=[(2.0, "bcast", 1), (20.0, "kill", 0),
                             (45.0, "restart", 0),
                             (100.0, "bcast", 3)],
                     telemetry=True,
                     watchdog_rules=[
                         "retransmit-storm: sum(arq_retransmits)"
                         " / 10s >= 5.0",
                         "rejoin-cascade: sum(rejoins) / 30s >= 0.5"],
                     check_delivery=False)
        s.run()
        assert s._watchdog.incidents == []

    def test_healthy_fleet_never_trips_defaults(self):
        """The default SLO thresholds stay quiet on a clean fleet —
        a watchdog that cries wolf is worse than none."""
        from rlo_tpu.tools.rlo_top import run_fleet
        fleet = run_fleet(4, seed=0,
                          watchdog_rules=list(DEFAULT_RULES))
        fleet.drive(12.0)
        fleet.converge()
        for plane in fleet.planes:
            if plane.watchdog is not None:
                assert plane.watchdog.incidents == []
        fleet.cleanup()
