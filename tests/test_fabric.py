"""Rootless elastic serving fabric (rlo_tpu/serving, docs/DESIGN.md
§11): a multi-rank DecodeServer tier scheduled by the paper's own
primitives, proven in the deterministic simulator before any real
transport — the PR-3 convention.

The acceptance scenarios:

  - a serving rank killed MID-decode: every accepted request completes
    exactly once on a survivor with identical tokens (seed-exact);
  - split-brain during a request burst: both sides keep serving, the
    minority's accepted requests are re-admitted after the heal with
    no duplicate completions;
  - kill + elastic rejoin under continuous load: the rejoined rank
    converges (placement included) and the fleet drains;
  - same seed => byte-identical schedule AND identical completion
    tokens on every rank;
  - the real ``models.serve.DecodeServer`` behind the fabric
    (ModelBackend): fabric completions equal the dense ``generate``
    oracle, including a request re-queued across a kill.
"""

import logging

import pytest

from rlo_tpu.serving.backend import StubBackend, stub_tokens
from rlo_tpu.serving.fabric import DecodeFabric, fleet_stats
from rlo_tpu.serving.placement import (Placement, owner_of, pick_owner,
                                       rendezvous_owner)
from rlo_tpu.serving.scenario import (FABRIC_SCENARIO_KINDS,
                                      FabricScenario,
                                      make_fabric_scenario)
from rlo_tpu.transport.sim import SimViolation, make_scenario

logging.getLogger("rlo_tpu").setLevel(logging.ERROR)


# ---------------------------------------------------------------------------
# placement / routing units
# ---------------------------------------------------------------------------

class TestPlacement:
    def test_rendezvous_deterministic_and_stable(self):
        members = (0, 1, 2, 3)
        owners = [rendezvous_owner(1, s, members) for s in range(64)]
        assert owners == [rendezvous_owner(1, s, members)
                          for s in range(64)]
        # spreads across members (HRW, not all-on-one)
        assert len(set(owners)) > 1
        # removing a member only moves ITS requests (HRW minimality)
        shrunk = (0, 1, 3)
        for s, o in enumerate(owners):
            if o != 2:
                assert rendezvous_owner(1, s, shrunk) == o

    def test_owner_of_admit_record_authoritative(self):
        pl = Placement(version=1, proposer=0, members=(0, 1, 2))
        assert owner_of((0, 7), 2, pl) == 2
        # admit-time owner left the member set: rendezvous re-places
        pl2 = Placement(version=2, proposer=0, members=(0, 1))
        assert owner_of((0, 7), 2, pl2) in (0, 1)

    def test_placement_codec_and_order(self):
        pl = Placement(version=3, proposer=1, members=(0, 2, 3))
        assert Placement.decode(pl.encode()) == pl
        assert Placement.decode(b"\x01") is None
        assert Placement(4, 0, (0, 1)).key() > pl.key()

    def test_pick_owner_least_loaded(self):
        loads = {0: (0, 5), 1: (2, 0), 2: (1, 0)}
        assert pick_owner(0, (0, 1, 2), loads) == 1
        # no reports at all: lowest rank
        assert pick_owner(2, (1, 2, 3), {}) == 1


class TestStubBackend:
    def test_tokens_deterministic_and_rank_independent(self):
        a = stub_tokens((5, 6, 7), 12)
        assert a == stub_tokens((5, 6, 7), 12)
        assert len(a) == 12
        assert a != stub_tokens((5, 6, 8), 12)

    def test_slot_scheduling_and_cancel(self):
        b = StubBackend(n_slots=1, round_len=4)
        b.submit("a", (1, 2), 8)
        b.submit("b", (3, 4), 4)
        assert b.load() == (1, 2)
        assert b.step_round() == []          # a mid-flight
        assert b.cancel("a") is True
        done = b.step_round()                # b admitted + finishes
        assert [k for k, _ in done] == ["b"]
        assert done[0][1] == stub_tokens((3, 4), 4)
        assert not b.has_work()


# ---------------------------------------------------------------------------
# the acceptance scenarios (deterministic simulator, stub backend)
# ---------------------------------------------------------------------------

class TestFabricScenarios:
    def test_kill_mid_decode_exactly_once_on_survivors(self):
        """Kill the warm-up owner with decodes in flight: the
        PR-1/PR-3 failure machinery detects it, IAR re-places, and
        survivors complete EVERY accepted request exactly once with
        oracle-identical tokens — and no decode work is duplicated."""
        res = make_fabric_scenario("fabric_kill", seed=2).run()
        assert res["requeues"] > 0          # orphans were re-queued
        assert res["dup_done"] == 0         # no duplicated decode
        # every survivor completed every accepted request
        assert set(res["completed"].values()) == {res["submitted"]}
        # identical tokens on every rank (oracle equality is checked
        # inside FabricScenario.run property checks)
        views = list(res["done_tokens"].values())
        assert all(v == views[0] for v in views[1:])

    def test_split_brain_burst_readmitted_after_heal(self):
        """A partition lands mid-burst: both sides keep serving under
        their own placements; after the heal the minority's accepted
        requests are re-admitted (pending ADMITs re-broadcast on view
        growth) and the fleet converges with no duplicate
        completions."""
        res = make_fabric_scenario("fabric_split", seed=0).run()
        assert res["readmitted"] > 0        # re-admission exercised
        assert res["requeues"] > 0          # cross-side re-placement
        assert res["rejoins"] > 0           # the heal went through IAR
        assert set(res["completed"].values()) == {res["submitted"]}

    def test_rejoin_under_load_converges(self):
        """Kill + elastic rejoin with submissions continuing
        throughout: the restarted rank is admitted through IAR,
        adopts the fleet's request state (ADMIT/DONE re-broadcast),
        and the final placement covers all four ranks again."""
        res = make_fabric_scenario("fabric_rejoin", seed=0).run()
        assert res["rejoins"] > 0
        assert res["requeues"] > 0
        assert res["placement_version"] > 0
        assert set(res["completed"].values()) == {res["submitted"]}

    def test_same_seed_identical_schedule_and_tokens(self):
        a = make_fabric_scenario("fabric_kill", seed=1).run()
        b = make_fabric_scenario("fabric_kill", seed=1).run()
        assert a["digest"] == b["digest"] != "protocol-only"
        assert a["done_tokens"] == b["done_tokens"]

    def test_make_scenario_routes_fabric_kinds(self):
        for kind in FABRIC_SCENARIO_KINDS:
            assert isinstance(make_scenario(kind, 0), FabricScenario)

    def test_violation_carries_seed_and_replay_recipe(self):
        sc = FabricScenario(world_size=4, seed=31)
        with pytest.raises(SimViolation) as ei:
            sc._fail("synthetic")
        msg = str(ei.value)
        assert "seed 31" in msg and "FabricScenario(" in msg


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------

def test_done_ttl_evicts_completion_cache():
    """ISSUE-11 satellite: with ``done_ttl`` set, the rid→tokens DONE
    table ages out past the horizon (fabric.done_evicted counts it),
    while the default keeps everything; exactly-once completion is
    untouched within the horizon."""
    from rlo_tpu.engine import EngineManager, ProgressEngine
    from rlo_tpu.transport.sim import SimWorld

    world = SimWorld(3, seed=5)
    mgr = EngineManager()
    engines = [ProgressEngine(world.transport(r), manager=mgr,
                              clock=world.clock)
               for r in range(3)]
    fabrics = [DecodeFabric(engines[r], StubBackend(n_slots=2),
                            decode_interval=1.0,
                            done_ttl=30.0 if r != 2 else None)
               for r in range(3)]

    def run_until(cond, limit):
        while world.now < limit:
            world.step()
            mgr.progress_all()
            for f in fabrics:
                f.pump()
            if cond():
                return True
        return False

    rid = fabrics[0].submit((4, 4), 6)
    assert run_until(
        lambda: all(f.result(rid) is not None for f in fabrics), 40.0)
    tokens = fabrics[0].result(rid)
    assert tokens == stub_tokens((4, 4), 6, None)
    # age the fleet past the horizon: TTL fabrics evict, default keeps
    # (each rank evicts on its own clock — wait for both)
    assert run_until(lambda: all(f.result(rid) is None
                                 for f in fabrics[:2]), 120.0)
    for f in fabrics[:2]:
        assert f.result(rid) is None
        snap = f.metrics.snapshot()
        assert snap["counters"]["fabric.done_evicted"] >= 1
        assert not f.done and not f.done_by
    assert fabrics[2].result(rid) == tokens  # default: keep forever
    assert "fabric.done_evicted" not in \
        fabrics[2].metrics.snapshot()["counters"]
    # the completion LOG (client-visible exactly-once record) survives
    assert all(rid in f.completions for f in fabrics)
    # a DONE replayed for an evicted rid (heal re-broadcast from a
    # keep-everything peer) must NOT re-complete it: the tombstone
    # absorbs the copy and the log stays exactly-once
    from rlo_tpu.serving.fabric import _enc_done
    replay = _enc_done(rid, fabrics[2].done_by[rid],
                       fabrics[2].done[rid])
    fabrics[0]._on_record(replay, 2)
    assert fabrics[0].completions.count(rid) == 1
    assert fabrics[0].result(rid) is None
    snap0 = fabrics[0].metrics.snapshot()["counters"]
    assert snap0["fabric.done_copies"] >= 1
    # ...and a replayed ADMIT for the evicted rid is not re-admitted
    from rlo_tpu.serving.fabric import _enc_admit
    fabrics[0]._on_record(_enc_admit(rid, 0, 6, -1, (4, 4)), 2)
    assert rid not in fabrics[0].requests
    # a fresh request after eviction still completes exactly once
    rid2 = fabrics[1].submit((7,), 4)
    assert run_until(
        lambda: all(f.result(rid2) is not None for f in fabrics), 200.0)
    assert all(f.completions.count(rid2) == 1 for f in fabrics)


def test_fleet_stats_rollup():
    """Fleet stats: summed counters, merged e2e latency summary
    (submit -> last token INCLUDING fail-over re-queue time), and
    per-rank snapshots — run off the kill scenario so the e2e
    histogram really contains post-kill completions."""
    from rlo_tpu.engine import EngineManager, ProgressEngine
    from rlo_tpu.transport.sim import SimWorld

    world = SimWorld(4, seed=0)
    mgr = EngineManager()
    engines = [ProgressEngine(world.transport(r), manager=mgr,
                              clock=world.clock, failure_timeout=6.0,
                              heartbeat_interval=1.0, arq_rto=1.5,
                              arq_max_retries=6, op_deadline=20.0)
               for r in range(4)]
    fabrics = [DecodeFabric(engines[r], StubBackend(n_slots=2),
                            decode_interval=1.0) for r in range(4)]
    rids = [fabrics[1].submit((9, 9, 9), 20),
            fabrics[2].submit((8, 8), 12)]
    live = {0, 1, 2, 3}
    killed = False
    while world.now < 80.0:
        if world.now >= 3.0 and not killed:
            killed = True          # kill the warm-up owner mid-decode
            world.kill_rank(0)
            engines[0].cleanup()
            live.discard(0)
        world.step()
        mgr.progress_all()
        for r in sorted(live):
            fabrics[r].pump()
        if all(f.result(rid) is not None
               for f in (fabrics[1], fabrics[2], fabrics[3])
               for rid in rids):
            break
    fl = fleet_stats([fabrics[r] for r in sorted(live)])
    assert fl["counters"]["fabric.requests_completed"] >= 2 * 3
    assert fl["e2e_usec"]["count"] >= 2 * 3
    assert fl["e2e_usec"]["p50"] is not None
    assert set(fl["ranks"]) == {"1", "2", "3"}
    one = fl["ranks"]["1"]
    assert one["placement"]["members"] == [1, 2, 3]
    assert one["backend"]["backend"] == "stub"
    # both requests completed exactly once everywhere, tokens = oracle
    for r in (1, 2, 3):
        assert fabrics[r].result(rids[0]) == stub_tokens((9, 9, 9), 20)
        assert len(fabrics[r].completions) == \
            len(set(fabrics[r].completions))


# ---------------------------------------------------------------------------
# the real DecodeServer behind the fabric (ModelBackend)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_model():
    import jax

    from rlo_tpu.models.transformer import TransformerConfig, init_params
    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4,
                            n_layers=2, d_ff=64, dtype="float32")
    params = init_params(jax.random.PRNGKey(0), cfg)
    return params, cfg


def _model_fabric_world(params, cfg, n_ranks):
    from rlo_tpu.engine import EngineManager, ProgressEngine
    from rlo_tpu.models.serve import DecodeServer
    from rlo_tpu.serving.backend import ModelBackend
    from rlo_tpu.transport.sim import SimWorld

    world = SimWorld(n_ranks, seed=0)
    mgr = EngineManager()
    engines = [ProgressEngine(world.transport(r), manager=mgr,
                              clock=world.clock, failure_timeout=6.0,
                              heartbeat_interval=1.0, arq_rto=1.5,
                              arq_max_retries=6, op_deadline=20.0)
               for r in range(n_ranks)]
    fabrics = [DecodeFabric(
        engines[r],
        ModelBackend(DecodeServer(params, cfg, n_slots=2, max_len=64,
                                  round_len=4, prompt_buckets=(8, 16))),
        decode_interval=1.0) for r in range(n_ranks)]
    return world, mgr, engines, fabrics


def _dense_oracle(params, cfg, prompt, max_new):
    import jax.numpy as jnp
    import numpy as np

    from rlo_tpu.models.generate import generate
    out = generate(params, jnp.asarray(prompt, jnp.int32)[None, :],
                   cfg, max_new=max_new)
    return tuple(int(t) for t in np.asarray(out)[0])


def test_model_backend_fabric_matches_dense_generate(tiny_model):
    """2-rank fabric over the REAL continuous-batching DecodeServer:
    every fabric completion equals the dense generate oracle — the
    fabric is a scheduling/placement layer, not a numerics change."""
    import numpy as np

    params, cfg = tiny_model
    world, mgr, engines, fabrics = _model_fabric_world(params, cfg, 2)
    rng = np.random.default_rng(0)
    reqs = [(tuple(int(t) for t in rng.integers(0, cfg.vocab, (p,))),
             m) for p, m in ((5, 6), (9, 8), (4, 3))]
    rids = [fabrics[0].submit(p, m) for p, m in reqs]
    live = (0, 1)
    while world.now < 60.0:
        world.step()
        mgr.progress_all()
        for r in live:
            fabrics[r].pump()
        if all(fabrics[r].result(rid) is not None
               for r in live for rid in rids):
            break
    for (p, m), rid in zip(reqs, rids):
        want = _dense_oracle(params, cfg, p, m)
        for r in live:
            assert fabrics[r].result(rid) == want


def test_model_backend_requeue_after_kill_identical_tokens(tiny_model):
    """3-rank fabric, the owner killed mid-decode: the re-queued
    request restarts from the prompt on a survivor's DecodeServer and
    completes with tokens identical to the dense oracle (greedy decode
    over replicated weights)."""
    import numpy as np

    params, cfg = tiny_model
    world, mgr, engines, fabrics = _model_fabric_world(params, cfg, 3)
    rng = np.random.default_rng(1)
    prompt = tuple(int(t) for t in rng.integers(0, cfg.vocab, (6,)))
    # gateway 1; the admit-time owner is rank 0 (least-loaded default
    # before any gossip lands), which we kill mid-decode
    rid = fabrics[1].submit(prompt, 14)
    live = {0, 1, 2}
    killed = False
    while world.now < 90.0:
        if not killed and world.now >= 2.5:
            killed = True
            world.kill_rank(0)
            engines[0].cleanup()
            live.discard(0)
        world.step()
        mgr.progress_all()
        for r in sorted(live):
            fabrics[r].pump()
        if killed and all(fabrics[r].result(rid) is not None
                          for r in live):
            break
    assert killed
    want = _dense_oracle(params, cfg, prompt, 14)
    for r in sorted(live):
        assert fabrics[r].result(rid) == want, f"rank {r} diverged"
    assert sum(f.requeues for f in (fabrics[1], fabrics[2])) == 1
    # exactly-once: one completion record per rank, no duplicates
    for r in sorted(live):
        assert fabrics[r].completions.count(rid) == 1


# ---------------------------------------------------------------------------
# fabric_bench reproduces itself (the BENCH_fabric.json contract)
# ---------------------------------------------------------------------------

def test_fabric_bench_quick_reproduces_itself(tmp_path):
    """Two --quick fabric_bench runs agree on every seed-exact metric
    (produce -> JSON -> gate contract of BENCH_fabric.json), and the
    failover leg actually re-queues work."""
    import json
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    docs = []
    for name in ("a.json", "b.json"):
        out = tmp_path / name
        proc = subprocess.run(
            [sys.executable, "benchmarks/fabric_bench.py", "--quick",
             "--out", str(out)],
            capture_output=True, text=True, cwd=repo, timeout=240)
        assert proc.returncode == 0, proc.stderr
        docs.append(json.loads(out.read_text()))
    da, db = docs
    assert da["suite"] == "fabric_bench"
    for name, m in da["metrics"].items():
        if m["direction"] == "exact":
            assert db["metrics"][name]["value"] == m["value"], name
    assert da["metrics"]["failover4.requeues"]["value"] > 0


def test_magic_only_record_is_counted_not_crash():
    """Round-15 regression (rlo-sentinel S2): a payload that is
    exactly FABRIC_MAGIC — or magic + nothing — passes the pump's
    startswith() routing but has no kind byte.  Pre-fix, _on_record
    raised IndexError inside every rank's pump; now it counts an
    unknown record and the fleet keeps serving."""
    from rlo_tpu.engine import EngineManager, ProgressEngine
    from rlo_tpu.serving.fabric import FABRIC_MAGIC
    from rlo_tpu.transport.sim import SimWorld

    world = SimWorld(2, seed=9)
    mgr = EngineManager()
    engines = [ProgressEngine(world.transport(r), manager=mgr,
                              clock=world.clock) for r in range(2)]
    fabrics = [DecodeFabric(engines[r], StubBackend(n_slots=1),
                            decode_interval=1.0) for r in range(2)]
    # direct hit on the record dispatch (the minimal pre-fix crash)
    fabrics[0]._on_record(bytes(FABRIC_MAGIC), 1)
    assert fabrics[0].metrics.snapshot()["counters"][
        "fabric.unknown_records"] >= 1
    # and through the real wire path: a hostile/corrupt broadcast
    engines[1].bcast(bytes(FABRIC_MAGIC))
    for _ in range(60):
        world.step()
        mgr.progress_all()
        for f in fabrics:
            f.pump()
    # the fleet still serves after absorbing the junk frame
    rid = fabrics[0].submit((3, 3), 4)
    for _ in range(200):
        world.step()
        mgr.progress_all()
        for f in fabrics:
            f.pump()
        if all(f.result(rid) is not None for f in fabrics):
            break
    assert fabrics[1].result(rid) == stub_tokens((3, 3), 4, None)
