"""Pallas flash-decode kernel vs the einsum cache-attend oracle
(rlo_tpu.pallas.decode vs models.generate._attend_cache).

The kernel is exact-class against the f32 oracle (same masking, online
softmax changes only association order); for int8 caches it is MORE
precise than the einsum path (f32 accumulation vs the bf16 matmul), so
the quantized comparison targets the dequantized-f32 reference.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rlo_tpu.models.generate import (_attend_cache, _attend_cache_block,
                                     _quantize_kv)
from rlo_tpu.pallas.decode import (can_flash_decode, flash_block_decode,
                                   flash_decode)

B, NH, NKV, D, L = 3, 8, 4, 64, 48


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, 1, NH, D)), jnp.float32)
    # SEQ-MINOR cache layout (b, kvh, head_dim, L) — models.generate
    kc = jnp.asarray(rng.standard_normal((B, NKV, D, L)), jnp.float32)
    vc = jnp.asarray(rng.standard_normal((B, NKV, D, L)), jnp.float32)
    return q, kc, vc, 1.0 / np.sqrt(D)


def _oracle(q, kc, vc, pos, scale, ks=None, vs=None):
    return np.asarray(_attend_cache(q, kc, vc, pos, scale, k_scale=ks,
                                    v_scale=vs, use_flash=False))


@pytest.mark.parametrize("pos", [0, 7, L - 1])
def test_scalar_pos_matches_oracle(data, pos):
    q, kc, vc, scale = data
    got = np.asarray(flash_decode(q, kc, vc, pos, scale,
                                  interpret=True, block_k=16))
    np.testing.assert_allclose(got, _oracle(q, kc, vc, pos, scale),
                               rtol=2e-5, atol=2e-5)


def test_padded_tail_block(data):
    """block_k that does not divide max_len: the tail tile is padded
    and masked; garbage beyond max_len must not reach the output."""
    q, kc, vc, scale = data
    got = np.asarray(flash_decode(q, kc, vc, 40, scale,
                                  interpret=True, block_k=32))
    np.testing.assert_allclose(got, _oracle(q, kc, vc, 40, scale),
                               rtol=2e-5, atol=2e-5)


def test_ragged_per_row_positions(data):
    q, kc, vc, scale = data
    posv = jnp.asarray([3, L - 1, 11], jnp.int32)
    got = np.asarray(flash_decode(q, kc, vc, posv, scale,
                                  interpret=True, block_k=16))
    np.testing.assert_allclose(got, _oracle(q, kc, vc, posv, scale),
                               rtol=2e-5, atol=2e-5)


def test_mha_no_grouping(data):
    """nkv == nh (r = 1): the degenerate group size."""
    q, _, _, scale = data
    rng = np.random.default_rng(1)
    kc = jnp.asarray(rng.standard_normal((B, NH, D, L)), jnp.float32)
    vc = jnp.asarray(rng.standard_normal((B, NH, D, L)), jnp.float32)
    got = np.asarray(flash_decode(q, kc, vc, 20, scale,
                                  interpret=True, block_k=16))
    np.testing.assert_allclose(got, _oracle(q, kc, vc, 20, scale),
                               rtol=2e-5, atol=2e-5)


def _quant_seqminor(kc):
    """Quantize a seq-minor (b, g, d, L) cache per (b, g, L) position:
    run _quantize_kv on the head-minor view, flip back."""
    qk, ks = _quantize_kv(kc.transpose(0, 1, 3, 2))
    return qk.transpose(0, 1, 3, 2), ks


def test_int8_matches_f32_dequant_reference(data):
    """The kernel dequantizes in VMEM — compare against the f32
    dequantized einsum. int8 tiles matmul in bf16 (int8 -> bf16 is
    lossless; the rounding is in the f32 q cast and the products), so
    the tolerance is bf16-matmul class, the same class as the einsum
    path's own bf16 trick — the point of the kernel is bandwidth, and
    correctness is pinned exactly by the f32 legs above."""
    q, kc, vc, scale = data
    qk, ks = _quant_seqminor(kc)
    qv, vs = _quant_seqminor(vc)
    kd = jnp.asarray(np.asarray(qk, np.float32)
                     * np.asarray(ks)[:, :, None, :])
    vd = jnp.asarray(np.asarray(qv, np.float32)
                     * np.asarray(vs)[:, :, None, :])
    want = _oracle(q, kd, vd, 30, scale)
    got = np.asarray(flash_decode(q, qk, qv, 30, scale, ks, vs,
                                  interpret=True, block_k=16))
    np.testing.assert_allclose(got, want, rtol=1e-2, atol=1e-2)


def test_int8_padded_tail(data):
    """Quantized + non-dividing block_k: the tail tile's SCALE block
    is uninitialized too — pv must be re-masked or 0*NaN rides into
    the accumulator (the v-zeroing alone does not cover vs)."""
    q, kc, vc, scale = data
    qk, ks = _quant_seqminor(kc)
    qv, vs = _quant_seqminor(vc)
    got = np.asarray(flash_decode(q, qk, qv, 40, scale, ks, vs,
                                  interpret=True, block_k=32))
    kd = jnp.asarray(np.asarray(qk, np.float32)
                     * np.asarray(ks)[:, :, None, :])
    vd = jnp.asarray(np.asarray(qv, np.float32)
                     * np.asarray(vs)[:, :, None, :])
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got, _oracle(q, kd, vd, 40, scale),
                               rtol=1e-2, atol=1e-2)


def test_gate():
    assert can_flash_decode(1024, 64)
    assert can_flash_decode(16, 128)
    assert not can_flash_decode(16, 80)  # lane-hostile head_dim
    assert can_flash_decode(1216, 64)  # non-dividing L: padded tail
    assert not can_flash_decode(0, 64)


def test_attend_cache_flash_flag_parity(data):
    """_attend_cache(use_flash=True) in interpret mode must agree with
    its own einsum path — the production gate swaps implementations,
    not semantics. (On CPU the gate defaults to einsum; force the
    kernel through the interpret default.)"""
    q, kc, vc, scale = data
    a = np.asarray(_attend_cache(q, kc, vc, 25, scale, use_flash=True))
    b = np.asarray(_attend_cache(q, kc, vc, 25, scale, use_flash=False))
    np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-5)


def _block_oracle(q, kc, vc, pos0, scale, ks=None, vs=None):
    b, T = q.shape[0], q.shape[1]
    p0 = jnp.asarray(pos0, jnp.int32)
    p0 = jnp.full((b,), p0) if p0.ndim == 0 else p0
    pos_q = p0[:, None] + jnp.arange(T, dtype=jnp.int32)
    return np.asarray(_attend_cache_block(q, kc, vc, pos_q, scale,
                                          k_scale=ks, v_scale=vs,
                                          use_flash=False))


@pytest.mark.parametrize("T", [1, 4])
def test_block_decode_matches_block_oracle(data, T):
    """flash_block_decode (the speculative verify kernel) vs the
    einsum block attend: per-query causal masks at pos0 + t."""
    _, kc, vc, scale = data
    rng = np.random.default_rng(7)
    q = jnp.asarray(rng.standard_normal((B, T, NH, D)), jnp.float32)
    got = np.asarray(flash_block_decode(q, kc, vc, 9, scale,
                                        interpret=True, block_k=16))
    np.testing.assert_allclose(got, _block_oracle(q, kc, vc, 9, scale),
                               rtol=2e-5, atol=2e-5)


def test_block_decode_ragged_pos0(data):
    _, kc, vc, scale = data
    rng = np.random.default_rng(8)
    T = 3
    q = jnp.asarray(rng.standard_normal((B, T, NH, D)), jnp.float32)
    pos0 = jnp.asarray([0, L - T, 17], jnp.int32)
    got = np.asarray(flash_block_decode(q, kc, vc, pos0, scale,
                                        interpret=True, block_k=16))
    np.testing.assert_allclose(got,
                               _block_oracle(q, kc, vc, pos0, scale),
                               rtol=2e-5, atol=2e-5)


def test_block_decode_int8(data):
    _, kc, vc, scale = data
    rng = np.random.default_rng(9)
    T = 4
    q = jnp.asarray(rng.standard_normal((B, T, NH, D)), jnp.float32)
    qk, ks = _quant_seqminor(kc)
    qv, vs = _quant_seqminor(vc)
    kd = jnp.asarray(np.asarray(qk, np.float32)
                     * np.asarray(ks)[:, :, None, :])
    vd = jnp.asarray(np.asarray(qv, np.float32)
                     * np.asarray(vs)[:, :, None, :])
    got = np.asarray(flash_block_decode(q, qk, qv, 21, scale, ks, vs,
                                        interpret=True, block_k=32))
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got, _block_oracle(q, kd, vd, 21, scale),
                               rtol=1e-2, atol=1e-2)


def test_block_T1_is_flash_decode(data):
    """T=1 block == single-token flash decode BITWISE — the shared-
    numerics argument speculative losslessness rests on requires the
    degenerate case to be the same computation, not a near one."""
    q, kc, vc, scale = data
    a = np.asarray(flash_decode(q, kc, vc, 13, scale, interpret=True,
                                block_k=16))
    b = np.asarray(flash_block_decode(q, kc, vc, 13, scale,
                                      interpret=True, block_k=16))
    np.testing.assert_array_equal(a, b)


def test_jittable_and_sharded(data):
    """jit + tp-sharded shard_map. The shard_map leg runs
    check_vma=False: pallas's HLO interpreter slices blocks with
    unvarying grid indices, which the vma checker rejects for varying
    operands (JAX's error message itself prescribes check_vma=False);
    the Mosaic path on real TPU does not go through that interpreter,
    and flash_decode pre-varies its pos operand so the kernel's
    operands stay vma-uniform there."""
    from jax.sharding import PartitionSpec as P

    from rlo_tpu.parallel.mesh import make_mesh, shard_jit
    q, kc, vc, scale = data
    f = jax.jit(lambda q, k, v: flash_decode(q, k, v, 12, scale,
                                             interpret=True,
                                             block_k=16))
    np.testing.assert_allclose(np.asarray(f(q, kc, vc)),
                               _oracle(q, kc, vc, 12, scale),
                               rtol=2e-5, atol=2e-5)
    mesh = make_mesh((2,), ("tp",))
    g = shard_jit(
        lambda q, k, v: flash_decode(q, k, v, 12, scale,
                                     interpret=True, block_k=16),
        mesh, (P(None, None, "tp"), P(None, "tp"), P(None, "tp")),
        P(None, None, "tp"), check_vma=False)
    np.testing.assert_allclose(np.asarray(g(q, kc, vc)),
                               _oracle(q, kc, vc, 12, scale),
                               rtol=2e-5, atol=2e-5)


class TestWriteKvRow:
    """Aliased single-position cache write kernel vs the DUS oracle."""

    def _mk(self, dtype=jnp.float32, L=256):
        rng = np.random.default_rng(11)
        cache = jnp.asarray(rng.standard_normal((B, NKV, D, L)), dtype)
        row = jnp.asarray(rng.standard_normal((B, NKV, D)), dtype)
        return cache, row

    def test_matches_dus_scalar_pos(self):
        from rlo_tpu.pallas.decode import write_kv_row
        cache, row = self._mk()
        got = np.asarray(write_kv_row(cache, row, 129, interpret=True))
        want = np.asarray(cache).copy()
        want[:, :, :, 129] = np.asarray(row)
        np.testing.assert_array_equal(got, want)

    def test_matches_dus_ragged(self):
        from rlo_tpu.pallas.decode import write_kv_row
        cache, row = self._mk()
        pos = jnp.asarray([0, 255, 131], jnp.int32)
        got = np.asarray(write_kv_row(cache, row, pos, interpret=True))
        want = np.asarray(cache).copy()
        for bidx, p in enumerate(np.asarray(pos)):
            want[bidx, :, :, p] = np.asarray(row)[bidx]
        np.testing.assert_array_equal(got, want)

    def test_int8(self):
        from rlo_tpu.pallas.decode import write_kv_row
        rng = np.random.default_rng(12)
        cache = jnp.asarray(rng.integers(-127, 127, (B, NKV, D, 128)),
                            jnp.int8)
        row = jnp.asarray(rng.integers(-127, 127, (B, NKV, D)),
                          jnp.int8)
        got = np.asarray(write_kv_row(cache, row, 127, interpret=True))
        want = np.asarray(cache).copy()
        want[:, :, :, 127] = np.asarray(row)
        np.testing.assert_array_equal(got, want)

    def test_gate(self):
        from rlo_tpu.pallas.decode import can_write_row
        assert can_write_row(128) and can_write_row(1216)
        assert not can_write_row(64)


class TestWriteKvBlock:
    """Aliased T-column cache write (the verify-path scatter killer)."""

    def _mk(self, L=384, T=5):
        rng = np.random.default_rng(21)
        cache = jnp.asarray(rng.standard_normal((B, NKV, D, L)),
                            jnp.float32)
        rows = jnp.asarray(rng.standard_normal((B, NKV, D, T)),
                           jnp.float32)
        return cache, rows

    @pytest.mark.parametrize("pos0", [0, 100, 126, 256, 379])
    def test_matches_scatter(self, pos0):
        from rlo_tpu.pallas.decode import write_kv_block
        cache, rows = self._mk()
        T = rows.shape[3]
        got = np.asarray(write_kv_block(cache, rows, pos0,
                                        interpret=True))
        want = np.asarray(cache).copy()
        want[:, :, :, pos0:pos0 + T] = np.asarray(rows)
        np.testing.assert_array_equal(got, want)

    def test_ragged_pos0_straddles_blocks(self):
        from rlo_tpu.pallas.decode import write_kv_block
        cache, rows = self._mk()
        T = rows.shape[3]
        pos0 = jnp.asarray([125, 0, 379], jnp.int32)  # straddle/edge
        got = np.asarray(write_kv_block(cache, rows, pos0,
                                        interpret=True))
        want = np.asarray(cache).copy()
        for bi, p in enumerate(np.asarray(pos0)):
            want[bi, :, :, p:p + T] = np.asarray(rows)[bi]
        np.testing.assert_array_equal(got, want)

    def test_gate(self):
        from rlo_tpu.pallas.decode import can_write_block
        assert can_write_block(256) and can_write_block(1280)
        assert not can_write_block(128)   # needs two slidable blocks
        assert not can_write_block(200)   # non-x128


def test_write_row_oob_pos_is_dropped():
    """serve advances retired slots past max_len: an out-of-range pos
    must write NOTHING (the scatter it replaced dropped OOB writes)."""
    from rlo_tpu.pallas.decode import write_kv_row
    rng = np.random.default_rng(31)
    cache = jnp.asarray(rng.standard_normal((B, NKV, D, 256)),
                        jnp.float32)
    row = jnp.asarray(rng.standard_normal((B, NKV, D)), jnp.float32)
    pos = jnp.asarray([256, 300, 10_000], jnp.int32)
    got = np.asarray(write_kv_row(cache, row, pos, interpret=True))
    np.testing.assert_array_equal(got, np.asarray(cache))
