"""rlo-prover self-verification + oracle cross-check
(docs/DESIGN.md §16).

Mirror of tests/test_lint.py / test_sentinel.py's two-halves pattern,
plus a third half unique to the prover:

  1. The clean-tree contract: ``run_prover`` on this checkout reports
     zero findings — every committed schedule is a valid, delivering
     CollectivePermute program and every Pallas kernel's geometry is
     legal, in tier-1, on every run.

  2. Mutation fixtures: for each rule family P1–P5 a temp copy of the
     tree is seeded with exactly one violation and the prover must
     trip with the right rule ID — a rule that never fires is
     indistinguishable from no rule.  The S0 integration fixture
     proves a stale ``rlo-prover:`` anchor is flagged by
     rlo-sentinel's shared stale-anchor audit.

  3. Oracle cross-check: the prover's symbolic schedule simulator is
     pinned against REAL executors on tiny meshes (n in {2, 3, 4, 8},
     every bcast origin) so the symbolic model cannot silently diverge
     from what ships — a numpy executor that replays the committed
     topology schedules with the exact per-round update semantics of
     ``tpu_collectives.rootless_bcast``, the engine-substrate ring
     collectives over the loopback transport (``ops.collectives``,
     which shares ``ring_reduce_scatter_chunk`` with the TPU
     lowering), and — where this jax build exposes ``jax.shard_map``
     — the lowered collectives themselves on a virtual CPU mesh.
"""

from __future__ import annotations

import json
import shutil
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from rlo_tpu import topology
from rlo_tpu.tools.rlo_prover import (run_prover, simulate_bcast,
                                      simulate_doubling_all_gather,
                                      simulate_halving_reduce_scatter,
                                      simulate_rd_allreduce,
                                      simulate_ring_allreduce)

REPO_ROOT = Path(__file__).resolve().parents[1]

_IGNORE = shutil.ignore_patterns(
    "__pycache__", ".pytest_cache", "*.so", "*.o", "*.pyc",
    "rlo_selftest*", "rlo_demo", "rlo_demo_mpi", "rlo_demo_tsan",
    "rlo_demo_asan", "femtompirun")

ORACLE_NS = [2, 3, 4, 8]


@pytest.fixture()
def tree(tmp_path):
    """An analyzable copy of the source tree (sources only) that
    fixtures may mutate freely."""
    shutil.copytree(REPO_ROOT / "rlo_tpu", tmp_path / "rlo_tpu",
                    ignore=_IGNORE)
    return tmp_path


def mutate(root: Path, rel: str, old: str, new: str) -> int:
    """Replace ``old`` (must occur exactly once) with ``new``; returns
    the 1-indexed line of the edit."""
    path = root / rel
    text = path.read_text()
    assert text.count(old) == 1, \
        f"fixture drift: {old!r} occurs {text.count(old)}x in {rel}"
    line = text[:text.index(old)].count("\n") + 1
    path.write_text(text.replace(old, new))
    return line


def findings_for(root: Path, rule: str):
    return [f for f in run_prover(root) if f.rule == rule]


# ---------------------------------------------------------------------------
# 1. clean tree
# ---------------------------------------------------------------------------

def test_head_is_clean():
    """Zero findings on this checkout — the tier-1 drift gate."""
    findings = run_prover(REPO_ROOT)
    assert findings == [], "\n".join(str(f) for f in findings)


# ---------------------------------------------------------------------------
# 2. one seeded violation per rule family
# ---------------------------------------------------------------------------

def test_p1_fires_on_dst_collision(tree):
    """Collapsing the binomial round's dst formula makes every rank of
    a round deliver into one dst — the CollectivePermute contract the
    schedule compiles onto forbids it."""
    mutate(tree, "rlo_tpu/topology.py",
           "(((r + origin) % world_size), "
           "((r + step + origin) % world_size))",
           "(((r + origin) % world_size), "
           "((step + origin) % world_size))")
    hits = findings_for(tree, "P1")
    assert any(f.file == "rlo_tpu/topology.py" and
               "collision" in f.msg and "binomial" in f.msg
               for f in hits), hits


def test_p2_fires_on_dropped_contribution(tree):
    """Truncating one pair from every recursive-doubling round drops a
    rank's contribution from the other subcube — the token algebra
    catches the incomplete final multiset."""
    mutate(tree, "rlo_tpu/topology.py",
           "        rounds.append(xor_perm(world_size, 1 << i))",
           "        rounds.append(xor_perm(world_size, 1 << i)[:-1])")
    hits = findings_for(tree, "P2")
    assert any("recursive_doubling" in f.msg and
               ("dropped" in f.msg or "no partner" in f.msg)
               for f in hits), hits


def test_p2_fires_on_chunk_misalignment(tree):
    """Skewing ring_reduce_scatter_chunk by one step makes senders and
    receivers disagree about which chunk is in flight."""
    mutate(tree, "rlo_tpu/topology.py",
           "    return (rank - step) % world_size",
           "    return (rank - step - 1) % world_size")
    hits = findings_for(tree, "P2")
    assert any("misalignment" in f.msg or "double-count" in f.msg
               for f in hits), hits


def test_p3_fires_on_missized_blockspec(tree):
    """A 100-lane pool block is neither the whole page nor a 128-lane
    multiple — Mosaic would reject or silently pad the tiling."""
    mutate(tree, "rlo_tpu/pallas/decode.py",
           "            pl.BlockSpec((1, nkv, d, ps),\n"
           "                         lambda i, page_ref, off_ref, "
           "nv_ref: (\n"
           "                             page_ref[0], 0, 0, 0)),",
           "            pl.BlockSpec((1, nkv, d, 100),\n"
           "                         lambda i, page_ref, off_ref, "
           "nv_ref: (\n"
           "                             page_ref[0], 0, 0, 0)),")
    hits = findings_for(tree, "P3")
    assert any(f.file == "rlo_tpu/pallas/decode.py" and
               "lane dim 100" in f.msg for f in hits), hits


def test_p3_fires_on_unclamped_scalar_index(tree):
    """Dropping the jnp.minimum clamp in write_kv_row's block
    index_map lets a retired slot's out-of-range position select an
    illegal cache block — the hostile scalar-prefetch probe catches
    it."""
    mutate(tree, "rlo_tpu/pallas/decode.py",
           "            pl.BlockSpec((1, nkv, d, 128),\n"
           "                         lambda ib, pos_ref, _n=L // 128: (\n"
           "                             ib, 0, 0,\n"
           "                             jnp.minimum(pos_ref[ib] // 128,\n"
           "                                         _n - 1))),",
           "            pl.BlockSpec((1, nkv, d, 128),\n"
           "                         lambda ib, pos_ref, _n=L // 128: (\n"
           "                             ib, 0, 0,\n"
           "                             pos_ref[ib] // 128)),")
    hits = findings_for(tree, "P3")
    assert any("out of range" in f.msg and "write_kv_row" in f.msg
               for f in hits), hits


def test_p4_fires_on_hardcoded_axis(tree):
    """A literal axis name in a per-shard collective drifts silently
    when the mesh is renamed — it must flow from a parameter."""
    mutate(tree, "rlo_tpu/ops/ring_attention.py",
           "            kc = lax.ppermute(kc, axis, perm)\n"
           "            vc = lax.ppermute(vc, axis, perm)",
           "            kc = lax.ppermute(kc, \"ring\", perm)\n"
           "            vc = lax.ppermute(vc, axis, perm)")
    hits = findings_for(tree, "P4")
    assert any(f.file == "rlo_tpu/ops/ring_attention.py" and
               "'ring'" in f.msg for f in hits), hits


def test_p4_axis_ok_anchor_suppresses(tree):
    """The same literal, anchored, is sanctioned — and consumed, so
    the S0 audit stays quiet too."""
    mutate(tree, "rlo_tpu/ops/ring_attention.py",
           "            kc = lax.ppermute(kc, axis, perm)\n"
           "            vc = lax.ppermute(vc, axis, perm)",
           "            # rlo-prover: axis-ok fixture-sanctioned\n"
           "            kc = lax.ppermute(kc, \"ring\", perm)\n"
           "            vc = lax.ppermute(vc, axis, perm)")
    assert findings_for(tree, "P4") == []
    from rlo_tpu.tools.rlo_sentinel import run_sentinel
    assert [f for f in run_sentinel(tree) if f.rule == "S0"] == []


def test_p5_fires_on_drifted_page_size(tree):
    """A 64-token default page drifts from the 128-lane device page
    contract the kernels and the pool layout assume."""
    mutate(tree, "rlo_tpu/models/serve.py",
           "paged: bool = False, page_size: int = 128,",
           "paged: bool = False, page_size: int = 64,")
    hits = findings_for(tree, "P5")
    assert any(f.file == "rlo_tpu/models/serve.py" and
               "page_size default = 64" in f.msg for f in hits), hits


def test_s0_fires_on_stale_prover_anchor(tree):
    """The shared anchor grammar: an rlo-prover anchor nothing
    consumes is flagged by rlo-sentinel's S0 audit (satellite of the
    single-namespace design in tools/runner.py)."""
    from rlo_tpu.tools.rlo_sentinel import run_sentinel
    mutate(tree, "rlo_tpu/ops/ring_attention.py",
           "    ws = lax.axis_size(axis)\n"
           "    idx = lax.axis_index(axis)\n"
           "    blk, h, d = q.shape",
           "    # rlo-prover: axis-ok suppresses nothing here\n"
           "    ws = lax.axis_size(axis)\n"
           "    idx = lax.axis_index(axis)\n"
           "    blk, h, d = q.shape")
    hits = [f for f in run_sentinel(tree) if f.rule == "S0"]
    assert any("rlo-prover: axis-ok" in f.msg and "stale" in f.msg
               for f in hits), hits


# ---------------------------------------------------------------------------
# CLI contract
# ---------------------------------------------------------------------------

def test_cli_exit_codes_and_json(tree):
    mutate(tree, "rlo_tpu/models/serve.py",
           "paged: bool = False, page_size: int = 128,",
           "paged: bool = False, page_size: int = 64,")
    proc = subprocess.run(
        [sys.executable, "-m", "rlo_tpu.tools.rlo_prover",
         "--root", str(tree)],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "P5" in proc.stdout
    # findings print as file:line: diagnostics (the check.sh contract)
    assert any(ln.split(":")[0].endswith(".py") and
               ln.split(":")[1].isdigit()
               for ln in proc.stdout.splitlines() if "P5" in ln)
    # machine-readable output carries the same findings
    proc = subprocess.run(
        [sys.executable, "-m", "rlo_tpu.tools.rlo_prover",
         "--root", str(tree), "--json"],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert proc.returncode == 1
    data = json.loads(proc.stdout)
    assert any(d["rule"] == "P5" and d["line"] > 0 and
               d["severity"] == "error" for d in data), data
    # rule selection: a family that is still clean exits 0
    proc = subprocess.run(
        [sys.executable, "-m", "rlo_tpu.tools.rlo_prover",
         "--root", str(tree), "--rules", "P1,P2"],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_clean_head_exits_zero():
    proc = subprocess.run(
        [sys.executable, "-m", "rlo_tpu.tools.rlo_prover"],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# 3. oracle cross-check: symbolic model vs real executors
# ---------------------------------------------------------------------------

def _np_exec_bcast(rounds, xs):
    """Replay a bcast schedule on concrete per-rank values with the
    exact round semantics of tpu_collectives.rootless_bcast: one
    ppermute per round, every round-dst takes the permuted value."""
    xs = list(xs)
    for rnd in rounds:
        old = list(xs)
        for src, dst in rnd:
            xs[dst] = old[src]
    return xs


@pytest.mark.parametrize("n", ORACLE_NS)
@pytest.mark.parametrize("schedule", ["binomial_bcast_schedule",
                                      "skip_ring_bcast_schedule"])
def test_oracle_bcast_every_origin(n, schedule):
    """The symbolic token state maps 1:1 onto a concrete replay of the
    same schedule, for every origin."""
    gen = getattr(topology, schedule)
    for origin in range(n):
        rounds = gen(n, origin).rounds
        tok = simulate_bcast(rounds, n)
        xs = [float(100 + r) for r in range(n)]
        got = _np_exec_bcast(rounds, xs)
        assert got == [xs[t] for t in tok]
        assert tok == [origin] * n  # and the model says it delivers


@pytest.mark.parametrize("n", ORACLE_NS)
def test_oracle_ring_allreduce_matches_loopback_engine(n):
    """The symbolic ring model's claimed contribution sets translate
    to the numbers the REAL engine-substrate ring (ops.collectives
    over the loopback transport — same ring_reduce_scatter_chunk
    schedule as the TPU lowering) actually produces."""
    from rlo_tpu.ops.collectives import Comm, run_collectives
    from rlo_tpu.transport import make_world
    gathered, defects = simulate_ring_allreduce(n, topology)
    assert defects == []
    xs = [np.arange(4, dtype=np.float64) * 0 + 2.0 ** r
          for r in range(n)]
    world, comms = make_world("loopback", n), None
    comms = [Comm(world.transport(r)) for r in range(n)]
    got = run_collectives(
        [c.allreduce(x, algorithm="ring") for c, x in zip(comms, xs)])
    # powers of two make the sum a readable contribution bitmask:
    # sum == mask means exactly-once per rank
    for r in range(n):
        for chunk_mask in gathered[r]:
            assert chunk_mask == (1 << n) - 1
        assert np.allclose(got[r], float((1 << n) - 1))


@pytest.mark.parametrize("n", [2, 4, 8])
def test_oracle_pow2_symbolic_models(n):
    """Recursive-doubling / halving-doubling symbolic results match
    the loopback recursive-doubling executor and numpy sums."""
    from rlo_tpu.ops.collectives import Comm, run_collectives
    from rlo_tpu.transport import make_world
    acc, defects = simulate_rd_allreduce(n, topology)
    assert defects == [] and all(a == (1 << n) - 1 for a in acc)
    owned, defects = simulate_halving_reduce_scatter(n, topology)
    assert defects == []
    assert [c for c, _ in owned] == list(range(n))
    final, defects = simulate_doubling_all_gather(n, owned, topology)
    assert defects == []
    assert all(m == (1 << n) - 1 for row in final for m in row)
    xs = [np.full(3, 2.0 ** r) for r in range(n)]
    world = make_world("loopback", n)
    comms = [Comm(world.transport(r)) for r in range(n)]
    got = run_collectives(
        [c.allreduce(x, algorithm="recursive_doubling")
         for c, x in zip(comms, xs)])
    for r in range(n):
        assert np.allclose(got[r], float((1 << n) - 1))


def _shard_map_gate():
    """None when the lowered-program oracle can run, else the skip
    reason — which must be PROVABLY version-caused.  ``jax.shard_map``
    is a top-level API from jax 0.6 (mesh.shard_jit also needs its
    check_vma typing); on an older pin the skip is legitimate.  On a
    0.6+ jax where the symbol is nonetheless missing something else
    broke, and a silent skip would let the oracle rot invisibly — so
    that case asserts instead of skipping."""
    import jax
    if hasattr(jax, "shard_map"):
        return None
    ver = tuple(int(p) for p in jax.__version__.split(".")[:2])
    assert ver < (0, 6), (
        f"jax {jax.__version__} should expose jax.shard_map but does "
        f"not — the oracle's version gate has rotted; investigate "
        f"instead of skipping")
    return (f"version gate: jax {jax.__version__} < 0.6 has no "
            f"top-level jax.shard_map")


def test_oracle_skip_is_version_caused():
    """The oracle may only ever be skipped BY THE VERSION GATE: when
    the gate returns a reason it names the pinned jax version, and
    when it returns None the oracle genuinely has jax.shard_map."""
    import jax
    reason = _shard_map_gate()
    if reason is None:
        assert hasattr(jax, "shard_map")
    else:
        assert "version gate" in reason and jax.__version__ in reason


@pytest.mark.parametrize("n", [2, 4, 8])
def test_oracle_lowered_collectives_on_cpu_mesh(n):
    """Where this jax build exposes jax.shard_map, pin the symbolic
    model against the ACTUAL lowered program on a virtual CPU mesh."""
    reason = _shard_map_gate()
    if reason is not None:
        pytest.skip(reason)
    import jax
    from jax.sharding import PartitionSpec as P
    from rlo_tpu.ops import tpu_collectives as tc
    from rlo_tpu.parallel.mesh import make_mesh, shard_jit
    mesh = make_mesh((n,), ("x",))
    xs = np.stack([np.full(4, 2.0 ** r, np.float32)
                   for r in range(n)])
    for origin in range(n):
        for schedule in ("binomial", "skip_ring"):
            fn = shard_jit(
                lambda v, o=origin, s=schedule:
                tc.rootless_bcast(v, o, "x", schedule=s),
                mesh, (P("x"),), P("x"))
            got = np.asarray(jax.device_get(fn(xs)))
            gen = (topology.binomial_bcast_schedule
                   if schedule == "binomial"
                   else topology.skip_ring_bcast_schedule)
            tok = simulate_bcast(gen(n, origin).rounds, n)
            want = np.stack([xs[t] for t in tok])
            np.testing.assert_allclose(got, want)
    fn = shard_jit(lambda v: tc.allreduce(v, "x", algorithm="ring"),
                   mesh, (P("x"),), P("x"))
    got = np.asarray(jax.device_get(fn(xs)))
    np.testing.assert_allclose(got,
                               np.broadcast_to(xs.sum(0), got.shape))
