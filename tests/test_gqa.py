"""Grouped-query attention (cfg.n_kv_heads) across the model stack.

Oracles: (a) a GQA forward equals an MHA forward whose K/V projections
are the GQA ones explicitly repeated per group (exact semantics, not
just shape); (b) training runs and moves GQA params (dp + tp sharded,
with kv heads divided across tp); (c) KV-cache decode matches the
O(n^2) recompute oracle and the cache stores only kv_heads (the
memory win); (d) ring/ulysses sequence parallelism accept GQA configs.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from rlo_tpu.models.generate import generate, init_kv_cache
from rlo_tpu.models.transformer import (TransformerConfig, forward,
                                        init_params, param_pspecs,
                                        train_step)
from rlo_tpu.parallel.mesh import make_mesh, shard_jit

GQA = TransformerConfig(vocab=89, d_model=32, n_heads=4, n_layers=2,
                        d_ff=64, dtype="float32", n_kv_heads=2)


def tokens_for(cfg, batch=2, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, cfg.vocab, (batch, seq)),
                       jnp.int32)


def to_mha(params, cfg):
    """Rebuild MHA params whose fused wqkv reproduces the GQA model
    exactly: K/V projection columns repeated per query-head group."""
    rep = cfg.n_heads // cfg.kv_heads
    hd = cfg.head_dim
    layers = []
    for layer in params["layers"]:
        wq = layer["wq"]                       # (d, nh*hd)
        wk, wv = layer["wkv"][:, 0, :], layer["wkv"][:, 1, :]

        def expand(w):
            d = w.shape[0]
            return jnp.repeat(w.reshape(d, cfg.kv_heads, hd), rep,
                              axis=1).reshape(d, cfg.n_heads * hd)

        wqkv = jnp.stack([wq, expand(wk), expand(wv)], axis=1)
        nl = {k: v for k, v in layer.items()
              if k not in ("wq", "wkv")}
        nl["wqkv"] = wqkv
        layers.append(nl)
    return dict(params, layers=layers)


def test_gqa_equals_explicitly_repeated_mha():
    params = init_params(jax.random.PRNGKey(0), GQA)
    toks = tokens_for(GQA)
    got = np.asarray(forward(params, toks, GQA))
    mha_cfg = dataclasses.replace(GQA, n_kv_heads=None)
    want = np.asarray(forward(to_mha(params, GQA), toks, mha_cfg))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_gqa_train_step_moves_params():
    params = init_params(jax.random.PRNGKey(1), GQA)
    new_params, loss = train_step(params, tokens_for(GQA), GQA,
                                  lr=1e-2)
    assert np.isfinite(float(loss))
    delta = sum(float(np.abs(np.asarray(a) - np.asarray(b)).sum())
                for a, b in zip(jax.tree.leaves(new_params),
                                jax.tree.leaves(params)))
    assert delta > 0


def test_gqa_tp_sharded_matches_single_device():
    mesh = make_mesh((2,), ("tp",))
    params = init_params(jax.random.PRNGKey(2), GQA)
    toks = tokens_for(GQA, seed=3)
    specs = param_pspecs(GQA, "tp")
    step = shard_jit(
        lambda p, t: train_step(p, t, GQA, lr=1e-2, tp_axis="tp"),
        mesh, (specs, P()), (specs, P()))
    p_tp, l_tp = step(params, toks)
    p_one, l_one = train_step(params, toks, GQA, lr=1e-2)
    assert abs(float(l_tp) - float(l_one)) < 1e-5
    for a, b in zip(jax.tree.leaves(p_tp), jax.tree.leaves(p_one)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("sp_attention", ["ring", "ulysses"])
def test_gqa_sequence_parallel(sp_attention):
    cfg = dataclasses.replace(GQA, sp_attention=sp_attention)
    mesh = make_mesh((2,), ("sp",))
    params = init_params(jax.random.PRNGKey(4), cfg)
    toks = tokens_for(cfg, seq=32, seed=5)
    step = shard_jit(
        lambda p, t: train_step(p, t, cfg, lr=1e-2, sp_axis="sp"),
        mesh, (P(), P(None, "sp")), (P(), P()))
    _, loss_sp = step(params, toks)
    _, loss_one = train_step(params, toks, cfg, lr=1e-2)
    assert abs(float(loss_sp) - float(loss_one)) < 1e-4


def test_gqa_decode_matches_naive_loop():
    params = init_params(jax.random.PRNGKey(5), GQA)
    prompt = tokens_for(GQA, seq=6, seed=6)
    max_new = 8
    got = np.asarray(generate(params, prompt, GQA, max_new=max_new))
    seq = np.asarray(prompt)
    for _ in range(max_new):
        logits = np.asarray(forward(params, jnp.asarray(seq), GQA)
                            )[:, -1, :]
        nxt = logits.argmax(-1).astype(np.int32)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, seq[:, prompt.shape[1]:])


def test_gqa_cache_stores_only_kv_heads():
    # head-leading SEQ-MINOR layout (b, kv_heads, head_dim, max_len) — the
    # Mosaic-native tiling the flash-decode kernel requires
    cache = init_kv_cache(GQA, batch=2, max_len=16)
    exp_len = 16 if jax.default_backend() != "tpu" else 128
    assert cache[0]["k"].shape == (2, GQA.kv_heads, GQA.head_dim,
                                   exp_len)
    assert GQA.kv_heads == 2 < GQA.n_heads


def test_gqa_pipeline_parallel():
    """Pipeline parallelism with GQA layers: pipeline_pspecs(cfg=...)
    must produce the wq/wkv spec tree matching stack_layers output."""
    from rlo_tpu.models.pipeline import (pipeline_pspecs,
                                         pipeline_train_step,
                                         stack_layers)

    mesh = make_mesh((2,), ("pp",))
    params = init_params(jax.random.PRNGKey(6), GQA)
    pparams = stack_layers(params)
    specs = pipeline_pspecs("pp", cfg=GQA)
    toks = tokens_for(GQA, batch=4, seq=16, seed=7)
    step = shard_jit(
        lambda p, t: pipeline_train_step(p, t, GQA, "pp", n_micro=2,
                                         lr=1e-2),
        mesh, (specs, P()), (specs, P()))
    _, loss = step(pparams, toks)
    assert np.isfinite(float(loss))


def test_invalid_kv_heads_rejected():
    bad = dataclasses.replace(GQA, n_kv_heads=3)  # 4 % 3 != 0
    with pytest.raises(AssertionError):
        init_params(jax.random.PRNGKey(0), bad)
