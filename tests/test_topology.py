"""Unit + property tests for the skip-ring topology and static schedules.

The oracles mirror the reference's implicit invariants
(/root/reference/rootless_ops.c:1412-1579): exactly-once delivery for every
(world_size, origin) pair, vote-count consistency between fwd_send_cnt and the
actual forward fan-out, and schedule well-formedness (unique ppermute
src/dst per round).
"""

from collections import Counter, deque

import pytest

from rlo_tpu import topology as T

WORLD_SIZES = list(range(2, 34)) + [48, 64, 100, 128]


def simulate_skip_ring_bcast(ws: int, origin: int) -> Counter:
    """Replay the reference forwarding rules event-by-event; return per-rank
    delivery counts (forward on every arrival, as _bc_forward does)."""
    deliveries = Counter()
    q = deque((dst, origin) for dst in T.initiator_targets(ws, origin))
    while q:
        rank, frm = q.popleft()
        deliveries[rank] += 1
        assert deliveries[rank] <= ws, "forwarding loop detected"
        for dst in T.fwd_targets(ws, rank, origin, frm):
            q.append((dst, rank))
    return deliveries


class TestLevels:
    def test_known_levels_ws8(self):
        # odd ranks are leaves; level counts trailing zeros; rank 0 is hub
        assert [T.level(8, r) for r in range(8)] == [2, 0, 1, 0, 2, 0, 1, 0]

    def test_rank0_non_pow2(self):
        assert T.level(6, 0) == 2  # floor(log2(6))
        assert T.level(9, 0) == 3

    def test_last_wall(self):
        assert T.last_wall(8, 6) == 4  # clear lowest set bit
        assert T.last_wall(8, 5) == 4
        assert T.last_wall(8, 4) == 0
        assert T.last_wall(8, 0) == 4  # rank 0: 2**level

    def test_send_list_pow2(self):
        assert T.send_list(8, 0) == ((1, 2, 4), 2)
        assert T.send_list(8, 4) == ((5, 6, 0), 2)
        assert T.send_list(8, 3) == ((4,), 0)

    def test_send_list_non_pow2_truncation(self):
        # last rank in a non-pow2 world points only at 0
        targets, cc = T.send_list(6, 5)
        assert targets == (0,) and cc == 0
        # a rank whose 2**i hop overflows truncates and redirects to 0:
        # rank 4 in ws=6 has level 2 but 4+2=6 overflows, so channel 1 -> 0
        assert T.send_list(6, 4) == ((5, 0), 1)

    @pytest.mark.parametrize("ws", WORLD_SIZES)
    def test_send_list_in_range(self, ws):
        for r in range(ws):
            targets, cc = T.send_list(ws, r)
            assert len(targets) == cc + 1
            assert all(0 <= t < ws for t in targets)
            assert r not in targets


class TestBcastDelivery:
    @pytest.mark.parametrize("ws", WORLD_SIZES)
    def test_exactly_once_all_origins(self, ws):
        for origin in range(ws):
            deliveries = simulate_skip_ring_bcast(ws, origin)
            assert deliveries.get(origin, 0) == 0, "origin must not self-deliver"
            others = set(range(ws)) - {origin}
            assert set(deliveries) == others
            assert all(c == 1 for c in deliveries.values()), (
                f"duplicate delivery ws={ws} origin={origin}: {deliveries}")

    @pytest.mark.parametrize("ws", [2, 3, 4, 6, 8, 11, 16, 23, 32])
    def test_fwd_send_cnt_matches_targets(self, ws):
        # fwd_send_cnt is the IAR votes_needed predictor — it must equal the
        # actual forward fan-out for every (rank, origin, from) reachable state
        for origin in range(ws):
            q = deque((dst, origin) for dst in T.initiator_targets(ws, origin))
            while q:
                rank, frm = q.popleft()
                n = T.fwd_send_cnt(ws, rank, origin, frm)
                targets = T.fwd_targets(ws, rank, origin, frm)
                assert n == len(targets)
                for dst in targets:
                    q.append((dst, rank))


def check_schedule(sched: T.BcastSchedule):
    ws, origin = sched.world_size, sched.origin
    reached = {origin}
    for rnd in sched.rounds:
        srcs = [e[0] for e in rnd]
        dsts = [e[1] for e in rnd]
        assert len(set(srcs)) == len(srcs), "ppermute srcs must be unique"
        assert len(set(dsts)) == len(dsts), "ppermute dsts must be unique"
        for src, dst in rnd:
            assert src in reached, "sender must already hold the message"
            assert dst not in reached, "exactly-once violated"
        reached.update(dsts)
    assert reached == set(range(ws))


class TestSchedules:
    @pytest.mark.parametrize("ws", WORLD_SIZES)
    def test_skip_ring_schedule_valid(self, ws):
        for origin in range(min(ws, 9)):
            check_schedule(T.skip_ring_bcast_schedule(ws, origin))

    @pytest.mark.parametrize("ws", WORLD_SIZES)
    def test_binomial_schedule_valid(self, ws):
        for origin in range(min(ws, 9)):
            sched = T.binomial_bcast_schedule(ws, origin)
            check_schedule(sched)
            assert sched.num_rounds == (ws - 1).bit_length()

    def test_ring_perm(self):
        assert T.ring_perm(4) == ((0, 1), (1, 2), (2, 3), (3, 0))

    def test_recursive_doubling(self):
        rounds = T.recursive_doubling_rounds(8)
        assert len(rounds) == 3
        for rnd in rounds:
            # self-inverse pairing covering all ranks
            m = dict(rnd)
            assert all(m[m[s]] == s for s in m)
        with pytest.raises(ValueError):
            T.recursive_doubling_rounds(6)

    def test_describe_smoke(self):
        out = T.describe(6)
        assert "rank   5" in out or "rank 5" in out.replace("  ", " ")


class TestHalvingDoubling:
    def test_distances(self):
        assert T.halving_doubling_distances(8) == (4, 2, 1)
        assert T.halving_doubling_distances(2) == (1,)
        with pytest.raises(ValueError, match="power-of-2"):
            T.halving_doubling_distances(6)

    def test_xor_perm_self_inverse(self):
        for ws, d in [(8, 4), (8, 2), (8, 1), (16, 8)]:
            m = dict(T.xor_perm(ws, d))
            assert sorted(m) == list(range(ws))
            assert sorted(m.values()) == list(range(ws))
            assert all(m[m[s]] == s for s in m)

    def test_halving_chunk_ownership(self):
        """Simulating the halving schedule on plain ints: after all rounds,
        rank r's kept-range start equals r (shard r owns chunk r)."""
        ws = 16
        for rank in range(ws):
            lo, size = 0, ws
            for d in T.halving_doubling_distances(ws):
                lo += d if (rank & d) else 0
                size //= 2
            assert (lo, size) == (rank, 1)
