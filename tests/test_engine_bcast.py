"""Rootless broadcast behavioral parity tests.

Oracles mirror the reference integration suite (testcases.c): per-rank
received counts (test_gen_bcast :59-108), broadcast from every rank
(test_wrapper_bcast :699-724), and the hacky-sack all-to-all stress
(:638-697) — here run in-process over the loopback transport, including
seeded latency/reordering fuzz the reference never had.
"""

import random

import pytest

from rlo_tpu.engine import ProgressEngine, EngineManager, drain
from rlo_tpu.transport import make_world
from rlo_tpu.wire import Tag


def build_world(ws, latency=0, seed=None, **eng_kwargs):
    world = make_world("loopback", ws, latency=latency, seed=seed)
    manager = EngineManager()
    engines = [ProgressEngine(world.transport(r), manager=manager, **eng_kwargs)
               for r in range(ws)]
    return world, engines


def collect_all(eng):
    out = []
    while (m := eng.pickup_next()) is not None:
        out.append(m)
    return out


WORLD_SIZES = [2, 3, 4, 5, 6, 7, 8, 11, 16, 23, 32]


class TestBcast:
    @pytest.mark.parametrize("ws", WORLD_SIZES)
    def test_single_root_counts(self, ws):
        world, engines = build_world(ws)
        cnt = 5
        root = ws // 2
        for i in range(cnt):
            engines[root].bcast(f"msg-{i}".encode())
        drain([world], engines)
        for r, eng in enumerate(engines):
            msgs = collect_all(eng)
            if r == root:
                assert msgs == []
            else:
                assert len(msgs) == cnt
                assert [m.data.decode() for m in msgs] == \
                    [f"msg-{i}" for i in range(cnt)]
                assert all(m.origin == root for m in msgs)
                assert all(m.type == Tag.BCAST for m in msgs)

    @pytest.mark.parametrize("ws", WORLD_SIZES)
    def test_every_rank_broadcasts(self, ws):
        world, engines = build_world(ws)
        for r in range(ws):
            engines[r].bcast(f"from-{r}".encode())
        drain([world], engines)
        for r, eng in enumerate(engines):
            msgs = collect_all(eng)
            assert len(msgs) == ws - 1
            assert {m.data.decode() for m in msgs} == \
                {f"from-{o}" for o in range(ws) if o != r}

    @pytest.mark.parametrize("ws,latency,seed", [
        (4, 3, 0), (7, 5, 1), (8, 4, 2), (16, 6, 3), (23, 8, 4)])
    def test_bcast_under_latency_fuzz(self, ws, latency, seed):
        world, engines = build_world(ws, latency=latency, seed=seed)
        for r in range(ws):
            engines[r].bcast(f"fuzz-{r}".encode())
        drain([world], engines)
        for r, eng in enumerate(engines):
            msgs = collect_all(eng)
            assert len(msgs) == ws - 1

    @pytest.mark.parametrize("ws", [4, 8, 16])
    def test_hacky_sack(self, ws):
        """All-to-all stress: every catch of the 'ball' triggers a new
        broadcast (testcases.c:638-697)."""
        world, engines = build_world(ws, latency=2, seed=99)
        rng = random.Random(7)
        rounds = 20
        holder = 0
        for i in range(rounds):
            engines[holder].bcast(f"ball-{i}".encode())
            holder = rng.choice([r for r in range(ws) if r != holder])
        drain([world], engines)
        total_pickup = 0
        for eng in engines:
            total_pickup += len(collect_all(eng))
        # every bcast delivered to ws-1 ranks, exactly once
        assert total_pickup == rounds * (ws - 1)

    def test_payload_too_large(self):
        world, engines = build_world(2)
        with pytest.raises(ValueError):
            engines[0].bcast(b"x" * (engines[0].msg_size_max + 1))

    def test_counters(self):
        world, engines = build_world(4)
        engines[1].bcast(b"a")
        drain([world], engines)
        assert engines[1].sent_bcast_cnt == 1
        assert sum(e.recved_bcast_cnt for e in engines) == 3

    def test_pickup_while_forwarding(self):
        """A message may be picked up before its forwards complete
        (queue_wait_and_pickup semantics, rootless_ops.c:938-955)."""
        world, engines = build_world(8, latency=10, seed=5)
        engines[0].bcast(b"slow")
        # progress a bounded number of steps, picking up as soon as possible
        seen = [False] * 8
        for _ in range(500):
            for r, eng in enumerate(engines):
                if (m := eng.pickup_next()) is not None:
                    assert not seen[r]
                    seen[r] = True
            from rlo_tpu.engine import progress_all
            engines[0].manager.progress_all()
            if all(seen[1:]):
                break
        assert all(seen[1:])
        drain([world], engines)
