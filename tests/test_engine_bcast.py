"""Rootless broadcast behavioral parity tests.

Oracles mirror the reference integration suite (testcases.c): per-rank
received counts (test_gen_bcast :59-108), broadcast from every rank
(test_wrapper_bcast :699-724), and the hacky-sack all-to-all stress
(:638-697) — here run in-process over the loopback transport, including
seeded latency/reordering fuzz the reference never had.
"""

import random

import pytest

from rlo_tpu.engine import ProgressEngine, EngineManager, drain
from rlo_tpu.transport import make_world
from rlo_tpu.wire import Tag


def build_world(ws, latency=0, seed=None, **eng_kwargs):
    world = make_world("loopback", ws, latency=latency, seed=seed)
    manager = EngineManager()
    engines = [ProgressEngine(world.transport(r), manager=manager, **eng_kwargs)
               for r in range(ws)]
    return world, engines


def collect_all(eng):
    out = []
    while (m := eng.pickup_next()) is not None:
        out.append(m)
    return out


WORLD_SIZES = [2, 3, 4, 5, 6, 7, 8, 11, 16, 23, 32]


class TestBcast:
    @pytest.mark.parametrize("ws", WORLD_SIZES)
    def test_single_root_counts(self, ws):
        world, engines = build_world(ws)
        cnt = 5
        root = ws // 2
        for i in range(cnt):
            engines[root].bcast(f"msg-{i}".encode())
        drain([world], engines)
        for r, eng in enumerate(engines):
            msgs = collect_all(eng)
            if r == root:
                assert msgs == []
            else:
                assert len(msgs) == cnt
                assert [m.data.decode() for m in msgs] == \
                    [f"msg-{i}" for i in range(cnt)]
                assert all(m.origin == root for m in msgs)
                assert all(m.type == Tag.BCAST for m in msgs)

    @pytest.mark.parametrize("ws", WORLD_SIZES)
    def test_every_rank_broadcasts(self, ws):
        world, engines = build_world(ws)
        for r in range(ws):
            engines[r].bcast(f"from-{r}".encode())
        drain([world], engines)
        for r, eng in enumerate(engines):
            msgs = collect_all(eng)
            assert len(msgs) == ws - 1
            assert {m.data.decode() for m in msgs} == \
                {f"from-{o}" for o in range(ws) if o != r}

    @pytest.mark.parametrize("ws,latency,seed", [
        (4, 3, 0), (7, 5, 1), (8, 4, 2), (16, 6, 3), (23, 8, 4)])
    def test_bcast_under_latency_fuzz(self, ws, latency, seed):
        world, engines = build_world(ws, latency=latency, seed=seed)
        for r in range(ws):
            engines[r].bcast(f"fuzz-{r}".encode())
        drain([world], engines)
        for r, eng in enumerate(engines):
            msgs = collect_all(eng)
            assert len(msgs) == ws - 1

    @pytest.mark.parametrize("ws", [4, 8, 16])
    def test_hacky_sack(self, ws):
        """All-to-all stress: every catch of the 'ball' triggers a new
        broadcast (testcases.c:638-697)."""
        world, engines = build_world(ws, latency=2, seed=99)
        rng = random.Random(7)
        rounds = 20
        holder = 0
        for i in range(rounds):
            engines[holder].bcast(f"ball-{i}".encode())
            holder = rng.choice([r for r in range(ws) if r != holder])
        drain([world], engines)
        total_pickup = 0
        for eng in engines:
            total_pickup += len(collect_all(eng))
        # every bcast delivered to ws-1 ranks, exactly once
        assert total_pickup == rounds * (ws - 1)

    def test_payload_too_large(self):
        world, engines = build_world(2)
        with pytest.raises(ValueError):
            engines[0].bcast(b"x" * (engines[0].msg_size_max + 1))

    def test_counters(self):
        world, engines = build_world(4)
        engines[1].bcast(b"a")
        drain([world], engines)
        assert engines[1].sent_bcast_cnt == 1
        assert sum(e.recved_bcast_cnt for e in engines) == 3

    def test_dedup_window_edge_python(self):
        """Round-2 VERDICT item 8b: pin the Python per-origin dedup
        bound. In-window reorder delivers exactly once; when the
        out-of-order set exceeds 4096 pending seqs, the oldest half's
        gaps are absorbed as seen — a late arrival of an absorbed seq
        is dropped (documented at-most-once degradation), and the set
        can never grow without bound under sustained loss."""
        from rlo_tpu.engine import _Msg
        from rlo_tpu.wire import Frame

        world, engines = build_world(4)
        eng = engines[1]

        def is_dup(seq):
            return eng._bcast_is_dup(_Msg(
                frame=Frame(origin=0, pid=-1, vote=seq, payload=b""),
                tag=int(Tag.BCAST), src=0))

        # in-window reorder: every seq accepted once, replays rejected
        for seq in (5, 3, 0, 1, 2, 4):
            assert not is_dup(seq), seq
        for seq in (5, 3, 0):
            assert is_dup(seq), seq
        # overflow: seqs 7..4104+ pending (6 missing) until the bound
        # absorbs the oldest half
        for seq in range(7, 7 + 4200):
            assert not is_dup(seq)
        ent = eng._seen_bcast[0]
        assert len(ent[1]) <= 4096  # the set is bounded
        assert ent[0] > 5           # watermark advanced past the gap
        # the gap seq (6) was absorbed: its late arrival must drop
        assert is_dup(6)
        # new traffic above the watermark still flows
        assert not is_dup(7 + 4200)

    def test_dedup_window_edge_native(self):
        """C mirror: the 256-bit reorder window. A jump beyond the
        window absorbs the stalest gaps (late arrivals drop,
        at-most-once); within-window reorder stays exactly-once.
        Driven end-to-end: injected BCAST frames at a leaf engine,
        oracle = pickup deliveries."""
        from rlo_tpu.native.bindings import NativeEngine, NativeWorld
        from rlo_tpu.wire import Frame

        with NativeWorld(4) as world:
            eng = NativeEngine(world, 1)

            def inject(seq):
                f = Frame(origin=0, pid=-1, vote=seq, payload=b"x")
                world.inject(src=0, dst=1, tag=int(Tag.BCAST),
                             raw=f.encode())
                for _ in range(50):
                    world.progress_all()

            def delivered():
                n = 0
                while eng.pickup_next() is not None:
                    n += 1
                return n

            # within-window reorder (window = 256 above the watermark)
            for seq in (5, 3, 0, 255, 1):
                inject(seq)
            assert delivered() == 5
            inject(3)  # replay
            assert delivered() == 0
            # jump beyond the window: seq 600 forces absorption of the
            # stalest gaps (2, 4, 6..255 partially — shift = 600-256+
            # watermark math); the absorbed seq 2 must then drop late
            inject(600)
            assert delivered() == 1
            inject(2)  # was a gap, now absorbed below the watermark
            assert delivered() == 0
            # fresh in-window traffic still flows
            inject(599)
            assert delivered() == 1

    def test_pickup_while_forwarding(self):
        """A message may be picked up before its forwards complete
        (queue_wait_and_pickup semantics, rootless_ops.c:938-955)."""
        world, engines = build_world(8, latency=10, seed=5)
        engines[0].bcast(b"slow")
        # progress a bounded number of steps, picking up as soon as possible
        seen = [False] * 8
        for _ in range(500):
            for r, eng in enumerate(engines):
                if (m := eng.pickup_next()) is not None:
                    assert not seen[r]
                    seen[r] = True
            from rlo_tpu.engine import progress_all
            engines[0].manager.progress_all()
            if all(seen[1:]):
                break
        assert all(seen[1:])
        drain([world], engines)


class TestFlatFanout:
    """fanout='flat' (round 4, mirror of the C engine's
    rlo_engine_set_fanout): depth-1 spanning tree — origin sends to
    every live member, receivers are leaves. Rootlessness, dedup, and
    IAR vote accounting are schedule-independent; these pin it at the
    Python engine level (the C side is pinned by the demo suite under
    RLO_FANOUT=flat)."""

    def test_bcast_delivers_exactly_once_everywhere(self):
        for ws in (2, 5, 8):
            world, engines = build_world(ws, fanout="flat")
            # the static skip-ring list stays untouched (flat bypasses
            # it in _cur_initiator_targets rather than mutating it)
            from rlo_tpu import topology
            assert engines[0].initiator_targets == \
                topology.initiator_targets(ws, 0)
            assert engines[0]._cur_initiator_targets() == tuple(
                range(1, ws))
            assert engines[1]._fwd_targets(0, 0) == ()
            engines[0].bcast(b"flat")
            engines[ws - 1].bcast(b"rootless")  # any origin
            drain([world], engines)
            for r, eng in enumerate(engines):
                got = sorted(m.data for m in collect_all(eng))
                want = sorted(b for o, b in ((0, b"flat"),
                                             (ws - 1, b"rootless"))
                              if o != r)
                assert got == want, (ws, r)

    def test_iar_veto_and_approval(self):
        from rlo_tpu.engine import EngineManager, ProgressEngine
        from rlo_tpu.transport.loopback import LoopbackWorld

        world = LoopbackWorld(6)
        mgr = EngineManager()
        votes = [1] * 6
        engines = [ProgressEngine(world.transport(r),
                                  judge_cb=lambda p, c, r=r: votes[r],
                                  manager=mgr, fanout="flat")
                   for r in range(6)]
        # proposer hears every member directly (await_from prunes as
        # leaf votes arrive, possibly within this very call's progress
        # turn, so the assertable invariant is votes_needed)
        engines[2].submit_proposal(b"p", pid=2)
        assert engines[2].my_own_proposal.votes_needed == 5
        for _ in range(10_000):
            mgr.progress_all()
            if engines[2].vote_my_proposal() != -1:
                break
        assert engines[2].vote_my_proposal() == 1
        drain([world], engines)
        for r, eng in enumerate(engines):
            collect_all(eng)  # consume decisions
        # veto round from another proposer
        votes[4] = 0
        engines[5].submit_proposal(b"q", pid=5)
        for _ in range(10_000):
            mgr.progress_all()
            if engines[5].vote_my_proposal() != -1:
                break
        assert engines[5].vote_my_proposal() == 0
        drain([world], engines)

    def test_invalid_fanout_rejected(self):
        import pytest as _pytest
        with _pytest.raises(ValueError, match="unknown fanout"):
            build_world(4, fanout="butterfly")
