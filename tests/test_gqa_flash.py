"""Grouped-query attention through the FUSED paths (VERDICT r3 #1).

GQA's point is bandwidth: n_kv_heads compact K/V should be what streams
from HBM (flash kernel) and what crosses ICI (ring ppermute / ulysses
all_to_all) — never an explicitly repeated n_heads-sized copy. Oracles:

  (a) the flash kernel attends grouped K/V natively (group dim folded
      into the Q axis) and matches the explicitly-repeated call in
      values AND grads;
  (b) ring/ulysses with compact K/V match the full-attention oracle on
      repeated K/V, fused and unfused;
  (c) structural: the ppermute ops in the lowered ring jaxpr carry
      n_kv_heads-shaped operands (the ICI-bytes reduction is real, not
      just semantic), for both the pallas and unfused paths — and the
      same for the sp training step of the GQA transformer.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from rlo_tpu.ops.ring_attention import full_attention, ring_attention
from rlo_tpu.ops.ulysses import ulysses_attention
from rlo_tpu.pallas.flash import flash_attention
from rlo_tpu.parallel.mesh import make_mesh, shard_jit

WS = 8
H, HKV, D = 4, 2, 16
G = H // HKV


def make_qkv(seed, seq, dtype=jnp.float32):
    rng = np.random.default_rng(seed)

    def one(heads):
        return jnp.asarray(
            rng.standard_normal((seq, heads, D)) * 0.5, dtype)

    return one(H), one(HKV), one(HKV)


def repeat_kv(t):
    return jnp.repeat(t, G, axis=1)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grouped_matches_repeated(causal):
    q, k, v = make_qkv(0, 32)
    got = flash_attention(q, k, v, causal=causal, interpret=True,
                          block_q=16)
    want = flash_attention(q, repeat_kv(k), repeat_kv(v), causal=causal,
                           interpret=True, block_q=16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grouped_grads_match_repeated(causal):
    q, k, v = make_qkv(1, 32)
    w = jnp.cos(jnp.arange(q.size).reshape(q.shape) * 0.01)

    def loss_grouped(q_, k_, v_):
        out = flash_attention(q_, k_, v_, causal=causal, interpret=True,
                              block_q=16)
        return jnp.sum(out.astype(jnp.float32) * w)

    def loss_repeated(q_, k_, v_):
        out = flash_attention(q_, repeat_kv(k_), repeat_kv(v_),
                              causal=causal, interpret=True, block_q=16)
        return jnp.sum(out.astype(jnp.float32) * w)

    gg = jax.grad(loss_grouped, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_repeated, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gg, gr, ["dq", "dk", "dv"]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4, err_msg=name)


def _run_ring(q, k, v, causal, use_pallas, block_q=256):
    mesh = make_mesh((WS,), ("sp",))
    fn = shard_jit(
        lambda q_, k_, v_: ring_attention(
            q_, k_, v_, "sp", causal=causal, use_pallas=use_pallas,
            block_q=block_q),
        mesh, (P("sp"), P("sp"), P("sp")), P("sp"),
        check_vma=not use_pallas)
    return np.asarray(fn(q, k, v))


@pytest.mark.parametrize("use_pallas", [False, True])
@pytest.mark.parametrize("causal", [False, True])
def test_ring_grouped_matches_full(causal, use_pallas):
    q, k, v = make_qkv(2, 64)
    want = np.asarray(full_attention(q, repeat_kv(k), repeat_kv(v),
                                     causal=causal))
    got = _run_ring(q, k, v, causal, use_pallas, block_q=8)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("use_pallas", [False, True])
def test_ulysses_grouped_matches_full(use_pallas):
    # ulysses needs kv heads divisible by the axis size: use ws=2
    mesh = make_mesh((2,), ("sp",))
    q, k, v = make_qkv(3, 64)
    fn = shard_jit(
        lambda q_, k_, v_: ulysses_attention(
            q_, k_, v_, "sp", causal=True, use_pallas=use_pallas,
            block_q=16),
        mesh, (P("sp"), P("sp"), P("sp")), P("sp"),
        check_vma=not use_pallas)
    want = np.asarray(full_attention(q, repeat_kv(k), repeat_kv(v),
                                     causal=True))
    np.testing.assert_allclose(np.asarray(fn(q, k, v)), want,
                               rtol=2e-5, atol=2e-5)


def _collect_prim_shapes(jaxpr, name, acc):
    """All output shapes of ``name`` primitives, recursing into every
    sub-jaxpr (scan/while/pjit/shard_map/custom_vjp bodies)."""
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == name:
            acc.extend(tuple(v.aval.shape) for v in eqn.outvars)
        for p in eqn.params.values():
            _collect_from_param(p, name, acc)


def _collect_from_param(p, name, acc):
    # duck-typed: ClosedJaxpr has .jaxpr, Jaxpr has .eqns
    if hasattr(p, "jaxpr") and hasattr(getattr(p, "jaxpr"), "eqns"):
        _collect_prim_shapes(p.jaxpr, name, acc)
    elif hasattr(p, "eqns"):
        _collect_prim_shapes(p, name, acc)
    elif isinstance(p, (list, tuple)):
        for x in p:
            _collect_from_param(x, name, acc)


def ppermute_shapes(fn, *args):
    jaxpr = jax.make_jaxpr(fn)(*args)
    acc = []
    _collect_prim_shapes(jaxpr.jaxpr, name="ppermute", acc=acc)
    return acc


@pytest.mark.parametrize("use_pallas", [False, True])
def test_ring_rotates_compact_kv(use_pallas):
    """STRUCTURAL: every ppermute in the ring jaxpr moves n_kv_heads
    (not n_heads) worth of K/V — the ICI-bytes reduction GQA exists
    for. The pallas path rotates head-leading (Hkv, blk, D); the
    unfused path rotates caller-layout (blk, Hkv, D)."""
    q, k, v = make_qkv(4, 64)
    mesh = make_mesh((WS,), ("sp",))
    fn = shard_jit(
        lambda q_, k_, v_: ring_attention(
            q_, k_, v_, "sp", causal=True, use_pallas=use_pallas,
            block_q=8),
        mesh, (P("sp"), P("sp"), P("sp")), P("sp"),
        check_vma=not use_pallas)
    shapes = ppermute_shapes(fn, q, k, v)
    blk = 64 // WS
    assert shapes, "expected ppermute ops in the ring jaxpr"
    want = (HKV, blk, D) if use_pallas else (blk, HKV, D)
    for s in shapes:
        assert s == want, f"ppermute moves {s}, expected compact {want}"


def test_gqa_sp_train_step_rotates_compact_kv():
    """End-to-end structural check on the real training step: the ring
    K/V rotations in a GQA sp train_step jaxpr carry kv_heads — no
    jnp.repeat sneaks in between the projection and the ring."""
    from rlo_tpu.models.transformer import (TransformerConfig,
                                            init_params, train_step)

    cfg = TransformerConfig(vocab=89, d_model=32, n_heads=4, n_layers=1,
                            d_ff=64, dtype="float32", n_kv_heads=2)
    mesh = make_mesh((2,), ("sp",))
    params = init_params(jax.random.PRNGKey(0), cfg)
    toks = jnp.zeros((2, 16), jnp.int32)
    step = shard_jit(
        lambda p, t: train_step(p, t, cfg, lr=1e-2, sp_axis="sp"),
        mesh, (P(), P(None, "sp")), (P(), P()))
    shapes = ppermute_shapes(step, params, toks)
    blk = 16 // 2
    assert shapes, "expected ppermute ops in the sp train jaxpr"
    # ring K/V rotations (4d with batch) must be compact — unfused
    # layout (b, blk, Hkv, D) or fused head-leading (b, Hkv, blk, D),
    # whichever the platform gate picked; the loss's label shift
    # ppermute (2, 1) also appears
    kv_rot = [s for s in shapes if len(s) == 4]
    assert kv_rot, f"no K/V rotations found in {shapes}"
    for s in kv_rot:
        assert cfg.n_kv_heads in (s[1], s[2]) and \
            cfg.n_heads not in (s[1], s[2]), \
            f"K/V rotation {s} does not carry compact " \
            f"{cfg.n_kv_heads}-head K/V"


@pytest.mark.parametrize("use_pallas", [False, True])
def test_ulysses_grouped_kv_fewer_than_axis(use_pallas):
    """n_kv_heads smaller than the ulysses axis: K/V partially repeats
    to the smallest ws-divisible head count (here 2 -> 4 of 8 query
    heads) and still matches the oracle."""
    ws = 4
    rng = np.random.default_rng(7)
    q = jnp.asarray(rng.standard_normal((64, 8, D)) * 0.5, jnp.float32)
    k = jnp.asarray(rng.standard_normal((64, 2, D)) * 0.5, jnp.float32)
    v = jnp.asarray(rng.standard_normal((64, 2, D)) * 0.5, jnp.float32)
    mesh = make_mesh((ws,), ("sp",))
    fn = shard_jit(
        lambda q_, k_, v_: ulysses_attention(
            q_, k_, v_, "sp", causal=True, use_pallas=use_pallas,
            block_q=16),
        mesh, (P("sp"), P("sp"), P("sp")), P("sp"),
        check_vma=not use_pallas)
    want = np.asarray(full_attention(q, jnp.repeat(k, 4, axis=1),
                                     jnp.repeat(v, 4, axis=1),
                                     causal=True))
    np.testing.assert_allclose(np.asarray(fn(q, k, v)), want,
                               rtol=2e-5, atol=2e-5)


def test_ring_rejects_nondivisible_heads():
    q, _, _ = make_qkv(5, 64)
    k = jnp.zeros((64, 3, D), jnp.float32)
    mesh = make_mesh((WS,), ("sp",))
    fn = shard_jit(
        lambda q_, k_, v_: ring_attention(q_, k_, v_, "sp", causal=True),
        mesh, (P("sp"), P("sp"), P("sp")), P("sp"))
    with pytest.raises(ValueError, match="multiple"):
        fn(q, k, k)
