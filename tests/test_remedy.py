"""Consensus-gated remediation (rlo_tpu/observe/remedy.py,
docs/DESIGN.md §22): the control half of the fleet telescope.

Layers under test, innermost out:

  - the record vocabulary: codec roundtrip, kind-byte alignment with
    ``fabric.Rec``, newest-wins ``(version, proposer)`` ordering;
  - the judge predicate (``DecodeFabric._judge_remedy``): membership
    coherence, the min-alive quorum, the blast-radius cap;
  - execution (``DecodeFabric._apply_remedy``): idempotent,
    newest-wins per key-space, stale records can never regress state;
  - :class:`RemedyPolicy` hysteresis: trip -> want -> proposal on the
    proposer only, per-action cooldown, veto retry, cause-quiet
    expiry, un-quarantine only after a full clear window;
  - watchdog view-change forgiveness (the false-positive fix): a
    legitimate membership change resets the rate windows of the two
    churn-cost counters, at most once per rule window;
  - health-aware placement: quarantined ranks never own work;
  - the ``remedy_*`` scenarios end to end, including the seed-replay
    case proving two runs are schedule-digest- AND decision-identical.
"""

import logging
from types import SimpleNamespace

import pytest

from rlo_tpu.observe.remedy import (DEFAULT_ACTIONS, KIND_BACKPRESSURE,
                                    KIND_NAMES, KIND_QUARANTINE,
                                    KIND_REBALANCE, KIND_UNQUARANTINE,
                                    REMEDY_KINDS, REMEDY_PID_BASE,
                                    RemedyPolicy, RemedyRecord)
from rlo_tpu.observe.watchdog import (DEFAULT_RULES, Incident, Watchdog,
                                      parse_rule)
from rlo_tpu.serving.fabric import FABRIC_PID_BASE, DecodeFabric, Rec
from rlo_tpu.serving.placement import healthy_members, pick_owner
from rlo_tpu.serving.scenario import make_fabric_scenario
from rlo_tpu.transport.sim import make_scenario

logging.getLogger("rlo_tpu").setLevel(logging.ERROR)


# ---------------------------------------------------------------------------
# record vocabulary
# ---------------------------------------------------------------------------

class TestRecordCodec:
    def test_roundtrip_every_kind(self):
        for i, kind in enumerate(REMEDY_KINDS):
            rec = RemedyRecord(kind=kind, target=3 - i, level=i,
                               version=10 + i, proposer=i % 4)
            back = RemedyRecord.decode(kind, rec.encode())
            assert back == rec
            assert back.name() == KIND_NAMES[kind]

    def test_decode_rejects_garbage(self):
        rec = RemedyRecord(KIND_QUARANTINE, 1, 0, 5, 0)
        raw = rec.encode()
        assert RemedyRecord.decode(99, raw) is None     # unknown kind
        assert RemedyRecord.decode(KIND_QUARANTINE, raw[:-1]) is None
        assert RemedyRecord.decode(KIND_QUARANTINE, raw, off=4) is None

    def test_kind_bytes_align_with_fabric_rec(self):
        # observe.remedy owns the vocabulary but must not import the
        # fabric; the fabric pins the same values. Drift here would
        # silently mis-dispatch records in fabric._on_record.
        assert KIND_QUARANTINE == int(Rec.QUARANTINE) == 5
        assert KIND_UNQUARANTINE == int(Rec.UNQUARANTINE) == 6
        assert KIND_BACKPRESSURE == int(Rec.BACKPRESSURE) == 7
        assert KIND_REBALANCE == int(Rec.REBALANCE) == 8
        # remedy rounds ride a reserved pid window beside placement
        assert REMEDY_PID_BASE == FABRIC_PID_BASE + 1024

    def test_newest_wins_key_order(self):
        a = RemedyRecord(KIND_QUARANTINE, 1, 0, version=4, proposer=2)
        b = RemedyRecord(KIND_UNQUARANTINE, 1, 0, version=5, proposer=0)
        tie = RemedyRecord(KIND_QUARANTINE, 1, 0, version=4, proposer=3)
        assert b.key() > a.key()
        assert tie.key() > a.key()  # proposer breaks exact version ties


# ---------------------------------------------------------------------------
# the judge predicate (shared by relay judgment and proposer pre-flight)
# ---------------------------------------------------------------------------

def _judge_stub(group, quarantined=(), min_alive=3, blast=0.25):
    return SimpleNamespace(
        engine=SimpleNamespace(group=tuple(group)),
        quarantined=set(quarantined),
        remedy_min_alive=min_alive,
        remedy_blast_frac=blast)


def _judge(stub, rec):
    return DecodeFabric._judge_remedy(stub, rec)


class TestJudge:
    def test_vetoes_nonmember_target(self):
        s = _judge_stub(group=(0, 1, 2, 3))
        rec = RemedyRecord(KIND_QUARANTINE, 7, 0, 5, 0)
        assert _judge(s, rec) == 0

    def test_vetoes_below_min_alive_quorum(self):
        # 4 members, one already quarantined, min-alive 3: a second
        # quarantine would leave 2 live non-quarantined members
        s = _judge_stub(group=(0, 1, 2, 3), quarantined=(3,))
        assert _judge(s, RemedyRecord(KIND_QUARANTINE, 2, 0, 5, 0)) == 0
        # the partitioned-minority shape: a 2-member side can never
        # quarantine anyone against a STATIC-majority quorum
        side = _judge_stub(group=(2, 3), min_alive=3)
        assert _judge(side, RemedyRecord(KIND_QUARANTINE, 3, 0, 9, 2)) == 0

    def test_vetoes_blast_radius_cap(self):
        # 8 members, cap = int(0.25 * 8) = 2, quorum satisfied either
        # way: the THIRD quarantine breaches the cap
        s = _judge_stub(group=range(8), quarantined=(1, 2), min_alive=2)
        assert _judge(s, RemedyRecord(KIND_QUARANTINE, 3, 0, 5, 0)) == 0
        # re-quarantining an already-quarantined member is idempotent,
        # not a new casualty — the cap does not veto it
        assert _judge(s, RemedyRecord(KIND_QUARANTINE, 2, 0, 5, 0)) == 1

    def test_quarantine_allowed_inside_budget(self):
        s = _judge_stub(group=range(8), min_alive=2)
        assert _judge(s, RemedyRecord(KIND_QUARANTINE, 5, 0, 5, 0)) == 1

    def test_unquarantine_gated_on_liveness_only(self):
        s = _judge_stub(group=(0, 1, 2), quarantined=(2,))
        assert _judge(s, RemedyRecord(KIND_UNQUARANTINE, 2, 0, 6, 0)) == 1
        # lifting a DEAD rank's quarantine re-arms the flap: veto
        dead = _judge_stub(group=(0, 1), quarantined=(2,))
        assert _judge(dead, RemedyRecord(KIND_UNQUARANTINE, 2, 0, 6, 0)) == 0

    def test_backpressure_level_bounds(self):
        s = _judge_stub(group=(0, 1, 2, 3))
        assert _judge(s, RemedyRecord(KIND_BACKPRESSURE, -1, 3, 5, 0)) == 1
        assert _judge(s, RemedyRecord(KIND_BACKPRESSURE, -1, 17, 5, 0)) == 0
        assert _judge(s, RemedyRecord(KIND_BACKPRESSURE, -1, -2, 5, 0)) == 0

    def test_rebalance_and_unknown(self):
        s = _judge_stub(group=(0, 1, 2, 3))
        assert _judge(s, RemedyRecord(KIND_REBALANCE, -1, 2, 5, 0)) == 1
        assert _judge(s, RemedyRecord(99, -1, 0, 5, 0)) == 0


# ---------------------------------------------------------------------------
# execution: idempotent, newest-wins per key-space
# ---------------------------------------------------------------------------

class _Counter:
    def __init__(self):
        self.value = 0

    def inc(self, n=1):
        self.value += n

    def set(self, v):
        self.value = v


class _Metrics:
    def __init__(self):
        self._m = {}

    def counter(self, name):
        return self._m.setdefault(name, _Counter())

    gauge = counter


def _apply_stub(group=(0, 1, 2, 3)):
    return SimpleNamespace(
        clock=lambda: 42.0,
        engine=SimpleNamespace(group=tuple(group)),
        quarantined=set(),
        metrics=_Metrics(),
        remedy_log=[],
        bp_level=0,
        bp_window=25.0,
        _bp_next_decay=float("inf"),
        _bp_ver=None, _bp_rec=None,
        _quar_ver={}, _quar_recs={},
        _rebal_ver=None, _rebal_pending=False,
        _next_place=0.0,
        _remedy_ver_max=0)


def _apply(stub, rec):
    DecodeFabric._apply_remedy(stub, rec)


class TestApplyRemedy:
    def test_stale_record_never_regresses_quarantine(self):
        f = _apply_stub()
        _apply(f, RemedyRecord(KIND_QUARANTINE, 2, 0, version=5,
                               proposer=0))
        assert f.quarantined == {2}
        # a stale UNQUARANTINE re-flooded out of an old view: no-op
        _apply(f, RemedyRecord(KIND_UNQUARANTINE, 2, 0, version=4,
                               proposer=3))
        assert f.quarantined == {2}
        assert len(f.remedy_log) == 1  # the stale record left no trace
        # the genuinely newer lift wins
        _apply(f, RemedyRecord(KIND_UNQUARANTINE, 2, 0, version=6,
                               proposer=1))
        assert f.quarantined == set()
        assert len(f.remedy_log) == 2

    def test_quarantine_idempotent_per_target(self):
        f = _apply_stub()
        rec = RemedyRecord(KIND_QUARANTINE, 1, 0, 7, 2)
        _apply(f, rec)
        _apply(f, rec)  # decision fan-out + heal re-broadcast replay
        assert f.quarantined == {1}
        assert len(f.remedy_log) == 1

    def test_backpressure_newest_wins_and_arms_decay(self):
        f = _apply_stub()
        _apply(f, RemedyRecord(KIND_BACKPRESSURE, -1, 2, 5, 0))
        assert f.bp_level == 2 and f._bp_next_decay == 42.0 + 25.0
        _apply(f, RemedyRecord(KIND_BACKPRESSURE, -1, 5, 4, 0))  # stale
        assert f.bp_level == 2
        _apply(f, RemedyRecord(KIND_BACKPRESSURE, -1, 0, 6, 0))
        assert f.bp_level == 0 and f._bp_next_decay == float("inf")

    def test_rebalance_forces_fresh_placement_round(self):
        f = _apply_stub()
        f._next_place = 99.0
        _apply(f, RemedyRecord(KIND_REBALANCE, -1, 3, 5, 0))
        assert f._rebal_pending and f._next_place == float("-inf")

    def test_version_high_water_feeds_next_proposal(self):
        f = _apply_stub()
        _apply(f, RemedyRecord(KIND_QUARANTINE, 1, 0, version=11,
                               proposer=0))
        assert f._remedy_ver_max == 11
        f.engine.epoch = 2
        assert DecodeFabric.next_remedy_version(f) == 12


# ---------------------------------------------------------------------------
# RemedyPolicy hysteresis (stubbed fabric + watchdog, manual clock)
# ---------------------------------------------------------------------------

class _FakeFabric:
    """The minimal surface RemedyPolicy touches. The judge/propose
    hooks are recordable and rig-able so every hysteresis branch is
    reachable without a simulator."""

    def __init__(self, rank=0, group=(0, 1, 2, 3)):
        self.rank = rank
        self.engine = SimpleNamespace(group=tuple(group), epoch=1)
        self.quarantined = set()
        self.bp_level = 0
        self.remedy = None
        self._now = [0.0]
        self.telemetry = SimpleNamespace(
            view=SimpleNamespace(incarnations=lambda: dict(self.incs)))
        self.incs = {}
        self.judge_verdict = 1
        self.slot_free = True
        self.submitted = []
        self._ver = 0

    def clock(self):
        return self._now[0]

    def advance(self, dt):
        self._now[0] += dt

    def _judge_remedy(self, rec):
        return self.judge_verdict

    def propose_remedy(self, rec):
        if not self.slot_free:
            return False
        self.submitted.append(rec)
        return True

    def next_remedy_version(self):
        self._ver += 1
        return self._ver


def _trip(wd, name, vtime):
    rule = next(r for r in wd.rules if r.name == name)
    wd.incidents.append(Incident(rule=rule, value=99.0, vtime=vtime,
                                 trip=0))


def _policy(rank=0, **kw):
    fab = _FakeFabric(rank=rank)
    wd = SimpleNamespace(rules=[parse_rule(r) for r in DEFAULT_RULES],
                         incidents=[])
    pol = RemedyPolicy(fab, wd, **kw)
    assert fab.remedy is pol  # construction registers itself
    return fab, wd, pol


class TestPolicyHysteresis:
    def test_storm_trip_quarantines_the_flapper(self):
        fab, wd, pol = _policy()
        fab.incs = {1: 0, 2: 2, 3: 1}  # rank 2 flapped twice
        _trip(wd, "retransmit-storm", 1.0)
        pol.step()
        assert [(r.kind, r.target) for r in fab.submitted] == \
            [(KIND_QUARANTINE, 2)]

    def test_no_flapper_falls_back_to_backpressure(self):
        fab, wd, pol = _policy()
        fab.incs = {r: 0 for r in range(4)}  # nobody restarted: load
        _trip(wd, "retransmit-storm", 1.0)
        pol.step()
        assert [r.kind for r in fab.submitted] == [KIND_BACKPRESSURE]
        assert fab.submitted[0].level == 1  # AIMD: one level up

    def test_backlog_trip_maps_to_backpressure(self):
        fab, wd, pol = _policy()
        _trip(wd, "pickup-backlog-growth", 1.0)
        pol.step()
        assert [r.kind for r in fab.submitted] == [KIND_BACKPRESSURE]

    def test_epoch_lag_trip_maps_to_rebalance(self):
        fab, wd, pol = _policy()
        _trip(wd, "epoch-lag-ceiling", 1.0)
        pol.step()
        assert [r.kind for r in fab.submitted] == [KIND_REBALANCE]
        assert fab.submitted[0].level == fab.engine.epoch

    def test_only_the_proposer_submits(self):
        fab, wd, pol = _policy(rank=2)  # lowest non-quarantined is 0
        _trip(wd, "pickup-backlog-growth", 1.0)
        pol.step()
        assert fab.submitted == []
        # the proposer role moves to the next survivor: quarantining
        # ranks 0 and 1 makes rank 2 the proposer
        fab.quarantined = {0, 1}
        pol.step()
        assert len(fab.submitted) == 1

    def test_cooldown_paces_repeat_proposals(self):
        fab, wd, pol = _policy(cooldown=12.0)
        _trip(wd, "pickup-backlog-growth", 1.0)
        pol.step()
        _trip(wd, "pickup-backlog-growth", 2.0)  # still tripping
        fab.advance(5.0)
        pol.step()  # inside the cooldown: no second submit
        assert len(fab.submitted) == 1
        fab.advance(8.0)
        pol.step()
        assert len(fab.submitted) == 2
        assert fab.submitted[1].level == 1  # decide never ran: +1 again

    def test_vetoed_want_survives_and_retries(self):
        fab, wd, pol = _policy(retry=3.0)
        fab.incs = {3: 1}
        fab.judge_verdict = 0  # e.g. target mid-flap, not a member
        _trip(wd, "retransmit-storm", 1.0)
        pol.step()
        assert fab.submitted == []
        fab.advance(1.0)
        pol.step()  # inside retry pacing: no pre-flight spam
        fab.judge_verdict = 1
        assert fab.submitted == []
        fab.advance(3.0)
        pol.step()  # target rejoined, veto lifted: proposal goes out
        assert [(r.kind, r.target) for r in fab.submitted] == \
            [(KIND_QUARANTINE, 3)]

    def test_busy_slot_retries_next_pump_without_cooldown(self):
        fab, wd, pol = _policy()
        fab.slot_free = False  # a placement round is in flight
        _trip(wd, "pickup-backlog-growth", 1.0)
        pol.step()
        assert fab.submitted == []
        fab.slot_free = True
        pol.step()  # no retry pacing for slot-busy: next pump wins
        assert len(fab.submitted) == 1

    def test_want_expires_when_cause_goes_quiet(self):
        fab, wd, pol = _policy(clear_window=35.0)
        fab.judge_verdict = 0  # keep the want un-proposable
        _trip(wd, "pickup-backlog-growth", 1.0)
        pol.step()
        fab.advance(40.0)  # cause quiet past clear_window
        fab.judge_verdict = 1
        pol.step()
        assert fab.submitted == []  # expired, not proposed late

    def test_decided_outcome_drops_the_want(self):
        fab, wd, pol = _policy()
        _trip(wd, "pickup-backlog-growth", 1.0)
        pol.step()
        rec = fab.submitted[0]
        pol.on_outcome(rec, True)
        assert pol.decided == 1 and pol.stats()["wants"] == []
        pol.on_outcome(rec, False)
        assert pol.rejected == 1
        assert pol.log[0][1] == "BACKPRESSURE" and pol.log[0][4] is True

    def test_unquarantine_waits_a_full_clear_window(self):
        # actions={}: the trip feeds the quiet clock but maps to no
        # corrective want, isolating the un-quarantine hysteresis
        fab, wd, pol = _policy(clear_window=35.0, actions={})
        fab.quarantined = {2}
        _trip(wd, "retransmit-storm", 0.0)
        pol.step()  # consume the trip
        fab.advance(20.0)
        pol.step()  # rules quiet only 20s: hysteresis holds
        assert fab.submitted == []
        fab.advance(20.0)
        pol.step()  # quiet 40s >= clear_window: lift proposed
        assert [(r.kind, r.target) for r in fab.submitted] == \
            [(KIND_UNQUARANTINE, 2)]

    def test_unquarantine_needs_target_back_in_view(self):
        fab, wd, pol = _policy(clear_window=35.0)
        fab.quarantined = {9}  # not in the membership view
        fab.advance(50.0)
        pol.step()
        assert fab.submitted == []

    def test_default_actions_cover_every_default_rule(self):
        assert set(DEFAULT_ACTIONS) == \
            {parse_rule(r).name for r in DEFAULT_RULES}


# ---------------------------------------------------------------------------
# watchdog view-change forgiveness (the false-positive fix)
# ---------------------------------------------------------------------------

class _FakePlane:
    """Just enough TelemetryPlane for Watchdog.check(): a manual clock
    and scriptable rollups."""

    def __init__(self):
        self.now = 0.0
        self.vals = {"arq_retransmits": 0, "rejoins": 0,
                     "pickup_backlog": 0, "view_changes": 0}
        self.view = SimpleNamespace(
            rollups=lambda: dict(self.vals),
            rollup_max=lambda: dict(self.vals))
        self.watchdog = None

    def clock(self):
        return self.now


class TestWatchdogForgiveness:
    RULES = ("retransmit-storm: sum(arq_retransmits) / 10s >= 5.0",
             "pickup-backlog-growth: sum(pickup_backlog) / 10s >= 20.0")

    def test_heal_spike_with_view_change_is_forgiven(self):
        plane = _FakePlane()
        wd = Watchdog(plane, self.RULES, incident_dir="", cooldown=15.0)
        for _ in range(4):
            plane.now += 1.0
            assert wd.check() == []
        # an admission lands: retransmits spike AND view_changes bumps
        # in the same pump — that spike is heal cost, not a storm
        plane.now += 1.0
        plane.vals["arq_retransmits"] = 120
        plane.vals["view_changes"] = 1
        assert wd.check() == []
        assert wd.forgiveness == 1
        # the post-heal value is the new baseline: staying flat after
        # the spike never trips
        for _ in range(12):
            plane.now += 1.0
            assert wd.check() == []

    def test_same_spike_without_view_change_trips(self):
        plane = _FakePlane()
        wd = Watchdog(plane, self.RULES, incident_dir="", cooldown=15.0)
        for _ in range(4):
            plane.now += 1.0
            wd.check()
        plane.now += 1.0
        plane.vals["arq_retransmits"] = 120  # loss, with no vc bump
        fired = wd.check()
        assert [i.rule.name for i in fired] == ["retransmit-storm"]
        assert wd.forgiveness == 0

    def test_forgiveness_rate_limited_per_window(self):
        # a SUSTAINED flap bumps the view faster than the window;
        # forgiving every bump would blind the rule to the cascade
        plane = _FakePlane()
        wd = Watchdog(plane, self.RULES, incident_dir="", cooldown=15.0)
        plane.now = 1.0
        wd.check()
        plane.now = 2.0
        plane.vals["view_changes"] = 1
        plane.vals["arq_retransmits"] = 30
        wd.check()
        assert wd.forgiveness == 1
        plane.now = 5.0
        plane.vals["view_changes"] = 2  # second bump INSIDE the window
        plane.vals["arq_retransmits"] = 160
        fired = wd.check()
        assert wd.forgiveness == 1  # not forgiven again
        assert [i.rule.name for i in fired] == ["retransmit-storm"]

    def test_forgiveness_scoped_to_churn_cost_keys(self):
        # pickup_backlog is not a FORGIVE_KEY: a backlog surge during
        # a view change is still a backlog surge
        plane = _FakePlane()
        wd = Watchdog(plane, self.RULES, incident_dir="", cooldown=15.0)
        plane.now = 1.0
        wd.check()
        plane.now = 2.0
        plane.vals["view_changes"] = 1
        plane.vals["pickup_backlog"] = 500
        fired = wd.check()
        assert [i.rule.name for i in fired] == ["pickup-backlog-growth"]


# ---------------------------------------------------------------------------
# health-aware placement
# ---------------------------------------------------------------------------

class TestHealthyPlacement:
    def test_healthy_members_filters_quarantined(self):
        assert healthy_members((0, 1, 2, 3), (2,)) == (0, 1, 3)
        assert healthy_members((0, 1, 2, 3), ()) == (0, 1, 2, 3)

    def test_never_empty_fallback(self):
        # quarantine excluding everyone: serving degraded beats not
        # serving (the blast-radius judges keep this unreachable)
        assert healthy_members((0, 1), (0, 1)) == (0, 1)

    def test_quarantined_rank_never_picked_as_owner(self):
        loads = {0: (1, 3), 1: (8, 0), 2: (2, 1)}  # rank 1 least loaded
        members = healthy_members((0, 1, 2), (1,))
        for gw in range(3):
            assert pick_owner(gw, members, loads) != 1


# ---------------------------------------------------------------------------
# the remedy_* scenarios end to end (DEFAULT watchdog rules armed)
# ---------------------------------------------------------------------------

class TestRemedyScenarios:
    def test_remedy_flap_quarantines_drains_recovers(self):
        # run() property-checks §22 internally (min-alive, blast cap,
        # expected quarantine target, drain, recovery) and raises
        # SimViolation with a replay recipe on any failure
        res = make_fabric_scenario("remedy_flap", 0).run()
        rem = res["remedy"]
        assert rem["decided"] >= 2  # the quarantine AND its lift
        assert rem["trips"] >= 1
        assert rem["final_quarantined"] == []  # hysteresis lifted it
        names = [e[1] for e in rem["decision_log"] if e[4]]
        assert "QUARANTINE" in names and "UNQUARANTINE" in names

    def test_remedy_flap_seed_replay_identical(self):
        # R5 determinism for the whole remediation loop: same seed =>
        # byte-identical world schedule AND an identical decision
        # sequence (vtime, kind, target, level, outcome)
        a = make_fabric_scenario("remedy_flap", 0).run()
        b = make_fabric_scenario("remedy_flap", 0).run()
        assert a["digest"] == b["digest"] != "protocol-only"
        assert a["remedy"]["decision_log"] == b["remedy"]["decision_log"]
        assert a["remedy"]["decision_log"]  # non-vacuous: decisions ran
        assert a["remedy"]["logs"] == b["remedy"]["logs"]

    def test_remedy_hotspot_backpressure_applies_and_decays(self):
        res = make_fabric_scenario("remedy_hotspot", 0).run()
        rem = res["remedy"]
        bp = [e for logs in rem["logs"].values() for e in logs
              if e[1] == "BACKPRESSURE" and e[3] >= 1]
        assert bp  # the fleet throttled admissions under the hotspot
        assert rem["bp_final"] == 0  # and additively recovered after

    @pytest.mark.slow
    def test_remedy_split_no_dual_quarantine(self):
        # asymmetric partition: the minority side can never satisfy
        # the min-alive quorum, so at most one side decides; run()
        # asserts quarantine-state agreement once the run ends healed
        res = make_fabric_scenario("remedy_split", 0).run()
        assert res["remedy"]["decided"] >= 1
        assert res["remedy"]["final_quarantined"] == []

    def test_clean_churn_weather_never_trips(self):
        # the false-positive regression pin (§22 satellite): ordinary
        # churn weather — kills, rejoins, burst loss, batched
        # admissions — must ride the forgiveness path, not trip the
        # default SLOs (a trip here would quarantine a healthy joiner)
        res = make_scenario("churn_weather", 0).run()
        assert res.get("incidents", []) == []
