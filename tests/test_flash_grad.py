"""Differentiability of the flash kernel (round-2 VERDICT item 1).

The confirmed round-2 crash: jax.grad through the pallas flash path
raised "JVP with aliasing not supported", and ring/ulysses attention
auto-enable that path on TPU — so sp-sharded *training* on the target
hardware was broken. The fix is a custom_vjp whose backward recomputes
score tiles in VMEM (pallas/flash.py:_pallas_bwd). These tests pin:

- the VJP exists: jax.grad through flash_block_update_hld, ring
  attention, and ulysses attention with use_pallas=True does not raise;
- grad parity: both backward implementations ('pallas' hand-written,
  'xla' autodiff-through-restatement) match autodiff through the
  unfused reference math, for single updates and chained updates
  (the ring-loop composition), causal and not, multi-tile K included;
- dtype contract: cotangents come back in the primal dtypes (bf16
  K/V get bf16 grads).

All run in interpret mode on the CPU mesh — the identical kernel code
path that compiles on TPU.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rlo_tpu.ops.ring_attention import full_attention, ring_attention
from rlo_tpu.ops.ulysses import ulysses_attention
from rlo_tpu.pallas.flash import (_NEG, _ref_block_update_hld,
                                  flash_attention, flash_block_update_hld)
from rlo_tpu.parallel.mesh import make_mesh, shard_jit
from jax.sharding import PartitionSpec as P

WS = 8


def make_hld(seed, h, lq, lk, d, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((h, lq, d)) * 0.5, dtype)
    k = jnp.asarray(rng.standard_normal((h, lk, d)) * 0.5, dtype)
    v = jnp.asarray(rng.standard_normal((h, lk, d)) * 0.5, dtype)
    m = jnp.asarray(rng.standard_normal((h, 1, lq)), jnp.float32)
    l = jnp.asarray(rng.uniform(0.5, 2.0, (h, 1, lq)), jnp.float32)
    o = jnp.asarray(rng.standard_normal((h, lq, d)), jnp.float32)
    qp = jnp.arange(lq, dtype=jnp.int32).reshape(1, lq)
    kp = jnp.arange(lk, dtype=jnp.int32).reshape(1, lk)
    return q, k, v, m, l, o, qp, kp


def _loss_of(update):
    """Scalar functional of a block update's (m', l', o') — weights
    every output so every cotangent path is exercised."""
    def loss(q, k, v, m, l, o, qp, kp):
        m2, l2, o2 = update(q, k, v, m, l, o, qp, kp)
        return (jnp.sum(o2 * jnp.cos(jnp.arange(o2.size)
                                     .reshape(o2.shape) * 0.01))
                + jnp.sum(jnp.sin(l2)) + jnp.sum(m2 * 0.3))
    return loss


@pytest.mark.parametrize("bwd", ["xla", "pallas"])
@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("h,lq,lk,d,block_q,block_k", [
    (2, 32, 32, 16, 16, None),      # multi-Q-tile, single K tile
    (1, 16, 64, 8, 16, 16),         # forced multi-K-tile accumulation
    (3, 24, 48, 16, 8, 24),         # odd-ish tiling both axes
])
def test_single_update_grads_match_reference(bwd, causal, h, lq, lk, d,
                                             block_q, block_k):
    args = make_hld(0, h, lq, lk, d)
    flash = functools.partial(flash_block_update_hld, causal=causal,
                              scale=0.3, block_q=block_q,
                              block_k=block_k, interpret=True, bwd=bwd)
    ref = functools.partial(_ref_block_update_hld, causal=causal,
                            scale=0.3)

    def ref_update(q, k, v, m, l, o, qp, kp):
        return ref(q, k, v, m, l, o, qp, kp)

    g_flash = jax.grad(_loss_of(flash), argnums=(0, 1, 2, 3, 4, 5))(*args)
    g_ref = jax.grad(_loss_of(ref_update), argnums=(0, 1, 2, 3, 4, 5))(*args)
    for gf, gr, name in zip(g_flash, g_ref,
                            ["dq", "dk", "dv", "dm", "dl", "do"]):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=2e-4, atol=2e-4, err_msg=name)


@pytest.mark.parametrize("bwd", ["xla", "pallas"])
@pytest.mark.parametrize("causal", [False, True])
def test_chained_updates_grads_match_reference(bwd, causal):
    """Two chained block updates + normalization — the ring-attention
    composition shape, where the (m, l, o) cotangents flowing between
    steps are nontrivial and the m' cotangent identity must hold."""
    h, lq, d = 2, 16, 8
    rng = np.random.default_rng(7)
    q = jnp.asarray(rng.standard_normal((h, lq, d)) * 0.5, jnp.float32)
    k1 = jnp.asarray(rng.standard_normal((h, lq, d)) * 0.5, jnp.float32)
    v1 = jnp.asarray(rng.standard_normal((h, lq, d)) * 0.5, jnp.float32)
    k2 = jnp.asarray(rng.standard_normal((h, lq, d)) * 0.5, jnp.float32)
    v2 = jnp.asarray(rng.standard_normal((h, lq, d)) * 0.5, jnp.float32)
    qp = jnp.arange(lq, dtype=jnp.int32).reshape(1, lq)
    kp1 = qp
    kp2 = jnp.arange(lq, 2 * lq, dtype=jnp.int32).reshape(1, lq)

    def chain(update):
        def loss(q, k1, v1, k2, v2):
            m = jnp.full((h, 1, lq), _NEG, jnp.float32)
            l = jnp.zeros((h, 1, lq), jnp.float32)
            o = jnp.zeros((h, lq, d), jnp.float32)
            m, l, o = update(q, k1, v1, m, l, o, qp, kp1)
            m, l, o = update(q, k2, v2, m, l, o, qp, kp2)
            lt = l.transpose(0, 2, 1)
            out = o / jnp.where(lt > 0, lt, 1.0)
            return jnp.sum(out * jnp.tanh(
                jnp.arange(out.size).reshape(out.shape) * 0.01))
        return loss

    flash = functools.partial(flash_block_update_hld, causal=causal,
                              scale=0.35, block_q=8, interpret=True,
                              bwd=bwd)
    ref = functools.partial(_ref_block_update_hld, causal=causal,
                            scale=0.35)
    g_flash = jax.grad(chain(flash), argnums=(0, 1, 2, 3, 4))(
        q, k1, v1, k2, v2)
    g_ref = jax.grad(chain(ref), argnums=(0, 1, 2, 3, 4))(
        q, k1, v1, k2, v2)
    for gf, gr, name in zip(g_flash, g_ref,
                            ["dq", "dk1", "dv1", "dk2", "dv2"]):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=3e-4, atol=3e-4, err_msg=name)


@pytest.mark.parametrize("causal", [False, True])
def test_tie_routing_matches_jax_semantics(causal):
    """Degenerate inputs that force every tie branch of the exact
    backward: duplicated K rows (reduce_max divides the cotangent among
    cnt tied argmax slots), m preset to the exact row max (maximum's
    0.5/0.5 split), and a zero q row (every score ties at 0). The
    shipped random-data cases never leave cnt==1, so this is the only
    coverage of _rowstats_kernel's count actually being used."""
    h, lq, lk, d = 1, 8, 16, 8
    rng = np.random.default_rng(21)
    q = jnp.asarray(rng.standard_normal((h, lq, d)) * 0.5, jnp.float32)
    q = q.at[0, 3].set(0.0)                   # all-tie row (scores 0)
    k = jnp.asarray(rng.standard_normal((h, lk, d)) * 0.5, jnp.float32)
    k = k.at[0, 9].set(k[0, 4])               # duplicated key: cnt=2
    v = jnp.asarray(rng.standard_normal((h, lk, d)) * 0.5, jnp.float32)
    l = jnp.asarray(rng.uniform(0.5, 2.0, (h, 1, lq)), jnp.float32)
    o = jnp.asarray(rng.standard_normal((h, lq, d)), jnp.float32)
    qp = jnp.arange(lq, dtype=jnp.int32).reshape(1, lq)
    kp = jnp.arange(lk, dtype=jnp.int32).reshape(1, lk)
    # m = the exact row max for rows 0-1 (maximum tie), -inf-ish for 2+
    ref = functools.partial(_ref_block_update_hld, causal=causal,
                            scale=0.3)
    m = jnp.full((h, 1, lq), _NEG, jnp.float32)
    m2_probe, _, _ = ref(q, k, v, m, l, o, qp, kp)
    m = m.at[0, 0, 0:2].set(m2_probe[0, 0, 0:2])
    args = (q, k, v, m, l, o, qp, kp)

    flash = functools.partial(flash_block_update_hld, causal=causal,
                              scale=0.3, block_q=8, block_k=8,
                              interpret=True, bwd="pallas")
    g_flash = jax.grad(_loss_of(flash), argnums=(0, 1, 2, 3, 4, 5))(*args)
    g_ref = jax.grad(_loss_of(ref), argnums=(0, 1, 2, 3, 4, 5))(*args)
    for gf, gr, name in zip(g_flash, g_ref,
                            ["dq", "dk", "dv", "dm", "dl", "do"]):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=2e-4, atol=2e-4, err_msg=name)


@pytest.mark.parametrize("causal", [False, True])
def test_chained_pallas_fast_matches_reference(causal):
    """The fast backward (no tie prepass) must still be exact through
    the normalized composition — the production training path."""
    h, lq, d = 2, 16, 8
    rng = np.random.default_rng(9)
    mk = lambda: jnp.asarray(
        rng.standard_normal((h, lq, d)) * 0.5, jnp.float32)
    q, k1, v1, k2, v2 = mk(), mk(), mk(), mk(), mk()
    qp = jnp.arange(lq, dtype=jnp.int32).reshape(1, lq)
    kp2 = jnp.arange(lq, 2 * lq, dtype=jnp.int32).reshape(1, lq)

    def chain(update):
        def loss(q, k1, v1, k2, v2):
            m = jnp.full((h, 1, lq), _NEG, jnp.float32)
            l = jnp.zeros((h, 1, lq), jnp.float32)
            o = jnp.zeros((h, lq, d), jnp.float32)
            m, l, o = update(q, k1, v1, m, l, o, qp, qp)
            m, l, o = update(q, k2, v2, m, l, o, qp, kp2)
            lt = l.transpose(0, 2, 1)
            out = o / jnp.where(lt > 0, lt, 1.0)
            return jnp.sum(out * jnp.tanh(
                jnp.arange(out.size).reshape(out.shape) * 0.01))
        return loss

    fast = functools.partial(flash_block_update_hld, causal=causal,
                             scale=0.35, block_q=8, interpret=True,
                             bwd="pallas_fast")
    ref = functools.partial(_ref_block_update_hld, causal=causal,
                            scale=0.35)
    g_fast = jax.grad(chain(fast), argnums=(0, 1, 2, 3, 4))(
        q, k1, v1, k2, v2)
    g_ref = jax.grad(chain(ref), argnums=(0, 1, 2, 3, 4))(
        q, k1, v1, k2, v2)
    for gf, gr, name in zip(g_fast, g_ref,
                            ["dq", "dk1", "dv1", "dk2", "dv2"]):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=3e-4, atol=3e-4, err_msg=name)


def test_bf16_kv_cotangent_dtypes():
    h, lq, d = 1, 16, 8
    q, k, v, m, l, o, qp, kp = make_hld(3, h, lq, lq, d)
    kb, vb = k.astype(jnp.bfloat16), v.astype(jnp.bfloat16)
    flash = functools.partial(flash_block_update_hld, causal=True,
                              scale=0.3, block_q=8, interpret=True)
    g = jax.grad(_loss_of(flash), argnums=(0, 1, 2))(
        q, kb, vb, m, l, o, qp, kp)
    assert g[0].dtype == jnp.float32
    assert g[1].dtype == jnp.bfloat16
    assert g[2].dtype == jnp.bfloat16


def make_qkv(seed, seq, heads, dim, dtype=jnp.float32):
    rng = np.random.default_rng(seed)

    def one():
        return jnp.asarray(
            rng.standard_normal((seq, heads, dim)) * 0.5, dtype)

    return one(), one(), one()


def _sharded_grad(attn_fn, q, k, v, use_pallas, **kw):
    """grad of a scalar loss of the sharded attention output, wrt the
    full (replicated-gradient) q, k, v."""
    mesh = make_mesh((WS,), ("sp",))

    def loss(q_, k_, v_):
        out = shard_jit(
            lambda a, b, c: attn_fn(a, b, c, "sp",
                                    use_pallas=use_pallas, **kw),
            mesh, (P("sp"), P("sp"), P("sp")), P("sp"),
            check_vma=False)(q_, k_, v_)
        w = jnp.sin(jnp.arange(out.size).reshape(out.shape) * 0.01)
        return jnp.sum(out.astype(jnp.float32) * w)

    return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_grad_flash_matches_unfused(causal):
    q, k, v = make_qkv(11, 64, 2, 16)
    g_flash = _sharded_grad(ring_attention, q, k, v, True, causal=causal,
                            block_q=8)
    g_plain = _sharded_grad(ring_attention, q, k, v, False,
                            causal=causal)
    for gf, gp, name in zip(g_flash, g_plain, ["dq", "dk", "dv"]):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gp),
                                   rtol=3e-4, atol=3e-4, err_msg=name)


def test_ring_attention_grad_striped_causal():
    q, k, v = make_qkv(12, 64, 2, 16)
    g_flash = _sharded_grad(ring_attention, q, k, v, True, causal=True,
                            block_q=8, layout="striped")
    g_plain = _sharded_grad(ring_attention, q, k, v, False, causal=True,
                            layout="striped")
    for gf, gp, name in zip(g_flash, g_plain, ["dq", "dk", "dv"]):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gp),
                                   rtol=3e-4, atol=3e-4, err_msg=name)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_grad_flash_matches_unfused(causal):
    q, k, v = make_qkv(13, 64, 8, 16)
    g_flash = _sharded_grad(ulysses_attention, q, k, v, True,
                            causal=causal, block_q=8)
    g_plain = _sharded_grad(ulysses_attention, q, k, v, False,
                            causal=causal)
    for gf, gp, name in zip(g_flash, g_plain, ["dq", "dk", "dv"]):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gp),
                                   rtol=3e-4, atol=3e-4, err_msg=name)


def test_flash_attention_whole_grad_matches_full():
    """Single-device whole attention: grads through flash_attention
    equal grads through the unfused full_attention oracle."""
    q, k, v = make_qkv(14, 32, 2, 16)

    def loss(attn):
        def f(q_, k_, v_):
            out = attn(q_, k_, v_)
            w = jnp.cos(jnp.arange(out.size).reshape(out.shape) * 0.02)
            return jnp.sum(out.astype(jnp.float32) * w)
        return f

    g_flash = jax.grad(
        loss(functools.partial(flash_attention, causal=True, block_q=8,
                               interpret=True)),
        argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(
        loss(functools.partial(full_attention, causal=True)),
        argnums=(0, 1, 2))(q, k, v)
    for gf, gp, name in zip(g_flash, g_full, ["dq", "dk", "dv"]):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gp),
                                   rtol=3e-4, atol=3e-4, err_msg=name)


def test_value_unchanged_by_vjp_wrapper():
    """The custom_vjp wrapper must not perturb the primal: forward
    values equal the round-2 kernel output (parity vs the reference
    restatement)."""
    args = make_hld(5, 2, 32, 32, 16)
    got = flash_block_update_hld(*args, causal=True, scale=0.3,
                                 block_q=16, interpret=True)
    want = _ref_block_update_hld(*args, causal=True, scale=0.3)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=1e-5, atol=1e-5)
