"""Reliable delivery: ARQ, op deadlines, and the chaos-soak harness.

The reference library is fire-and-forget — RLO_FAILED exists in its
status enum but is never assigned, and there are no timeouts, retries,
or loss recovery (SURVEY.md §5). This suite proves the net-new
reliability layer end to end:

  - ARQ: per-(src, dst) link seqs, retransmit-until-acked with
    exponential backoff, cumulative ACKs (standalone + heartbeat
    piggyback), and receive-side dedup that makes retransmits
    idempotent through the store-and-forward broadcast path;
  - op deadlines: a proposal that cannot resolve FAILS at its deadline
    (finally assigning ReqState.FAILED for timeouts) and a rootless
    ABORT unparks the round at every relay;
  - the chaos soak: randomized drop/dup/burst-loss/reorder schedules
    plus a mid-soak rank kill, asserting every op terminates and no
    payload is ever delivered twice.
"""

import random
import tempfile
from pathlib import Path

import pytest

from rlo_tpu.engine import EngineManager, ProgressEngine, ReqState
from rlo_tpu.transport.loopback import LoopbackWorld
from rlo_tpu.wire import Tag


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def make_world(ws, latency=0, seed=None, **kw):
    clock = FakeClock()
    world = LoopbackWorld(ws, latency=latency, seed=seed)
    mgr = EngineManager()
    engines = [ProgressEngine(world.transport(r), manager=mgr,
                              clock=clock, **kw)
               for r in range(ws)]
    return world, mgr, engines, clock


def spin(mgr, clock, ticks, dt=0.5):
    for _ in range(ticks):
        clock.advance(dt)
        mgr.progress_all()


def iter_pickups(engines):
    for e in engines:
        while True:
            m = e.pickup_next()
            if m is None:
                break
            yield e.rank, m


# ---------------------------------------------------------------------------
# ARQ: loss recovery + duplicate suppression
# ---------------------------------------------------------------------------

class TestArq:
    def test_dropped_frames_are_retransmitted(self):
        world, mgr, engines, clock = make_world(8, arq_rto=1.0)
        # lose the first two frames rank 0 sends to each of its
        # overlay targets — without ARQ the bcast silently loses
        # subtrees forever
        for dst in engines[0]._cur_initiator_targets():
            world.drop_next(0, dst, 2)
        engines[0].bcast(b"payload-0")
        engines[0].bcast(b"payload-1")
        spin(mgr, clock, 60, dt=0.7)
        got = {}
        for rank, m in iter_pickups(engines):
            got.setdefault(rank, []).append(m.data)
        assert all(sorted(got[r]) == [b"payload-0", b"payload-1"]
                   for r in range(1, 8)), got
        assert sum(e.arq_retransmits for e in engines) >= 2
        assert all(e.arq_unacked() == 0 for e in engines)

    def test_dropped_vote_no_longer_wedges_consensus(self):
        # THE acceptance scenario: the reference wedges
        # RLO_submit_proposal forever on one lost vote frame
        world, mgr, engines, clock = make_world(8, arq_rto=1.0)
        # rank 1 is a leaf in rank 0's tree: its first reliable frame
        # back to 0 is its vote
        world.drop_next(1, 0, 1)
        rc = engines[0].submit_proposal(b"prop", pid=3)
        assert rc == -1  # the vote is in the dropped frame
        spin(mgr, clock, 60, dt=0.7)
        assert engines[0].check_proposal_state() == ReqState.COMPLETED
        assert engines[0].vote_my_proposal() == 1
        assert sum(e.arq_retransmits for e in engines) >= 1

    def test_duplicated_frames_deliver_once(self):
        world, mgr, engines, clock = make_world(4, arq_rto=1.0)
        # duplicate everything rank 0 sends for a while: receivers
        # must drop the copies at the link layer before tag dispatch
        for dst in range(1, 4):
            world.dup_next(0, dst, 10)
        engines[0].bcast(b"once")
        spin(mgr, clock, 40, dt=0.7)
        counts = {}
        for rank, m in iter_pickups(engines):
            counts[rank] = counts.get(rank, 0) + 1
        assert counts == {1: 1, 2: 1, 3: 1}, counts
        assert sum(e.arq_dup_drops for e in engines) >= 1

    def test_retransmit_gives_up_after_max_retries(self):
        world, mgr, engines, clock = make_world(4, arq_rto=1.0,
                                                arq_max_retries=3)
        # the first overlay edge swallows everything; no heartbeat
        # detector is on, so ARQ must give up on its own — and the
        # give-up now escalates to a FAILURE declaration (a half-dead
        # link IS a failure; docs/DESIGN.md §8). The victim is alive
        # and petitions back in, so "failed at rank 0" flaps while the
        # black hole persists: poll for the declared state instead of
        # asserting at an arbitrary instant.
        victim = engines[0]._cur_initiator_targets()[0]
        world.drop_next(0, victim, 10_000)
        engines[0].bcast(b"x")
        for _ in range(300):
            spin(mgr, clock, 1, dt=1.0)
            if engines[0].arq_gave_up >= 1 and \
                    victim in engines[0].failed:
                break
        assert engines[0].arq_gave_up >= 1
        assert victim in engines[0].failed  # give-up => declared
        # gave up, not stuck: nothing remains queued at the
        # black-holed (now declared-failed) link
        assert not engines[0]._tx_unacked.get(victim)

    def test_give_up_does_not_wedge_the_link(self):
        """After ARQ gives up on a frame, the SKIP notice advances the
        receiver's watermark so LATER frames on that link still get
        cumulatively acked — one abandoned frame must not force every
        subsequent frame through retransmit-to-exhaustion."""
        world, mgr, engines, clock = make_world(4, arq_rto=1.0,
                                                arq_max_retries=3)
        victim = engines[0]._cur_initiator_targets()[0]
        world.drop_next(0, victim, 1)  # exactly one frame: a hole
        engines[0].bcast(b"lost")
        spin(mgr, clock, 60, dt=1.0)
        assert engines[0].arq_gave_up == 0 or True  # may have recovered
        # force a give-up: swallow the frame AND all its retransmits
        world.drop_next(0, victim, 10)
        engines[0].bcast(b"doomed")
        spin(mgr, clock, 200, dt=1.0)
        assert engines[0].arq_gave_up >= 1
        assert engines[0].arq_unacked() == 0
        # the link must still work: new traffic acks promptly, without
        # burning through the retry budget
        retx_before = engines[0].arq_retransmits
        engines[0].bcast(b"after-the-hole")
        spin(mgr, clock, 30, dt=1.0)
        assert engines[0].arq_unacked() == 0
        assert engines[0].arq_retransmits == retx_before
        got = [m.data for _, m in iter_pickups(engines)]
        assert got.count(b"after-the-hole") == 3

    def test_acks_piggyback_on_heartbeats(self):
        # no reverse data traffic: the retransmit queue must still
        # drain via the heartbeat piggyback path
        world, mgr, engines, clock = make_world(
            4, arq_rto=50.0, failure_timeout=8.0,
            heartbeat_interval=1.0)
        engines[0].bcast(b"hb-acked")
        # rto 50 >> test horizon: standalone re-acks alone would also
        # cover it, so verify the queue empties LONG before any
        # retransmit fires
        spin(mgr, clock, 20, dt=0.5)
        assert all(e.arq_unacked() == 0 for e in engines)
        assert sum(e.arq_retransmits for e in engines) == 0

    def test_arq_rejects_bad_rto(self):
        world = LoopbackWorld(2)
        with pytest.raises(ValueError):
            ProgressEngine(world.transport(0), manager=EngineManager(),
                           arq_rto=0.0)


# ---------------------------------------------------------------------------
# Op deadlines + rootless ABORT
# ---------------------------------------------------------------------------

class TestOpDeadlines:
    def test_proposal_fails_at_deadline_without_arq(self):
        # no ARQ, vote lost forever: the round must FAIL at the
        # deadline instead of polling -1 until the end of time
        world, mgr, engines, clock = make_world(8)
        world.drop_next(1, 0, 1)  # leaf vote gone for good
        rc = engines[0].submit_proposal(b"p", pid=5, deadline=10.0)
        assert rc == -1
        spin(mgr, clock, 6, dt=1.0)
        assert engines[0].check_proposal_state() == ReqState.IN_PROGRESS
        spin(mgr, clock, 12, dt=1.0)
        assert engines[0].check_proposal_state() == ReqState.FAILED
        assert engines[0].vote_my_proposal() == -1
        assert engines[0].ops_failed == 1

    def test_abort_unparks_relays_and_delivers_notice(self):
        world, mgr, engines, clock = make_world(8)
        world.drop_next(1, 0, 1)
        engines[0].submit_proposal(b"p", pid=5, deadline=5.0)
        spin(mgr, clock, 30, dt=1.0)
        assert engines[0].check_proposal_state() == ReqState.FAILED
        # every relay's parked round is gone (the engines are
        # checkpointable again) and the abort notice was delivered
        aborts = {}
        for rank, m in iter_pickups(engines):
            if m.type == int(Tag.ABORT):
                aborts[rank] = m.pid
        assert all(not e.queue_iar_pending for e in engines)
        assert set(aborts) == set(range(1, 8))
        assert all(pid == 5 for pid in aborts.values())

    def test_failed_pid_can_resubmit_after_deadline(self):
        # composes with elastic re-form: the timed-out op retries
        world, mgr, engines, clock = make_world(8)
        world.drop_next(1, 0, 1)
        engines[0].submit_proposal(b"p", pid=5, deadline=5.0)
        spin(mgr, clock, 20, dt=1.0)
        assert engines[0].check_proposal_state() == ReqState.FAILED
        rc = engines[0].submit_proposal(b"p2", pid=5, deadline=50.0)
        spin(mgr, clock, 30, dt=1.0)
        assert engines[0].check_proposal_state() == ReqState.COMPLETED
        assert engines[0].vote_my_proposal() == 1

    def test_engine_default_deadline_applies(self):
        world, mgr, engines, clock = make_world(4, op_deadline=5.0)
        world.drop_next(1, 0, 1)
        world.drop_next(2, 0, 1)
        world.drop_next(3, 0, 1)
        engines[0].submit_proposal(b"p", pid=9)
        spin(mgr, clock, 20, dt=1.0)
        assert engines[0].check_proposal_state() == ReqState.FAILED

    def test_bcast_deadline_stops_tracking_undeliverable_sends(self):
        # latency holds frames in flight; the deadline abandons the op
        # instead of tracking handles forever
        world, mgr, engines, clock = make_world(4, latency=10_000, seed=7)
        msg = engines[0].bcast(b"x", deadline=5.0)
        assert msg.state == ReqState.IN_PROGRESS
        spin(mgr, clock, 20, dt=1.0)
        assert msg.state == ReqState.FAILED
        assert not engines[0].queue_wait
        assert engines[0].ops_failed == 1

    def test_deadline_does_not_fire_after_decision_sent(self):
        world, mgr, engines, clock = make_world(4, arq_rto=1.0)
        rc = engines[0].submit_proposal(b"p", pid=2, deadline=5.0)
        spin(mgr, clock, 30, dt=1.0)
        assert engines[0].check_proposal_state() == ReqState.COMPLETED
        assert engines[0].ops_failed == 0


# ---------------------------------------------------------------------------
# Chaos soak: randomized kill/drop/dup/reorder schedules over many
# bcast + IAR rounds — every op terminates, no payload delivers twice
# ---------------------------------------------------------------------------

def dump_soak_artifacts(seed, ws):
    """Failed-soak diagnosability: dump the per-rank tracer JSONL and
    the merged Chrome trace to a tmp directory and print the paths
    (with the seed), so a wedged run can be scrubbed in Perfetto
    instead of being just red. Best-effort: an artifact-dump failure
    must never mask the real assertion."""
    from rlo_tpu.utils.timeline import merge_timeline
    from rlo_tpu.utils.tracing import TRACER
    try:
        td = Path(tempfile.mkdtemp(prefix=f"rlo_soak_seed{seed}_"))
        paths = []
        for r in range(ws):
            p = td / f"rank{r}.jsonl"
            TRACER.dump_jsonl(str(p), rank=r)
            paths.append(str(p))
        trace = merge_timeline(paths, out_path=td / "trace.json")
        print(f"\nchaos soak FAILED (seed {seed}): tracer artifacts "
              f"in {td} ({trace['otherData']['events']} events; load "
              f"trace.json in Perfetto / chrome://tracing)")
    except Exception as exc:  # pragma: no cover - diagnostics only
        print(f"\nchaos soak FAILED (seed {seed}); artifact dump "
              f"also failed: {exc!r}")


def run_soak(seed, ws=8, rounds=14, kill_at=7):
    rng = random.Random(seed)
    clock = FakeClock()
    world = LoopbackWorld(ws, latency=3, seed=seed)
    world.set_burst_loss(0.02, 3)
    mgr = EngineManager()
    engines = [ProgressEngine(world.transport(r), manager=mgr,
                              clock=clock, arq_rto=2.0,
                              arq_max_retries=6,
                              failure_timeout=40.0,
                              heartbeat_interval=4.0,
                              op_deadline=120.0)
               for r in range(ws)]
    delivered = {r: [] for r in range(ws)}  # rank -> [(origin, data)]
    decisions = {r: {} for r in range(ws)}  # rank -> {(pid, origin): n}
    submitted = []  # (proposer, pid)
    sent = []       # (origin, data)
    dead = set()

    def pump(ticks, dt=1.0):
        for _ in range(ticks):
            clock.advance(dt)
            mgr.progress_all()
            for r in range(ws):
                if r in dead:
                    continue
                while True:
                    m = engines[r].pickup_next()
                    if m is None:
                        break
                    if m.type == int(Tag.BCAST):
                        delivered[r].append((m.origin, m.data))
                    elif m.type == int(Tag.IAR_DECISION):
                        key = (m.pid, m.origin)
                        decisions[r][key] = decisions[r].get(key, 0) + 1

    for rnd in range(rounds):
        alive = [r for r in range(ws) if r not in dead]
        # random fault injection for this round
        for _ in range(rng.randrange(3)):
            a, b = rng.sample(range(ws), 2)
            world.drop_next(a, b, rng.randrange(1, 3))
        for _ in range(rng.randrange(3)):
            a, b = rng.sample(range(ws), 2)
            world.dup_next(a, b, rng.randrange(1, 3))
        # a few broadcasts from random survivors
        for _ in range(rng.randrange(1, 4)):
            origin = rng.choice(alive)
            data = f"r{rnd}-{origin}-{rng.randrange(1000)}".encode()
            engines[origin].bcast(data)
            sent.append((origin, data))
        # one consensus round, sometimes with a targeted vote drop
        proposer = rng.choice(alive)
        pid = 100 + rnd
        if rng.random() < 0.5:
            peer = rng.choice([r for r in alive if r != proposer])
            world.drop_next(peer, proposer, 1)
        engines[proposer].submit_proposal(
            f"prop-{rnd}".encode(), pid=pid)
        submitted.append((proposer, pid))
        if rnd == kill_at:
            victim = rng.choice([r for r in alive])
            world.kill_rank(victim)
            engines[victim].cleanup()
            dead.add(victim)
        pump(rng.randrange(5, 30))

    # let everything settle: remaining retransmits, heartbeats,
    # failure detection, deadlines
    pump(400)
    return (world, engines, clock, dead, delivered, decisions,
            submitted, sent)


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_chaos_soak(seed):
    from rlo_tpu.utils.tracing import TRACER
    TRACER.clear()
    try:
        with TRACER.enable():
            (world, engines, clock, dead, delivered, decisions,
             submitted, sent) = run_soak(seed)
            ws = len(engines)
            _check_soak(seed, world, engines, dead, delivered,
                        decisions, submitted)
    except AssertionError:
        dump_soak_artifacts(seed, ws)
        raise
    finally:
        TRACER.clear()


def _check_soak(seed, world, engines, dead, delivered, decisions,
                submitted):
    ws = len(engines)
    survivors = [r for r in range(ws) if r not in dead]

    # 1. every op on a surviving proposer TERMINATED: COMPLETED or
    #    FAILED-by-deadline, never hanging IN_PROGRESS
    for proposer, pid in submitted:
        if proposer in dead:
            continue
        st = engines[proposer].my_own_proposal.state
        assert st in (ReqState.COMPLETED, ReqState.FAILED), \
            f"seed {seed}: proposer {proposer} pid {pid} hung in {st}"

    # 2. no relay is left parked on a round forever (aborts/decisions/
    #    failure discounting cleared them all)
    for r in survivors:
        assert not engines[r].queue_iar_pending, \
            f"seed {seed}: rank {r} still parks " \
            f"{len(engines[r].queue_iar_pending)} rounds"

    # 3. exactly-once: despite dup injection, ARQ retransmits, and
    #    view-change re-floods, no payload was ever delivered twice
    for r in survivors:
        assert len(delivered[r]) == len(set(delivered[r])), \
            f"seed {seed}: rank {r} saw duplicate broadcast payloads"
        for key, n in decisions[r].items():
            assert n == 1, f"seed {seed}: rank {r} saw decision " \
                           f"{key} {n} times"

    # 4. no survivor-to-survivor delivery was lost while no failure
    #    was in flight: ARQ + re-flood means every broadcast a
    #    survivor initiated AFTER the kill settled reaches everyone
    # (pre-kill traffic can legitimately be at-most-once if the dead
    # rank was mid-forward, so only assert the exactly-once and
    # termination invariants globally, plus ARQ quiescence:)
    for r in survivors:
        assert engines[r].arq_unacked() == 0, \
            f"seed {seed}: rank {r} still has unacked frames"

    # 5. the chaos actually exercised the machinery
    assert world.dropped_cnt > 0
    assert sum(e.arq_retransmits for e in engines) > 0


# ---------------------------------------------------------------------------
# Native C engine parity: the same ARQ state machine in rlo_engine.c
# ---------------------------------------------------------------------------

class TestNativeArqParity:
    def _native(self):
        pytest.importorskip("numpy")
        from rlo_tpu.native import bindings as nb
        try:
            nb.load()
        except Exception as exc:  # pragma: no cover - no cc in env
            pytest.skip(f"native core unavailable: {exc}")
        return nb

    def test_native_dropped_frames_retransmit(self):
        nb = self._native()
        with nb.NativeWorld(8) as world:
            engines = [nb.NativeEngine(world, r) for r in range(8)]
            for e in engines:
                e.enable_arq(500, max_retries=12)
            for dst in range(1, 8):
                world.drop_next(0, dst, 2)
            engines[0].bcast(b"native-0")
            engines[0].bcast(b"native-1")
            world.drain(100_000_000)
            for r in range(1, 8):
                got = []
                while (m := engines[r].pickup_next()) is not None:
                    got.append(m.data)
                assert sorted(got) == [b"native-0", b"native-1"]
                assert engines[r].err == 0
            assert sum(e.arq_retransmits for e in engines) >= 2
            assert all(e.arq_unacked == 0 for e in engines)

    def test_native_duplicates_dropped(self):
        nb = self._native()
        with nb.NativeWorld(4) as world:
            engines = [nb.NativeEngine(world, r) for r in range(4)]
            for e in engines:
                e.enable_arq(500, max_retries=8)
            for dst in range(1, 4):
                world.dup_next(0, dst, 8)
            engines[0].bcast(b"once")
            world.drain(100_000_000)
            for r in range(1, 4):
                got = []
                while (m := engines[r].pickup_next()) is not None:
                    got.append(m.data)
                assert got == [b"once"]
            assert sum(e.arq_dup_drops for e in engines) >= 1

    def test_native_dropped_vote_recovers(self):
        nb = self._native()
        import time
        with nb.NativeWorld(8) as world:
            engines = [nb.NativeEngine(world, r) for r in range(8)]
            for e in engines:
                e.enable_arq(500, max_retries=12)
            world.drop_next(1, 0, 1)  # rank 1 is a leaf: its vote
            rc = engines[0].submit_proposal(b"p", pid=4)
            deadline = time.monotonic() + 10.0
            while rc == -1 and time.monotonic() < deadline:
                world.progress_all()
                rc = engines[0].vote_my_proposal()
            assert rc == 1
            world.drain(100_000_000)


def test_soak_without_kill_is_lossless():
    """With faults but no rank kill, delivery is exactly-once AND
    complete: every broadcast reaches every other rank."""
    (world, engines, clock, dead, delivered, decisions, submitted,
     sent) = run_soak(seed=11, kill_at=-1)
    ws = len(engines)
    assert not dead
    for origin, data in sent:
        for r in range(ws):
            if r == origin:
                continue
            assert (origin, data) in delivered[r], \
                f"rank {r} never saw {data!r} from {origin}"
    for r in range(ws):
        assert len(delivered[r]) == len(set(delivered[r]))
    # every proposal terminated (completed or failed-by-deadline)
    for proposer, pid in submitted:
        st = engines[proposer].my_own_proposal.state
        assert st in (ReqState.COMPLETED, ReqState.FAILED)
