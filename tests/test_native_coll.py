"""C engine-substrate ring collectives (rlo_coll.c) — numerics parity
with the Python coroutine Comm (rlo_tpu/ops/collectives.py) and with
numpy oracles, driven in-process round-robin exactly like
run_collectives(). The ring replaces the O(ws^2) bcast-gather fallback
in the Native/Mpi backend facades (the multi-process legs are covered
by tests/test_mpi_transport.py and the demo bench case)."""

import numpy as np
import pytest

from rlo_tpu.native.bindings import NativeColl, NativeWorld, run_colls

WORLD_SIZES = [2, 3, 5, 8, 13]


@pytest.fixture(params=WORLD_SIZES)
def world_colls(request):
    ws = request.param
    w = NativeWorld(ws)
    colls = [NativeColl(w, r) for r in range(ws)]
    yield ws, colls
    for c in colls:
        c.close()
    w.close()


class TestRingAllreduce:
    @pytest.mark.parametrize("op,npfn", [("sum", np.add),
                                         ("min", np.minimum),
                                         ("max", np.maximum)])
    def test_matches_numpy(self, world_colls, op, npfn):
        ws, colls = world_colls
        rng = np.random.default_rng(ws)
        xs = [rng.standard_normal(37).astype(np.float32)
              for _ in range(ws)]
        outs = run_colls(colls, [
            lambda r=r: colls[r].allreduce_start(xs[r], op)
            for r in range(ws)])
        want = xs[0]
        for x in xs[1:]:
            want = npfn(want, x)
        for o in outs:
            np.testing.assert_allclose(np.asarray(o), want, rtol=1e-5)

    def test_matches_python_comm(self, world_colls):
        """Same payloads through the C ring and the Python coroutine
        ring must agree to float32 association-order tolerance."""
        from rlo_tpu.ops.collectives import Comm, run_collectives
        from rlo_tpu.transport.loopback import LoopbackWorld

        ws, colls = world_colls
        rng = np.random.default_rng(ws + 100)
        xs = [rng.standard_normal(64).astype(np.float32)
              for _ in range(ws)]
        c_outs = run_colls(colls, [
            lambda r=r: colls[r].allreduce_start(xs[r], "sum")
            for r in range(ws)])
        world = LoopbackWorld(ws)
        comms = [Comm(world.transport(r)) for r in range(ws)]
        py_outs = run_collectives(
            [c.allreduce(xs[r], algorithm="ring")
             for r, c in enumerate(comms)])
        for co, po in zip(c_outs, py_outs):
            np.testing.assert_allclose(np.asarray(co), po, rtol=1e-5)


class TestRingPieces:
    def test_reduce_scatter_chunks_reassemble(self, world_colls):
        ws, colls = world_colls
        rng = np.random.default_rng(ws + 7)
        xs = [rng.standard_normal(41).astype(np.float32)  # ragged
              for _ in range(ws)]
        outs = run_colls(colls, [
            lambda r=r: colls[r].reduce_scatter_start(xs[r], "sum")
            for r in range(ws)])
        full = np.concatenate([np.asarray(o) for o in outs])[:41]
        np.testing.assert_allclose(full, np.sum(xs, axis=0), rtol=1e-5)

    def test_all_gather(self, world_colls):
        ws, colls = world_colls
        blobs = [bytes([r]) * 5 for r in range(ws)]
        outs = run_colls(colls, [
            lambda r=r: colls[r].all_gather_start(blobs[r])
            for r in range(ws)])
        want = b"".join(blobs)
        for o in outs:
            assert o.tobytes() == want

    def test_all_to_all_transpose(self, world_colls):
        ws, colls = world_colls
        grid = [[bytes([16 * s + d, s ^ d]) for d in range(ws)]
                for s in range(ws)]
        outs = run_colls(colls, [
            lambda r=r: colls[r].all_to_all_start(grid[r])
            for r in range(ws)])
        for d in range(ws):
            want = b"".join(grid[s][d] for s in range(ws))
            assert outs[d].tobytes() == want, d

    def test_barrier_completes(self, world_colls):
        ws, colls = world_colls
        run_colls(colls, [colls[r].barrier_start for r in range(ws)])

    def test_busy_coll_rejects_second_op(self, world_colls):
        ws, colls = world_colls
        x = np.ones(4, np.float32)
        colls[0].allreduce_start(x)
        with pytest.raises(RuntimeError):
            colls[0].allreduce_start(x)
        # complete the round: rank 0 is already armed, arm the rest
        run_colls(colls, [lambda: None] + [
            lambda r=r: colls[r].allreduce_start(x)
            for r in range(1, ws)])

    def test_interleaved_with_engine_traffic(self):
        """Colls (comm 64) and progress engines (comm 0) share one
        world: the inbox demultiplexes by comm, so a broadcast storm
        running INTERLEAVED with a ring allreduce must disturb
        neither — every bcast delivers exactly once and the reduction
        is exact."""
        from rlo_tpu.native.bindings import NativeEngine

        ws = 6
        with NativeWorld(ws) as w:
            engines = [NativeEngine(w, r) for r in range(ws)]
            colls = [NativeColl(w, r) for r in range(ws)]
            try:
                xs = [np.full(16, float(r + 1), np.float32)
                      for r in range(ws)]
                outs = [colls[r].allreduce_start(xs[r])
                        for r in range(ws)]
                alive = set(range(ws))
                for burst in range(3):
                    for r in range(ws):
                        engines[r].bcast(f"b{burst}r{r}".encode())
                    for r in list(alive):  # advance colls mid-storm
                        if colls[r].poll() == 1:
                            alive.discard(r)
                for _ in range(100_000):
                    for r in list(alive):
                        if colls[r].poll() == 1:
                            alive.discard(r)
                    w.progress_all()
                    if not alive:
                        break
                assert not alive, "collective starved by engine traffic"
                w.drain()
                want = sum(range(1, ws + 1))
                for o in outs:
                    np.testing.assert_allclose(np.asarray(o), want)
                for r, e in enumerate(engines):
                    got = sorted(m.data
                                 for m in iter(e.pickup_next, None))
                    expect = sorted(f"b{b}r{s}".encode()
                                    for b in range(3)
                                    for s in range(ws) if s != r)
                    assert got == expect, (r, got)
            finally:
                for c in colls:
                    c.close()

    def test_sequential_ops_reuse_coll(self, world_colls):
        """Back-to-back collectives on the same coll objects (fresh
        opids per phase) must not cross-match."""
        ws, colls = world_colls
        for k in range(3):
            xs = [np.full(8, float(r + 1 + k), np.float32)
                  for r in range(ws)]
            outs = run_colls(colls, [
                lambda r=r: colls[r].allreduce_start(xs[r])
                for r in range(ws)])
            want = sum(range(1 + k, ws + 1 + k))
            for o in outs:
                np.testing.assert_allclose(np.asarray(o), want)


def test_full_world_ring_beyond_64_ranks():
    """Full-world contexts must work at ANY world size — the subset
    member map is a fixed 64-entry table, so the full-world endpoint
    path must stay pure arithmetic (round-3 review regression: a
    100-rank ring read past the table and hung)."""
    from rlo_tpu.native.bindings import NativeColl, NativeWorld, run_colls

    ws = 100
    with NativeWorld(ws) as world:
        colls = [NativeColl(world, r, comm=70) for r in range(ws)]
        try:
            xs = [np.full(4, 1.0, np.float32) for _ in range(ws)]
            outs = run_colls(colls, [
                lambda r=r: colls[r].allreduce_start(xs[r])
                for r in range(ws)])
            for o in outs:
                np.testing.assert_allclose(np.asarray(o), float(ws))
        finally:
            for c in colls:
                c.close()
