"""Ulysses all-to-all sequence parallelism vs full attention and ring
attention — the second long-context strategy on the same substrate.

Oracles: head-scatter attention equals unsharded softmax attention
(causal and bidirectional) and the ring variant on identical inputs; the
transformer trains with sp_attention='ulysses' matching the
single-device step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from rlo_tpu.models.transformer import (TransformerConfig, init_params,
                                        loss_fn, train_step)
from rlo_tpu.ops.ring_attention import full_attention, ring_attention
from rlo_tpu.ops.ulysses import ulysses_attention
from rlo_tpu.parallel.mesh import make_mesh, shard_jit

WS = 8


def make_qkv(seed, seq, heads, dim, dtype=jnp.float32):
    rng = np.random.default_rng(seed)

    def one():
        return jnp.asarray(
            rng.standard_normal((seq, heads, dim)) * 0.5, dtype)
    return one(), one(), one()


def run_sharded(fn, q, k, v, ws=WS, check_vma=True):
    mesh = make_mesh((ws,), ("sp",))
    f = shard_jit(fn, mesh, (P("sp"), P("sp"), P("sp")), P("sp"),
                  check_vma=check_vma)
    return np.asarray(f(q, k, v))


class TestFlashLocalAttention:
    """The Ulysses quadratic part through the fused flash kernel
    (interpret mode; check_vma off — the pallas interpreter does not
    thread vma types, same caveat as the ring-attention tests)."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_matches_full(self, causal):
        from rlo_tpu.ops.ring_attention import full_attention
        from rlo_tpu.ops.ulysses import ulysses_attention
        q, k, v = make_qkv(11, 64, 8, 16)
        want = np.asarray(full_attention(q, k, v, causal=causal))
        got = run_sharded(
            lambda a, b, c: ulysses_attention(
                a, b, c, "sp", causal=causal, use_pallas=True,
                block_q=8),
            q, k, v, check_vma=False)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


class TestParity:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("heads,dim", [(8, 16), (16, 8), (32, 4)])
    def test_matches_full_attention(self, causal, heads, dim):
        q, k, v = make_qkv(0, 64, heads, dim)
        want = np.asarray(full_attention(q, k, v, causal=causal))
        got = run_sharded(
            lambda q_, k_, v_: ulysses_attention(q_, k_, v_, "sp",
                                                 causal=causal), q, k, v)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize("algorithm", ["xla", "ring"])
    def test_matches_ring_attention(self, algorithm):
        q, k, v = make_qkv(1, 64, 8, 16)
        ring = run_sharded(
            lambda q_, k_, v_: ring_attention(q_, k_, v_, "sp",
                                              causal=True), q, k, v)
        uly = run_sharded(
            lambda q_, k_, v_: ulysses_attention(
                q_, k_, v_, "sp", causal=True, algorithm=algorithm),
            q, k, v)
        np.testing.assert_allclose(uly, ring, rtol=2e-4, atol=2e-5)

    def test_heads_must_divide(self):
        q, k, v = make_qkv(2, 64, 4, 8)  # 4 heads < 8 shards
        with pytest.raises(ValueError, match="divide the head"):
            run_sharded(lambda q_, k_, v_: ulysses_attention(
                q_, k_, v_, "sp"), q, k, v)


class TestTransformerIntegration:
    CFG = TransformerConfig(vocab=32, d_model=64, n_heads=8, n_layers=2,
                            d_ff=64, dtype="float32",
                            sp_attention="ulysses")

    def test_loss_parity_with_single_device(self):
        params = init_params(jax.random.PRNGKey(0), self.CFG)
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(0, 32, (2, 32)), jnp.int32)
        want = float(loss_fn(params, tokens, self.CFG))
        mesh = make_mesh((WS,), ("sp",))
        f = shard_jit(
            lambda p, t: loss_fn(p, t, self.CFG, sp_axis="sp"),
            mesh, (P(), P(None, "sp")), P())
        got = float(f(params, tokens))
        assert abs(got - want) < 2e-4, (got, want)

    def test_composes_with_tensor_parallel(self):
        """ulysses + tp + dp on one mesh: tp splits heads first, then
        ulysses scatters the LOCAL heads over sp — the step must match
        single-device exactly."""
        from rlo_tpu.models.transformer import param_pspecs
        cfg = TransformerConfig(vocab=32, d_model=64, n_heads=8,
                                n_layers=1, d_ff=64, dtype="float32",
                                sp_attention="ulysses")
        params = init_params(jax.random.PRNGKey(2), cfg)
        rng = np.random.default_rng(2)
        tokens = jnp.asarray(rng.integers(0, 32, (4, 32)), jnp.int32)
        ref_p, ref_loss = jax.jit(
            lambda p, t: train_step(p, t, cfg, lr=0.05))(params, tokens)
        mesh = make_mesh((2, 2, 2), ("dp", "sp", "tp"))
        specs = param_pspecs(cfg, "tp")
        step = shard_jit(
            lambda p, t: train_step(p, t, cfg, lr=0.05, sp_axis="sp",
                                    dp_axis="dp", tp_axis="tp"),
            mesh, (specs, P("dp", "sp")), (specs, P()))
        new_p, loss = step(params, tokens)
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=1e-5)
        for (k, a), (_, b) in zip(
                jax.tree_util.tree_flatten_with_path(new_p)[0],
                jax.tree_util.tree_flatten_with_path(ref_p)[0]):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-4,
                err_msg=jax.tree_util.keystr(k))

    def test_train_step_parity(self):
        params = init_params(jax.random.PRNGKey(1), self.CFG)
        rng = np.random.default_rng(1)
        tokens = jnp.asarray(rng.integers(0, 32, (4, 32)), jnp.int32)
        ref_p, ref_loss = jax.jit(
            lambda p, t: train_step(p, t, self.CFG, lr=0.05))(params,
                                                              tokens)
        mesh = make_mesh((2, 4), ("dp", "sp"))
        step = shard_jit(
            lambda p, t: train_step(p, t, self.CFG, lr=0.05,
                                    sp_axis="sp", dp_axis="dp"),
            mesh, (P(), P("dp", "sp")), (P(), P()))
        new_p, loss = step(params, tokens)
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=1e-5)
        for (ka, a), (_, b) in zip(
                jax.tree_util.tree_flatten_with_path(new_p)[0],
                jax.tree_util.tree_flatten_with_path(ref_p)[0]):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5,
                err_msg=jax.tree_util.keystr(ka))
