"""KV-cache generation vs the training forward (models.generate).

Oracles: (a) decode-step logits equal the training `forward`'s logits
at every position (the cached path must be the same math, O(1) per
token); (b) greedy generation equals the naive recompute-everything
loop token for token; (c) the whole generate is jittable with static
shapes; (d) sampling respects the rng/temperature contract.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rlo_tpu.models.generate import (decode_step, generate,
                                     init_kv_cache, prefill)
from rlo_tpu.models.transformer import (TransformerConfig, forward,
                                        init_params)

CFG = TransformerConfig(vocab=97, d_model=32, n_heads=4, n_layers=2,
                        d_ff=64, dtype="float32")


@pytest.fixture(scope="module")
def setup():
    params = init_params(jax.random.PRNGKey(0), CFG)
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, CFG.vocab, (2, 8)), jnp.int32)
    return params, prompt


def test_decode_logits_match_forward(setup):
    """Every prefix position: cached decode logits == forward logits
    of the same prefix's last position."""
    params, prompt = setup
    b, plen = prompt.shape
    cache = init_kv_cache(CFG, b, plen)
    for pos in range(plen):
        logits, cache = decode_step(params, prompt[:, pos], pos, cache,
                                    CFG)
        want = np.asarray(forward(params, prompt[:, :pos + 1], CFG)
                          )[:, -1, :]
        np.testing.assert_allclose(np.asarray(logits), want,
                                   rtol=2e-4, atol=2e-4)


def test_greedy_matches_naive_loop(setup):
    """Greedy cache generation == recomputing the full forward for
    every new token (the O(n^2) oracle)."""
    params, prompt = setup
    max_new = 12
    got = np.asarray(generate(params, prompt, CFG, max_new=max_new))
    seq = np.asarray(prompt)
    for _ in range(max_new):
        logits = np.asarray(forward(params, jnp.asarray(seq), CFG)
                            )[:, -1, :]
        nxt = logits.argmax(-1).astype(np.int32)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, seq[:, prompt.shape[1]:])


def test_generate_is_jittable(setup):
    params, prompt = setup
    f = jax.jit(lambda p, t: generate(p, t, CFG, max_new=6))
    a = np.asarray(f(params, prompt))
    b = np.asarray(generate(params, prompt, CFG, max_new=6))
    np.testing.assert_array_equal(a, b)


def test_prefill_matches_forward_last(setup):
    params, prompt = setup
    cache = init_kv_cache(CFG, prompt.shape[0], prompt.shape[1])
    logits, _ = prefill(params, prompt, cache, CFG)
    want = np.asarray(forward(params, prompt, CFG))[:, -1, :]
    np.testing.assert_allclose(np.asarray(logits), want, rtol=2e-4,
                               atol=2e-4)


def test_sampling_contract(setup):
    params, prompt = setup
    with pytest.raises(ValueError, match="needs rng"):
        generate(params, prompt, CFG, max_new=2, temperature=0.7)
    out = generate(params, prompt, CFG, max_new=4, temperature=0.7,
                   rng=jax.random.PRNGKey(1))
    assert out.shape == (2, 4)
    # temperature ~0+ converges to greedy
    cold = generate(params, prompt, CFG, max_new=4, temperature=1e-4,
                    rng=jax.random.PRNGKey(1))
    greedy = generate(params, prompt, CFG, max_new=4)
    np.testing.assert_array_equal(np.asarray(cold), np.asarray(greedy))


def test_moe_rejected(setup):
    import dataclasses
    cfg = dataclasses.replace(CFG, n_experts=2)
    with pytest.raises(NotImplementedError):
        init_kv_cache(cfg, 1, 8)
