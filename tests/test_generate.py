"""KV-cache generation vs the training forward (models.generate).

Oracles: (a) decode-step logits equal the training `forward`'s logits
at every position (the cached path must be the same math, O(1) per
token); (b) greedy generation equals the naive recompute-everything
loop token for token; (c) the whole generate is jittable with static
shapes; (d) sampling respects the rng/temperature contract.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rlo_tpu.models.generate import (decode_step, generate,
                                     init_kv_cache, prefill)
from rlo_tpu.models.transformer import (TransformerConfig, forward,
                                        init_params)

CFG = TransformerConfig(vocab=97, d_model=32, n_heads=4, n_layers=2,
                        d_ff=64, dtype="float32")


@pytest.fixture(scope="module")
def setup():
    params = init_params(jax.random.PRNGKey(0), CFG)
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, CFG.vocab, (2, 8)), jnp.int32)
    return params, prompt


def test_decode_logits_match_forward(setup):
    """Every prefix position: cached decode logits == forward logits
    of the same prefix's last position."""
    params, prompt = setup
    b, plen = prompt.shape
    cache = init_kv_cache(CFG, b, plen)
    for pos in range(plen):
        logits, cache = decode_step(params, prompt[:, pos], pos, cache,
                                    CFG)
        want = np.asarray(forward(params, prompt[:, :pos + 1], CFG)
                          )[:, -1, :]
        np.testing.assert_allclose(np.asarray(logits), want,
                                   rtol=2e-4, atol=2e-4)


def test_greedy_matches_naive_loop(setup):
    """Greedy cache generation == recomputing the full forward for
    every new token (the O(n^2) oracle)."""
    params, prompt = setup
    max_new = 12
    got = np.asarray(generate(params, prompt, CFG, max_new=max_new))
    seq = np.asarray(prompt)
    for _ in range(max_new):
        logits = np.asarray(forward(params, jnp.asarray(seq), CFG)
                            )[:, -1, :]
        nxt = logits.argmax(-1).astype(np.int32)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, seq[:, prompt.shape[1]:])


def test_generate_is_jittable(setup):
    params, prompt = setup
    f = jax.jit(lambda p, t: generate(p, t, CFG, max_new=6))
    a = np.asarray(f(params, prompt))
    b = np.asarray(generate(params, prompt, CFG, max_new=6))
    np.testing.assert_array_equal(a, b)


def test_prefill_matches_forward_last(setup):
    params, prompt = setup
    cache = init_kv_cache(CFG, prompt.shape[0], prompt.shape[1])
    logits, _ = prefill(params, prompt, cache, CFG)
    want = np.asarray(forward(params, prompt, CFG))[:, -1, :]
    np.testing.assert_allclose(np.asarray(logits), want, rtol=2e-4,
                               atol=2e-4)


def test_sampling_contract(setup):
    params, prompt = setup
    with pytest.raises(ValueError, match="needs rng"):
        generate(params, prompt, CFG, max_new=2, temperature=0.7)
    out = generate(params, prompt, CFG, max_new=4, temperature=0.7,
                   rng=jax.random.PRNGKey(1))
    assert out.shape == (2, 4)
    # temperature ~0+ converges to greedy
    cold = generate(params, prompt, CFG, max_new=4, temperature=1e-4,
                    rng=jax.random.PRNGKey(1))
    greedy = generate(params, prompt, CFG, max_new=4)
    np.testing.assert_array_equal(np.asarray(cold), np.asarray(greedy))


def test_moe_greedy_decode_matches_oracle():
    """MoE decode (round-4 VERDICT item 7): greedy cache generation ==
    the O(n^2) recompute oracle. capacity_factor >= n_experts makes
    BOTH paths drop-free, where decode's drop-free routing and the
    training forward's capacity routing coincide exactly (capacity
    dropping is order-dependent across the token axis, hence not
    causal — see generate._decode_cfg)."""
    import dataclasses

    cfg = dataclasses.replace(CFG, n_experts=2, capacity_factor=2.0)
    params = init_params(jax.random.PRNGKey(7), cfg)
    rng = np.random.default_rng(8)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab, (2, 6)), jnp.int32)
    max_new = 8
    got = np.asarray(generate(params, prompt, cfg, max_new=max_new))
    seq = np.asarray(prompt)
    for _ in range(max_new):
        logits = np.asarray(forward(params, jnp.asarray(seq), cfg)
                            )[:, -1, :]
        nxt = logits.argmax(-1).astype(np.int32)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, seq[:, prompt.shape[1]:])


def test_tp_sharded_generate_matches_single_device():
    """Tensor-parallel decode (round-4 VERDICT item 7): the whole
    generate loop under shard_map on a tp mesh — sharded params
    (param_pspecs), per-shard compact KV cache (kv_heads/tp local
    heads) — produces the same greedy tokens as single-device."""
    import dataclasses

    from jax.sharding import PartitionSpec as P

    from rlo_tpu.models.transformer import param_pspecs
    from rlo_tpu.parallel.mesh import make_mesh, shard_jit

    cfg = dataclasses.replace(CFG, n_kv_heads=2)  # GQA + tp
    mesh = make_mesh((2,), ("tp",))
    params = init_params(jax.random.PRNGKey(9), cfg)
    rng = np.random.default_rng(10)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab, (2, 5)), jnp.int32)
    specs = param_pspecs(cfg, "tp")
    f = shard_jit(
        lambda p, t: generate(p, t, cfg, max_new=7, tp_axis="tp"),
        mesh, (specs, P()), P())
    got = np.asarray(f(params, prompt))
    want = np.asarray(generate(params, prompt, cfg, max_new=7))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("variant", ["dense", "gqa_rope", "moe"])
def test_ragged_prompts_match_per_row_dense(variant):
    """Ragged generation (round 4, the serving shape): one batch with
    per-row prompt lengths must produce, row for row, exactly what a
    dense generate of that row's truncated prompt produces — per-row
    positions, cache slots, and masks all the way through (including
    per-row rotary angles on rope configs, and drop-free MoE routing
    so padding cannot consume expert capacity; parity condition
    capacity_factor >= n_experts as for MoE decode)."""
    import dataclasses

    cfg = CFG
    if variant == "gqa_rope":
        cfg = dataclasses.replace(cfg, n_kv_heads=2,
                                  pos_encoding="rope")
    elif variant == "moe":
        cfg = dataclasses.replace(cfg, n_experts=2,
                                  capacity_factor=2.0)
    params = init_params(jax.random.PRNGKey(21), cfg)
    rng = np.random.default_rng(22)
    lengths = [3, 7, 5, 1]
    plen = max(lengths)
    prompt = np.zeros((len(lengths), plen), np.int32)
    for i, L in enumerate(lengths):
        prompt[i, :L] = rng.integers(0, cfg.vocab, L)
    max_new = 6
    got = np.asarray(generate(
        params, jnp.asarray(prompt), cfg, max_new=max_new,
        max_len=plen + max_new,
        prompt_lengths=jnp.asarray(lengths, jnp.int32)))
    for i, L in enumerate(lengths):
        want = np.asarray(generate(
            params, jnp.asarray(prompt[i:i + 1, :L]), cfg,
            max_new=max_new))
        np.testing.assert_array_equal(got[i], want[0], err_msg=f"row {i}")


def test_ragged_is_jittable():
    params = init_params(jax.random.PRNGKey(23), CFG)
    prompt = jnp.zeros((2, 5), jnp.int32)
    lengths = jnp.asarray([2, 5], jnp.int32)
    f = jax.jit(lambda p, t, ln: generate(p, t, CFG, max_new=4,
                                          max_len=9,
                                          prompt_lengths=ln))
    a = np.asarray(f(params, prompt, lengths))
    b = np.asarray(generate(params, prompt, CFG, max_new=4, max_len=9,
                            prompt_lengths=lengths))
    np.testing.assert_array_equal(a, b)


def test_ep_sharded_moe_decode_matches_single_device():
    """Expert-parallel decode (round-4 VERDICT item 7): generate with
    ep_axis on an expert-sharded mesh — per-shard batch rows, expert
    weights sharded per param_pspecs, tokens crossing shards through
    the all_to_all dispatch — equals single-device greedy decode."""
    import dataclasses

    from jax.sharding import PartitionSpec as P

    from rlo_tpu.models.transformer import param_pspecs
    from rlo_tpu.parallel.mesh import make_mesh, shard_jit

    cfg = dataclasses.replace(CFG, n_experts=2, capacity_factor=2.0)
    params = init_params(jax.random.PRNGKey(13), cfg)
    rng = np.random.default_rng(14)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab, (4, 6)), jnp.int32)
    mesh = make_mesh((2,), ("ep",))
    specs = param_pspecs(cfg, ep_axis="ep")
    gen = shard_jit(
        lambda p, t: generate(p, t, cfg, max_new=6, ep_axis="ep"),
        mesh, (specs, P("ep")), P("ep"))
    got = np.asarray(gen(params, prompt))
    want = np.asarray(generate(params, prompt, cfg, max_new=6))
    np.testing.assert_array_equal(got, want)


def test_tp_decode_step_logits_parity():
    """One tp-sharded decode_step with an explicitly sharded cache
    (kv_cache_pspecs) matches the single-device logits."""
    import dataclasses

    from jax.sharding import PartitionSpec as P

    from rlo_tpu.models.generate import kv_cache_pspecs
    from rlo_tpu.models.transformer import param_pspecs
    from rlo_tpu.parallel.mesh import make_mesh, shard_jit

    cfg = dataclasses.replace(CFG, n_kv_heads=2)
    mesh = make_mesh((2,), ("tp",))
    params = init_params(jax.random.PRNGKey(11), cfg)
    rng = np.random.default_rng(12)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab, (2, 4)), jnp.int32)
    cspecs = kv_cache_pspecs(cfg, "tp")
    pspecs = param_pspecs(cfg, "tp")
    f = shard_jit(
        lambda p, t: prefill(p, t, init_kv_cache(cfg, 2, 6,
                                                 tp_axis="tp"),
                             cfg, tp_axis="tp"),
        mesh, (pspecs, P()), (P(), cspecs))
    logits_tp, cache_tp = f(params, prompt)
    cache0 = init_kv_cache(cfg, 2, 6)
    logits_one, cache_one = prefill(params, prompt, cache0, cfg)
    np.testing.assert_allclose(np.asarray(logits_tp),
                               np.asarray(logits_one),
                               rtol=2e-4, atol=2e-4)
    # the reassembled sharded cache equals the single-device cache
    for la, lb in zip(cache_tp, cache_one):
        for key in ("k", "v"):
            np.testing.assert_allclose(np.asarray(la[key]),
                                       np.asarray(lb[key]),
                                       rtol=2e-4, atol=2e-4)

    step = shard_jit(
        lambda p, t, c: decode_step(p, t, 4, c, cfg, tp_axis="tp"),
        mesh, (pspecs, P(), cspecs), (P(), cspecs))
    tok = jnp.asarray(np.argmax(np.asarray(logits_one), -1), jnp.int32)
    logits2_tp, _ = step(params, tok, cache_tp)
    logits2_one, _ = decode_step(params, tok, 4, cache_one, cfg)
    np.testing.assert_allclose(np.asarray(logits2_tp),
                               np.asarray(logits2_one),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("variant", ["dense", "gqa", "rope", "gqa_rope"])
def test_prefill_matches_scan(variant):
    """The one-forward-pass prefill must equal the token-at-a-time
    scan oracle exactly: last-position logits AND every cache entry
    (the subsequent decode reads the cache, so cache parity is the
    stronger contract). Covers GQA (compact cached K/V) and rope
    (keys cached rotated)."""
    import dataclasses

    from rlo_tpu.models.generate import prefill_scan

    cfg = CFG
    if "gqa" in variant:
        cfg = dataclasses.replace(cfg, n_kv_heads=2)
    if "rope" in variant:
        cfg = dataclasses.replace(cfg, pos_encoding="rope")
    params = init_params(jax.random.PRNGKey(3), cfg)
    rng = np.random.default_rng(4)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab, (2, 8)), jnp.int32)
    max_len = 12  # cache longer than the prompt: the tail must stay 0
    cache0 = init_kv_cache(cfg, 2, max_len)
    logits_a, cache_a = prefill(params, prompt, cache0, cfg)
    logits_b, cache_b = prefill_scan(params, prompt, cache0, cfg)
    np.testing.assert_allclose(np.asarray(logits_a),
                               np.asarray(logits_b),
                               rtol=2e-4, atol=2e-4)
    for la, lb in zip(cache_a, cache_b):
        for key in ("k", "v"):
            np.testing.assert_allclose(np.asarray(la[key]),
                                       np.asarray(lb[key]),
                                       rtol=2e-4, atol=2e-4)


def test_generate_with_long_cache_uses_blockwise_prefill(setup):
    """generate() end-to-end with max_len > plen + max_new still
    matches the O(n^2) oracle (the blockwise prefill writes only the
    prompt positions; decode masks beyond pos)."""
    params, prompt = setup
    got = np.asarray(generate(params, prompt, CFG, max_new=5,
                              max_len=32))
    seq = np.asarray(prompt)
    for _ in range(5):
        logits = np.asarray(forward(params, jnp.asarray(seq), CFG)
                            )[:, -1, :]
        nxt = logits.argmax(-1).astype(np.int32)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, seq[:, prompt.shape[1]:])


class TestQuantizedKVCache:
    """cfg.kv_cache_dtype='int8': half the cache bytes; per-(position,
    head) symmetric quantization with the dequant folded into the
    attend (models.generate._attend_cache)."""

    def _cfg(self, **kw):
        import dataclasses
        return dataclasses.replace(CFG, kv_cache_dtype="int8", **kw)

    def test_cache_layout_and_bytes(self):
        cfg = self._cfg()
        cache = init_kv_cache(cfg, 2, 16)
        exact = init_kv_cache(CFG, 2, 16)
        for lc in cache:
            assert lc["k"].dtype == jnp.int8 and lc["v"].dtype == jnp.int8
            assert lc["ks"].shape == (2, CFG.n_heads, 16)
        q_bytes = sum(sum(a.nbytes for a in lc.values()) for lc in cache)
        e_bytes = sum(sum(a.nbytes for a in lc.values()) for lc in exact)
        # vs the f32 exact cache: (hd + 4)/(4*hd) — 0.375 at this toy
        # head_dim of 8, ~0.27 at a real head_dim of 64+
        hd = CFG.head_dim
        assert q_bytes <= ((hd + 4) / (4 * hd) + 0.01) * e_bytes

    def test_roundtrip_error_bound(self):
        from rlo_tpu.models.generate import _quantize_kv
        x = jnp.asarray(np.random.default_rng(0).standard_normal(
            (2, 8, 4, 16)), jnp.float32)
        q, s = _quantize_kv(x)
        err = np.abs(np.asarray(q, np.float32) * np.asarray(s)[..., None]
                     - np.asarray(x))
        assert err.max() <= float(np.asarray(s).max()) * 0.5 + 1e-7

    def test_decode_logits_close_to_exact(self, setup):
        """Quantized decode vs exact decode: logits within the
        quantization error envelope at every position."""
        params, prompt = setup
        cfg = self._cfg()
        b, plen = prompt.shape
        cache_q = init_kv_cache(cfg, b, plen)
        cache_e = init_kv_cache(CFG, b, plen)
        for pos in range(plen):
            lq, cache_q = decode_step(params, prompt[:, pos], pos,
                                      cache_q, cfg)
            le, cache_e = decode_step(params, prompt[:, pos], pos,
                                      cache_e, CFG)
            scale = np.abs(np.asarray(le)).max() + 1.0
            np.testing.assert_allclose(np.asarray(lq), np.asarray(le),
                                       atol=0.05 * scale)

    def test_generate_runs_and_is_jittable(self, setup):
        params, prompt = setup
        cfg = self._cfg()
        f = jax.jit(lambda p, t: generate(p, t, cfg, max_new=6))
        toks = np.asarray(f(params, prompt))
        assert toks.shape == (2, 6)
        assert (toks >= 0).all() and (toks < cfg.vocab).all()
        # greedy tokens usually survive 8-bit cache error at this size
        exact = np.asarray(generate(params, prompt, CFG, max_new=6))
        assert (toks == exact).mean() >= 0.5

    @pytest.mark.parametrize("variant", ["dense", "gqa_rope"])
    def test_ragged_matches_per_row_dense_exactly(self, variant):
        """Ragged and dense generate quantize the same K/V values at
        the same points, so per-row parity is EXACT inside the
        quantized world — the same oracle as the unquantized path."""
        import dataclasses
        cfg = self._cfg()
        if variant == "gqa_rope":
            cfg = dataclasses.replace(cfg, n_kv_heads=2,
                                      pos_encoding="rope")
        params = init_params(jax.random.PRNGKey(31), cfg)
        rng = np.random.default_rng(32)
        lengths = [3, 6, 2]
        plen = max(lengths)
        prompt = np.zeros((len(lengths), plen), np.int32)
        for i, L in enumerate(lengths):
            prompt[i, :L] = rng.integers(0, cfg.vocab, L)
        max_new = 5
        got = np.asarray(generate(
            params, jnp.asarray(prompt), cfg, max_new=max_new,
            max_len=plen + max_new,
            prompt_lengths=jnp.asarray(lengths, jnp.int32)))
        for i, L in enumerate(lengths):
            want = np.asarray(generate(
                params, jnp.asarray(prompt[i:i + 1, :L]), cfg,
                max_new=max_new))
            np.testing.assert_array_equal(got[i], want[0],
                                          err_msg=f"row {i}")

    def test_prefill_matches_scan_within_association_error(self):
        """Blockwise prefill attends the DEQUANTIZED block (the values
        decode reads back), so prefill and the decode-step scan agree
        to matmul-association error — NOT the (much larger)
        quantization envelope that an unquantized-attend prefill
        would diverge by."""
        from rlo_tpu.models.generate import prefill_scan
        cfg = self._cfg()
        params = init_params(jax.random.PRNGKey(35), cfg)
        rng = np.random.default_rng(36)
        prompt = jnp.asarray(rng.integers(0, cfg.vocab, (2, 8)),
                             jnp.int32)
        cache0 = init_kv_cache(cfg, 2, 12)
        la, ca = prefill(params, prompt, cache0, cfg)
        lb, cb = prefill_scan(params, prompt, cache0, cfg)
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=2e-3, atol=2e-3)
        # layer 0 sees identical inputs -> identical quantized
        # entries; deeper layers' inputs differ by the association
        # error of the layer below, which can flip a near-tie
        # round() by one step — allow exactly that
        np.testing.assert_array_equal(np.asarray(ca[0]["k"]),
                                      np.asarray(cb[0]["k"]))
        np.testing.assert_allclose(np.asarray(ca[0]["ks"]),
                                   np.asarray(cb[0]["ks"]), rtol=1e-6)
        for xa, xb in zip(ca[1:], cb[1:]):
            diff = np.abs(np.asarray(xa["k"], np.int32)
                          - np.asarray(xb["k"], np.int32))
            assert diff.max() <= 1

    def test_tp_sharded_matches_single_device_exactly(self):
        """tp shards whole K/V heads and quantization is per-head, so
        sharded quantized decode equals single-device quantized decode
        bit for bit."""
        import dataclasses

        from jax.sharding import PartitionSpec as P

        from rlo_tpu.models.transformer import param_pspecs
        from rlo_tpu.parallel.mesh import make_mesh, shard_jit

        cfg = dataclasses.replace(CFG, kv_cache_dtype="int8",
                                  n_kv_heads=2)
        mesh = make_mesh((2,), ("tp",))
        params = init_params(jax.random.PRNGKey(33), cfg)
        rng = np.random.default_rng(34)
        prompt = jnp.asarray(rng.integers(0, cfg.vocab, (2, 5)),
                             jnp.int32)
        specs = param_pspecs(cfg, "tp")
        f = shard_jit(
            lambda p, t: generate(p, t, cfg, max_new=6, tp_axis="tp"),
            mesh, (specs, P()), P())
        got = np.asarray(f(params, prompt))
        want = np.asarray(generate(params, prompt, cfg, max_new=6))
        np.testing.assert_array_equal(got, want)
