"""Traffic laboratory (rlo_tpu/workloads + the calendar-queue
scheduler, docs/DESIGN.md §14).

Four contracts:

  1. **Generator determinism** — every canned trace kind is a pure
     function of (seed, config): same seed => same digest, different
     seed => different digest; the serve_bench compatibility shim
     still reproduces the committed BENCH_serve.json traces.
  2. **JSONL round-trip** — dumps/loads preserves the digest; a
     torn-tail (truncated) file loads its surviving prefix loudly
     instead of raising; garbage headers and newer schemas refuse.
  3. **Calendar-queue oracle equivalence** — the slotted scheduler
     pops in BYTE-IDENTICAL order to the heapq oracle for any push
     sequence, randomized timestamp ties and overflow-window items
     included; a full-mode SimWorld run digests identically under
     both schedulers.
  4. **Weather profiles** — samplers draw only from the passed rng
     (replayable), burst loss is actually correlated, churn scripts
     respect their invariants, and the fabric_churn scenario kind
     (check.sh fuzz sweep) runs its properties clean.
"""

import json
from random import Random

import pytest

from rlo_tpu.transport.sim import (ALL_SCENARIO_KINDS, CalendarScheduler,
                                   FABRIC_SCENARIO_KINDS, HeapScheduler,
                                   Scenario, SimViolation, SimWorld,
                                   make_scenario)
from rlo_tpu.workloads import (TRACE_KINDS, GilbertLoss, HeavyTailDelay,
                               Trace, TraceError, churn_script,
                               make_trace, make_weather)

import logging

logging.getLogger("rlo_tpu").setLevel(logging.ERROR)


# ---------------------------------------------------------------------------
# 1. generator determinism
# ---------------------------------------------------------------------------

class TestGenerators:
    @pytest.mark.parametrize("kind", TRACE_KINDS)
    def test_same_seed_same_digest(self, kind):
        a = make_trace(kind, 7)
        b = make_trace(kind, 7)
        assert a.digest() == b.digest()
        assert [r.row() for r in a.requests] == \
            [r.row() for r in b.requests]
        assert len(a.requests) > 0

    @pytest.mark.parametrize("kind", TRACE_KINDS)
    def test_different_seed_different_digest(self, kind):
        assert make_trace(kind, 0).digest() != \
            make_trace(kind, 1).digest()

    def test_config_overrides_change_digest(self):
        assert make_trace("swarm", 0).digest() != \
            make_trace("swarm", 0, zipf_alpha=2.0).digest()

    def test_times_sorted_and_bounded(self):
        for kind in TRACE_KINDS:
            tr = make_trace(kind, 3, horizon=50.0)
            ts = [r.t for r in tr.requests]
            assert ts == sorted(ts)
            assert all(0.0 <= t < 50.0 for t in ts)

    def test_swarm_prefixes_actually_shared(self):
        tr = make_trace("swarm", 0)
        by_tenant = {}
        for r in tr.requests:
            by_tenant.setdefault(r.tenant, []).append(r.prompt)
        shared = 0
        for prompts in by_tenant.values():
            if len(prompts) < 2:
                continue
            plen = min(len(p) for p in prompts)
            k = 0
            while k < plen and len({p[k] for p in prompts}) == 1:
                k += 1
            shared = max(shared, k)
        assert shared >= 8  # at least one full shared system prefix

    def test_unknown_kind_raises(self):
        with pytest.raises(TraceError):
            make_trace("tsunami", 0)

    def test_poisson_compat_reproduces_committed_legs(self):
        """The shim digests must match the pins serve_bench asserts
        in-bench — double-entry bookkeeping for the committed
        BENCH_serve.json traffic."""
        from rlo_tpu.workloads.traces import compat_digest, \
            poisson_compat
        dense = compat_digest(*poisson_compat(
            128, n_req=8, rate=1.5, seed=0, max_len=64, buckets=(16,)))
        prefix = compat_digest(*poisson_compat(
            128, n_req=8, rate=1.5, seed=1, max_len=64, buckets=(16,),
            prefix_len=8))
        assert dense == ("2e170cbc3e3069f4f24598ed9b4e250b"
                         "70ec6245e1346814b928f82e3b36cb6a")
        assert prefix == ("b7018e756d78af9db7232d1b353eba48"
                          "0224d7aabb0e32ab668b777bdd325214")


# ---------------------------------------------------------------------------
# 2. JSONL round-trip + truncation tolerance
# ---------------------------------------------------------------------------

class TestJsonl:
    def test_round_trip_preserves_digest(self, tmp_path):
        tr = make_trace("mmpp", 5)
        p = tmp_path / "t.jsonl"
        tr.dump_jsonl(p)
        back = Trace.load_jsonl(p)
        assert back.digest() == tr.digest()
        assert back.truncated == 0
        assert back.config == tr.config

    def test_truncated_file_keeps_prefix(self, tmp_path):
        tr = make_trace("diurnal", 2)
        text = tr.dumps()
        p = tmp_path / "torn.jsonl"
        p.write_text(text[:int(len(text) * 0.6)])  # torn mid-line
        back = Trace.load_jsonl(p)
        assert 0 < len(back.requests) < len(tr.requests)
        assert back.truncated > 0
        # the surviving prefix is the exact original prefix
        assert [r.row() for r in back.requests] == \
            [r.row() for r in tr.requests[:len(back.requests)]]

    def test_header_shortfall_counts_truncated(self, tmp_path):
        tr = make_trace("flash", 1)
        lines = tr.dumps().splitlines()
        p = tmp_path / "short.jsonl"
        p.write_text("\n".join(lines[:len(lines) // 2]) + "\n")
        back = Trace.load_jsonl(p)
        assert back.truncated == len(tr.requests) - len(back.requests)

    def test_bad_header_raises(self, tmp_path):
        p = tmp_path / "bad.jsonl"
        p.write_text("not json\n")
        with pytest.raises(TraceError):
            Trace.load_jsonl(p)
        p.write_text("")
        with pytest.raises(TraceError):
            Trace.load_jsonl(p)

    def test_newer_schema_refused(self):
        head = json.dumps({"schema": 99, "kind": "x", "seed": 0,
                           "n": 0, "config": {}})
        with pytest.raises(TraceError):
            Trace.loads(head + "\n")


# ---------------------------------------------------------------------------
# 3. calendar queue == heapq oracle
# ---------------------------------------------------------------------------

def _drain_equal(pushes, interleave_pops=0, width=0.01, nslots=16):
    """Feed the same (t, ctr) stream to both schedulers — optionally
    popping mid-stream — and assert identical pop sequences."""
    heap, cal = HeapScheduler(), CalendarScheduler(width, nslots)
    out_h, out_c = [], []
    for i, item in enumerate(pushes):
        heap.push(item)
        cal.push(item)
        if interleave_pops and i % interleave_pops == 0 and len(heap):
            out_h.append(heap.pop())
            out_c.append(cal.pop())
    while len(heap):
        out_h.append(heap.pop())
        out_c.append(cal.pop())
    assert len(cal) == 0
    assert out_h == out_c
    return out_h


class TestCalendarOracle:
    def test_randomized_timestamp_ties(self):
        # many exact ties: t drawn from a tiny discrete set, so slot
        # lists and the heap both break ties on the ctr field alone
        for seed in range(5):
            rng = Random(seed)
            pushes = [(rng.choice([0.0, 0.01, 0.02, 0.5, 0.51]),
                       ctr, "src", ctr % 4, 7, b"x", None)
                      for ctr in range(200)]
            out = _drain_equal(pushes)
            assert [x[:2] for x in out] == sorted(x[:2] for x in out)

    def test_interleaved_pops_and_monotone_pushes(self):
        rng = Random(42)
        now, ctr, pushes = 0.0, 0, []
        for _ in range(300):
            now += rng.random() * 0.05
            pushes.append((now + rng.uniform(0.001, 0.25), ctr,
                           0, 1, 7, b"p", None))
            ctr += 1
        _drain_equal(pushes, interleave_pops=3)

    def test_overflow_heap_window(self):
        # items far beyond the ring window exercise the overflow heap
        # and its migration on window advance
        rng = Random(9)
        pushes = [(rng.uniform(0.0, 50.0), ctr, 0, 1, 7, b"f", None)
                  for ctr in range(120)]
        _drain_equal(pushes, width=0.01, nslots=8)

    def test_empty_pop_raises(self):
        cal = CalendarScheduler(0.01, 8)
        with pytest.raises(IndexError):
            cal.pop()

    def test_simworld_digest_scheduler_independent(self):
        """Full-mode (digest-on) scenario: byte-identical schedule
        digest under both schedulers — the §14 oracle-equivalence
        rule end to end."""
        script = [(2.0 + i, "bcast", i % 4) for i in range(6)] + \
            [(15.0, "kill", 2), (30.0, "restart", 2)]
        a = Scenario(world_size=4, seed=13, duration=90.0,
                     script=script).run()
        b = Scenario(world_size=4, seed=13, duration=90.0,
                     script=script, scheduler="calendar").run()
        assert a["digest"] == b["digest"]
        assert a["events"] == b["events"]
        assert a["delivered"] == b["delivered"]

    def test_unknown_scheduler_rejected(self):
        with pytest.raises(ValueError):
            SimWorld(4, scheduler="splay")


# ---------------------------------------------------------------------------
# pending_events counter + violation message exposure
# ---------------------------------------------------------------------------

class TestPendingEvents:
    def test_counter_tracks_in_flight_frames(self):
        world = SimWorld(2, seed=0)
        tr = world.transport(0)
        assert world.pending_events() == 0
        for i in range(5):
            tr.isend(1, 7, bytes([i]))
        assert world.pending_events() == 5
        n = world.pending_events()
        while world.pending_events():
            world.step()
            n -= 1
            assert world.pending_events() == n
        assert world.quiescent() is False  # inbox still undrained

    def test_violation_message_carries_pending_events(self):
        sc = Scenario(world_size=4, seed=3)
        sc._world = SimWorld(4, seed=3)
        sc._world.transport(0).isend(1, 7, b"x")
        with pytest.raises(SimViolation) as ei:
            sc._fail("synthetic")
        msg = str(ei.value)
        assert "pending events at failure: 1" in msg
        assert "replay: Scenario(" in msg


# ---------------------------------------------------------------------------
# 4. weather profiles + the fabric_churn scenario kind
# ---------------------------------------------------------------------------

class TestWeather:
    def test_heavy_tail_delay_bounded_and_replayable(self):
        d = HeavyTailDelay()
        assert d(Random(5)) == d(Random(5))  # same rng => same sample
        rng = Random(1)
        samples = [d(rng) for _ in range(4000)]
        assert all(d.base <= s <= d.cap for s in samples)
        # heavy tail: p99 well above the median
        samples.sort()
        assert samples[-40] > 5 * samples[2000]

    def test_gilbert_loss_correlated_and_replayable(self):
        g1, g2 = GilbertLoss(), GilbertLoss()
        rng_a, rng_b = Random(3), Random(3)
        s1 = [g1(rng_a) for _ in range(5000)]
        s2 = [g2(rng_b) for _ in range(5000)]
        assert s1 == s2
        assert g1.bad_entries == g2.bad_entries > 0
        # correlation: drops cluster — the mean run length of drops
        # exceeds what iid loss at the same rate would produce (~1.07)
        runs, cur = [], 0
        for x in s1:
            if x:
                cur += 1
            elif cur:
                runs.append(cur)
                cur = 0
        assert runs and sum(runs) / len(runs) > 1.5

    def test_churn_script_invariants(self):
        ws, dur, settle, min_down = 8, 200.0, 60.0, 13.0
        steps = churn_script(11, world_size=ws, rate=0.08,
                             duration=dur, mean_down=20.0,
                             min_down=min_down, min_live=4,
                             settle=settle)
        assert steps == churn_script(11, world_size=ws, rate=0.08,
                                     duration=dur, mean_down=20.0,
                                     min_down=min_down, min_live=4,
                                     settle=settle)
        assert steps == sorted(steps, key=lambda s: s[0])
        live = set(range(ws))
        down_at = {}
        for t, act, r in steps:
            assert t <= dur - settle
            if act == "kill":
                assert r in live
                live.discard(r)
                down_at[r] = t
            else:
                assert act == "restart" and r not in live
                live.add(r)
            assert len(live) >= 4
        assert live == set(range(ws))  # everyone restarted by the end

    def test_weather_repr_is_replay_recipe(self):
        w = make_weather("churn", 4, world_size=4, rate=0.03,
                         duration=120.0)
        w2 = eval(repr(w), {"make_weather": make_weather})
        assert w2.script == w.script

    def test_stateful_weather_reused_across_runs_replays(self):
        """A Weather with a stateful sampler (the Gilbert chain) is
        reset at run start, so reusing ONE object across runs — the
        natural violation-debugging idiom — still replays bit-for-bit
        instead of starting the second run mid-burst."""
        w = make_weather("burst_loss")
        script = [(2.0 + i, "bcast", i % 4) for i in range(4)]
        mk = lambda: Scenario(world_size=4, seed=12, duration=40.0,
                              script=script, weather=w)
        sc = mk()
        a = sc.run()
        assert w.drop_fn.bad_entries >= 0
        b = sc.run()          # same scenario object, run twice
        c = mk().run()        # fresh scenario, same weather object
        assert a["digest"] == b["digest"] == c["digest"]

    def test_scenario_with_wan_weather_replays(self):
        script = [(2.0 + i, "bcast", i % 4) for i in range(4)]
        mk = lambda: Scenario(world_size=4, seed=8, duration=40.0,
                              script=script,
                              weather=make_weather("wan"))
        a, b = mk().run(), mk().run()
        assert a["digest"] == b["digest"]
        assert a["delivered"] == b["delivered"]
        # and the weather actually changed the schedule
        dry = Scenario(world_size=4, seed=8, duration=40.0,
                       script=script).run()
        assert dry["digest"] != a["digest"]

    def test_replay_recipe_does_not_double_weather_steps(self):
        """The recipe prints the PRE-merge script plus the weather:
        rebuilding from it must merge the weather steps exactly once,
        not re-apply them on top of an already-merged script."""
        w = make_weather("churn", 2, world_size=4, rate=0.05,
                         duration=120.0)
        sc = Scenario(world_size=4, seed=2, duration=120.0,
                      script=[(1.0, "bcast", 0)], weather=w)
        recipe = sc._replay_recipe()
        assert recipe.endswith(").run()")
        rebuilt = eval(recipe[:-len(".run()")],
                       {"Scenario": Scenario,
                        "make_weather": make_weather})
        assert rebuilt.script == sc.script
        assert rebuilt.script_arg == sc.script_arg

    def test_fabric_churn_registered_and_clean(self):
        assert "fabric_churn" in FABRIC_SCENARIO_KINDS
        assert "fabric_churn" in ALL_SCENARIO_KINDS
        res = make_scenario("fabric_churn", 0).run()
        assert res["rejoins"] > 0  # churn actually churned
        assert res["submitted"] > 0

    def test_fabric_recipe_replays_digest_identical(self):
        """The printed FabricScenario recipe carries every non-default
        knob (decode pacing, slots, paged-stub config, weather), so
        rebuilding from it replays the violating schedule exactly."""
        from rlo_tpu.serving.scenario import FabricScenario
        sc = make_scenario("fabric_kill", 1)
        a = sc.run()
        recipe = sc._replay_recipe()
        rebuilt = eval(recipe[:-len(".run()")],
                       {"FabricScenario": FabricScenario,
                        "make_weather": make_weather})
        b = rebuilt.run()
        assert a["digest"] == b["digest"]
        assert a["events"] == b["events"]

    @pytest.mark.slow
    def test_fabric_churn_sweep(self):
        for seed in range(25):
            make_scenario("fabric_churn", seed).run()


# ---------------------------------------------------------------------------
# workload_bench reproducibility (subprocess, like test_perf_gate)
# ---------------------------------------------------------------------------

class TestWorkloadBench:
    def test_quick_reproduces_itself(self, tmp_path):
        import subprocess
        import sys as _sys
        from pathlib import Path

        from rlo_tpu.tools.perf_gate import run_gate

        repo = Path(__file__).resolve().parents[1]
        docs = []
        for name in ("a", "b"):
            out = tmp_path / f"{name}.json"
            proc = subprocess.run(
                [_sys.executable, "benchmarks/workload_bench.py",
                 "--quick", "--out", str(out)],
                capture_output=True, text=True, cwd=repo)
            assert proc.returncode == 0, proc.stderr
            docs.append(json.loads(out.read_text()))
        assert docs[0]["suite"] == "workload_bench"
        assert run_gate(docs[0], docs[1]) == []
        # the acceptance surface: generator digests + the scale
        # datapoints + the trace-driven fabric leg all present
        keys = docs[0]["metrics"]
        assert "trace.swarm.digest" in keys
        assert "oracle.n256.schedulers_match" in keys
        assert any(k.startswith("fanout.n") for k in keys)
        assert any(k.startswith("fabric.trace_swarm.") for k in keys)
