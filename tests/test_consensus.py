"""IAR leaderless-consensus parity tests.

Oracles mirror testcases.c: single proposal with parameterized
agree/disagree outcome (:243-332), concurrent engines on the same ranks
(:110-241), and multiple simultaneous proposers (:401-594). The key
invariants: every rank sees every decision exactly once, all ranks agree on
each decision value, and the action callback runs exactly on approving
ranks that held the proposal.
"""

import pytest


def test_pid_reuse_across_sequential_rounds():
    """A pid may be reused by a LATER proposer (only concurrent
    collisions are forbidden): a rank whose completed own proposal
    carries the same pid must still relay votes for the new round.
    Regression for a review-caught deadlock."""
    from rlo_tpu.engine import EngineManager, ProgressEngine, drain
    from rlo_tpu.transport.loopback import LoopbackWorld

    ws = 4
    world = LoopbackWorld(ws)
    mgr = EngineManager()
    engines = [ProgressEngine(world.transport(r), manager=mgr)
               for r in range(ws)]
    for proposer in range(ws):
        rc = engines[proposer].submit_proposal(b"round", pid=7)
        for _ in range(100_000):
            if rc != -1:
                break
            mgr.progress_all()
            rc = engines[proposer].vote_my_proposal()
        assert rc == 1, f"proposer {proposer} deadlocked on reused pid"
        drain([world], engines)
        for e in engines:
            while e.pickup_next() is not None:
                pass
    for e in engines:
        e.cleanup()

from rlo_tpu.engine import ProgressEngine, EngineManager, ReqState, drain
from rlo_tpu.transport import make_world
from rlo_tpu.wire import Tag


class Ctx:
    """Per-rank application context recording callback activity."""

    def __init__(self, rank, veto=False):
        self.rank = rank
        self.veto = veto
        self.judged = []
        self.actions = []


def judge(payload, ctx: Ctx) -> int:
    ctx.judged.append(bytes(payload))
    return 0 if ctx.veto else 1


def action(payload, ctx: Ctx):
    ctx.actions.append(bytes(payload))


def build(ws, veto_ranks=(), latency=0, seed=None):
    world = make_world("loopback", ws, latency=latency, seed=seed)
    manager = EngineManager()
    ctxs = [Ctx(r, veto=(r in veto_ranks)) for r in range(ws)]
    engines = [ProgressEngine(world.transport(r), judge_cb=judge,
                              app_ctx=ctxs[r], action_cb=action,
                              manager=manager)
               for r in range(ws)]
    return world, engines, ctxs


def decisions_of(eng):
    out = []
    while (m := eng.pickup_next()) is not None:
        if m.type == Tag.IAR_DECISION:
            out.append(m)
    return out


WORLD_SIZES = [2, 3, 4, 5, 7, 8, 16, 23]


class TestSingleProposal:
    @pytest.mark.parametrize("ws", WORLD_SIZES)
    @pytest.mark.parametrize("proposer", [0, 1])
    def test_all_approve(self, ws, proposer):
        proposer = proposer % ws
        world, engines, ctxs = build(ws)
        engines[proposer].submit_proposal(b"prop", pid=proposer)
        drain([world], engines)
        assert engines[proposer].vote_my_proposal() == 1
        assert engines[proposer].check_proposal_state() == ReqState.COMPLETED
        for r in range(ws):
            if r == proposer:
                continue
            # every non-proposer judged it, executed it, and saw the decision
            assert ctxs[r].judged == [b"prop"]
            assert ctxs[r].actions == [b"prop"]
            ds = decisions_of(engines[r])
            assert len(ds) == 1 and ds[0].vote == 1 and ds[0].pid == proposer

    @pytest.mark.parametrize("ws", WORLD_SIZES)
    def test_one_veto_declines(self, ws):
        veto_rank = ws - 1
        world, engines, ctxs = build(ws, veto_ranks={veto_rank})
        engines[0].submit_proposal(b"prop", pid=0)
        drain([world], engines)
        assert engines[0].vote_my_proposal() == 0
        for r in range(1, ws):
            ds = decisions_of(engines[r])
            assert len(ds) == 1 and ds[0].vote == 0
            assert ctxs[r].actions == []  # declined: no one executes

    @pytest.mark.parametrize("ws", [4, 8, 16])
    def test_proposer_self_veto_via_rejudge(self, ws):
        """The proposer re-judges its own proposal after collecting yes
        votes (rootless_ops.c:773) — a proposer whose context turned veto
        must decline its own proposal."""
        world, engines, ctxs = build(ws)
        ctxs[0].veto = True  # context changes after submission is simulated
        engines[0].submit_proposal(b"prop", pid=0)
        drain([world], engines)
        assert engines[0].vote_my_proposal() == 0

    @pytest.mark.parametrize("ws,latency,seed", [(8, 4, 0), (16, 6, 1),
                                                 (23, 5, 2)])
    def test_under_latency_fuzz(self, ws, latency, seed):
        world, engines, ctxs = build(ws, latency=latency, seed=seed)
        engines[2].submit_proposal(b"zz", pid=2)
        drain([world], engines)
        assert engines[2].vote_my_proposal() == 1
        for r in range(ws):
            if r != 2:
                assert len(ctxs[r].actions) == 1


class TestMultiProposal:
    @pytest.mark.parametrize("ws", [4, 8, 16])
    def test_two_proposers_consistent(self, ws):
        """Two simultaneous proposers: all ranks must agree on every
        decision, and each rank sees exactly two decisions
        (testcases.c:401-486 counts decisions the same way)."""
        world, engines, ctxs = build(ws)
        engines[0].submit_proposal(b"A", pid=0)
        engines[1].submit_proposal(b"B", pid=1)
        drain([world], engines)
        by_pid = {}
        for r in range(ws):
            ds = decisions_of(engines[r])
            expect = 2 if r not in (0, 1) else 1  # proposers skip their own
            assert len(ds) == expect, f"rank {r}: {ds}"
            for d in ds:
                by_pid.setdefault(d.pid, set()).add(d.vote)
        by_pid.setdefault(0, set()).add(engines[0].vote_my_proposal())
        by_pid.setdefault(1, set()).add(engines[1].vote_my_proposal())
        assert set(by_pid) == {0, 1}
        for pid, votes in by_pid.items():
            assert len(votes) == 1, f"inconsistent decision for pid {pid}"

    @pytest.mark.parametrize("ws", [8, 16])
    def test_conflicting_proposals_lexicographic(self, ws):
        """Conflict resolution delegated to the judgement callback, like
        is_proposal_approved_cb (testcases.c:18-37): approve only proposals
        lexicographically >= my own submission."""
        world = make_world("loopback", ws)
        manager = EngineManager()
        my_prop = {0: b"apple", 1: b"banana"}

        class LexCtx:
            def __init__(self, rank):
                self.rank = rank
                self.actions = []

        def lex_judge(payload, ctx):
            mine = my_prop.get(ctx.rank)
            if mine is None:
                return 1
            return 1 if bytes(payload) >= mine else 0

        def lex_action(payload, ctx):
            ctx.actions.append(bytes(payload))

        ctxs = [LexCtx(r) for r in range(ws)]
        engines = [ProgressEngine(world.transport(r), judge_cb=lex_judge,
                                  app_ctx=ctxs[r], action_cb=lex_action,
                                  manager=manager)
                   for r in range(ws)]
        engines[0].submit_proposal(b"apple", pid=0)
        engines[1].submit_proposal(b"banana", pid=1)
        drain([world], engines)
        # banana >= apple: rank 0 approves banana; apple < banana: rank 1
        # vetoes apple. So pid 1 approved, pid 0 declined.
        assert engines[1].vote_my_proposal() == 1
        assert engines[0].vote_my_proposal() == 0


class TestConcurrentMultiProposal:
    """Reference test_concurrent_iar_multi_proposal (testcases.c:488-594):
    the PRODUCT of engine multiplexing and multiple simultaneous
    proposers — several proposers on each of two engines at once, with
    pid reuse across sequential rounds. Decision-count oracles: every
    rank sees exactly one decision per foreign proposal per engine, all
    values agree."""

    @staticmethod
    def proposers_of(ws):
        # reference active_1 + active_2_mod pattern (testcases.c:401-486)
        return sorted({1 % ws} | {r for r in range(ws) if r % 4 == 0})

    @pytest.mark.parametrize("ws", [4, 8, 13])
    def test_multi_proposal_on_two_engines(self, ws):
        manager = EngineManager()
        world_a = make_world("loopback", ws)
        world_b = make_world("loopback", ws)
        eng_a = [ProgressEngine(world_a.transport(r), manager=manager)
                 for r in range(ws)]
        eng_b = [ProgressEngine(world_b.transport(r), manager=manager)
                 for r in range(ws)]
        proposers = self.proposers_of(ws)
        for rnd in range(3):  # pid reuse: every round reuses pid=rank
            for p in proposers:
                eng_a[p].submit_proposal(f"A{rnd}p{p}".encode(), pid=p)
                eng_b[p].submit_proposal(f"B{rnd}p{p}".encode(), pid=p)
            drain([world_a, world_b], eng_a + eng_b)
            for engines in (eng_a, eng_b):
                for r in range(ws):
                    ds = decisions_of(engines[r])
                    want = len(proposers) - (1 if r in proposers else 0)
                    assert len(ds) == want, (rnd, r, ds)
                    assert sorted(d.pid for d in ds) == [
                        p for p in proposers if p != r]
                    assert all(d.vote == 1 for d in ds)
            for p in proposers:
                assert eng_a[p].vote_my_proposal() == 1
                assert eng_b[p].vote_my_proposal() == 1

    @pytest.mark.parametrize("ws", [4, 8, 13])
    def test_native_multi_proposal_on_two_engines(self, ws):
        """C-engine mirror over the in-process native world (the
        multi-process version is demo scenario `multi2`)."""
        from rlo_tpu.native.bindings import NativeEngine, NativeWorld

        with NativeWorld(ws) as wa, NativeWorld(ws) as wb:
            eng_a = [NativeEngine(wa, r) for r in range(ws)]
            eng_b = [NativeEngine(wb, r) for r in range(ws)]
            proposers = self.proposers_of(ws)

            def spin_all():
                for _ in range(100_000):
                    wa.progress_all()
                    wb.progress_all()
                    if wa.quiescent() and wb.quiescent() and all(
                            e.idle() for e in eng_a + eng_b):
                        return
                raise RuntimeError("no quiescence")

            for rnd in range(3):
                for p in proposers:
                    assert eng_a[p].submit_proposal(
                        f"A{rnd}".encode(), pid=p) >= -1
                    assert eng_b[p].submit_proposal(
                        f"B{rnd}".encode(), pid=p) >= -1
                spin_all()
                for engines in (eng_a, eng_b):
                    for r in range(ws):
                        pids = []
                        while (m := engines[r].pickup_next()) is not None:
                            if m.type == int(Tag.IAR_DECISION):
                                assert m.vote == 1
                                pids.append(m.pid)
                        assert sorted(pids) == [
                            p for p in proposers if p != r], (rnd, r)
                for p in proposers:
                    assert eng_a[p].vote_my_proposal() == 1
                    assert eng_b[p].vote_my_proposal() == 1


class TestDecisionDedup:
    """A decision forwarded by a mix of old- and new-topology trees
    during a view change can arrive twice; the settled-round dedup
    delivers each (pid, gen) exactly once and runs the action callback
    exactly once — in both engines."""

    def test_duplicate_decision_dropped_python(self):
        import struct
        from rlo_tpu.engine import EngineManager, ProgressEngine
        from rlo_tpu.wire import Frame

        world = make_world("loopback", 4)
        mgr = EngineManager()
        acted = []
        engines = [ProgressEngine(world.transport(r), manager=mgr,
                                  action_cb=lambda p, c: acted.append(p))
                   for r in range(4)]
        engines[0].submit_proposal(b"p", pid=0)
        drain([world], engines)
        gen = engines[0].my_own_proposal.gen
        # replay the decision frame at rank 2 (as a mixed-overlay
        # duplicate would)
        dup = Frame(origin=0, pid=0, vote=1,
                    payload=struct.pack("<i", gen))
        world.transport(0).isend(2, int(Tag.IAR_DECISION), dup.encode())
        for _ in range(50):
            mgr.progress_all()
        ds = decisions_of(engines[2])
        assert len(ds) == 1, ds  # replay suppressed
        assert acted.count(b"p") == 3  # ranks 1-3, once each

    def test_duplicate_proposal_not_rejudged_python(self):
        """A proposal arriving twice (mixed-overlay trees) must be
        judged and voted exactly once — a second judge/vote, possibly
        to a different parent, would corrupt the vote accounting. The
        duplicate is still forwarded for coverage."""
        from rlo_tpu.engine import EngineManager, ProgressEngine
        from rlo_tpu.wire import Frame

        world = make_world("loopback", 4)
        mgr = EngineManager()
        judged = []
        engines = [ProgressEngine(world.transport(r), manager=mgr,
                                  judge_cb=lambda p, c, r=r: (
                                      judged.append(r), 1)[1])
                   for r in range(4)]
        engines[0].submit_proposal(b"p", pid=0)
        drain([world], engines)
        assert engines[0].vote_my_proposal() == 1
        base = sorted(judged)
        gen = engines[0].my_own_proposal.gen
        # replay the proposal at rank 1 as if re-sent by origin 0
        dup = Frame(origin=0, pid=0, vote=gen, payload=b"p")
        world.transport(0).isend(1, int(Tag.IAR_PROPOSAL), dup.encode())
        for _ in range(100):
            mgr.progress_all()
        drain([world], engines)
        assert sorted(judged) == base, (judged, base)  # no re-judging

    def test_duplicate_proposal_not_rejudged_native(self):
        from rlo_tpu.native.bindings import NativeEngine, NativeWorld
        from rlo_tpu.wire import Frame

        judged = []
        with NativeWorld(4) as world:
            engines = [NativeEngine(
                world, r,
                judge_cb=lambda p, c, r=r: (judged.append(r), 1)[1])
                for r in range(4)]
            assert engines[0].submit_proposal(b"p", pid=0) >= -1
            for _ in range(10_000):
                world.progress_all()
                if engines[0].vote_my_proposal() != -1:
                    break
            world.drain()
            base = sorted(judged)
            # the decision payload at a relay carries the generation
            seen = [m for m in iter(engines[2].pickup_next, None)
                    if m.type == int(Tag.IAR_DECISION)]
            import struct
            gen = struct.unpack_from("<i", seen[0].data)[0]
            dup = Frame(origin=0, pid=0, vote=gen, payload=b"p")
            world.inject(src=0, dst=1, tag=int(Tag.IAR_PROPOSAL),
                         raw=dup.encode())
            for _ in range(100):
                world.progress_all()
            world.drain()
            assert sorted(judged) == base, (judged, base)

    def test_pending_duplicate_votes_back_to_new_parent(self):
        """The deadlock case: a relay that receives a PENDING duplicate
        from a different (new-view) parent must vote its accumulated
        verdict back to that parent — the sender's await list includes
        this rank and silence would hang its round forever."""
        import struct
        from rlo_tpu.engine import EngineManager, ProgressEngine
        from rlo_tpu.transport.loopback import LoopbackWorld
        from rlo_tpu.wire import Frame

        world = LoopbackWorld(4)
        mgr = EngineManager()
        judged = []
        eng1 = ProgressEngine(world.transport(1), manager=mgr,
                              judge_cb=lambda p, c: (judged.append(1),
                                                     1)[1])
        gen = 12345
        # original proposal from origin/parent 0: rank 1 judges, votes
        # to 0, parks the pending state
        orig = Frame(origin=0, pid=7, vote=gen, payload=b"p")
        world.transport(0).isend(1, int(Tag.IAR_PROPOSAL), orig.encode())
        mgr.progress_all()
        assert judged == [1]
        assert len(eng1.queue_iar_pending) == 1
        # drain rank 0's inbox (the original vote)
        while world.transport(0).poll() is not None:
            pass
        # duplicate arrives from rank 2 (a new-view parent)
        dup = Frame(origin=0, pid=7, vote=gen, payload=b"p")
        world.transport(2).isend(1, int(Tag.IAR_PROPOSAL), dup.encode())
        mgr.progress_all()
        assert judged == [1]  # not re-judged
        assert len(eng1.queue_iar_pending) == 1  # not re-parked
        got = []
        while (item := world.transport(2).poll()) is not None:
            got.append(item)
        votes = [(s, t, Frame.decode(raw)) for (s, t, raw) in got
                 if t == int(Tag.IAR_VOTE)]
        assert len(votes) == 1, got
        s, t, f = votes[0]
        assert s == 1 and f.pid == 7 and f.vote == 1
        assert struct.unpack_from("<i", f.payload)[0] == gen

    def test_unresolved_duplicate_defers_vote_until_merge(self):
        """Round-2 advisor finding: a relay with subtree votes still
        outstanding must NOT vote an interim verdict to a duplicate's
        (new-view) parent — if a descendant's veto later completes the
        round, that veto would go only to the original parent, which in
        the view-change scenario is exactly the dead rank. The dup
        parent must instead receive the FINAL merged vote when the
        round resolves, so the veto survives on the new path."""
        import struct
        from rlo_tpu.engine import EngineManager, ProgressEngine
        from rlo_tpu.transport.loopback import LoopbackWorld
        from rlo_tpu.wire import Frame

        world = LoopbackWorld(8)
        mgr = EngineManager()
        # relay-with-children is a skip-ring-only shape (under 'flat'
        # every receiver is a leaf) — pin the schedule explicitly so
        # the suite also passes under RLO_FANOUT=flat
        eng2 = ProgressEngine(world.transport(2), manager=mgr,
                              fanout="skip_ring")
        gen = 777
        orig = Frame(origin=0, pid=5, vote=gen, payload=b"p")
        world.transport(0).isend(2, int(Tag.IAR_PROPOSAL), orig.encode())
        mgr.progress_all()
        ps = eng2.queue_iar_pending[0].prop_state
        children = list(ps.await_from)
        assert children, "need a relay with children for this scenario"
        assert not ps.resolved
        # duplicate arrives from rank 6 (a re-formed-tree parent)
        dup = Frame(origin=0, pid=5, vote=gen, payload=b"p")
        world.transport(6).isend(2, int(Tag.IAR_PROPOSAL), dup.encode())
        mgr.progress_all()
        # deferred: no vote sent to rank 6 yet
        got6 = []
        while (item := world.transport(6).poll()) is not None:
            got6.append(item)
        assert not [1 for (_, t, _) in got6 if t == int(Tag.IAR_VOTE)]
        assert 6 in ps.dup_parents
        # children's merged votes arrive; the LAST one is a veto
        for i, c in enumerate(children):
            v = 0 if i == len(children) - 1 else 1
            vf = Frame(origin=c, pid=5, vote=v,
                       payload=struct.pack("<i", gen))
            world.transport(c).isend(2, int(Tag.IAR_VOTE), vf.encode())
        for _ in range(10):
            mgr.progress_all()
        assert ps.resolved and ps.vote == 0

        def votes_at(rank):
            out = []
            while (item := world.transport(rank).poll()) is not None:
                if item[1] == int(Tag.IAR_VOTE):
                    out.append(Frame.decode(item[2]))
            return out

        # BOTH parents got the merged veto
        v0 = votes_at(0)
        v6 = votes_at(6)
        assert [f.vote for f in v0] == [0], v0
        assert [f.vote for f in v6] == [0], v6
        assert struct.unpack_from("<i", v6[0].payload)[0] == gen

    def test_declined_relay_parked_never_rejudged(self):
        """A relay that voted NO must remember the round: a duplicate
        from a re-formed tree gets the final 0 immediately and the
        judge callback must not fire a second time."""
        import struct
        from rlo_tpu.engine import EngineManager, ProgressEngine
        from rlo_tpu.transport.loopback import LoopbackWorld
        from rlo_tpu.wire import Frame

        world = LoopbackWorld(8)
        mgr = EngineManager()
        judged = []
        eng2 = ProgressEngine(world.transport(2), manager=mgr,
                              judge_cb=lambda p, c: (judged.append(1),
                                                     0)[1])
        gen = 778
        orig = Frame(origin=0, pid=5, vote=gen, payload=b"p")
        world.transport(0).isend(2, int(Tag.IAR_PROPOSAL), orig.encode())
        mgr.progress_all()
        assert judged == [1]
        ps = eng2.queue_iar_pending[0].prop_state
        assert ps.resolved and ps.vote == 0
        dup = Frame(origin=0, pid=5, vote=gen, payload=b"p")
        world.transport(6).isend(2, int(Tag.IAR_PROPOSAL), dup.encode())
        mgr.progress_all()
        assert judged == [1]  # never re-judged
        got = []
        while (item := world.transport(6).poll()) is not None:
            got.append(item)
        votes = [Frame.decode(raw) for (_, t, raw) in got
                 if t == int(Tag.IAR_VOTE)]
        assert [f.vote for f in votes] == [0]
        assert struct.unpack_from("<i", votes[0].payload)[0] == gen

    def test_decision_in_reflood_log_and_clears_parked_round(self):
        """Decisions ride the view-change re-flood log (code-review
        finding on the round-3 consensus rework): with parent-died
        rounds now staying parked, a decision lost with a dead relay
        would block checkpointing forever unless survivors re-flood it.
        Pins: (a) after a round, the decision frame sits in every
        participant's re-flood log with its own tag; (b) a re-flooded
        decision arriving point-to-point (not via the tree) clears a
        parked round and fires the action; (c) the proposer drops a
        re-flooded copy of its own decision."""
        import struct
        from rlo_tpu.engine import EngineManager, ProgressEngine
        from rlo_tpu.transport.loopback import LoopbackWorld
        from rlo_tpu.wire import Frame

        world = make_world("loopback", 4)
        mgr = EngineManager()
        engines = [ProgressEngine(world.transport(r), manager=mgr)
                   for r in range(4)]
        engines[0].submit_proposal(b"p", pid=0)
        drain([world], engines)
        gen = engines[0].my_own_proposal.gen
        for r, eng in enumerate(engines):
            tags = [t for t, _ in eng._recent_bcasts]
            assert int(Tag.IAR_DECISION) in tags, (r, tags)

        # (b) a fresh relay with a parked round, decision arriving as a
        # point-to-point re-flood from a NON-parent rank
        world2 = LoopbackWorld(8)
        mgr2 = EngineManager()
        acted = []
        eng2 = ProgressEngine(world2.transport(2), manager=mgr2,
                              action_cb=lambda p, c: acted.append(p))
        orig = Frame(origin=0, pid=5, vote=777, payload=b"q")
        world2.transport(0).isend(2, int(Tag.IAR_PROPOSAL), orig.encode())
        mgr2.progress_all()
        assert len(eng2.queue_iar_pending) == 1
        dec = Frame(origin=0, pid=5, vote=1,
                    payload=struct.pack("<i", 777))
        world2.transport(5).isend(2, int(Tag.IAR_DECISION), dec.encode())
        for _ in range(10):
            mgr2.progress_all()
        assert not eng2.queue_iar_pending  # round cleared
        assert acted == [b"q"]             # action fired once

        # (c) proposer ignores a re-flooded copy of its own decision
        own_before = len(
            [m for m in iter(engines[0].pickup_next, None)])
        own_dec = Frame(origin=0, pid=0, vote=1,
                        payload=struct.pack("<i", gen))
        world.transport(3).isend(0, int(Tag.IAR_DECISION),
                                 own_dec.encode())
        for _ in range(10):
            mgr.progress_all()
        extra = [m for m in iter(engines[0].pickup_next, None)]
        assert not extra, extra

    def test_declined_relay_not_rejudged_native(self):
        """C mirror of test_declined_relay_parked_never_rejudged: a
        relay that voted NO keeps the round parked, so a duplicate from
        a re-formed tree must not fire the judge a second time (the old
        code freed the declined round, making every dup look new)."""
        from rlo_tpu.native.bindings import NativeEngine, NativeWorld
        from rlo_tpu.wire import Frame

        judged = []
        with NativeWorld(8) as world:
            # engine only at the relay under test; other ranks' inboxes
            # are passive sinks for its forwards/votes
            NativeEngine(world, 2,
                         judge_cb=lambda p, c: (judged.append(2), 0)[1])
            gen = 779
            orig = Frame(origin=0, pid=5, vote=gen, payload=b"p")
            world.inject(src=0, dst=2, tag=int(Tag.IAR_PROPOSAL),
                         raw=orig.encode())
            for _ in range(100):
                world.progress_all()
            assert judged == [2]
            dup = Frame(origin=0, pid=5, vote=gen, payload=b"p")
            world.inject(src=6, dst=2, tag=int(Tag.IAR_PROPOSAL),
                         raw=dup.encode())
            for _ in range(100):
                world.progress_all()
            assert judged == [2]  # never re-judged

    def test_unresolved_duplicate_defers_vote_native(self):
        """C mirror of the deferred-dup scenario: an approving relay
        with child votes outstanding records the dup parent instead of
        voting an interim verdict; the round resolves when the (vetoing)
        child votes arrive. Observable natively as: exactly one judge
        call, no engine error, and the world going quiescent (the dup
        parent DID eventually receive a vote — a deadlocked round would
        leave the relay's pending send unforwarded forever)."""
        import struct
        from rlo_tpu.native.bindings import NativeEngine, NativeWorld
        from rlo_tpu.wire import Frame

        judged = []
        with NativeWorld(8) as world:
            eng = NativeEngine(world, 2,
                               judge_cb=lambda p, c: (judged.append(2),
                                                      1)[1])
            gen = 780
            orig = Frame(origin=0, pid=5, vote=gen, payload=b"p")
            world.inject(src=0, dst=2, tag=int(Tag.IAR_PROPOSAL),
                         raw=orig.encode())
            for _ in range(100):
                world.progress_all()
            dup = Frame(origin=0, pid=5, vote=gen, payload=b"p")
            world.inject(src=6, dst=2, tag=int(Tag.IAR_PROPOSAL),
                         raw=dup.encode())
            for _ in range(100):
                world.progress_all()
            assert judged == [2]
            # children 3 and 4 (skip-ring fwd targets of rank 2 for an
            # origin-0 proposal) vote; 4 vetoes
            for child, v in ((3, 1), (4, 0)):
                vf = Frame(origin=child, pid=5, vote=v,
                           payload=struct.pack("<i", gen))
                world.inject(src=child, dst=2,
                             tag=int(Tag.IAR_VOTE), raw=vf.encode())
            for _ in range(200):
                world.progress_all()
            assert judged == [2]
            # the engine reached a resolved state without protocol error
            assert eng.err == 0

    def test_duplicate_decision_dropped_native(self):
        import struct
        from rlo_tpu.native.bindings import NativeEngine, NativeWorld
        from rlo_tpu.wire import Frame

        with NativeWorld(4) as world:
            engines = [NativeEngine(world, r) for r in range(4)]
            assert engines[0].submit_proposal(b"p", pid=0) >= -1
            for _ in range(10_000):
                world.progress_all()
                if engines[0].vote_my_proposal() != -1:
                    break
            world.drain()
            # count decisions at rank 2, then replay the decision frame
            seen = [m for m in iter(engines[2].pickup_next, None)
                    if m.type == int(Tag.IAR_DECISION)]
            assert len(seen) == 1
            # reconstruct the decision's generation from the payload
            gen = struct.unpack_from("<i", seen[0].data)[0]
            dup = Frame(origin=0, pid=0, vote=1,
                        payload=struct.pack("<i", gen))
            world.inject(src=0, dst=2, tag=int(Tag.IAR_DECISION),
                         raw=dup.encode())
            for _ in range(100):
                world.progress_all()
            world.drain()
            assert all(m.type != int(Tag.IAR_DECISION)
                       for m in iter(engines[2].pickup_next, None))


class TestEngineMultiplexing:
    @pytest.mark.parametrize("ws", [4, 8])
    def test_two_engines_concurrently(self, ws):
        """Two engines per rank over independent transports progress each
        other (testcases.c:110-241: concurrent IAR on two engines)."""
        manager = EngineManager()
        world_a = make_world("loopback", ws)
        world_b = make_world("loopback", ws)
        ctx_a = [Ctx(r) for r in range(ws)]
        ctx_b = [Ctx(r) for r in range(ws)]
        eng_a = [ProgressEngine(world_a.transport(r), judge_cb=judge,
                                app_ctx=ctx_a[r], action_cb=action,
                                manager=manager) for r in range(ws)]
        eng_b = [ProgressEngine(world_b.transport(r), judge_cb=judge,
                                app_ctx=ctx_b[r], action_cb=action,
                                manager=manager) for r in range(ws)]
        eng_a[0].submit_proposal(b"on-a", pid=0)
        eng_b[1].submit_proposal(b"on-b", pid=1)
        eng_a[2].bcast(b"plain")
        drain([world_a, world_b], eng_a + eng_b)
        assert eng_a[0].vote_my_proposal() == 1
        assert eng_b[1].vote_my_proposal() == 1
        for r in range(ws):
            if r != 0:
                assert ctx_a[r].actions == [b"on-a"]
            if r != 1:
                assert ctx_b[r].actions == [b"on-b"]
