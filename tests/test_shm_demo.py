"""Multi-process SHM transport: run the native demo binary end-to-end.

The demo is the framework's `mpirun -n N ./demo` analogue (reference
Makefile:5, testcases.c:742-780): rlo_shm_launch forks N real OS
processes that communicate through SPSC shared-memory rings, replicating
the reference integration scenarios (SURVEY.md §4) with their
behavior-level oracles. pytest drives the binary the way the reference
suite is driven by mpirun.
"""

import subprocess
from pathlib import Path

import pytest

NATIVE = Path(__file__).resolve().parent.parent / "rlo_tpu" / "native"


@pytest.fixture(scope="module")
def demo_bin():
    subprocess.run(["make", "demo"], cwd=NATIVE, check=True,
                   capture_output=True)
    return NATIVE / "rlo_demo"


def run_demo(demo_bin, *args, timeout=300):
    proc = subprocess.run([str(demo_bin), *map(str, args)],
                          capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, (
        f"demo failed (rc={proc.returncode})\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}")
    return proc.stdout


@pytest.mark.parametrize("ws", [2, 3, 5, 8])
def test_all_cases(demo_bin, ws):
    out = run_demo(demo_bin, "-n", ws, "-m", 8)
    assert "FAIL" not in out
    # one PASS line per case (+1: iar runs agree and veto variants)
    assert out.count("PASS") == 12


def test_failure_detection(demo_bin):
    out = run_demo(demo_bin, "-n", 4, "-c", "fail")
    assert out.count("PASS") == 1


def test_engine_elastic_recovery_multiprocess(demo_bin):
    """Full engine-level failure recovery across real OS processes."""
    out = run_demo(demo_bin, "-n", 6, "-c", "efail")
    assert out.count("PASS") == 1


def test_bcast_many_messages(demo_bin):
    out = run_demo(demo_bin, "-n", 6, "-c", "bcast", "-m", 200)
    assert out.count("PASS") == 1


def test_explicit_veto_rank(demo_bin):
    out = run_demo(demo_bin, "-n", 8, "-c", "iar", "-veto", 3)
    assert out.count("PASS") == 1


def test_nonpow2_stress(demo_bin):
    out = run_demo(demo_bin, "-n", 13, "-c", "hacky", "-m", 32)
    assert out.count("PASS") == 1
