"""Checkpoint/resume subsystem (rlo_tpu.utils.checkpoint).

The reference has no checkpointing (SURVEY.md §5); these tests define the
rebuild's contract: sharded pytree round-trips, retention, bit-exact
resume-training equivalence, and quiesced engine snapshot/restore.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from rlo_tpu.engine import ProgressEngine, drain
from rlo_tpu.models.transformer import (TransformerConfig, init_params,
                                        train_step)
from rlo_tpu.parallel.mesh import make_mesh
from rlo_tpu.transport.loopback import LoopbackWorld
from rlo_tpu.utils import checkpoint as ck

WS = 8


@pytest.fixture(scope="module")
def mesh():
    return make_mesh((WS,), ("x",))


def sharded_tree(mesh):
    sh = NamedSharding(mesh, P("x"))
    return {
        "params": {"w": jax.device_put(
            jnp.arange(float(WS * 4)).reshape(WS, 4), sh)},
        "step": jnp.int32(7),
    }


class TestPytreeRoundTrip:
    @pytest.mark.parametrize("backend", ["orbax", "npz"])
    def test_round_trip_preserves_values_and_sharding(self, mesh, tmp_path,
                                                      backend):
        tree = sharded_tree(mesh)
        path = str(tmp_path / "ckpt")
        ck.save_pytree(path, tree, backend=backend)
        out = ck.restore_pytree(path, like=tree)
        np.testing.assert_array_equal(np.asarray(out["params"]["w"]),
                                      np.asarray(tree["params"]["w"]))
        assert int(out["step"]) == 7
        assert out["params"]["w"].sharding == tree["params"]["w"].sharding

    def test_restore_onto_different_sharding(self, mesh, tmp_path):
        """Template controls placement: save sharded, restore replicated."""
        tree = sharded_tree(mesh)
        path = str(tmp_path / "ckpt")
        ck.save_pytree(path, tree)
        like = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(
                a.shape, a.dtype, sharding=NamedSharding(mesh, P())), tree)
        out = ck.restore_pytree(path, like=like)
        assert out["params"]["w"].sharding.spec == P()
        np.testing.assert_array_equal(np.asarray(out["params"]["w"]),
                                      np.asarray(tree["params"]["w"]))

    def test_npz_requires_template(self, tmp_path):
        path = str(tmp_path / "ckpt")
        ck.save_pytree(path, {"a": np.ones(3)}, backend="npz")
        with pytest.raises(ValueError, match="template"):
            ck._npz_restore(path, None)

    def test_npz_missing_leaf(self, tmp_path):
        path = str(tmp_path / "ckpt")
        ck.save_pytree(path, {"a": np.ones(3)}, backend="npz")
        with pytest.raises(KeyError, match="missing"):
            ck.restore_pytree(path, like={"a": np.ones(3), "b": np.ones(2)})


class TestManager:
    def test_retention_and_latest(self, tmp_path):
        mgr = ck.CheckpointManager(str(tmp_path / "run"), max_to_keep=3,
                                   backend="npz")
        for step in (1, 2, 5, 9, 10):
            mgr.save(step, {"x": np.full(2, float(step))})
        assert mgr.all_steps() == [5, 9, 10]
        assert mgr.latest_step() == 10
        out = mgr.restore(like={"x": np.zeros(2)})
        np.testing.assert_array_equal(out["x"], [10.0, 10.0])
        out5 = mgr.restore(step=5, like={"x": np.zeros(2)})
        np.testing.assert_array_equal(out5["x"], [5.0, 5.0])

    def test_partial_checkpoint_falls_back_to_last_good(self, tmp_path):
        """A crash mid-save leaves a step dir without the RLO_BACKEND
        marker (it is written last); restore() must skip it and load the
        newest COMPLETE step instead of failing."""
        import os
        mgr = ck.CheckpointManager(str(tmp_path), backend="npz")
        mgr.save(9, {"w": np.arange(4.0)})
        # simulate a kill mid-save of step 10: dir + truncated payload,
        # no marker
        partial = os.path.join(str(tmp_path), "step_10")
        os.makedirs(partial)
        with open(os.path.join(partial, "state.npz"), "wb") as f:
            f.write(b"\x00\x01truncated")
        assert mgr.latest_step() == 9
        out = mgr.restore(like={"w": np.zeros(4)})
        np.testing.assert_array_equal(np.asarray(out["w"]), np.arange(4.0))
        # the next successful save sweeps the crashed partial
        mgr.save(11, {"w": np.arange(4.0) + 1})
        assert not os.path.exists(partial)

    def test_overwrite_is_swap_not_delete_first(self, tmp_path):
        """save_pytree over an existing checkpoint assembles the new one
        in a temp dir and swaps by rename — at no point is the directory
        a half-written mix."""
        import os
        path = str(tmp_path / "ckpt")
        ck.save_pytree(path, {"w": np.arange(3.0)}, backend="npz")
        ck.save_pytree(path, {"w": np.arange(3.0) * 2}, backend="npz")
        out = ck.restore_pytree(path, like={"w": np.zeros(3)})
        np.testing.assert_array_equal(np.asarray(out["w"]),
                                      np.arange(3.0) * 2)
        leftovers = [n for n in os.listdir(str(tmp_path))
                     if n.endswith((".tmp-rlo", ".old-rlo"))]
        assert leftovers == []

    def test_crash_inside_swap_window_recovers(self, tmp_path):
        """A kill between save_pytree's two renames leaves the complete
        new checkpoint at .tmp-rlo and nothing at the path; restore and
        the manager must promote it back instead of losing both copies."""
        import os
        path = str(tmp_path / "ckpt")
        ck.save_pytree(path, {"w": np.arange(5.0)}, backend="npz")
        # simulate the window: old renamed away, tmp complete, not swapped
        os.rename(path, path + ".old-rlo")
        shutil_copytree = __import__("shutil").copytree
        shutil_copytree(path + ".old-rlo", path + ".tmp-rlo")
        out = ck.restore_pytree(path, like={"w": np.zeros(5)})
        np.testing.assert_array_equal(np.asarray(out["w"]), np.arange(5.0))
        assert os.path.exists(path)
        # manager-level: a stranded step promotes during all_steps()
        mgr = ck.CheckpointManager(str(tmp_path / "m"), backend="npz")
        mgr.save(4, {"w": np.arange(2.0)})
        os.rename(mgr._step_dir(4), mgr._step_dir(4) + ".tmp-rlo")
        assert mgr.latest_step() == 4
        out = mgr.restore(like={"w": np.zeros(2)})
        np.testing.assert_array_equal(np.asarray(out["w"]), np.arange(2.0))

    def test_restore_empty_raises(self, tmp_path):
        mgr = ck.CheckpointManager(str(tmp_path / "empty"))
        with pytest.raises(FileNotFoundError):
            mgr.restore()


class TestResumeTraining:
    def test_resume_matches_uninterrupted(self, tmp_path):
        """Train 4 steps straight vs train 2, checkpoint, restore into a
        fresh pytree, train 2 more — parameters must match bit-exactly."""
        cfg = TransformerConfig(vocab=32, d_model=32, n_heads=2, n_layers=1,
                                d_ff=64, dtype="float32")
        params = init_params(jax.random.PRNGKey(0), cfg)
        rng = np.random.default_rng(0)
        batches = [jnp.asarray(rng.integers(0, cfg.vocab, (2, 16)), jnp.int32)
                   for _ in range(4)]
        step = jax.jit(lambda p, t: train_step(p, t, cfg, lr=1e-2))

        straight = params
        for b in batches:
            straight, _ = step(straight, b)

        half = params
        for b in batches[:2]:
            half, _ = step(half, b)
        mgr = ck.CheckpointManager(str(tmp_path / "run"))
        mgr.save(2, {"params": half, "step": jnp.int32(2)})

        restored = mgr.restore(like={"params": half, "step": jnp.int32(0)})
        assert int(restored["step"]) == 2
        resumed = restored["params"]
        for b in batches[2:]:
            resumed, _ = step(resumed, b)

        for a, b in zip(jax.tree.leaves(straight), jax.tree.leaves(resumed)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestParallelModelCheckpoint:
    def test_tp_sharded_train_state_roundtrips_across_meshes(self,
                                                             tmp_path):
        """Integration across subsystems: a tensor-parallel-sharded
        flagship train state checkpoints and restores onto a DIFFERENT
        tp degree (4 -> 2), re-sharding from the template — then
        training continues bit-identically to an uncheckpointed run."""
        from jax.sharding import NamedSharding
        from rlo_tpu.models.transformer import param_pspecs
        from rlo_tpu.parallel.mesh import shard_jit

        cfg = TransformerConfig(vocab=32, d_model=32, n_heads=4,
                                n_layers=1, d_ff=64, dtype="float32")
        params = init_params(jax.random.PRNGKey(0), cfg)
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(0, cfg.vocab, (2, 16)),
                             jnp.int32)
        specs = param_pspecs(cfg, "tp")

        def place(mesh, tree):
            return jax.tree.map(
                lambda x, s: jax.device_put(
                    x, NamedSharding(mesh, s)), tree, specs,
                is_leaf=lambda x: isinstance(x, P))

        mesh4 = make_mesh((4,), ("tp",))
        step4 = shard_jit(
            lambda p, t: train_step(p, t, cfg, lr=1e-2, tp_axis="tp"),
            mesh4, (specs, P()), (specs, P()))
        p4, _ = step4(place(mesh4, params), tokens)
        ck.save_pytree(str(tmp_path / "tp"), p4)

        mesh2 = make_mesh((2,), ("tp",))
        like = jax.tree.map(
            lambda x, s: jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=NamedSharding(mesh2, s)),
            params, specs, is_leaf=lambda x: isinstance(x, P))
        restored = ck.restore_pytree(str(tmp_path / "tp"), like)
        # values survive the re-shard
        for a, b in zip(jax.tree.leaves(restored), jax.tree.leaves(p4)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # and training continues on the new mesh, matching the step a
        # never-checkpointed run would take
        step2 = shard_jit(
            lambda p, t: train_step(p, t, cfg, lr=1e-2, tp_axis="tp"),
            mesh2, (specs, P()), (specs, P()))
        cont, _ = step2(restored, tokens)
        want, _ = step2(place(mesh2, jax.tree.map(np.asarray, p4)),
                        tokens)
        for a, b in zip(jax.tree.leaves(cont), jax.tree.leaves(want)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestEngineSnapshot:
    def test_snapshot_restore_counters(self, tmp_path):
        world = LoopbackWorld(4)
        engines = [ProgressEngine(world.transport(r)) for r in range(4)]
        engines[1].bcast(b"hello")
        engines[3].bcast(b"again")
        drain([world], engines)
        for e in engines:
            while e.pickup_next() is not None:
                pass
        path = str(tmp_path / "engines.json")
        ck.save_engine_state(path, engines)
        snaps = ck.load_engine_state_file(path)
        for e in engines:
            e.cleanup()

        world2 = LoopbackWorld(4)
        fresh = [ProgressEngine(world2.transport(r)) for r in range(4)]
        for e, s in zip(fresh, snaps):
            ck.load_engine_state(e, s)
        assert fresh[1].sent_bcast_cnt == 1
        assert fresh[3].sent_bcast_cnt == 1
        assert fresh[0].recved_bcast_cnt == 2
        # resumed engines keep working
        fresh[2].bcast(b"after-resume")
        drain([world2], fresh)
        assert fresh[2].sent_bcast_cnt == 1
        assert fresh[0].recved_bcast_cnt == 3
        for e in fresh:
            e.cleanup()

    def test_snapshot_rejects_busy_engine(self):
        world = LoopbackWorld(2)
        engines = [ProgressEngine(world.transport(r)) for r in range(2)]
        engines[0].queue_wait.append(object())  # simulate in-flight send
        with pytest.raises(RuntimeError, match="drain"):
            ck.engine_state_dict(engines[0])
        engines[0].queue_wait.clear()
        for e in engines:
            e.cleanup()

    def test_snapshot_carries_pickup_queue(self, tmp_path):
        """Delivered-but-unpicked messages survive a snapshot/restore, so
        an application resumes with its pickup queue intact."""
        world = LoopbackWorld(3)
        engines = [ProgressEngine(world.transport(r)) for r in range(3)]
        engines[0].bcast(b"undelivered-payload")
        drain([world], engines)
        snap = ck.engine_state_dict(engines[2])  # NOT picked up yet
        for e in engines:
            e.cleanup()
        world2 = LoopbackWorld(3)
        fresh = ProgressEngine(world2.transport(2))
        ck.load_engine_state(fresh, snap)
        msg = fresh.pickup_next()
        assert msg is not None and msg.data == b"undelivered-payload"
        assert msg.origin == 0
        assert fresh.pickup_next() is None
        fresh.cleanup()

    def test_snapshot_rejects_mid_consensus(self):
        """An own proposal awaiting votes cannot be checkpointed — the
        votes would arrive at a process that no longer exists. Split
        managers so the proposer's sends complete (idle) while the peers
        have not judged yet: the mid-consensus gate, not the in-flight
        gate, must catch this."""
        from rlo_tpu.engine import EngineManager
        world = LoopbackWorld(4)
        mgr_p, mgr_o = EngineManager(), EngineManager()
        proposer = ProgressEngine(world.transport(0), manager=mgr_p)
        others = [ProgressEngine(world.transport(r), manager=mgr_o)
                  for r in range(1, 4)]
        rc = proposer.submit_proposal(b"p", pid=0)
        assert rc == -1 and proposer.idle()  # sends done, votes pending
        with pytest.raises(RuntimeError, match="mid-consensus"):
            ck.engine_state_dict(proposer)
        for _ in range(1000):
            mgr_o.progress_all()
            mgr_p.progress_all()
            if proposer.vote_my_proposal() != -1:
                break
        assert proposer.vote_my_proposal() == 1
        drain([world], [proposer] + others)
        for e in [proposer] + others:
            e.cleanup()

    def test_native_engine_snapshot_roundtrip(self):
        """The C engine's snapshot mirrors the Python one: counters
        survive a world teardown/rebuild and the engine keeps working."""
        from rlo_tpu.native.bindings import NativeEngine, NativeWorld
        with NativeWorld(4) as world:
            engines = [NativeEngine(world, r) for r in range(4)]
            engines[1].bcast(b"hello")
            world.drain()
            for e in engines:
                while e.pickup_next() is not None:
                    pass
            snaps = [e.state_dict() for e in engines]
        assert snaps[1]["sent_bcast"] == 1
        assert snaps[0]["recved_bcast"] == 1
        with NativeWorld(4) as world2:
            fresh = [NativeEngine(world2, r) for r in range(4)]
            for e, s in zip(fresh, snaps):
                e.load_state_dict(s)
            assert fresh[1].sent_bcast_cnt == 1
            fresh[2].bcast(b"after-resume")
            world2.drain()
            assert fresh[0].recved_bcast_cnt == 2
            with pytest.raises(ValueError, match="mismatch"):
                fresh[0].load_state_dict(snaps[1])

    def test_native_snapshot_rejects_busy(self):
        from rlo_tpu.native.bindings import NativeEngine, NativeWorld
        with NativeWorld(4) as world:
            engines = [NativeEngine(world, r) for r in range(4)]
            engines[0].bcast(b"x")
            world.drain()  # delivered but NOT picked up on 1..3
            with pytest.raises(RuntimeError, match="drain and pick up"):
                engines[2].state_dict()

    def test_snapshot_rank_mismatch(self):
        world = LoopbackWorld(2)
        engines = [ProgressEngine(world.transport(r)) for r in range(2)]
        snap = ck.engine_state_dict(engines[0])
        with pytest.raises(ValueError, match="rank"):
            ck.load_engine_state(engines[1], snap)
        for e in engines:
            e.cleanup()
