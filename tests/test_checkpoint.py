"""Checkpoint/resume subsystem (rlo_tpu.utils.checkpoint).

The reference has no checkpointing (SURVEY.md §5); these tests define the
rebuild's contract: sharded pytree round-trips, retention, bit-exact
resume-training equivalence, and quiesced engine snapshot/restore.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from rlo_tpu.engine import ProgressEngine, drain
from rlo_tpu.models.transformer import (TransformerConfig, init_params,
                                        train_step)
from rlo_tpu.parallel.mesh import make_mesh
from rlo_tpu.transport.loopback import LoopbackWorld
from rlo_tpu.utils import checkpoint as ck

WS = 8


@pytest.fixture(scope="module")
def mesh():
    return make_mesh((WS,), ("x",))


def sharded_tree(mesh):
    sh = NamedSharding(mesh, P("x"))
    return {
        "params": {"w": jax.device_put(
            jnp.arange(float(WS * 4)).reshape(WS, 4), sh)},
        "step": jnp.int32(7),
    }


class TestPytreeRoundTrip:
    @pytest.mark.parametrize("backend", ["orbax", "npz"])
    def test_round_trip_preserves_values_and_sharding(self, mesh, tmp_path,
                                                      backend):
        tree = sharded_tree(mesh)
        path = str(tmp_path / "ckpt")
        ck.save_pytree(path, tree, backend=backend)
        out = ck.restore_pytree(path, like=tree)
        np.testing.assert_array_equal(np.asarray(out["params"]["w"]),
                                      np.asarray(tree["params"]["w"]))
        assert int(out["step"]) == 7
        assert out["params"]["w"].sharding == tree["params"]["w"].sharding

    def test_restore_onto_different_sharding(self, mesh, tmp_path):
        """Template controls placement: save sharded, restore replicated."""
        tree = sharded_tree(mesh)
        path = str(tmp_path / "ckpt")
        ck.save_pytree(path, tree)
        like = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(
                a.shape, a.dtype, sharding=NamedSharding(mesh, P())), tree)
        out = ck.restore_pytree(path, like=like)
        assert out["params"]["w"].sharding.spec == P()
        np.testing.assert_array_equal(np.asarray(out["params"]["w"]),
                                      np.asarray(tree["params"]["w"]))

    def test_npz_requires_template(self, tmp_path):
        path = str(tmp_path / "ckpt")
        ck.save_pytree(path, {"a": np.ones(3)}, backend="npz")
        with pytest.raises(ValueError, match="template"):
            ck._npz_restore(path, None)

    def test_npz_missing_leaf(self, tmp_path):
        path = str(tmp_path / "ckpt")
        ck.save_pytree(path, {"a": np.ones(3)}, backend="npz")
        with pytest.raises(KeyError, match="missing"):
            ck.restore_pytree(path, like={"a": np.ones(3), "b": np.ones(2)})


class TestManager:
    def test_retention_and_latest(self, tmp_path):
        mgr = ck.CheckpointManager(str(tmp_path / "run"), max_to_keep=3,
                                   backend="npz")
        for step in (1, 2, 5, 9, 10):
            mgr.save(step, {"x": np.full(2, float(step))})
        assert mgr.all_steps() == [5, 9, 10]
        assert mgr.latest_step() == 10
        out = mgr.restore(like={"x": np.zeros(2)})
        np.testing.assert_array_equal(out["x"], [10.0, 10.0])
        out5 = mgr.restore(step=5, like={"x": np.zeros(2)})
        np.testing.assert_array_equal(out5["x"], [5.0, 5.0])

    def test_restore_empty_raises(self, tmp_path):
        mgr = ck.CheckpointManager(str(tmp_path / "empty"))
        with pytest.raises(FileNotFoundError):
            mgr.restore()


class TestResumeTraining:
    def test_resume_matches_uninterrupted(self, tmp_path):
        """Train 4 steps straight vs train 2, checkpoint, restore into a
        fresh pytree, train 2 more — parameters must match bit-exactly."""
        cfg = TransformerConfig(vocab=32, d_model=32, n_heads=2, n_layers=1,
                                d_ff=64, dtype="float32")
        params = init_params(jax.random.PRNGKey(0), cfg)
        rng = np.random.default_rng(0)
        batches = [jnp.asarray(rng.integers(0, cfg.vocab, (2, 16)), jnp.int32)
                   for _ in range(4)]
        step = jax.jit(lambda p, t: train_step(p, t, cfg, lr=1e-2))

        straight = params
        for b in batches:
            straight, _ = step(straight, b)

        half = params
        for b in batches[:2]:
            half, _ = step(half, b)
        mgr = ck.CheckpointManager(str(tmp_path / "run"))
        mgr.save(2, {"params": half, "step": jnp.int32(2)})

        restored = mgr.restore(like={"params": half, "step": jnp.int32(0)})
        assert int(restored["step"]) == 2
        resumed = restored["params"]
        for b in batches[2:]:
            resumed, _ = step(resumed, b)

        for a, b in zip(jax.tree.leaves(straight), jax.tree.leaves(resumed)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestEngineSnapshot:
    def test_snapshot_restore_counters(self, tmp_path):
        world = LoopbackWorld(4)
        engines = [ProgressEngine(world.transport(r)) for r in range(4)]
        engines[1].bcast(b"hello")
        engines[3].bcast(b"again")
        drain([world], engines)
        for e in engines:
            while e.pickup_next() is not None:
                pass
        path = str(tmp_path / "engines.json")
        ck.save_engine_state(path, engines)
        snaps = ck.load_engine_state_file(path)
        for e in engines:
            e.cleanup()

        world2 = LoopbackWorld(4)
        fresh = [ProgressEngine(world2.transport(r)) for r in range(4)]
        for e, s in zip(fresh, snaps):
            ck.load_engine_state(e, s)
        assert fresh[1].sent_bcast_cnt == 1
        assert fresh[3].sent_bcast_cnt == 1
        assert fresh[0].recved_bcast_cnt == 2
        # resumed engines keep working
        fresh[2].bcast(b"after-resume")
        drain([world2], fresh)
        assert fresh[2].sent_bcast_cnt == 1
        assert fresh[0].recved_bcast_cnt == 3
        for e in fresh:
            e.cleanup()

    def test_snapshot_rejects_busy_engine(self):
        world = LoopbackWorld(2)
        engines = [ProgressEngine(world.transport(r)) for r in range(2)]
        engines[0].queue_wait.append(object())  # simulate in-flight send
        with pytest.raises(RuntimeError, match="drain"):
            ck.engine_state_dict(engines[0])
        engines[0].queue_wait.clear()
        for e in engines:
            e.cleanup()

    def test_snapshot_rank_mismatch(self):
        world = LoopbackWorld(2)
        engines = [ProgressEngine(world.transport(r)) for r in range(2)]
        snap = ck.engine_state_dict(engines[0])
        with pytest.raises(ValueError, match="rank"):
            ck.load_engine_state(engines[1], snap)
        for e in engines:
            e.cleanup()
