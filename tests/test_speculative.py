"""Speculative decoding (models.speculative) — the lossless oracle.

Greedy speculative decode must equal plain greedy decode token for
token, for ANY draft: a worthless draft only slows it down (every
round still emits the target's own next prediction), a perfect draft
only speeds it up. The tests drive the rejection-heavy path (random
draft), the full-acceptance path (draft == target), and a partial
path (perturbed target), across dense / GQA+rope / int8-cache
configs and gamma in {1, 3, 8}.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rlo_tpu.models.generate import (block_decode, decode_step,
                                     generate, init_kv_cache)
from rlo_tpu.models.speculative import speculative_generate
from rlo_tpu.models.transformer import TransformerConfig, init_params

CFG = TransformerConfig(vocab=61, d_model=32, n_heads=4, n_layers=2,
                        d_ff=64, dtype="float32")
DRAFT = TransformerConfig(vocab=61, d_model=16, n_heads=2, n_layers=1,
                          d_ff=32, dtype="float32")


@pytest.fixture(scope="module")
def setup():
    params = init_params(jax.random.PRNGKey(0), CFG)
    dparams = init_params(jax.random.PRNGKey(5), DRAFT)
    rng = np.random.default_rng(1)
    prompt = jnp.asarray(rng.integers(0, CFG.vocab, (3, 6)), jnp.int32)
    return params, dparams, prompt


@pytest.mark.parametrize("gamma", [1, 3, 8])
def test_lossless_random_draft(setup, gamma):
    """Rejection-heavy: an untrained draft agrees ~1/vocab of the
    time; output must still be exactly the target's greedy tokens."""
    params, dparams, prompt = setup
    want = np.asarray(generate(params, prompt, CFG, max_new=10))
    got = np.asarray(speculative_generate(
        params, dparams, prompt, CFG, DRAFT, max_new=10, gamma=gamma))
    np.testing.assert_array_equal(got, want)


def test_lossless_self_draft(setup):
    """Full-acceptance: draft == target accepts every proposal; the
    all-gamma-accepted bookkeeping (bonus == d_gamma) must hold."""
    params, _, prompt = setup
    want = np.asarray(generate(params, prompt, CFG, max_new=12))
    got = np.asarray(speculative_generate(
        params, params, prompt, CFG, CFG, max_new=12, gamma=4))
    np.testing.assert_array_equal(got, want)


def test_lossless_perturbed_draft(setup):
    """Partial acceptance: target + noise agrees on easy tokens and
    diverges on hard ones — the mixed accept/reject path."""
    params, _, prompt = setup
    noisy = jax.tree.map(
        lambda p: p + 0.03 * jax.random.normal(
            jax.random.PRNGKey(9), p.shape, p.dtype), params)
    want = np.asarray(generate(params, prompt, CFG, max_new=10))
    got = np.asarray(speculative_generate(
        params, noisy, prompt, CFG, CFG, max_new=10, gamma=4))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("variant", ["gqa_rope", "int8"])
def test_lossless_variants(setup, variant):
    _, _, prompt = setup
    cfg = CFG
    if variant == "gqa_rope":
        cfg = dataclasses.replace(CFG, n_kv_heads=2,
                                  pos_encoding="rope")
    else:
        cfg = dataclasses.replace(CFG, kv_cache_dtype="int8")
    dcfg = dataclasses.replace(DRAFT,
                               kv_cache_dtype=cfg.kv_cache_dtype)
    params = init_params(jax.random.PRNGKey(11), cfg)
    dparams = init_params(jax.random.PRNGKey(12), dcfg)
    want = np.asarray(generate(params, prompt, cfg, max_new=9))
    got = np.asarray(speculative_generate(
        params, dparams, prompt, cfg, dcfg, max_new=9, gamma=3))
    np.testing.assert_array_equal(got, want)


def test_jittable(setup):
    params, dparams, prompt = setup
    f = jax.jit(lambda p, d, t: speculative_generate(
        p, d, t, CFG, DRAFT, max_new=8, gamma=3))
    want = np.asarray(generate(params, prompt, CFG, max_new=8))
    np.testing.assert_array_equal(np.asarray(f(params, dparams,
                                               prompt)), want)


def test_argument_errors(setup):
    params, dparams, prompt = setup
    bad = dataclasses.replace(DRAFT, vocab=17)
    with pytest.raises(ValueError, match="vocab"):
        speculative_generate(params, dparams, prompt, CFG, bad,
                             max_new=4)
    with pytest.raises(ValueError, match="gamma"):
        speculative_generate(params, dparams, prompt, CFG, DRAFT,
                             max_new=4, gamma=0)
    with pytest.raises(ValueError, match="max_len"):
        speculative_generate(params, dparams, prompt, CFG, DRAFT,
                             max_new=4, gamma=2, max_len=8)


def test_sampling_needs_rng(setup):
    params, dparams, prompt = setup
    with pytest.raises(ValueError, match="rng"):
        speculative_generate(params, dparams, prompt, CFG, DRAFT,
                             max_new=4, temperature=0.7)


def test_sampling_self_draft_accepts_everything(setup):
    """draft == target at the same temperature: p_t == p_d up to the
    T=1-vs-block einsum association, so acceptance u < pt/pd ~ 1 is
    (near-)certain and the loop takes the minimum number of rounds."""
    params, _, prompt = setup
    max_new, gamma = 13, 4
    out, rounds = speculative_generate(
        params, params, prompt, CFG, CFG, max_new=max_new, gamma=gamma,
        temperature=0.8, rng=jax.random.PRNGKey(3), return_rounds=True)
    assert out.shape == (3, max_new)
    assert (np.asarray(out) >= 0).all()
    assert (np.asarray(out) < CFG.vocab).all()
    # 1 prefill token + gamma/round: ceil(12 / 4) = 3 rounds (+1 slack
    # for a last-bit fp rejection between the two einsum shapes)
    assert int(rounds) <= -(-(max_new - 1) // gamma) + 1


def test_sampling_matches_target_distribution():
    """The rejection scheme's output must be distributed EXACTLY like
    plain temperature sampling from the target. Position 0 samples
    from the prefill logits directly; position 1's exact marginal is
    enumerable on a tiny vocab: p1(w) = sum_t0 p0(t0) p(w | t0).
    Compare the speculative empirical marginal (heavy rejection path:
    an unrelated random draft) against that exact distribution."""
    vocab = 23
    cfg = TransformerConfig(vocab=vocab, d_model=16, n_heads=2,
                            n_layers=2, d_ff=32, dtype="float32")
    dcfg = TransformerConfig(vocab=vocab, d_model=8, n_heads=1,
                             n_layers=1, d_ff=16, dtype="float32")
    params = init_params(jax.random.PRNGKey(0), cfg)
    dparams = init_params(jax.random.PRNGKey(1), dcfg)
    temp = 1.3
    prompt = jnp.asarray([[5, 11, 2]], jnp.int32)

    # exact marginals from the target alone
    cache = init_kv_cache(cfg, 1, 8)
    logits0, cache = __import__("rlo_tpu.models.generate",
                                fromlist=["prefill"]).prefill(
        params, prompt, cache, cfg)
    p0 = jax.nn.softmax(logits0[0] / temp)                 # (V,)

    def next_probs(t0):
        lg, _ = decode_step(params, jnp.asarray([t0], jnp.int32), 3,
                            cache, cfg)
        return jax.nn.softmax(lg[0] / temp)

    P1 = jax.vmap(next_probs)(jnp.arange(vocab))           # (V, V)
    p1_exact = np.asarray(p0 @ P1)

    n = 4096
    f = jax.jit(jax.vmap(lambda key: speculative_generate(
        params, dparams, prompt, cfg, dcfg, max_new=2, gamma=3,
        temperature=temp, rng=key)[0]))
    outs = np.asarray(f(jax.random.split(jax.random.PRNGKey(7), n)))
    for posn, exact in ((0, np.asarray(p0)), (1, p1_exact)):
        emp = np.bincount(outs[:, posn], minlength=vocab) / n
        tv = 0.5 * np.abs(emp - exact).sum()
        assert tv < 0.07, (posn, tv)


def test_sampling_lossless_vs_plain_sampling_stats():
    """Same check against plain generate's own empirical marginals —
    the two samplers must be statistically indistinguishable."""
    vocab = 23
    cfg = TransformerConfig(vocab=vocab, d_model=16, n_heads=2,
                            n_layers=2, d_ff=32, dtype="float32")
    dcfg = TransformerConfig(vocab=vocab, d_model=8, n_heads=1,
                             n_layers=1, d_ff=16, dtype="float32")
    params = init_params(jax.random.PRNGKey(0), cfg)
    dparams = init_params(jax.random.PRNGKey(1), dcfg)
    temp, n, max_new = 0.9, 4096, 3
    prompt = jnp.asarray([[1, 7]], jnp.int32)
    f_spec = jax.jit(jax.vmap(lambda key: speculative_generate(
        params, dparams, prompt, cfg, dcfg, max_new=max_new, gamma=2,
        temperature=temp, rng=key)[0]))
    f_plain = jax.jit(jax.vmap(lambda key: generate(
        params, prompt, cfg, max_new=max_new, temperature=temp,
        rng=key)[0]))
    keys_a = jax.random.split(jax.random.PRNGKey(21), n)
    keys_b = jax.random.split(jax.random.PRNGKey(22), n)
    a = np.asarray(f_spec(keys_a))
    bb = np.asarray(f_plain(keys_b))
    for posn in range(max_new):
        ea = np.bincount(a[:, posn], minlength=vocab) / n
        eb = np.bincount(bb[:, posn], minlength=vocab) / n
        tv = 0.5 * np.abs(ea - eb).sum()
        assert tv < 0.09, (posn, tv)


@pytest.mark.parametrize("variant", ["dense", "gqa_rope", "int8"])
def test_block_decode_matches_sequential(variant):
    """block_decode (the verify primitive) == T sequential
    decode_steps: logits at every position and the final cache."""
    cfg = CFG
    if variant == "gqa_rope":
        cfg = dataclasses.replace(cfg, n_kv_heads=2,
                                  pos_encoding="rope")
    elif variant == "int8":
        cfg = dataclasses.replace(cfg, kv_cache_dtype="int8")
    params = init_params(jax.random.PRNGKey(2), cfg)
    rng = np.random.default_rng(3)
    b, T, L = 2, 4, 12
    toks = jnp.asarray(rng.integers(0, cfg.vocab, (b, 3 + T)),
                       jnp.int32)
    cache_a = init_kv_cache(cfg, b, L)
    cache_b = init_kv_cache(cfg, b, L)
    for pos in range(3):
        _, cache_a = decode_step(params, toks[:, pos], pos, cache_a,
                                 cfg)
        _, cache_b = decode_step(params, toks[:, pos], pos, cache_b,
                                 cfg)
    blk, cache_a = block_decode(params, toks[:, 3:], jnp.asarray([3, 3]),
                                cache_a, cfg)
    seq = []
    for i in range(T):
        lb, cache_b = decode_step(params, toks[:, 3 + i], 3 + i,
                                  cache_b, cfg)
        seq.append(np.asarray(lb))
    np.testing.assert_allclose(np.asarray(blk), np.stack(seq, 1),
                               rtol=2e-4, atol=2e-4)
    for ca, cb in zip(cache_a, cache_b):
        for key in ca:
            np.testing.assert_allclose(
                np.asarray(ca[key], np.float32),
                np.asarray(cb[key], np.float32), rtol=1e-5, atol=1e-5)
