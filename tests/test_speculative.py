"""Speculative decoding (models.speculative) — the lossless oracle.

Greedy speculative decode must equal plain greedy decode token for
token, for ANY draft: a worthless draft only slows it down (every
round still emits the target's own next prediction), a perfect draft
only speeds it up. The tests drive the rejection-heavy path (random
draft), the full-acceptance path (draft == target), and a partial
path (perturbed target), across dense / GQA+rope / int8-cache
configs and gamma in {1, 3, 8}.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rlo_tpu.models.generate import (block_decode, decode_step,
                                     generate, init_kv_cache)
from rlo_tpu.models.speculative import speculative_generate
from rlo_tpu.models.transformer import TransformerConfig, init_params

CFG = TransformerConfig(vocab=61, d_model=32, n_heads=4, n_layers=2,
                        d_ff=64, dtype="float32")
DRAFT = TransformerConfig(vocab=61, d_model=16, n_heads=2, n_layers=1,
                          d_ff=32, dtype="float32")


@pytest.fixture(scope="module")
def setup():
    params = init_params(jax.random.PRNGKey(0), CFG)
    dparams = init_params(jax.random.PRNGKey(5), DRAFT)
    rng = np.random.default_rng(1)
    prompt = jnp.asarray(rng.integers(0, CFG.vocab, (3, 6)), jnp.int32)
    return params, dparams, prompt


@pytest.mark.parametrize("gamma", [1, 3, 8])
def test_lossless_random_draft(setup, gamma):
    """Rejection-heavy: an untrained draft agrees ~1/vocab of the
    time; output must still be exactly the target's greedy tokens."""
    params, dparams, prompt = setup
    want = np.asarray(generate(params, prompt, CFG, max_new=10))
    got = np.asarray(speculative_generate(
        params, dparams, prompt, CFG, DRAFT, max_new=10, gamma=gamma))
    np.testing.assert_array_equal(got, want)


def test_lossless_self_draft(setup):
    """Full-acceptance: draft == target accepts every proposal; the
    all-gamma-accepted bookkeeping (bonus == d_gamma) must hold."""
    params, _, prompt = setup
    want = np.asarray(generate(params, prompt, CFG, max_new=12))
    got = np.asarray(speculative_generate(
        params, params, prompt, CFG, CFG, max_new=12, gamma=4))
    np.testing.assert_array_equal(got, want)


def test_lossless_perturbed_draft(setup):
    """Partial acceptance: target + noise agrees on easy tokens and
    diverges on hard ones — the mixed accept/reject path."""
    params, _, prompt = setup
    noisy = jax.tree.map(
        lambda p: p + 0.03 * jax.random.normal(
            jax.random.PRNGKey(9), p.shape, p.dtype), params)
    want = np.asarray(generate(params, prompt, CFG, max_new=10))
    got = np.asarray(speculative_generate(
        params, noisy, prompt, CFG, CFG, max_new=10, gamma=4))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("variant", ["gqa_rope", "int8"])
def test_lossless_variants(setup, variant):
    _, _, prompt = setup
    cfg = CFG
    if variant == "gqa_rope":
        cfg = dataclasses.replace(CFG, n_kv_heads=2,
                                  pos_encoding="rope")
    else:
        cfg = dataclasses.replace(CFG, kv_cache_dtype="int8")
    dcfg = dataclasses.replace(DRAFT,
                               kv_cache_dtype=cfg.kv_cache_dtype)
    params = init_params(jax.random.PRNGKey(11), cfg)
    dparams = init_params(jax.random.PRNGKey(12), dcfg)
    want = np.asarray(generate(params, prompt, cfg, max_new=9))
    got = np.asarray(speculative_generate(
        params, dparams, prompt, cfg, dcfg, max_new=9, gamma=3))
    np.testing.assert_array_equal(got, want)


def test_jittable(setup):
    params, dparams, prompt = setup
    f = jax.jit(lambda p, d, t: speculative_generate(
        p, d, t, CFG, DRAFT, max_new=8, gamma=3))
    want = np.asarray(generate(params, prompt, CFG, max_new=8))
    np.testing.assert_array_equal(np.asarray(f(params, dparams,
                                               prompt)), want)


def test_argument_errors(setup):
    params, dparams, prompt = setup
    bad = dataclasses.replace(DRAFT, vocab=17)
    with pytest.raises(ValueError, match="vocab"):
        speculative_generate(params, dparams, prompt, CFG, bad,
                             max_new=4)
    with pytest.raises(ValueError, match="gamma"):
        speculative_generate(params, dparams, prompt, CFG, DRAFT,
                             max_new=4, gamma=0)
    with pytest.raises(ValueError, match="max_len"):
        speculative_generate(params, dparams, prompt, CFG, DRAFT,
                             max_new=4, gamma=2, max_len=8)


@pytest.mark.parametrize("variant", ["dense", "gqa_rope", "int8"])
def test_block_decode_matches_sequential(variant):
    """block_decode (the verify primitive) == T sequential
    decode_steps: logits at every position and the final cache."""
    cfg = CFG
    if variant == "gqa_rope":
        cfg = dataclasses.replace(cfg, n_kv_heads=2,
                                  pos_encoding="rope")
    elif variant == "int8":
        cfg = dataclasses.replace(cfg, kv_cache_dtype="int8")
    params = init_params(jax.random.PRNGKey(2), cfg)
    rng = np.random.default_rng(3)
    b, T, L = 2, 4, 12
    toks = jnp.asarray(rng.integers(0, cfg.vocab, (b, 3 + T)),
                       jnp.int32)
    cache_a = init_kv_cache(cfg, b, L)
    cache_b = init_kv_cache(cfg, b, L)
    for pos in range(3):
        _, cache_a = decode_step(params, toks[:, pos], pos, cache_a,
                                 cfg)
        _, cache_b = decode_step(params, toks[:, pos], pos, cache_b,
                                 cfg)
    blk, cache_a = block_decode(params, toks[:, 3:], jnp.asarray([3, 3]),
                                cache_a, cfg)
    seq = []
    for i in range(T):
        lb, cache_b = decode_step(params, toks[:, 3 + i], 3 + i,
                                  cache_b, cfg)
        seq.append(np.asarray(lb))
    np.testing.assert_allclose(np.asarray(blk), np.stack(seq, 1),
                               rtol=2e-4, atol=2e-4)
    for ca, cb in zip(cache_a, cache_b):
        for key in ca:
            np.testing.assert_allclose(
                np.asarray(ca[key], np.float32),
                np.asarray(cb[key], np.float32), rtol=1e-5, atol=1e-5)
