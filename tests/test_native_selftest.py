"""Tier-1 sanitizer leg: build the native core with
-fsanitize=address,undefined and run rlo_selftest under it.

check.sh has always run the ASan/UBSan selftest, but check.sh is not
tier-1 — this wrapper puts the sanitized C engine (including the new
ARQ ack/retransmit paths, the loss/dup fault-injection plumbing, and
the forked TCP peer-death scenario) into the plain pytest run, so a
leak/UAF/UB regression in the C core fails CI and not just the manual
one-shot script.
"""

import shutil
import subprocess
from pathlib import Path

import pytest

NATIVE = Path(__file__).resolve().parent.parent / "rlo_tpu" / "native"


def _sanitizers_available(cc: str) -> bool:
    probe = subprocess.run(
        [cc, "-xc", "-", "-fsanitize=address,undefined", "-o",
         "/dev/null"],
        input="int main(void){return 0;}\n",
        capture_output=True, text=True)
    return probe.returncode == 0


def test_native_selftest_sanitizer_clean():
    cc = shutil.which("cc")
    if cc is None:
        pytest.skip("no C compiler in this environment")
    if shutil.which("make") is None:
        pytest.skip("no make in this environment")
    if not _sanitizers_available("cc"):
        pytest.skip("cc cannot link -fsanitize=address,undefined")
    build = subprocess.run(["make", "-s", "selftest"], cwd=NATIVE,
                           capture_output=True, text=True, timeout=300)
    assert build.returncode == 0, \
        f"sanitized selftest build failed:\n{build.stdout}\n{build.stderr}"
    run = subprocess.run([str(NATIVE / "rlo_selftest")], cwd=NATIVE,
                         capture_output=True, text=True, timeout=300)
    assert run.returncode == 0, \
        f"rlo_selftest failed under ASan/UBSan:\n{run.stdout}\n{run.stderr}"
    # UBSan reports land on stderr without changing the exit code
    # unless -fno-sanitize-recover; treat any runtime report as a fail
    assert "runtime error" not in run.stderr, run.stderr
    assert "AddressSanitizer" not in run.stderr, run.stderr
