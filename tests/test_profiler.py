"""In-engine phase profiler (docs/DESIGN.md §10).

Covers the three contracts the performance observatory rests on:

  1. cross-engine schema parity — ProgressEngine.metrics()["phases"]
     and NativeEngine.metrics()["phases"] emit the IDENTICAL nested
     schema (the ENGINE_PHASE_KEYS order mirrored by rlo_phase_stats),
     with matching deterministic counts for the per-op phases;
  2. the disabled-path overhead contract — off by default, zero
     collection while off, and a (generously bounded) wall-clock smoke
     showing the disabled run does not cost more than the enabled one;
  3. timeline integration — Ev.PHASE samples render as validated
     Chrome duration slices alongside the PR-2 flow edges.
"""

import time

import pytest

from rlo_tpu.engine import EngineManager, ProgressEngine, drain
from rlo_tpu.transport.loopback import LoopbackWorld
from rlo_tpu.utils.metrics import ENGINE_PHASE_KEYS, HIST_BUCKETS
from rlo_tpu.utils.tracing import TRACER, Ev

WS = 4


def _drive_python(profiler: bool):
    world = LoopbackWorld(WS, latency=2, seed=7)
    mgr = EngineManager()
    engines = [ProgressEngine(world.transport(r), manager=mgr,
                              arq_rto=0.05) for r in range(WS)]
    if profiler:
        for e in engines:
            e.enable_profiler()
    for r in range(WS):
        engines[r].bcast(f"m{r}".encode())
    drain([world], engines)
    for e in engines:
        while e.pickup_next() is not None:
            pass
    if engines[1].submit_proposal(b"prop", pid=11) == -1:
        drain([world], engines)
        assert engines[1].vote_my_proposal() in (0, 1)
    for e in engines:
        while e.pickup_next() is not None:
            pass
    snaps = [e.metrics() for e in engines]
    for e in engines:
        e.cleanup()
    return snaps


def _drive_native(profiler: bool):
    from rlo_tpu.native.bindings import NativeEngine, NativeWorld

    with NativeWorld(WS, latency=2, seed=7) as world:
        engines = [NativeEngine(world, r) for r in range(WS)]
        for e in engines:
            e.enable_arq(50_000)
            if profiler:
                e.enable_profiler()
        for r in range(WS):
            engines[r].bcast(f"m{r}".encode())
        world.drain()
        for e in engines:
            while e.pickup_next() is not None:
                pass
        if engines[1].submit_proposal(b"prop", pid=11) == -1:
            world.drain()
            assert engines[1].vote_my_proposal() in (0, 1)
        for e in engines:
            while e.pickup_next() is not None:
                pass
        return [e.metrics() for e in engines]


def _schema(snap_phases):
    """(phase key -> histogram field names) — the structural shape."""
    return {k: sorted(v) for k, v in snap_phases.items()}


class TestSchemaParity:
    def test_python_and_native_phase_schema_identical(self):
        """The profiler twin of
        test_python_and_native_report_identical_metrics: same scenario,
        both engines, identical phase keys, histogram layout, and
        deterministic per-op counts."""
        py = _drive_python(profiler=True)
        nat = _drive_native(profiler=True)
        for r in range(WS):
            pp, np_ = py[r]["phases"], nat[r]["phases"]
            assert tuple(pp) == tuple(np_) == ENGINE_PHASE_KEYS
            assert _schema(pp) == _schema(np_)
            for k in ENGINE_PHASE_KEYS:
                assert len(pp[k]["buckets"]) == HIST_BUCKETS
                assert len(np_[k]["buckets"]) == HIST_BUCKETS
            # per-op phases are scenario-deterministic: each rank
            # initiated exactly one broadcast, so both timers fired
            # exactly once on both engines
            assert pp["bcast_all_delivered"]["count"] == 1
            assert np_["bcast_all_delivered"]["count"] == 1
            assert pp["bcast_first_fwd"]["count"] == 1
            assert np_["bcast_first_fwd"]["count"] == 1
            # hot-path stages saw real traffic on both engines
            for k in ("frame_decode", "send", "tag_dispatch",
                      "pickup_drain", "arq_scan"):
                assert pp[k]["count"] > 0, k
                assert np_[k]["count"] > 0, k
        # the proposer resolved its round: both proposal phases fired
        assert py[1]["phases"]["prop_votes_aggregated"]["count"] == 1
        assert nat[1]["phases"]["prop_votes_aggregated"]["count"] == 1
        assert py[1]["phases"]["prop_decision"]["count"] == 1
        assert nat[1]["phases"]["prop_decision"]["count"] == 1

    def test_disabled_phases_identical_across_engines(self):
        """Profiler off (the default): both engines report the same
        all-zero phase block — one schema, not two."""
        py = _drive_python(profiler=False)
        nat = _drive_native(profiler=False)
        for r in range(WS):
            assert py[r]["phases"] == nat[r]["phases"]
            assert all(h["count"] == 0
                       for h in py[r]["phases"].values())


class TestDisabledPath:
    def test_off_by_default_and_toggleable(self):
        world = LoopbackWorld(2)
        mgr = EngineManager()
        engines = [ProgressEngine(world.transport(r), manager=mgr)
                   for r in range(2)]
        engines[0].bcast(b"a")
        drain([world], engines)
        assert all(h["count"] == 0
                   for h in engines[0].metrics()["phases"].values())
        engines[0].enable_profiler()
        engines[0].bcast(b"b")
        drain([world], engines)
        on_counts = {k: h["count"] for k, h in
                     engines[0].metrics()["phases"].items()}
        assert on_counts["bcast_all_delivered"] == 1
        assert on_counts["send"] >= 1
        engines[0].enable_profiler(False)
        engines[0].bcast(b"c")
        drain([world], engines)
        assert {k: h["count"] for k, h in
                engines[0].metrics()["phases"].items()} == on_counts
        for e in engines:
            e.cleanup()

    def test_disabled_overhead_smoke(self):
        """The §10 overhead contract, coarsely: the profiler-off run
        of an identical workload must not be slower than the
        profiler-on run beyond generous noise bounds (off does
        strictly less work per event)."""
        def run(profiler: bool) -> float:
            world = LoopbackWorld(2, latency=0, seed=1)
            mgr = EngineManager()
            engines = [ProgressEngine(world.transport(r), manager=mgr)
                       for r in range(2)]
            if profiler:
                for e in engines:
                    e.enable_profiler()
            t0 = time.perf_counter()
            for _ in range(150):
                engines[0].bcast(b"x" * 64)
                drain([world], engines)
                while engines[1].pickup_next() is not None:
                    pass
            dt = time.perf_counter() - t0
            for e in engines:
                e.cleanup()
            return dt

        run(False)  # warm caches
        off, on = run(False), run(True)
        assert off <= on * 1.5 + 0.5, (off, on)


class TestTimeline:
    def test_phase_samples_render_as_duration_slices(self):
        from rlo_tpu.utils.timeline import (PHASE_NAMES, merge_timeline,
                                            validate_chrome_trace)

        world = LoopbackWorld(2, latency=1, seed=3)
        mgr = EngineManager()
        engines = [ProgressEngine(world.transport(r), manager=mgr)
                   for r in range(2)]
        for e in engines:
            e.enable_profiler()
        TRACER.clear()
        with TRACER.enable():
            engines[0].bcast(b"slice me")
            drain([world], engines)
            while engines[1].pickup_next() is not None:
                pass
        phase_evs = TRACER.events(Ev.PHASE)
        assert phase_evs, "no Ev.PHASE samples emitted"
        assert all(0 <= e.a < len(ENGINE_PHASE_KEYS) for e in phase_evs)
        assert all(e.b >= 0 for e in phase_evs)
        trace = merge_timeline([[e.to_dict() for e in TRACER.events()]])
        validate_chrome_trace(trace)
        slices = [ev for ev in trace["traceEvents"]
                  if ev.get("cat") == "phase"]
        assert slices
        names = {ev["name"] for ev in slices}
        assert names <= set(PHASE_NAMES)
        assert all(ev["dur"] >= 1 for ev in slices)
        TRACER.clear()
        for e in engines:
            e.cleanup()

    def test_native_phase_events_drain_with_names(self):
        from rlo_tpu.native import bindings

        bindings.trace_clear()
        bindings.trace_set(True)
        try:
            with bindings.NativeWorld(2) as world:
                engines = [bindings.NativeEngine(world, r)
                           for r in range(2)]
                for e in engines:
                    e.enable_profiler()
                engines[0].bcast(b"native slice")
                world.drain()
                while engines[1].pickup_next() is not None:
                    pass
                evs = bindings.trace_drain()
        finally:
            bindings.trace_set(False)
            bindings.trace_clear()
        phases = [e for e in evs if e["kind"] == "PHASE"]
        assert phases, "C engine emitted no PHASE events"
        assert all(0 <= e["a"] < len(ENGINE_PHASE_KEYS)
                   for e in phases)


class TestRegistrySurface:
    def test_histogram_percentile_helpers(self):
        from rlo_tpu.utils.metrics import Histogram, hist_summary

        h = Histogram()
        assert h.p50() is None and h.summary()["p99"] is None
        for v in [1] * 90 + [1000] * 10:
            h.observe(v)
        assert h.p50() == 2.0
        assert h.p90() == 2.0
        assert h.p99() == 1024.0
        s = h.summary()
        assert s["count"] == 100
        assert s["mean"] == pytest.approx((90 + 10 * 1000) / 100)
        assert s["min"] == 1 and s["max"] == 1000
        assert s == hist_summary(h.snapshot())
        assert "buckets" not in s
