"""Wire format round-trips and loopback transport semantics."""

import pytest

from rlo_tpu import wire
from rlo_tpu.transport import make_world
from rlo_tpu.wire import Frame, Tag


class TestFrame:
    def test_roundtrip(self):
        f = Frame(origin=3, pid=7, vote=1, payload=b"hello world")
        assert Frame.decode(f.encode()) == f

    def test_roundtrip_empty_payload(self):
        f = Frame(origin=0)
        raw = f.encode()
        assert len(raw) == wire.HEADER_SIZE
        assert Frame.decode(raw) == f

    def test_variable_size(self):
        # the reference always ships 32 KB (rootless_ops.c:1588); we must not
        small = Frame(origin=1, payload=b"x").encode()
        big = Frame(origin=1, payload=b"x" * 10000).encode()
        assert len(small) == wire.HEADER_SIZE + 1
        assert len(big) == wire.HEADER_SIZE + 10000

    def test_truncated_raises(self):
        raw = Frame(origin=1, payload=b"abcdef").encode()
        with pytest.raises(ValueError):
            Frame.decode(raw[:-1])
        with pytest.raises(ValueError):
            Frame.decode(raw[:3])

    def test_negative_sentinels(self):
        f = Frame(origin=5, pid=-1, vote=-2, payload=b"")
        assert Frame.decode(f.encode()).vote == -2

    def test_seq_roundtrip(self):
        # the ARQ link seq is a first-class header field
        f = Frame(origin=2, pid=9, vote=0, payload=b"data", seq=41)
        g = Frame.decode(f.encode())
        assert g == f and g.seq == 41

    def test_seq_defaults_unstamped(self):
        assert Frame(origin=1).seq == -1
        assert Frame.decode(Frame(origin=1).encode()).seq == -1

    def test_restamp_seq_patches_in_place(self):
        raw = Frame(origin=3, pid=4, vote=5, payload=b"xyz").encode()
        out = wire.restamp_seq(raw, 1234)
        g = Frame.decode(out)
        assert (g.origin, g.pid, g.vote, g.payload, g.seq) == \
            (3, 4, 5, b"xyz", 1234)
        # only the seq bytes differ
        assert out[:wire.SEQ_OFFSET] == raw[:wire.SEQ_OFFSET]
        assert out[wire.SEQ_OFFSET + 4:] == raw[wire.SEQ_OFFSET + 4:]

    def test_decode_empty_and_header_only_truncations(self):
        with pytest.raises(ValueError):
            Frame.decode(b"")
        with pytest.raises(ValueError):
            Frame.decode(b"\x00" * (wire.HEADER_SIZE - 1))

    def test_decode_length_field_overrun_raises(self):
        # a header whose data_len claims more payload than present
        import struct
        raw = struct.pack("<iiiiQ", 0, -1, -1, -1, 100) + b"short"
        with pytest.raises(ValueError):
            Frame.decode(raw)

    def test_decode_ignores_trailing_garbage(self):
        # transports deliver whole frames; extra bytes past data_len
        # are not the payload's problem
        raw = Frame(origin=1, payload=b"ok").encode() + b"JUNK"
        assert Frame.decode(raw).payload == b"ok"

    def test_unknown_tag_rejected_by_tag_enum(self):
        # tags travel out-of-band; the enum is the validity gate
        with pytest.raises(ValueError):
            Tag(99)

    def test_ack_and_abort_tags_exist_and_classify(self):
        assert int(Tag.ACK) == 13 and int(Tag.ABORT) == 14
        assert Tag.ABORT in wire.BCAST_TAGS      # store-and-forward
        assert Tag.ACK not in wire.BCAST_TAGS    # point-to-point only
        assert Tag.ACK in wire.ARQ_EXEMPT_TAGS   # never ARQ-tracked
        assert Tag.HEARTBEAT in wire.ARQ_EXEMPT_TAGS


class TestLoopback:
    def test_basic_delivery(self):
        w = make_world("loopback", 4)
        t0, t3 = w.transport(0), w.transport(3)
        h = t0.isend(3, Tag.BCAST, b"payload")
        assert h.done()
        assert t3.poll() == (0, Tag.BCAST, b"payload")
        assert t3.poll() is None

    def test_fifo_per_pair(self):
        w = make_world("loopback", 2)
        for i in range(10):
            w.transport(0).isend(1, Tag.DATA, bytes([i]))
        got = [w.transport(1).poll()[2][0] for _ in range(10)]
        assert got == list(range(10))

    def test_fifo_preserved_under_latency(self):
        w = make_world("loopback", 2, latency=5, seed=42)
        for i in range(50):
            w.transport(0).isend(1, Tag.DATA, bytes([i]))
        got = []
        t1 = w.transport(1)
        spins = 0
        while len(got) < 50:
            m = t1.poll()
            spins += 1
            assert spins < 10000
            if m:
                got.append(m[2][0])
        assert got == list(range(50))

    def test_latency_handles_complete_eventually(self):
        w = make_world("loopback", 2, latency=3, seed=7)
        h = w.transport(0).isend(1, Tag.BCAST, b"z")
        t1 = w.transport(1)
        got = []
        spins = 0
        while not h.done() or not got:
            m = t1.poll()
            if m:
                got.append(m)
            spins += 1
            assert spins < 1000
        assert got == [(0, Tag.BCAST, b"z")]

    def test_quiescent(self):
        w = make_world("loopback", 3)
        assert w.quiescent()
        w.transport(0).isend(2, Tag.BCAST, b"q")
        assert not w.quiescent()
        w.transport(2).poll()
        assert w.quiescent()

    def test_world_too_small(self):
        # reference rejects ws < 2 at bcomm_init (rootless_ops.c:1464)
        with pytest.raises(ValueError):
            make_world("loopback", 1)

    def test_bad_destination(self):
        w = make_world("loopback", 2)
        with pytest.raises(ValueError):
            w.transport(0).isend(5, Tag.BCAST, b"")

    def test_unknown_backend(self):
        with pytest.raises(ValueError):
            make_world("nope", 4)

    def test_dup_next_delivers_twice(self):
        w = make_world("loopback", 2)
        w.dup_next(0, 1, 1)
        w.transport(0).isend(1, Tag.DATA, b"d")
        w.transport(0).isend(1, Tag.DATA, b"e")  # past the window
        t1 = w.transport(1)
        got = [t1.poll()[2] for _ in range(3)]
        assert got == [b"d", b"d", b"e"]
        assert t1.poll() is None
        assert w.duplicated_cnt == 1

    def test_dup_next_preserves_fifo_under_latency(self):
        w = make_world("loopback", 2, latency=4, seed=9)
        w.dup_next(0, 1, 2)
        for i in range(6):
            w.transport(0).isend(1, Tag.DATA, bytes([i]))
        got = []
        t1 = w.transport(1)
        for _ in range(10_000):
            m = t1.poll()
            if m:
                got.append(m[2][0])
            if len(got) == 8:
                break
        assert got == [0, 0, 1, 1, 2, 3, 4, 5]

    def test_burst_loss_drops_consecutive_messages(self):
        w = make_world("loopback", 2, seed=5)
        w.set_burst_loss(1.0, 3)  # every message starts a burst
        for i in range(3):
            w.transport(0).isend(1, Tag.DATA, bytes([i]))
        assert w.transport(1).poll() is None
        assert w.dropped_cnt == 3
        w.set_burst_loss(0.0)
        w.transport(0).isend(1, Tag.DATA, b"ok")
        assert w.transport(1).poll() == (0, Tag.DATA, b"ok")

    def test_burst_loss_validates_args(self):
        w = make_world("loopback", 2)
        with pytest.raises(ValueError):
            w.set_burst_loss(1.5)
        with pytest.raises(ValueError):
            w.set_burst_loss(0.5, 0)
