"""Wire format round-trips and loopback transport semantics."""

import pytest

from rlo_tpu import wire
from rlo_tpu.transport import make_world
from rlo_tpu.wire import Frame, Tag


class TestFrame:
    def test_roundtrip(self):
        f = Frame(origin=3, pid=7, vote=1, payload=b"hello world")
        assert Frame.decode(f.encode()) == f

    def test_roundtrip_empty_payload(self):
        f = Frame(origin=0)
        raw = f.encode()
        assert len(raw) == wire.HEADER_SIZE
        assert Frame.decode(raw) == f

    def test_variable_size(self):
        # the reference always ships 32 KB (rootless_ops.c:1588); we must not
        small = Frame(origin=1, payload=b"x").encode()
        big = Frame(origin=1, payload=b"x" * 10000).encode()
        assert len(small) == wire.HEADER_SIZE + 1
        assert len(big) == wire.HEADER_SIZE + 10000

    def test_truncated_raises(self):
        raw = Frame(origin=1, payload=b"abcdef").encode()
        with pytest.raises(ValueError):
            Frame.decode(raw[:-1])
        with pytest.raises(ValueError):
            Frame.decode(raw[:3])

    def test_negative_sentinels(self):
        f = Frame(origin=5, pid=-1, vote=-2, payload=b"")
        assert Frame.decode(f.encode()).vote == -2


class TestLoopback:
    def test_basic_delivery(self):
        w = make_world("loopback", 4)
        t0, t3 = w.transport(0), w.transport(3)
        h = t0.isend(3, Tag.BCAST, b"payload")
        assert h.done()
        assert t3.poll() == (0, Tag.BCAST, b"payload")
        assert t3.poll() is None

    def test_fifo_per_pair(self):
        w = make_world("loopback", 2)
        for i in range(10):
            w.transport(0).isend(1, Tag.DATA, bytes([i]))
        got = [w.transport(1).poll()[2][0] for _ in range(10)]
        assert got == list(range(10))

    def test_fifo_preserved_under_latency(self):
        w = make_world("loopback", 2, latency=5, seed=42)
        for i in range(50):
            w.transport(0).isend(1, Tag.DATA, bytes([i]))
        got = []
        t1 = w.transport(1)
        spins = 0
        while len(got) < 50:
            m = t1.poll()
            spins += 1
            assert spins < 10000
            if m:
                got.append(m[2][0])
        assert got == list(range(50))

    def test_latency_handles_complete_eventually(self):
        w = make_world("loopback", 2, latency=3, seed=7)
        h = w.transport(0).isend(1, Tag.BCAST, b"z")
        t1 = w.transport(1)
        got = []
        spins = 0
        while not h.done() or not got:
            m = t1.poll()
            if m:
                got.append(m)
            spins += 1
            assert spins < 1000
        assert got == [(0, Tag.BCAST, b"z")]

    def test_quiescent(self):
        w = make_world("loopback", 3)
        assert w.quiescent()
        w.transport(0).isend(2, Tag.BCAST, b"q")
        assert not w.quiescent()
        w.transport(2).poll()
        assert w.quiescent()

    def test_world_too_small(self):
        # reference rejects ws < 2 at bcomm_init (rootless_ops.c:1464)
        with pytest.raises(ValueError):
            make_world("loopback", 1)

    def test_bad_destination(self):
        w = make_world("loopback", 2)
        with pytest.raises(ValueError):
            w.transport(0).isend(5, Tag.BCAST, b"")

    def test_unknown_backend(self):
        with pytest.raises(ValueError):
            make_world("nope", 4)
