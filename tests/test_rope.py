"""Rotary position embedding (cfg.pos_encoding='rope').

Oracles: (a) the rotation's defining property — attention scores
depend only on RELATIVE positions (shifting every position by a
constant leaves q·kᵀ unchanged); (b) sp-sharded training (ring AND
ulysses, which depend on GLOBAL positions being used) matches the
single device exactly; (c) KV-cache decode (keys cached rotated)
matches the O(n^2) recompute oracle, including combined with GQA;
(d) pipeline parallelism runs; (e) rope vs sincos genuinely differ
(the flag is wired, not ignored).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from rlo_tpu.models.generate import generate
from rlo_tpu.models.transformer import (TransformerConfig, _rope,
                                        forward, init_params,
                                        train_step)
from rlo_tpu.parallel.mesh import make_mesh, shard_jit

ROPE = TransformerConfig(vocab=89, d_model=32, n_heads=4, n_layers=2,
                         d_ff=64, dtype="float32",
                         pos_encoding="rope")


def tokens_for(cfg, batch=2, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, cfg.vocab, (batch, seq)),
                       jnp.int32)


def test_scores_depend_on_relative_positions_only():
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((2, 8, 3, 16)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, 8, 3, 16)), jnp.float32)
    pos = jnp.arange(8)

    def scores(shift):
        qr = _rope(q, pos + shift)
        kr = _rope(k, pos + shift)
        return np.asarray(jnp.einsum("bqhd,bkhd->bhqk", qr, kr))

    np.testing.assert_allclose(scores(0), scores(137), rtol=1e-4,
                               atol=1e-4)
    # and rotation is not a no-op: absolute q.k changes
    assert not np.allclose(
        scores(0), np.asarray(jnp.einsum("bqhd,bkhd->bhqk", q, k)),
        atol=1e-3)


class TestRopeScaling:
    """Context-extension levers (cfg.rope_scaling, round 4)."""

    def test_linear_is_position_interpolation(self):
        """'linear' at scale s == the unscaled rotation evaluated at
        pos/s — the defining identity of position interpolation."""
        rng = np.random.default_rng(3)
        t = jnp.asarray(rng.standard_normal((2, 8, 3, 16)), jnp.float32)
        pos = jnp.arange(0, 64, 8)  # positions beyond a 'trained' range
        got = _rope(t, pos, scaling="linear", scale=4.0)
        want = _rope(t, pos.astype(jnp.float32) / 4.0)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    def test_ntk_rescales_base(self):
        """'ntk' at scale s == plain rotary with base
        10000 * s^(hd/(hd-2)) — computed directly."""
        rng = np.random.default_rng(4)
        hd = 16
        t = jnp.asarray(rng.standard_normal((2, 8, 3, hd)), jnp.float32)
        pos = jnp.arange(8)
        got = np.asarray(_rope(t, pos, scaling="ntk", scale=8.0))
        base = 10000.0 * 8.0 ** (hd / (hd - 2))
        half = hd // 2
        freqs = np.exp(-np.log(base) * np.arange(half) / half)
        ang = np.arange(8)[:, None] * freqs[None, :]
        cos = np.cos(ang)[None, :, None, :]
        sin = np.sin(ang)[None, :, None, :]
        tn = np.asarray(t)
        t1, t2 = tn[..., :half], tn[..., half:]
        want = np.concatenate([t1 * cos - t2 * sin,
                               t1 * sin + t2 * cos], -1)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_ntk_preserves_high_freq_extends_low(self):
        """The NTK property itself: the highest-frequency pair's angle
        moves <10% while the lowest-frequency pair's period grows by
        ~the scale factor."""
        hd, s = 64, 16.0
        half = hd // 2
        base0, base1 = 10000.0, 10000.0 * s ** (hd / (hd - 2))
        f0 = np.exp(-np.log(base0) * np.arange(half) / half)
        f1 = np.exp(-np.log(base1) * np.arange(half) / half)
        assert f1[0] == f0[0]                      # highest: untouched
        assert abs(f1[1] / f0[1] - 1) < 0.1        # near-highest: <10%
        # lowest-frequency period grows ~s (up to the (d-2)/d exponent)
        growth = f0[-1] / f1[-1]
        assert s * 0.5 < growth <= s * 1.01

    @pytest.mark.parametrize("scaling", ["linear", "ntk"])
    def test_scaled_model_trains_and_decodes(self, scaling):
        """End-to-end: a scaled-rope config trains (finite loss,
        params move) and KV-cache decode still matches the O(n^2)
        recompute oracle (keys cached rotated with the SAME scaled
        rotation)."""
        cfg = dataclasses.replace(ROPE, rope_scaling=scaling,
                                  rope_scale=4.0)
        params = init_params(jax.random.PRNGKey(5), cfg)
        new_params, loss = train_step(params, tokens_for(cfg), cfg,
                                      lr=1e-2)
        assert np.isfinite(float(loss))
        prompt = tokens_for(cfg, seq=6, seed=7)
        got = np.asarray(generate(params, prompt, cfg, max_new=6))
        seq = np.asarray(prompt)
        for _ in range(6):
            logits = np.asarray(forward(params, jnp.asarray(seq), cfg)
                                )[:, -1, :]
            nxt = logits.argmax(-1).astype(np.int32)
            seq = np.concatenate([seq, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(got, seq[:, prompt.shape[1]:])

    def test_scaled_sp_sharded_matches_single_device(self):
        """Scaling composes with sp sharding (global positions scale
        uniformly across shards)."""
        cfg = dataclasses.replace(ROPE, rope_scaling="ntk",
                                  rope_scale=2.0)
        mesh = make_mesh((2,), ("sp",))
        params = init_params(jax.random.PRNGKey(6), cfg)
        toks = tokens_for(cfg, seq=32, seed=8)
        step = shard_jit(
            lambda p, t: train_step(p, t, cfg, lr=1e-2, sp_axis="sp"),
            mesh, (P(), P(None, "sp")), (P(), P()))
        _, loss_sp = step(params, toks)
        _, loss_one = train_step(params, toks, cfg, lr=1e-2)
        assert abs(float(loss_sp) - float(loss_one)) < 1e-4

    def test_invalid_configs_rejected(self):
        toks = tokens_for(ROPE, seq=4)
        bad1 = dataclasses.replace(ROPE, rope_scaling="yarn")
        params = init_params(jax.random.PRNGKey(0), bad1)
        with pytest.raises(ValueError, match="unknown rope_scaling"):
            forward(params, toks, bad1)
        bad2 = dataclasses.replace(ROPE, pos_encoding="sincos",
                                   rope_scaling="ntk")
        with pytest.raises(ValueError, match="requires"):
            forward(params, toks, bad2)
        bad3 = dataclasses.replace(ROPE, rope_scaling="linear",
                                   rope_scale=0.5)
        with pytest.raises(ValueError, match=">= 1"):
            forward(params, toks, bad3)


def test_rope_differs_from_sincos():
    params_shape_cfg = dataclasses.replace(ROPE, pos_encoding="sincos")
    params = init_params(jax.random.PRNGKey(0), ROPE)
    toks = tokens_for(ROPE)
    a = np.asarray(forward(params, toks, ROPE))
    b = np.asarray(forward(params, toks, params_shape_cfg))
    assert not np.allclose(a, b, atol=1e-3)


@pytest.mark.parametrize("sp_attention", ["ring", "ulysses"])
def test_rope_sequence_parallel_matches_single_device(sp_attention):
    """Global positions under sharding: shard r must rotate with its
    own global slice, or the sharded loss diverges."""
    cfg = dataclasses.replace(ROPE, sp_attention=sp_attention)
    mesh = make_mesh((2,), ("sp",))
    params = init_params(jax.random.PRNGKey(2), cfg)
    toks = tokens_for(cfg, seq=32, seed=3)
    step = shard_jit(
        lambda p, t: train_step(p, t, cfg, lr=1e-2, sp_axis="sp"),
        mesh, (P(), P(None, "sp")), (P(), P()))
    _, loss_sp = step(params, toks)
    _, loss_one = train_step(params, toks, cfg, lr=1e-2)
    assert abs(float(loss_sp) - float(loss_one)) < 1e-4


@pytest.mark.parametrize("n_kv_heads", [None, 2])
def test_rope_decode_matches_naive_loop(n_kv_heads):
    cfg = dataclasses.replace(ROPE, n_kv_heads=n_kv_heads)
    params = init_params(jax.random.PRNGKey(3), cfg)
    prompt = tokens_for(cfg, seq=6, seed=4)
    max_new = 8
    got = np.asarray(generate(params, prompt, cfg, max_new=max_new))
    seq = np.asarray(prompt)
    for _ in range(max_new):
        logits = np.asarray(forward(params, jnp.asarray(seq), cfg)
                            )[:, -1, :]
        nxt = logits.argmax(-1).astype(np.int32)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, seq[:, prompt.shape[1]:])


def test_rope_pipeline_parallel():
    from rlo_tpu.models.pipeline import (pipeline_pspecs,
                                         pipeline_train_step,
                                         stack_layers)

    mesh = make_mesh((2,), ("pp",))
    params = init_params(jax.random.PRNGKey(4), ROPE)
    pparams = stack_layers(params)
    specs = pipeline_pspecs("pp", cfg=ROPE)
    toks = tokens_for(ROPE, batch=4, seq=16, seed=5)
    step = shard_jit(
        lambda p, t: pipeline_train_step(p, t, ROPE, "pp", n_micro=2,
                                         lr=1e-2),
        mesh, (specs, P()), (specs, P()))
    _, loss = step(pparams, toks)
    assert np.isfinite(float(loss))


def test_rope_train_step_moves_params():
    params = init_params(jax.random.PRNGKey(5), ROPE)
    new_params, loss = train_step(params, tokens_for(ROPE), ROPE,
                                  lr=1e-2)
    assert np.isfinite(float(loss))
    delta = sum(float(np.abs(np.asarray(a) - np.asarray(b)).sum())
                for a, b in zip(jax.tree.leaves(new_params),
                                jax.tree.leaves(params)))
    assert delta > 0
