"""Engine-substrate data collectives vs numpy oracles.

BASELINE.json config 1 analogue: float32 allreduce across 8 ranks with a
1 MB buffer — run in-process over the loopback transport, plus
reduce-scatter / all-gather / barrier and latency-fuzz and threaded-driver
variants.
"""

import threading

import numpy as np
import pytest

from rlo_tpu.ops.collectives import Comm, run_blocking, run_collectives
from rlo_tpu.transport import make_world

WORLD_SIZES = [2, 3, 4, 5, 7, 8, 16]


def make_comms(ws, **kw):
    world = make_world("loopback", ws, **kw)
    return world, [Comm(world.transport(r)) for r in range(ws)]


def rank_data(ws, shape=(64,), dtype=np.float32, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.standard_normal(shape).astype(dtype) + r
            for r in range(ws)]


class TestAllreduce:
    @pytest.mark.parametrize("ws", WORLD_SIZES)
    @pytest.mark.parametrize("algorithm", ["recursive_doubling", "ring"])
    def test_sum_matches_numpy(self, ws, algorithm):
        world, comms = make_comms(ws)
        xs = rank_data(ws, shape=(33, 7))
        want = np.sum(xs, axis=0)
        got = run_collectives(
            [c.allreduce(x, algorithm=algorithm) for c, x in zip(comms, xs)])
        for g in got:
            np.testing.assert_allclose(g, want, rtol=1e-5)

    @pytest.mark.parametrize("op,npop", [("min", np.min), ("max", np.max),
                                         ("prod", np.prod)])
    def test_other_ops(self, op, npop):
        ws = 8
        world, comms = make_comms(ws)
        xs = rank_data(ws)
        want = npop(np.stack(xs), axis=0)
        got = run_collectives(
            [c.allreduce(x, op=op) for c, x in zip(comms, xs)])
        for g in got:
            np.testing.assert_allclose(g, want, rtol=1e-5)

    def test_vote_and_reduce(self):
        """The IAR AND-merge generalized to tensors (int32 votes)."""
        ws = 7
        world, comms = make_comms(ws)
        xs = [np.ones(5, np.int32) for _ in range(ws)]
        xs[3][2] = 0  # one dissenter on element 2
        got = run_collectives(
            [c.allreduce(x, op="and") for c, x in zip(comms, xs)])
        for g in got:
            np.testing.assert_array_equal(g, [1, 1, 0, 1, 1])

    def test_ring_min_with_padding(self):
        """Ring algorithm + min op + ragged size: identity padding must not
        leak into results."""
        ws = 8
        world, comms = make_comms(ws)
        xs = rank_data(ws, shape=(ws * 2 + 3,))
        want = np.min(np.stack(xs), axis=0)
        got = run_collectives(
            [c.allreduce(x, op="min", algorithm="ring")
             for c, x in zip(comms, xs)])
        for g in got:
            np.testing.assert_allclose(g, want, rtol=1e-5)

    def test_1mb_float32_8ranks(self):
        """BASELINE config 1 shape: 1 MB float32, 8 ranks."""
        ws = 8
        world, comms = make_comms(ws)
        n = (1 << 20) // 4
        xs = rank_data(ws, shape=(n,))
        want = np.sum(xs, axis=0)
        got = run_collectives(
            [c.allreduce(x) for c, x in zip(comms, xs)])  # auto -> ring
        for g in got:
            np.testing.assert_allclose(g, want, rtol=1e-4)

    @pytest.mark.parametrize("ws", [3, 8])
    def test_under_latency_fuzz(self, ws):
        world, comms = make_comms(ws, latency=5, seed=11)
        xs = rank_data(ws)
        want = np.sum(xs, axis=0)
        got = run_collectives([c.allreduce(x) for c, x in zip(comms, xs)])
        for g in got:
            np.testing.assert_allclose(g, want, rtol=1e-5)

    def test_threaded_blocking_driver(self):
        ws = 8
        world, comms = make_comms(ws)
        xs = rank_data(ws)
        want = np.sum(xs, axis=0)
        got = [None] * ws

        def work(r):
            got[r] = run_blocking(comms[r].allreduce(xs[r]))

        threads = [threading.Thread(target=work, args=(r,))
                   for r in range(ws)]
        [t.start() for t in threads]
        [t.join(timeout=30) for t in threads]
        for g in got:
            np.testing.assert_allclose(g, want, rtol=1e-5)

    def test_back_to_back_ops_stay_matched(self):
        """Two sequential collectives must not cross-match messages."""
        ws = 4
        world, comms = make_comms(ws, latency=3, seed=2)
        xs = rank_data(ws)
        ys = rank_data(ws, seed=1)

        def both(c, x, y):
            a = yield from c.allreduce(x)
            b = yield from c.allreduce(y, algorithm="ring")
            return a, b

        got = run_collectives(
            [both(c, x, y) for c, x, y in zip(comms, xs, ys)])
        for a, b in got:
            np.testing.assert_allclose(a, np.sum(xs, axis=0), rtol=1e-5)
            np.testing.assert_allclose(b, np.sum(ys, axis=0), rtol=1e-5)


class TestReduceScatterAllGather:
    @pytest.mark.parametrize("ws", WORLD_SIZES)
    def test_reduce_scatter(self, ws):
        world, comms = make_comms(ws)
        xs = rank_data(ws, shape=(ws * 3 + 1,))  # force padding
        full = np.sum(xs, axis=0)
        pad = (-len(full)) % ws
        padded = np.concatenate([full, np.zeros(pad, np.float32)])
        want_chunks = padded.reshape(ws, -1)
        got = run_collectives(
            [c.reduce_scatter(x) for c, x in zip(comms, xs)])
        for r, g in enumerate(got):
            np.testing.assert_allclose(g, want_chunks[r], rtol=1e-5)

    @pytest.mark.parametrize("ws", WORLD_SIZES)
    def test_all_gather(self, ws):
        world, comms = make_comms(ws)
        xs = [np.full((2, 3), r, np.float32) for r in range(ws)]
        got = run_collectives([c.all_gather(x) for c, x in zip(comms, xs)])
        want = np.concatenate(xs, axis=0)
        for g in got:
            np.testing.assert_array_equal(g, want)

    def test_rs_ag_composition_equals_allreduce(self):
        ws = 8
        world, comms = make_comms(ws)
        xs = rank_data(ws, shape=(ws * 5,))

        def rs_ag(c, x):
            chunk = yield from c.reduce_scatter(x)
            full = yield from c.all_gather(chunk)
            return full

        got = run_collectives([rs_ag(c, x) for c, x in zip(comms, xs)])
        want = np.sum(xs, axis=0)
        for g in got:
            np.testing.assert_allclose(g, want, rtol=1e-5)


class TestAllToAll:
    @pytest.mark.parametrize("ws", WORLD_SIZES)
    def test_matches_transpose(self, ws):
        world, comms = make_comms(ws)
        # chunk (r, d): rank r's payload for rank d
        data = [[np.full((2,), 10 * r + d, np.float32)
                 for d in range(ws)] for r in range(ws)]
        got = run_collectives(
            [c.all_to_all(row) for c, row in zip(comms, data)])
        for d in range(ws):
            for r in range(ws):
                np.testing.assert_array_equal(got[d][r], data[r][d])

    def test_wrong_chunk_count_rejected(self):
        world, comms = make_comms(4)
        with pytest.raises(ValueError, match="one chunk per rank"):
            run_collectives([c.all_to_all([np.zeros(1)] * 3)
                             for c in comms])


class TestBarrier:
    @pytest.mark.parametrize("ws", WORLD_SIZES)
    def test_barrier_completes(self, ws):
        world, comms = make_comms(ws, latency=4, seed=3)
        got = run_collectives([c.barrier() for c in comms])
        assert got == [True] * ws
        assert world.quiescent()
