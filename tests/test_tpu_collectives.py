"""TPU-backend collectives vs XLA-native baselines on the virtual CPU mesh.

Parity oracles required by SURVEY.md §4: ring/recursive-doubling allreduce
vs `lax.psum`, ring all-gather vs `lax.all_gather`, rootless ppermute bcast
vs replication, device consensus vs vote AND — all under jit+shard_map on
an 8-device mesh (conftest forces the CPU backend with 8 virtual devices).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from rlo_tpu.ops import tpu_collectives as tc
from rlo_tpu.parallel.consensus import TpuConsensus
from rlo_tpu.parallel.mesh import make_mesh, shard_jit

WS = 8


@pytest.fixture(scope="module")
def mesh():
    return make_mesh((WS,), ("x",))


def sharded_rand(shape, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal(shape), dtype)


class TestAllreduce:
    @pytest.mark.parametrize("algorithm", ["psum", "ring", "bidir_ring",
                                           "recursive_doubling",
                                           "halving_doubling"])
    @pytest.mark.parametrize("op", ["sum", "min", "max"])
    def test_matches_psum(self, mesh, algorithm, op):
        x = sharded_rand((WS, 16, 33))  # ragged inner size: forces padding
        f = shard_jit(
            lambda v: tc.allreduce(v, "x", op=op, algorithm=algorithm,
                                   use_pallas=False),
            mesh, P("x"), P("x"))
        base = shard_jit(
            lambda v: tc.allreduce(v, "x", op=op, algorithm="psum"),
            mesh, P("x"), P("x"))
        # ring/rd reduce in a different association order than one AllReduce
        np.testing.assert_allclose(np.asarray(f(x)), np.asarray(base(x)),
                                   rtol=1e-3, atol=1e-5)

    @pytest.mark.parametrize("ws", [2, 3, 5, 8])
    @pytest.mark.parametrize("use_pallas", [False, True])
    def test_bidir_ring_any_world_size(self, ws, use_pallas):
        """The pipelined bidirectional ring must hold for non-power-of-2
        axis sizes and with the Pallas fused combine (interpret on CPU).
        pipeline_chunks=2 is pinned explicitly: the off-TPU default is
        now 1, and the nq>1 cross-sub-chunk schedule must keep numeric
        execution coverage, not just lowering coverage."""
        mesh = make_mesh((ws,), ("x",))
        x = sharded_rand((ws, 4, 33), seed=ws)
        f = shard_jit(
            lambda v: tc.allreduce(v, "x", algorithm="bidir_ring",
                                   use_pallas=use_pallas,
                                   pipeline_chunks=2),
            mesh, P("x"), P("x"))
        want = np.broadcast_to(np.asarray(x).sum(0), x.shape)
        np.testing.assert_allclose(np.asarray(f(x)), want,
                                   rtol=1e-4, atol=1e-6)

    def test_ring_with_pallas_combine(self, mesh):
        """The Pallas fused combine (interpret mode on CPU) inside the ring
        schedule must agree with psum."""
        x = sharded_rand((WS, 8, 128))
        f = shard_jit(
            lambda v: tc.allreduce(v, "x", algorithm="ring",
                                   use_pallas=True),
            mesh, P("x"), P("x"))
        want = np.broadcast_to(np.asarray(x).sum(0), x.shape)
        np.testing.assert_allclose(np.asarray(f(x)), want, rtol=1e-4)

    def test_bf16_ring_fused(self, mesh):
        """bf16 payload with f32 accumulation in the fused combine
        (BASELINE config 3 shape, scaled down)."""
        x = sharded_rand((WS, 16, 128), jnp.bfloat16)
        f = shard_jit(
            lambda v: tc.allreduce(v, "x", algorithm="ring",
                                   use_pallas=True),
            mesh, P("x"), P("x"))
        want = np.asarray(x, np.float32).sum(0)
        got = np.asarray(f(x), np.float32)
        # bf16 has an 8-bit mantissa: tolerance is absolute-dominated
        # (quantization step ~0.02 at magnitude ~2.8)
        np.testing.assert_allclose(got[0], want, rtol=2e-2, atol=0.06)

    def test_int_and_or(self, mesh):
        v = jnp.ones((WS, 4), jnp.int32).at[3, 2].set(0)
        f = shard_jit(lambda x: tc.allreduce(x, "x", op="and"),
                      mesh, P("x"), P("x"))
        np.testing.assert_array_equal(np.asarray(f(v))[0], [1, 1, 0, 1])

    def test_rd_rejects_non_pow2(self):
        sub = make_mesh((6,), ("x",))
        x = jnp.ones((6, 8))
        f = shard_jit(lambda v: tc.allreduce(v, "x",
                                             algorithm="recursive_doubling",
                                             use_pallas=False),
                      sub, P("x"), P("x"))
        with pytest.raises(ValueError, match="power-of-2"):
            f(x)

    def test_rd_pow2_subset_mesh(self):
        sub = make_mesh((4,), ("x",))
        x = jnp.ones((4, 8))
        ok = shard_jit(lambda v: tc.allreduce(v, "x",
                                              algorithm="recursive_doubling",
                                              use_pallas=False),
                       sub, P("x"), P("x"))
        np.testing.assert_allclose(np.asarray(ok(x)), 4.0)


class TestHierarchicalAllreduce:
    """Multi-slice two-tier allreduce: in-slice reduce-scatter, DCN
    allreduce of only the scattered shard, in-slice all-gather."""

    @pytest.mark.parametrize("ici_alg,dcn_alg",
                             [("auto", "psum"), ("ring", "ring"),
                              ("auto", "bidir_ring")])
    def test_matches_two_axis_psum(self, ici_alg, dcn_alg):
        mesh = make_mesh((2, 4), ("dcn", "ici"))
        x = jnp.asarray(np.random.default_rng(0).standard_normal(
            (2, 4, 33)), jnp.float32)  # ragged: padding path
        f = shard_jit(
            lambda v: tc.hierarchical_allreduce(
                v, "ici", "dcn", ici_algorithm=ici_alg,
                dcn_algorithm=dcn_alg, use_pallas=False),
            mesh, P("dcn", "ici"), P("dcn", "ici"))
        want = np.broadcast_to(np.asarray(x).sum((0, 1)), x.shape)
        np.testing.assert_allclose(np.asarray(f(x)), want,
                                   rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("op", ["min", "max"])
    def test_min_max(self, op):
        mesh = make_mesh((2, 4), ("dcn", "ici"))
        x = jnp.asarray(np.random.default_rng(1).standard_normal(
            (2, 4, 16)), jnp.float32)
        f = shard_jit(
            lambda v: tc.hierarchical_allreduce(v, "ici", "dcn", op=op,
                                                use_pallas=False),
            mesh, P("dcn", "ici"), P("dcn", "ici"))
        red = getattr(np.asarray(x), op)(axis=(0, 1))
        np.testing.assert_allclose(np.asarray(f(x)),
                                   np.broadcast_to(red, x.shape),
                                   rtol=1e-6)

    @pytest.mark.parametrize("shape", [(1, 8), (2, 3)])
    def test_degenerate_and_non_pow2(self, shape):
        """ws_dcn=1 must degrade to a pure in-slice schedule; non-pow2
        in-slice sizes take the ring RS/AG branch."""
        mesh = make_mesh(shape, ("dcn", "ici"))
        x = jnp.asarray(np.random.default_rng(2).standard_normal(
            shape + (17,)), jnp.float32)
        f = shard_jit(
            lambda v: tc.hierarchical_allreduce(v, "ici", "dcn",
                                                use_pallas=False),
            mesh, P("dcn", "ici"), P("dcn", "ici"))
        want = np.broadcast_to(np.asarray(x).sum((0, 1)), x.shape)
        np.testing.assert_allclose(np.asarray(f(x)), want,
                                   rtol=1e-4, atol=1e-5)

    def test_int8_dcn_exact_on_representable_values(self):
        """A payload whose in-slice-reduced chunks are all +-127 has
        scale exactly 1 at the quantization point (which sits AFTER
        the in-slice reduce-scatter), so the compressed DCN hop must
        reproduce the exact sum."""
        mesh = make_mesh((2, 4), ("dcn", "ici"))
        rng = np.random.default_rng(3)
        # only ici-shard 0 of each slice contributes, values +-127:
        # every reduced chunk is +-127 everywhere -> amax 127, scale 1
        x = np.zeros((2, 4, 64), np.float32)
        x[:, 0, :] = 127.0 * rng.choice([-1.0, 1.0], (2, 64))
        xj = jnp.asarray(x)
        f = shard_jit(
            lambda v: tc.hierarchical_allreduce(v, "ici", "dcn",
                                                dcn_algorithm="int8",
                                                use_pallas=False),
            mesh, P("dcn", "ici"), P("dcn", "ici"))
        want = np.broadcast_to(x.sum((0, 1)), x.shape)
        np.testing.assert_allclose(np.asarray(f(xj)), want,
                                   rtol=1e-6, atol=1e-4)

    def test_int8_dcn_error_bound(self):
        """Random data: per-element error of the compressed hop is
        bounded by ws_dcn half-steps of the largest per-slice scale."""
        mesh = make_mesh((2, 4), ("dcn", "ici"))
        rng = np.random.default_rng(4)
        x = rng.standard_normal((2, 4, 128)).astype(np.float32)
        xj = jnp.asarray(x)
        f = shard_jit(
            lambda v: tc.hierarchical_allreduce(v, "ici", "dcn",
                                                dcn_algorithm="int8",
                                                use_pallas=False),
            mesh, P("dcn", "ici"), P("dcn", "ici"))
        got = np.asarray(f(xj))
        want = np.broadcast_to(x.sum((0, 1)), x.shape)
        # after the in-slice RS each dcn shard holds a slice-summed
        # chunk; its scale is amax/127 over that chunk
        bound = 2 * (np.abs(x.sum(1)).max() / 127.0) * 0.51 + 1e-5
        assert np.abs(got - want).max() <= bound

    def test_int8_dcn_wire_is_int8(self):
        """The compression must reach the wire: the only dcn-axis
        collectives are the i8 payload gather and the f32 scale
        gather — no f32 tensor of the chunk size crosses DCN."""
        import re
        wd, wi = 2, 4
        mesh = make_mesh((wd, wi), ("dcn", "ici"))
        per_shard = wi * 128
        x = jnp.zeros((wd, wi, per_shard), jnp.float32)
        f = shard_jit(
            lambda v: tc.hierarchical_allreduce(v, "ici", "dcn",
                                                dcn_algorithm="int8",
                                                use_pallas=False),
            mesh, P("dcn", "ici"), P("dcn", "ici"))
        txt = f.lower(x).as_text()
        gathers = re.findall(
            r'all_gather.*?replica_groups\s*=\s*dense<\[\[(\d+),\s*(\d+)\]'
            r'[^\n]*?:\s*\(tensor<([0-9x]+)x(i8|f32)>\)', txt)
        cross = [(int(a), int(b), dims, dt) for a, b, dims, dt in gathers
                 if abs(int(b) - int(a)) == wi]  # dcn-axis groups
        assert cross, f"no dcn-axis all_gather found: {gathers}"
        payload = [g for g in cross if g[3] == "i8"]
        assert payload and all(
            int(g[2].split("x")[-1]) == per_shard // wi or
            g[2] == str(per_shard // wi) for g in payload)
        # any f32 crossing dcn must be the scalar scale, not the chunk
        for _, _, dims, dt in cross:
            if dt == "f32":
                elems = 1
                for d in dims.split("x"):
                    elems *= int(d)
                assert elems == 1, f"f32 chunk crossed DCN: {dims}"
        assert "all_reduce" not in txt  # psum path fully replaced

    def test_int8_single_slice_is_lossless_noop(self):
        """ws_dcn=1 with int8 configured: the dcn hop is skipped
        entirely — no quantization error may leak into single-slice
        runs that keep the config flag set."""
        mesh = make_mesh((1, 8), ("dcn", "ici"))
        x = jnp.asarray(np.random.default_rng(5).standard_normal(
            (1, 8, 33)), jnp.float32)
        f = shard_jit(
            lambda v: tc.hierarchical_allreduce(v, "ici", "dcn",
                                                dcn_algorithm="int8",
                                                use_pallas=False),
            mesh, P("dcn", "ici"), P("dcn", "ici"))
        want = np.broadcast_to(np.asarray(x).sum((0, 1)), x.shape)
        np.testing.assert_allclose(np.asarray(f(x)), want,
                                   rtol=1e-5, atol=1e-6)
        assert "i8" not in f.lower(x).as_text()

    def test_int8_rejects_non_sum(self):
        mesh = make_mesh((2, 4), ("dcn", "ici"))
        x = jnp.zeros((2, 4, 8), jnp.float32)
        f = shard_jit(
            lambda v: tc.hierarchical_allreduce(v, "ici", "dcn", op="min",
                                                dcn_algorithm="int8",
                                                use_pallas=False),
            mesh, P("dcn", "ici"), P("dcn", "ici"))
        with pytest.raises(ValueError, match="op='sum' only"):
            f(x)

    def test_dcn_traffic_is_scattered_shard_only(self):
        """THE point of the hierarchy: the only collective on the dcn
        axis carries 1/ws_ici of the buffer, never the full payload."""
        import re
        wd, wi = 2, 4
        mesh = make_mesh((wd, wi), ("dcn", "ici"))
        per_shard = wi * 128
        x = jnp.zeros((wd, wi, per_shard), jnp.float32)
        f = shard_jit(
            lambda v: tc.hierarchical_allreduce(v, "ici", "dcn",
                                                use_pallas=False),
            mesh, P("dcn", "ici"), P("dcn", "ici"))
        txt = f.lower(x).as_text()
        # the dcn psum is the only stablehlo.all_reduce in the program;
        # its replica groups pair shards ACROSS slices (stride wi). The
        # op carries a multi-line reduction region, so match through it
        # to the trailing `}) : (tensor<...>)` operand type.
        groups = re.findall(
            r'all_reduce.*?replica_groups\s*=\s*dense<\[\[(\d+),\s*(\d+)\]'
            r'.*?\}\)\s*:\s*\(tensor<(\d+)xf32>\)', txt, re.DOTALL)
        assert groups, "no all_reduce found on the dcn axis"
        for a, b, elems in groups:
            assert abs(int(b) - int(a)) == wi  # cross-slice pairing
            assert int(elems) == per_shard // wi  # scattered shard only


def _permute_bytes_by_direction(lowered_text: str, ws: int):
    """Sum collective_permute operand bytes in StableHLO text, grouped
    by ring direction (first source->target pair: +1 hop = fwd, -1 =
    bwd; anything else = other)."""
    import re
    fwd = bwd = other = 0
    n = 0
    for m in re.finditer(
            r'collective_permute"?\(?[^\n]*?source_target_pairs\s*=\s*'
            r'dense<\[\[(\d+),\s*(\d+)\][^\n]*?'
            r'tensor<([0-9x]*)x?(f32|f64|i32|bf16)>\)?\s*$',
            lowered_text, re.MULTILINE):
        src, dst = int(m.group(1)), int(m.group(2))
        dims = [int(d) for d in m.group(3).split("x") if d]
        elems = 1
        for d in dims:
            elems *= d
        nbytes = elems * {"f32": 4, "i32": 4, "f64": 8, "bf16": 2}[
            m.group(4)]
        n += 1
        if dst == (src + 1) % ws:
            fwd += nbytes
        elif dst == (src - 1) % ws:
            bwd += nbytes
        else:
            other += nbytes
    return fwd, bwd, other, n


class TestAllreduceCostModel:
    """Weak-5 closure (round-3 VERDICT): the bidirectional ring's win —
    half the serialized bytes per link DIRECTION at the same step count
    — cannot show up in wall time on a CPU mesh (one memory bus; every
    launch serializes), so pin it by construction: the analytic cost
    model vs the actual bytes the unrolled HLO moves."""

    def test_bidir_hlo_bytes_match_model(self, mesh):
        nq = 2
        per_shard = 2 * WS * nq * 32  # divisible: no padding term
        x = sharded_rand((WS, per_shard))
        f = shard_jit(
            lambda v: tc.allreduce(v, "x", algorithm="bidir_ring",
                                   use_pallas=False, pipeline_chunks=nq),
            mesh, P("x"), P("x"))
        txt = f.lower(x).as_text()
        fwd, bwd, other, n = _permute_bytes_by_direction(txt, WS)
        model = tc.allreduce_cost("bidir_ring", WS, per_shard * 4,
                                  pipeline_chunks=nq)
        assert other == 0  # every hop is a ring neighbor hop
        assert n == model["n_permutes"] == 4 * (WS - 1) * nq
        assert fwd == bwd == model["fwd_bytes"]
        # THE claim: per link direction, half the unidirectional ring's
        # serialized bytes, at the same dependent step count
        ring = tc.allreduce_cost("ring", WS, per_shard * 4)
        assert fwd * 2 == ring["fwd_bytes"]
        assert model["steps"] == ring["steps"]

    def test_bidir_hlo_bytes_match_model_padded(self, mesh):
        """Ragged payload: the model's element-granular padding must
        match the bytes the padded program actually moves."""
        nq = 2
        per_shard = 2 * WS * nq * 32 + 7  # forces the padding path
        x = sharded_rand((WS, per_shard))
        f = shard_jit(
            lambda v: tc.allreduce(v, "x", algorithm="bidir_ring",
                                   use_pallas=False, pipeline_chunks=nq),
            mesh, P("x"), P("x"))
        txt = f.lower(x).as_text()
        fwd, bwd, other, n = _permute_bytes_by_direction(txt, WS)
        model = tc.allreduce_cost("bidir_ring", WS, per_shard * 4,
                                  pipeline_chunks=nq)
        assert other == 0 and n == model["n_permutes"]
        assert fwd == bwd == model["fwd_bytes"]

    def test_ring_hlo_bytes_match_model(self, mesh):
        """The fori_loop-rolled unidirectional ring: per-iteration HLO
        carries one chunk forward; trip count (ws-1) per phase gives
        the model's total."""
        per_shard = WS * 64
        x = sharded_rand((WS, per_shard))
        f = shard_jit(
            lambda v: tc.allreduce(v, "x", algorithm="ring",
                                   use_pallas=False),
            mesh, P("x"), P("x"))
        txt = f.lower(x).as_text()
        fwd, bwd, other, n = _permute_bytes_by_direction(txt, WS)
        model = tc.allreduce_cost("ring", WS, per_shard * 4)
        chunk_bytes = per_shard * 4 // WS
        # static text: one RS-loop permute + one AG-loop permute + the
        # reduce_scatter's final ownership rotation is absent in
        # allreduce (rolled gather starts from owned chunk)
        assert bwd == other == 0
        assert fwd == 2 * chunk_bytes
        assert fwd * (WS - 1) == model["fwd_bytes"]

    def test_cost_model_totals(self):
        n = 1 << 20
        ring = tc.allreduce_cost("ring", 8, n)
        bidir = tc.allreduce_cost("bidir_ring", 8, n)
        hd = tc.allreduce_cost("halving_doubling", 8, n)
        rd = tc.allreduce_cost("recursive_doubling", 8, n)
        # bandwidth-optimal schedules all move 2n(ws-1)/ws per rank
        assert ring["total_bytes"] == bidir["total_bytes"] \
            == hd["total_bytes"] == 2 * n * 7 // 8
        # recursive doubling trades bytes for latency
        assert rd["total_bytes"] == 3 * n
        assert rd["steps"] == 3 < hd["steps"] == 6 < ring["steps"] == 14
        assert tc.allreduce_cost("ring", 1, n)["total_bytes"] == 0
        with pytest.raises(ValueError, match="power-of-2"):
            tc.allreduce_cost("recursive_doubling", 6, n)
        with pytest.raises(ValueError, match="no cost model"):
            tc.allreduce_cost("psum", 8, n)


from rlo_tpu.utils.hlo import permute_total_bytes as _permute_total_bytes  # noqa: E402,E501


class TestRound5CostModels:
    """Round-5 VERDICT item 5: the round-4 schedules (hierarchical,
    int8-DCN, all_to_all) get the same lowered-HLO byte pinning the
    ring family got in round 3 — the claims hold by construction."""

    def test_hierarchical_ici_hlo_bytes_match_model(self):
        """pow-2 slice: halving RS + doubling AG are fully unrolled,
        so every collective_permute in the program is ICI-tier and
        their byte total must equal the model exactly (the DCN psum
        lowers to all_reduce, not permutes)."""
        wd, wi = 2, 4
        mesh = make_mesh((wd, wi), ("dcn", "ici"))
        per_shard = wi * 96
        x = jnp.zeros((wd, wi, per_shard), jnp.float32)
        f = shard_jit(
            lambda v: tc.hierarchical_allreduce(v, "ici", "dcn",
                                                use_pallas=False),
            mesh, P("dcn", "ici"), P("dcn", "ici"))
        txt = f.lower(x).as_text()
        total, n = _permute_total_bytes(txt, require=True)
        model = tc.hierarchical_allreduce_cost(wi, wd, per_shard * 4)
        assert total == model["ici_bytes"] \
            == 2 * (wi - 1) * (per_shard // wi) * 4
        assert n == model["ici_permutes"]
        # the dcn all_reduce operand is the scattered shard, and the
        # model's element count states exactly that
        assert model["dcn_elems"] == per_shard // wi
        # the wi-fold DCN claim the hierarchy exists for
        assert model["dcn_bytes"] * wi == model["dcn_bytes_flat"]

    def test_hierarchical_int8_dcn_bytes_match_model(self):
        """int8 DCN hop: the lowered all_gather carries exactly the
        model's dcn_elems as i8 — the byte claim on the wire."""
        import re
        wd, wi = 2, 4
        mesh = make_mesh((wd, wi), ("dcn", "ici"))
        per_shard = wi * 64
        x = jnp.zeros((wd, wi, per_shard), jnp.float32)
        f = shard_jit(
            lambda v: tc.hierarchical_allreduce(v, "ici", "dcn",
                                                dcn_algorithm="int8",
                                                use_pallas=False),
            mesh, P("dcn", "ici"), P("dcn", "ici"))
        txt = f.lower(x).as_text()
        model = tc.hierarchical_allreduce_cost(
            wi, wd, per_shard * 4, dcn_algorithm="int8")
        from rlo_tpu.utils.hlo import all_gather_operands
        payload = [e for e, dt in all_gather_operands(txt, require=True)
                   if dt == "i8"]
        assert payload and all(p == model["dcn_elems"]
                               for p in payload), payload
        # per-rank dcn bytes: (wd-1) int8 chunks + (wd-1) 4-byte scales
        assert model["dcn_bytes"] == (wd - 1) * (model["dcn_elems"] + 4)

    def test_int8_crossover_pinned(self):
        """The docstring's 8/ws_dcn crossover, pinned numerically:
        gain below 8 slices, parity at 8, loss beyond (sidecar scale
        bytes excluded by using a large chunk)."""
        n = 1 << 20
        for wd, expect in ((2, 4.0), (4, 2.0), (8, 1.0), (16, 0.5)):
            c = tc.hierarchical_allreduce_cost(
                4, wd, n, dcn_algorithm="int8")
            assert abs(c["dcn_compression"] - expect) < 0.01, (wd, c)

    def test_all_to_all_direct_hlo_bytes_match_model(self, mesh):
        """'direct' is an unrolled python loop: ws-1 permutes, offset
        o carrying one chunk over o ring hops — injected bytes AND
        hop-weighted link bytes both pinned to the model."""
        import re
        chunk = 32
        x = jnp.zeros((WS, WS, chunk), jnp.float32)
        f = shard_jit(
            lambda v: tc.all_to_all(v[0], "x", algorithm="direct")[None],
            mesh, P("x"), P("x"))
        txt = f.lower(x).as_text()
        from rlo_tpu.utils.hlo import permute_entries
        injected = hop_bytes = n = 0
        for src, dst, nbytes in permute_entries(txt, require=True):
            o = (dst - src) % WS
            injected += nbytes
            hop_bytes += o * nbytes
            n += 1
        model = tc.all_to_all_cost("direct", WS, WS * chunk * 4)
        assert n == model["n_permutes"] == WS - 1
        assert injected == model["injected_bytes"]
        assert hop_bytes == model["link_hop_bytes"]

    def test_all_to_all_cost_totals(self):
        """ring pays exactly 2x direct's link bytes (the docstring
        claim); xla is modeled at direct's optimum."""
        n = 1 << 16
        d = tc.all_to_all_cost("direct", 8, n)
        r = tc.all_to_all_cost("ring", 8, n)
        xl = tc.all_to_all_cost("xla", 8, n)
        assert r["link_hop_bytes"] == 2 * d["link_hop_bytes"]
        assert xl["link_hop_bytes"] == d["link_hop_bytes"]
        assert d["injected_bytes"] == 7 * n // 8
        assert r["injected_bytes"] == 7 * n
        assert tc.all_to_all_cost("direct", 1, 0)["n_permutes"] == 0
        with pytest.raises(ValueError, match="divide"):
            tc.all_to_all_cost("direct", 8, n + 1)
        with pytest.raises(ValueError, match="no cost model"):
            tc.all_to_all_cost("nope", 8, n)

    def test_hierarchical_forced_ring_on_pow2_pinned(self):
        """ici_algorithm='ring' on a pow-2 slice: the RS honors the
        forced ring but the AG is doubling (picked by pow2 alone) —
        the model must describe THAT mixed program, launch count
        included."""
        wd, wi = 2, 4
        mesh = make_mesh((wd, wi), ("dcn", "ici"))
        per_shard = wi * 96
        x = jnp.zeros((wd, wi, per_shard), jnp.float32)
        f = shard_jit(
            lambda v: tc.hierarchical_allreduce(v, "ici", "dcn",
                                                ici_algorithm="ring",
                                                use_pallas=False),
            mesh, P("dcn", "ici"), P("dcn", "ici"))
        txt = f.lower(x).as_text()
        total, n = _permute_total_bytes(txt, require=True)
        model = tc.hierarchical_allreduce_cost(wi, wd, per_shard * 4,
                                               ici_algorithm="ring")
        chunk = per_shard // wi * 4
        # NOTE: the ring RS here is python-unrolled? No — it's a
        # fori_loop; static text shows ONE loop-body permute + the
        # ownership rotation + unrolled doubling AG. Pin the static
        # text pieces and the model total separately.
        k = wi.bit_length() - 1
        assert model["ici_permutes"] == wi + k
        assert model["ici_bytes"] == (2 * wi - 1) * chunk
        # static text: 1 rolled RS permute + 1 rotation + k doubling
        assert n == 2 + k, txt.count("collective_permute")
        assert total == 2 * chunk + (wi - 1) * chunk

    def test_hierarchical_cost_non_pow2_and_errors(self):
        c = tc.hierarchical_allreduce_cost(3, 2, 3 * 40)
        # ring RS (2 steps + rotation) + ring AG (2 steps), 40-byte
        # chunks (30 elems pad to 10/shard)
        assert c["ici_bytes"] == (2 * 3 - 1) * 40
        assert c["ici_permutes"] == 2 * (3 - 1) + 1
        one = tc.hierarchical_allreduce_cost(1, 4, 64)
        assert one["ici_bytes"] == 0 and one["dcn_elems"] == 16
        none = tc.hierarchical_allreduce_cost(4, 1, 64)
        assert none["dcn_bytes"] == 0
        with pytest.raises(ValueError, match="multiple"):
            tc.hierarchical_allreduce_cost(4, 2, 63)


class TestReduceScatterAllGather:
    @pytest.mark.parametrize("algorithm", ["ring", "halving", "auto"])
    def test_reduce_scatter_chunks(self, mesh, algorithm):
        x = sharded_rand((WS, WS * 5 + 3))  # ragged: padding path
        f = shard_jit(lambda v: tc.reduce_scatter(v, "x", algorithm=algorithm,
                                                  use_pallas=False),
                      mesh, P("x"), P("x"))
        got = np.asarray(f(x))  # (WS * chunk,) concatenated shards
        full = np.asarray(x).sum(0)
        pad = (-full.size) % WS
        padded = np.concatenate([full, np.zeros(pad, np.float32)])
        np.testing.assert_allclose(got, padded, rtol=1e-5)

    @pytest.mark.parametrize("algorithm", ["ring", "doubling"])
    def test_all_gather_matches_xla(self, mesh, algorithm):
        x = sharded_rand((WS, 3, 5))
        man = shard_jit(lambda v: tc.all_gather(v, "x", algorithm=algorithm),
                        mesh, P("x"), P("x"))
        xla = shard_jit(lambda v: tc.all_gather(v, "x"),
                        mesh, P("x"), P("x"))
        np.testing.assert_allclose(np.asarray(man(x)), np.asarray(xla(x)),
                                   rtol=1e-6)

    def test_halving_rejects_non_pow2(self):
        sub = make_mesh((6,), ("x",))
        x = jnp.ones((6, 12))
        f = shard_jit(lambda v: tc.reduce_scatter(v, "x",
                                                  algorithm="halving",
                                                  use_pallas=False),
                      sub, P("x"), P("x"))
        with pytest.raises(ValueError, match="power-of-2"):
            f(x)

    def test_auto_falls_back_to_ring_non_pow2(self):
        sub = make_mesh((6,), ("x",))
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.standard_normal((6, 14)), jnp.float32)
        f = shard_jit(lambda v: tc.reduce_scatter(v, "x", use_pallas=False),
                      sub, P("x"), P("x"))
        got = np.asarray(f(x))
        full = np.asarray(x).sum(0)
        pad = (-full.size) % 6
        padded = np.concatenate([full, np.zeros(pad, np.float32)])
        np.testing.assert_allclose(got, padded, rtol=1e-5)

    def test_halving_doubling_with_pallas_combine(self, mesh):
        """Pallas fused combine (interpret mode on CPU) inside the halving
        schedule; bf16 payload (BASELINE config 4 dtype path)."""
        x = sharded_rand((WS, 16, 128), jnp.bfloat16)
        f = shard_jit(
            lambda v: tc.allreduce(v, "x", algorithm="halving_doubling",
                                   use_pallas=True),
            mesh, P("x"), P("x"))
        want = np.asarray(x, np.float32).sum(0)
        got = np.asarray(f(x), np.float32)
        np.testing.assert_allclose(got[0], want, rtol=2e-2, atol=0.06)

    def test_rs_ag_equals_allreduce(self, mesh):
        x = sharded_rand((WS, 24))

        def rs_ag(v):
            chunk = tc.reduce_scatter(v, "x", use_pallas=False)
            return tc.all_gather(chunk, "x").reshape(-1)[:v.size // 1]

        f = shard_jit(rs_ag, mesh, P("x"), P("x"))
        got = np.asarray(f(x)).reshape(WS, -1)[:, :24]
        want = np.broadcast_to(np.asarray(x).sum(0), (WS, 24))
        np.testing.assert_allclose(got, want, rtol=1e-5)


class TestRootlessBcast:
    @pytest.mark.parametrize("schedule", ["binomial", "skip_ring"])
    @pytest.mark.parametrize("origin", [0, 3, 7])
    def test_every_origin(self, mesh, schedule, origin):
        x = sharded_rand((WS, 4, 4))
        f = shard_jit(
            lambda v: tc.rootless_bcast(v, origin, "x", schedule=schedule),
            mesh, P("x"), P("x"))
        got = np.asarray(f(x))
        want = np.broadcast_to(np.asarray(x)[origin], got.shape)
        np.testing.assert_array_equal(got, want)

    def test_gather_strategy_traced_origin(self, mesh):
        x = sharded_rand((WS, 4))

        def f(v, o):
            return tc.rootless_bcast(v, o, "x", schedule="gather")

        g = jax.jit(jax.shard_map(
            f, mesh=mesh, in_specs=(P("x"), P()), out_specs=P("x")))
        for origin in (0, 5):
            got = np.asarray(g(x, jnp.int32(origin)))
            want = np.broadcast_to(np.asarray(x)[origin], got.shape)
            np.testing.assert_array_equal(got, want)


class TestBarrierConsensus:
    def test_barrier_runs(self, mesh):
        f = shard_jit(lambda v: v + tc.barrier("x"), mesh, P("x"), P("x"))
        np.testing.assert_array_equal(
            np.asarray(f(jnp.zeros(WS, jnp.int32))), np.zeros(WS))

    def test_consensus_unanimous(self, mesh):
        c = TpuConsensus(mesh, "x")
        assert c.decide_votes(np.ones(WS, np.int32)) == 1

    def test_consensus_dissent(self, mesh):
        c = TpuConsensus(mesh, "x")
        votes = np.ones(WS, np.int32)
        votes[5] = 0
        assert c.decide_votes(votes) == 0

    def test_consensus_callbacks(self, mesh):
        log = []
        c = TpuConsensus(mesh, "x",
                         judge_cb=lambda p, ctx: 0 if p == b"bad" else 1,
                         app_ctx=log,
                         action_cb=lambda p, ctx: ctx.append(p))
        assert c.submit(b"good") == 1
        assert c.submit(b"bad") == 0
        assert log == [b"good"]

    def test_sharded_judgment_device_veto(self, mesh):
        """Every shard judges its OWN device slice (rootless_ops.c:698):
        one shard's data failing the predicate vetoes the round even
        though a single controller drives the mesh — the replicated
        host vote could never produce this."""
        log = []
        c = TpuConsensus(mesh, "x",
                         action_cb=lambda p, ctx: log.append(p))
        finite = lambda v: jnp.all(jnp.isfinite(v)).astype(jnp.int32)
        x = np.ones((WS, 8), np.float32)
        assert c.submit_sharded(b"clean", x, finite, key="fin") == 1
        assert log == [b"clean"]
        bad = x.copy()
        bad[3, 5] = np.inf  # ONLY shard 3's device slice is poisoned
        assert c.submit_sharded(b"poisoned", bad, finite,
                                key="fin") == 0
        assert log == [b"clean"]  # no action on decline

    def test_sharded_judgment_host_vote_ands_in(self, mesh):
        c = TpuConsensus(mesh, "x",
                         judge_cb=lambda p, ctx: 0 if p == b"bad" else 1)
        finite = lambda v: jnp.all(jnp.isfinite(v)).astype(jnp.int32)
        x = np.ones((WS, 4), np.float32)
        assert c.submit_sharded(b"ok", x, finite, key="fin2") == 1
        assert c.submit_sharded(b"bad", x, finite, key="fin2") == 0

    def test_shard_votes_exposes_per_shard_verdicts(self, mesh):
        c = TpuConsensus(mesh, "x")
        x = np.ones((WS, 4), np.float32)
        x[2, 0] = np.nan
        x[6, 3] = np.inf
        votes = c.shard_votes(
            x, lambda v: jnp.all(jnp.isfinite(v)).astype(jnp.int32),
            key="fin3")
        want = np.ones(WS, np.int32)
        want[2] = want[6] = 0
        np.testing.assert_array_equal(votes.reshape(-1), want)

    def test_host_sharded_io_callback_judges(self, mesh):
        """Per-shard HOST judges via io_callback: untraceable Python
        logic sees each shard's own block."""
        seen = []

        def shard_judge(blk):
            seen.append(float(np.asarray(blk).sum()))
            return float(np.asarray(blk).sum()) < 10.0

        c = TpuConsensus(mesh, "x")
        x = np.ones((WS, 4), np.float32)
        assert c.submit_host_sharded(b"p", x, shard_judge) == 1
        assert len(seen) == WS  # every shard judged its own block
        y = x.copy()
        y[4] = 100.0  # shard 4's sum violates the bound
        assert c.submit_host_sharded(b"p", y, shard_judge) == 0

    def test_host_sharded_reuse_hits_compile_cache(self, mesh):
        """Same judge across rounds must reuse one compiled program
        (round-2 advisor: a per-call io_callback closure recompiled and
        leaked a cache entry per round)."""
        def shard_judge(blk):
            return bool(np.asarray(blk).sum() < 100.0)

        c = TpuConsensus(mesh, "x")
        x = np.ones((WS, 4), np.float32)
        assert c.submit_host_sharded(b"p", x, shard_judge) == 1
        n_before = len(c._sharded_cache)
        for _ in range(3):
            assert c.submit_host_sharded(b"p", x, shard_judge) == 1
        assert len(c._sharded_cache) == n_before

    def test_host_sharded_bound_method_judge_reuse(self, mesh):
        """Bound-method judges (obj.judge is a fresh object per
        access) must also hit the compiled-program cache."""
        class Judge:
            def ok(self, blk):
                return bool(np.asarray(blk).sum() < 100.0)

        j = Judge()
        c = TpuConsensus(mesh, "x")
        x = np.ones((WS, 4), np.float32)
        assert c.submit_host_sharded(b"p", x, j.ok) == 1
        n_before = len(c._sharded_cache)
        for _ in range(3):
            assert c.submit_host_sharded(b"p", x, j.ok) == 1
        assert len(c._sharded_cache) == n_before
        # a DIFFERENT instance is a different judge: new program
        j2 = Judge()
        assert c.submit_host_sharded(b"p", x, j2.ok) == 1
        assert len(c._sharded_cache) == n_before + 1


class TestMultiAxisMesh:
    def test_allreduce_over_one_axis_of_2d_mesh(self):
        mesh = make_mesh((2, 4), ("dp", "tp"))
        x = sharded_rand((2, 4, 6))
        f = jax.jit(jax.shard_map(
            lambda v: tc.allreduce(v, "tp", algorithm="ring",
                                   use_pallas=False),
            mesh=mesh, in_specs=P("dp", "tp"), out_specs=P("dp", "tp")))
        got = np.asarray(f(x))
        want = np.asarray(x).sum(axis=1, keepdims=True)
        np.testing.assert_allclose(got, np.broadcast_to(want, got.shape),
                                   rtol=1e-5)


class TestMultisliceMesh:
    def test_single_slice_fallback_dcn_size_1(self):
        """On a single-slice platform (CPU: no slice_index), the dcn
        axis degrades to size 1 and programs run unchanged."""
        from rlo_tpu.parallel.mesh import make_multislice_mesh
        mesh = make_multislice_mesh((2, 4), ("dp", "tp"))
        assert mesh.axis_names == ("dcn", "dp", "tp")
        assert mesh.devices.shape == (1, 2, 4)
        x = sharded_rand((2, 4, 6))
        f = jax.jit(jax.shard_map(
            lambda v: tc.allreduce(v, "tp") + 0 * jnp.float32(
                jax.lax.psum(1, "dcn")),  # dcn axis is usable
            mesh=mesh, in_specs=P(None, "dp", "tp"),
            out_specs=P(None, "dp", "tp")))
        got = np.asarray(f(x[None]))[0]
        want = np.asarray(x).sum(axis=1, keepdims=True)
        np.testing.assert_allclose(got, np.broadcast_to(want, got.shape),
                                   rtol=1e-5)

    def test_ici_shape_must_fit_in_slice(self):
        from rlo_tpu.parallel.mesh import make_multislice_mesh
        with pytest.raises(ValueError, match="needs"):
            make_multislice_mesh((64,), ("x",))
