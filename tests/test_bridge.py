"""C-core <-> JAX bridge: native control plane gating the TPU data plane
(SURVEY.md §7 step 8).

Oracles: facade ops route to the right plane and stay numerically
correct; a consensus-approved proposal actually runs the collective (and
the action callback fired on every rank); a shape/dtype mismatch on ANY
rank vetoes the round before any device work.
"""

import numpy as np
import pytest

import rlo_tpu

WS = 4


@pytest.fixture(scope="module")
def backend():
    with rlo_tpu.init(backend="hybrid", world_size=WS) as b:
        yield b


def _xs(ws=WS, n=64, dtype=np.float32, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.standard_normal(n).astype(dtype) for _ in range(ws)]


class TestPlanes:
    def test_data_plane_allreduce(self, backend):
        xs = _xs()
        out = backend.allreduce(xs)
        np.testing.assert_allclose(out[2], np.sum(xs, axis=0),
                                   rtol=1e-5, atol=1e-5)

    def test_control_plane_bcast_and_consensus(self, backend):
        xs = _xs()
        got = backend.bcast(origin=3, x=xs[1])
        np.testing.assert_array_equal(got[0], xs[1])
        assert backend.consensus([1] * WS) == 1
        assert backend.consensus([1, 1, 0, 1]) == 0


class TestProposedCollective:
    def test_approved_runs_collective(self, backend):
        xs = _xs(seed=1)
        decision, out = backend.propose_collective("allreduce", xs,
                                                   proposer=2)
        assert decision == 1
        np.testing.assert_allclose(out[0], np.sum(xs, axis=0),
                                   rtol=1e-5, atol=1e-5)

    def test_mismatched_shape_vetoes(self, backend):
        xs = _xs(seed=2)
        xs[3] = xs[3][:32]  # rank 3's tensor disagrees with the proposal
        decision, out = backend.propose_collective("allreduce", xs,
                                                   proposer=0)
        assert decision == 0 and out is None

    def test_mismatched_dtype_vetoes(self, backend):
        xs = _xs(seed=3)
        xs[1] = xs[1].astype(np.float64)
        decision, out = backend.propose_collective("all_gather", xs)
        assert decision == 0 and out is None

    def test_reduce_scatter_gated(self, backend):
        xs = _xs(seed=4)
        decision, out = backend.propose_collective("reduce_scatter", xs)
        assert decision == 1
        full = np.sum(xs, axis=0)
        np.testing.assert_allclose(out[1], full.reshape(WS, -1)[1],
                                   rtol=1e-5, atol=1e-5)

    def test_unknown_op_rejected(self, backend):
        with pytest.raises(ValueError, match="unknown collective"):
            backend.propose_collective("transpose", _xs())

    def test_device_judge_shard_vetoes(self, backend):
        """Per-shard DEVICE judgment routed through the C vote tree
        (VERDICT item 2): each rank's vote is computed inside shard_map
        from its own device slice; one shard's non-finite tensor vetoes
        the round even though one controller process drives the mesh,
        and the structural judges alone would all approve."""
        import jax.numpy as jnp
        finite = lambda v: jnp.all(jnp.isfinite(v)).astype(jnp.int32)
        xs = _xs(seed=5)
        decision, out = backend.propose_collective(
            "allreduce", xs, device_judge=finite)
        assert decision == 1
        np.testing.assert_allclose(out[0], np.sum(xs, axis=0),
                                   rtol=1e-5, atol=1e-5)
        xs[2][7] = np.inf  # poison only rank 2's device shard
        decision, out = backend.propose_collective(
            "allreduce", xs, device_judge=finite)
        assert decision == 0 and out is None

    def test_device_judge_proposer_self_veto(self, backend):
        """The proposer's own device shard failing the predicate must
        decline its own proposal (the re-judge path, :773)."""
        import jax.numpy as jnp
        finite = lambda v: jnp.all(jnp.isfinite(v)).astype(jnp.int32)
        xs = _xs(seed=6)
        xs[0][0] = np.nan  # proposer rank 0's own shard
        decision, out = backend.propose_collective(
            "allreduce", xs, proposer=0, device_judge=finite)
        assert decision == 0 and out is None

    def test_device_judge_reuse_hits_compile_cache(self, backend):
        """Repeated rounds with the SAME judge must reuse one compiled
        shard_map program: the round-2 advisor found each call minting
        a fresh wrapper lambda, recompiling, and permanently leaking a
        cache entry per round."""
        import jax.numpy as jnp
        finite = lambda v: jnp.all(jnp.isfinite(v)).astype(jnp.int32)
        xs = _xs(seed=7)
        backend.propose_collective("allreduce", xs, device_judge=finite)
        cache = backend._consensus._sharded_cache
        n_before = len(cache)
        for seed in (8, 9, 10):
            decision, _ = backend.propose_collective(
                "allreduce", _xs(seed=seed), device_judge=finite)
            assert decision == 1
        assert len(cache) == n_before, (
            "repeat rounds with one judge grew the compiled-program "
            f"cache from {n_before} to {len(cache)}")

    def test_bound_method_judge_reuse_hits_compile_cache(self, backend):
        """obj.judge mints a fresh bound-method object per attribute
        access, so id()-keyed caching silently degrades to a recompile
        per round — the wrapper cache must key methods on
        (id(__self__), __func__) instead."""
        import jax.numpy as jnp

        class Judge:
            def judge(self, v):
                return jnp.all(jnp.isfinite(v)).astype(jnp.int32)

        j = Judge()
        xs = _xs(seed=11)
        backend.propose_collective("allreduce", xs,
                                   device_judge=j.judge)
        cache = backend._consensus._sharded_cache
        n_before = len(cache)
        for seed in (12, 13, 14):
            decision, _ = backend.propose_collective(
                "allreduce", _xs(seed=seed), device_judge=j.judge)
            assert decision == 1
        assert len(cache) == n_before
