"""Pipeline parallelism: layers sharded over pp, microbatches streamed
through a ppermute chain.

Oracles: the pipelined loss equals the flagship model's loss exactly
(microbatching only reorders batch-independent work); a pipelined train
step takes the same step as the single-device model; stacking round-
trips; training converges; pp composes with dp.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from rlo_tpu.models.pipeline import (pipeline_loss, pipeline_pspecs,
                                     pipeline_train_step, stack_layers,
                                     unstack_layers)
from rlo_tpu.models.transformer import (TransformerConfig, init_params,
                                        loss_fn, train_step)
from rlo_tpu.parallel.mesh import make_mesh, shard_jit

CFG = TransformerConfig(vocab=32, d_model=32, n_heads=4, n_layers=4,
                        d_ff=64, dtype="float32")


def _data(batch=8, seq=16, seed=0):
    params = init_params(jax.random.PRNGKey(seed), CFG)
    rng = np.random.default_rng(seed)
    tokens = jnp.asarray(rng.integers(0, CFG.vocab, (batch, seq)),
                         jnp.int32)
    return params, tokens


def test_stack_unstack_roundtrip():
    params, _ = _data()
    rt = unstack_layers(stack_layers(params), CFG.n_layers)
    for (k, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(params)[0],
            jax.tree_util.tree_flatten_with_path(rt)[0]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=jax.tree_util.keystr(k))


@pytest.mark.parametrize("pp,n_micro", [(2, 2), (4, 4), (4, 8), (2, 1)])
def test_pipeline_loss_matches_flagship(pp, n_micro):
    params, tokens = _data()
    want = float(loss_fn(params, tokens, CFG))
    pparams = stack_layers(params)
    mesh = make_mesh((pp,), ("pp",))
    specs = pipeline_pspecs("pp")
    f = shard_jit(
        lambda p, t: pipeline_loss(p, t, CFG, "pp", n_micro),
        mesh, (specs, P()), P())
    got = float(f(pparams, tokens))
    assert abs(got - want) < 2e-5, (got, want)


def test_pipeline_train_step_matches_single_device():
    params, tokens = _data(seed=1)
    ref_p, ref_loss = jax.jit(
        lambda p, t: train_step(p, t, CFG, lr=0.05))(params, tokens)
    pparams = stack_layers(params)
    mesh = make_mesh((4,), ("pp",))
    specs = pipeline_pspecs("pp")
    step = shard_jit(
        lambda p, t: pipeline_train_step(p, t, CFG, "pp", n_micro=4,
                                         lr=0.05),
        mesh, (specs, P()), (specs, P()))
    new_p, loss = step(pparams, tokens)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    got = unstack_layers(jax.tree.map(np.asarray, new_p), CFG.n_layers)
    for (k, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(got)[0],
            jax.tree_util.tree_flatten_with_path(ref_p)[0]):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5,
            err_msg=jax.tree_util.keystr(k))


def test_pipeline_composes_with_dp():
    """(dp, pp) = (2, 4): tokens sharded over dp, layers over pp; the
    combined step must match the single-device step."""
    params, tokens = _data(batch=8, seed=2)
    ref_p, ref_loss = jax.jit(
        lambda p, t: train_step(p, t, CFG, lr=0.05))(params, tokens)
    pparams = stack_layers(params)
    mesh = make_mesh((2, 4), ("dp", "pp"))
    specs = pipeline_pspecs("pp")
    step = shard_jit(
        lambda p, t: pipeline_train_step(p, t, CFG, "pp", n_micro=2,
                                         lr=0.05, dp_axis="dp"),
        mesh, (specs, P("dp")), (specs, P()))
    new_p, loss = step(pparams, tokens)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    got = unstack_layers(jax.tree.map(np.asarray, new_p), CFG.n_layers)
    for (k, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(got)[0],
            jax.tree_util.tree_flatten_with_path(ref_p)[0]):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5,
            err_msg=jax.tree_util.keystr(k))


def test_pipeline_training_reduces_loss():
    cfg = TransformerConfig(vocab=16, d_model=32, n_heads=2, n_layers=4,
                            d_ff=32, dtype="float32")
    params = init_params(jax.random.PRNGKey(5), cfg)
    rng = np.random.default_rng(5)
    rows = [(rng.integers(0, 16) + np.arange(24)) % 16 for _ in range(4)]
    tokens = jnp.asarray(np.stack(rows), jnp.int32)
    pparams = stack_layers(params)
    mesh = make_mesh((4,), ("pp",))
    specs = pipeline_pspecs("pp")
    step = shard_jit(
        lambda p, t: pipeline_train_step(p, t, cfg, "pp", n_micro=4,
                                         lr=0.2),
        mesh, (specs, P()), (specs, P()))
    losses = []
    for _ in range(80):
        pparams, loss = step(pparams, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_batch_not_divisible_rejected():
    params, tokens = _data(batch=6)
    pparams = stack_layers(params)
    mesh = make_mesh((2,), ("pp",))
    specs = pipeline_pspecs("pp")
    with pytest.raises(AssertionError, match="n_micro"):
        shard_jit(lambda p, t: pipeline_loss(p, t, CFG, "pp", 4),
                  mesh, (specs, P()), P())(pparams, tokens)
