"""Pipeline parallelism: layers sharded over pp, microbatches streamed
through a ppermute chain.

Oracles: the pipelined loss equals the flagship model's loss exactly
(microbatching only reorders batch-independent work); a pipelined train
step takes the same step as the single-device model; stacking round-
trips; training converges; pp composes with dp.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from rlo_tpu.models.pipeline import (pipeline_loss, pipeline_pspecs,
                                     pipeline_train_step, stack_layers,
                                     unstack_layers)
from rlo_tpu.models.transformer import (TransformerConfig, init_params,
                                        loss_fn, train_step)
from rlo_tpu.parallel.mesh import make_mesh, shard_jit

CFG = TransformerConfig(vocab=32, d_model=32, n_heads=4, n_layers=4,
                        d_ff=64, dtype="float32")


def _data(batch=8, seq=16, seed=0):
    params = init_params(jax.random.PRNGKey(seed), CFG)
    rng = np.random.default_rng(seed)
    tokens = jnp.asarray(rng.integers(0, CFG.vocab, (batch, seq)),
                         jnp.int32)
    return params, tokens


def test_stack_unstack_roundtrip():
    params, _ = _data()
    rt = unstack_layers(stack_layers(params), CFG.n_layers)
    for (k, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(params)[0],
            jax.tree_util.tree_flatten_with_path(rt)[0]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=jax.tree_util.keystr(k))


@pytest.mark.parametrize("pp,n_micro", [(2, 2), (4, 4), (4, 8), (2, 1)])
def test_pipeline_loss_matches_flagship(pp, n_micro):
    params, tokens = _data()
    want = float(loss_fn(params, tokens, CFG))
    pparams = stack_layers(params)
    mesh = make_mesh((pp,), ("pp",))
    specs = pipeline_pspecs("pp")
    f = shard_jit(
        lambda p, t: pipeline_loss(p, t, CFG, "pp", n_micro),
        mesh, (specs, P()), P())
    got = float(f(pparams, tokens))
    assert abs(got - want) < 2e-5, (got, want)


def test_pipeline_train_step_matches_single_device():
    params, tokens = _data(seed=1)
    ref_p, ref_loss = jax.jit(
        lambda p, t: train_step(p, t, CFG, lr=0.05))(params, tokens)
    pparams = stack_layers(params)
    mesh = make_mesh((4,), ("pp",))
    specs = pipeline_pspecs("pp")
    step = shard_jit(
        lambda p, t: pipeline_train_step(p, t, CFG, "pp", n_micro=4,
                                         lr=0.05),
        mesh, (specs, P()), (specs, P()))
    new_p, loss = step(pparams, tokens)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    got = unstack_layers(jax.tree.map(np.asarray, new_p), CFG.n_layers)
    for (k, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(got)[0],
            jax.tree_util.tree_flatten_with_path(ref_p)[0]):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5,
            err_msg=jax.tree_util.keystr(k))


def test_pipeline_composes_with_dp():
    """(dp, pp) = (2, 4): tokens sharded over dp, layers over pp; the
    combined step must match the single-device step."""
    params, tokens = _data(batch=8, seed=2)
    ref_p, ref_loss = jax.jit(
        lambda p, t: train_step(p, t, CFG, lr=0.05))(params, tokens)
    pparams = stack_layers(params)
    mesh = make_mesh((2, 4), ("dp", "pp"))
    specs = pipeline_pspecs("pp")
    step = shard_jit(
        lambda p, t: pipeline_train_step(p, t, CFG, "pp", n_micro=2,
                                         lr=0.05, dp_axis="dp"),
        mesh, (specs, P("dp")), (specs, P()))
    new_p, loss = step(pparams, tokens)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    got = unstack_layers(jax.tree.map(np.asarray, new_p), CFG.n_layers)
    for (k, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(got)[0],
            jax.tree_util.tree_flatten_with_path(ref_p)[0]):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5,
            err_msg=jax.tree_util.keystr(k))


def test_pipeline_training_reduces_loss():
    cfg = TransformerConfig(vocab=16, d_model=32, n_heads=2, n_layers=4,
                            d_ff=32, dtype="float32")
    params = init_params(jax.random.PRNGKey(5), cfg)
    rng = np.random.default_rng(5)
    rows = [(rng.integers(0, 16) + np.arange(24)) % 16 for _ in range(4)]
    tokens = jnp.asarray(np.stack(rows), jnp.int32)
    pparams = stack_layers(params)
    mesh = make_mesh((4,), ("pp",))
    specs = pipeline_pspecs("pp")
    step = shard_jit(
        lambda p, t: pipeline_train_step(p, t, cfg, "pp", n_micro=4,
                                         lr=0.2),
        mesh, (specs, P()), (specs, P()))
    losses = []
    for _ in range(80):
        pparams, loss = step(pparams, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_batch_not_divisible_rejected():
    params, tokens = _data(batch=6)
    pparams = stack_layers(params)
    mesh = make_mesh((2,), ("pp",))
    specs = pipeline_pspecs("pp")
    with pytest.raises(AssertionError, match="n_micro"):
        shard_jit(lambda p, t: pipeline_loss(p, t, CFG, "pp", 4),
                  mesh, (specs, P()), P())(pparams, tokens)


# ---------------------------------------------------------------------------
# 1F1B schedule (round-5 VERDICT item 8)
# ---------------------------------------------------------------------------

from rlo_tpu.models.pipeline import (pipeline_1f1b_train_step,  # noqa: E402
                                     pipeline_cost)


@pytest.mark.parametrize("pp,n_micro", [(2, 4), (4, 4), (4, 8)])
def test_1f1b_matches_gpipe_and_single_device(pp, n_micro):
    """THE parity oracle: the 1F1B step's loss and updated params equal
    both the GPipe step's and the single-device train_step's — same
    math, different schedule."""
    params, tokens = _data(seed=3)
    ref_p, ref_loss = jax.jit(
        lambda p, t: train_step(p, t, CFG, lr=0.05))(params, tokens)
    pparams = stack_layers(params)
    mesh = make_mesh((pp,), ("pp",))
    specs = pipeline_pspecs("pp")
    step_g = shard_jit(
        lambda p, t: pipeline_train_step(p, t, CFG, "pp",
                                         n_micro=n_micro, lr=0.05),
        mesh, (specs, P()), (specs, P()))
    step_1 = shard_jit(
        lambda p, t: pipeline_1f1b_train_step(p, t, CFG, "pp",
                                              n_micro=n_micro, lr=0.05),
        mesh, (specs, P()), (specs, P()))
    gp, gl = step_g(pparams, tokens)
    fp, fl = step_1(pparams, tokens)
    np.testing.assert_allclose(float(fl), float(gl), rtol=1e-5)
    np.testing.assert_allclose(float(fl), float(ref_loss), rtol=1e-5)
    got = unstack_layers(jax.tree.map(np.asarray, fp), CFG.n_layers)
    for (k, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(got)[0],
            jax.tree_util.tree_flatten_with_path(ref_p)[0]):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5,
            err_msg=jax.tree_util.keystr(k))
    for (k, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(
                jax.tree.map(np.asarray, fp))[0],
            jax.tree_util.tree_flatten_with_path(
                jax.tree.map(np.asarray, gp))[0]):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-6,
            err_msg="1f1b vs gpipe " + jax.tree_util.keystr(k))


def test_1f1b_composes_with_dp():
    params, tokens = _data(batch=8, seed=4)
    ref_p, ref_loss = jax.jit(
        lambda p, t: train_step(p, t, CFG, lr=0.05))(params, tokens)
    pparams = stack_layers(params)
    mesh = make_mesh((2, 4), ("dp", "pp"))
    specs = pipeline_pspecs("pp")
    step = shard_jit(
        lambda p, t: pipeline_1f1b_train_step(p, t, CFG, "pp",
                                              n_micro=2, lr=0.05,
                                              dp_axis="dp"),
        mesh, (specs, P("dp")), (specs, P()))
    new_p, loss = step(pparams, tokens)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    got = unstack_layers(jax.tree.map(np.asarray, new_p), CFG.n_layers)
    for (k, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(got)[0],
            jax.tree_util.tree_flatten_with_path(ref_p)[0]):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5,
            err_msg=jax.tree_util.keystr(k))


def _subjaxprs(eqn):
    """Every sub-jaxpr in an eqn's params (closed or plain, incl. lists)."""
    def norm(v):
        if hasattr(v, "eqns"):
            return v
        if hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
            return v.jaxpr
        return None
    for v in eqn.params.values():
        for u in (v if isinstance(v, (list, tuple)) else (v,)):
            j = norm(u)
            if j is not None:
                yield j


def _scan_eqns(jaxpr):
    """Yield every (scan eqn, body jaxpr) in a jaxpr, recursively."""
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "scan":
            yield eqn, eqn.params["jaxpr"].jaxpr
        for j in _subjaxprs(eqn):
            yield from _scan_eqns(j)


def _count_prim(jaxpr, name):
    n = 0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == name:
            n += 1
        for j in _subjaxprs(eqn):
            n += _count_prim(j, name)
    return n


@pytest.mark.parametrize("pp,n_micro", [(2, 4), (4, 8)])
def test_schedule_pinned_to_cost_model(pp, n_micro):
    """The cost model's tick and per-tick permute counts vs the traced
    program (jaxpr): GPipe's forward scan runs fwd_ticks with 1
    ppermute per tick; 1F1B's single scan runs total_ticks with 2."""
    params, tokens = _data()
    pparams = stack_layers(params)
    mesh = make_mesh((pp,), ("pp",))
    specs = pipeline_pspecs("pp")
    import jax as _jax
    for schedule, fn, want_ticks_key in (
            ("gpipe",
             lambda p, t: pipeline_loss(p, t, CFG, "pp", n_micro),
             "fwd_ticks"),
            ("1f1b",
             lambda p, t: pipeline_1f1b_train_step(
                 p, t, CFG, "pp", n_micro=n_micro),
             "total_ticks")):
        cost = pipeline_cost(schedule, pp, n_micro)
        shardy = _jax.shard_map(
            fn, mesh=mesh, in_specs=(specs, P()),
            out_specs=(P() if schedule == "gpipe" else (specs, P())),
            check_vma=True)
        jaxpr = _jax.make_jaxpr(shardy)(pparams, tokens)
        scans = [(e, b) for e, b in _scan_eqns(jaxpr.jaxpr)]
        # the pipeline scan is the one carrying ppermutes in its body
        pipe = [(e, b) for e, b in scans
                if _count_prim(b, "ppermute") > 0]
        assert pipe, f"{schedule}: no ppermute-carrying scan found"
        (eqn, body), = pipe[:1]
        assert eqn.params["length"] == cost[want_ticks_key], schedule
        n_perm = _count_prim(body, "ppermute")
        assert n_perm == cost["permutes_per_tick"], (schedule, n_perm)


def test_cost_model_totals_and_errors():
    g = pipeline_cost("gpipe", 4, 8)
    f = pipeline_cost("1f1b", 4, 8)
    assert g["fwd_ticks"] == 11 and g["bubble_fraction"] == 3 / 11
    assert f["total_ticks"] == 14 and f["bubble_fraction"] == 6 / 14
    # THE 1F1B claim: boundary storage bounded by the ring (2pp-1),
    # not the microbatch count
    assert f["peak_boundary_blocks"] == 7 < g["peak_boundary_blocks"] == 11
    big = pipeline_cost("1f1b", 4, 64)
    assert big["peak_boundary_blocks"] == 7  # M-independent
    assert pipeline_cost("gpipe", 4, 64)["peak_boundary_blocks"] == 67
    with pytest.raises(ValueError, match="no cost model"):
        pipeline_cost("dualpipe", 4, 8)
    with pytest.raises(ValueError, match=">= 1"):
        pipeline_cost("gpipe", 0, 8)
