"""ROOTLESS_BACKEND switch: one op surface, every backend.

The north-star requirement (BASELINE.json): a runtime backend switch at
init so the same program runs on a CPU transport or the TPU lowering.
Each backend facade must produce numerically identical collectives; the
mpi/shm entries must fail with actionable messages in this build (no MPI
installation; shm is one-process-per-rank C-only).
"""

import os

import numpy as np
import pytest

import rlo_tpu

WS = 4
BACKENDS = ["loopback", "native", "tpu"]


def make(backend):
    return rlo_tpu.init(backend=backend, world_size=WS)


def rand_xs(seed, shape=(3, 5), dtype=np.float32):
    rng = np.random.default_rng(seed)
    return [rng.standard_normal(shape).astype(dtype) for _ in range(WS)]


@pytest.mark.parametrize("backend", BACKENDS)
class TestFacadeOps:
    def test_allreduce_matches_numpy(self, backend):
        with make(backend) as b:
            xs = rand_xs(0)
            want = np.sum(xs, axis=0)
            outs = b.allreduce(xs)
            for o in outs:
                np.testing.assert_allclose(o, want, rtol=1e-5)

    def test_allreduce_min(self, backend):
        with make(backend) as b:
            xs = rand_xs(1)
            want = np.minimum.reduce(xs)
            for o in b.allreduce(xs, op="min"):
                np.testing.assert_allclose(o, want, rtol=1e-6)

    def test_bcast_any_origin(self, backend):
        with make(backend) as b:
            for origin in (0, 2, WS - 1):  # rootless: any rank initiates
                x = np.arange(12, dtype=np.int32).reshape(3, 4) + origin
                outs = b.bcast(origin, x)
                for o in outs:
                    np.testing.assert_array_equal(o, x)

    def test_consensus_and_of_votes(self, backend):
        with make(backend) as b:
            assert b.consensus([1] * WS) == 1
            votes = [1] * WS
            votes[WS - 1] = 0
            assert b.consensus(votes) == 0

    def test_reduce_scatter_chunks(self, backend):
        with make(backend) as b:
            xs = rand_xs(2, shape=(WS * 2,))
            full = np.sum(xs, axis=0)
            outs = b.reduce_scatter(xs)
            for r, o in enumerate(outs):
                np.testing.assert_allclose(
                    o.reshape(-1), full.reshape(WS, -1)[r], rtol=1e-5)

    def test_all_gather_stacks(self, backend):
        with make(backend) as b:
            xs = rand_xs(3, shape=(2, 3))
            want = np.stack(xs)
            for o in b.all_gather(xs):
                np.testing.assert_allclose(o, want, rtol=1e-6)

    def test_all_to_all_transposes(self, backend):
        with make(backend) as b:
            xss = [[np.full((2,), 10 * r + d, np.float32)
                    for d in range(WS)] for r in range(WS)]
            out = b.all_to_all(xss)
            for d in range(WS):
                for r in range(WS):
                    np.testing.assert_array_equal(out[d][r], xss[r][d])

    def test_barrier_completes(self, backend):
        with make(backend) as b:
            b.barrier()


class TestSwitch:
    def test_env_var_selects(self, monkeypatch):
        monkeypatch.setenv("ROOTLESS_BACKEND", "loopback")
        with rlo_tpu.init(world_size=WS) as b:
            assert b.name == "loopback"

    def test_argument_overrides_env(self, monkeypatch):
        monkeypatch.setenv("ROOTLESS_BACKEND", "native")
        with rlo_tpu.init(backend="loopback", world_size=WS) as b:
            assert b.name == "loopback"

    def test_unknown_backend_lists_known(self):
        with pytest.raises(ValueError, match="loopback"):
            rlo_tpu.init(backend="nonsense")

    def test_mpi_unavailable_is_actionable(self):
        # this image has no MPI; the switch must say so, not segfault
        with pytest.raises(RuntimeError, match="[Mm]pi|MPI"):
            rlo_tpu.init(backend="mpi")

    def test_shm_points_to_demo(self):
        with pytest.raises(RuntimeError, match="rlo_demo"):
            rlo_tpu.init(backend="shm")

    def test_auto_on_cpu_mesh_is_tpu_multidevice(self):
        # conftest forces an 8-device CPU platform -> auto picks the
        # mesh-collective backend
        with rlo_tpu.init(world_size=WS) as b:
            assert b.name == "tpu"
            assert b.world_size == WS
