"""Ring attention vs full attention on the 8-device virtual mesh.

Oracle: sharding the sequence over the ring and streaming K/V blocks must
be numerically equivalent (up to fp accumulation order) to unsharded
softmax attention — causal and bidirectional, any head/dim shape, and for
every shard of the output."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from rlo_tpu.ops.ring_attention import full_attention, ring_attention
from rlo_tpu.parallel.mesh import make_mesh, shard_jit

WS = 8


def make_qkv(seed, seq, heads, dim, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    def one():
        return jnp.asarray(
            rng.standard_normal((seq, heads, dim)) * 0.5, dtype)
    return one(), one(), one()


def run_ring(q, k, v, causal, use_pallas=None, block_q=256,
             block_k=None):
    mesh = make_mesh((WS,), ("sp",))
    # check_vma off when exercising the Pallas kernel in interpret mode:
    # the pallas interpreter's internal grid loop does not thread
    # varying-manual-axes types (a known JAX rough edge); the compiled
    # TPU path runs under check_vma=True unchanged
    fn = shard_jit(
        lambda q_, k_, v_: ring_attention(q_, k_, v_, "sp", causal=causal,
                                          use_pallas=use_pallas,
                                          block_q=block_q,
                                          block_k=block_k),
        mesh, (P("sp"), P("sp"), P("sp")), P("sp"),
        check_vma=not use_pallas)
    return np.asarray(fn(q, k, v))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("seq,heads,dim", [(64, 4, 16), (32, 1, 8),
                                           (128, 2, 32)])
def test_matches_full_attention(causal, seq, heads, dim):
    q, k, v = make_qkv(0, seq, heads, dim)
    want = np.asarray(full_attention(q, k, v, causal=causal))
    got = run_ring(q, k, v, causal)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_bf16_inputs():
    q, k, v = make_qkv(1, 64, 2, 16, jnp.bfloat16)
    want = np.asarray(
        full_attention(q, k, v, causal=True).astype(jnp.float32))
    got = run_ring(q, k, v, True).astype(np.float32)
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)


def test_causal_first_token_attends_only_itself():
    # token 0's output must equal v[0] exactly (softmax over one key)
    q, k, v = make_qkv(2, 64, 2, 16)
    got = run_ring(q, k, v, True)
    np.testing.assert_allclose(got[0], np.asarray(v)[0], rtol=1e-5,
                               atol=1e-6)


def test_memory_shape_invariant():
    # per-shard blocks: output shape equals q shape, dtype preserved
    q, k, v = make_qkv(3, 64, 4, 16)
    got = run_ring(q, k, v, False)
    assert got.shape == (64, 4, 16)
    assert got.dtype == np.float32


class TestStripedLayout:
    """Striped sharding (causal load balancing): pre-permute the
    sequence with stripe_sequence, run the ring with layout='striped',
    un-permute the output — must equal full attention on the original
    order, einsum and flash paths alike."""

    def run_striped(self, q, k, v, causal, use_pallas=None, block_q=256):
        from rlo_tpu.ops.ring_attention import (stripe_sequence,
                                                unstripe_sequence)
        mesh = make_mesh((WS,), ("sp",))
        fn = shard_jit(
            lambda q_, k_, v_: ring_attention(
                q_, k_, v_, "sp", causal=causal, layout="striped",
                use_pallas=use_pallas, block_q=block_q),
            mesh, (P("sp"), P("sp"), P("sp")), P("sp"),
            check_vma=not use_pallas)
        out = fn(stripe_sequence(q, WS), stripe_sequence(k, WS),
                 stripe_sequence(v, WS))
        return np.asarray(unstripe_sequence(out, WS))

    def test_stripe_roundtrip(self):
        from rlo_tpu.ops.ring_attention import (stripe_sequence,
                                                unstripe_sequence)
        x = jnp.arange(24).reshape(24, 1, 1)
        y = unstripe_sequence(stripe_sequence(x, 8), 8)
        np.testing.assert_array_equal(np.asarray(y), np.asarray(x))
        # shard 0 of the striped layout holds tokens 0, 8, 16
        s = np.asarray(stripe_sequence(x, 8)).reshape(-1)
        np.testing.assert_array_equal(s[:3], [0, 8, 16])

    @pytest.mark.parametrize("causal", [False, True])
    def test_striped_matches_full(self, causal):
        q, k, v = make_qkv(9, 64, 2, 16)
        want = np.asarray(full_attention(q, k, v, causal=causal))
        got = self.run_striped(q, k, v, causal)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_striped_flash_matches_full(self):
        q, k, v = make_qkv(10, 64, 2, 16)
        want = np.asarray(full_attention(q, k, v, causal=True))
        got = self.run_striped(q, k, v, True, use_pallas=True, block_q=8)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


class TestFlashKernel:
    """The fused Pallas block update (rlo_tpu/pallas/flash.py, interpret
    mode on CPU) must reproduce the einsum path inside the full ring —
    the per-step (m, l, o) accumulation, causal masking across shard
    boundaries, and bf16 inputs."""

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("seq,heads,dim", [(64, 4, 16), (128, 2, 32)])
    def test_flash_matches_full_attention(self, causal, seq, heads, dim):
        q, k, v = make_qkv(4, seq, heads, dim)
        want = np.asarray(full_attention(q, k, v, causal=causal))
        got = run_ring(q, k, v, causal, use_pallas=True, block_q=4)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_flash_matches_einsum_path_exactly_shaped(self):
        q, k, v = make_qkv(5, 64, 2, 16)
        a = run_ring(q, k, v, True, use_pallas=False)
        b = run_ring(q, k, v, True, use_pallas=True, block_q=8)
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)

    def test_flash_bf16(self):
        q, k, v = make_qkv(6, 64, 2, 16, jnp.bfloat16)
        want = np.asarray(
            full_attention(q, k, v, causal=True).astype(jnp.float32))
        got = run_ring(q, k, v, True, use_pallas=True,
                       block_q=8).astype(np.float32)
        np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)

    def test_block_q_must_divide(self):
        q, k, v = make_qkv(7, 56, 1, 8)  # 7 tokens/shard
        with pytest.raises(ValueError, match="divide"):
            run_ring(q, k, v, False, use_pallas=True, block_q=4)

    @pytest.mark.parametrize("causal", [False, True])
    def test_forced_kv_tiling_parity(self, causal):
        """Explicit block_k forces the multi-K-tile path (scratch init
        at ik==0, cross-tile accumulation, flush at ik==n_k-1) that the
        auto policy would run untiled at test sizes — the long-sequence
        machinery must match the oracle exactly."""
        from rlo_tpu.pallas.flash import flash_attention

        q, k, v = make_qkv(8, 48, 2, 16)
        want = np.asarray(full_attention(q, k, v, causal=causal))
        # 48 keys in 6 tiles of 8 — n_k > 1 guaranteed
        got = np.asarray(flash_attention(q, k, v, causal=causal,
                                         block_q=16, block_k=8,
                                         interpret=True))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
        # and inside the full ring (every per-step update tiled)
        got_ring = run_ring(q, k, v, causal, use_pallas=True,
                            block_q=6, block_k=2)
        np.testing.assert_allclose(got_ring, want, rtol=2e-5, atol=2e-5)
